"""Full-read consensus: k-tier escalation, window stitching, read splitting.

Oracle equivalent of the reference's per-read driver around ``handleWindow``
(SURVEY.md §3.1: window loop, k escalation on failure, stitching of
overlapping window consensi, read split at unsolved windows; reference
file:line backfill pending — mount empty, SURVEY.md §0).

Stitching: consecutive windows overlap by ``w - adv`` bases; each new window
consensus is spliced onto the accumulated sequence by aligning a suffix of the
accumulator against a prefix of the new consensus (the reference stitches by
agreement over the overlap region). An unsolved window either splits the read
(daccord's default: emit corrected fragments) or, in ``patch`` mode, keeps the
original A bases for that span.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .align import overlap_suffix_prefix
from .dbg import DBGParams, WindowResult, window_consensus
from .profile import ErrorProfile, OffsetLikely, profile_vs_consensus, rough_profile
from .windows import RefinedOverlap, WindowSegments


@dataclass
class ConsensusConfig:
    w: int = 40
    adv: int = 10
    # escalation ladder: (k, min_count, edge_min_count). Larger k resolves
    # in-window repeats (the reference's escalate-k-on-failure); the final
    # low-count tier rescues sparse piles where a true k-mer fell under the
    # frequency filter.
    tiers: tuple[tuple[int, int, int], ...] = ((8, 2, 2), (10, 2, 2), (12, 2, 2), (8, 1, 1))
    dbg: DBGParams = field(default_factory=DBGParams)
    mode: str = "split"          # "split" | "patch"
    min_fragment: int = 40
    # homopolymer rescue (oracle/hp.py): re-solve hp-damaged windows in
    # run-length-compressed space. Host-side, engine-agnostic post-pass.
    hp_rescue: bool = False
    hp_err: float = 0.12         # route solved windows above this err
                                 # (r4 sweep: 0.12 -> Q 14.23 vs 13.40 at
                                 # 0.18 on the hp regime; 0.25 -> 11.53;
                                 # min_run 2 vs 3 a wash — BASELINE.md r4)
    hp_min_run: int = 3          # ...only when a run at least this long exists
    hp_margin: float = 0.005     # expanded result must beat direct err by this
    hp_vote: str = "median"      # run-length vote: "median" (flat, r4) or
                                 # "posterior" (profile-calibrated length
                                 # posterior, oracle/hp.py r5)
    hp_accept: str = "rescore"   # acceptance: "rescore" (raw unit-cost,
                                 # r4) or "likelihood" (experimental
                                 # likelihood-ratio under the observation
                                 # model; python path only, engages with
                                 # the posterior's slope gate)
    hp_lambda_c: float = 3.0     # compressed-space edit penalty (log
                                 # units) for the likelihood acceptance

    def __post_init__(self):
        # pack_result's 5-bit tier field reserves HP_TIER (29) for
        # hp-rescued windows; a ladder that deep would alias direct-solved
        # rows as rescued in the histogram and the hp write-back
        from .hp import HP_TIER

        # tier codes are 0-based indices into ``tiers``, so depth HP_TIER
        # (codes 0..HP_TIER-1) is still legal; one more collides
        if len(self.tiers) > HP_TIER:
            raise ValueError(
                f"ladder depth {len(self.tiers)} collides with the reserved "
                f"hp tier code {HP_TIER}; use fewer tiers")
        if self.hp_vote not in ("median", "posterior"):
            raise ValueError(f"hp_vote={self.hp_vote!r}: must be 'median' "
                             "or 'posterior'")
        if self.hp_accept not in ("rescore", "likelihood"):
            raise ValueError(f"hp_accept={self.hp_accept!r}: must be "
                             "'rescore' or 'likelihood'")

    @property
    def k_values(self) -> tuple[int, ...]:
        return tuple(sorted({t[0] for t in self.tiers}))


@dataclass
class CorrectedRead:
    fragments: list[np.ndarray]
    n_windows: int = 0
    n_solved: int = 0
    k_histogram: dict = field(default_factory=dict)


def make_offset_likely(profile: ErrorProfile,
                       cfg: ConsensusConfig) -> dict[int, OffsetLikely]:
    """One OL table per k tier (P spans the admissible DP lengths)."""
    tables = {}
    for k in cfg.k_values:
        P = cfg.w - k + 1 + cfg.dbg.len_slack
        O = cfg.w + 16
        tables[k] = OffsetLikely(profile, positions=P, max_offset=O)
    return tables


def estimate_profile_two_pass(refined: list[RefinedOverlap],
                              windows: list[WindowSegments],
                              cfg: ConsensusConfig,
                              sample: int = 48) -> ErrorProfile:
    """Reference-style error-profile pass: rough estimate from trace diffs,
    then true single-read rates from segments aligned to a sample consensus
    (SURVEY.md §3.1 'error-profile estimation pass')."""
    rough = rough_profile(refined)
    ol1 = make_offset_likely(rough, cfg)
    stride = max(1, len(windows) // sample)
    pairs: list[tuple[np.ndarray, np.ndarray]] = []
    for ws in windows[::stride]:
        res = solve_window(ws, ol1, cfg)
        if res.seq is not None:
            pairs.extend((res.seq, seg) for seg in ws.segments)
    if not pairs:
        return rough
    return profile_vs_consensus(pairs)


def solve_window(ws: WindowSegments, ol_tables: dict[int, OffsetLikely],
                 cfg: ConsensusConfig) -> WindowResult:
    """Try escalation tiers in order until one solves the window."""
    best = WindowResult(None, reason="depth")
    for k, mc, emc in cfg.tiers:
        p = DBGParams(**{**cfg.dbg.__dict__, "k": k,
                         "min_count": mc, "edge_min_count": emc})
        res = window_consensus(ws.segments, ol_tables[k], p, wlen=ws.wlen)
        if res.seq is not None:
            best = res
            break
        best = res
    if cfg.hp_rescue and len(ws.segments) >= cfg.dbg.min_depth:
        from .hp import hp_candidate

        hp = hp_candidate(ws.segments, best.seq, best.err, ol_tables, cfg)
        if hp is not None:
            return hp
    return best


def stitch_results(a_bases: np.ndarray,
                   results: list[tuple[int, int, np.ndarray | None]],
                   cfg: ConsensusConfig) -> list[np.ndarray]:
    """Stitch per-window consensi into corrected fragments.

    ``results`` rows are (wstart, wlen, consensus-or-None) in window order.
    Separated from the solving loop so the device pipeline (which solves
    windows in large cross-read batches) can reuse the exact stitching
    semantics of the oracle.

    The accumulator is a piece list concatenated once per fragment — the
    splice only ever inspects the accumulator's tail, so growth is O(read
    length), not O(read length²); long ONT-scale reads (100k+ windows)
    stitch in linear time.
    """
    frags: list[np.ndarray] = []
    pieces: list[np.ndarray] = []
    plen = 0
    active = False
    acc_end = 0

    def tail(n: int) -> np.ndarray:
        out: list[np.ndarray] = []
        need = n
        for arr in reversed(pieces):
            if need <= 0:
                break
            take = min(len(arr), need)
            out.append(arr[len(arr) - take :])
            need -= take
        if not out:
            return np.zeros(0, dtype=np.int8)
        return out[0] if len(out) == 1 else np.concatenate(out[::-1])

    def drop_tail(n: int) -> None:
        nonlocal plen
        while n > 0 and pieces:
            last = pieces[-1]
            if len(last) <= n:
                n -= len(last)
                plen -= len(last)
                pieces.pop()
            else:
                pieces[-1] = last[: len(last) - n]
                plen -= n
                n = 0

    def append(arr: np.ndarray) -> None:
        nonlocal plen
        if len(arr):
            pieces.append(arr)
            plen += len(arr)

    def restart(arr: np.ndarray) -> None:
        nonlocal pieces, plen, active
        pieces = [arr]
        plen = len(arr)
        active = True

    def flush() -> None:
        nonlocal pieces, plen, active
        if pieces:
            acc = pieces[0] if len(pieces) == 1 else np.concatenate(pieces)
            if len(acc) >= cfg.min_fragment:
                frags.append(acc)
        pieces = []
        plen = 0
        active = False

    for wstart, wlen, seq in results:
        if seq is None:
            if cfg.mode == "patch":
                patch = np.asarray(a_bases[wstart : wstart + wlen], dtype=np.int8)
                if not active:
                    restart(patch)
                else:
                    olap = acc_end - wstart
                    if olap > 0:
                        drop_tail(olap)
                    append(patch)
                acc_end = wstart + wlen
            else:
                flush()
            continue
        if not active:
            restart(seq)
        else:
            # splice the next window consensus onto the accumulator: align
            # acc's tail (~nominal overlap) against seq's head, join at the
            # best correspondence; strong disagreement => stitch failure
            # (flush and restart => the read splits)
            nominal = acc_end - wstart
            t = min(plen, nominal + 10)
            head = min(len(seq), nominal + 10)
            cost, a_start, b_end = overlap_suffix_prefix(tail(t), seq[:head])
            olap_len = max(t - a_start, b_end)
            if olap_len < max(4, nominal // 4) or cost > 0.35 * olap_len:
                flush()
                restart(seq)
            else:
                append(seq[b_end:])
        acc_end = wstart + wlen
    flush()
    return frags


def correct_read(a_bases: np.ndarray, windows: list[WindowSegments],
                 ol_tables: dict[int, OffsetLikely], cfg: ConsensusConfig) -> CorrectedRead:
    rows: list[tuple[int, int, np.ndarray | None]] = []
    n_solved = 0
    khist: dict = {}
    for ws in windows:
        res = solve_window(ws, ol_tables, cfg)
        rows.append((ws.wstart, ws.wlen, res.seq))
        if res.seq is not None:
            n_solved += 1
            khist[res.k] = khist.get(res.k, 0) + 1
    frags = stitch_results(a_bases, rows, cfg)
    return CorrectedRead(fragments=frags, n_windows=len(windows), n_solved=n_solved,
                         k_histogram=khist)
