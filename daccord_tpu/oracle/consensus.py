"""Full-read consensus: k-tier escalation, window stitching, read splitting.

Oracle equivalent of the reference's per-read driver around ``handleWindow``
(SURVEY.md §3.1: window loop, k escalation on failure, stitching of
overlapping window consensi, read split at unsolved windows; reference
file:line backfill pending — mount empty, SURVEY.md §0).

Stitching: consecutive windows overlap by ``w - adv`` bases; each new window
consensus is spliced onto the accumulated sequence by aligning a suffix of the
accumulator against a prefix of the new consensus (the reference stitches by
agreement over the overlap region). An unsolved window either splits the read
(daccord's default: emit corrected fragments) or, in ``patch`` mode, keeps the
original A bases for that span.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .align import overlap_suffix_prefix
from .dbg import DBGParams, WindowResult, window_consensus
from .profile import ErrorProfile, OffsetLikely, profile_vs_consensus, rough_profile
from .windows import RefinedOverlap, WindowSegments


@dataclass
class ConsensusConfig:
    w: int = 40
    adv: int = 10
    # escalation ladder: (k, min_count, edge_min_count). Larger k resolves
    # in-window repeats (the reference's escalate-k-on-failure); the final
    # low-count tier rescues sparse piles where a true k-mer fell under the
    # frequency filter.
    tiers: tuple[tuple[int, int, int], ...] = ((8, 2, 2), (10, 2, 2), (12, 2, 2), (8, 1, 1))
    dbg: DBGParams = field(default_factory=DBGParams)
    mode: str = "split"          # "split" | "patch"
    min_fragment: int = 40

    @property
    def k_values(self) -> tuple[int, ...]:
        return tuple(sorted({t[0] for t in self.tiers}))


@dataclass
class CorrectedRead:
    fragments: list[np.ndarray]
    n_windows: int = 0
    n_solved: int = 0
    k_histogram: dict = field(default_factory=dict)


def make_offset_likely(profile: ErrorProfile, cfg: ConsensusConfig) -> dict[int, OffsetLikely]:
    """One OL table per k tier (P spans the admissible DP lengths)."""
    tables = {}
    for k in cfg.k_values:
        P = cfg.w - k + 1 + cfg.dbg.len_slack
        O = cfg.w + 16
        tables[k] = OffsetLikely(profile, positions=P, max_offset=O)
    return tables


def estimate_profile_two_pass(refined: list[RefinedOverlap],
                              windows: list[WindowSegments],
                              cfg: ConsensusConfig,
                              sample: int = 48) -> ErrorProfile:
    """Reference-style error-profile pass: rough estimate from trace diffs,
    then true single-read rates from segments aligned to a sample consensus
    (SURVEY.md §3.1 'error-profile estimation pass')."""
    rough = rough_profile(refined)
    ol1 = make_offset_likely(rough, cfg)
    stride = max(1, len(windows) // sample)
    pairs: list[tuple[np.ndarray, np.ndarray]] = []
    for ws in windows[::stride]:
        res = solve_window(ws, ol1, cfg)
        if res.seq is not None:
            pairs.extend((res.seq, seg) for seg in ws.segments)
    if not pairs:
        return rough
    return profile_vs_consensus(pairs)


def solve_window(ws: WindowSegments, ol_tables: dict[int, OffsetLikely],
                 cfg: ConsensusConfig) -> WindowResult:
    """Try escalation tiers in order until one solves the window."""
    best = WindowResult(None, reason="depth")
    for k, mc, emc in cfg.tiers:
        p = DBGParams(**{**cfg.dbg.__dict__, "k": k,
                         "min_count": mc, "edge_min_count": emc})
        res = window_consensus(ws.segments, ol_tables[k], p, wlen=ws.wlen)
        if res.seq is not None:
            return res
        best = res
    return best


def _splice(acc: np.ndarray, nxt: np.ndarray, nominal_olap: int) -> np.ndarray | None:
    """Splice window consensus ``nxt`` onto accumulator ``acc``.

    The true overlap is ~``nominal_olap`` bases; align acc's tail against nxt's
    head and join at the best correspondence. Returns None when the overlap
    disagrees too much (stitch failure -> split).
    """
    tail = min(len(acc), nominal_olap + 10)
    head = min(len(nxt), nominal_olap + 10)
    cost, a_start, b_end = overlap_suffix_prefix(acc[len(acc) - tail :], nxt[:head])
    olap_len = max(tail - a_start, b_end)
    if olap_len < max(4, nominal_olap // 4) or cost > 0.35 * olap_len:
        return None
    return np.concatenate([acc, nxt[b_end:]])


def stitch_results(a_bases: np.ndarray,
                   results: list[tuple[int, int, np.ndarray | None]],
                   cfg: ConsensusConfig) -> list[np.ndarray]:
    """Stitch per-window consensi into corrected fragments.

    ``results`` rows are (wstart, wlen, consensus-or-None) in window order.
    Separated from the solving loop so the device pipeline (which solves
    windows in large cross-read batches) can reuse the exact stitching
    semantics of the oracle.
    """
    frags: list[np.ndarray] = []
    acc: np.ndarray | None = None
    acc_end = 0

    def flush():
        nonlocal acc
        if acc is not None and len(acc) >= cfg.min_fragment:
            frags.append(acc)
        acc = None

    for wstart, wlen, seq in results:
        if seq is None:
            if cfg.mode == "patch":
                patch = np.asarray(a_bases[wstart : wstart + wlen], dtype=np.int8)
                if acc is None:
                    acc = patch
                else:
                    olap = acc_end - wstart
                    acc = np.concatenate([acc[: len(acc) - max(olap, 0)], patch]) if olap > 0 else np.concatenate([acc, patch])
                acc_end = wstart + wlen
            else:
                flush()
            continue
        if acc is None:
            acc = seq
        else:
            spliced = _splice(acc, seq, nominal_olap=acc_end - wstart)
            if spliced is None:
                flush()
                acc = seq
            else:
                acc = spliced
        acc_end = wstart + wlen
    flush()
    return frags


def correct_read(a_bases: np.ndarray, windows: list[WindowSegments],
                 ol_tables: dict[int, OffsetLikely], cfg: ConsensusConfig) -> CorrectedRead:
    rows: list[tuple[int, int, np.ndarray | None]] = []
    n_solved = 0
    khist: dict = {}
    for ws in windows:
        res = solve_window(ws, ol_tables, cfg)
        rows.append((ws.wstart, ws.wlen, res.seq))
        if res.seq is not None:
            n_solved += 1
            khist[res.k] = khist.get(res.k, 0) + 1
    frags = stitch_results(a_bases, rows, cfg)
    return CorrectedRead(fragments=frags, n_windows=len(windows), n_solved=n_solved,
                         k_histogram=khist)
