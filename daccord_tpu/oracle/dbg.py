"""Per-window local de Bruijn graph consensus — the ``handleWindow`` spec.

Numpy executable specification of the reference's L4 consensus core:
``DebruijnGraph<k>`` / ``DebruijnGraphInterface`` / ``handleWindow`` in
``src/daccord.cpp`` (structures named by BASELINE.json's north_star; behavior
per the daccord paper — reference file:line backfill pending, SURVEY.md §0/§8).

Pipeline per window (SURVEY.md §3.3):

  1. pack k-mers from all segments, with their segment offsets;
  2. frequency filter (errors produce low-count k-mers) plus (k+1)-mer support
     for edges ((k,k+1)-mer consistency);
  3. per-k-mer position weights = offset-occurrence counts x OffsetLikely;
  4. bounded-length heaviest-path DP from a window-start anchor k-mer to a
     window-end anchor k-mer (the reference escalates k until the graph is
     workable; bounded path length additionally makes cycles harmless);
  5. top candidates rescored by edit distance against all segments; argmin
     wins; windows whose best candidate still disagrees with the pile are
     reported unsolved.

The batched device implementation (``kernels.window_kernel``) must match this
module on the parity harness; keep semantic changes synchronized.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .align import edit_distance_sum, pack_segments
from .profile import OffsetLikely

NEG = np.float32(-1e30)


@dataclass
class DBGParams:
    k: int = 8
    min_count: int = 2           # k-mer frequency filter floor
    count_frac: float = 0.0      # additional adaptive floor: frac * depth
    edge_min_count: int = 2      # (k+1)-mer support needed for an edge
    anchor_slack: int = 2        # offsets <= slack qualify as window-start anchors
    end_slack: int = 3           # offsets >= seglen-k-end_slack qualify as end anchors
    len_slack: int = 8           # accepted consensus length deviation from w
    n_candidates: int = 3
    min_depth: int = 3
    max_err: float = 0.3         # reject consensus if mean edit rate above this


@dataclass
class WindowResult:
    seq: np.ndarray | None       # int8 consensus bases, or None if unsolved
    err: float = 1.0             # mean per-base edit rate of winner vs segments
    k: int = 0
    n_candidates: int = 0
    reason: str = ""


def _pack_kmers(seg: np.ndarray, k: int) -> np.ndarray:
    """All k-mer codes of one segment (base-4 big-endian packing)."""
    n = len(seg) - k + 1
    if n <= 0:
        return np.zeros(0, dtype=np.int64)
    codes = np.zeros(n, dtype=np.int64)
    s = seg.astype(np.int64)
    for j in range(k):
        codes = codes * 4 + s[j : j + n]
    return codes


def window_consensus(segments: list[np.ndarray], ol: OffsetLikely,
                     params: DBGParams, wlen: int = 40) -> WindowResult:
    k = params.k
    D = len(segments)
    if D < params.min_depth:
        return WindowResult(None, reason="depth")

    # ---- 1. k-mers + offsets, (k+1)-mers --------------------------------
    codes_list, offs_list, endflag_list, startflag_list = [], [], [], []
    codes1_list = []
    for seg in segments:
        c = _pack_kmers(seg, k)
        if len(c) == 0:
            continue
        o = np.arange(len(c))
        codes_list.append(c)
        offs_list.append(o)
        startflag_list.append(o <= params.anchor_slack)
        endflag_list.append(o >= len(c) - 1 - params.end_slack)
        codes1_list.append(_pack_kmers(seg, k + 1))
    if not codes_list:
        return WindowResult(None, reason="empty")
    codes = np.concatenate(codes_list)
    offs = np.concatenate(offs_list)
    is_start = np.concatenate(startflag_list)
    is_end = np.concatenate(endflag_list)
    codes1 = np.concatenate(codes1_list) if codes1_list else np.zeros(0, dtype=np.int64)

    # ---- 2. frequency filter -------------------------------------------
    uniq, inv, cnt = np.unique(codes, return_inverse=True, return_counts=True)
    thresh = max(params.min_count, int(np.ceil(params.count_frac * D)))
    keep = cnt >= thresh
    if not np.any(keep):
        return WindowResult(None, reason="allfiltered")
    kept = uniq[keep]                       # sorted kmer codes
    nk = len(kept)
    remap = np.full(len(uniq), -1, dtype=np.int64)
    remap[keep] = np.arange(nk)
    kid = remap[inv]                        # per-occurrence kept-index or -1
    ok = kid >= 0

    # occurrence-offset matrix and anchor masks
    O = ol.O
    occ = np.zeros((nk, O), dtype=np.float32)
    oo = np.clip(offs[ok], 0, O - 1)
    np.add.at(occ, (kid[ok], oo), 1.0)
    src_ok = np.zeros(nk, dtype=bool)
    snk_ok = np.zeros(nk, dtype=bool)
    np.logical_or.at(src_ok, kid[ok], is_start[ok])
    np.logical_or.at(snk_ok, kid[ok], is_end[ok])

    # ---- 2b. edges from (k+1)-mer support ------------------------------
    u1, c1 = np.unique(codes1, return_counts=True)
    sup = c1 >= params.edge_min_count
    u1s = u1[sup]
    # (k+1)-mer = prefix kmer * 4 + last base; suffix kmer = code % 4**k
    pref = u1s >> 2  # == u1s // 4
    last = u1s & 3
    mask_k = (1 << (2 * k)) - 1
    suff = ((pref << 2) | last) & mask_k
    # map prefix/suffix codes into kept indices
    pi = np.searchsorted(kept, pref)
    si = np.searchsorted(kept, suff)
    valid = (pi < nk) & (si < nk)
    valid[valid] &= (kept[pi[valid]] == pref[valid]) & (kept[si[valid]] == suff[valid])
    adj = np.zeros((nk, nk), dtype=bool)
    adj[pi[valid], si[valid]] = True
    if not adj.any():
        return WindowResult(None, reason="noedges")

    # ---- 3. position weights -------------------------------------------
    W = ol.weights(occ)                     # [nk, P]
    P = min(ol.P, wlen - k + 1 + params.len_slack)

    # ---- 4. heaviest path DP -------------------------------------------
    score = np.full((P, nk), NEG, dtype=np.float32)
    ptr = np.full((P, nk), -1, dtype=np.int32)
    score[0, src_ok] = W[src_ok, 0]
    adjW = np.where(adj, np.float32(0), NEG)  # [u, v]
    for t in range(1, P):
        prev = score[t - 1][:, None] + adjW   # [u, v]
        best_u = np.argmax(prev, axis=0)
        best = prev[best_u, np.arange(nk)]
        score[t] = np.where(best > NEG / 2, best + W[:, t], NEG)
        ptr[t] = np.where(best > NEG / 2, best_u, -1)

    # admissible ends: sink-anchored kmers at plausible consensus lengths
    t_lo = max(0, wlen - k - params.len_slack)
    t_hi = min(P - 1, wlen - k + params.len_slack)
    end_scores = score[t_lo : t_hi + 1].copy()
    end_scores[:, ~snk_ok] = NEG
    flat = end_scores.reshape(-1)
    # stable: ties resolve to the lowest flat index — a DEFINED order that
    # the native C++ engine (dazz_native.cpp solve_windows) replicates; the
    # default introsort's tie order is implementation-specific
    order = np.argsort(-flat, kind="stable")

    # ---- 5. candidates + rescore ---------------------------------------
    best_err = np.inf
    best_seq = None
    n_cand = 0
    seg_total = sum(len(s) for s in segments)
    packed_segs = pack_segments(segments)   # flattened once for all candidates
    seen_final: set[int] = set()
    for idx in order[: 4 * params.n_candidates]:
        s = flat[idx]
        if s <= NEG / 2 or n_cand >= params.n_candidates:
            break
        t = t_lo + int(idx) // nk
        v = int(idx) % nk
        if v in seen_final:
            continue
        seen_final.add(v)
        # backtrack
        path = np.empty(t + 1, dtype=np.int64)
        cur = v
        for tt in range(t, -1, -1):
            path[tt] = cur
            cur = ptr[tt, cur] if tt > 0 else cur
        # expand k-mer path to bases
        first = kept[path[0]]
        bases = [(first >> (2 * (k - 1 - j))) & 3 for j in range(k)]
        for tt in range(1, t + 1):
            bases.append(int(kept[path[tt]] & 3))
        cand = np.asarray(bases, dtype=np.int8)
        n_cand += 1
        tot = edit_distance_sum(cand, packed_segs)
        err = tot / max(seg_total, 1)
        if err < best_err:
            best_err = err
            best_seq = cand

    if best_seq is None:
        return WindowResult(None, k=k, reason="nopath")
    if best_err > params.max_err:
        return WindowResult(None, err=best_err, k=k, n_candidates=n_cand, reason="badscore")
    return WindowResult(best_seq, err=best_err, k=k, n_candidates=n_cand, reason="ok")
