"""Pairwise alignment primitives (numpy banded edit-distance DP).

Oracle-side equivalent of libmaus2 ``lcs/NP.hpp`` / ``lcs/NNP.hpp`` /
``AlignmentTraceContainer`` (SURVEY.md §2.2; reference file:line citations
pending backfill — mount empty, SURVEY.md §0). Used to

  (a) refine LAS trace-point tiles to base-accurate A->B correspondence when
      cutting windows (the reference's NP role), and
  (b) rescore consensus candidates against window segments (NNP role) in the
      oracle; the production rescorer is the batched device DP in
      ``kernels.rescore``.

The DP is plain unit-cost Levenshtein with an adaptive band, which matches the
reference's edit-distance semantics (NP is an exact O(nd) edit-distance
aligner; a wide-enough band gives the identical optimum).
"""

from __future__ import annotations

import numpy as np

_BIG = 1 << 30


def _native_lib():
    """Soft dependency on the C++ host library (None when unavailable)."""
    try:
        from ..native import load

        return load()
    except Exception:
        return None


def pack_segments(segs: list[np.ndarray]) -> tuple:
    """Flatten a segment list once for repeated :func:`edit_distance_sum`
    calls (the candidate loop rescores the same pile per candidate)."""
    lens = np.asarray([len(s) for s in segs], dtype=np.int32)
    offs = np.zeros(len(segs), dtype=np.int64)
    if len(lens):
        np.cumsum(lens[:-1], out=offs[1:])
    flat = (np.ascontiguousarray(
        np.concatenate([np.asarray(s, np.int8) for s in segs]), dtype=np.int8)
        if len(lens) and lens.sum() else np.zeros(1, np.int8))
    return flat, offs, lens, segs


def edit_distance_sum(cand: np.ndarray, segs) -> int:
    """Sum of exact edit distances of ``cand`` vs each segment.

    ``segs`` is a segment list or a :func:`pack_segments` result. The
    consensus-rescore hot loop (oracle ``window_consensus`` candidates,
    hp-rescue acceptance) as ONE native call when the C++ library is up:
    the per-pair Python row-DP costs ~0.5 ms in interpreter overhead alone,
    ~75 ms per hp-routed window; the native verify-retry banded DP does the
    whole pile in ~100 us."""
    packed = segs if isinstance(segs, tuple) else pack_segments(segs)
    flat, offs, lens, seg_list = packed
    lib = _native_lib()
    if lib is None or not len(lens):
        return sum(edit_distance(cand, s) for s in seg_list)
    import ctypes

    cand = np.ascontiguousarray(cand, dtype=np.int8)
    lib.edit_distance_sum.restype = ctypes.c_int64
    return int(lib.edit_distance_sum(
        cand.ctypes.data_as(ctypes.c_void_p), len(cand),
        flat.ctypes.data_as(ctypes.c_void_p),
        offs.ctypes.data_as(ctypes.c_void_p),
        lens.ctypes.data_as(ctypes.c_void_p), len(lens)))


def edit_distance(a: np.ndarray, b: np.ndarray, band: int | None = None) -> int:
    """Unit-cost edit distance between int8 base arrays.

    ``band=None`` (the default) is EXACT on every host: the native path and
    the Python fallback both use the verify-retry rule (a result d below the
    band slack proves every optimal path stayed interior, so the banded
    value equals the full DP's; otherwise the band doubles). An explicit
    ``band`` requests the plain banded approximation."""
    a = np.asarray(a)
    b = np.asarray(b)
    n, m = len(a), len(b)
    if n == 0:
        return m
    if m == 0:
        return n
    if band is None:
        lib = _native_lib()
        if lib is not None:
            # native exact path (verify-retry banded, see edit_distance_sum)
            import ctypes

            a8 = np.ascontiguousarray(a, dtype=np.int8)
            b8 = np.ascontiguousarray(b, dtype=np.int8)
            offs = np.zeros(1, dtype=np.int64)
            lens = np.asarray([m], dtype=np.int32)
            lib.edit_distance_sum.restype = ctypes.c_int64
            return int(lib.edit_distance_sum(
                a8.ctypes.data_as(ctypes.c_void_p), n,
                b8.ctypes.data_as(ctypes.c_void_p),
                offs.ctypes.data_as(ctypes.c_void_p),
                lens.ctypes.data_as(ctypes.c_void_p), 1))
        # python fallback: same verify-retry exactness rule as the native
        # path, so results never depend on whether the .so built
        B = abs(n - m) + max(16, (max(n, m) >> 2))
        while True:
            d = _edit_distance_banded(a, b, n, m, B)
            if d < B or B > n + m:
                return d
            B *= 2
    return _edit_distance_banded(a, b, n, m, max(band, abs(n - m) + 1))


def _edit_distance_banded(a, b, n: int, m: int, band: int) -> int:
    prev = np.arange(m + 1, dtype=np.int32)
    for i in range(1, n + 1):
        lo = max(1, i - band)
        hi = min(m, i + band)
        cur = np.full(m + 1, _BIG, dtype=np.int32)
        if lo == 1:
            cur[0] = i
        seg = b[lo - 1 : hi]
        sub = prev[lo - 1 : hi] + (seg != a[i - 1])
        dele = prev[lo : hi + 1] + 1
        best = np.minimum(sub, dele)
        # insertion scan cur[j] = min(best[j], cur[j-1]+1) as a prefix-min:
        # cur[j] = min_{j0<=j} vals[j0] + (j - j0)
        vals = np.concatenate(([cur[lo - 1]], best))
        ar = np.arange(len(vals), dtype=np.int32)
        cur[lo - 1 + 1 : hi + 1] = (np.minimum.accumulate(vals - ar) + ar)[1:]
        prev = cur
    return int(prev[m])


def align_path(a: np.ndarray, b: np.ndarray, band: int | None = None) -> tuple[int, np.ndarray]:
    """Full DP with backtrack.

    Returns (distance, a2b) where ``a2b`` has length ``len(a)+1`` and maps every
    A prefix boundary to the aligned B prefix boundary (monotone). This is the
    shape consumed by window cutting: B position of A position ``i`` is
    ``a2b[i]``.
    """
    a = np.asarray(a)
    b = np.asarray(b)
    n, m = len(a), len(b)
    if band is None and n and m:
        lib = _native_lib()
        if lib is not None:
            # native verify-retry banded DP: bit-identical a2b by
            # construction (same backtrack tie order; see dazz_native.cpp
            # align_path), used by window cutting and the hp run-length vote
            import ctypes

            a8 = np.ascontiguousarray(a, dtype=np.int8)
            b8 = np.ascontiguousarray(b, dtype=np.int8)
            a2b = np.zeros(n + 1, dtype=np.int64)
            lib.align_map.restype = ctypes.c_int64
            d = int(lib.align_map(a8.ctypes.data_as(ctypes.c_void_p), n,
                                  b8.ctypes.data_as(ctypes.c_void_p), m,
                                  a2b.ctypes.data_as(ctypes.c_void_p)))
            return d, a2b
    D = np.empty((n + 1, m + 1), dtype=np.int32)
    D[0] = np.arange(m + 1)
    D[:, 0] = np.arange(n + 1)
    ar = np.arange(m + 1, dtype=np.int32)
    for i in range(1, n + 1):
        sub = D[i - 1, :m] + (b != a[i - 1])
        dele = D[i - 1, 1:] + 1
        best = np.minimum(sub, dele)
        vals = np.concatenate(([D[i, 0]], best + 0))
        vals[1:] -= ar[1:]
        D[i, 1:] = (np.minimum.accumulate(vals) + ar)[1:]
    # backtrack, preferring diagonal moves
    a2b = np.zeros(n + 1, dtype=np.int64)
    i, j = n, m
    a2b[n] = m
    while i > 0:
        if j > 0 and D[i, j] == D[i - 1, j - 1] + (a[i - 1] != b[j - 1]):
            i -= 1
            j -= 1
        elif D[i, j] == D[i - 1, j] + 1:
            i -= 1
        else:
            j -= 1
            continue
        a2b[i] = j
    a2b[0] = 0  # global alignment: boundary 0 maps to boundary 0
    return int(D[n, m]), a2b


def infix_distance(needle: np.ndarray, haystack: np.ndarray) -> int:
    """Best edit distance of ``needle`` against any infix of ``haystack``.

    Free start/end gaps in the haystack (classic semi-global alignment); used
    by the Q-score harness to score corrected fragments against the truth.
    """
    a = np.asarray(needle)
    b = np.asarray(haystack)
    n, m = len(a), len(b)
    if n == 0:
        return 0
    if m:
        lib = _native_lib()
        if lib is not None:
            # native Myers search (exact, ~50x the numpy row DP — the
            # Q-score harness's hot loop; parity-tested below)
            import ctypes

            a8 = np.ascontiguousarray(a, dtype=np.int8)
            b8 = np.ascontiguousarray(b, dtype=np.int8)
            lib.infix_distance.restype = ctypes.c_int64
            return int(lib.infix_distance(
                a8.ctypes.data_as(ctypes.c_void_p), n,
                b8.ctypes.data_as(ctypes.c_void_p), m))
    prev = np.zeros(m + 1, dtype=np.int32)  # free start in haystack
    ar = np.arange(m + 1, dtype=np.int32)
    for i in range(1, n + 1):
        sub = prev[:m] + (b != a[i - 1])
        dele = prev[1:] + 1
        best = np.minimum(sub, dele)
        vals = np.concatenate(([np.int32(i)], best))
        vals[1:] -= ar[1:]
        prev = np.minimum.accumulate(vals) + ar
    return int(prev.min())


def overlap_suffix_prefix(a: np.ndarray, b: np.ndarray) -> tuple[int, int, int]:
    """Best alignment of a suffix of ``a`` against a prefix of ``b``.

    Used by window stitching: returns (cost, a_start, b_end) minimizing
    edit cost of a[a_start:] vs b[:b_end], normalized against trivial empty
    overlaps by requiring the aligned span to score better than its length.
    """
    a = np.ascontiguousarray(a, dtype=np.int8)
    b = np.ascontiguousarray(b, dtype=np.int8)
    n, m = len(a), len(b)
    lib = _native_lib()
    if lib is not None and n and m:
        import ctypes

        cost = ctypes.c_int32()
        a_start = ctypes.c_int32()
        b_end = ctypes.c_int32()
        lib.suffix_prefix(a.ctypes.data_as(ctypes.c_void_p), n,
                          b.ctypes.data_as(ctypes.c_void_p), m,
                          ctypes.byref(cost), ctypes.byref(a_start), ctypes.byref(b_end))
        return cost.value, a_start.value, b_end.value
    # classic semi-global formulation: free start in a (first column 0), free
    # end in b. Vectorized rows (this runs once per window during stitching —
    # a Python cell loop here dominated whole-pipeline wall time).
    D = np.empty((n + 1, m + 1), dtype=np.int32)
    D[:, 0] = 0  # suffix start is free
    D[0, :] = np.arange(m + 1)  # b prefix must be consumed from 0
    ar = np.arange(m + 1, dtype=np.int32)
    for i in range(1, n + 1):
        sub = D[i - 1, :m] + (b != a[i - 1])
        dele = D[i - 1, 1:] + 1
        best = np.minimum(sub, dele)
        vals = np.concatenate(([D[i, 0]], best))
        vals[1:] -= ar[1:]
        D[i, 1:] = (np.minimum.accumulate(vals) + ar)[1:]
    # choose b_end minimizing cost - 0.5 * matched_len  (favor long overlaps)
    costs = D[n, :].astype(np.float64) - 0.5 * np.arange(m + 1)
    b_end = int(np.argmin(costs))
    cost = int(D[n, b_end])
    # backtrack for the a-suffix start, with the tie order of the original
    # fill (substitution, then deletion, then insertion)
    i, j = n, b_end
    while j > 0:
        if i > 0 and D[i, j] == D[i - 1, j - 1] + (a[i - 1] != b[j - 1]):
            i -= 1
            j -= 1
        elif i > 0 and D[i, j] == D[i - 1, j] + 1:
            i -= 1
        else:
            j -= 1
    return cost, i, b_end
