"""Pile assembly and windowing: LAS piles -> base-accurate window segments.

Oracle-side equivalent of the reference's L3 layer — the inline pile/window
structures in ``src/daccord.cpp`` that refine trace-point blocks to base-level
correspondences with lcs::NP and cut fixed windows along the A read
(SURVEY.md §3.1 hot loops; reference file:line citations pending backfill —
mount empty, SURVEY.md §0).

Window convention (daccord defaults): windows of length ``w`` (40) advancing by
``a`` (10) along the A read; window ``j`` covers ``[j*a, j*a + w)``. Only
overlaps spanning the whole window contribute a segment.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..formats.dazzdb import DazzDB
from ..formats.las import Overlap
from ..utils.bases import revcomp_ints
from .align import align_path


@dataclass
class RefinedOverlap:
    """An overlap with a base-accurate A->B prefix map over its span."""

    ovl: Overlap
    b_oriented: np.ndarray   # B bases in A-colinear orientation (int8)
    a2b: np.ndarray          # len aepos-abpos+1; b_oriented index per A boundary
    diffs: int


def refine_overlap(ovl: Overlap, a_bases: np.ndarray, b_bases: np.ndarray,
                   tspace: int) -> RefinedOverlap:
    """Refine per-tile trace points to a base-level A->B map.

    ``b_bases`` is the stored B read; it is complemented here when the overlap
    says so (DALIGNER convention: bbpos/bepos are complement-space coords).
    """
    b_or = revcomp_ints(b_bases) if ovl.is_comp else np.asarray(b_bases, dtype=np.int8)
    bounds = ovl.tile_bounds(tspace)
    ntiles = len(bounds) - 1
    trace = ovl.trace
    assert trace.shape[0] == ntiles, (trace.shape, ntiles)

    a2b = np.zeros(ovl.aepos - ovl.abpos + 1, dtype=np.int64)
    bpos = ovl.bbpos
    total_d = 0
    for t in range(ntiles):
        a0, a1 = int(bounds[t]), int(bounds[t + 1])
        blen = int(trace[t, 1])
        atile = a_bases[a0:a1]
        btile = b_or[bpos : bpos + blen]
        d, tile_a2b = align_path(atile, btile)
        total_d += d
        a2b[a0 - ovl.abpos : a1 - ovl.abpos] = bpos + tile_a2b[:-1]
        bpos += blen
    a2b[-1] = bpos
    return RefinedOverlap(ovl=ovl, b_oriented=b_or, a2b=a2b, diffs=total_d)


@dataclass
class WindowSegments:
    """All B segments covering one window of the A read."""

    wstart: int
    wlen: int
    segments: list[np.ndarray]     # int8 arrays, variable length
    breads: list[int]              # source B read ids (for depth caps / QV)


def cut_windows(a_bases: np.ndarray, refined: list[RefinedOverlap],
                w: int = 40, adv: int = 10,
                include_a: bool = True) -> list[WindowSegments]:
    """Cut windows [j*adv, j*adv+w) and collect spanning B segments.

    ``include_a``: the A read's own bases also pile into each window (the
    reference counts the read itself as evidence).
    """
    rlen = len(a_bases)
    out: list[WindowSegments] = []
    nwin = 0 if rlen < w else (rlen - w) // adv + 1
    for j in range(nwin):
        ws, we = j * adv, j * adv + w
        segs: list[np.ndarray] = []
        breads: list[int] = []
        if include_a:
            segs.append(np.asarray(a_bases[ws:we], dtype=np.int8))
            breads.append(-1)
        for r in refined:
            o = r.ovl
            if o.abpos <= ws and o.aepos >= we:
                b0 = int(r.a2b[ws - o.abpos])
                b1 = int(r.a2b[we - o.abpos])
                if b1 > b0:
                    segs.append(r.b_oriented[b0:b1])
                    breads.append(o.bread)
        out.append(WindowSegments(wstart=ws, wlen=w, segments=segs, breads=breads))
    return out


def build_pile_windows(db: DazzDB, aread: int, pile: list[Overlap], tspace: int,
                       w: int = 40, adv: int = 10) -> tuple[np.ndarray, list[WindowSegments]]:
    """Full L3 pass for one A read: decode, refine every overlap, cut windows."""
    a_bases = db.read_bases(aread)
    refined = [refine_overlap(o, a_bases, db.read_bases(o.bread), tspace) for o in pile]
    return a_bases, cut_windows(a_bases, refined, w=w, adv=adv)
