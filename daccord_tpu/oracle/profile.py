"""Error-profile estimation and OffsetLikely position-weight tables.

Equivalent of the reference's error-profile estimation pass and ``OffsetLikely``
structure (``src/daccord.cpp``; named as a real reference structure by
BASELINE.json's north_star — file:line backfill pending, SURVEY.md §0/§8; the
algorithmic role follows the daccord paper, Tischler & Myers bioRxiv 106252).

``OffsetLikely`` answers: for a consensus position ``p`` inside a window, what
is the probability that the segment base realizing it sits at segment offset
``o``? Indels shift offsets; the distribution of the offset of consensus
position ``p`` is the p-fold convolution of the per-base length-increment
distribution

    P(0)      = p_del                      (base missing from the segment)
    P(1 + i)  = (1 - p_del) (1-p_ins) p_ins^i   (base + i following insertions)

The table ``OL[p, o]`` is consumed as a matmul against per-k-mer offset
occurrence counts to produce per-k-mer position weights (BASELINE.json:
"OffsetLikely position-weight scoring runs as a batched matmul").
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .windows import RefinedOverlap


@dataclass
class ErrorProfile:
    p_ins: float
    p_del: float
    p_sub: float
    # homopolymer observation model, fit from the same consensus-vs-segment
    # alignments as the base rates (profile_vs_consensus): the per-base
    # indel intensity inside a run of true length L is
    #     q(L) = hp_base * (1 + hp_slope * min(L-1, hp_cap)),
    # split del:ins by the global p_del:p_ins ratio. hp_base is the L=1
    # anchor — it must be fit jointly with the slope because the GLOBAL
    # p_ins/p_del average over all positions and already absorb run
    # inflation on hp-damaged data. hp_base == 0 means "not fit" (thin
    # data); consumers fall back to the global rates with slope 0. Clean
    # data fits hp_slope ~ 0. Consumed by the hp rescue tier's calibrated
    # run-length vote (oracle/hp.py).
    hp_slope: float = 0.0
    hp_base: float = 0.0
    hp_cap: int = 8

    @property
    def p_err(self) -> float:
        return self.p_ins + self.p_del + self.p_sub

    def save(self, path: str) -> None:
        """Write the profile as JSON (the reference caches its error profile
        in a sidecar file so repeat runs skip the estimation pass).

        Atomic (write + rename): concurrent -J shards racing on the same path
        each leave a complete file, never a torn one."""
        import json
        import os

        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wt") as fh:
            json.dump({"format": "daccord-tpu-eprof-v1", "p_ins": self.p_ins,
                       "p_del": self.p_del, "p_sub": self.p_sub,
                       "hp_slope": self.hp_slope, "hp_base": self.hp_base,
                       "hp_cap": self.hp_cap}, fh)
            fh.write("\n")
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "ErrorProfile":
        """Read an eprof file. v2 files (the retired empirical-OL format,
        which also carried offset counts) still load — the counts are
        ignored; see the retirement note on :class:`OffsetLikely`."""
        import json

        with open(path, "rt") as fh:
            d = json.load(fh)
        if d.get("format") not in ("daccord-tpu-eprof-v1", "daccord-tpu-eprof-v2"):
            raise ValueError(f"{path}: not a daccord-tpu error-profile file")
        return cls(p_ins=float(d["p_ins"]), p_del=float(d["p_del"]),
                   p_sub=float(d["p_sub"]),
                   # pre-r5 files carry no hp fields -> slope 0 (no length
                   # dependence), matching their era's behavior exactly
                   hp_slope=float(d.get("hp_slope", 0.0)),
                   hp_base=float(d.get("hp_base", 0.0)),
                   hp_cap=int(d.get("hp_cap", 8)))


def estimate_profile(refined: list[RefinedOverlap], a_len_total: int | None = None) -> ErrorProfile:
    """Estimate indel/sub rates from base-accurate refined overlaps.

    Op counts come from the a2b prefix maps: an A position whose map advances 0
    is (locally) a deletion in B; advances of 1+i imply i insertions. Since the
    pair error rate is the sum of both reads' error rates, per-read rates are
    half the pair rates (both reads drawn from the same noise process — the
    reference's estimator likewise works on pair alignments).

    NOTE: raw op counts from optimal unit-cost paths carry the del+ins ->
    sub collapse bias quantified (and corrected) in
    :func:`profile_vs_consensus`; the production pipeline uses the two-pass
    estimator (``estimate_profile_two_pass``), which routes through that
    corrected counter. This single-pass variant is kept for diagnostics.
    """
    n_adv0 = 0       # pair deletions
    n_ins = 0        # pair inserted bases
    n_bases = 0
    n_diffs = 0
    for r in refined:
        steps = np.diff(r.a2b)
        n_adv0 += int(np.sum(steps == 0))
        n_ins += int(np.sum(np.maximum(steps - 1, 0)))
        n_bases += len(steps)
        n_diffs += r.diffs
    if n_bases == 0:
        return ErrorProfile(0.08, 0.04, 0.015)
    pair_del = n_adv0 / n_bases
    pair_ins = n_ins / n_bases
    pair_sub = max(n_diffs / n_bases - pair_del - pair_ins, 0.0)
    return ErrorProfile(p_ins=pair_ins / 2, p_del=pair_del / 2, p_sub=pair_sub / 2)


def rough_profile(refined: list[RefinedOverlap]) -> ErrorProfile:
    """First-pass profile from trace diffs alone.

    Pair alignments cannot identify per-read insertion/deletion rates (A and B
    drifts cancel), so the total error rate comes from per-tile diff counts
    (halved: a pair alignment sees both reads' errors) and is split by typical
    long-read proportions. Refined by :func:`profile_vs_consensus` in pass two.
    """
    n_diffs = sum(r.diffs for r in refined)
    n_bases = sum(len(r.a2b) - 1 for r in refined)
    e = 0.5 * n_diffs / max(n_bases, 1)
    e = min(max(e, 0.01), 0.35)
    return ErrorProfile(p_ins=0.55 * e, p_del=0.30 * e, p_sub=0.15 * e)


def profile_vs_consensus(
        pairs: list[tuple[np.ndarray, np.ndarray]]) -> ErrorProfile:
    """Second-pass profile: ops of (segment vs consensus) alignments.

    Each pair is (consensus, segment); the consensus stands in for the truth,
    so op counts give the *single-read* error process directly: a consensus
    base consuming 0 segment bases is a deletion, 2+ an insertion run, and a
    mismatching 1-step a substitution.
    """
    from .align import align_path  # local import to avoid cycle at module load

    HP_CAP = 8   # runlen-1 cap on the slope model (matches the clip regime
    #              where per-base rates saturate; rates above it are pooled)
    n_del = n_ins = n_sub = n_pos = 0
    # run-level hp observations for the slope fit: for each INTERIOR
    # consensus run (length L, base b), the observed same-base length o in
    # the aligned segment span. Per-position indel attribution is unusable
    # here — an optimal path may blame a run's indels on any same-base
    # position or a boundary neighbor — but the run-total o is attribution-
    # free. Edge runs are skipped (truncated by the window cut).
    hp_n = np.zeros(HP_CAP + 1, dtype=np.int64)        # runs per bucket
    hp_ratio = np.zeros(HP_CAP + 1, dtype=np.float64)  # sum of o / L
    hp_sq = np.zeros(HP_CAP + 1, dtype=np.float64)     # sum of (o / L)^2
    hp_L = np.zeros(HP_CAP + 1, dtype=np.float64)      # sum of L (top
    #                                                    bucket pools L>cap)
    for cons, seg in pairs:
        if len(cons) == 0:
            continue
        _, c2s = align_path(cons, seg)
        steps = np.diff(c2s)
        n_del += int(np.sum(steps == 0))
        n_ins += int(np.sum(np.maximum(steps - 1, 0)))
        one = steps == 1
        if np.any(one):
            idx = np.nonzero(one)[0]
            n_sub += int(np.sum(cons[idx] != seg[c2s[idx]]))
        n_pos += len(steps)
        starts = np.concatenate(([0], np.flatnonzero(cons[1:] != cons[:-1]) + 1))
        rl = np.diff(np.concatenate((starts, [len(cons)])))
        ns = len(seg)
        claimed = [0, 0, 0, 0]   # per base: end of the last counted span
        for ri in range(1, len(starts) - 1):   # interior runs only
            s0, L = int(starts[ri]), int(rl[ri])
            b = cons[s0]
            lo = max(int(c2s[s0]), claimed[b])
            hi = max(int(c2s[s0 + L]), lo)
            # greedy same-base span extension: an optimal path may attribute
            # a run-adjacent same-base insertion block to the NEIGHBORING
            # consensus position (identical cost), which would silently drop
            # it from o — absorb contiguous same-base bases on both sides.
            # The per-base `claimed` cursor keeps same-base counted spans
            # disjoint, so a merged piece (deleted spacer between two
            # same-base runs) is counted once, never double-claimed; claims
            # on OTHER bases never block (a different-base neighbor's span
            # routinely covers this run's boundary insertions).
            while hi < ns and seg[hi] == b:
                hi += 1
            while lo > claimed[b] and seg[lo - 1] == b:
                lo -= 1
            claimed[b] = hi
            o = int(np.sum(seg[lo:hi] == b))
            x = min(L - 1, HP_CAP)
            hp_n[x] += 1
            hp_ratio[x] += o / L
            hp_sq[x] += (o / L) ** 2
            hp_L[x] += L
    if n_pos == 0:
        return ErrorProfile(0.08, 0.04, 0.015)
    i_o, d_o, s_o = n_ins / n_pos, n_del / n_pos, n_sub / n_pos

    # De-collapse correction: a unit-cost optimal path represents a deletion
    # with an insertion within ~W positions as one substitution (cost 1 beats
    # del+ins at 2), systematically deflating both indel rates and inflating
    # the sub rate. Invert that mapping to first order: the collapsed mass x
    # satisfies x = d * P(insertion within the +-W collapse window), with
    # d = d_o + x and i = i_o + x the true rates. W=2 from alignment geometry
    # (beyond ~2 positions the intervening bases must match by chance, so
    # collapses die off). Verified on simulated reads with known rates:
    # uncorrected (6.7, 2.8, 3.4)% vs true (8, 4, 1.5)% -> corrected
    # (~8.0, ~4.1, ~2.1)%.
    W = 2
    x = 0.0
    for _ in range(12):
        p_near = 1.0 - (1.0 - min(i_o + x, 0.5)) ** (2 * W + 1)
        x = min((d_o + x) * p_near, s_o)
    p_ins, p_del = i_o + x, d_o + x
    p_sub = max(s_o - x, 0.0)

    # hp observation-model fit: 2-D grid over (q1, s) matching the measured
    # per-bucket mean AND standard deviation of o/L against the vote's
    # generative model (oracle/hp.py hp_length_tables): per-base indel
    # intensity q(x) = q1*(1+s*x), split del:ins by the global ratio, each
    # clipped at 0.45. Per-base same-base contribution is
    # Bern((1-qd)(1-psub)) + Geom(qi), so
    #   E[o/L]  = (1-qd)(1-psub) + qi/(1-qi)
    #   Var[o/L] = (p1(1-p1) + qi/(1-qi)^2) / L          (p1 = surviving)
    # The variance term is essential: a near-symmetric indel process moves
    # the mean hardly at all, and intensity then lives in the spread. Both
    # parameters must come from these curves — the global p_ins/p_del
    # average over all positions and already absorb run inflation, so they
    # cannot anchor x=0. Clean data fits s ~ 0; thin buckets (< 30 runs)
    # are dropped.
    hp_slope = 0.0
    hp_base = 0.0
    got = hp_n >= 30
    if got.sum() >= 3:
        xs = np.arange(HP_CAP + 1, dtype=np.float64)[got]
        nb = hp_n[got].astype(np.float64)
        mean_m = hp_ratio[got] / nb
        sd_m = np.sqrt(np.maximum(hp_sq[got] / nb - mean_m ** 2, 0.0))
        Lb = hp_L[got] / nb
        wts = nb
        tot = p_del + p_ins
        fd = p_del / tot if tot > 0 else 0.33
        fi = 1.0 - fd
        best = None
        for q1 in np.arange(0.01, 0.301, 0.01):
            for s in np.arange(0.0, 6.01, 0.1):
                qd = np.minimum(q1 * fd * (1.0 + s * xs), 0.45)
                qi = np.minimum(q1 * fi * (1.0 + s * xs), 0.45)
                p1 = (1.0 - qd) * (1.0 - p_sub)
                mu = p1 + qi / (1.0 - qi)
                var = (p1 * (1.0 - p1) + qi / (1.0 - qi) ** 2) / Lb
                sd = np.sqrt(var)
                sse = float(np.sum(wts * ((mean_m - mu) ** 2
                                          + (sd_m - sd) ** 2)))
                if best is None or sse < best[0]:
                    best = (sse, float(q1), float(s))
        _, hp_base, hp_slope = best
    return ErrorProfile(p_ins=p_ins, p_del=p_del, p_sub=p_sub,
                        hp_slope=hp_slope, hp_base=hp_base, hp_cap=HP_CAP)


class OffsetLikely:
    """OL[p, o] tables for p in [0, P) and o in [0, O), analytic convolution.

    RETIRED (r4): the empirical-OL blend — mixing measured offset counts
    from the estimation pass into these tables as a pseudo-count prior —
    was measured slightly NEGATIVE in 7/8 mismatch regimes at the
    production sample (r3) and still <= the analytic tables at 4/48/256
    piles (r4 eolprobe: −0.08/−0.32/−0.22 Q vs off). The sampling noise
    hypothesis did not hold at large samples, so the blend and its
    plumbing (offset-count collection, eprof-v2 counts, per-config
    offset_counts threading) were deleted per VERDICT r3 item 9; this
    docstring and BASELINE.md r3/r4 are the record.
    """

    def __init__(self, profile: ErrorProfile, positions: int, max_offset: int,
                 ins_tail: int = 6):
        self.profile = profile
        self.P = positions
        self.O = max_offset
        # per-base length increment distribution, truncated at 1 + ins_tail
        p_del, p_ins = profile.p_del, profile.p_ins
        inc = np.zeros(2 + ins_tail)
        inc[0] = p_del
        rem = 1.0 - p_del
        for i in range(ins_tail + 1):
            inc[1 + i] = rem * (1 - p_ins) * (p_ins ** i)
        inc /= inc.sum()
        self.inc = inc

        ol = np.zeros((positions, max_offset), dtype=np.float64)
        cur = np.zeros(max_offset)
        cur[0] = 1.0  # position 0 sits at offset 0 by construction of the cut
        ol[0] = cur
        for p in range(1, positions):
            cur = np.convolve(cur, inc)[:max_offset]
            s = cur.sum()
            if s > 0:
                cur = cur / s
            ol[p] = cur
        self.table = ol.astype(np.float32)

    def weights(self, occ: np.ndarray) -> np.ndarray:
        """occ: [n_kmers, O] offset occurrence counts -> [n_kmers, P] weights."""
        return occ.astype(np.float32) @ self.table.T
