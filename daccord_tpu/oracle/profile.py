"""Error-profile estimation and OffsetLikely position-weight tables.

Equivalent of the reference's error-profile estimation pass and ``OffsetLikely``
structure (``src/daccord.cpp``; named as a real reference structure by
BASELINE.json's north_star — file:line backfill pending, SURVEY.md §0/§8; the
algorithmic role follows the daccord paper, Tischler & Myers bioRxiv 106252).

``OffsetLikely`` answers: for a consensus position ``p`` inside a window, what
is the probability that the segment base realizing it sits at segment offset
``o``? Indels shift offsets; the distribution of the offset of consensus
position ``p`` is the p-fold convolution of the per-base length-increment
distribution

    P(0)      = p_del                      (base missing from the segment)
    P(1 + i)  = (1 - p_del) (1-p_ins) p_ins^i   (base + i following insertions)

The table ``OL[p, o]`` is consumed as a matmul against per-k-mer offset
occurrence counts to produce per-k-mer position weights (BASELINE.json:
"OffsetLikely position-weight scoring runs as a batched matmul").
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .windows import RefinedOverlap


@dataclass
class ErrorProfile:
    p_ins: float
    p_del: float
    p_sub: float

    @property
    def p_err(self) -> float:
        return self.p_ins + self.p_del + self.p_sub

    def save(self, path: str) -> None:
        """Write the profile as JSON (the reference caches its error profile
        in a sidecar file so repeat runs skip the estimation pass).

        Atomic (write + rename): concurrent -J shards racing on the same path
        each leave a complete file, never a torn one."""
        import json
        import os

        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wt") as fh:
            json.dump({"format": "daccord-tpu-eprof-v1", "p_ins": self.p_ins,
                       "p_del": self.p_del, "p_sub": self.p_sub}, fh)
            fh.write("\n")
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "ErrorProfile":
        """Read an eprof file. v2 files (the retired empirical-OL format,
        which also carried offset counts) still load — the counts are
        ignored; see the retirement note on :class:`OffsetLikely`."""
        import json

        with open(path, "rt") as fh:
            d = json.load(fh)
        if d.get("format") not in ("daccord-tpu-eprof-v1", "daccord-tpu-eprof-v2"):
            raise ValueError(f"{path}: not a daccord-tpu error-profile file")
        return cls(p_ins=float(d["p_ins"]), p_del=float(d["p_del"]),
                   p_sub=float(d["p_sub"]))


def estimate_profile(refined: list[RefinedOverlap], a_len_total: int | None = None) -> ErrorProfile:
    """Estimate indel/sub rates from base-accurate refined overlaps.

    Op counts come from the a2b prefix maps: an A position whose map advances 0
    is (locally) a deletion in B; advances of 1+i imply i insertions. Since the
    pair error rate is the sum of both reads' error rates, per-read rates are
    half the pair rates (both reads drawn from the same noise process — the
    reference's estimator likewise works on pair alignments).

    NOTE: raw op counts from optimal unit-cost paths carry the del+ins ->
    sub collapse bias quantified (and corrected) in
    :func:`profile_vs_consensus`; the production pipeline uses the two-pass
    estimator (``estimate_profile_two_pass``), which routes through that
    corrected counter. This single-pass variant is kept for diagnostics.
    """
    n_adv0 = 0       # pair deletions
    n_ins = 0        # pair inserted bases
    n_bases = 0
    n_diffs = 0
    for r in refined:
        steps = np.diff(r.a2b)
        n_adv0 += int(np.sum(steps == 0))
        n_ins += int(np.sum(np.maximum(steps - 1, 0)))
        n_bases += len(steps)
        n_diffs += r.diffs
    if n_bases == 0:
        return ErrorProfile(0.08, 0.04, 0.015)
    pair_del = n_adv0 / n_bases
    pair_ins = n_ins / n_bases
    pair_sub = max(n_diffs / n_bases - pair_del - pair_ins, 0.0)
    return ErrorProfile(p_ins=pair_ins / 2, p_del=pair_del / 2, p_sub=pair_sub / 2)


def rough_profile(refined: list[RefinedOverlap]) -> ErrorProfile:
    """First-pass profile from trace diffs alone.

    Pair alignments cannot identify per-read insertion/deletion rates (A and B
    drifts cancel), so the total error rate comes from per-tile diff counts
    (halved: a pair alignment sees both reads' errors) and is split by typical
    long-read proportions. Refined by :func:`profile_vs_consensus` in pass two.
    """
    n_diffs = sum(r.diffs for r in refined)
    n_bases = sum(len(r.a2b) - 1 for r in refined)
    e = 0.5 * n_diffs / max(n_bases, 1)
    e = min(max(e, 0.01), 0.35)
    return ErrorProfile(p_ins=0.55 * e, p_del=0.30 * e, p_sub=0.15 * e)


def profile_vs_consensus(
        pairs: list[tuple[np.ndarray, np.ndarray]]) -> ErrorProfile:
    """Second-pass profile: ops of (segment vs consensus) alignments.

    Each pair is (consensus, segment); the consensus stands in for the truth,
    so op counts give the *single-read* error process directly: a consensus
    base consuming 0 segment bases is a deletion, 2+ an insertion run, and a
    mismatching 1-step a substitution.
    """
    from .align import align_path  # local import to avoid cycle at module load

    n_del = n_ins = n_sub = n_pos = 0
    for cons, seg in pairs:
        if len(cons) == 0:
            continue
        _, c2s = align_path(cons, seg)
        steps = np.diff(c2s)
        n_del += int(np.sum(steps == 0))
        n_ins += int(np.sum(np.maximum(steps - 1, 0)))
        one = steps == 1
        if np.any(one):
            idx = np.nonzero(one)[0]
            n_sub += int(np.sum(cons[idx] != seg[c2s[idx]]))
        n_pos += len(steps)
    if n_pos == 0:
        return ErrorProfile(0.08, 0.04, 0.015)
    i_o, d_o, s_o = n_ins / n_pos, n_del / n_pos, n_sub / n_pos

    # De-collapse correction: a unit-cost optimal path represents a deletion
    # with an insertion within ~W positions as one substitution (cost 1 beats
    # del+ins at 2), systematically deflating both indel rates and inflating
    # the sub rate. Invert that mapping to first order: the collapsed mass x
    # satisfies x = d * P(insertion within the +-W collapse window), with
    # d = d_o + x and i = i_o + x the true rates. W=2 from alignment geometry
    # (beyond ~2 positions the intervening bases must match by chance, so
    # collapses die off). Verified on simulated reads with known rates:
    # uncorrected (6.7, 2.8, 3.4)% vs true (8, 4, 1.5)% -> corrected
    # (~8.0, ~4.1, ~2.1)%.
    W = 2
    x = 0.0
    for _ in range(12):
        p_near = 1.0 - (1.0 - min(i_o + x, 0.5)) ** (2 * W + 1)
        x = min((d_o + x) * p_near, s_o)
    return ErrorProfile(p_ins=i_o + x, p_del=d_o + x, p_sub=max(s_o - x, 0.0))


class OffsetLikely:
    """OL[p, o] tables for p in [0, P) and o in [0, O), analytic convolution.

    RETIRED (r4): the empirical-OL blend — mixing measured offset counts
    from the estimation pass into these tables as a pseudo-count prior —
    was measured slightly NEGATIVE in 7/8 mismatch regimes at the
    production sample (r3) and still <= the analytic tables at 4/48/256
    piles (r4 eolprobe: −0.08/−0.32/−0.22 Q vs off). The sampling noise
    hypothesis did not hold at large samples, so the blend and its
    plumbing (offset-count collection, eprof-v2 counts, per-config
    offset_counts threading) were deleted per VERDICT r3 item 9; this
    docstring and BASELINE.md r3/r4 are the record.
    """

    def __init__(self, profile: ErrorProfile, positions: int, max_offset: int,
                 ins_tail: int = 6):
        self.profile = profile
        self.P = positions
        self.O = max_offset
        # per-base length increment distribution, truncated at 1 + ins_tail
        p_del, p_ins = profile.p_del, profile.p_ins
        inc = np.zeros(2 + ins_tail)
        inc[0] = p_del
        rem = 1.0 - p_del
        for i in range(ins_tail + 1):
            inc[1 + i] = rem * (1 - p_ins) * (p_ins ** i)
        inc /= inc.sum()
        self.inc = inc

        ol = np.zeros((positions, max_offset), dtype=np.float64)
        cur = np.zeros(max_offset)
        cur[0] = 1.0  # position 0 sits at offset 0 by construction of the cut
        ol[0] = cur
        for p in range(1, positions):
            cur = np.convolve(cur, inc)[:max_offset]
            s = cur.sum()
            if s > 0:
                cur = cur / s
            ol[p] = cur
        self.table = ol.astype(np.float32)

    def weights(self, occ: np.ndarray) -> np.ndarray:
        """occ: [n_kmers, O] offset occurrence counts -> [n_kmers, P] weights."""
        return occ.astype(np.float32) @ self.table.T
