from .align import edit_distance, align_path, overlap_suffix_prefix, infix_distance
from .windows import refine_overlap, cut_windows, build_pile_windows, WindowSegments, RefinedOverlap
from .profile import ErrorProfile, OffsetLikely, estimate_profile
from .dbg import DBGParams, WindowResult, window_consensus
from .consensus import ConsensusConfig, CorrectedRead, correct_read, solve_window, make_offset_likely, estimate_profile_two_pass

__all__ = [
    "edit_distance", "align_path", "overlap_suffix_prefix", "infix_distance",
    "refine_overlap", "cut_windows", "build_pile_windows", "WindowSegments", "RefinedOverlap",
    "ErrorProfile", "OffsetLikely", "estimate_profile",
    "DBGParams", "WindowResult", "window_consensus",
    "ConsensusConfig", "CorrectedRead", "correct_read", "solve_window", "make_offset_likely", "estimate_profile_two_pass",
]
