"""Homopolymer-robust consensus rescue (run-length-compressed DBG tier).

Motivation (BASELINE.md r3 mismatch table): PacBio-rate indels with a
homopolymer slope — p_indel x (1 + runlen) — push in-run error to the
30-45% clip, where k-mer consensus degenerates: a run >= k is
self-repeating in k-mer space, so the graph cannot count its length, and
the heaviest path picks an essentially arbitrary run length. The r3
measurement: hp-regime Q collapses to 10.7 vs a 26.4 clean control. The
reference's full-graph DBG shares this failure class (a k-mer graph has no
run-length observable either); this tier is a capability the reference does
NOT have — the "beat the reference" item of VERDICT r3 (#2).

Mechanism: in run-length-compressed space the hp indel process is
*invisible* — changing a run's length does not change the compressed
sequence at all. So:

  1. run-length-compress every segment (keep per-position run lengths);
  2. solve the ordinary DBG consensus in compressed space, where only
     substitutions and inter-run indels remain (a LOW-error subproblem);
  3. re-expand the compressed consensus: each position's run length is a
     vote over the run lengths of segment positions that align to it with
     the same base (alignment via the banded edit-distance traceback);
  4. accept the expansion only if its rescored error against the ORIGINAL
     segments beats the direct solver's result (or clears ``max_err`` where
     the direct solver failed) — clean-data non-regression by construction.

Routing is engine-agnostic: the pipeline applies this pass on host after
any engine (JAX device ladder, C++ native, oracle) returns per-window
``err``; only windows that failed or solved badly AND show a long run are
routed, so the clean-data cost is a cheap max-run scan.
"""

from __future__ import annotations

from dataclasses import replace

import numpy as np

from .align import align_path, edit_distance_sum
from .dbg import DBGParams, WindowResult, window_consensus

HP_TIER = 29  # tier code reported for hp-rescued windows (pack_result's
              # 5-bit tier field allows < 31; ConsensusConfig rejects
              # ladders deep enough to collide with this code)


def hp_compress(seg: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Run-length encode: returns (compressed int8 bases, int32 run lengths)."""
    seg = np.asarray(seg, dtype=np.int8)
    n = len(seg)
    if n == 0:
        return seg, np.zeros(0, dtype=np.int32)
    starts = np.concatenate(([0], np.flatnonzero(seg[1:] != seg[:-1]) + 1))
    runs = np.diff(np.concatenate((starts, [n]))).astype(np.int32)
    return seg[starts], runs


def hp_expand(cseq: np.ndarray, runs: np.ndarray) -> np.ndarray:
    return np.repeat(cseq, np.maximum(runs, 1)).astype(np.int8)


def max_run(seg: np.ndarray) -> int:
    """Length of the longest homopolymer run (0 for empty input)."""
    if len(seg) == 0:
        return 0
    return int(hp_compress(seg)[1].max())


_LTAB_CACHE: dict = {}

# heat-multiplier grid for the posterior vote: per-window intensity
# multipliers quantized to [LO, HI] in STEP increments. The ONE definition —
# the python vote, the native table build (native/api.py) and the C++ index
# map (dazz_native.cpp, passed these values) must all agree or votes would
# silently read the wrong table.
HP_HEAT_LO = 1.0
HP_HEAT_HI = 3.0
HP_HEAT_STEP = 0.25
HP_HEAT_N = int(round((HP_HEAT_HI - HP_HEAT_LO) / HP_HEAT_STEP)) + 1


def hp_heat(direct_err: float, p_err: float) -> float:
    """Quantized per-window heat multiplier (shared by python + native)."""
    m = (direct_err / max(p_err, 1e-3)) if np.isfinite(direct_err) else 1.5
    return float(np.clip(round(m / HP_HEAT_STEP) * HP_HEAT_STEP,
                         HP_HEAT_LO, HP_HEAT_HI))


def hp_length_tables(profile, Lmax: int = 20, Omax: int = 56,
                     mult: float = 1.0) -> np.ndarray:
    """``T[L, o] = log P(observed same-base length o | true run length L)``.

    Observation model (matches the fit in profile_vs_consensus): each of the
    L true bases survives with prob (1-qd)(1-psub) and is followed by
    Geom(qi) same-base insertions, with the indel intensity length-scaled:
    q(L) = hp_base * (1 + hp_slope * min(L-1, hp_cap)), split del:ins by the
    global ratio, clipped at 0.45. P(o|L) is the L-fold convolution of the
    per-base contribution. Rows L=1..Lmax; row 0 is unused (-inf).
    An unfit profile (hp_base == 0) falls back to the global rates with
    slope 0 — a flat-rate posterior, still split-robust vs the median.
    """
    key = (round(profile.p_del, 5), round(profile.p_ins, 5),
           round(profile.p_sub, 5), round(profile.hp_slope, 3),
           round(profile.hp_base, 4), profile.hp_cap, Lmax, Omax,
           round(mult, 2))
    hit = _LTAB_CACHE.get(key)
    if hit is not None:
        return hit
    tot = profile.p_del + profile.p_ins
    fd = profile.p_del / tot if tot > 0 else 0.33
    base, slope = profile.hp_base, profile.hp_slope
    if base <= 0.0:
        base, slope = max(tot, 1e-4), 0.0
    # per-window intensity multiplier: the profile's hp fit comes from
    # tier-0-SOLVED sample windows (biased clean on damaged regimes), so a
    # routed window's own direct error rate, relative to the profile, says
    # how much hotter its indel process runs than the fit assumed
    base = base * mult
    T = np.full((Lmax + 1, Omax + 1), -np.inf)
    for L in range(1, Lmax + 1):
        x = min(L - 1, profile.hp_cap)
        qd = min(base * fd * (1.0 + slope * x), 0.45)
        qi = min(base * (1.0 - fd) * (1.0 + slope * x), 0.45)
        q0 = 1.0 - (1.0 - qd) * (1.0 - profile.p_sub)   # contributes no
        # same-base symbol (deleted or substituted); insertions still follow
        gi = (1.0 - qi) * np.power(qi, np.arange(Omax + 1))
        contrib = q0 * gi
        contrib[1:] += (1.0 - q0) * gi[:-1]
        dist = contrib
        for _ in range(L - 1):
            dist = np.convolve(dist, contrib)[: Omax + 1]
        # renormalize the truncation tail so long-L rows stay comparable
        s = dist.sum()
        if s > 0:
            dist = dist / s
        with np.errstate(divide="ignore"):
            T[L] = np.log(dist)
    _LTAB_CACHE[key] = T
    if len(_LTAB_CACHE) > 64:
        _LTAB_CACHE.pop(next(iter(_LTAB_CACHE)))
    return T


def vote_runs_posterior(cons_c: np.ndarray,
                        comp: list[tuple[np.ndarray, np.ndarray]],
                        ltab: np.ndarray) -> np.ndarray:
    """Calibrated per-position run lengths: length-posterior argmax.

    Per segment the observation is the SUM of same-base run lengths over the
    aligned span (split pieces from in-run substitutions are merged — the
    bias the flat median inherits), with one-position greedy extension when
    the optimal path attributed a boundary piece to the neighbor. The vote
    is argmax_L sum_s log P(o_s | L) under the profile-calibrated
    observation model (hp_length_tables); ties break to the smaller L.
    Positions with no evidence keep run length 1.
    """
    n = len(cons_c)
    Lmax = ltab.shape[0] - 1
    Omax = ltab.shape[1] - 1
    ll = np.zeros((n, Lmax + 1))
    nvotes = np.zeros(n, dtype=np.int64)
    for cseg, runs in comp:
        if len(cseg) == 0:
            continue
        m = len(cseg)
        _, a2b = align_path(cons_c, cseg)
        claimed = [0, 0, 0, 0]   # per base: end of the last counted span
        for i in range(n):
            c = cons_c[i]
            lo = max(int(a2b[i]), claimed[c])
            hi = max(int(a2b[i + 1]), lo)
            # greedy one-position extension: a boundary same-base piece the
            # path gave to the neighbor belongs to this run (cons_c runs
            # are maximal, so the immediate neighbor never claims base c).
            # The per-base `claimed` cursor keeps same-base counted spans
            # disjoint — a merged piece (deleted spacer between two
            # same-base runs) is counted by exactly one position.
            if hi < m and cseg[hi] == c:
                hi += 1
            if lo > claimed[c] and cseg[lo - 1] == c:
                lo -= 1
            if hi <= lo:
                continue
            claimed[c] = hi
            o = 0
            for j in range(lo, hi):
                if cseg[j] == c:
                    o += int(runs[j])
            ll[i] += ltab[:, min(o, Omax)]
            nvotes[i] += 1
    out = np.ones(n, dtype=np.int32)
    voted = nvotes > 0
    if voted.any():
        out[voted] = np.argmax(ll[voted, 1:], axis=1).astype(np.int32) + 1
    return out


def hp_loglik(cand: np.ndarray,
              comp: list[tuple[np.ndarray, np.ndarray]],
              ltab: np.ndarray, lam_c: float) -> float:
    """Log-likelihood of the segment data under a candidate sequence.

    The calibrated ACCEPTANCE objective (cfg.hp_accept="likelihood"): the
    candidate is run-length-compressed; each segment contributes its
    run-length observations' log P(o_s | L_i) (the same claim-cursor walk
    as the posterior vote) plus a compressed-space edit penalty
    ``-lam_c * d_c`` (substitutions/inter-run indels are NOT part of the
    length model; lam_c ~ -log(compressed-space per-base error rate)).
    Comparing J across candidates compares how well each explains the SAME
    data — unlike the raw unit-cost rescore, a true-length candidate is not
    charged for fixing the data's own drift.
    """
    cc, cruns = hp_compress(cand)
    n = len(cc)
    if n == 0:
        return -np.inf
    Lmax = ltab.shape[0] - 1
    Omax = ltab.shape[1] - 1
    L_idx = np.clip(cruns, 1, Lmax)
    J = 0.0
    for cseg, runs in comp:
        if len(cseg) == 0:
            continue
        m = len(cseg)
        d_c, a2b = align_path(cc, cseg)
        J -= lam_c * float(d_c)
        claimed = [0, 0, 0, 0]
        for i in range(n):
            c = cc[i]
            lo = max(int(a2b[i]), claimed[c])
            hi = max(int(a2b[i + 1]), lo)
            if hi < m and cseg[hi] == c:
                hi += 1
            if lo > claimed[c] and cseg[lo - 1] == c:
                lo -= 1
            if hi <= lo:
                continue
            claimed[c] = hi
            o = 0
            for j in range(lo, hi):
                if cseg[j] == c:
                    o += int(runs[j])
            v = ltab[int(L_idx[i]), min(o, Omax)]
            if np.isfinite(v):
                J += float(v)
            else:
                J -= 60.0   # impossible-under-model observation: a finite
                #             but crushing penalty (log ~ e-26) so one
                #             outlier cannot veto via -inf
    return J


def vote_runs(cons_c: np.ndarray,
              comp: list[tuple[np.ndarray, np.ndarray]]) -> np.ndarray:
    """Per-position run lengths for the compressed consensus by aligned vote.

    For each compressed segment, the edit-distance traceback maps every
    consensus position to a span of segment positions; run lengths of
    same-base matches are collected and the (rounded) median wins — depth
    ~20 independent noisy run-length observations beat any single read's
    hp-inflated indels. Positions with no evidence keep run length 1.
    """
    n = len(cons_c)
    votes: list[list[int]] = [[] for _ in range(n)]
    for cseg, runs in comp:
        if len(cseg) == 0:
            continue
        _, a2b = align_path(cons_c, cseg)
        for i in range(n):
            lo, hi = int(a2b[i]), int(a2b[i + 1])
            for j in range(lo, hi):
                if cseg[j] == cons_c[i]:
                    votes[i].append(int(runs[j]))
    out = np.ones(n, dtype=np.int32)
    for i, v in enumerate(votes):
        if v:
            out[i] = max(1, int(round(float(np.median(v)))))
    return out


def solve_window_hp(segments: list[np.ndarray], ol, dbg: DBGParams,
                    wlen: int, vote: str = "median",
                    direct_err: float = float("inf")) -> WindowResult | None:
    """Solve one window in run-length-compressed space and re-expand.

    ``ol`` is the tier's OffsetLikely table (compressed-space offsets are a
    subset of its domain — the compressed window is strictly shorter, so the
    table's P/O cover it; the analytic shape is approximate there, which the
    rescoring acceptance rule absorbs). Returns None when the compressed
    subproblem is degenerate or unsolved; the caller keeps the direct result.
    """
    comp = [hp_compress(s) for s in segments]
    clens = [len(c) for c, _ in comp]
    if not clens:
        return None
    wlen_c = int(np.median(clens))
    if wlen_c < dbg.k + 4:
        return None
    res = window_consensus([c for c, _ in comp], ol, dbg, wlen=wlen_c)
    if res.seq is None:
        return None
    prof = ol.profile
    if vote == "posterior" and prof.hp_slope >= 0.1:
        # the calibrated posterior only engages when the PROFILE shows
        # length-dependent indel structure (fitted slope >= 0.1): on clean
        # data the fit is ~0 and the asymmetric observation model (plus the
        # heat multiplier below) over-corrects runs the median gets right —
        # measured −0.42 Q on the clean control without this gate
        # (BASELINE.md r5 vote table)
        # quantized per-window heat (hp_heat): direct_err / profile rate;
        # unsolved windows (no direct err) get a middling boost — they are
        # at least as damaged as the routing threshold implies
        m = hp_heat(direct_err, prof.p_ins + prof.p_del + prof.p_sub)
        runs = vote_runs_posterior(res.seq, comp,
                                   hp_length_tables(prof, mult=m))
    else:
        runs = vote_runs(res.seq, comp)
    seq = hp_expand(res.seq, runs)
    # pathological expansions (a mis-voted giant run) never beat the direct
    # result anyway; bound them before paying the rescore
    if not (wlen // 2 <= len(seq) <= 2 * wlen):
        return None
    tot = sum(len(s) for s in segments)
    err = edit_distance_sum(seq, segments) / max(tot, 1)
    return WindowResult(seq, err=float(err), k=dbg.k, reason="hp")


def hp_candidate(segments: list[np.ndarray], direct_seq, direct_err: float,
                 ol_tables: dict, cfg) -> WindowResult | None:
    """Route + solve + accept gate for one window; None = keep direct result.

    ``cfg`` is a ConsensusConfig. Routing: the window failed or solved with
    err > ``hp_err``, and a run >= ``hp_min_run`` is present (in the direct
    consensus if solved, else in any segment) — without a long run there is
    nothing an hp vote could fix. Acceptance: the expanded candidate must
    beat the direct err by ``hp_margin`` (or clear max_err where the direct
    solver failed).
    """
    solved = direct_seq is not None
    if solved and direct_err <= cfg.hp_err:
        return None
    probe = [direct_seq] if solved else segments
    if max(max_run(s) for s in probe) < cfg.hp_min_run:
        return None
    k, mc, emc = cfg.tiers[0]
    dbg = replace(cfg.dbg, k=k, min_count=mc, edge_min_count=emc)
    res = solve_window_hp(segments, ol_tables[k], dbg, cfg.w,
                          vote=cfg.hp_vote, direct_err=direct_err)
    if res is None:
        return None
    prof = ol_tables[k].profile
    if (cfg.hp_accept == "likelihood" and solved
            and cfg.hp_vote == "posterior" and prof.hp_slope >= 0.1):
        # likelihood-ratio acceptance (hp_loglik): accept the candidate
        # that better EXPLAINS the segments under the calibrated model,
        # instead of the raw unit-cost rescore (which charges a true-length
        # candidate for fixing the data's own drift — BASELINE.md r5
        # anatomy; the expected-deviation variant of this idea measured
        # negative and is recorded there). Same slope gate as the vote;
        # failed-direct windows keep the raw max_err bar below. A loose
        # raw-error sanity bound keeps pathological likelihood wins out.
        ltab = hp_length_tables(
            prof, mult=hp_heat(direct_err,
                               prof.p_ins + prof.p_del + prof.p_sub))
        comp = [hp_compress(s) for s in segments]
        lam_c = cfg.hp_lambda_c
        if (hp_loglik(res.seq, comp, ltab, lam_c)
                > hp_loglik(direct_seq, comp, ltab, lam_c)
                and res.err <= direct_err + 0.10):
            return res
        return None
    bar = (direct_err - cfg.hp_margin) if solved else cfg.dbg.max_err
    if res.err >= bar:
        return None
    return res
