"""Pallas TPU kernel: bounded-length max-plus heaviest-path DP.

The graph-traversal stage of the window solver as a hand-written TPU kernel
(BASELINE.json north_star: "graph construction and heaviest-path traversal
become a Pallas kernel"). One grid step per window; the adjacency block, the
OffsetLikely-weighted position scores, and the DP state all live in VMEM for
the whole P-step recurrence, so the only HBM traffic is one read of the
inputs and one write of the score/backpointer stacks.

Semantics are identical to the lax.scan formulation in ``window_kernel``
(max-plus transition, first-argmax tie-breaking); ``tests/test_pallas.py``
enforces bit-parity. Falls back to interpret mode off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30  # python float: jnp constants may not be captured by pallas kernels


@functools.partial(jax.jit, static_argnames=("interpret",))
def heaviest_path_batch(adjW: jnp.ndarray, wt: jnp.ndarray, s0: jnp.ndarray,
                        interpret: bool = False):
    """adjW [B,M,M] f32 (0 / -inf), wt [B,P,M] f32, s0 [B,M] f32 ->
    (scores [B,P,M] f32, ptrs [B,P,M] i32)."""
    B, M, _ = adjW.shape
    P = wt.shape[1]
    s0 = s0[:, None, :]   # [B, 1, M]: TPU block shapes need >=2 trailing dims
    grid = (B,)
    out = pl.pallas_call(
        _dp_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, M, M), lambda b: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, P, M), lambda b: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, M), lambda b: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, P, M), lambda b: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, P, M), lambda b: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, P, M), jnp.float32),
            jax.ShapeDtypeStruct((B, P, M), jnp.int32),
        ],
        interpret=interpret,
    )(adjW, wt, s0)
    return out


def _dp_kernel(adjW_ref, wt_ref, s0_ref, scores_ref, ptrs_ref):
    # block shapes carry a leading singleton window axis; state stays 2-D
    # ([1, M] rows) throughout — Mosaic's layout inference dislikes 1-D<->2-D
    # reshapes, so the u-axis broadcast goes through broadcast_in_dim.
    P = wt_ref.shape[1]
    M = adjW_ref.shape[1]
    s = s0_ref[0, :, :]                    # [1, M]
    scores_ref[0, 0, :] = s[0, :]
    ptrs_ref[0, 0, :] = jnp.zeros_like(ptrs_ref[0, 0, :])

    def body(t, s):
        # cand[u, v] = s[u] + adjW[u, v]; s is a row over v, broadcast over u
        s_row = jax.lax.broadcast_in_dim(s, (M, M), (0, 1))  # s_row[x, v] = s[0, v]
        cand = jnp.transpose(s_row) + adjW_ref[0, :, :]      # cand[u, v] = s[0, u] + adjW

        best = jnp.max(cand, axis=0, keepdims=True)           # [1, M]
        # explicit first-max tie-break: Mosaic's argmax tie order differs from
        # XLA's; parity with the scan formulation requires the lowest index
        iota_u = jax.lax.broadcasted_iota(jnp.int32, (M, M), 0)
        best_bc = jax.lax.broadcast_in_dim(best, (M, M), (0, 1))
        best_u = jnp.min(jnp.where(cand == best_bc, iota_u, M), axis=0).astype(jnp.int32)
        s_new = jnp.where(best > -5e29, best + wt_ref[0, pl.ds(t, 1), :], -1e30)
        scores_ref[0, t, :] = s_new[0, :]
        ptrs_ref[0, t, :] = best_u
        return s_new

    jax.lax.fori_loop(1, P, body, s)
