"""Fused Pallas TPU kernel: heaviest-path DP + candidate selection + backtrack.

Second-generation Pallas path (VERDICT r3 weak #2 / next-round #4). The r1
DP-only kernel (``pallas_dp``) measured *slower* than the lax.scan
formulation at production M=64 (525k vs 660k bases/s) for a layout reason:
its grid ran one window per step, so every VPU op worked on [M]=64 lanes —
half a lane-width — while the scan path vmaps the whole batch and fills the
vector unit with B. This kernel fixes both findings:

- **tile of TB windows per grid step**: all state is [TB, ..] so vector ops
  are at least TB x M wide (TB=16, M=64 -> 1024 lanes per op);
- **one kernel owns the window from DP to candidates**: the [B, P, M]
  score/pointer stacks live and die in VMEM scratch — the scan path
  materializes both to HBM between the vmapped DP and the backtrack
  (~86 MB round trip per 2048-window batch at M=64) — and only the C
  candidate sequences ([B, C, CL] int32, ~1 MB) leave the kernel.

Graph *construction* (k-mer sort/top-M compaction and the (k+1)-mer support
einsum, ``window_kernel._prep_one``) deliberately stays in XLA: the einsum
is already an MXU matmul, and a 4^k-bin counting histogram does not fit VMEM
for the k=10/12 escalation tiers — sort+top_k is XLA's own strength. The
Myers bit-parallel rescore also stays in XLA (it was the r2/r3 optimization
win and is layout-friendly as a vmapped scan).

Semantics are bit-identical to the scan formulation (first-argmax ties via
explicit min-iota, t-major end-state order, same one-hot backtrack);
``tests/test_pallas.py`` enforces parity. Off-TPU runs use interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30   # python floats: jnp constants may not be captured by kernels
PAD = 4


def gather_pages(pool: jnp.ndarray, table: jnp.ndarray,
                 interpret: bool = False) -> jnp.ndarray:
    """Paged-pool gather: pool [N, PL] int8, table [B, PPW] i32 ->
    [B, PPW, PL] int8 (the Ragged Paged Attention page-fetch pattern,
    arxiv 2604.15464, applied to window segments).

    The flat page table rides the scalar-prefetch lane so page addresses
    are known before the body runs; the pool stays in ANY (compiler-placed,
    HBM at real pool sizes) and each table slot is one
    ``pltpu.make_async_copy`` HBM->VMEM row DMA into the window's output
    block. DMAs are issued per slot and drained at the end of the window's
    loop — correctness-first; widening to multi-page DMAs over
    pool-contiguous runs (which the packer's (window, segment, page) fill
    order makes the common case) is the queued on-chip follow-up next to
    the ``decision:paged`` kernelbench row. Used on TPU behind
    ``use_pallas``; every other backend takes the pure-jnp ``take``
    fallback in ``paging.gather_windows`` (bit-identical; interpret=True
    covers parity tests off-TPU).
    """
    N, PL = pool.shape
    B, PPW = table.shape

    def kern(tbl_ref, pool_ref, out_ref):
        b = pl.program_id(0)

        def scoped(sems):
            def start_slot(p, _):
                page = tbl_ref[b * PPW + p]
                pltpu.make_async_copy(pool_ref.at[page],
                                      out_ref.at[0, p],
                                      sems.at[p]).start()
                return _

            jax.lax.fori_loop(0, PPW, start_slot, 0)

            def wait_slot(p, _):
                page = tbl_ref[b * PPW + p]
                pltpu.make_async_copy(pool_ref.at[page],
                                      out_ref.at[0, p],
                                      sems.at[p]).wait()
                return _

            jax.lax.fori_loop(0, PPW, wait_slot, 0)

        pl.run_scoped(scoped, pltpu.SemaphoreType.DMA((PPW,)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, PPW, PL), lambda b, tbl: (b, 0, 0),
                               memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, PPW, PL), jnp.int8),
        interpret=interpret,
    )(table.reshape(-1).astype(jnp.int32), pool)


def _tile(B: int) -> int:
    for tb in (16, 8, 4, 2):
        if B % tb == 0:
            return tb
    return 1


@functools.partial(jax.jit,
                   static_argnames=("k", "cons_len", "n_candidates", "t_lo",
                                    "t_hi", "interpret"))
def dp_backtrack_batch(adjW: jnp.ndarray, wt: jnp.ndarray, s0: jnp.ndarray,
                       snk_ok: jnp.ndarray, sel: jnp.ndarray, *, k: int,
                       cons_len: int, n_candidates: int, t_lo: int, t_hi: int,
                       interpret: bool = False):
    """adjW [B,M,M] f32, wt [B,P,M] f32, s0/snk_ok/sel [B,M] ->
    (cand [B,C,CL] i32, clen [B,C] i32, ok [B,C] bool).

    C = n_candidates end states with distinct final k-mers, chosen exactly
    like ``window_kernel._finish_one`` (t-major argmax with first-tie)."""
    B, M, _ = adjW.shape
    P = wt.shape[1]
    C, CL = n_candidates, cons_len
    TB = _tile(B)
    kern = functools.partial(_fused_kernel, k=k, CL=CL, C=C, P=P, M=M,
                             TB=TB, t_lo=t_lo, t_hi=t_hi)
    cand, clen, ok = pl.pallas_call(
        kern,
        grid=(B // TB,),
        in_specs=[
            pl.BlockSpec((TB, M, M), lambda g: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, P, M), lambda g: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, 1, M), lambda g: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, 1, M), lambda g: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, 1, M), lambda g: (g, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((TB, C, CL), lambda g: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, 1, C), lambda g: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, 1, C), lambda g: (g, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, C, CL), jnp.int32),
            jax.ShapeDtypeStruct((B, 1, C), jnp.int32),
            jax.ShapeDtypeStruct((B, 1, C), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((TB, P, M), jnp.float32),   # DP scores
            pltpu.VMEM((TB, P, M), jnp.int32),     # DP backpointers
            pltpu.VMEM((TB, P), jnp.int32),        # k-mer codes on the path
        ],
        interpret=interpret,
    )(adjW, wt, s0[:, None, :], snk_ok[:, None, :].astype(jnp.int32),
      sel[:, None, :])
    return cand, clen[:, 0, :], ok[:, 0, :] != 0


def _fused_kernel(adjW_ref, wt_ref, s0_ref, snk_ref, sel_ref,
                  cand_ref, clen_ref, ok_ref,
                  scores_ref, ptrs_ref, kpath_ref,
                  *, k, CL, C, P, M, TB, t_lo, t_hi):
    # ---- heaviest-path max-plus DP, state [TB, M] ----------------------
    s = s0_ref[:, 0, :]                                    # [TB, M]
    scores_ref[:, 0, :] = s
    ptrs_ref[:, 0, :] = jnp.zeros((TB, M), jnp.int32)
    iota_u3 = jax.lax.broadcasted_iota(jnp.int32, (TB, M, M), 1)

    def dp_step(t, s):
        # cand3[w, u, v] = s[w, u] + adjW[w, u, v]
        s3 = jax.lax.broadcast_in_dim(s, (TB, M, M), (0, 1))
        cand3 = s3 + adjW_ref[:, :, :]
        best = jnp.max(cand3, axis=1)                      # [TB, M]
        best3 = jax.lax.broadcast_in_dim(best, (TB, M, M), (0, 2))
        # explicit first-max tie-break: parity with XLA argmax's lowest index
        best_u = jnp.min(jnp.where(cand3 == best3, iota_u3, M),
                         axis=1).astype(jnp.int32)
        s_new = jnp.where(best > NEG / 2, best + wt_ref[:, t, :], NEG)
        scores_ref[:, t, :] = s_new
        ptrs_ref[:, t, :] = best_u
        return s_new

    jax.lax.fori_loop(1, P, dp_step, s)

    # ---- admissible end states -----------------------------------------
    iota_t = jax.lax.broadcasted_iota(jnp.int32, (TB, P, M), 1)
    iota_v = jax.lax.broadcasted_iota(jnp.int32, (TB, P, M), 2)
    t_ok = (iota_t >= t_lo) & (iota_t <= t_hi)
    snk = jax.lax.broadcast_in_dim(snk_ref[:, 0, :] != 0, (TB, P, M), (0, 2))
    final = jnp.where(t_ok & snk, scores_ref[:, :, :], NEG)

    sel_i = sel_ref[:, 0, :]                               # [TB, M] codes
    iota_m = jax.lax.broadcasted_iota(jnp.int32, (TB, M), 1)
    iota_cl = jax.lax.broadcasted_iota(jnp.int32, (TB, CL), 1)
    # tail one-hot: onehot[t, j] = (t == clip(j - k + 1, 0, P-1)); matmul
    # replaces a serializing gather (codes &3 first -> exact in f32)
    jj = jax.lax.broadcasted_iota(jnp.int32, (P, CL), 1)
    ti = jax.lax.broadcasted_iota(jnp.int32, (P, CL), 0)
    onehot_tail = (ti == jnp.clip(jj - k + 1, 0, P - 1)).astype(jnp.float32)

    # Mosaic note (2026-08-02, first real-v5e compile): every intermediate
    # below stays rank>=2 ([TB, 1] instead of [TB]). Rank-1 vectors whose
    # only dim lands on sublanes force an implicit-dim reshape that crashes
    # the v5e Mosaic layout inferer (`inferReshape: arr.size() >=
    # layout_rank` SIGABRT in tpu_compile_helper) — keepdims reductions and
    # [TB, 1] broadcasts avoid the reshape entirely and lower identically.
    chosen = jnp.zeros((TB, M), dtype=jnp.bool_)
    flat_idx = iota_t * M + iota_v
    for c in range(C):
        chosen3 = jax.lax.broadcast_in_dim(chosen, (TB, P, M), (0, 2))
        fmask = jnp.where(chosen3, NEG, final)
        mx = jnp.max(jnp.max(fmask, axis=2), axis=1, keepdims=True)  # [TB,1]
        mx3 = jax.lax.broadcast_in_dim(mx, (TB, P, M), (0, 1))
        idx = jnp.min(jnp.min(jnp.where(fmask == mx3, flat_idx, P * M),
                              axis=2), axis=1, keepdims=True)        # [TB,1]
        t_best = idx // M                                  # [TB, 1]
        v_best = idx % M
        v_bc = jax.lax.broadcast_in_dim(v_best, (TB, M), (0, 1))
        chosen = chosen | (iota_m == v_bc)

        # ---- gather-free one-hot backtrack ----------------------------
        def back_step(i, node):
            t = P - 1 - i
            forced = jnp.where(t == t_best, v_best, node)  # [TB, 1]
            forced = jnp.clip(forced, 0, M - 1)
            oh = iota_m == jax.lax.broadcast_in_dim(forced, (TB, M), (0, 1))
            kmer = jnp.sum(jnp.where(oh, sel_i, 0), axis=1, keepdims=True)
            ptr_val = jnp.sum(jnp.where(oh, ptrs_ref[:, t, :], 0), axis=1,
                              keepdims=True)
            kpath_ref[:, pl.ds(t, 1)] = kmer
            return jnp.where((t <= t_best) & (t > 0), ptr_val, forced)

        jax.lax.fori_loop(0, P, back_step, jnp.zeros_like(v_best))

        kp = kpath_ref[:, :]                               # [TB, P]
        first = jax.lax.broadcast_in_dim(kp[:, 0:1], (TB, CL), (0, 1))
        shifts = jnp.clip(2 * (k - 1 - iota_cl), 0, 30)
        head = jax.lax.shift_right_logical(first, shifts) & 3
        tail = jnp.dot((kp & 3).astype(jnp.float32), onehot_tail,
                       preferred_element_type=jnp.float32).astype(jnp.int32)
        base = jnp.where(iota_cl < k, head, tail)
        tcl = jax.lax.broadcast_in_dim(t_best, (TB, CL), (0, 1))
        cand_ref[:, c, :] = jnp.where(iota_cl < tcl + k, base, PAD)
        clen_ref[:, :, c] = (t_best + k).astype(jnp.int32)
        ok_ref[:, :, c] = (mx > NEG / 2).astype(jnp.int32)
