from .tensorize import BatchShape, WindowBatch, tensorize_windows, pad_batch
from .window_kernel import KernelParams, solve_window_batch
from .tiers import (TierLadder, rescue_candidates, solve_ladder,
                    solve_ladder_split, solve_tier0_async, solve_tiered)
from .paging import (PagedWindowBatch, ShapeFamily, pack_paged, unpack_paged)

__all__ = ["BatchShape", "WindowBatch", "tensorize_windows", "pad_batch",
           "KernelParams", "solve_window_batch", "TierLadder", "solve_tiered",
           "solve_ladder", "solve_ladder_split", "solve_tier0_async",
           "rescue_candidates", "PagedWindowBatch", "ShapeFamily",
           "pack_paged", "unpack_paged"]
