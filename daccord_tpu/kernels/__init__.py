from .tensorize import BatchShape, WindowBatch, tensorize_windows, pad_batch
from .window_kernel import KernelParams, solve_window_batch
from .tiers import TierLadder, solve_tiered, solve_ladder

__all__ = ["BatchShape", "WindowBatch", "tensorize_windows", "pad_batch",
           "KernelParams", "solve_window_batch", "TierLadder", "solve_tiered", "solve_ladder"]
