"""Batched fixed-shape ``handleWindow`` on device (jit/vmap JAX).

Device-side equivalent of the reference's L4 consensus core
(``handleWindow`` / ``DebruijnGraph<k>`` / ``OffsetLikely`` in
``src/daccord.cpp`` — structures named by BASELINE.json north_star; file:line
backfill pending, SURVEY.md §0/§8), re-designed for the MXU/VPU:

- k-mer extraction/packing and (k,k+1)-mer frequency filtering as vmapped jnp
  sort/segment ops (BASELINE.json: "vmapped jnp ops");
- per-window graph compaction to the top-M surviving k-mers; M x M adjacency
  from (k+1)-mer support;
- OffsetLikely position weights as one batched matmul (occ [M,O] x OL [O,P]);
- heaviest path as bounded-length max-plus DP over lax.scan (cycles are
  harmless under a length bound — the reference instead escalates k);
- candidate rescoring as a batched full edit-distance DP with an
  associative-scan prefix-min for the insertion recurrence.

Semantics intentionally mirror ``oracle.dbg.window_consensus`` (tie-breaking
included: k-mers kept in code-sorted order, argmax-first DP ties, t-major end
state order); the parity harness in tests/test_kernels.py enforces this.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

NEG = jnp.float32(-1e30)
PAD = 4


@dataclass(frozen=True)
class KernelParams:
    k: int = 8
    min_count: int = 2
    count_frac: float = 0.0
    edge_min_count: int = 2
    anchor_slack: int = 2
    end_slack: int = 3
    len_slack: int = 8
    n_candidates: int = 3
    min_depth: int = 3
    max_err: float = 0.3
    max_kmers: int = 64
    wlen: int = 40

    @property
    def cons_len(self) -> int:
        # P - 1 + k == wlen + len_slack for every k: one uniform output shape
        return self.wlen + self.len_slack

    @property
    def positions(self) -> int:
        return self.wlen - self.k + 1 + self.len_slack


def _kmer_ids(seqs: jnp.ndarray, lens: jnp.ndarray, k: int) -> jnp.ndarray:
    """[D, L] int8 -> [D, L-k+1] int32 codes; invalid positions = 4**k."""
    D, L = seqs.shape
    npos = L - k + 1
    s = seqs.astype(jnp.int32)
    ids = jnp.zeros((D, npos), dtype=jnp.int32)
    for j in range(k):
        ids = ids * 4 + s[:, j : j + npos]
    valid = (jnp.arange(npos)[None, :] + k) <= lens[:, None]
    return jnp.where(valid, ids, jnp.int32(4**k))


def _edit_distance_row_scan(cand: jnp.ndarray, cand_len: jnp.ndarray,
                            seg: jnp.ndarray, seg_len: jnp.ndarray) -> jnp.ndarray:
    """Unit-cost edit distance of cand[:cand_len] vs seg[:seg_len] (full DP)."""
    L = seg.shape[0]
    ar = jnp.arange(L + 1, dtype=jnp.int32)

    def row(prev, ci):
        cb, i = ci
        sub = prev[:L] + (seg != cb).astype(jnp.int32)
        dele = prev[1:] + 1
        best = jnp.minimum(sub, dele)
        vals = jnp.concatenate([jnp.array([i], dtype=jnp.int32), best - ar[1:]])
        cur = jax.lax.associative_scan(jnp.minimum, vals) + ar
        return cur, cur[seg_len]

    # derive the carry from data so its varying-axes match under shard_map
    init = ar + 0 * seg_len
    _, outs = jax.lax.scan(row, init, (cand.astype(jnp.int32),
                                       jnp.arange(1, cand.shape[0] + 1, dtype=jnp.int32)))
    # outs[i-1] = D[i, seg_len]; i = cand_len
    return jnp.where(cand_len == 0, seg_len,
                     outs[jnp.clip(cand_len - 1, 0, cand.shape[0] - 1)])


def _solve_one(seqs: jnp.ndarray, lens: jnp.ndarray, nsegs: jnp.ndarray,
               ol: jnp.ndarray, p: KernelParams):
    """Solve one window. seqs [D, L] int8, lens [D] i32, ol [P, O] f32."""
    k, M = p.k, p.max_kmers
    D, L = seqs.shape
    npos = L - k + 1
    SENT = jnp.int32(4**k)
    P, O = ol.shape

    # ---- k-mer counting + top-M compaction -----------------------------
    ids = _kmer_ids(seqs, lens, k)                       # [D, npos]
    flat = ids.reshape(-1)
    N = flat.shape[0]
    sorted_ids = jnp.sort(flat)
    newrun = jnp.concatenate([jnp.array([True]), sorted_ids[1:] != sorted_ids[:-1]])
    is_start = newrun & (sorted_ids < SENT)
    run_id = jnp.cumsum(newrun.astype(jnp.int32)) - 1
    counts = jax.ops.segment_sum((sorted_ids < SENT).astype(jnp.int32), run_id, num_segments=N)
    start_counts = jnp.where(is_start, counts[run_id], 0)
    thresh = jnp.maximum(jnp.int32(p.min_count),
                         jnp.ceil(p.count_frac * nsegs).astype(jnp.int32))
    start_counts = jnp.where(start_counts >= thresh, start_counts, 0)
    topv, topi = jax.lax.top_k(start_counts, M)
    sel = jnp.where(topv > 0, sorted_ids[topi], SENT)
    sel = jnp.sort(sel)                                   # oracle order: code-ascending
    sel_valid = sel < SENT

    # ---- occurrences, anchors ------------------------------------------
    eq = (ids[:, :, None] == sel[None, None, :]) & (ids < SENT)[:, :, None]  # [D,npos,M]
    occ_pos = jnp.sum(eq, axis=0).astype(jnp.float32)     # [npos, M]
    o_idx = jnp.minimum(jnp.arange(npos), O - 1)
    occ = jax.ops.segment_sum(occ_pos, o_idx, num_segments=O).T  # [M, O]

    offs = jnp.arange(npos)[None, :, None]
    src_ok = jnp.any(eq & (offs <= p.anchor_slack), axis=(0, 1))
    end_lo = (lens - k - p.end_slack)[:, None, None]
    snk_ok = jnp.any(eq & (offs >= end_lo), axis=(0, 1))

    # ---- (k+1)-mer edge support ----------------------------------------
    ids1 = _kmer_ids(seqs, lens, k + 1).reshape(-1)
    sorted1 = jnp.sort(ids1)
    q = sel[:, None] * 4 + jnp.arange(4)[None, :]         # [M, 4]
    ext = (jnp.searchsorted(sorted1, q.reshape(-1), side="right")
           - jnp.searchsorted(sorted1, q.reshape(-1), side="left")).reshape(M, 4)
    mask_km1 = jnp.int32(4 ** (k - 1) - 1)
    compat = (sel[:, None] & mask_km1) == (sel[None, :] >> 2)
    support = jnp.take_along_axis(ext, (sel & 3)[None, :].repeat(M, axis=0), axis=1)
    adj = (compat & (support >= p.edge_min_count)
           & sel_valid[:, None] & sel_valid[None, :])

    # ---- position weights + heaviest-path DP ---------------------------
    W = occ @ ol.T                                        # [M, P]
    adjW = jnp.where(adj, jnp.float32(0), NEG)
    score0 = jnp.where(src_ok & sel_valid, W[:, 0], NEG)

    def step(s_prev, t):
        cand = s_prev[:, None] + adjW                     # [u, v]
        best_u = jnp.argmax(cand, axis=0)
        best = jnp.max(cand, axis=0)
        s_new = jnp.where(best > NEG / 2, best + W[:, t], NEG)
        return s_new, (s_new, best_u.astype(jnp.int32))

    _, (scores_rest, ptrs_rest) = jax.lax.scan(step, score0, jnp.arange(1, P))
    scores = jnp.concatenate([score0[None], scores_rest])  # [P, M]
    ptrs = jnp.concatenate([jnp.zeros((1, M), jnp.int32), ptrs_rest])

    t_lo = max(0, p.wlen - k - p.len_slack)
    t_hi = min(P - 1, p.wlen - k + p.len_slack)
    t_ok = (jnp.arange(P) >= t_lo) & (jnp.arange(P) <= t_hi)
    final = jnp.where(t_ok[:, None] & snk_ok[None, :], scores, NEG)

    # ---- candidates: top states with distinct final k-mer --------------
    CL = p.cons_len
    seg_total = jnp.maximum(jnp.sum(lens), 1).astype(jnp.float32)

    def backtrack(t_best, v_best):
        def back(v, t):
            node = jnp.where(t == t_best, v_best, v)
            node = jnp.clip(node, 0, M - 1)
            nxt = jnp.where((t <= t_best) & (t > 0), ptrs[t, node], node)
            return nxt, node
        _, nodes_rev = jax.lax.scan(back, 0 * v_best, jnp.arange(P - 1, -1, -1))
        path = nodes_rev[::-1]                            # [P]
        first = sel[path[0]]
        j = jnp.arange(CL)
        shifts = 2 * (k - 1 - j)
        head = (first >> jnp.clip(shifts, 0, 30)) & 3
        tt = jnp.clip(j - k + 1, 0, P - 1)
        tail = sel[path[tt]] & 3
        base = jnp.where(j < k, head, tail)
        cons = jnp.where(j < t_best + k, base, PAD).astype(jnp.int8)
        return cons, (t_best + k).astype(jnp.int32)

    def rescore(cons, cons_len):
        dists = jax.vmap(lambda sg, sl: _edit_distance_row_scan(cons, cons_len, sg, sl))(
            seqs, lens)
        dists = jnp.where(lens > 0, dists, 0)
        return jnp.sum(dists).astype(jnp.float32) / seg_total

    chosen = jnp.zeros(M, dtype=bool)
    best_err = jnp.float32(jnp.inf)
    best_cons = jnp.full(CL, PAD, dtype=jnp.int8)
    best_len = jnp.int32(0)
    any_path = jnp.bool_(False)
    for _ in range(p.n_candidates):
        fmask = jnp.where(chosen[None, :], NEG, final)
        idx = jnp.argmax(fmask.reshape(-1))
        sc = fmask.reshape(-1)[idx]
        ok = sc > NEG / 2
        t_best = (idx // M).astype(jnp.int32)
        v_best = (idx % M).astype(jnp.int32)
        cons, clen = backtrack(t_best, v_best)
        err = jnp.where(ok, rescore(cons, clen), jnp.float32(jnp.inf))
        better = ok & (err < best_err)
        best_err = jnp.where(better, err, best_err)
        best_cons = jnp.where(better, cons, best_cons)
        best_len = jnp.where(better, clen, best_len)
        any_path = any_path | ok
        chosen = chosen.at[v_best].set(True)

    solved = (any_path & (best_err <= p.max_err) & (nsegs >= p.min_depth))
    out_cons = jnp.where(solved, best_cons, PAD).astype(jnp.int8)
    return dict(cons=out_cons,
                cons_len=jnp.where(solved, best_len, 0),
                err=jnp.where(any_path, best_err, jnp.float32(jnp.inf)),
                solved=solved)


@functools.partial(jax.jit, static_argnames=("params",))
def solve_window_batch(seqs: jnp.ndarray, lens: jnp.ndarray, nsegs: jnp.ndarray,
                       ol: jnp.ndarray, params: KernelParams):
    """Solve a batch: seqs [B,D,L] int8, lens [B,D] i32, nsegs [B] i32,
    ol [P,O] f32 (the OffsetLikely table for params.k)."""
    fn = functools.partial(_solve_one, p=params)
    return jax.vmap(fn, in_axes=(0, 0, 0, None))(seqs, lens, nsegs, ol)
