"""Batched fixed-shape ``handleWindow`` on device (jit/vmap JAX).

Device-side equivalent of the reference's L4 consensus core
(``handleWindow`` / ``DebruijnGraph<k>`` / ``OffsetLikely`` in
``src/daccord.cpp`` — structures named by BASELINE.json north_star; file:line
backfill pending, SURVEY.md §0/§8), re-designed for the MXU/VPU:

- k-mer extraction/packing and (k,k+1)-mer frequency filtering as vmapped jnp
  sort/segment ops (BASELINE.json: "vmapped jnp ops");
- per-window graph compaction to the top-M surviving k-mers; M x M adjacency
  from (k+1)-mer support;
- OffsetLikely position weights as one batched matmul (occ [M,O] x OL [O,P]);
- heaviest path as bounded-length max-plus DP over lax.scan (cycles are
  harmless under a length bound — the reference instead escalates k);
- candidate rescoring as a batched bit-parallel (Myers/Hyyrö) edit-distance
  DP: the whole DP column packed into uint32 lanes, one scan step per
  segment base.

Semantics intentionally mirror ``oracle.dbg.window_consensus`` (tie-breaking
included: k-mers kept in code-sorted order, argmax-first DP ties, t-major end
state order); the parity harness in tests/test_kernels.py enforces this.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# numpy (not jnp) scalars: a module-level jnp constant would initialize the
# default backend at import time — importing the library must not touch a
# device (the CLI's --backend=cpu override runs after import)
NEG = np.float32(-1e30)
PAD = 4


@dataclass(frozen=True)
class KernelParams:
    k: int = 8
    min_count: int = 2
    count_frac: float = 0.0
    edge_min_count: int = 2
    anchor_slack: int = 2
    end_slack: int = 3
    len_slack: int = 8
    n_candidates: int = 3
    min_depth: int = 3
    max_err: float = 0.3
    max_kmers: int = 64
    wlen: int = 40

    @property
    def cons_len(self) -> int:
        # P - 1 + k == wlen + len_slack for every k: one uniform output shape
        return self.wlen + self.len_slack

    @property
    def positions(self) -> int:
        return self.wlen - self.k + 1 + self.len_slack


def _kmer_ids(seqs: jnp.ndarray, lens: jnp.ndarray, k: int) -> jnp.ndarray:
    """[D, L] int8 -> [D, L-k+1] int32 codes; invalid positions = 4**k."""
    D, L = seqs.shape
    npos = L - k + 1
    s = seqs.astype(jnp.int32)
    ids = jnp.zeros((D, npos), dtype=jnp.int32)
    for j in range(k):
        ids = ids * 4 + s[:, j : j + npos]
    valid = (jnp.arange(npos)[None, :] + k) <= lens[:, None]
    return jnp.where(valid, ids, jnp.int32(4**k))


_BIG = np.int32(1 << 20)


def _edit_distance_row_scan(cand: jnp.ndarray, cand_len: jnp.ndarray,
                            seg: jnp.ndarray, seg_len: jnp.ndarray) -> jnp.ndarray:
    """Unit-cost edit distance of cand[:cand_len] vs seg[:seg_len] (full DP).

    Row-scan formulation (reference implementation; superseded on the hot path
    by :func:`_edit_distance_antidiag`, kept for cross-checking)."""
    L = seg.shape[0]
    ar = jnp.arange(L + 1, dtype=jnp.int32)

    def row(prev, ci):
        cb, i = ci
        sub = prev[:L] + (seg != cb).astype(jnp.int32)
        dele = prev[1:] + 1
        best = jnp.minimum(sub, dele)
        vals = jnp.concatenate([jnp.array([i], dtype=jnp.int32), best - ar[1:]])
        cur = jax.lax.associative_scan(jnp.minimum, vals) + ar
        return cur, cur[seg_len]

    # derive the carry from data so its varying-axes match under shard_map
    init = ar + 0 * seg_len
    _, outs = jax.lax.scan(row, init, (cand.astype(jnp.int32),
                                       jnp.arange(1, cand.shape[0] + 1, dtype=jnp.int32)))
    # outs[i-1] = D[i, seg_len]; i = cand_len
    return jnp.where(cand_len == 0, seg_len,
                     outs[jnp.clip(cand_len - 1, 0, cand.shape[0] - 1)])


def _edit_distance_antidiag(cand: jnp.ndarray, cand_len: jnp.ndarray,
                            seg: jnp.ndarray, seg_len: jnp.ndarray) -> jnp.ndarray:
    """Exact edit distance via an anti-diagonal wavefront.

    All three DP dependencies of diagonal ``d`` live on ``d-1``/``d-2``, so
    every cell of a diagonal is computed in one vector op. Superseded on the
    hot path by :func:`_edit_distance_myers` (fewer steps, 4 uint32 of state
    per pair instead of two length-``n+1`` carries); kept for cross-checking.
    """
    n = cand.shape[0]
    m = seg.shape[0]
    ar = jnp.arange(n + 1, dtype=jnp.int32)
    # seg_ext[n+1+m-d + i] == seg[d-1-i] (sentinel 9 outside; padded on both
    # ends so the dynamic_slice start never clamps)
    seg_ext = jnp.concatenate([jnp.full(n + 1, 9, jnp.int32),
                               seg[::-1].astype(jnp.int32),
                               jnp.full(n + 1, 9, jnp.int32)])
    cand_sh = jnp.concatenate([jnp.array([8], jnp.int32), cand.astype(jnp.int32)])

    A0 = jnp.where(ar == 0, 0, _BIG) + 0 * seg_len   # diag 0 (data-derived carry)
    Am1 = jnp.full(n + 1, _BIG) + 0 * seg_len

    def step(carry, d):
        Ap, App = carry        # diag d-1, d-2
        sh_p = jnp.concatenate([jnp.array([_BIG]), Ap[:-1]])
        sh_pp = jnp.concatenate([jnp.array([_BIG]), App[:-1]])
        svec = jax.lax.dynamic_slice(seg_ext, (n + 1 + m - d,), (n + 1,))
        mis = (cand_sh != svec).astype(jnp.int32)
        A = jnp.minimum(jnp.minimum(sh_pp + mis, sh_p + 1), Ap + 1)
        A = jnp.where(ar == d, d, A)                      # j == 0 boundary
        A = jnp.where((ar == 0) & (d <= m), d, A)         # i == 0 boundary
        A = jnp.where((ar > d) | (d - ar > m), _BIG, A)   # outside the matrix
        return (A, Ap), A[cand_len]

    _, outs = jax.lax.scan(step, (A0, Am1), jnp.arange(1, n + m + 1))
    outs = jnp.concatenate([A0[cand_len][None], outs])
    return outs[cand_len + seg_len]


def _edit_distance_myers(cand: jnp.ndarray, cand_len: jnp.ndarray,
                         seg: jnp.ndarray, seg_len: jnp.ndarray) -> jnp.ndarray:
    """Exact edit distance via Myers/Hyyrö bit-parallel DP (2x uint32 words).

    The whole DP column lives in four uint32 lanes (VP/VN over two 32-bit
    words), so each of the ``m`` scan steps is ~20 scalar bitwise ops per
    (candidate, segment) pair — versus the anti-diagonal wavefront's
    ``n+m`` steps over an ``n+1``-vector. Hot-path rescore formulation;
    bit-parity with :func:`_edit_distance_antidiag` is enforced in tests.
    Supports cand_len <= 64 (cons_len is 48 at the default w=40).
    """
    n = cand.shape[0]
    if n > 64:  # static shape: only two 32-bit words of DP column are kept
        return _edit_distance_antidiag(cand, cand_len, seg, seg_len)
    u32 = jnp.uint32
    pos = jnp.arange(n)
    valid = pos < cand_len
    w_of = (pos >> 5).astype(jnp.int32)
    b_of = (pos & 31).astype(u32)

    def peq_word(c, w):
        hit = valid & (cand.astype(jnp.int32) == c) & (w_of == w)
        return jnp.sum(jnp.where(hit, u32(1) << b_of, u32(0)), dtype=u32)

    peq = jnp.stack([jnp.stack([peq_word(c, w) for w in range(2)])
                     for c in range(4)])                     # [4, 2] u32
    nn = cand_len.astype(u32)

    def ones_mask(k):                                         # k low one-bits
        k = jnp.minimum(k, u32(32))
        return jnp.where(k == 0, u32(0), u32(0xFFFFFFFF) >> (u32(32) - k))

    vp0_i = ones_mask(jnp.minimum(nn, u32(32)))
    vp1_i = ones_mask(jnp.where(nn > 32, nn - u32(32), u32(0)))
    hb_w = ((cand_len - 1) >> 5).astype(jnp.int32)            # top-bit word/bit
    hb_b = ((cand_len - 1) & 31).astype(u32)
    hb0 = jnp.where(hb_w == 0, u32(1) << hb_b, u32(0))
    hb1 = jnp.where(hb_w == 1, u32(1) << hb_b, u32(0))

    def step(carry, ct):
        vp0, vp1, vn0, vn1, score = carry
        sel4 = jnp.arange(4) == ct                            # PAD(4) -> Eq=0
        e0 = jnp.sum(jnp.where(sel4, peq[:, 0], u32(0)), dtype=u32)
        e1 = jnp.sum(jnp.where(sel4, peq[:, 1], u32(0)), dtype=u32)
        x0 = e0 | vn0
        x1 = e1 | vn1
        a0 = x0 & vp0
        a1 = x1 & vp1
        s0 = vp0 + a0                                         # add with carry
        s1 = vp1 + a1 + (s0 < a0).astype(u32)
        d00 = (s0 ^ vp0) | x0
        d01 = (s1 ^ vp1) | x1
        hn0 = vp0 & d00
        hn1 = vp1 & d01
        hp0 = vn0 | ~(vp0 | d00)
        hp1 = vn1 | ~(vp1 | d01)
        up = ((hp0 & hb0) | (hp1 & hb1)) != 0
        dn = ((hn0 & hb0) | (hn1 & hb1)) != 0
        score = score + jnp.where(up, 1, jnp.where(dn, -1, 0))
        x20 = (hp0 << 1) | u32(1)                             # D[0,j]=j carry-in
        x21 = (hp1 << 1) | (hp0 >> 31)
        h20 = hn0 << 1
        h21 = (hn1 << 1) | (hn0 >> 31)
        vn0 = x20 & d00
        vn1 = x21 & d01
        vp0 = h20 | ~(x20 | d00)
        vp1 = h21 | ~(x21 | d01)
        return (vp0, vp1, vn0, vn1, score), score

    # derive every carry component from data so varying-axes match under
    # shard_map (an unvarying literal init vs a varying carry output is a
    # scan type error)
    init = (vp0_i, vp1_i, u32(0) * nn, u32(0) * nn, cand_len.astype(jnp.int32))
    _, outs = jax.lax.scan(step, init, seg.astype(jnp.int32))
    outs = jnp.concatenate([cand_len.astype(jnp.int32)[None], outs])
    return jnp.where(cand_len == 0, seg_len, outs[seg_len])


def _prep_one(seqs: jnp.ndarray, lens: jnp.ndarray, nsegs: jnp.ndarray,
              ol: jnp.ndarray, p: KernelParams) -> dict:
    """Graph construction for one window: k-mer counting/compaction, (k,k+1)
    edge support, OffsetLikely position weights, source/sink anchors.

    Split from the path DP + candidate stages so the DP can run either as the
    in-vmap lax.scan or as the batch-wide Pallas kernel (pallas_dp)."""
    k, M = p.k, p.max_kmers
    D, L = seqs.shape
    npos = L - k + 1
    SENT = jnp.int32(4**k)
    P, O = ol.shape

    # ---- k-mer counting + top-M compaction -----------------------------
    ids = _kmer_ids(seqs, lens, k)                       # [D, npos]
    flat = ids.reshape(-1)
    N = flat.shape[0]
    sorted_ids = jnp.sort(flat)
    newrun = jnp.concatenate([jnp.array([True]), sorted_ids[1:] != sorted_ids[:-1]])
    is_start = newrun & (sorted_ids < SENT)
    # run length at each run start = next run start - this index, via a reverse
    # cummin of run-start indices (no segment scatter, no gather — both are
    # serialization points on TPU; invalid ids sort last so every valid run is
    # terminated by the sentinel run or the array end)
    ar_n = jnp.arange(N, dtype=jnp.int32)
    starts = jnp.where(newrun, ar_n, jnp.int32(N))
    nxt = jnp.concatenate([starts[1:], jnp.array([N], jnp.int32)])
    nxt = jax.lax.associative_scan(jnp.minimum, nxt, reverse=True)
    start_counts = jnp.where(is_start, nxt - ar_n, 0)
    thresh = jnp.maximum(jnp.int32(p.min_count),
                         jnp.ceil(p.count_frac * nsegs).astype(jnp.int32))
    start_counts = jnp.where(start_counts >= thresh, start_counts, 0)
    topv, topi = jax.lax.top_k(start_counts, M)
    sel = jnp.where(topv > 0, sorted_ids[topi], SENT)
    sel = jnp.sort(sel)                                   # oracle order: code-ascending
    sel_valid = sel < SENT

    # ---- occurrences, anchors ------------------------------------------
    eq = (ids[:, :, None] == sel[None, None, :]) & (ids < SENT)[:, :, None]  # [D,npos,M]
    occ_pos = jnp.sum(eq, axis=0).astype(jnp.float32)     # [npos, M]
    o_idx = jnp.minimum(jnp.arange(npos), O - 1)
    occ = jax.ops.segment_sum(occ_pos, o_idx, num_segments=O).T  # [M, O]

    offs = jnp.arange(npos)[None, :, None]
    src_ok = jnp.any(eq & (offs <= p.anchor_slack), axis=(0, 1))
    end_lo = (lens - k - p.end_slack)[:, None, None]
    snk_ok = jnp.any(eq & (offs >= end_lo), axis=(0, 1))

    # ---- (k+1)-mer edge support ----------------------------------------
    # every occurrence of the (k+1)-mer u.c has ids[i]==u and ids[i+1]==v
    # (v = the (k-1)-overlap successor), so its count is exactly the number of
    # adjacent (kept, kept) position pairs — one bf16 matmul on the MXU
    # instead of a sorted search (profiled 100x faster on TPU).
    eqh = eq.astype(jnp.bfloat16)
    support = jnp.einsum("diu,div->uv", eqh[:, :-1, :], eqh[:, 1:, :],
                         preferred_element_type=jnp.float32)
    mask_km1 = jnp.int32(4 ** (k - 1) - 1)
    compat = (sel[:, None] & mask_km1) == (sel[None, :] >> 2)
    adj = (compat & (support >= p.edge_min_count)
           & sel_valid[:, None] & sel_valid[None, :])

    # ---- position weights ----------------------------------------------
    W = occ @ ol.T                                        # [M, P]
    adjW = jnp.where(adj, jnp.float32(0), NEG)
    score0 = jnp.where(src_ok & sel_valid, W[:, 0], NEG)
    # top-M cap diagnostics: did more k-mers survive the frequency filter
    # than the compacted active set holds? (the only source of kernel-vs-
    # oracle disagreement; counted per window in pipeline stats)
    m_overflow = jnp.sum((start_counts > 0).astype(jnp.int32)) > M
    return dict(sel=sel, adjW=adjW, W=W, score0=score0, snk_ok=snk_ok,
                m_overflow=m_overflow)


def _dp_scan_one(adjW: jnp.ndarray, W: jnp.ndarray, score0: jnp.ndarray):
    """Heaviest-path max-plus DP for one window (lax.scan formulation).

    Semantically identical to ``pallas_dp.heaviest_path_batch`` (bit-parity
    enforced in tests/test_pallas.py); W is [M, P]."""
    P = W.shape[1]
    M = adjW.shape[0]

    def step(s_prev, t):
        cand = s_prev[:, None] + adjW                     # [u, v]
        best_u = jnp.argmax(cand, axis=0)
        best = jnp.max(cand, axis=0)
        s_new = jnp.where(best > NEG / 2, best + W[:, t], NEG)
        return s_new, (s_new, best_u.astype(jnp.int32))

    _, (scores_rest, ptrs_rest) = jax.lax.scan(step, score0, jnp.arange(1, P))
    scores = jnp.concatenate([score0[None], scores_rest])  # [P, M]
    ptrs = jnp.concatenate([jnp.zeros((1, M), jnp.int32), ptrs_rest])
    return scores, ptrs


def _finish_one(seqs: jnp.ndarray, lens: jnp.ndarray, nsegs: jnp.ndarray,
                scores: jnp.ndarray, ptrs: jnp.ndarray, sel: jnp.ndarray,
                snk_ok: jnp.ndarray, p: KernelParams):
    """Candidate extraction + rescore for one window, given the DP result."""
    k, M = p.k, p.max_kmers
    P = scores.shape[0]

    t_lo = max(0, p.wlen - k - p.len_slack)
    t_hi = min(P - 1, p.wlen - k + p.len_slack)
    t_ok = (jnp.arange(P) >= t_lo) & (jnp.arange(P) <= t_hi)
    final = jnp.where(t_ok[:, None] & snk_ok[None, :], scores, NEG)

    # ---- candidates: top states with distinct final k-mer --------------
    CL = p.cons_len

    # gather-free backtrack: the pointer chase and the path->k-mer lookup both
    # run as one-hot multiply-reduces over the M lanes (per-step dynamic
    # gathers serialize on TPU; this was the kernel's largest cost)
    rev_ptrs = ptrs[::-1]
    ts_rev = jnp.arange(P - 1, -1, -1)
    ar_m = jnp.arange(M, dtype=jnp.int32)

    def backtrack(t_best, v_best):
        def back(v, xt):
            ptr_t, t = xt
            node = jnp.where(t == t_best, v_best, v)
            node = jnp.clip(node, 0, M - 1)
            onehot = ar_m == node
            kmer = jnp.sum(jnp.where(onehot, sel, 0))
            ptr_val = jnp.sum(jnp.where(onehot, ptr_t, 0))
            nxt = jnp.where((t <= t_best) & (t > 0), ptr_val, node)
            return nxt, kmer
        _, kmers_rev = jax.lax.scan(back, 0 * v_best, (rev_ptrs, ts_rev))
        kpath = kmers_rev[::-1]                           # [P] k-mer codes
        first = kpath[0]
        j = jnp.arange(CL)
        shifts = 2 * (k - 1 - j)
        head = (first >> jnp.clip(shifts, 0, 30)) & 3
        tt = jnp.clip(j - k + 1, 0, P - 1)                # constant indices
        tail = kpath[tt] & 3
        base = jnp.where(j < k, head, tail)
        cons = jnp.where(j < t_best + k, base, PAD).astype(jnp.int8)
        return cons, (t_best + k).astype(jnp.int32)

    # pick the top-n_candidates end states with distinct final k-mers (cheap
    # argmax loop), then backtrack all of them in ONE vmapped scan
    chosen = jnp.zeros(M, dtype=bool)
    tbs, vbs, oks = [], [], []
    for _ in range(p.n_candidates):
        fmask = jnp.where(chosen[None, :], NEG, final)
        idx = jnp.argmax(fmask.reshape(-1))
        sc = fmask.reshape(-1)[idx]
        t_best = (idx // M).astype(jnp.int32)
        v_best = (idx % M).astype(jnp.int32)
        tbs.append(t_best)
        vbs.append(v_best)
        oks.append(sc > NEG / 2)
        chosen = chosen | (ar_m == v_best)
    cand_arr, clen_arr = jax.vmap(backtrack)(jnp.stack(tbs), jnp.stack(vbs))
    ok_arr = jnp.stack(oks)                           # [C]
    return _rescore_pick_one(seqs, lens, nsegs, cand_arr, clen_arr, ok_arr, p)


def _rescore_pick_one(seqs, lens, nsegs, cand_arr, clen_arr, ok_arr,
                      p: KernelParams):
    """Myers-rescore the C candidates of one window and accept the argmin —
    the tail of the solve shared by the scan and fused-Pallas paths (so
    their acceptance semantics cannot diverge)."""
    seg_total = jnp.maximum(jnp.sum(lens), 1).astype(jnp.float32)

    def rescore_one(cons, cons_len):
        dists = jax.vmap(lambda sg, sl: _edit_distance_myers(cons, cons_len, sg, sl))(
            seqs, lens)
        dists = jnp.where(lens > 0, dists, 0)
        return jnp.sum(dists).astype(jnp.float32) / seg_total

    errs = jax.vmap(rescore_one)(cand_arr, clen_arr)  # [C]
    errs = jnp.where(ok_arr, errs, jnp.float32(jnp.inf))
    ci = jnp.argmin(errs)
    best_err = errs[ci]
    best_cons = cand_arr[ci]
    best_len = jnp.where(ok_arr[ci], clen_arr[ci], 0)
    any_path = jnp.any(ok_arr)

    solved = (any_path & (best_err <= p.max_err) & (nsegs >= p.min_depth))
    out_cons = jnp.where(solved, best_cons, PAD).astype(jnp.int8)
    return dict(cons=out_cons,
                cons_len=jnp.where(solved, best_len, 0),
                err=jnp.where(any_path, best_err, jnp.float32(jnp.inf)),
                solved=solved)


def _solve_one(seqs: jnp.ndarray, lens: jnp.ndarray, nsegs: jnp.ndarray,
               ol: jnp.ndarray, p: KernelParams):
    """Solve one window. seqs [D, L] int8, lens [D] i32, ol [P, O] f32."""
    g = _prep_one(seqs, lens, nsegs, ol, p)
    scores, ptrs = _dp_scan_one(g["adjW"], g["W"], g["score0"])
    out = _finish_one(seqs, lens, nsegs, scores, ptrs, g["sel"], g["snk_ok"], p)
    out["m_overflow"] = g["m_overflow"]
    return out


def solve_batch_pallas_core(seqs, lens, nsegs, ol, p: KernelParams,
                            interpret: bool = False):
    """Batch solve with DP + candidate selection + backtrack as ONE fused
    Pallas kernel (``pallas_window.dp_backtrack_batch``).

    Same contract (and bitwise the same results, enforced by
    tests/test_pallas.py) as ``vmap(_solve_one)``: graph construction runs
    vmapped (sort/top-k/einsum are XLA/MXU-native), then one kernel owns
    the window until its C candidate sequences exist — the [B, P, M]
    score/pointer stacks never leave VMEM — and the shared Myers rescore
    accepts the winner."""
    from .pallas_window import dp_backtrack_batch

    g = jax.vmap(functools.partial(_prep_one, p=p),
                 in_axes=(0, 0, 0, None))(seqs, lens, nsegs, ol)
    wt = jnp.transpose(g["W"], (0, 2, 1))                 # [B, P, M]
    P = wt.shape[1]
    t_lo = max(0, p.wlen - p.k - p.len_slack)
    t_hi = min(P - 1, p.wlen - p.k + p.len_slack)
    cand, clen, ok = dp_backtrack_batch(
        g["adjW"], wt, g["score0"], g["snk_ok"], g["sel"], k=p.k,
        cons_len=p.cons_len, n_candidates=p.n_candidates, t_lo=t_lo,
        t_hi=t_hi, interpret=interpret)
    out = jax.vmap(functools.partial(_rescore_pick_one, p=p))(
        seqs, lens, nsegs, cand.astype(jnp.int8), clen, ok)
    out["m_overflow"] = g["m_overflow"]
    return out


def pallas_needs_interpret() -> bool:
    """Mosaic lowering of the Pallas kernel exists only on TPU; every other
    backend must run it in interpret mode (bit-identical, slow). The one
    policy point shared by the pipeline and the mesh solver."""
    return jax.default_backend() != "tpu"


def solve_batch_core(seqs, lens, nsegs, ol, p: KernelParams,
                     use_pallas: bool = False, interpret: bool = False):
    """Unjitted batch solve: the single dispatch point between the vmap/scan
    formulation and the Pallas-DP path (used by both ``solve_window_batch``
    and the escalation ladder in ``kernels.tiers``)."""
    if use_pallas:
        return solve_batch_pallas_core(seqs, lens, nsegs, ol, p,
                                       interpret=interpret)
    fn = functools.partial(_solve_one, p=p)
    return jax.vmap(fn, in_axes=(0, 0, 0, None))(seqs, lens, nsegs, ol)


@functools.partial(jax.jit, static_argnames=("params", "use_pallas", "interpret"))
def solve_window_batch(seqs: jnp.ndarray, lens: jnp.ndarray, nsegs: jnp.ndarray,
                       ol: jnp.ndarray, params: KernelParams,
                       use_pallas: bool = False, interpret: bool = False):
    """Solve a batch: seqs [B,D,L] int8, lens [B,D] i32, nsegs [B] i32,
    ol [P,O] f32 (the OffsetLikely table for params.k).

    ``use_pallas`` routes the heaviest-path DP through the Pallas kernel
    (``interpret=True`` for off-TPU parity runs)."""
    return solve_batch_core(seqs, lens, nsegs, ol, params, use_pallas, interpret)
