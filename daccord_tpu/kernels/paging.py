"""Ragged paged window batching: shape-family pages instead of dense rectangles.

Every dispatch before this module padded to a dense ``[B, D, L]`` rectangle
(``kernels/tensorize.py``), so a batch ships — and the tunnel transfers —
every dead cell between a segment's real length and the global (D, L) maxima.
Pad waste is a first-class BASELINE.md metric; this module attacks it with
the Ragged Paged Attention design (PAPERS.md, arxiv 2604.15464): segment
bases live in a flat **page pool** ``[n_pages, page_len]`` (int8) addressed
by a per-window **page table** ``[B, pages_per_window]``, and batches are
bucketed into a small set of **shape families** ``(depth, pages_per_window)``
quantized to powers of two, auto-derived from the corpus length x depth
histogram under a compile-count budget.

Layout (SeGraM's segment-contiguous memory argument, arxiv 2205.05883):
each segment starts on a page boundary and occupies ``ceil(len/page_len)``
consecutive table slots of its window, in segment order — the device-side
gather derives every offset from the ``lens`` table already on the wire,
and the host pack moves whole pages (one page-granular ``np.take``, no
per-byte index math on the feeder hot path; byte-packing segments was
measured 10x slower to pack for a ~10% waste edge). Rounding waste is
bounded at ``page_len - 1`` bases per segment, which sizes the default
page at 16. Each family also carries a fixed per-window **pool budget**
(``pool_pages``, derived from the corpus mean with slack): the pool ships at
``1 + B * pool_pages`` rows — ONE static shape per (family, batch width), so
paging adds exactly one compile per family per stream — and the pipeline's
router cuts a batch early when its windows' pages would overflow the budget
(density stays high because same-family windows have similar page counts).

Paging changes which cells EXIST, never any window's bytes: the device-side
gather (``gather_windows``; Pallas kernel in ``pallas_window.gather_pages``
or the pure-jnp ``take`` fallback) reconstructs the exact dense ``[B, D, L]``
tile ``tensorize_windows`` would have produced, and the tier ladder runs
unchanged on it. The round-trip property (paged pack -> unpack == dense
tensorize) is enforced by tests/test_paging.py, which is what lets the whole
existing fault/capacity/fleet matrix verify the paged path on CPU.

Page 0 of every pool is an all-PAD sentinel; unused table slots (windows
with fewer pages than the family width, pad rows) point there, so slicing
and padding a paged batch are O(rows) table operations — the capacity
governor's bisect/clamp rungs work on paged batches unchanged
(``slice_paged``/``pad_paged``, dispatched from ``tensorize.slice_batch``/
``pad_batch``).

Pad-waste accounting convention: ``pad_waste()`` counts base-PAYLOAD cells
(the pool), symmetric with the dense metric which counts ``seqs`` only —
dense runs never counted their lens/nsegs metadata either. The page table's
bytes (4 per slot) are real transfer cost and are reported separately
(``shipped_cells`` / the ``batch.paged`` event) so the paged-vs-dense
decision row can weigh them.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..utils.bases import PAD
from .tensorize import BatchShape, WindowBatch

#: default page length (bases). Segments are page-aligned, so rounding
#: waste averages PAGE_LEN/2 per segment — 16 keeps that under ~20% of a
#: typical w=40 window segment while the page stays a useful DMA/table
#: granule (4-byte table entry per 16-byte page). Must divide seg_len.
PAGE_LEN = 16

#: pool-budget slack over the sample mean pages/window (derive_families):
#: the corpus histogram drifts along a shard, and a budget cut exactly at
#: the mean would split every second batch.
POOL_SLACK = 1.15


@dataclass(frozen=True)
class ShapeFamily:
    """One paged compile shape: ``depth`` rows in the lens table, ``pages``
    table slots per window (drawn from a power-of-two grid capped at the
    structural maxima — quantization keeps the candidate grid, and so the
    compile count, bounded) and the fixed per-window ``pool_pages`` budget
    the shipped pool is sized by."""

    depth: int
    pages: int
    page_len: int = PAGE_LEN
    pool_pages: int = 0     # per-window pool budget; 0 = structural (pages)

    @property
    def budget(self) -> int:
        """Effective per-window pool budget in pages."""
        return self.pool_pages if self.pool_pages > 0 else self.pages

    def pool_rows(self, batch_size: int) -> int:
        """Static pool row count for a ``batch_size``-wide dispatch."""
        return 1 + batch_size * self.budget

    def describe(self) -> str:
        return f"D{self.depth}xP{self.pages}x{self.page_len}b{self.budget}"


@dataclass
class PagedWindowBatch:
    """Paged wire format of one window batch.

    ``pool`` is shared (never row-sliced): ``table`` rows index into it, and
    row 0 is the all-PAD sentinel every unused slot points at. ``lens``/
    ``nsegs`` are exactly the dense batch's — the gather derives every page
    offset from ``lens`` alone (page-aligned segments in segment order per
    window). Pool cells past a segment's last base are undefined (never
    PAD-scrubbed): every consumer masks by ``lens``, and scrubbing would put
    a full-pool memset back on the feeder hot path.
    """

    pool: np.ndarray       # int8 [n_pages, page_len]; row 0 = PAD sentinel
    table: np.ndarray      # int32 [B, pages]; 0 = sentinel/unused slot
    lens: np.ndarray       # int32 [B, D]
    nsegs: np.ndarray      # int32 [B]
    family: ShapeFamily
    shape: BatchShape      # dense-equivalent shape (gather target [B, D, L])
    read_ids: np.ndarray   # int64 [B]
    wstarts: np.ndarray    # int64 [B]
    stream: str = "full"
    job: str = ""          # serving-plane tag (see WindowBatch.job):
                           # telemetry only, never part of a shape key

    @property
    def size(self) -> int:
        return len(self.nsegs)

    @property
    def shipped_cells(self) -> int:
        """Total cells this batch ships: payload pool plus the page table in
        cell units (int32 = 4 cells each) — the honest transfer cost."""
        return int(self.pool.size) + int(self.table.size) * 4

    def pad_waste(self) -> float:
        """Fraction of shipped PAYLOAD cells that are dead (dense-comparable
        form of the §7.3 metric; see the module docstring's convention)."""
        used = int(self.lens.sum())
        return 1.0 - used / max(int(self.pool.size), 1)

    def to_dense(self) -> WindowBatch:
        """Host-side unpack to the exact dense batch that was packed (the
        round-trip inverse of :func:`pack_paged`) — used by degraded-mode
        engines (native C++ / host-routed ladder) that iterate dense rows."""
        B = self.size
        D, L = self.shape.depth, self.shape.seg_len
        PL = self.family.page_len
        seqs = np.full((B, D, L), PAD, dtype=np.int8)
        lens = np.asarray(self.lens)
        pps = page_counts(lens, PL)                          # [B, D]
        off = np.cumsum(pps, axis=1) - pps                   # excl page slot
        b_idx, d_idx, p_idx = np.nonzero(
            np.arange(L // PL)[None, None, :] < pps[:, :, None])
        pages = self.pool[self.table[b_idx, off[b_idx, d_idx] + p_idx]]
        seqs.reshape(B, D, L // PL, PL)[b_idx, d_idx, p_idx] = pages
        # page tails past a segment's length hold undefined pool bytes;
        # re-mask so the round-trip reproduces tensorize's PAD cells exactly
        j = np.arange(L, dtype=np.int32)
        np.copyto(seqs, PAD, where=j[None, None, :] >= lens[:, :, None])
        return WindowBatch(seqs=seqs, lens=lens.copy(),
                           nsegs=self.nsegs.copy(), shape=self.shape,
                           read_ids=self.read_ids.copy(),
                           wstarts=self.wstarts.copy(), stream=self.stream,
                           job=self.job)


def page_counts(lens: np.ndarray, page_len: int = PAGE_LEN) -> np.ndarray:
    """Pages each segment occupies: ceil(lens / page_len), elementwise."""
    lens = np.asarray(lens)
    if page_len & (page_len - 1) == 0:
        # pow2 fast path (the default): shift beats two negations + floordiv
        # on the feeder hot path
        return (lens + (page_len - 1)) >> (page_len.bit_length() - 1)
    return -(-lens // page_len)


def window_pages(lens: np.ndarray, page_len: int = PAGE_LEN) -> np.ndarray:
    """Pages per window ([B] from lens [B, D]): page-aligned segments, so
    the sum of per-segment page counts — the family router's second
    coordinate next to nsegs, and the pool-budget unit."""
    return page_counts(lens, page_len).sum(axis=1).astype(np.int64)


def pack_paged(batch: WindowBatch, family: ShapeFamily,
               target_rows: int | None = None, prof=None) -> PagedWindowBatch:
    """Pack a dense batch into ``family``'s paged wire format.

    ``target_rows`` pads the TABLE side to the dispatch width with sentinel
    rows (cheap — no dense pad tile is ever materialized); the pool is sized
    at ``family.pool_rows(target_rows)``. Every window must fit the family
    (``nsegs <= depth``, pages <= ``pages``) and the batch must fit the pool
    budget — the router guarantees both; violated invariants raise, because
    a silently truncated window would break byte identity.

    The copy is PAGE-granular (one ``np.take`` of whole pool rows out of the
    dense tile viewed as pages, plus one table scatter): index arrays scale
    with page count, not byte count — this runs on the feeder hot path per
    dispatch, where per-byte index math measured ~10x the feeder-wall budget.
    Pool cells past a segment's last base are deliberately left undefined
    (see PagedWindowBatch); only the sentinel page is scrubbed.

    ``prof`` (:class:`~..utils.obs.StageProfile`) books the pack wall under
    the ``pack`` feeder stage — the paged twin of ``pad_batch``'s timer, so
    the saturation profiler attributes dense and paged assembly identically.
    """
    if prof is not None:
        with prof.timed("pack"):
            return pack_paged(batch, family, target_rows=target_rows)
    B = batch.size
    rows = B if target_rows is None else int(target_rows)
    assert rows >= B
    D, L = batch.shape.depth, batch.shape.seg_len
    PL = family.page_len
    if L % PL:
        raise ValueError(f"page_len {PL} must divide seg_len {L}")
    if D > family.depth:
        raise ValueError(f"batch depth {D} exceeds family depth {family.depth}")
    lens = np.asarray(batch.lens)
    pps = page_counts(lens, PL)                              # [B, D]
    wp = pps.sum(axis=1)                                     # [B]
    if B and int(wp.max(initial=0)) > family.pages:
        raise ValueError("window exceeds family page budget "
                         f"({int(wp.max())} > {family.pages})")
    n_rows = family.pool_rows(rows)
    n_used = int(wp.sum())
    if n_used > n_rows - 1:
        raise ValueError(f"batch needs {n_used} pages; pool budget is "
                         f"{n_rows - 1} (router must cut the batch)")
    pool = np.empty((n_rows, PL), dtype=np.int8)
    pool[0] = PAD                                            # sentinel page
    if n_used:
        # dense pages of live segments, in (window, segment, page) order —
        # exactly the pool order, so one page-granular take fills the body.
        # Index arrays are built per live SEGMENT (repeat + ragged arange),
        # never by scanning the [B, D, L/PL] grid
        pps_f = pps.reshape(-1)
        rnz = np.nonzero(pps_f)[0].astype(np.int32)
        pc = pps_f[rnz]
        ra = np.arange(n_used, dtype=np.int32) - np.repeat(
            (np.cumsum(pc, dtype=np.int32) - pc), pc)
        np.take(batch.seqs.reshape(B * D * (L // PL), PL),
                np.repeat(rnz * np.int32(L // PL), pc) + ra, axis=0,
                out=pool[1 : 1 + n_used])
    table = np.zeros((rows, family.pages), dtype=np.int32)
    if n_used:
        # window b's wp[b] slots hold consecutive pool pages; same
        # repeat + ragged-arange construction at window granularity
        wnz = np.nonzero(wp)[0].astype(np.int32)
        wc = wp[wnz].astype(np.int32)
        wa = np.arange(n_used, dtype=np.int32) - np.repeat(
            np.cumsum(wc, dtype=np.int32) - wc, wc)
        table.reshape(-1)[np.repeat(wnz * np.int32(family.pages), wc) + wa] = \
            np.arange(1, n_used + 1, dtype=np.int32)

    def _pad_rows(a, fill=0):
        if rows == B:
            return a
        out = np.full((rows,) + a.shape[1:], fill, dtype=a.dtype)
        out[:B] = a
        return out

    return PagedWindowBatch(
        pool=pool, table=table, lens=_pad_rows(lens),
        nsegs=_pad_rows(batch.nsegs), family=family,
        shape=BatchShape(depth=D, seg_len=L, wlen=batch.shape.wlen),
        read_ids=_pad_rows(batch.read_ids, fill=-1),
        wstarts=_pad_rows(batch.wstarts), stream=batch.stream,
        job=batch.job)


def unpack_paged(pb: PagedWindowBatch) -> WindowBatch:
    """Alias of :meth:`PagedWindowBatch.to_dense` (the property-test name)."""
    return pb.to_dense()


def slice_paged(pb: PagedWindowBatch, lo: int, hi: int) -> PagedWindowBatch:
    """Row slice [lo, hi) — table/lens/nsegs/ids views; the pool is SHARED
    (page indices stay valid), so the governor's bisect rung costs O(rows),
    not a pool copy. Mirrors tensorize.slice_batch's field semantics."""
    import dataclasses

    return dataclasses.replace(
        pb, table=pb.table[lo:hi], lens=pb.lens[lo:hi], nsegs=pb.nsegs[lo:hi],
        read_ids=pb.read_ids[lo:hi], wstarts=pb.wstarts[lo:hi])


def pad_paged(pb: PagedWindowBatch, target: int) -> PagedWindowBatch:
    """Pad to ``target`` windows: appended rows carry zero lens/nsegs and a
    sentinel-page table row, so they gather to all-PAD tiles exactly like
    dense pad rows (and can never be rescue candidates). The pool keeps its
    shape — a governor slice+pad round trip must not change the program's
    pool operand."""
    B = pb.size
    if B == target:
        return pb
    assert B < target
    table = np.zeros((target, pb.table.shape[1]), dtype=np.int32)
    table[:B] = pb.table
    lens = np.zeros((target, pb.lens.shape[1]), dtype=np.int32)
    lens[:B] = pb.lens
    nsegs = np.zeros(target, dtype=np.int32)
    nsegs[:B] = pb.nsegs
    read_ids = np.full(target, -1, dtype=np.int64)
    read_ids[:B] = pb.read_ids
    wstarts = np.zeros(target, dtype=np.int64)
    wstarts[:B] = pb.wstarts
    import dataclasses

    return dataclasses.replace(pb, table=table, lens=lens, nsegs=nsegs,
                               read_ids=read_ids, wstarts=wstarts)


# ---------------------------------------------------------------------------
# device-side gather: paged wire -> the exact dense [B, D, L] tile
# ---------------------------------------------------------------------------

def gather_windows(pool, table, lens, *, page_len: int, seg_len: int,
                   use_pallas: bool = False, interpret: bool = False):
    """Reconstruct the dense ``[B, D, L]`` int8 tile on device.

    Segment ``d`` of a window starts at table slot ``cumsum(ceil(lens /
    page_len))[d]`` (page-aligned segments), so position ``j`` lives in slot
    ``start + j // page_len`` at cell ``j % page_len`` — derived from
    ``lens`` alone. ``use_pallas`` routes the pool-page gather (the
    HBM-heavy half) through ``pallas_window.gather_pages``; the index
    arithmetic after it is shared with the pure-jnp fallback so the two
    paths cannot diverge.
    """
    import jax.numpy as jnp

    B, PPW = table.shape
    D = lens.shape[1]
    L = seg_len
    PL = page_len
    pps = -(-lens // PL)                                   # [B, D] pages/seg
    off = jnp.cumsum(pps, axis=1) - pps                    # excl slot index
    j = jnp.arange(L, dtype=jnp.int32)
    if use_pallas:
        from .pallas_window import gather_pages

        gathered = gather_pages(pool, table, interpret=interpret)
        flat = gathered.reshape(B, PPW * PL)
        idx = off[:, :, None] * PL + j[None, None, :]
        idx = jnp.clip(idx, 0, PPW * PL - 1).reshape(B, D * L)
        dense = jnp.take_along_axis(flat, idx, axis=1).reshape(B, D, L)
    else:
        slot = off[:, :, None] + (j // PL)[None, None, :]  # [B, D, L]
        slot = jnp.clip(slot, 0, PPW - 1).reshape(B, D * L)
        pidx = jnp.take_along_axis(table, slot, axis=1).reshape(B, D, L)
        dense = pool.reshape(-1)[pidx * PL + (j % PL)[None, None, :]]
    return jnp.where(j[None, None, :] < lens[:, :, None], dense,
                     jnp.int8(PAD)).astype(jnp.int8)


# ---------------------------------------------------------------------------
# shape families: derived from the corpus length x depth histogram
# ---------------------------------------------------------------------------

def derive_families(nsegs: np.ndarray, pages: np.ndarray, *, max_depth: int,
                    max_pages: int, budget: int = 4,
                    page_len: int = PAGE_LEN) -> list[ShapeFamily]:
    """Pick <= ``budget`` shape families from a window sample.

    Candidate grid = power-of-two (depth, pages) cells up to the structural
    maxima; the full-coverage family is always included (every window must
    route somewhere). The rest are chosen greedily: each step adds the
    candidate that most reduces the sample's total table-slot cost (every
    window costs the CHEAPEST fitting family's page width — the pool is
    usage-sized, so family choice governs table width and budget fit) until
    the budget is exhausted or nothing saves. Each family then gets its
    ``pool_pages`` budget from the mean pages of the windows it would serve
    (x ``POOL_SLACK``). Replaces the hand-tuned ``depth_buckets=(8,16)`` /
    empty ``seg_len_buckets`` defaults with families grounded in the corpus
    itself; deterministic for a given sample. Returns families sorted by
    (pages, depth) — router order.
    """
    nsegs = np.asarray(nsegs, dtype=np.int64)
    pages = np.asarray(pages, dtype=np.int64)
    # pow2 candidate grid BELOW the structural maxima, plus the exact maxima
    # themselves: rounding the full-coverage family UP past max_depth would
    # hand the router a family deeper than the feeder's tensors (a non-pow2
    # --depth then crashes at the first pack)
    d_top = max(int(max_depth), 1)
    p_top = max(int(max_pages), 1)
    d_grid = sorted({1 << i for i in range(d_top.bit_length())
                     if (1 << i) <= d_top} | {d_top})
    p_grid = sorted({1 << i for i in range(p_top.bit_length())
                     if (1 << i) <= p_top} | {p_top})
    full = (d_top, p_top)
    chosen: list[tuple[int, int]] = [full]

    def cost(fams: list[tuple[int, int]]) -> int:
        c = np.full(len(nsegs), np.iinfo(np.int64).max, dtype=np.int64)
        for d, p in fams:
            fits = (nsegs <= d) & (pages <= p)
            c = np.where(fits, np.minimum(c, p), c)
        return int(c.sum())

    if len(nsegs):
        cur = cost(chosen)
        cands = [(d, p) for d in d_grid for p in p_grid if (d, p) != full]
        while len(chosen) < max(budget, 1) and cands:
            best, best_cost = None, cur
            for c in cands:
                cc = cost(chosen + [c])
                if cc < best_cost:
                    best, best_cost = c, cc
            if best is None:
                break
            chosen.append(best)
            cands.remove(best)
            cur = best_cost
    chosen.sort(key=lambda dp: (dp[1], dp[0]))
    fams = [ShapeFamily(depth=d, pages=p, page_len=page_len)
            for d, p in chosen]
    if len(nsegs) == 0:
        return fams
    # pool budgets from the windows each family would actually serve
    assign = assign_family(fams, nsegs, pages)
    out = []
    for fi, f in enumerate(fams):
        mine = pages[assign == fi]
        if len(mine):
            bud = min(max(int(np.ceil(float(mine.mean()) * POOL_SLACK)), 1),
                      f.pages)
        else:
            bud = f.pages
        out.append(ShapeFamily(depth=f.depth, pages=f.pages,
                               page_len=page_len, pool_pages=bud))
    return out


def assign_family(families: list[ShapeFamily], nsegs: np.ndarray,
                  pages: np.ndarray) -> np.ndarray:
    """Index of the cheapest family fitting each window ([B] int64).

    Families are in router order (sorted by pages then depth), so the first
    fit is the cheapest table width; the mandatory full-coverage family
    guarantees every window lands. Raises if one doesn't (a window deeper/
    longer than the structural maxima would otherwise truncate silently).
    """
    nsegs = np.asarray(nsegs)
    pages = np.asarray(pages)
    out = np.full(len(nsegs), -1, dtype=np.int64)
    for fi in reversed(range(len(families))):
        f = families[fi]
        fits = (nsegs <= f.depth) & (pages <= f.pages)
        out = np.where(fits, fi, out)
    if len(out) and out.min() < 0:
        bad = int(np.nonzero(out < 0)[0][0])
        raise ValueError(
            f"window (nsegs={int(nsegs[bad])}, pages={int(pages[bad])}) fits "
            f"no family; largest is {families[-1].describe()}")
    return out
