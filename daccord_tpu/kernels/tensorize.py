"""Window batch tensorization: ragged piles -> fixed-shape device tensors.

The reference processes one ragged window at a time inside ``handleWindow``;
the TPU path instead packs W windows x D segments x L bases into padded int8
tensors (PAD=4) with explicit lengths, the shape the batched kernel consumes
(SURVEY.md §7.1 item 3 "Tensorization"). Depth above ``max_depth`` is capped
(the A-read segment, placed first, always survives the cap).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..oracle.windows import WindowSegments
from ..utils.bases import PAD


@dataclass
class BatchShape:
    depth: int = 32       # D: max segments per window
    seg_len: int = 64     # L: max segment length
    wlen: int = 40        # w: window length (static for the kernel)


@dataclass
class WindowBatch:
    """Fixed-shape batch of windows. Arrays are host numpy; runtime ships them
    to the device (DLPack/zero-copy where possible)."""

    seqs: np.ndarray      # int8 [B, D, L], PAD=4 beyond lens
    lens: np.ndarray      # int32 [B, D], 0 for absent segments
    nsegs: np.ndarray     # int32 [B]
    shape: BatchShape
    # bookkeeping for scatter-back (parallel lists, length B)
    read_ids: np.ndarray  # int64 [B]
    wstarts: np.ndarray   # int64 [B]
    stream: str = "full"  # which ladder program solves this batch: "full"
                          # (fused ladder — the default), "tier0" (two-stream
                          # Stream A), "rescue" (Stream B dense rescue; same
                          # program as "full", tagged for routing/replay —
                          # the supervisor keys compile classification and
                          # failover replay on it, kernels/tiers.py)
    job: str = ""         # serving-plane tag (daccord_tpu/serve): which
                          # job(s) the rows belong to — "" for batch runs, a
                          # job id for a solo job's batches, "a+b" for a
                          # cross-job merged batch. Telemetry only: it MUST
                          # never enter a compile/shape key (cohabiting jobs
                          # share the jitted program — that is the point)

    @property
    def size(self) -> int:
        return len(self.nsegs)

    def pad_waste(self) -> float:
        """Fraction of seq cells that are padding (the §7.3 metric)."""
        total = self.seqs.size
        used = int(self.lens.sum())
        return 1.0 - used / max(total, 1)


def tensorize_windows(items: list[tuple[int, WindowSegments]],
                      shape: BatchShape, prof=None) -> WindowBatch:
    """Pack (read_id, WindowSegments) pairs into one WindowBatch.

    The segment copies run as ONE concatenated buffer + flat-index scatter
    instead of O(B*D) single-row numpy assignments: this sits on the
    measured host-feeder hot path (the python windowing fallback and every
    bench/tool that tensorizes), where per-row assignment overhead
    dominated the actual byte movement (tools/feederbench.py). ``prof``
    (a :class:`~..utils.obs.StageProfile`) books the call's wall under the
    ``tensorize`` feeder stage — the saturation profiler's own timer, so
    the measurement lives with the work, not at scattered call sites."""
    if prof is not None:
        with prof.timed("tensorize"):
            return tensorize_windows(items, shape)
    B = len(items)
    D, L = shape.depth, shape.seg_len
    seqs = np.full((B, D, L), PAD, dtype=np.int8)
    lens = np.zeros((B, D), dtype=np.int32)
    nsegs = np.zeros(B, dtype=np.int32)
    read_ids = np.zeros(B, dtype=np.int64)
    wstarts = np.zeros(B, dtype=np.int64)
    segs: list[np.ndarray] = []
    rows: list[int] = []          # flat (b * D + d) row of each segment
    for b, (rid, ws) in enumerate(items):
        read_ids[b] = rid
        wstarts[b] = ws.wstart
        d = min(len(ws.segments), D)
        nsegs[b] = d
        base = b * D
        for di in range(d):
            s = np.asarray(ws.segments[di], dtype=np.int8)
            segs.append(s[:L] if len(s) > L else s)
            rows.append(base + di)
    if segs:
        slens = np.fromiter(map(len, segs), np.int64, len(segs))
        rows_a = np.asarray(rows, dtype=np.int64)
        lens.reshape(-1)[rows_a] = slens
        flat = np.concatenate(segs) if len(segs) > 1 else segs[0]
        # ragged arange: position of every base within its own segment
        pos = np.arange(len(flat), dtype=np.int64) - np.repeat(
            np.cumsum(slens) - slens, slens)
        seqs.reshape(-1)[np.repeat(rows_a * L, slens) + pos] = flat
    return WindowBatch(seqs=seqs, lens=lens, nsegs=nsegs, shape=shape,
                       read_ids=read_ids, wstarts=wstarts)


def slice_batch(batch, lo: int, hi: int):
    """Row slice [lo, hi) of a batch — views, no copies; only the per-row
    arrays are replaced, so shape/stream (and any future non-row field)
    carry over untouched — a bisected Stream B rescue batch must keep
    routing to the rescue program. The capacity governor's bisect rung is
    this plus :func:`pad_batch`: by per-window independence the re-batched
    windows solve to identical bytes at any width. Paged batches
    (``kernels/paging.py``) slice by table rows — the page pool is shared."""
    import dataclasses

    if getattr(batch, "pool", None) is not None:
        from .paging import slice_paged

        return slice_paged(batch, lo, hi)
    return dataclasses.replace(
        batch, seqs=batch.seqs[lo:hi], lens=batch.lens[lo:hi],
        nsegs=batch.nsegs[lo:hi], read_ids=batch.read_ids[lo:hi],
        wstarts=batch.wstarts[lo:hi])


def pad_batch(batch, target: int, prof=None):
    """Pad a batch to ``target`` windows (static batch shapes for jit).

    Target-shape arrays are allocated ONCE and filled (live rows copied,
    the pad region written in place) — the previous five full
    ``np.concatenate`` calls copied every live cell AND materialized the
    pad blocks separately on every partial-bucket and rescue-pool flush.
    Paged batches pad by sentinel table rows (``paging.pad_paged``).
    ``prof`` books the wall under the ``pack`` feeder stage (saturation
    profiler, ISSUE 14) — same contract as :func:`tensorize_windows`."""
    if prof is not None:
        with prof.timed("pack"):
            return pad_batch(batch, target)
    B = batch.size
    if B == target:
        return batch
    assert B < target
    if getattr(batch, "pool", None) is not None:
        from .paging import pad_paged

        return pad_paged(batch, target)
    D, L = batch.shape.depth, batch.shape.seg_len
    seqs = np.empty((target, D, L), dtype=np.int8)
    seqs[:B] = batch.seqs
    seqs[B:] = PAD
    lens = np.zeros((target, D), dtype=np.int32)
    lens[:B] = batch.lens
    nsegs = np.zeros(target, dtype=np.int32)
    nsegs[:B] = batch.nsegs
    read_ids = np.empty(target, dtype=np.int64)
    read_ids[:B] = batch.read_ids
    read_ids[B:] = -1
    wstarts = np.zeros(target, dtype=np.int64)
    wstarts[:B] = batch.wstarts
    return WindowBatch(seqs=seqs, lens=lens, nsegs=nsegs, shape=batch.shape,
                       read_ids=read_ids, wstarts=wstarts,
                       stream=batch.stream, job=batch.job)
