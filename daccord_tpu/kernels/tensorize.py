"""Window batch tensorization: ragged piles -> fixed-shape device tensors.

The reference processes one ragged window at a time inside ``handleWindow``;
the TPU path instead packs W windows x D segments x L bases into padded int8
tensors (PAD=4) with explicit lengths, the shape the batched kernel consumes
(SURVEY.md §7.1 item 3 "Tensorization"). Depth above ``max_depth`` is capped
(the A-read segment, placed first, always survives the cap).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..oracle.windows import WindowSegments
from ..utils.bases import PAD


@dataclass
class BatchShape:
    depth: int = 32       # D: max segments per window
    seg_len: int = 64     # L: max segment length
    wlen: int = 40        # w: window length (static for the kernel)


@dataclass
class WindowBatch:
    """Fixed-shape batch of windows. Arrays are host numpy; runtime ships them
    to the device (DLPack/zero-copy where possible)."""

    seqs: np.ndarray      # int8 [B, D, L], PAD=4 beyond lens
    lens: np.ndarray      # int32 [B, D], 0 for absent segments
    nsegs: np.ndarray     # int32 [B]
    shape: BatchShape
    # bookkeeping for scatter-back (parallel lists, length B)
    read_ids: np.ndarray  # int64 [B]
    wstarts: np.ndarray   # int64 [B]
    stream: str = "full"  # which ladder program solves this batch: "full"
                          # (fused ladder — the default), "tier0" (two-stream
                          # Stream A), "rescue" (Stream B dense rescue; same
                          # program as "full", tagged for routing/replay —
                          # the supervisor keys compile classification and
                          # failover replay on it, kernels/tiers.py)

    @property
    def size(self) -> int:
        return len(self.nsegs)

    def pad_waste(self) -> float:
        """Fraction of seq cells that are padding (the §7.3 metric)."""
        total = self.seqs.size
        used = int(self.lens.sum())
        return 1.0 - used / max(total, 1)


def tensorize_windows(items: list[tuple[int, WindowSegments]],
                      shape: BatchShape) -> WindowBatch:
    """Pack (read_id, WindowSegments) pairs into one WindowBatch."""
    B = len(items)
    D, L = shape.depth, shape.seg_len
    seqs = np.full((B, D, L), PAD, dtype=np.int8)
    lens = np.zeros((B, D), dtype=np.int32)
    nsegs = np.zeros(B, dtype=np.int32)
    read_ids = np.zeros(B, dtype=np.int64)
    wstarts = np.zeros(B, dtype=np.int64)
    for b, (rid, ws) in enumerate(items):
        read_ids[b] = rid
        wstarts[b] = ws.wstart
        d = 0
        for seg in ws.segments:
            if d >= D:
                break
            s = np.asarray(seg, dtype=np.int8)[:L]
            seqs[b, d, : len(s)] = s
            lens[b, d] = len(s)
            d += 1
        nsegs[b] = d
    return WindowBatch(seqs=seqs, lens=lens, nsegs=nsegs, shape=shape,
                       read_ids=read_ids, wstarts=wstarts)


def slice_batch(batch: WindowBatch, lo: int, hi: int) -> WindowBatch:
    """Row slice [lo, hi) of a batch — views, no copies; only the per-row
    arrays are replaced, so shape/stream (and any future non-row field)
    carry over untouched — a bisected Stream B rescue batch must keep
    routing to the rescue program. The capacity governor's bisect rung is
    this plus :func:`pad_batch`: by per-window independence the re-batched
    windows solve to identical bytes at any width."""
    import dataclasses

    return dataclasses.replace(
        batch, seqs=batch.seqs[lo:hi], lens=batch.lens[lo:hi],
        nsegs=batch.nsegs[lo:hi], read_ids=batch.read_ids[lo:hi],
        wstarts=batch.wstarts[lo:hi])


def pad_batch(batch: WindowBatch, target: int) -> WindowBatch:
    """Pad a batch to ``target`` windows (static batch shapes for jit)."""
    B = batch.size
    if B == target:
        return batch
    assert B < target
    pad = target - B
    D, L = batch.shape.depth, batch.shape.seg_len
    return WindowBatch(
        seqs=np.concatenate([batch.seqs, np.full((pad, D, L), PAD, dtype=np.int8)]),
        lens=np.concatenate([batch.lens, np.zeros((pad, D), dtype=np.int32)]),
        nsegs=np.concatenate([batch.nsegs, np.zeros(pad, dtype=np.int32)]),
        shape=batch.shape,
        read_ids=np.concatenate([batch.read_ids, np.full(pad, -1, dtype=np.int64)]),
        wstarts=np.concatenate([batch.wstarts, np.zeros(pad, dtype=np.int64)]),
        stream=batch.stream,
    )
