"""Escalation ladder over the batched window kernel.

The reference escalates k inside ``handleWindow`` per window; on device that
would force data-dependent control flow, so the ladder runs per *batch*
(SURVEY.md §7.3 item 4 "adaptive k without recompilation storms": fixed tiers,
statically-shaped programs). Tier 0 solves ~90%+ of windows; failures are
*compacted on device* (fixed-capacity nonzero/gather) and pushed through the
escalation tiers inside the SAME jitted program, so one batch costs exactly
one dispatch and one device->host fetch — critical when the TPU sits behind a
high-latency tunnel (measured ~65 ms per blocking transfer on axon).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from dataclasses import replace as dc_replace

import jax
import jax.numpy as jnp
import numpy as np

from ..oracle.consensus import ConsensusConfig
from ..oracle.profile import ErrorProfile
from .tensorize import WindowBatch
from .window_kernel import KernelParams, solve_batch_core, solve_window_batch


@dataclass
class TierLadder:
    params: list[KernelParams]
    tables: dict[int, jnp.ndarray]   # k -> OL table [P, O] f32
    wide_p0: KernelParams | None = None   # overflow-rescue tier: tier 0 at
                                 # the rescue active-set size; windows whose
                                 # top-M cap bound are re-solved uncapped
                                 # (reference full-graph semantics,
                                 # SURVEY.md:65; BASELINE.md top-M table)

    @classmethod
    def from_config(cls, profile: ErrorProfile, cfg: ConsensusConfig,
                    max_kmers: int = 64, rescue_max_kmers: int = 256,
                    overflow_rescue: bool = False) -> "TierLadder":
        """Table construction delegates to the oracle's ``make_offset_likely``
        so kernel and oracle tables cannot desynchronize (the bit-parity
        tests depend on identical tables)."""
        from ..oracle.consensus import make_offset_likely

        tables = {k: jnp.asarray(t.table)
                  for k, t in make_offset_likely(profile, cfg).items()}
        params = [
            KernelParams(k=k, min_count=mc, edge_min_count=emc,
                         count_frac=cfg.dbg.count_frac,
                         anchor_slack=cfg.dbg.anchor_slack,
                         end_slack=cfg.dbg.end_slack,
                         len_slack=cfg.dbg.len_slack,
                         n_candidates=cfg.dbg.n_candidates,
                         min_depth=cfg.dbg.min_depth,
                         max_err=cfg.dbg.max_err,
                         # min_count=1 tiers keep every count-1 k-mer; they need
                         # a much larger active set or the rescue fails on the
                         # arbitrary truncation (run compacted, so affordable)
                         max_kmers=rescue_max_kmers if mc <= 1 else max_kmers,
                         wlen=cfg.w)
            for k, mc, emc in cfg.tiers
        ]
        # pack_result stores tier+1 in 5 bits next to the overflow counter
        assert len(params) < 31, "ladder too deep for the packed-result layout"
        wide_p0 = None
        if overflow_rescue and params[0].max_kmers < rescue_max_kmers:
            import dataclasses

            wide_p0 = dataclasses.replace(params[0],
                                          max_kmers=rescue_max_kmers)
        return cls(params=params, tables=tables, wide_p0=wide_p0)


def ladder_core(seqs, lens, nsegs, tables: tuple, params: tuple[KernelParams, ...],
                esc_cap: int, use_pallas: bool = False,
                pallas_interpret: bool = False,
                wide_p0: KernelParams | None = None):
    """Full escalation ladder as one traceable program.

    ``tables[i]`` is the OffsetLikely table for ``params[i]``. Failures of
    tier 0 are compacted into ``esc_cap`` slots (device-side gather) and run
    through the remaining tiers with already-solved slots depth-masked; results
    scatter back. Failures beyond ``esc_cap`` stay unsolved (reported via
    ``esc_overflow``; cap generously — tier-0 failure rate is <10%).

    ``wide_p0`` (overflow rescue) re-solves every window whose tier-0 top-M
    cap bound at the rescue active-set size, replacing the capped result when
    the wide solve succeeds — the reference's full-graph semantics restored
    for exactly the windows where truncation could matter. Runs before the
    failure escalation so wide-solved windows skip the rescue tiers.

    ``use_pallas`` routes every tier's heaviest-path DP through the Pallas
    kernel (TPU only; semantics bit-identical, tests/test_pallas.py).
    """
    p0 = params[0]
    out0 = solve_batch_core(seqs, lens, nsegs, tables[0], p0, use_pallas,
                            pallas_interpret)
    solved = out0["solved"]
    cons = out0["cons"]
    cons_len = out0["cons_len"]
    err = out0["err"]
    tier = jnp.where(solved, 0, -1).astype(jnp.int32)
    # top-M-cap flag: the one place kernel and oracle can disagree. Seeded
    # from tier 0; escalation tiers OR in their own caps below so every
    # window that ANY processing tier truncated carries the flag
    m_ovf = out0["m_overflow"]

    if wide_p0 is not None:
        # rescue capacity = the FULL batch, independent of esc_cap: the top-M
        # cap binds on most windows at production depth (unlike tier-0
        # failures, which esc_cap is sized for), so truncating the rescue
        # would silently skip exactly the windows it exists for. The host
        # path (solve_tiered) rescues every overflowed window; parity
        # requires the same here. lax.cond skips the solve when none bind.
        EW = seqs.shape[0]
        ovf = m_ovf & (nsegs >= p0.min_depth)
        wcount = jnp.sum(ovf.astype(jnp.int32))

        def run_wide(args):
            cons, cons_len, err, solved, tier, m_ovf = args
            idx = jnp.nonzero(ovf, size=EW, fill_value=0)[0]
            live = jnp.arange(EW) < wcount
            out_w = solve_batch_core(seqs[idx], lens[idx],
                                     jnp.where(live, nsegs[idx], 0),
                                     tables[0], wide_p0, use_pallas,
                                     pallas_interpret)
            take = live & out_w["solved"]
            B = seqs.shape[0]
            idx_w = jnp.where(take, idx, B)   # non-taken -> out of bounds, drop
            # the uncapped result replaces the capped one even when both
            # solved; the flag clears only where the wide set didn't cap too
            clear = take & ~out_w["m_overflow"]
            idx_c = jnp.where(clear, idx, B)
            return (cons.at[idx_w].set(out_w["cons"], mode="drop"),
                    cons_len.at[idx_w].set(out_w["cons_len"], mode="drop"),
                    err.at[idx_w].set(out_w["err"], mode="drop"),
                    solved.at[idx_w].set(True, mode="drop"),
                    tier.at[idx_w].set(0, mode="drop"),
                    m_ovf.at[idx_c].set(False, mode="drop"))

        cons, cons_len, err, solved, tier, m_ovf = jax.lax.cond(
            wcount > 0, run_wide, lambda args: args,
            (cons, cons_len, err, solved, tier, m_ovf))

    overflow = jnp.int32(0)
    if len(params) > 1 and esc_cap > 0:
        E = esc_cap
        fail = (~solved) & (nsegs >= p0.min_depth)
        count = jnp.sum(fail.astype(jnp.int32))
        overflow = jnp.maximum(count - E, 0)

        def run_esc(args):
            cons, cons_len, err, solved, tier, m_ovf = args
            idx = jnp.nonzero(fail, size=E, fill_value=0)[0]
            live = jnp.arange(E) < count
            sseqs = seqs[idx]
            slens = lens[idx]
            snsegs = jnp.where(live, nsegs[idx], 0)
            e_solved = jnp.zeros(E, dtype=bool)
            CL = cons.shape[1]
            e_cons = jnp.full((E, CL), 4, dtype=jnp.int8)
            e_len = jnp.zeros(E, dtype=jnp.int32)
            e_err = jnp.full(E, jnp.inf, dtype=jnp.float32)
            e_tier = jnp.full(E, -1, dtype=jnp.int32)
            e_movf = jnp.zeros(E, dtype=bool)
            for ti in range(1, len(params)):
                p = params[ti]
                processed = live & ~e_solved
                out_t = solve_batch_core(sseqs, slens,
                                         jnp.where(e_solved, 0, snsegs),
                                         tables[ti], p, use_pallas,
                                         pallas_interpret)
                e_movf = e_movf | (processed & out_t["m_overflow"])
                take = live & out_t["solved"] & ~e_solved
                e_cons = jnp.where(take[:, None], out_t["cons"], e_cons)
                e_len = jnp.where(take, out_t["cons_len"], e_len)
                e_err = jnp.where(take, out_t["err"], e_err)
                e_tier = jnp.where(take, ti, e_tier)
                e_solved = e_solved | take
            # fill slots of the fixed-size nonzero alias index 0; route them
            # out of bounds and drop, or their stale writes clobber window 0
            B = seqs.shape[0]
            idx_w = jnp.where(live & e_solved, idx, B)
            # the overflow flag scatters for ALL live escaped windows (an
            # unsolved-but-truncated window is still unexplained vs oracle)
            idx_all = jnp.where(live, idx, B)
            return (cons.at[idx_w].set(e_cons, mode="drop"),
                    cons_len.at[idx_w].set(e_len, mode="drop"),
                    err.at[idx_w].set(e_err, mode="drop"),
                    solved.at[idx_w].set(True, mode="drop"),
                    tier.at[idx_w].set(e_tier, mode="drop"),
                    m_ovf.at[idx_all].set(m_ovf[idx] | e_movf, mode="drop"))

        # batches with zero tier-0 failures (the common case at >99% solve
        # rate) skip the rescue tiers entirely at runtime
        cons, cons_len, err, solved, tier, m_ovf = jax.lax.cond(
            count > 0, run_esc, lambda args: args,
            (cons, cons_len, err, solved, tier, m_ovf))

    return dict(cons=cons, cons_len=cons_len, err=err, solved=solved, tier=tier,
                m_ovf=m_ovf, esc_overflow=overflow)


@functools.partial(jax.jit,
                   static_argnames=("params", "esc_cap", "use_pallas",
                                    "pallas_interpret", "wide_p0"))
def _ladder_jit(seqs, lens, nsegs, tables, params, esc_cap, use_pallas=False,
                pallas_interpret=False, wide_p0=None):
    return ladder_core(seqs, lens, nsegs, tables, params, esc_cap, use_pallas,
                       pallas_interpret, wide_p0)


def tier0_core(seqs, lens, nsegs, table0, p0: KernelParams,
               use_pallas: bool = False, pallas_interpret: bool = False):
    """Stream A of the two-stream ladder: tier 0 ONLY (the cheap M=64
    kernel), shaped exactly like :func:`ladder_core` output so the packed
    wire format and the pipeline's scatter path are shared. No wide rescue
    and no escalation run here — failures and top-M-overflow windows are
    pooled on host (:func:`rescue_candidates`) and re-solved in a dense
    Stream B batch (:func:`ladder_core` at the pool size)."""
    out0 = solve_batch_core(seqs, lens, nsegs, table0, p0, use_pallas,
                            pallas_interpret)
    solved = out0["solved"]
    return dict(cons=out0["cons"], cons_len=out0["cons_len"], err=out0["err"],
                solved=solved,
                tier=jnp.where(solved, 0, -1).astype(jnp.int32),
                m_ovf=out0["m_overflow"], esc_overflow=jnp.int32(0))


@functools.partial(jax.jit,
                   static_argnames=("p0", "use_pallas", "pallas_interpret"))
def _tier0_packed_jit(seqs, lens, nsegs, table0, p0, use_pallas=False,
                      pallas_interpret=False):
    return pack_result(tier0_core(seqs, lens, nsegs, table0, p0, use_pallas,
                                  pallas_interpret))


def ladder_core_paged(pool, table, lens, nsegs, tables: tuple,
                      params: tuple[KernelParams, ...], esc_cap: int,
                      page_len: int, seg_len: int, use_pallas: bool = False,
                      pallas_interpret: bool = False,
                      wide_p0: KernelParams | None = None):
    """Paged-wire form of :func:`ladder_core`: a device-side page gather
    (``paging.gather_windows`` — the Pallas kernel under ``use_pallas``, the
    pure-jnp ``take`` fallback elsewhere) reconstructs the exact dense
    ``[B, D, L]`` tile inside the SAME jitted program, then the unchanged
    ladder consumes it. Paging changes which cells cross the wire, never any
    window's result — byte parity with the dense program is the invariant
    (tests/test_paging.py)."""
    from .paging import gather_windows

    seqs = gather_windows(pool, table, lens, page_len=page_len,
                          seg_len=seg_len, use_pallas=use_pallas,
                          interpret=pallas_interpret)
    return ladder_core(seqs, lens, nsegs, tables, params, esc_cap,
                       use_pallas, pallas_interpret, wide_p0)


def tier0_core_paged(pool, table, lens, nsegs, table0, p0: KernelParams,
                     page_len: int, seg_len: int, use_pallas: bool = False,
                     pallas_interpret: bool = False):
    """Paged-wire Stream A core: page gather + :func:`tier0_core`."""
    from .paging import gather_windows

    seqs = gather_windows(pool, table, lens, page_len=page_len,
                          seg_len=seg_len, use_pallas=use_pallas,
                          interpret=pallas_interpret)
    return tier0_core(seqs, lens, nsegs, table0, p0, use_pallas,
                      pallas_interpret)


@functools.partial(jax.jit,
                   static_argnames=("params", "esc_cap", "page_len",
                                    "seg_len", "use_pallas",
                                    "pallas_interpret", "wide_p0"))
def _ladder_packed_paged_jit(pool, table, lens, nsegs, tables, params,
                             esc_cap, page_len, seg_len, use_pallas=False,
                             pallas_interpret=False, wide_p0=None):
    return pack_result(ladder_core_paged(pool, table, lens, nsegs, tables,
                                         params, esc_cap, page_len, seg_len,
                                         use_pallas, pallas_interpret,
                                         wide_p0))


@functools.partial(jax.jit,
                   static_argnames=("p0", "page_len", "seg_len", "use_pallas",
                                    "pallas_interpret"))
def _tier0_packed_paged_jit(pool, table, lens, nsegs, table0, p0, page_len,
                            seg_len, use_pallas=False,
                            pallas_interpret=False):
    return pack_result(tier0_core_paged(pool, table, lens, nsegs, table0, p0,
                                        page_len, seg_len, use_pallas,
                                        pallas_interpret))


def pack_result(out: dict) -> jnp.ndarray:
    """Pack a ladder result dict into ONE int32 array [B, words+3].

    The tunneled TPU pays a large fixed cost per fetched *array* (measured
    ~60-300 ms per device->host fetch on axon, vs ~1 GB/s once moving), so the
    five result arrays are bit-packed on device into a single fetch:
    ``cons`` int8 x4 per word, then cons_len, err (f32 bitcast), tier
    (solved == tier >= 0), with esc_overflow folded into row 0's spare bits.
    """
    cons = out["cons"]
    B, CL = cons.shape
    words = (CL + 3) // 4
    c = jnp.pad(cons, ((0, 0), (0, words * 4 - CL)), constant_values=4)
    c = c.astype(jnp.uint8).astype(jnp.uint32).reshape(B, words, 4)
    cw = c[:, :, 0] | (c[:, :, 1] << 8) | (c[:, :, 2] << 16) | (c[:, :, 3] << 24)
    cw = jax.lax.bitcast_convert_type(cw, jnp.int32)
    errw = jax.lax.bitcast_convert_type(out["err"].astype(jnp.float32), jnp.int32)
    # tier is a small signed int; bit 5 carries the per-window top-M-cap
    # flag, and esc_overflow rides the high bits of row 0's tier column.
    # tier+1 gets the 5 low bits, so at most 31 tiers — far above any real
    # ladder (default: 4)
    tier = out["tier"].astype(jnp.int32) + 1
    movf = out.get("m_ovf")
    if movf is None:
        movf = jnp.zeros(B, jnp.int32)
    ovf = jnp.zeros(B, jnp.int32).at[0].set(
        jnp.asarray(out["esc_overflow"]).astype(jnp.int32))
    tierw = tier | (movf.astype(jnp.int32) << 5) | (ovf << 6)
    return jnp.concatenate([cw, out["cons_len"].astype(jnp.int32)[:, None],
                            errw[:, None], tierw[:, None]], axis=1)


def unpack_result(arr: np.ndarray, cons_len_cl: int) -> dict:
    """Host-side inverse of :func:`pack_result` (numpy, zero device work)."""
    B = arr.shape[0]
    CL = cons_len_cl
    words = (CL + 3) // 4
    cons = np.ascontiguousarray(arr[:, :words]).view(np.int8).reshape(B, words * 4)[:, :CL]
    cons_len = arr[:, words]
    err = np.ascontiguousarray(arr[:, words + 1]).view(np.float32)
    tierw = arr[:, words + 2]
    tier = (tierw & 31) - 1
    m_ovf = ((tierw >> 5) & 1).astype(bool)
    overflow = int(tierw[0] >> 6) if B else 0
    return dict(cons=cons, cons_len=cons_len, err=err, solved=tier >= 0,
                tier=tier, m_ovf=m_ovf, esc_overflow=overflow)


@functools.partial(jax.jit,
                   static_argnames=("params", "esc_cap", "use_pallas",
                                    "pallas_interpret", "wide_p0"))
def _ladder_packed_jit(seqs, lens, nsegs, tables, params, esc_cap,
                       use_pallas=False, pallas_interpret=False, wide_p0=None):
    return pack_result(ladder_core(seqs, lens, nsegs, tables, params, esc_cap,
                                   use_pallas, pallas_interpret, wide_p0))


class _PackedHandle:
    """In-flight packed ladder result (device array + unpack metadata)."""

    __slots__ = ("arr", "cl")

    def __init__(self, arr, cl: int):
        self.arr = arr
        self.cl = cl


def solve_ladder_async(batch: WindowBatch, ladder: TierLadder,
                       esc_cap: int | None = None, use_pallas: bool = False,
                       pallas_interpret: bool = False):
    """Dispatch the full ladder; returns a handle without blocking.

    Pair with :func:`fetch` — the pipeline keeps a couple of batches in flight
    so host windowing, device compute, and the tunnel transfer overlap. The
    result crosses the tunnel as ONE packed array (see :func:`pack_result`).

    ``esc_cap=None`` (default) sizes the escalation capacity to the full
    batch, making overflow (windows silently left unsolved past the cap)
    structurally impossible; the lax.cond still skips the rescue tiers at
    runtime when nothing failed.
    """
    if esc_cap is None:
        esc_cap = int(batch.size)
    tables = tuple(ladder.tables[p.k] for p in ladder.params)
    if getattr(batch, "pool", None) is not None:
        # paged wire format (kernels/paging.py): pool + page table ship,
        # the dense tile is gathered device-side inside the same program
        arr = _ladder_packed_paged_jit(
            jnp.asarray(batch.pool), jnp.asarray(batch.table),
            jnp.asarray(batch.lens), jnp.asarray(batch.nsegs), tables,
            tuple(ladder.params), esc_cap, batch.family.page_len,
            batch.shape.seg_len, use_pallas, pallas_interpret,
            ladder.wide_p0)
        return _PackedHandle(arr, ladder.params[0].cons_len)
    arr = _ladder_packed_jit(jnp.asarray(batch.seqs), jnp.asarray(batch.lens),
                             jnp.asarray(batch.nsegs), tables,
                             tuple(ladder.params), esc_cap, use_pallas,
                             pallas_interpret, ladder.wide_p0)
    return _PackedHandle(arr, ladder.params[0].cons_len)


def ladder_cost(batch: WindowBatch, ladder: TierLadder,
                esc_cap: int | None = None, use_pallas: bool = False,
                pallas_interpret: bool = False) -> dict | None:
    """HLO cost analysis (flops, bytes accessed) of the fused ladder
    program at this batch's shape (ISSUE 13: compile-cost telemetry for the
    fingerprint registry). Mirrors :func:`solve_ladder_async`'s dense arg
    assembly through the AOT lower+compile path — call AFTER a warmup solve
    so the compile is a cache hit, not a second 900 s spend."""
    from ..utils.obs import hlo_cost

    if esc_cap is None:
        esc_cap = int(batch.size)
    tables = tuple(ladder.tables[p.k] for p in ladder.params)
    return hlo_cost(_ladder_packed_jit, jnp.asarray(batch.seqs),
                    jnp.asarray(batch.lens), jnp.asarray(batch.nsegs),
                    tables, tuple(ladder.params), esc_cap, use_pallas,
                    pallas_interpret, ladder.wide_p0)


def fetch(out) -> dict:
    """Materialize a solver result on host (no-op for numpy dicts)."""
    if isinstance(out, _PackedHandle):
        return unpack_result(np.asarray(jax.device_get(out.arr)), out.cl)
    host = jax.device_get(out)
    return {k: np.asarray(v) for k, v in host.items()}


def fetch_many(handles: list) -> list[dict]:
    """Materialize several in-flight results in ONE device->host transfer.

    The tunneled TPU pays its fixed ~100 ms RTT per ``device_get`` CALL, not
    per array (measured 2026-07-30: 8 sequential fetches 988 ms vs the same
    8 arrays grouped 91 ms), so draining the in-flight window in groups
    divides the per-batch fetch floor by the group size."""
    packed = [i for i, h in enumerate(handles)
              if isinstance(h, _PackedHandle)]
    if len(packed) <= 1:
        return [fetch(h) for h in handles]
    # group every packed handle into ONE device_get even when the list is
    # mixed (e.g. a supervisor drain holding both device handles and
    # degraded-mode results): only the non-packed stragglers pay their own
    # fetch call
    arrs = jax.device_get([handles[i].arr for i in packed])
    outs: list = [None] * len(handles)
    for i, a in zip(packed, arrs):
        outs[i] = unpack_result(np.asarray(a), handles[i].cl)
    for i, h in enumerate(handles):
        if outs[i] is None:
            outs[i] = fetch(h)
    return outs


def solve_ladder(batch: WindowBatch, ladder: TierLadder,
                 esc_cap: int | None = None, use_pallas: bool = False,
                 pallas_interpret: bool = False) -> dict:
    """Single-dispatch full-ladder solve; host numpy results."""
    return fetch(solve_ladder_async(batch, ladder, esc_cap, use_pallas,
                                    pallas_interpret))


def audit_reference(ladder: TierLadder):
    """Trusted-host reference engine for the sampled shadow audit
    (ISSUE 20): the fused ladder solved one row at a time, pinned to the
    host cpu platform so a device-backed primary can never audit itself.
    Byte-identical to the batched ladder — windows are solved independently,
    the same invariant the audit's byte comparison rests on — but per-row
    each sampled window pays exactly its OWN escalation path: no
    ``esc_cap``-padded rescue chunk, and the wide top-M rescue runs only for
    rows whose cap actually bound instead of re-solving the whole sample.
    That pro-rata cost is what keeps the default-rate audit inside the
    BENCH_SDC <=2% overhead contract, and the (1, D, L) executable is the
    same one the culprit-attribution probe dispatches per member — one
    compiled program serves both."""
    host = jax.devices("cpu")[0]

    def _ref(b):
        if hasattr(b, "to_dense"):
            b = b.to_dense()
        outs = []
        with jax.default_device(host):
            for i in range(int(b.size)):
                row = dc_replace(
                    b, seqs=b.seqs[i:i + 1], lens=b.lens[i:i + 1],
                    nsegs=b.nsegs[i:i + 1], read_ids=b.read_ids[i:i + 1],
                    wstarts=b.wstarts[i:i + 1])
                outs.append(solve_ladder(row, ladder))
        merged = {k: np.concatenate([o[k] for o in outs])
                  for k in ("cons", "cons_len", "err", "solved", "tier",
                            "m_ovf")}
        merged["esc_overflow"] = max(int(o["esc_overflow"]) for o in outs)
        return merged

    _ref.__name__ = "host-row-ladder"
    return _ref


def solve_tier0_async(batch: WindowBatch, ladder: TierLadder,
                      use_pallas: bool = False,
                      pallas_interpret: bool = False):
    """Dispatch Stream A (tier 0 only) of the two-stream ladder; returns a
    packed handle exactly like :func:`solve_ladder_async` — one fetch, same
    wire format — but the program never carries the rescue tiers, so a
    tier-0 failure costs nothing here (the window pools for Stream B)."""
    p0 = ladder.params[0]
    if getattr(batch, "pool", None) is not None:
        arr = _tier0_packed_paged_jit(
            jnp.asarray(batch.pool), jnp.asarray(batch.table),
            jnp.asarray(batch.lens), jnp.asarray(batch.nsegs),
            ladder.tables[p0.k], p0, batch.family.page_len,
            batch.shape.seg_len, use_pallas, pallas_interpret)
        return _PackedHandle(arr, p0.cons_len)
    arr = _tier0_packed_jit(jnp.asarray(batch.seqs), jnp.asarray(batch.lens),
                            jnp.asarray(batch.nsegs), ladder.tables[p0.k],
                            p0, use_pallas, pallas_interpret)
    return _PackedHandle(arr, p0.cons_len)


def stream_dispatcher(ladder: TierLadder, use_pallas: bool = False,
                      pallas_interpret: bool = False):
    """Dispatch function routing a batch to the program its ``stream`` tag
    names: ``tier0`` → the Stream A tier0-only program, anything else
    (``full``/``rescue``) → the full ladder. The ONE routing rule shared by
    the pipeline's split-ladder dispatch and the serving plane's cross-job
    batcher (daccord_tpu/serve), so the two can never route a job-tagged
    batch to different programs. The ``job`` tag deliberately plays no part
    here — cohabiting jobs share the jitted program."""

    def dispatch(batch: WindowBatch):
        if getattr(batch, "stream", "full") == "tier0":
            return solve_tier0_async(batch, ladder, use_pallas=use_pallas,
                                     pallas_interpret=pallas_interpret)
        return solve_ladder_async(batch, ladder, use_pallas=use_pallas,
                                  pallas_interpret=pallas_interpret)

    return dispatch


def rescue_candidates(out: dict, nsegs: np.ndarray,
                      ladder: TierLadder) -> np.ndarray:
    """Bool mask of batch rows that the fused ladder would have routed
    through its rescue lanes — the two-stream pool-membership rule.

    Mirrors :func:`ladder_core` exactly: windows whose top-M cap bound
    (only when the overflow rescue is configured) and windows tier 0 failed
    at adequate depth (only when escalation tiers exist). Applied to a
    tier0-only result this selects Stream B's input; applied to a FULL
    ladder result (a supervisor-degraded Stream A batch replays on the
    full-ladder fallback engine) it still composes byte-identically — every
    pooled window re-solves to the same per-window result, and un-pooled
    windows already carry their final bytes."""
    nsegs = np.asarray(nsegs)
    deep = nsegs >= ladder.params[0].min_depth
    need = np.zeros(len(nsegs), dtype=bool)
    if len(ladder.params) > 1:
        need |= ~np.asarray(out["solved"]) & deep
    if ladder.wide_p0 is not None:
        need |= np.asarray(out["m_ovf"]) & deep
    return need


def solve_ladder_split(batch: WindowBatch, ladder: TierLadder,
                       rescue_batch: int | None = None,
                       use_pallas: bool = False,
                       pallas_interpret: bool = False, tracer=None) -> dict:
    """Two-stream solve of ONE batch (the kernel-level unit behind the
    pipeline's cross-batch pool): Stream A tier0 over the full batch, then
    Stream B (the full ladder, compacted) over the rescue candidates only,
    scattered back. Byte-identical to :func:`solve_ladder` by construction —
    every window is solved independently, so re-batching cannot change its
    bytes (enforced by tests/test_split_ladder.py).

    ``rescue_batch`` fixes Stream B's static shape (padded); None solves
    the candidates in one right-sized batch. ``tracer`` (a
    :class:`~..utils.obs.Tracer`) brackets the two streams in
    ``kernel.tier0``/``kernel.rescue`` spans so a trace attributes the
    cheap-vs-quadratic split of this unit's wall."""
    import dataclasses

    from ..utils.obs import Tracer
    from .tensorize import pad_batch as _pad

    tr = tracer if tracer is not None else Tracer(None)
    with tr.span("kernel.tier0", rows=int(batch.size)):
        out = fetch(solve_tier0_async(batch, ladder, use_pallas,
                                      pallas_interpret))
    out = {k: (np.array(v) if isinstance(v, np.ndarray) else v)
           for k, v in out.items()}
    idx = np.nonzero(rescue_candidates(out, batch.nsegs, ladder))[0]
    step = rescue_batch if rescue_batch else max(len(idx), 1)
    for c0 in range(0, len(idx), step):
        sub = idx[c0 : c0 + step]
        sb = dataclasses.replace(
            batch, seqs=batch.seqs[sub], lens=batch.lens[sub],
            nsegs=batch.nsegs[sub], read_ids=batch.read_ids[sub],
            wstarts=batch.wstarts[sub], stream="rescue")
        with tr.span("kernel.rescue", rows=int(len(sub)), slots=int(step)):
            r = fetch(solve_ladder_async(_pad(sb, step), ladder,
                                         use_pallas=use_pallas,
                                         pallas_interpret=pallas_interpret))
        n = len(sub)
        for key in ("cons", "cons_len", "err", "solved", "tier", "m_ovf"):
            out[key][sub] = r[key][:n]
    return out


def _solve_compact(batch: WindowBatch, idx: np.ndarray, table, p: KernelParams,
                   compact_size: int):
    """Chunked masked solve over batch rows ``idx``: pad each chunk to
    ``compact_size`` (one static shape per tier), solve, yield the chunk's
    row indices and its outputs trimmed to the live rows."""
    for c0 in range(0, len(idx), compact_size):
        sub = idx[c0 : c0 + compact_size]
        n = len(sub)
        sseqs = np.full((compact_size,) + batch.seqs.shape[1:], 4, dtype=np.int8)
        slens = np.zeros((compact_size, batch.lens.shape[1]), dtype=np.int32)
        snsegs = np.zeros(compact_size, dtype=np.int32)
        sseqs[:n] = batch.seqs[sub]
        slens[:n] = batch.lens[sub]
        snsegs[:n] = batch.nsegs[sub]
        out = solve_window_batch(jnp.asarray(sseqs), jnp.asarray(slens),
                                 jnp.asarray(snsegs), table, p)
        yield sub, {k: np.asarray(v)[:n] for k, v in out.items()}


def solve_tiered(batch: WindowBatch, ladder: TierLadder,
                 compact_size: int = 64, skip_tier0: bool = False) -> dict:
    """Run the escalation ladder; returns host numpy results per window.

    Tier 0 runs on the full batch; failures are *compacted* into fixed-size
    sub-batches of ``compact_size`` (padded) for the escalation tiers, so the
    expensive rescue tiers only pay for the <10% of windows that need them and
    every tier keeps a single static shape (no recompilation storms).

    Output dict: cons int8 [B, CL], cons_len i32 [B], err f32 [B],
    solved bool [B], tier i32 [B] (-1 = unsolved).
    """
    B = batch.size
    if B < compact_size:
        # never pad a rescue chunk beyond the batch itself: a k-row shadow
        # audit sample (ISSUE 20) would otherwise pay a full 64-row padded
        # solve per escalation tier — ~8x its share of the batch. The chunk
        # size cannot change bytes: escalation solves rows independently,
        # the same invariant the audit's byte comparison rests on.
        compact_size = max(1, 1 << (max(B, 1) - 1).bit_length())
    CL = ladder.params[0].cons_len
    cons = np.full((B, CL), 4, dtype=np.int8)
    cons_len = np.zeros(B, dtype=np.int32)
    err = np.full(B, np.inf, dtype=np.float32)
    solved = np.zeros(B, dtype=bool)
    tier_of = np.full(B, -1, dtype=np.int32)
    m_ovf = np.zeros(B, dtype=bool)

    if not skip_tier0:
        p0 = ladder.params[0]
        out = solve_window_batch(jnp.asarray(batch.seqs), jnp.asarray(batch.lens),
                                 jnp.asarray(batch.nsegs), ladder.tables[p0.k], p0)
        m_ovf = np.array(out["m_overflow"])   # writable copy: rescue tiers OR in
        o_solved = np.asarray(out["solved"])
        if o_solved.any():
            cons[o_solved] = np.asarray(out["cons"])[o_solved]
            cons_len[o_solved] = np.asarray(out["cons_len"])[o_solved]
            err[o_solved] = np.asarray(out["err"])[o_solved]
            solved[o_solved] = True
            tier_of[o_solved] = 0
        if ladder.wide_p0 is not None:
            # overflow rescue, host-routed: same semantics as ladder_core's
            # wide block — capped windows re-solve at the rescue set size and
            # the wide result replaces the capped one wherever it solves
            wp = ladder.wide_p0
            widx = np.nonzero(m_ovf & (batch.nsegs >= p0.min_depth))[0]
            for sub, out_w in _solve_compact(batch, widx, ladder.tables[wp.k],
                                             wp, compact_size):
                w_solved = out_w["solved"]
                take = sub[w_solved]
                if len(take):
                    cons[take] = out_w["cons"][w_solved]
                    cons_len[take] = out_w["cons_len"][w_solved]
                    err[take] = out_w["err"][w_solved]
                    solved[take] = True
                    tier_of[take] = 0
                m_ovf[sub[w_solved & ~out_w["m_overflow"]]] = False

    for ti, p in enumerate(ladder.params[1:], start=1):
        idx = np.nonzero(~solved & (batch.nsegs >= p.min_depth))[0]
        if len(idx) == 0:
            break
        for sub, out in _solve_compact(batch, idx, ladder.tables[p.k], p,
                                       compact_size):
            m_ovf[sub] |= out["m_overflow"]
            s_solved = out["solved"]
            take = sub[s_solved]
            if len(take):
                cons[take] = out["cons"][s_solved]
                cons_len[take] = out["cons_len"][s_solved]
                err[take] = out["err"][s_solved]
                solved[take] = True
                tier_of[take] = ti
    return dict(cons=cons, cons_len=cons_len, err=err, solved=solved, tier=tier_of,
                m_ovf=m_ovf)
