"""Host-side escalation ladder over the batched window kernel.

The reference escalates k inside ``handleWindow`` per window; on device that
would force data-dependent control flow, so the ladder runs per *batch*: tier
1 solves ~90%+ of windows, later tiers re-run only if failures remain (each
tier is its own jitted program with static k — SURVEY.md §7.3 item 4 "adaptive
k without recompilation storms": fixed tiers, per-tier jitted fns, failure
routing on host).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..oracle.consensus import ConsensusConfig
from ..oracle.profile import ErrorProfile, OffsetLikely
from .tensorize import WindowBatch
from .window_kernel import KernelParams, solve_window_batch


@dataclass
class TierLadder:
    params: list[KernelParams]
    tables: dict[int, jnp.ndarray]   # k -> OL table [P, O] f32

    @classmethod
    def from_config(cls, profile: ErrorProfile, cfg: ConsensusConfig,
                    max_kmers: int = 64, rescue_max_kmers: int = 256) -> "TierLadder":
        tables = {}
        for k in cfg.k_values:
            P = cfg.w - k + 1 + cfg.dbg.len_slack
            O = cfg.w + 16
            tables[k] = jnp.asarray(OffsetLikely(profile, positions=P, max_offset=O).table)
        params = [
            KernelParams(k=k, min_count=mc, edge_min_count=emc,
                         count_frac=cfg.dbg.count_frac,
                         anchor_slack=cfg.dbg.anchor_slack,
                         end_slack=cfg.dbg.end_slack,
                         len_slack=cfg.dbg.len_slack,
                         n_candidates=cfg.dbg.n_candidates,
                         min_depth=cfg.dbg.min_depth,
                         max_err=cfg.dbg.max_err,
                         # min_count=1 tiers keep every count-1 k-mer; they need
                         # a much larger active set or the rescue fails on the
                         # arbitrary truncation (run compacted, so affordable)
                         max_kmers=rescue_max_kmers if mc <= 1 else max_kmers,
                         wlen=cfg.w)
            for k, mc, emc in cfg.tiers
        ]
        return cls(params=params, tables=tables)


def solve_tiered(batch: WindowBatch, ladder: TierLadder,
                 compact_size: int = 64, skip_tier0: bool = False) -> dict:
    """Run the escalation ladder; returns host numpy results per window.

    Tier 0 runs on the full batch; failures are *compacted* into fixed-size
    sub-batches of ``compact_size`` (padded) for the escalation tiers, so the
    expensive rescue tiers only pay for the <10% of windows that need them and
    every tier keeps a single static shape (no recompilation storms).

    Output dict: cons int8 [B, CL], cons_len i32 [B], err f32 [B],
    solved bool [B], tier i32 [B] (-1 = unsolved).
    """
    B = batch.size
    CL = ladder.params[0].cons_len
    cons = np.full((B, CL), 4, dtype=np.int8)
    cons_len = np.zeros(B, dtype=np.int32)
    err = np.full(B, np.inf, dtype=np.float32)
    solved = np.zeros(B, dtype=bool)
    tier_of = np.full(B, -1, dtype=np.int32)

    if not skip_tier0:
        p0 = ladder.params[0]
        out = solve_window_batch(jnp.asarray(batch.seqs), jnp.asarray(batch.lens),
                                 jnp.asarray(batch.nsegs), ladder.tables[p0.k], p0)
        o_solved = np.asarray(out["solved"])
        if o_solved.any():
            cons[o_solved] = np.asarray(out["cons"])[o_solved]
            cons_len[o_solved] = np.asarray(out["cons_len"])[o_solved]
            err[o_solved] = np.asarray(out["err"])[o_solved]
            solved[o_solved] = True
            tier_of[o_solved] = 0

    for ti, p in enumerate(ladder.params[1:], start=1):
        idx = np.nonzero(~solved & (batch.nsegs >= p.min_depth))[0]
        if len(idx) == 0:
            break
        for c0 in range(0, len(idx), compact_size):
            sub = idx[c0 : c0 + compact_size]
            n = len(sub)
            sseqs = np.full((compact_size,) + batch.seqs.shape[1:], 4, dtype=np.int8)
            slens = np.zeros((compact_size, batch.lens.shape[1]), dtype=np.int32)
            snsegs = np.zeros(compact_size, dtype=np.int32)
            sseqs[:n] = batch.seqs[sub]
            slens[:n] = batch.lens[sub]
            snsegs[:n] = batch.nsegs[sub]
            out = solve_window_batch(jnp.asarray(sseqs), jnp.asarray(slens),
                                     jnp.asarray(snsegs), ladder.tables[p.k], p)
            s_solved = np.asarray(out["solved"])[:n]
            take = sub[s_solved]
            if len(take):
                cons[take] = np.asarray(out["cons"])[:n][s_solved]
                cons_len[take] = np.asarray(out["cons_len"])[:n][s_solved]
                err[take] = np.asarray(out["err"])[:n][s_solved]
                solved[take] = True
                tier_of[take] = ti
    return dict(cons=cons, cons_len=cons_len, err=err, solved=solved, tier=tier_of)
