"""Minimal streaming FASTA reader/writer.

Equivalent of libmaus2 ``fastx/FastAReader`` (reference path per SURVEY.md §2.2;
file:line backfill pending — reference mount empty, SURVEY.md §0). The writer
wraps at 80 columns like the reference tool output.
"""

from __future__ import annotations

import io
from dataclasses import dataclass
from typing import Iterable, Iterator

from ..utils import aio


@dataclass
class FastaRecord:
    name: str
    seq: str


def read_fasta(path_or_file) -> Iterator[FastaRecord]:
    """Stream records from a FASTA path/URL (``mem:`` supported — the aio
    stream factory, SURVEY.md §2.2) or an open text file object."""
    if isinstance(path_or_file, (str, bytes)):
        fh = aio.open_input(path_or_file, "rt")
        own = True
    else:
        fh = path_or_file
        own = False
    try:
        name = None
        chunks: list[str] = []
        for line in fh:
            line = line.strip()
            if not line:
                continue
            if line.startswith(">"):
                if name is not None:
                    yield FastaRecord(name, "".join(chunks))
                name = line[1:].split()[0] if len(line) > 1 else ""
                chunks = []
            else:
                chunks.append(line)
        if name is not None:
            yield FastaRecord(name, "".join(chunks))
    finally:
        if own:
            fh.close()


def write_fasta(path_or_file, records: Iterable[FastaRecord | tuple], width: int = 80) -> None:
    if isinstance(path_or_file, (str, bytes)):
        fh: io.TextIOBase = aio.open_output(path_or_file, "wt")
        own = True
    else:
        fh = path_or_file
        own = False
    try:
        for rec in records:
            if isinstance(rec, tuple):
                rec = FastaRecord(*rec)
            fh.write(f">{rec.name}\n")
            s = rec.seq
            for i in range(0, len(s), width):
                fh.write(s[i : i + width])
                fh.write("\n")
            if not s:
                fh.write("\n")
    finally:
        if own:
            fh.close()
