"""External-memory LAS sort and symmetric filtering.

The reference's LAsort/LAmerge are block-memory external sorts and its
``filtersym`` streams with bounded state (SURVEY.md §2.2 LAS layer row);
the in-memory ``lassort``/``filter_symmetric`` paths fail at CHM-scale
inputs (measurement ladder configs 4-5, SURVEY.md §6). This module holds
the scale-capable equivalents:

- :func:`sort_las_external` — chunked sorted runs on disk + k-way streaming
  merge. Peak memory is ``mem_records`` Overlap objects regardless of file
  size; byte-identical to the in-memory sort (stable on equal keys).
- :func:`filter_symmetric_external` — the A->B iff B->A semi-join, hash-
  partitioned on the match key so each partition's key set fits in memory;
  byte-identical output to ``lastools.filter_symmetric`` with a DB.

Both write temp files next to the output (same filesystem => atomic-rename
friendly, and big-input temp space lives where the output goes, not /tmp).
"""

from __future__ import annotations

import heapq
import os
import tempfile

import numpy as np

from .las import LasFile, Overlap, write_las

#: sort key shared by las-sort, las-merge and the external runs
def _sort_key(o: Overlap):
    return (o.aread, o.bread, o.abpos)


def sort_las_external(in_path: str, out_path: str,
                      mem_records: int = 2_000_000,
                      use_native: bool = True) -> int:
    """Sort a LAS by (aread, bread, abpos) with bounded memory.

    The hot path is the native C++ external sort (``las_sort`` — the
    reference's LAsort is native too; ~30x the Python record stream). The
    Python path below is the executable spec and the fallback; both produce
    byte-identical output for the same ``mem_records`` (same run
    partitioning, stable chunk sort, earliest-run-wins fan-in-64 merge;
    parity-tested). Records stream in; every ``mem_records`` of them become
    one sorted temp run; runs merge straight into ``out_path``. Returns the
    record count.
    """
    from ..utils.aio import is_mem, local_path

    if use_native and not (is_mem(in_path) or is_mem(out_path)):
        try:
            from ..native import available
            native_ok = available()
        except Exception:
            native_ok = False
        if native_ok:
            from ..native.api import las_sort_native
            from .las import invalidate_index

            in_fs, out_fs = local_path(in_path), local_path(out_path)
            with tempfile.TemporaryDirectory(
                    dir=os.path.dirname(os.path.abspath(out_fs)),
                    prefix=".lassort.") as td:
                n = las_sort_native(in_fs, out_fs, td, mem_records)
            invalidate_index(out_path)
            return n

    las = LasFile(in_path)
    with tempfile.TemporaryDirectory(
            dir=os.path.dirname(os.path.abspath(out_path)),
            prefix=".lassort.") as td:
        runs: list[str] = []
        chunk: list[Overlap] = []

        def flush():
            if not chunk:
                return
            chunk.sort(key=_sort_key)
            rp = os.path.join(td, f"run{len(runs)}.las")
            write_las(rp, las.tspace, chunk)
            runs.append(rp)
            chunk.clear()

        for o in las:
            chunk.append(o)
            if len(chunk) >= mem_records:
                flush()
        if not runs:
            # whole input fit in one chunk (the common block-level case):
            # sort and write directly, no spill + re-merge I/O
            chunk.sort(key=_sort_key)
            return write_las(out_path, las.tspace, chunk)
        flush()
        # multi-level merge: each open run holds a file descriptor for the
        # whole merge, so fan-in is capped well under the process fd limit
        # (at the 2M default, 64^2 runs already cover 8G records)
        FANIN = 64
        gen = len(runs)
        while len(runs) > FANIN:
            merged: list[str] = []
            for g0 in range(0, len(runs), FANIN):
                group = runs[g0 : g0 + FANIN]
                gen += 1
                rp = os.path.join(td, f"run{gen}.las")
                write_las(rp, las.tspace,
                          heapq.merge(*(iter(LasFile(r)) for r in group),
                                      key=_sort_key))
                for r in group:
                    os.remove(r)
                merged.append(rp)
            runs = merged
        streams = [iter(LasFile(r)) for r in runs]
        return write_las(out_path, las.tspace,
                         heapq.merge(*streams, key=_sort_key))


# --------------------------------------------------------------------------
# Symmetric filter, hash-partitioned
# --------------------------------------------------------------------------

# exact 3-word packing of the 7-field match key (unsigned: aread<<33 must
# not overflow for read ids up to 2^31):
#   k0 = aread<<33 | bread<<1 | comp
#   k1 = abpos<<32 | aepos                (positions < 2^31, non-negative)
#   k2 = bbpos<<32 | bepos
_KEY_DT = np.dtype([("k0", "<u8"), ("k1", "<u8"), ("k2", "<u8")])
_IDX_DT = np.dtype([("k0", "<u8"), ("k1", "<u8"), ("k2", "<u8"), ("i", "<i8")])


def _pack(a, b, comp, ab, ae, bb, be) -> np.ndarray:
    out = np.empty(len(a), dtype=_KEY_DT)
    out["k0"] = ((a.astype(np.uint64) << np.uint64(33))
                 | (b.astype(np.uint64) << np.uint64(1))
                 | comp.astype(np.uint64))
    out["k1"] = (ab.astype(np.uint64) << np.uint64(32)) | ae.astype(np.uint64)
    out["k2"] = (bb.astype(np.uint64) << np.uint64(32)) | be.astype(np.uint64)
    return out


def _batch_arrays(batch: list[Overlap], db):
    """(own_keys, mirror_keys) for one record batch (exact mirror through
    read lengths for complemented overlaps — same rule as
    ``lastools.filter_symmetric``)."""
    n = len(batch)
    a = np.fromiter((o.aread for o in batch), np.int64, n)
    b = np.fromiter((o.bread for o in batch), np.int64, n)
    comp = np.fromiter((o.is_comp for o in batch), np.int64, n)
    ab = np.fromiter((o.abpos for o in batch), np.int64, n)
    ae = np.fromiter((o.aepos for o in batch), np.int64, n)
    bb = np.fromiter((o.bbpos for o in batch), np.int64, n)
    be = np.fromiter((o.bepos for o in batch), np.int64, n)
    own = _pack(a, b, comp, ab, ae, bb, be)
    alen = np.fromiter((db.read_length(o.aread) for o in batch), np.int64, n)
    blen = np.fromiter((db.read_length(o.bread) for o in batch), np.int64, n)
    # mirror of (a,b,[ab,ae),[bb,be)): plain overlaps swap the intervals;
    # complemented overlaps also flip both through their read length
    m_ab = np.where(comp == 1, blen - be, bb)
    m_ae = np.where(comp == 1, blen - bb, be)
    m_bb = np.where(comp == 1, alen - ae, ab)
    m_be = np.where(comp == 1, alen - ab, ae)
    mirror = _pack(b, a, comp, m_ab, m_ae, m_bb, m_be)
    return own, mirror


def filter_symmetric_external(las_path: str, out_path: str, db,
                              mem_records: int = 2_000_000,
                              batch: int = 65536) -> int:
    """Keep A->B overlaps iff the matching B->A record exists, with bounded
    memory: keys hash-partition onto disk, each partition joins in memory,
    matches set bits in a novl-bit bitmap, and a final streaming pass writes
    the kept records. ``db`` supplies read lengths for the complement-space
    mirror (required — the exact semantics of the in-memory path).

    Memory bound: the per-partition join holds ~max(mem_records, novl/nparts)
    keys at once, plus the always-resident novl-byte keep bitmap. nparts is
    sized so the first term stays at mem_records, capped only by the process
    fd limit (the scatter phase keeps 2 files per partition open at once);
    at the default ulimit of 1024 that caps nparts near 480, i.e. ~1e9
    records before partitions start exceeding mem_records."""
    las = LasFile(las_path)
    novl = las.novl
    try:
        import resource
        soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
        if soft < 0 or soft == resource.RLIM_INFINITY:
            soft = 4096   # unlimited: RLIM_INFINITY is -1 on Linux
        fd_cap = max(16, (soft - 64) // 2)
    except Exception:
        fd_cap = 256
    nparts = min(fd_cap, max(1, (novl + mem_records - 1) // mem_records))
    keep = np.zeros(novl, dtype=bool)

    with tempfile.TemporaryDirectory(
            dir=os.path.dirname(os.path.abspath(out_path)),
            prefix=".filtersym.") as td:
        kf = [open(os.path.join(td, f"k{p}.bin"), "wb") for p in range(nparts)]
        mf = [open(os.path.join(td, f"m{p}.bin"), "wb") for p in range(nparts)]
        try:
            idx0 = 0
            buf: list[Overlap] = []

            def emit():
                nonlocal idx0
                if not buf:
                    return
                own, mirror = _batch_arrays(buf, db)
                # partition by the key the join runs on: a record's OWN key
                # and another record's MIRROR key land in the same partition
                po = (own["k0"] ^ own["k1"] ^ own["k2"]) % nparts
                pm = (mirror["k0"] ^ mirror["k1"] ^ mirror["k2"]) % nparts
                rows = np.empty(len(buf), dtype=_IDX_DT)
                rows["k0"], rows["k1"], rows["k2"] = (
                    mirror["k0"], mirror["k1"], mirror["k2"])
                rows["i"] = np.arange(idx0, idx0 + len(buf))
                for p in range(nparts):
                    sel = po == p
                    if sel.any():
                        kf[p].write(own[sel].tobytes())
                    sel = pm == p
                    if sel.any():
                        mf[p].write(rows[sel].tobytes())
                idx0 += len(buf)
                buf.clear()

            for o in las:
                buf.append(o)
                if len(buf) >= batch:
                    emit()
            emit()
        finally:
            for fh in kf + mf:
                fh.close()

        for p in range(nparts):
            keys = np.sort(np.fromfile(os.path.join(td, f"k{p}.bin"),
                                       dtype=_KEY_DT))
            rows = np.fromfile(os.path.join(td, f"m{p}.bin"), dtype=_IDX_DT)
            if len(keys) == 0 or len(rows) == 0:
                continue
            mk = rows[["k0", "k1", "k2"]].astype(_KEY_DT)
            pos = np.searchsorted(keys, mk)
            pos = np.minimum(pos, len(keys) - 1)
            hit = keys[pos] == mk
            keep[rows["i"][hit]] = True

    return write_las(out_path, las.tspace,
                     (o for i, o in enumerate(las) if keep[i]))
