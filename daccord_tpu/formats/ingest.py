"""Ingest integrity layer: validated LAS/DB decode + quarantine planning.

The data plane trusts nothing here: every record header streamed off a .las
byte range is validated BEFORE its bytes steer a seek or a decode, and every
violation becomes a structured :class:`IngestIssue` (kind, byte offset, pile)
instead of a bare ``struct.error`` that kills the shard. Validation lives in
this host decode layer by design — the accelerator path stays free of
per-record branching (PAPERS: SeGraM), and containment follows the ParaFold
stage-isolation model: one bad artifact quarantines one pile, never a run.

Issue taxonomy (``IngestIssue.kind``):

==============  ============================================================
``truncation``  file/range ends mid-record or mid-trace, or header count
                promises more records than the bytes hold
``bad_header``  LAS header (novl/tspace) or DB .idx header fails sanity
``bad_magic``   a sidecar magic tag does not match (``LIDX`` index sidecar)
``bad_tlen``    negative, odd, or past-EOF trace length — framing is lost
                from this record on (recovered by :func:`_resync`)
``bad_coords``  overlap coordinates out of read bounds / degenerate span /
                negative diffs (framing intact; the pile is quarantined)
``bad_read_id`` aread/bread outside ``[0, len(db))``
``sort_order``  aread went backwards (the pipeline requires DALIGNER order)
``trace_mismatch``  tlen disagrees with the tile count implied by
                [abpos, aepos) and tspace — a coordinate or tlen bit flipped
``db_read``     the record references a DB read whose .idx entry failed
                validation (see ``read_db(strict=False)``)
==============  ============================================================

The scanner (:func:`scan_las_range`) is a header-only pass (it seeks over
trace payloads), producing a :class:`LasScanReport`: the issue list, the
clean byte segments safe for the fast native/python decoders, and one
quarantine marker per contained pile. When framing is lost it resyncs by
scanning forward for a chain of plausible records starting a later pile.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field

import numpy as np

from ..utils import aio

#: records a resync candidate must chain through before it is believed
_RESYNC_CHAIN = 3
#: buffer granularity of the forward resync scan
_RESYNC_CHUNK = 1 << 20


@dataclass
class IngestIssue:
    """One validated-decode violation, pinned to its byte offset and pile."""

    kind: str
    path: str
    offset: int
    detail: str
    aread: int | None = None   # pile the issue lands in (None = unknown)
    record: int | None = None  # record index within the scanned range

    def describe(self) -> str:
        where = f"record {self.record}" if self.record is not None else "range"
        pile = f" pile aread={self.aread}" if self.aread is not None else ""
        return (f"{self.path}: offset={self.offset} {where}{pile}: "
                f"[{self.kind}] {self.detail}")


class IngestError(ValueError):
    """Structured ingest failure: carries the full issue list.

    Subclasses ``ValueError`` so existing corrupt-file handling (``las-check``
    catches ``(ValueError, struct.error)``) keeps working unchanged.
    """

    def __init__(self, issues: list[IngestIssue] | IngestIssue, max_report: int = 10):
        if isinstance(issues, IngestIssue):
            issues = [issues]
        self.issues = issues
        first = issues[0]
        self.kind, self.offset, self.path = first.kind, first.offset, first.path
        lines = [iss.describe() for iss in issues[:max_report]]
        if len(issues) > max_report:
            lines.append(f"... {len(issues) - max_report} more issues")
        super().__init__(
            f"ingest integrity failure ({len(issues)} issue"
            f"{'s' if len(issues) != 1 else ''}):\n  " + "\n  ".join(lines))


@dataclass
class LasScanReport:
    """Result of a validating scan over one LAS byte range.

    ``segments`` is the byte-ordered quarantine plan consumed by the
    pipeline: ``("clean", start, end)`` ranges safe for the unvalidated fast
    decoders, interleaved with ``("quarantine", aread|None, offset, kind,
    detail)`` markers — one per contained pile (or unknown region when
    framing was lost and the pile identity with it).
    """

    path: str
    start: int
    end: int
    n_records: int = 0
    n_piles: int = 0                    # clean piles only
    issues: list = field(default_factory=list)
    segments: list = field(default_factory=list)
    pile_ranges: list = field(default_factory=list)  # clean (start, end) per pile

    @property
    def ok(self) -> bool:
        return not self.issues

    def error(self) -> IngestError:
        return IngestError(self.issues)


def _expected_tiles(abpos: int, aepos: int, tspace: int) -> int:
    # mirror of Overlap.ntiles without constructing the dataclass
    if aepos <= abpos:
        return 0
    first = (abpos // tspace + 1) * tspace
    if first >= aepos:
        return 1
    return 1 + (aepos - first + tspace - 1) // tspace


def _check_record(vals: tuple, off: int, limit: int, tsize: int, tspace: int,
                  rlens: np.ndarray | None, nreads: int | None,
                  prev_aread: int | None, bad_reads: frozenset | set,
                  fsize: int | None = None) -> tuple[str, str] | None:
    """First violation of one unpacked record header, or None when valid.

    Returns ``(kind, detail)``. Check order matters: read-id bounds come
    before any ``rlens[...]`` use, and tlen (the framing field) is checked
    before the coordinate checks so a framing loss is reported as such.
    A trace running past the physical file end (``fsize``) is ``truncation``
    (the bytes are gone); past only ``limit`` is ``bad_tlen`` (absurd value).
    """
    from .las import _REC_SIZE

    tlen, diffs, abpos, bbpos, aepos, bepos, _flags, aread, bread = vals
    if aread < 0 or (nreads is not None and aread >= nreads):
        return "bad_read_id", f"aread={aread} outside [0, {nreads})"
    if bread < 0 or (nreads is not None and bread >= nreads):
        return "bad_read_id", f"bread={bread} outside [0, {nreads})"
    if aread in bad_reads or bread in bad_reads:
        which = "aread" if aread in bad_reads else "bread"
        return "db_read", f"{which}={aread if which == 'aread' else bread} " \
                          f"references a corrupt DB read record"
    if prev_aread is not None and aread < prev_aread:
        return "sort_order", f"aread went backwards ({prev_aread} -> {aread})"
    if tlen < 0 or tlen % 2:
        return "bad_tlen", f"tlen={tlen} (negative or odd)"
    rec_end = off + _REC_SIZE + tlen * tsize
    if fsize is not None and rec_end > fsize:
        return "truncation", (f"trace of tlen={tlen} runs {rec_end - fsize} "
                              f"bytes past EOF")
    if rec_end > limit:
        return "bad_tlen", (f"tlen={tlen} runs {rec_end - limit} "
                            f"bytes past the range end")
    rlen_a = int(rlens[aread]) if rlens is not None else None
    rlen_b = int(rlens[bread]) if rlens is not None else None
    if not (0 <= abpos < aepos and (rlen_a is None or aepos <= rlen_a)):
        return "bad_coords", (f"a-span [{abpos},{aepos}) out of bounds "
                              f"(A read length {rlen_a})")
    if not (0 <= bbpos < bepos and (rlen_b is None or bepos <= rlen_b)):
        return "bad_coords", (f"b-span [{bbpos},{bepos}) out of bounds "
                              f"(B read length {rlen_b})")
    if diffs < 0:
        return "bad_coords", f"diffs={diffs} negative"
    if tlen != 2 * _expected_tiles(abpos, aepos, tspace):
        return "trace_mismatch", (f"tlen={tlen} but [abpos,aepos) at tspace "
                                  f"{tspace} implies {2 * _expected_tiles(abpos, aepos, tspace)}")
    return None


def _try_chain(fh, off: int, limit: int, min_aread: int, tsize: int,
               tspace: int, rlens: np.ndarray | None, nreads: int | None,
               bad_reads) -> bool:
    """True when ``off`` starts a chain of plausible records opening a pile
    strictly after ``min_aread`` (the resync acceptance rule)."""
    from .las import _REC_FMT, _REC_SIZE

    prev = None
    for step in range(_RESYNC_CHAIN):
        if off == limit:
            return step > 0          # clean landing on the range end
        fh.seek(off)
        raw = fh.read(_REC_SIZE)
        if len(raw) < _REC_SIZE:
            return False
        vals = struct.unpack(_REC_FMT, raw)
        if _check_record(vals, off, limit, tsize, tspace, rlens, nreads,
                         prev, bad_reads) is not None:
            return False
        if step == 0 and vals[7] <= min_aread:
            return False             # must open a LATER pile, never rejoin
        prev = vals[7]
        off += _REC_SIZE + vals[0] * tsize
    return True


def _candidate_offsets(buf: bytes, span: int, min_aread: int,
                       nreads: int | None) -> np.ndarray:
    """Byte offsets in ``buf[:span]`` whose tlen/aread fields pass the cheap
    plausibility filter — vectorized over all four int32 alignment phases so
    the resync never pays a Python unpack per byte (a multi-GB unrecoverable
    region would otherwise stall the scan for hours)."""
    cands = []
    for p in range(4):
        if len(buf) - p < 4:
            # a 1-3 byte chunk residue has no int32 at this phase;
            # np.frombuffer would raise on the negative count
            continue
        a32 = np.frombuffer(buf, "<i4", offset=p,
                            count=(len(buf) - p) // 4)
        # offset i = p + 4j carries tlen at a32[j] and aread at a32[j + 7]
        m = min(len(a32) - 7, (span - p + 3) // 4)
        if m <= 0:
            continue
        tl = a32[:m]
        ar = a32[7 : 7 + m]
        ok = (tl >= 0) & ((tl & 1) == 0) & (ar > min_aread)
        if nreads is not None:
            ok &= ar < nreads
        cands.append(p + 4 * np.nonzero(ok)[0].astype(np.int64))
    if not cands:
        return np.zeros(0, np.int64)
    return np.sort(np.concatenate(cands))


def _resync(fh, pos: int, limit: int, min_aread: int, tsize: int, tspace: int,
            rlens: np.ndarray | None, nreads: int | None, bad_reads) -> int | None:
    """Forward-scan for the next believable pile start after a framing loss.

    Byte-granular over buffered chunks; a vectorized tlen/aread plausibility
    filter rejects almost every offset, and survivors must pass the full
    record check plus chain ``_RESYNC_CHAIN`` records. Returns the resync
    offset, or None when no later pile exists.
    """
    from .las import _REC_FMT, _REC_SIZE

    base = pos
    while base < limit:
        fh.seek(base)
        buf = fh.read(min(_RESYNC_CHUNK + _REC_SIZE, limit - base))
        span = min(len(buf), _RESYNC_CHUNK)
        for i in _candidate_offsets(buf, span, min_aread, nreads):
            i = int(i)
            if i + _REC_SIZE > len(buf):
                break
            vals = struct.unpack_from(_REC_FMT, buf, i)
            if _check_record(vals, base + i, limit, tsize, tspace, rlens,
                             nreads, None, bad_reads) is not None:
                continue
            if _try_chain(fh, base + i, limit, min_aread, tsize, tspace,
                          rlens, nreads, bad_reads):
                return base + i
        base += span
    return None


def scan_las_range(las, start: int | None = None, end: int | None = None,
                   rlens: np.ndarray | None = None,
                   bad_reads=frozenset(), max_issues: int = 1000) -> LasScanReport:
    """Validating header-only scan of ``las`` (a :class:`~.las.LasFile`) over
    ``[start, end)``; returns the :class:`LasScanReport` quarantine plan.

    With ``rlens`` (per-read lengths of the companion DB) coordinates are
    bounds-checked against read lengths and read ids against ``len(db)``;
    ``bad_reads`` marks DB read records that themselves failed validation so
    piles referencing them quarantine as ``db_read``.
    """
    from .las import _HDR_SIZE, _REC_FMT, _REC_SIZE

    path = las.path
    size = aio.getsize(path)
    s = _HDR_SIZE if start is None else int(start)
    e = size if end is None else int(end)
    # the novl cross-check applies whenever the RANGE covers the whole file,
    # however it was spelled — run_shard passes the full range explicitly
    whole_file = s == _HDR_SIZE and e == size
    nreads = len(rlens) if rlens is not None else None
    rep = LasScanReport(path=path, start=s, end=e)
    tsize, tspace = las._tsize, las.tspace

    def issue(kind: str, off: int, detail: str, aread=None, record=None):
        if len(rep.issues) < max_issues:
            rep.issues.append(IngestIssue(kind=kind, path=path, offset=off,
                                          detail=detail, aread=aread,
                                          record=record))

    segments: list = []
    clean_from: int | None = None      # start of the current run of clean piles

    def close_clean(upto: int):
        nonlocal clean_from
        if clean_from is not None and upto > clean_from:
            segments.append(("clean", clean_from, upto))
        clean_from = None

    pos = s
    nrec = 0
    cur_aread: int | None = None       # pile being walked
    pile_start = pos
    pile_bad: tuple[str, str] | None = None
    taint_next: tuple[str, str] | None = None  # mark the NEXT pile bad too
                                       # (set when a corrupt record's own
                                       # aread field is untrustworthy, so
                                       # pile membership is ambiguous)

    def close_pile(upto: int):
        """Commit the walked pile [pile_start, upto) as clean or quarantined."""
        nonlocal clean_from
        if cur_aread is None:
            return
        if pile_bad is None:
            if clean_from is None:
                clean_from = pile_start
            rep.n_piles += 1
            rep.pile_ranges.append((pile_start, upto))
        else:
            close_clean(pile_start)
            segments.append(("quarantine", cur_aread, pile_start,
                             pile_bad[0], pile_bad[1]))

    with aio.open_input(path, "rb") as fh:
        while pos < e:
            fh.seek(pos)
            raw = fh.read(_REC_SIZE)
            if pos + _REC_SIZE > e or len(raw) < _REC_SIZE:
                issue("truncation", pos, "range ends mid-record header",
                      aread=cur_aread, record=nrec)
                q_start = pile_start if cur_aread is not None else pos
                close_clean(q_start)
                segments.append(("quarantine", cur_aread, q_start,
                                 "truncation", "range ends mid-record"))
                cur_aread = None
                pos = e
                break
            vals = struct.unpack(_REC_FMT, raw)
            bad = _check_record(vals, pos, e, tsize, tspace, rlens, nreads,
                                cur_aread, bad_reads, fsize=size)
            if bad is None:
                aread = vals[7]
                if aread != cur_aread:
                    close_pile(pos)
                    cur_aread = aread
                    pile_start = pos
                    pile_bad = taint_next
                    taint_next = None
                nrec += 1
                pos += _REC_SIZE + vals[0] * tsize
                continue
            kind, detail = bad
            # which pile does this corrupt record belong to? When its aread
            # field survived the id/sort checks it is trustworthy: a
            # differing aread OPENS a new pile — the previous pile is
            # complete and clean, and must not be quarantined for its
            # neighbor's corruption. An untrustworthy aread (the aread
            # field itself violated, or sort order broke) leaves membership
            # ambiguous: taint the current pile AND the next one
            # (conservative containment beats silent divergence).
            trusted_aread = not (kind == "sort_order"
                                 or (kind == "bad_read_id"
                                     and detail.startswith("aread")))
            if (trusted_aread and cur_aread is not None
                    and vals[7] != cur_aread):
                close_pile(pos)
                cur_aread = vals[7]
                pile_start = pos
                pile_bad = None
                # a pending taint is satisfied by this pile: it IS the "next
                # pile" the ambiguous record may have belonged to, and it is
                # being quarantined anyway — a leaked taint would otherwise
                # falsely contain the next CLEAN pile after this one
                taint_next = None
            elif not trusted_aread:
                taint_next = (kind, detail)
            issue(kind, pos, detail, aread=cur_aread, record=nrec)
            nrec += 1
            # the reported kind may be an earlier check (read id, sort
            # order), but only a SANE tlen may steer the walk forward — a
            # doubly-corrupt record must go through resync, not advance by
            # a garbage (possibly negative) trace length
            framing_ok = (vals[0] >= 0 and vals[0] % 2 == 0
                          and pos + _REC_SIZE + vals[0] * tsize <= e)
            if kind in ("bad_tlen", "truncation") or not framing_ok:
                if cur_aread is None and trusted_aread:
                    # framing lost on the range-opening record, but its
                    # aread passed the id/sort checks: adopt it as the
                    # quarantined pile's key so the resync floor is the
                    # REAL pile id — otherwise resync (min_aread=-1) would
                    # rejoin this same pile mid-pile and its read would be
                    # silently corrected from partial evidence
                    cur_aread = vals[7]
                    pile_start = pos
                # framing lost: quarantine from the pile start and resync
                q_start = pile_start if cur_aread is not None else pos
                q_aread = cur_aread
                close_clean(q_start)
                nxt = _resync(fh, pos + 1, e,
                              cur_aread if cur_aread is not None else -1,
                              tsize, tspace, rlens, nreads, bad_reads)
                stop = nxt if nxt is not None else e
                segments.append(("quarantine", q_aread, q_start, kind,
                                 detail + f" (skipped {stop - q_start} bytes)"))
                cur_aread = None
                pile_bad = None
                # any pending ambiguity is wholly contained in the resync
                # quarantine segment; a taint surviving past it would
                # falsely contain the first clean pile after the resync
                taint_next = None
                pos = stop
                if nxt is None:
                    break
                continue
            # framing intact: the record still frames the stream — keep
            # walking the pile, which is now marked for quarantine
            if cur_aread is None:
                # a corrupt record opens the range: adopt its aread as the
                # pile key (emission bounds-checks it again downstream)
                cur_aread = vals[7]
                pile_start = pos
            if pile_bad is None:
                pile_bad = (kind, detail)
            pos += _REC_SIZE + vals[0] * tsize
    close_pile(pos)
    close_clean(pos)
    already_truncated = any(s[0] == "quarantine" and s[3] == "truncation"
                            for s in segments)
    # the count cross-check must run even when OTHER issue kinds were found
    # (a bad record mid-file must not mask a record-boundary EOF cut); it is
    # suppressed only when a truncation was already detected positionally
    if whole_file and nrec != las.novl and not already_truncated:
        if nrec < las.novl:
            # fewer records than promised: a record-boundary truncation only
            # this header cross-check can see
            issue("truncation", pos,
                  f"header promises {las.novl} records, file holds {nrec}")
            segments.append(("quarantine", None, pos, "truncation",
                             f"{las.novl - nrec} records missing at EOF"))
        else:
            # MORE records than promised: every byte is present and valid —
            # the header count is what's wrong (bit-flipped low, or records
            # appended without patching novl); nothing to quarantine
            issue("bad_header", 0,
                  f"header promises {las.novl} records, file holds {nrec} "
                  f"(surplus)")
    rep.n_records = nrec
    rep.segments = segments
    return rep


def scan_with_db(db, las, start: int | None = None,
                 end: int | None = None) -> LasScanReport:
    """:func:`scan_las_range` wired to a loaded DB: read lengths and any
    ``bad_reads`` marked by ``read_db(strict=False)`` feed the coordinate /
    read-id / db_read checks. The one construction shared by every policy
    gate (pipeline, checkpointed launch, CLI pre-estimation)."""
    rlens = np.fromiter((r.rlen for r in db.reads), np.int64, len(db.reads))
    return scan_las_range(las, start, end, rlens=rlens,
                          bad_reads=frozenset(getattr(db, "bad_reads", None)
                                              or set()))


def sidecar_issues(las_path: str) -> list[IngestIssue]:
    """Validate the ``<path>.idx`` aread-index sidecar, when present.

    The index loader itself silently rebuilds on any malformation (a torn
    sidecar must never sink a run); this is the *diagnostic* face of the same
    checks, used by ``las-check`` so operators learn a sidecar is torn
    before N array jobs each pay a silent full rescan.
    """
    if aio.is_mem(las_path):
        return []
    sidecar = aio.local_path(las_path) + ".idx"
    if not os.path.exists(sidecar):
        return []
    issues: list[IngestIssue] = []
    try:
        with open(sidecar, "rb") as fh:
            hdr = fh.read(8)
            if len(hdr) < 8:
                issues.append(IngestIssue("truncation", sidecar, len(hdr),
                                          "sidecar shorter than its header"))
                return issues
            magic, n = struct.unpack("<4sI", hdr)
            if magic != b"LIDX":
                issues.append(IngestIssue("bad_magic", sidecar, 0,
                                          f"magic {magic!r} != b'LIDX'"))
                return issues
            payload = fh.read(16 * n)
            if len(payload) < 16 * n:
                # short payload only: the loader reads exactly 16*n bytes,
                # so trailing extra bytes are harmless, not a torn sidecar
                issues.append(IngestIssue(
                    "truncation", sidecar, 8 + len(payload),
                    f"payload holds {len(payload)} bytes, header promises "
                    f"{16 * n}"))
    except OSError as ex:
        issues.append(IngestIssue("truncation", sidecar, 0, f"unreadable ({ex})"))
    return issues
