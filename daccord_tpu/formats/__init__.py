from .fasta import read_fasta, write_fasta, FastaRecord
from .dazzdb import DazzDB, DazzRead, write_db, read_db, write_track, read_track
from .ingest import IngestError, IngestIssue, LasScanReport, scan_las_range
from .las import Overlap, LasFile, write_las, read_las, index_las, OVL_COMP

__all__ = [
    "IngestError",
    "IngestIssue",
    "LasScanReport",
    "scan_las_range",
    "read_fasta",
    "write_fasta",
    "FastaRecord",
    "DazzDB",
    "DazzRead",
    "write_db",
    "read_db",
    "write_track",
    "read_track",
    "Overlap",
    "LasFile",
    "write_las",
    "read_las",
    "index_las",
    "OVL_COMP",
]
