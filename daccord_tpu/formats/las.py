"""DALIGNER .las overlap file reader/writer + aread-range byte index.

Equivalent of libmaus2 ``dazzler/align``: ``Overlap``, ``AlignmentFile``,
``SimpleOverlapParser``, ``OverlapIndexer``, ``AlignmentWriter`` (SURVEY.md
§2.2; reference file:line citations pending backfill — mount empty, SURVEY.md
§0). On-disk layout follows the public DALIGNER ``align.h`` convention:

Header::

    int64 novl          total number of overlap records
    int32 tspace        trace-point spacing (A-read tiles)

Record (40 bytes, the Overlap struct minus its leading trace pointer, LP64
field layout)::

    int32 tlen, diffs, abpos, bbpos, aepos, bepos
    uint32 flags                      (bit 0 = B complemented)
    int32 aread, bread
    4 bytes struct tail padding

followed by the trace array: ``tlen`` values, uint8 when
``tspace <= TRACE_XOVR(125)`` else uint16, laid out as pairs
``(diffs_in_tile, b_bases_in_tile)`` — ``tlen/2`` tiles covering
``[abpos, aepos)`` cut at multiples of ``tspace``.

The aread-range byte index built here is the multi-host sharding unit of the
runtime (SURVEY.md §2.3 row DP): each host streams only its own byte range.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from typing import Iterable, Iterator

import numpy as np

from ..utils import aio
from .ingest import IngestError, IngestIssue

TRACE_XOVR = 125
OVL_COMP = 0x1  # flags bit: B read is complemented

_REC_FMT = "<6iI2i4x"
_REC_SIZE = struct.calcsize(_REC_FMT)
assert _REC_SIZE == 40, _REC_SIZE


@dataclass
class Overlap:
    aread: int
    bread: int
    abpos: int
    aepos: int
    bbpos: int
    bepos: int
    flags: int = 0
    diffs: int = 0
    # trace: shape (ntiles, 2) int32 — per-tile (diffs, b_bases)
    trace: np.ndarray = field(default_factory=lambda: np.zeros((0, 2), dtype=np.int32))

    @property
    def is_comp(self) -> bool:
        return bool(self.flags & OVL_COMP)

    def ntiles(self, tspace: int) -> int:
        if self.aepos <= self.abpos:
            return 0
        first = (self.abpos // tspace + 1) * tspace
        if first >= self.aepos:
            return 1
        return 1 + (self.aepos - first + tspace - 1) // tspace

    def tile_bounds(self, tspace: int) -> np.ndarray:
        """A-read tile boundaries: array of len ntiles+1, [abpos..aepos]."""
        bounds = [self.abpos]
        nxt = (self.abpos // tspace + 1) * tspace
        while nxt < self.aepos:
            bounds.append(nxt)
            nxt += tspace
        bounds.append(self.aepos)
        return np.asarray(bounds, dtype=np.int64)


def _trace_dtype(tspace: int):
    return np.uint8 if tspace <= TRACE_XOVR else np.uint16


def _write_las_stream(fh, tspace: int, overlaps: Iterable[Overlap]) -> int:
    tdt = _trace_dtype(tspace)
    novl = 0
    fh.write(struct.pack("<qi4x", 0, tspace))  # novl patched at the end
    for ovl in overlaps:
        trace = np.asarray(ovl.trace, dtype=np.int64).reshape(-1)
        tlen = len(trace)
        fh.write(struct.pack(_REC_FMT, tlen, ovl.diffs, ovl.abpos, ovl.bbpos,
                             ovl.aepos, ovl.bepos, ovl.flags, ovl.aread, ovl.bread))
        fh.write(trace.astype(tdt).tobytes())
        novl += 1
    fh.seek(0)
    fh.write(struct.pack("<q", novl))
    return novl


def write_las(path: str, tspace: int, overlaps: Iterable[Overlap]) -> int:
    """Write overlaps to a .las path/URL (``mem:`` supported); returns record
    count.

    Real-file outputs commit via tmp + fsync + ``os.replace``: the header's
    ``novl`` is patched only after every record landed, so a crash mid-write
    must never leave a valid-looking LAS with ``novl=0`` at the target path
    that downstream tools would read as legitimately empty. (``mem:`` writes
    are already atomic — the store commits at close.)"""
    if aio.is_mem(path):
        with aio.open_output(path, "wb") as fh:
            novl = _write_las_stream(fh, tspace, overlaps)
    else:
        novl = aio.durable_write(
            path, lambda fh: _write_las_stream(fh, tspace, overlaps))
    invalidate_index(path)
    return novl


def invalidate_index(path: str) -> None:
    """Drop the aread-index sidecar of a (re)written LAS — one owner for
    the sidecar lifecycle rule, shared by every writer path (write_las, the
    native sort/merge dispatchers)."""
    if aio.is_mem(path):
        return
    try:
        os.remove(aio.local_path(path) + ".idx")
    except OSError:
        pass


_HDR_FMT = "<qi4x"
_HDR_SIZE = struct.calcsize(_HDR_FMT)


class LasFile:
    """Streaming .las reader with optional byte-range restriction.

    Accepts paths or aio URLs (``mem:`` in-memory files, SURVEY.md §2.2 aio
    row) everywhere; the persistent index sidecar only applies to real files.
    """

    def __init__(self, path: str):
        self.path = path
        with aio.open_input(path, "rb") as fh:
            hdr = fh.read(_HDR_SIZE)
        if len(hdr) < _HDR_SIZE:
            raise IngestError(IngestIssue(
                "truncation", path, len(hdr),
                f"file holds {len(hdr)} of the {_HDR_SIZE}-byte LAS header"))
        self.novl, self.tspace = struct.unpack(_HDR_FMT, hdr)
        if not (1 <= self.tspace <= 1_000_000):
            raise IngestError(IngestIssue(
                "bad_header", path, 8, f"tspace={self.tspace} out of range"))
        if self.novl < 0:
            # novl merely OVERSTATING the record bytes is NOT rejected here:
            # that is what a truncated file looks like, and the validating
            # scan (formats/ingest.py) quarantines truncation per-pile —
            # the constructor must stay usable on damaged files
            raise IngestError(IngestIssue(
                "bad_header", path, 0, f"novl={self.novl} negative"))
        self._tdt = _trace_dtype(self.tspace)
        self._tsize = np.dtype(self._tdt).itemsize

    def __iter__(self) -> Iterator[Overlap]:
        return self.iter_range()

    def iter_range(self, start: int | None = None, end: int | None = None) -> Iterator[Overlap]:
        """Iterate records in byte range [start, end) (defaults: whole file)."""
        with aio.open_input(self.path, "rb") as fh:
            fh.seek(start if start is not None else _HDR_SIZE)
            limit = end if end is not None else aio.getsize(self.path)
            while fh.tell() < limit:
                off = fh.tell()
                raw = fh.read(_REC_SIZE)
                if len(raw) < _REC_SIZE:
                    break
                tlen, diffs, abpos, bbpos, aepos, bepos, flags, aread, bread = struct.unpack(_REC_FMT, raw)
                if tlen < 0 or tlen % 2:
                    # validated decode: a corrupt tlen must surface as a
                    # structured error, never steer fh.read(negative) into
                    # swallowing the rest of the file
                    raise IngestError(IngestIssue(
                        "bad_tlen", self.path, off,
                        f"tlen={tlen} (negative or odd)", aread=aread))
                traw = fh.read(tlen * self._tsize)
                if len(traw) < tlen * self._tsize:
                    raise IngestError(IngestIssue(
                        "truncation", self.path, off,
                        f"trace of tlen={tlen} cut {tlen * self._tsize - len(traw)} "
                        f"bytes short", aread=aread))
                trace = np.frombuffer(traw, dtype=self._tdt).astype(np.int32).reshape(-1, 2)
                yield Overlap(aread=aread, bread=bread, abpos=abpos, aepos=aepos,
                              bbpos=bbpos, bepos=bepos, flags=flags, diffs=diffs,
                              trace=trace)

    def iter_piles(self, start: int | None = None, end: int | None = None) -> Iterator[tuple[int, list[Overlap]]]:
        """Group a (sorted-by-aread) stream into (aread, pile) tuples."""
        pile: list[Overlap] = []
        cur = None
        for ovl in self.iter_range(start, end):
            if cur is not None and ovl.aread != cur:
                yield cur, pile
                pile = []
            cur = ovl.aread
            pile.append(ovl)
        if cur is not None:
            yield cur, pile


def read_las(path: str) -> tuple[int, list[Overlap]]:
    f = LasFile(path)
    return f.tspace, list(f)


def index_las(path: str, use_sidecar: bool = True) -> np.ndarray:
    """Build an aread index: rows (aread, byte_offset_of_first_record).

    Enables byte-range sharding by aread range (the reference's
    OverlapIndexer role). Rows are emitted once per distinct aread, in file
    order; the file must be sorted by aread (DALIGNER sort order).

    The index persists as a ``<path>.idx`` sidecar (int64 pairs after an
    8-byte magic+count header) so N array jobs sharing one LAS pay one scan
    total, not one each; a sidecar older than the LAS is rebuilt.
    """
    if aio.is_mem(path):
        use_sidecar = False   # the sidecar cache is for durable files
    # sidecar lives next to the REAL file: a file: scheme must strip to the
    # same .idx path the plain-path form manages
    fs_path = aio.local_path(path)
    sidecar = fs_path + ".idx"
    if use_sidecar and os.path.exists(sidecar) \
            and os.path.getmtime(sidecar) >= os.path.getmtime(fs_path):
        # any malformed sidecar (truncated header/payload, concurrent-writer
        # corruption) falls through to a fresh scan instead of erroring
        try:
            with open(sidecar, "rb") as fh:
                hdr = fh.read(8)
                if len(hdr) == 8:
                    magic, n = struct.unpack("<4sI", hdr)
                    payload = fh.read(16 * n)
                    if magic == b"LIDX" and len(payload) == 16 * n:
                        return np.frombuffer(payload, dtype=np.int64).reshape(-1, 2)
        except OSError:
            pass
    f = LasFile(path)
    rows: list[tuple[int, int]] = []
    with aio.open_input(path, "rb") as fh:
        fh.seek(_HDR_SIZE)
        size = aio.getsize(path)
        last = None
        while fh.tell() < size:
            off = fh.tell()
            raw = fh.read(_REC_SIZE)
            if len(raw) < _REC_SIZE:
                break
            tlen = struct.unpack_from("<i", raw)[0]
            aread = struct.unpack_from("<i", raw, 28)[0]
            if tlen < 0 or off + _REC_SIZE + tlen * f._tsize > size:
                # a corrupt tlen would steer the seek into garbage and the
                # indexer would silently emit a wrong index; reject instead
                raise IngestError(IngestIssue(
                    "bad_tlen", path, off,
                    f"tlen={tlen} (negative or past EOF at size {size})",
                    aread=last))
            if aread != last:
                rows.append((aread, off))
                last = aread
            fh.seek(tlen * f._tsize, os.SEEK_CUR)
    idx = np.asarray(rows, dtype=np.int64).reshape(-1, 2)
    if use_sidecar:
        try:
            # per-process tmp name: concurrent array jobs racing to build the
            # same index must not interleave writes into one tmp inode
            tmp = f"{sidecar}.{os.getpid()}.tmp"
            with open(tmp, "wb") as fh:
                fh.write(struct.pack("<4sI", b"LIDX", len(idx)))
                fh.write(idx.tobytes())
            os.replace(tmp, sidecar)
        except OSError:
            pass  # read-only directory: the index simply isn't cached
    return idx


def shard_ranges(path: str, nshards: int) -> list[tuple[int, int]]:
    """Split a .las into ``nshards`` aread-aligned byte ranges (≈ equal bytes).

    This is the multi-host data-plane sharding primitive: the reference's
    ``-J i,n`` CLI sharding re-imagined as byte ranges over one file.
    """
    size = aio.getsize(path)
    if nshards <= 1:
        # no cut points to choose — skip the index entirely, so single-shard
        # runs (incl. quarantine-policy runs over a damaged LAS, whose index
        # build rightly fails) never pay or require the aread scan
        return [(_HDR_SIZE, size)]
    idx = index_las(path)
    if len(idx) == 0:
        # nshards >= 2 here (the early return above owns nshards <= 1)
        return [(_HDR_SIZE, size)] + [(size, size)] * (nshards - 1)
    starts = idx[:, 1]
    # choose cut points at pile boundaries closest to equal byte splits
    cuts = [_HDR_SIZE]
    for s in range(1, nshards):
        target = _HDR_SIZE + (size - _HDR_SIZE) * s // nshards
        j = int(np.searchsorted(starts, target))
        j = min(j, len(starts) - 1)
        cuts.append(int(starts[j]))
    cuts.append(size)
    # enforce monotonicity (tiny files)
    for i in range(1, len(cuts)):
        cuts[i] = max(cuts[i], cuts[i - 1])
    return [(cuts[i], cuts[i + 1]) for i in range(nshards)]


def range_for_areads(path: str, lo: int, hi: int) -> tuple[int, int]:
    """Byte range of the records whose aread is in [lo, hi).

    The per-DB-block workflow primitive: block i of the DB (see
    ``formats.dazzdb.db_blocks``) maps to the LAS byte range of its piles.
    Requires an aread-sorted LAS (DALIGNER order); uses the sidecar index.
    """
    idx = index_las(path)
    size = aio.getsize(path)
    if len(idx) == 0:
        return size, size
    areads = idx[:, 0]
    i = int(np.searchsorted(areads, lo, side="left"))
    j = int(np.searchsorted(areads, hi, side="left"))
    start = int(idx[i, 1]) if i < len(idx) else size
    end = int(idx[j, 1]) if j < len(idx) else size
    return start, end
