"""Dazzler database (.db / .idx / .bps) reader and writer, plus track I/O.

Equivalent of libmaus2 ``dazzler/db/DatabaseFile`` + ``Track*`` (SURVEY.md
§2.2; reference file:line citations pending backfill — the reference mount was
empty, SURVEY.md §0). The binary layout below follows the public DAZZ_DB
``DB.h`` structures as written to disk by ``fwrite(&db, sizeof(DAZZ_DB), ...)``
on LP64 platforms:

``.<name>.idx``::

    DAZZ_DB header, 112 bytes:
      int32  ureads, treads, cutoff, allarr        @ 0,4,8,12
      f32[4] freq                                  @ 16
      int32  maxlen                                @ 32   (+4 pad)
      int64  totlen                                @ 40
      int32  nreads, trimmed, part, ufirst, tfirst @ 48..67 (+4 pad)
      ptr    path                                  @ 72  (garbage on disk)
      int32  loaded                                @ 80   (+4 pad)
      ptr    bases, reads, tracks                  @ 88,96,104 (garbage)
    then ureads records of DAZZ_READ, 40 bytes each:
      int32 origin, rlen, fpulse                   @ 0,4,8 (+4 pad)
      int64 boff, coff                             @ 16,24
      int32 flags                                  @ 32   (+4 pad)

``.<name>.bps``::   2-bit packed bases, 4/byte, first base in the top bits.

``<name>.db``  ::   small text stub (file list + block partition), kept
                    human-compatible with ``fasta2DB`` output.

Track files ``.<name>.<track>.anno`` / ``.data`` follow the variable-length
Dazzler track convention used by daccord's ``inqual`` track: the .anno file is
``int32 nreads, int32 size(=0)`` followed by ``nreads+1`` int64 byte offsets
into ``.data``.

Byte-level parity with DAZZ_DB must be re-verified against the reference tree
when it appears (SURVEY.md §8 item 6); all internal producers/consumers in this
framework go through this module, so the framework is self-consistent either
way.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field

import numpy as np

from ..utils.bases import pack_2bit, unpack_2bit
from .ingest import IngestError, IngestIssue

_HDR_FMT = "<4i4fi4xq5i4x8si4x8s8s8s"  # 112 bytes, pointers as opaque 8-byte pads
_HDR_SIZE = struct.calcsize(_HDR_FMT)
assert _HDR_SIZE == 112, _HDR_SIZE

_READ_FMT = "<3i4x2qi4x"  # 40 bytes
_READ_SIZE = struct.calcsize(_READ_FMT)
assert _READ_SIZE == 40, _READ_SIZE

DB_BEST = 0x8  # DAZZ_READ flags (public DB.h values)
DB_CCS = 0x400


@dataclass
class DazzRead:
    origin: int
    rlen: int
    fpulse: int
    boff: int
    coff: int = -1
    flags: int = 0


@dataclass
class DazzDB:
    """In-memory handle over a Dazzler DB; bases stay packed until asked for."""

    path: str
    nreads: int
    totlen: int
    maxlen: int
    cutoff: int
    reads: list[DazzRead]
    bps: np.ndarray = field(repr=False)  # uint8 packed base store
    names: list[str] = field(default_factory=list, repr=False)
    # read ids whose .idx record failed validation under read_db(strict=False)
    # (quarantine policy): their rlen/boff are garbage, so their bases must
    # never be decoded and piles referencing them quarantine at ingest
    bad_reads: set = field(default_factory=set, repr=False)

    def read_bases(self, i: int) -> np.ndarray:
        """Decode read ``i`` to an int8 array of 0..3."""
        r = self.reads[i]
        if len(self.bps) == 0 and r.rlen > 0:
            raise ValueError("DB was opened with load_bases=False (no base store); "
                             "use read_db(path) or decode_reads_from_bps for bases")
        nbytes = (r.rlen + 3) // 4
        return unpack_2bit(self.bps[r.boff : r.boff + nbytes], r.rlen)

    def read_bases_batch(self, ids) -> list[np.ndarray]:
        """Decode many reads at once (native 2-bit batch decode when built —
        SURVEY.md §2.4; bit-identical Python fallback otherwise)."""
        ids = list(ids)
        if len(self.bps) == 0 and any(self.reads[i].rlen > 0 for i in ids):
            raise ValueError("DB was opened with load_bases=False (no base store); "
                             "use read_db(path) or decode_reads_from_bps for bases")
        try:
            from ..native import available
            from ..native.api import decode_reads_batch

            if available():
                boffs = np.asarray([self.reads[i].boff for i in ids], np.int64)
                rlens = np.asarray([self.reads[i].rlen for i in ids], np.int32)
                return decode_reads_batch(self.bps, boffs, rlens)
        except Exception:
            pass
        return [self.read_bases(i) for i in ids]

    def read_length(self, i: int) -> int:
        return self.reads[i].rlen

    def __len__(self) -> int:
        return self.nreads


def _db_stems(path: str) -> tuple[str, str]:
    """Return (dir, stem) for a ``foo.db`` path."""
    d, b = os.path.split(path)
    if b.endswith(".db"):
        b = b[:-3]
    return d, b


def _write_block_section(fh, bounds: list[int], block_bases: int, cutoff: int) -> None:
    """The .db stub's ``blocks =`` section (single source for writer parity
    with :func:`db_blocks`; fasta2DB layout)."""
    fh.write(f"blocks = {len(bounds) - 1:>9}\n")
    fh.write(f"size = {block_bases:>11} cutoff = {cutoff:>10} all = 1\n")
    for b in bounds:
        fh.write(f"{b:>11} {b:>11}\n")  # untrimmed == trimmed (all = 1)


def read_lengths(path: str) -> np.ndarray:
    """Per-read lengths from the .idx alone (no base-store load)."""
    d, stem = _db_stems(path)
    with open(os.path.join(d, f".{stem}.idx"), "rb") as fh:
        hdr = fh.read(_HDR_SIZE)
        ureads = struct.unpack_from("<i", hdr, 0)[0]
        raw = fh.read(_READ_SIZE * ureads)
    arr = np.frombuffer(raw, dtype=np.uint8).reshape(ureads, _READ_SIZE)
    return arr[:, 4:8].copy().view("<i4").reshape(-1)


def write_db(path: str, seqs: list[np.ndarray], names: list[str] | None = None, cutoff: int = 0) -> DazzDB:
    """Write reads (int8 arrays of 0..3) as a Dazzler DB triple (.db/.idx/.bps)."""
    d, stem = _db_stems(path)
    names = names or [f"read/{i}/0_{len(s)}" for i, s in enumerate(seqs)]

    reads: list[DazzRead] = []
    bps_chunks: list[bytes] = []
    boff = 0
    counts = np.zeros(4, dtype=np.int64)
    for i, s in enumerate(seqs):
        s = np.asarray(s, dtype=np.int8)
        packed = pack_2bit(s)
        reads.append(DazzRead(origin=i, rlen=len(s), fpulse=0, boff=boff))
        bps_chunks.append(packed)
        boff += len(packed)
        binc = np.bincount(s.astype(np.int64), minlength=4)[:4]
        counts += binc

    totlen = int(sum(len(s) for s in seqs))
    maxlen = int(max((len(s) for s in seqs), default=0))
    freq = (counts / max(totlen, 1)).astype(np.float32)
    n = len(seqs)

    bps_path = os.path.join(d, f".{stem}.bps")
    idx_path = os.path.join(d, f".{stem}.idx")
    db_path = os.path.join(d, f"{stem}.db")

    with open(bps_path, "wb") as fh:
        for c in bps_chunks:
            fh.write(c)

    with open(idx_path, "wb") as fh:
        hdr = struct.pack(
            _HDR_FMT,
            n, n, cutoff, 1,              # ureads, treads, cutoff, allarr
            *freq.tolist(),
            maxlen,
            totlen,
            n, 1, -1, 0, 0,               # nreads, trimmed, part(-1=whole), ufirst, tfirst
            b"\0" * 8, 0, b"\0" * 8, b"\0" * 8, b"\0" * 8,
        )
        fh.write(hdr)
        for r in reads:
            fh.write(struct.pack(_READ_FMT, r.origin, r.rlen, r.fpulse, r.boff, r.coff, r.flags))

    with open(db_path, "wt") as fh:
        fh.write("files =         1\n")
        fh.write(f"{n:>11} {stem} {stem}\n")
        _write_block_section(fh, [0, n], 200_000_000, cutoff)

    name_path = os.path.join(d, f".{stem}.names")
    with open(name_path, "wt") as fh:
        for nm in names:
            fh.write(nm + "\n")

    return DazzDB(path=db_path, nreads=n, totlen=totlen, maxlen=maxlen,
                  cutoff=cutoff, reads=reads,
                  bps=np.frombuffer(b"".join(bps_chunks), dtype=np.uint8),
                  names=names)


def read_db(path: str, load_bases: bool = True, strict: bool = True) -> DazzDB:
    """Load a DB triple written by :func:`write_db` (or DAZZ_DB-compatible).

    ``load_bases=False`` skips the .bps base store (multi-GB on real DBs) for
    consumers that only need read lengths/metadata — e.g. the track tools'
    per-block jobs, which must stay O(block) in memory.

    Every .idx byte is validated before it steers a decode: a torn header or
    a read count the file cannot hold raises a structured
    :class:`~.ingest.IngestError`; a per-read record whose ``rlen``/``boff``
    would index outside the base store raises under ``strict`` (the default)
    or — ``strict=False``, the ingest layer's quarantine policy — lands the
    read id in ``DazzDB.bad_reads`` so piles referencing it can be contained
    without sinking the run."""
    d, stem = _db_stems(path)
    idx_path = os.path.join(d, f".{stem}.idx")
    bps_path = os.path.join(d, f".{stem}.bps")

    idx_size = os.path.getsize(idx_path)
    # a missing .bps still loads with load_bases=False (lengths-only
    # consumers); bounds checks against the base store then cannot apply
    bps_size = os.path.getsize(bps_path) if os.path.exists(bps_path) else None
    with open(idx_path, "rb") as fh:
        hdr = fh.read(_HDR_SIZE)
        if len(hdr) < _HDR_SIZE:
            raise IngestError(IngestIssue(
                "truncation", idx_path, len(hdr),
                f"idx holds {len(hdr)} of the {_HDR_SIZE}-byte DB header"))
        (ureads, _treads, cutoff, _allarr,
         _f0, _f1, _f2, _f3,
         maxlen, totlen,
         nreads, _trimmed, _part, _ufirst, _tfirst,
         _p0, _loaded, _p1, _p2, _p3) = struct.unpack(_HDR_FMT, hdr)
        if ureads < 0 or totlen < 0 or not (0 <= nreads <= ureads):
            raise IngestError(IngestIssue(
                "bad_header", idx_path, 0,
                f"ureads={ureads} nreads={nreads} totlen={totlen} fail sanity"))
        if idx_size < _HDR_SIZE + _READ_SIZE * ureads:
            raise IngestError(IngestIssue(
                "truncation", idx_path, idx_size,
                f"idx holds {(idx_size - _HDR_SIZE) // _READ_SIZE} of "
                f"{ureads} read records"))
        reads = []
        bad: set[int] = set()
        issues: list[IngestIssue] = []
        raw = fh.read(_READ_SIZE * ureads)
        for i in range(ureads):
            origin, rlen, fpulse, boff, coff, flags = struct.unpack_from(_READ_FMT, raw, i * _READ_SIZE)
            nbytes = (rlen + 3) // 4
            if rlen < 0 or boff < 0 or (bps_size is not None
                                        and boff + nbytes > bps_size):
                issues.append(IngestIssue(
                    "db_read", idx_path, _HDR_SIZE + i * _READ_SIZE,
                    f"read {i}: rlen={rlen} boff={boff} outside the "
                    f"{bps_size}-byte base store", aread=i, record=i))
                bad.add(i)
            reads.append(DazzRead(origin, rlen, fpulse, boff, coff, flags))
        if issues and strict:
            raise IngestError(issues)

    bps = np.fromfile(bps_path, dtype=np.uint8) if load_bases else np.zeros(0, np.uint8)

    names: list[str] = []
    name_path = os.path.join(d, f".{stem}.names")
    if os.path.exists(name_path):
        with open(name_path) as fh:
            names = [ln.rstrip("\n") for ln in fh]

    return DazzDB(path=os.path.join(d, f"{stem}.db"), nreads=nreads, totlen=totlen,
                  maxlen=maxlen, cutoff=cutoff, reads=reads, bps=bps, names=names,
                  bad_reads=bad)


def decode_reads_from_bps(db: DazzDB, ids) -> list[np.ndarray]:
    """Decode selected reads by seeking the .bps on disk — O(selected bytes)
    memory, for lengths-only DB handles (``read_db(load_bases=False)``).
    The DAZZ_DB ``DBshow`` access pattern."""
    d, stem = _db_stems(db.path)
    out: list[np.ndarray] = []
    with open(os.path.join(d, f".{stem}.bps"), "rb") as fh:
        for i in ids:
            r = db.reads[i]
            nbytes = (r.rlen + 3) // 4
            fh.seek(r.boff)
            buf = np.frombuffer(fh.read(nbytes), dtype=np.uint8)
            out.append(unpack_2bit(buf, r.rlen))
    return out


# ---------------------------------------------------------------------------
# Tracks (variable-length per-read byte payloads; e.g. daccord's `inqual`)
# ---------------------------------------------------------------------------

def _track_paths(db_path: str, track: str, block: int | None) -> tuple[str, str]:
    """(.anno, .data) paths; block tracks use the Dazzler ``.<stem>.<block>.
    <track>`` naming so per-block jobs never collide (Catrack convention)."""
    d, stem = _db_stems(db_path)
    mid = f"{block}.{track}" if block is not None else track
    return (os.path.join(d, f".{stem}.{mid}.anno"),
            os.path.join(d, f".{stem}.{mid}.data"))


def write_track(db_path: str, track: str, payloads: list[bytes | np.ndarray],
                block: int | None = None) -> None:
    """Write a variable-length Dazzler track (.anno = offsets, .data = bytes).

    With ``block``, writes a per-block track covering only that block's reads
    (merge into the whole-DB track with :func:`catrack`).

    Both files go through tmp-name + ``os.replace`` so a crash mid-WRITE (the
    long window) never leaves a truncated file; each file is individually
    atomic. A crash exactly between the two renames can still pair the new
    .data with the old .anno — a much narrower window than the old in-place
    writes, closable only with a directory-level commit this format doesn't
    have. .data goes first so the common mismatch direction is old-data +
    old-anno (fully consistent)."""
    anno_path, data_path = _track_paths(db_path, track, block)

    blobs = [bytes(np.asarray(p, dtype=np.uint8).tobytes()) if isinstance(p, np.ndarray) else bytes(p)
             for p in payloads]
    offsets = np.zeros(len(blobs) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])

    anno_tmp = f"{anno_path}.tmp.{os.getpid()}"
    data_tmp = f"{data_path}.tmp.{os.getpid()}"
    with open(anno_tmp, "wb") as fh:
        fh.write(struct.pack("<2i", len(blobs), 0))
        fh.write(offsets.tobytes())
    with open(data_tmp, "wb") as fh:
        for b in blobs:
            fh.write(b)
    # .data first: a reader must never see the new .anno without its .data
    os.replace(data_tmp, data_path)
    os.replace(anno_tmp, anno_path)


def read_track(db_path: str, track: str, block: int | None = None) -> list[np.ndarray]:
    """Read a variable-length track back as per-read uint8 arrays."""
    anno_path, data_path = _track_paths(db_path, track, block)

    with open(anno_path, "rb") as fh:
        nreads, size = struct.unpack("<2i", fh.read(8))
        if size != 0:
            raise ValueError(f"unsupported fixed-size track (size={size})")
        offsets = np.frombuffer(fh.read(8 * (nreads + 1)), dtype=np.int64)
    data = np.fromfile(data_path, dtype=np.uint8)
    return [data[offsets[i] : offsets[i + 1]] for i in range(nreads)]


# ---------------------------------------------------------------------------
# Block partition (DAZZ_DB DBsplit role)
# ---------------------------------------------------------------------------

def split_db(db_path: str, block_bases: int = 200_000_000) -> list[tuple[int, int]]:
    """Recompute the .db stub's block partition (DAZZ_DB ``DBsplit -s`` role).

    Blocks hold consecutive reads totalling at most ``block_bases`` bases
    (boundaries at read edges; a single read longer than the limit gets its
    own block). Returns the partition as [start_read, end_read) pairs and
    rewrites the ``blocks =`` section of the .db text stub in fasta2DB layout.
    """
    # partition needs only the read lengths — never load the base store
    # (real DBs are multi-GB; DBsplit must stay .idx-only)
    rlens = read_lengths(db_path)
    d, stem = _db_stems(db_path)
    with open(os.path.join(d, f".{stem}.idx"), "rb") as fh:
        cutoff = struct.unpack_from("<4i", fh.read(16), 0)[2]
    bounds = [0]
    acc = 0
    for i, rlen in enumerate(rlens):
        if acc > 0 and acc + int(rlen) > block_bases:
            bounds.append(i)
            acc = 0
        acc += int(rlen)
    bounds.append(len(rlens))

    stub = os.path.join(d, f"{stem}.db")
    with open(stub, "rt") as fh:
        lines = fh.readlines()
    # files section: "files = N" then N lines; blocks section replaces the rest
    nfiles = int(lines[0].split("=")[1])
    head = lines[: 1 + nfiles]
    nb = len(bounds) - 1
    tmp = f"{stub}.tmp.{os.getpid()}"
    with open(tmp, "wt") as fh:  # atomic: a crash never corrupts the stub
        fh.writelines(head)
        _write_block_section(fh, bounds, block_bases, cutoff)
    os.replace(tmp, stub)
    return [(bounds[i], bounds[i + 1]) for i in range(nb)]


def catrack(db_path: str, track: str, delete: bool = False) -> int:
    """Merge per-block tracks into the whole-DB track (DAZZ_DB ``Catrack``
    role). Every block 1..N of the .db stub's partition must have its
    ``.<stem>.<i>.<track>`` pair present, and block i's track must cover
    exactly block i's reads. Returns the merged read count.

    With ``delete``, the block-track files are removed after a successful
    merge (Catrack ``-d``)."""
    blocks = db_blocks(db_path)
    payloads: list[np.ndarray] = []
    for i, (lo, hi) in enumerate(blocks, start=1):
        p = read_track(db_path, track, block=i)
        if len(p) != hi - lo:
            raise ValueError(
                f"block {i} track '{track}' covers {len(p)} reads, expected {hi - lo}")
        payloads.extend(p)
    write_track(db_path, track, payloads)
    if delete:
        for i in range(1, len(blocks) + 1):
            for path in _track_paths(db_path, track, i):
                os.remove(path)
    return len(payloads)


def db_blocks(db_path: str) -> list[tuple[int, int]]:
    """Read the block partition from the .db stub as [start, end) read pairs."""
    d, stem = _db_stems(db_path)
    with open(os.path.join(d, f"{stem}.db"), "rt") as fh:
        lines = [ln.rstrip("\n") for ln in fh]
    nfiles = int(lines[0].split("=")[1])
    nb = int(lines[1 + nfiles].split("=")[1])
    bounds = []
    for ln in lines[3 + nfiles : 3 + nfiles + nb + 1]:
        bounds.append(int(ln.split()[0]))
    return [(bounds[i], bounds[i + 1]) for i in range(nb)]
