"""Residual-error anatomy: where do a corrected FASTA's errors live?

Diagnostic for the hp rescue ceiling (BASELINE.md r5): aligns each corrected
fragment to its truth infix (same protocol as qveval), walks the edit path,
and classifies every error by the truth-side homopolymer run length at its
position and by op type. If the hp-regime residual were still run-length
miscalls, the long-run buckets would dominate; if it is spread across
runlen 1-2 substitutions/indels, the damage is below the run-length-vote
mechanism (compressed-space solve quality / acceptance bias), which is the
r5 measured finding.

Run: ``python -m daccord_tpu.tools.hperrors corrected.fasta truth.npz``
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def classify(frag: np.ndarray, tr: np.ndarray, buckets: dict) -> None:
    from daccord_tpu.oracle.align import align_path

    n, m = len(frag), len(tr)
    if n == 0 or m == 0:
        return
    # locate the best infix start/end with the semi-global DP row, then get
    # an exact path against that truth slice (with a small safety margin)
    D = np.empty((2, m + 1), dtype=np.int32)
    D[0] = 0
    prev = D[0]
    cur = D[1]
    for i in range(1, n + 1):
        cur[0] = i
        sub = prev[:m] + (tr != frag[i - 1])
        dele = prev[1:] + 1
        best = np.minimum(sub, dele)
        vals = np.concatenate(([cur[0]], best))
        ar = np.arange(m + 1, dtype=np.int32)
        vals[1:] -= ar[1:]
        cur[1:] = (np.minimum.accumulate(vals) + ar)[1:]
        prev, cur = cur, prev
    end = int(np.argmin(prev))
    start = max(0, end - n - int(0.3 * n) - 8)
    sl = tr[start:end]
    _, a2b = align_path(frag, sl)
    # truth run lengths per truth position
    if len(sl) == 0:
        return
    st = np.concatenate(([0], np.flatnonzero(sl[1:] != sl[:-1]) + 1))
    rl = np.repeat(np.diff(np.concatenate((st, [len(sl)]))),
                   np.diff(np.concatenate((st, [len(sl)]))))

    def bucket(L: int) -> str:
        return "run1-2" if L <= 2 else ("run3-5" if L <= 5 else "run6+")

    steps = np.diff(a2b)
    for i in range(len(frag)):
        lo, hi = int(a2b[i]), int(a2b[i + 1])
        if steps[i] == 0:
            # fragment base consumes no truth: an inserted (spurious) base;
            # blame the run at the insertion point
            L = int(rl[min(lo, len(rl) - 1)])
            buckets[f"ins_{bucket(L)}"] = buckets.get(f"ins_{bucket(L)}", 0) + 1
        else:
            if frag[i] != sl[lo]:
                L = int(rl[lo])
                buckets[f"sub_{bucket(L)}"] = buckets.get(f"sub_{bucket(L)}", 0) + 1
            for j in range(lo + 1, hi):
                # extra truth bases consumed: deletions from the fragment
                L = int(rl[j])
                buckets[f"del_{bucket(L)}"] = buckets.get(f"del_{bucket(L)}", 0) + 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("fasta")
    ap.add_argument("truth")
    ap.add_argument("--max-frags", type=int, default=400,
                    help="fragments sampled (the anatomy stabilizes fast)")
    args = ap.parse_args(argv)

    from daccord_tpu.formats.fasta import read_fasta
    from daccord_tpu.utils.bases import revcomp_ints, seq_to_ints

    t = np.load(args.truth)
    genome, starts, ends, strands = (t["genome"], t["starts"], t["ends"],
                                     t["strands"])
    buckets: dict = {}
    n = 0
    for rec in read_fasta(args.fasta):
        name = rec.name.split()[0]
        try:
            rid = int(name.removeprefix("read").split("/")[0])
            tr = genome[starts[rid]:ends[rid]]
            if strands[rid] == 1:
                tr = revcomp_ints(tr)
        except (ValueError, IndexError):
            continue
        classify(seq_to_ints(rec.seq), tr, buckets)
        n += 1
        if n >= args.max_frags:
            break
    tot = sum(buckets.values())
    line = {"fragments": n, "errors": tot,
            **{k: buckets[k] for k in sorted(buckets)},
            "long_run_share": round(sum(v for k, v in buckets.items()
                                        if not k.endswith("run1-2"))
                                    / max(tot, 1), 3)}
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
