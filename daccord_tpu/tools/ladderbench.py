"""Measurement-ladder bench: end-to-end runs shaped like BASELINE.md's configs.

BASELINE.md defines a five-config measurement ladder (E. coli 25x parity ->
CHM13 WGS multi-host). The real datasets need DALIGNER + genome downloads that
this sealed environment cannot reach, so each rung is represented by a
synthetic dataset with the same *shape* (coverage, read length regime, error
profile), scaled to what the host can feed in minutes. Every run goes through
the production CLI path (``correct_to_fasta``) and is scored with the qv-eval
harness; one JSON line per rung.

Rungs:
  cfg1  25x PacBio-like, oracle-vs-kernel parity regime (small, CPU ok)
  cfg2  100x PacBio-like single chip (the "first bases/sec/chip" rung)
  cfg3  80x multi-contig over an 8-device mesh (virtual CPU mesh when only
        one real chip is visible; exercises the sharded solver end to end)
  cfg4  60x streamed as 4 sequential LAS byte-range shards with mid-shard
        checkpoints + manifest merge (the streaming-shards rung)
  cfg5  ONT R10-like regime corrected by two concurrent OS processes, each
        owning one LAS shard, outputs merged (the multi-host scale-out
        model: zero cross-process communication, shared FS)
  cfg6  8%-diverged two-copy repeat, TWO ARMS: plain daccord vs the full
        track pipeline (inqual -> repeats -> filter -> filtersym ->
        QV-ranked daccord); reports both arms' Q in one row

Usage: ``python -m daccord_tpu.tools.ladderbench [--configs cfg1,...,cfg6]``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
CACHE = os.path.join(REPO, ".bench_cache")


def _dataset(name: str, **kw) -> dict:
    """Build (or reuse) a cached synthetic dataset; returns its file paths.

    The cache is keyed on the sim parameters (config.json comparison), so
    editing a rung's sim_kw invalidates the old dataset instead of silently
    reusing it."""
    from dataclasses import asdict

    from daccord_tpu.sim import SimConfig, make_dataset

    cfg = SimConfig(**kw)
    d = os.path.join(CACHE, f"ladder_{name}")
    paths = {k: os.path.join(d, f"{name}.{ext}")
             for k, ext in (("db", "db"), ("las", "las"), ("truth", "truth.npz"))}
    cfg_json = os.path.join(d, f"{name}.config.json")
    if all(os.path.exists(p) for p in paths.values()) and os.path.exists(cfg_json):
        with open(cfg_json) as fh:
            if json.load(fh) == asdict(cfg):
                return paths
        import shutil

        shutil.rmtree(d)
    out = make_dataset(d, cfg, name=name)
    return {k: out[k] for k in ("db", "las", "truth")}


def _qveval(fasta: str, truth: str, raw_db: str | None) -> dict:
    from daccord_tpu.tools.cli import qveval_main

    with tempfile.NamedTemporaryFile("rt", suffix=".json", delete=False) as fh:
        path = fh.name
    try:
        args = [fasta, truth, "--json", path]
        if raw_db is not None:   # raw-read scoring is a full DP pass; skip
            args += ["--raw-db", raw_db]   # it when the caller discards it
        rc = qveval_main(args)
        assert rc == 0
        with open(path) as fh2:
            return json.load(fh2)
    finally:
        os.unlink(path)


def run_rung(name: str, sim_kw: dict, feeder_threads: int = 0,
             mesh: int = 0, native: bool = False) -> dict:
    """One ladder rung through the production pipeline; returns the JSON row."""
    import jax

    from daccord_tpu.runtime.pipeline import PipelineConfig, correct_to_fasta
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()
    paths = _dataset(name, **sim_kw)
    cfg = PipelineConfig(feeder_threads=feeder_threads,
                         native_solver=native and mesh <= 1,
                         # pin engine threads to the bench's thread setting so
                         # --threads 1 stays a per-core anchor (comparable to
                         # the recorded r3 baselines) even though the CLI
                         # defaults native_threads to all cores
                         native_threads=max(feeder_threads, 1))
    out_fa = os.path.join(CACHE, f"ladder_{name}", "corrected.fasta")

    # profile estimation runs OUTSIDE the timed window for every rung, so
    # bases_out_per_s measures the correction pipeline symmetrically
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import estimate_profile_for_shard

    prof = estimate_profile_for_shard(read_db(paths["db"]),
                                      LasFile(paths["las"]), cfg)
    solver = None
    if mesh > 1:
        from daccord_tpu.parallel.mesh import build_sharded_solver

        solver = build_sharded_solver(mesh, prof, cfg.consensus)
    t0 = time.perf_counter()
    stats = correct_to_fasta(paths["db"], paths["las"], out_fa, cfg,
                             profile=prof, solver=solver)
    wall = time.perf_counter() - t0

    q = _qveval(out_fa, paths["truth"], paths["db"])
    return {
        "rung": name, "devices": mesh if mesh > 1 else 1,
        "backend": jax.default_backend(),
        "device0": str(jax.devices()[0]).replace(" ", ""),
        "reads": stats.n_reads, "windows": stats.n_windows,
        "solve_rate": round(stats.n_solved / max(stats.n_windows, 1), 4),
        "bases_in": stats.bases_in, "bases_out": stats.bases_out,
        "wall_s": round(wall, 2), "device_s": round(stats.device_s, 3),
        "bases_out_per_s": round(stats.bases_out / wall, 1),
        "pad_waste": round(stats.pad_waste, 4),
        "q_raw": q.get("raw_qscore"), "q_corrected": q.get("qscore"),
        "delta_q": q.get("delta_q"),
    }


RUNGS = {
    # 25x PacBio-like: the oracle-parity regime (BASELINE ladder config 1)
    "cfg1": dict(sim_kw=dict(genome_len=20_000, coverage=25, read_len_mean=4_000,
                             seed=11)),
    # 100x PacBio-like: single-chip throughput rung (config 2)
    "cfg2": dict(sim_kw=dict(genome_len=50_000, coverage=100, read_len_mean=8_000,
                             seed=12)),
    # 80x over an 8-device mesh (config 3; virtual CPU mesh off-pod)
    "cfg3": dict(sim_kw=dict(genome_len=30_000, coverage=80, read_len_mean=6_000,
                             repeat_fraction=0.05, seed=13), mesh=8),
    # 60x streamed as 4 byte-range shards with checkpoints (config 4's shape)
    "cfg4": dict(sim_kw=dict(genome_len=40_000, coverage=60, read_len_mean=7_000,
                             seed=14), shards=4),
    # ONT R10-like, two concurrent shard processes (config 5's regime)
    "cfg5": dict(sim_kw=dict(genome_len=30_000, coverage=15, read_len_mean=8_000,
                             read_len_sigma=0.5, p_ins=0.008, p_del=0.018,
                             p_sub=0.01, min_overlap=2_000, seed=15), procs=2),
    # diverged two-copy repeat: the full track pipeline (inqual -> QV-gated
    # repeats -> consistency filter -> filtersym -> QV-ranked daccord) vs the
    # trackless run — the reference's preprocessing chain exercised end to
    # end with a measured Q delta (BASELINE.md "Track-pipeline measurement")
    "cfg6": dict(sim_kw=dict(genome_len=6_000, coverage=24, read_len_mean=800,
                             repeat_fraction=0.35, repeat_divergence=0.08,
                             seed=43), tracks=True),
}


def run_rung_tracks(name: str, sim_kw: dict) -> dict:
    """Two-arm rung: plain daccord vs the full track pipeline, one JSON row.

    Runs every stage through the production CLI in subprocesses (CPU backend:
    the arms must be backend-identical, and track tools are host-only)."""
    paths = _dataset(name, **sim_kw)
    d = os.path.dirname(paths["db"])

    def cli(*a):
        r = subprocess.run([sys.executable, "-m", "daccord_tpu.tools.cli", *a],
                           cwd=REPO, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(f"{a[0]} failed: {r.stderr[-300:]}")

    t0 = time.perf_counter()
    plain_fa = os.path.join(d, "plain.fasta")
    cli("daccord", paths["db"], paths["las"], "-o", plain_fa,
        "--backend", "cpu", "--qv-track", "")
    filt = os.path.join(d, "filt.las")
    sym = os.path.join(d, "sym.las")
    depth = str(int(sim_kw.get("coverage", 20)))
    cli("inqual", paths["db"], paths["las"], "-d", depth)
    cli("repeats", paths["db"], paths["las"], "-d", depth, "--factor", "1.5")
    cli("filter", paths["db"], paths["las"], filt)
    cli("filtersym", filt, sym, "--db", paths["db"])
    tracks_fa = os.path.join(d, "tracks.fasta")
    cli("daccord", paths["db"], sym, "-o", tracks_fa, "--backend", "cpu")
    wall = time.perf_counter() - t0

    qp = _qveval(plain_fa, paths["truth"], paths["db"])
    qt = _qveval(tracks_fa, paths["truth"], None)   # q_raw comes from qp
    return {
        "rung": name, "backend": "cpu", "wall_s": round(wall, 2),
        "q_raw": qp.get("raw_qscore"),
        "q_plain": qp.get("qscore"), "q_tracks": qt.get("qscore"),
        "errors_plain": qp.get("errors"), "errors_tracks": qt.get("errors"),
        "delta_q_tracks": round((qt.get("qscore") or 0)
                                - (qp.get("qscore") or 0), 2),
    }


def run_rung_shards(name: str, sim_kw: dict, shards: int) -> dict:
    """Sequential byte-range shards with mid-shard checkpoints + merge."""
    import jax

    from daccord_tpu.parallel.launch import merge_shards, run_shard
    from daccord_tpu.runtime.pipeline import PipelineConfig
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()
    paths = _dataset(name, **sim_kw)
    outdir = os.path.join(CACHE, f"ladder_{name}", "shards")
    out_fa = os.path.join(CACHE, f"ladder_{name}", "corrected.fasta")
    t0 = time.perf_counter()
    manifests = [run_shard(paths["db"], paths["las"], outdir, s, shards,
                           PipelineConfig(), force=True, checkpoint_every=64)
                 for s in range(shards)]
    n_frags = merge_shards(outdir, shards, out_fa)
    wall = time.perf_counter() - t0
    q = _qveval(out_fa, paths["truth"], paths["db"])
    bases_out = sum(m.get("bases_out", 0) for m in manifests)
    # no bases_out_per_s here: the timed window covers the whole shard
    # workflow (incl. one profile-estimation pass PER shard, by design of the
    # resumable shard machinery), so the number would not be comparable with
    # the other rungs' correction-only throughput
    return {
        "rung": name, "shards": shards, "devices": 1,
        "backend": jax.default_backend(),
        "reads": sum(m.get("reads", 0) for m in manifests),
        "fragments": n_frags, "bases_out": bases_out,
        "wall_s": round(wall, 2),
        "q_raw": q.get("raw_qscore"), "q_corrected": q.get("qscore"),
        "delta_q": q.get("delta_q"),
    }


def run_rung_procs(name: str, sim_kw: dict, procs: int) -> dict:
    """Concurrent shard OS processes (multi-host model: shared FS, zero
    cross-process communication), merged afterwards. The subprocesses run the
    CPU backend: two clients cannot share the single tunneled TPU chip."""
    paths = _dataset(name, **sim_kw)
    outdir = os.path.join(CACHE, f"ladder_{name}", "shards")
    out_fa = os.path.join(CACHE, f"ladder_{name}", "corrected.fasta")
    t0 = time.perf_counter()
    running = [subprocess.Popen(
        [sys.executable, "-m", "daccord_tpu.tools.cli", "shard",
         paths["db"], paths["las"], outdir, "-J", f"{s},{procs}",
         "--force", "--backend", "cpu"],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)
        for s in range(procs)]
    errs = [p.communicate()[1] for p in running]
    if any(p.returncode != 0 for p in running):
        return {"rung": name, "error": [p.returncode for p in running],
                "stderr": " | ".join(e[-200:] for e in errs)}
    from daccord_tpu.parallel.launch import merge_shards

    n_frags = merge_shards(outdir, procs, out_fa)
    wall = time.perf_counter() - t0
    q = _qveval(out_fa, paths["truth"], paths["db"])
    return {
        "rung": name, "processes": procs, "backend": "cpu",
        "fragments": n_frags, "wall_s": round(wall, 2),
        "q_raw": q.get("raw_qscore"), "q_corrected": q.get("qscore"),
        "delta_q": q.get("delta_q"),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--configs", default="cfg1,cfg2,cfg3")
    p.add_argument("--threads", type=int, default=0, help="feeder threads")
    p.add_argument("--native", action="store_true",
                   help="solve with the native C++ engine (--backend "
                        "native's degraded-mode path, device-ladder top-M "
                        "semantics at the default -M 64; single-device "
                        "rungs only — mesh/tracks rungs unchanged)")
    p.add_argument("--inner", default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.inner:  # subprocess re-entry: force a virtual CPU mesh pre-init
        r = RUNGS[args.inner]
        mesh = r.get("mesh", 0)
        if mesh > 1:
            # this image's TPU plugin overrides JAX_PLATFORMS from
            # sitecustomize, so env vars are NOT enough: the config update
            # must land before any backend init (same dance as
            # __graft_entry__.dryrun_multichip)
            import jax

            jax.config.update("jax_num_cpu_devices", mesh)
            jax.config.update("jax_platforms", "cpu")
        row = run_rung(args.inner, r["sim_kw"], feeder_threads=args.threads,
                       mesh=mesh)   # --inner is only used for mesh rungs
        print(json.dumps(row))
        return 0

    names = args.configs.split(",")
    unknown = [n for n in names if n not in RUNGS]
    if unknown:
        p.error(f"unknown configs {unknown}; valid: {', '.join(RUNGS)}")

    from daccord_tpu.utils.obs import device_alive

    fallback = False
    if not device_alive():
        # dead axon tunnel hangs default-backend init forever; run the ladder
        # on CPU with a machine-detectable marker (same policy as bench.py)
        import jax

        jax.config.update("jax_platforms", "cpu")
        fallback = True

    import jax

    for name in names:
        r = RUNGS[name]
        mesh = r.get("mesh", 0)
        if r.get("tracks"):
            try:
                row = run_rung_tracks(name, r["sim_kw"])
            except Exception as exc:   # a failed stage must not kill the
                row = {"rung": name, "error": str(exc)[-400:]}   # whole ladder
            print(json.dumps({**row, "fallback": fallback}))
            continue
        if "shards" in r:
            print(json.dumps({**run_rung_shards(name, r["sim_kw"], r["shards"]),
                              "fallback": fallback}))
            continue
        if "procs" in r:
            print(json.dumps({**run_rung_procs(name, r["sim_kw"], r["procs"]),
                              "fallback": fallback}))
            continue
        if mesh > 1 and len(jax.devices()) < mesh:
            # not enough real devices: re-enter in a fresh interpreter, where
            # the --inner path forces a virtual CPU platform of the right
            # size via jax.config.update BEFORE backend init (env vars are
            # overridden by this image's TPU plugin; device counts are
            # sticky once any backend has initialized)
            proc = subprocess.run([sys.executable, "-m",
                                   "daccord_tpu.tools.ladderbench",
                                   "--inner", name, "--threads", str(args.threads)],
                                  cwd=REPO, capture_output=True, text=True)
            out = (proc.stdout or "").strip().splitlines()
            if proc.returncode != 0 or not out:
                print(json.dumps({"rung": name, "error": proc.returncode,
                                  "stderr": proc.stderr[-400:]}))
                continue
            # re-emit with the degradation marker all other rungs carry
            try:
                print(json.dumps({**json.loads(out[-1]), "fallback": fallback}))
            except json.JSONDecodeError:
                print(out[-1])
        else:
            row = run_rung(name, r["sim_kw"], feeder_threads=args.threads,
                           mesh=mesh, native=args.native)
            print(json.dumps({**row, "fallback": fallback}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
