"""Model-mismatch stress bench: error processes the estimator does NOT model.

Every Q number in BASELINE.md before round 3 came from ``sim/synth.py``'s base
generative model — the same iid ins/del/sub family the error-profile estimator
and the OffsetLikely tables assume. In a sealed environment (no real sequencer
data, SURVEY.md §4 item 5), the strongest available robustness evidence is a
*mis-specified* simulator: generate with processes the model does not contain,
then measure how far consensus quality and solve rate degrade. (The
empirical-OL on/off arms this bench originally carried are gone with the
feature — retired in r4 after measuring <= the analytic tables at every
sample size; BASELINE.md r3/r4.)

Regimes (one row each; ``--hp`` adds an ``--hp-rescue`` arm):

  base     clean PacBio-like control (the estimator's own model)
  hp       homopolymer-length-dependent indels (ONT's signature failure)
  burst    Poisson error bursts (polymerase stalls / signal dropouts)
  disp     per-read lognormal rate dispersion (junk-read tail)
  chimera  foreign inserts bridged at a junction (library artifacts)
  dropout  coverage dropout region (depth starvation)
  all      every process at once (pacbio_mismatch preset)
  ont_hp   ONT shape + hp-dominated indels (ont_r10_mismatch preset)

Usage: ``python -m daccord_tpu.tools.mismatchbench [--regimes a,b] [--out F]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .ladderbench import CACHE, _dataset, _qveval

# kept small enough that 16 arms finish in tens of minutes on a 1-core host;
# shapes chosen so every regime has >= ~15x depth outside its own stressor
_SHAPE = dict(genome_len=15_000, coverage=22, read_len_mean=2_500, seed=71)
_ONT_SHAPE = dict(genome_len=15_000, coverage=18, read_len_mean=8_000,
                  read_len_sigma=0.5, p_ins=0.008, p_del=0.018, p_sub=0.01,
                  min_overlap=2_000, seed=72)

REGIMES: dict[str, dict] = {
    "base": dict(**_SHAPE),
    "hp": dict(**_SHAPE, hp_indel_slope=1.0),
    "burst": dict(**_SHAPE, burst_rate=2e-4, burst_len_mean=30.0,
                  burst_mult=6.0),
    "disp": dict(**_SHAPE, read_rate_sigma=0.6),
    "chimera": dict(**_SHAPE, p_chimera=0.05),
    "dropout": dict(**_SHAPE, dropout_frac=0.2, dropout_factor=5.0),
    "all": dict(**_SHAPE, hp_indel_slope=0.5, burst_rate=2e-4,
                read_rate_sigma=0.4, p_chimera=0.03, dropout_frac=0.15),
    "ont_hp": dict(**_ONT_SHAPE, hp_indel_slope=1.0, read_rate_sigma=0.5,
                   burst_rate=1e-4),
}


def run_regime(name: str, sim_kw: dict, hp_arm: bool = False) -> dict:
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import (PipelineConfig, correct_to_fasta,
                                              estimate_profile_for_shard)

    paths = _dataset(f"mm_{name}", **sim_kw)
    d = os.path.dirname(paths["db"])
    cfg = PipelineConfig()
    prof = estimate_profile_for_shard(read_db(paths["db"]),
                                      LasFile(paths["las"]), cfg)
    row: dict = {"regime": name, "p_ins": round(prof.p_ins, 4),
                 "p_del": round(prof.p_del, 4), "p_sub": round(prof.p_sub, 4)}
    t0 = time.perf_counter()
    arms = [("std", False)]
    if hp_arm:
        arms.append(("hp", True))   # homopolymer rescue arm (oracle/hp.py)
    for arm, use_hp in arms:
        from daccord_tpu.oracle.consensus import ConsensusConfig

        acfg = PipelineConfig(consensus=ConsensusConfig(hp_rescue=use_hp))
        out_fa = os.path.join(d, f"corr_{arm}.fasta")
        stats = correct_to_fasta(paths["db"], paths["las"], out_fa, acfg,
                                 profile=prof)
        q = _qveval(out_fa, paths["truth"], paths["db"] if arm == "std" else None)
        row[f"q_{arm}"] = q.get("qscore")
        row[f"errors_{arm}"] = q.get("errors")
        row[f"solve_{arm}"] = round(stats.n_solved / max(stats.n_windows, 1), 4)
        if use_hp:
            row["hp_rescued"] = stats.n_hp_rescued
        if arm == "std":
            row["q_raw"] = q.get("raw_qscore")
            row["windows"] = stats.n_windows
    row["wall_s"] = round(time.perf_counter() - t0, 1)
    if hp_arm:
        row["delta_q_hp"] = round((row["q_hp"] or 0) - (row["q_std"] or 0), 2)
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--regimes", default=",".join(REGIMES))
    ap.add_argument("--hp", action="store_true",
                    help="add a third arm with --hp-rescue on")
    ap.add_argument("--out", default=None, help="also append rows to this jsonl")
    ap.add_argument("--backend", default="cpu", choices=("cpu", "auto"),
                    help="cpu (default: Q is backend-independent and the "
                         "tunnel may be dead) or auto")
    args = ap.parse_args(argv)
    if args.backend == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()
    os.makedirs(CACHE, exist_ok=True)
    for name in args.regimes.split(","):
        row = run_regime(name, REGIMES[name], hp_arm=args.hp)
        print(json.dumps(row), flush=True)
        if args.out:
            with open(args.out, "at") as fh:
                fh.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
