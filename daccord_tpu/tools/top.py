"""daccord-top: one-screen live health snapshot of a run, fleet, or server.

The telemetry spine (PR 6) records everything and the serve plane reports
p50/p99 after the fact, but nothing shows what is happening *now* — the gap
ISSUE 13 names. ``daccord-top`` tails the live events/metrics sidecars of
any telemetry-producing directory (a shard run, a fleet outdir, a
daccord-serve workdir) and renders a refreshing one-screen snapshot:

- **SHARDS** — per-source throughput (windows/sec, bases/sec), supervisor
  state, in-flight depth, rescue-pool density, RSS, and the last
  ``shard_done`` outcome;
- **MESH** — the per-device flight recorder (ISSUE 13): state (ok / lost /
  dropped), trust verdict + strike count (ISSUE 20 ratchet, from the latest
  ``trust.state``/``trust.load``), dispatch count + wall, rows, HBM peak,
  and the capacity rung per device index, from the latest ``mesh.device``
  rows;
- **SERVE** — job states, queue depth, shed level, SLO burn
  (rolling p99 vs target), and latency quantiles from the latest snapshot;
- **GOVERNOR** — active capacity ratchets (shape key → width);
- **FAULTS** — recent supervisor faults / failovers / mesh shrinks, plus
  the SDC defense plane's milestones (``sup_sdc`` audit divergence,
  ``audit.attrib`` culprit attribution, ``trust.state`` verdicts).

``--once`` renders a single snapshot and exits (tests, CI, cron health
checks); the default loop refreshes every ``--interval`` seconds. ``--json``
emits the raw snapshot dict for scripting. Reads are tail-bounded (the last
``--tail-kb`` of each events file), so a 100-GB fleet sidecar costs the same
as a toy run's.

Usage::

    daccord-top out/                 # fleet outdir: orchestrator + workers
    daccord-top srv/ --once          # serve workdir, one-shot
    daccord-top run.events.jsonl --json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time


def _tail_lines(path: str, tail_kb: int = 256) -> list[str]:
    """The last ``tail_kb`` KiB of ``path`` as complete lines (the first,
    possibly torn, line after a mid-file seek is dropped)."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as fh:
            if size > tail_kb * 1024:
                fh.seek(size - tail_kb * 1024)
                fh.readline()   # discard the torn line
            data = fh.read()
    except OSError:
        return []
    return data.decode(errors="replace").splitlines()


def _tail_records(path: str, tail_kb: int) -> list[dict]:
    out = []
    for ln in _tail_lines(path, tail_kb):
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def _load_json(path: str) -> dict | None:
    try:
        with open(path) as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def _expand_sources(paths: list[str]) -> tuple[list[str], list[str], list[str]]:
    """(event files, json sidecars, lease files) the snapshot reads: a
    directory contributes its ``*.events.jsonl``, the durable
    metrics/fleet/serve JSON sidecars, and any ``leases/*.lease`` beneath it
    (a fleet outdir or a serve peer dir — the per-process ownership state,
    ISSUE 15)."""
    events: list[str] = []
    sidecars: list[str] = []
    leases: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            events.extend(sorted(glob.glob(os.path.join(p, "*.events.jsonl"))))
            sidecars.extend(sorted(glob.glob(os.path.join(p, "*.metrics.json"))))
            for name in ("fleet.json", "serve.metrics.json"):
                fp = os.path.join(p, name)
                if os.path.exists(fp) and fp not in sidecars:
                    sidecars.append(fp)
            leases.extend(sorted(glob.glob(os.path.join(p, "leases",
                                                        "*.lease"))))
            # announce leases (ISSUE 16): a peer dir's peers/*.lease rows
            # are the router's discovery inputs — same LEASE panel
            leases.extend(sorted(glob.glob(os.path.join(p, "peers",
                                                        "*.lease"))))
        elif p.endswith(".lease"):
            leases.append(p)
        elif p.endswith(".json"):
            sidecars.append(p)
        else:
            events.append(p)
    return events, sidecars, leases


def collect(paths: list[str], tail_kb: int = 256) -> dict:
    """Build the snapshot dict ``render`` draws: one ``sources`` row per
    events file (latest metrics/state/outcome), the merged mesh device
    table, the latest serve health, active governor ratchets, and recent
    fault milestones."""
    events, sidecars, lease_files = _expand_sources(paths)
    snap: dict = {"ts": time.time(), "sources": [], "mesh": {},
                  "serve": None, "ratchets": {}, "faults": [],
                  "slo": None, "fleet": None, "leases": [], "router": None}
    # per-process lease/ownership state (ISSUE 15): who holds which
    # shard/job right now, and how stale each heartbeat is — the takeover
    # question ("is anyone going to pick this up?") answered at a glance
    now = time.time()
    for lp in lease_files:
        info = _load_json(lp) or {}
        try:
            age = now - os.path.getmtime(lp)
        except OSError:
            continue
        unit = info.get("job") if info.get("job") is not None else \
            info.get("shard")
        snap["leases"].append(
            {"name": os.path.basename(lp).rsplit(".lease", 1)[0],
             "holder": str(info.get("host", "?")),
             "unit": "-" if unit is None else str(unit),
             "age_s": round(age, 1)})
    for path in events:
        recs = _tail_records(path, tail_kb)
        src = os.path.basename(path).replace(".events.jsonl", "")
        row: dict = {"src": src, "state": None, "metrics": None,
                     "done": None, "slo": None, "shed": None,
                     "inflight": None, "pool": None, "verdict": None}
        for rec in recs:
            ev = rec.get("event")
            if ev == "stage.profile":
                # saturation profiler (ISSUE 14): the live verdict — a
                # later shard_done (committed form) overwrites it below
                row["verdict"] = rec.get("verdict")
            elif ev == "metrics":
                row["metrics"] = rec
                mesh = rec.get("mesh")
                if isinstance(mesh, dict):
                    # carry event-sourced trust verdicts (ISSUE 20) across:
                    # the metrics snapshot knows utilization, not trust
                    old = (snap.get("mesh") or {}).get("devices") or {}
                    snap["mesh"] = mesh
                    devs = mesh.setdefault("devices", {})
                    for k, v in old.items():
                        if "trust" in v:
                            devs.setdefault(k, {}).update(
                                {kk: v[kk] for kk in ("trust", "strikes")
                                 if kk in v})
            elif ev == "mesh.device":
                d = rec.get("device")
                if isinstance(d, int):
                    devs = snap["mesh"].setdefault("devices", {})
                    trust = devs.get(str(d), {}).get("trust")
                    devs[str(d)] = {k: v for k, v in rec.items()
                                    if k not in ("t", "ts", "event", "device")}
                    if trust is not None:
                        devs[str(d)]["trust"] = trust
            elif ev in ("trust.state", "trust.load"):
                # device trust ratchet (ISSUE 20): latest verdict per member
                d = rec.get("device")
                if isinstance(d, int):
                    devs = snap["mesh"].setdefault("devices", {})
                    drow = devs.setdefault(str(d), {})
                    drow["trust"] = rec.get("state_to") or rec.get("state")
                    drow["strikes"] = rec.get("strikes")
                if ev == "trust.state":
                    # a verdict transition is also a fault-panel milestone
                    snap["faults"].append(
                        {"src": src, "event": ev,
                         **{k: v for k, v in rec.items()
                            if k in ("device", "state_from", "state_to",
                                     "strikes")}})
            elif ev == "sup_state":
                row["state"] = rec.get("state_to")
            elif ev == "sup_init":
                row["state"] = row["state"] or "HEALTHY"
                row["engine"] = rec.get("primary")
            elif ev == "shard_done":
                row["done"] = rec
                if rec.get("verdict"):
                    row["verdict"] = rec.get("verdict")
            elif ev == "batch":
                row["inflight"] = rec.get("inflight")
                row["pool"] = rec.get("pool")
            elif ev == "governor.ratchet":
                snap["ratchets"][rec.get("key")] = rec.get("width")
            elif ev == "governor.restore" and rec.get("ok"):
                snap["ratchets"].pop(rec.get("key"), None)
            elif ev == "serve.slo":
                snap["slo"] = rec
            elif ev == "serve.shed":
                row["shed"] = rec.get("level")
            elif isinstance(ev, str) and (ev.startswith("router.")
                                          or ev.startswith("scale.")):
                # front door (ISSUE 16): fold the router's event stream
                # into the ROUTER panel — peer table (up/down + ready),
                # tenant ownership map, spill/scale tallies
                r = snap["router"]
                if r is None:
                    r = snap["router"] = {"peers": {}, "owners": {},
                                          "routes": 0, "spills": 0,
                                          "proxy_errors": 0, "scale": []}
                if ev == "router.peer_up":
                    r["peers"][rec.get("peer")] = {
                        "up": True, "ready": rec.get("ready"),
                        "url": rec.get("url")}
                elif ev == "router.peer_down":
                    p_ = r["peers"].setdefault(rec.get("peer"), {})
                    p_["up"] = False
                    p_["ready"] = False
                    p_["reason"] = rec.get("reason")
                elif ev == "router.route":
                    r["routes"] += 1
                    r["owners"][rec.get("tenant")] = rec.get("peer")
                elif ev == "router.spill":
                    r["spills"] += 1
                elif ev == "router.proxy_error":
                    r["proxy_errors"] += 1
                elif ev == "router.breaker":
                    # network fault matrix (ISSUE 18): per-peer breaker
                    # state rides the peer table
                    p_ = r["peers"].setdefault(rec.get("peer"), {})
                    p_["breaker"] = rec.get("state")
                elif ev == "router.partition":
                    p_ = r["peers"].setdefault(rec.get("peer"), {})
                    p_["partitioned"] = rec.get("state") == "begin"
                elif ev in ("scale.spawn", "scale.drain", "scale.reap"):
                    r["scale"].append(
                        {"event": ev, "peer": rec.get("peer"),
                         **{k: v for k, v in rec.items()
                            if k in ("rc", "reason", "n_spawned")}})
                    r["scale"] = r["scale"][-6:]
            elif ev in ("sup_fault", "sup_failover", "sup_failback",
                        "mesh.shrink", "mesh.degrade", "mesh.restore",
                        "fleet.poison", "fleet.capacity",
                        "governor.classify",
                        # crash-durable serve tier (ISSUE 15): recovery
                        # milestones belong on the operator screen
                        "serve.replay", "serve.takeover",
                        # storage fault matrix (ISSUE 17): disk refusals
                        # and pressure transitions are operator events
                        "io.fault", "disk.pressure", "journal.compact",
                        # network fault matrix (ISSUE 18): socket refusals
                        # and partition transitions likewise
                        "net.fault", "router.partition",
                        # SDC defense plane (ISSUE 20): audit divergence
                        # and culprit attribution (trust.state milestones
                        # are appended by the ratchet branch above)
                        "sup_sdc", "audit.attrib", "audit.disabled"):
                snap["faults"].append(
                    {"src": src, "event": ev,
                     **{k: v for k, v in rec.items()
                        if k in ("kind", "reason", "key", "nd_from", "nd_to",
                                 "culprit", "shard", "op", "job",
                                 "prev_host", "stale_s", "orphans",
                                 "finished", "domain", "error", "level",
                                 "free_mb", "before", "after",
                                 "peer", "state", "device", "divergent",
                                 "sampled", "state_from", "state_to",
                                 "strikes")}})
                if ev == "disk.pressure":
                    snap["disk"] = {"level": rec.get("level"),
                                    "src": rec.get("src"),
                                    "free_mb": rec.get("free_mb")}
        snap["sources"].append(row)
    for path in sidecars:
        d = _load_json(path)
        if d is None:
            continue
        base = os.path.basename(path)
        if base == "serve.metrics.json":
            snap["serve"] = d
        elif base == "fleet.json":
            snap["fleet"] = d
        else:
            # shardNNNN.metrics.json: attach the durable rollup to its row
            src = base.replace(".metrics.json", "")
            for row in snap["sources"]:
                if row["src"] == src and row["metrics"] is None:
                    row["metrics"] = {"gauges": d.get("gauges", {}),
                                      "counters": d.get("counters", {}),
                                      "hists": d.get("hists", {})}
    snap["faults"] = snap["faults"][-8:]
    return snap


def _pct(v) -> str:
    """A 0..1 fraction as a percent cell ('-' when unreported)."""
    if not isinstance(v, (int, float)):
        return "-"
    return f"{100.0 * float(v):.0f}"


def _fmt(v, nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if abs(v) >= 1e6:
            return f"{v / 1e6:.1f}M"
        if abs(v) >= 1e4:
            return f"{v / 1e3:.1f}k"
        return f"{v:.{nd}f}"
    return str(v)


def render(snap: dict) -> str:
    """The one-screen text snapshot (plain fixed-width — it must read the
    same in a tmux pane, a CI log, and a golden test)."""
    out: list[str] = []
    t = time.strftime("%H:%M:%S", time.localtime(snap["ts"]))
    out.append(f"daccord-top  {t}  ({len(snap['sources'])} source(s))")
    if snap["sources"]:
        out.append("")
        # IDLE%/BLK%/OVR%/VERDICT = the saturation column (ISSUE 14): device
        # idle fraction, host-blocked-on-device fraction, host/device
        # overlap fraction (ISSUE 19 — a starving staged pipeline shows a
        # falling OVR% live), and the committed (or live) bottleneck verdict
        out.append(f"  {'SOURCE':<18}{'STATE':<10}{'WIN/S':>8}{'BASES/S':>10}"
                   f"{'RSS MB':>8}{'INFL':>6}{'POOL':>6}{'IDLE%':>7}"
                   f"{'BLK%':>6}{'OVR%':>6}  {'VERDICT':<12}OUTCOME")
        for row in snap["sources"]:
            g = (row["metrics"] or {}).get("gauges", {})
            done = row["done"]
            outcome = "-"
            if done is not None:
                outcome = (f"done {done.get('windows', '?')}w "
                           f"{_fmt(done.get('windows_per_sec'))}w/s"
                           + (" DEGRADED" if done.get("degraded") else ""))
            out.append(
                f"  {row['src']:<18}{(row['state'] or '-'):<10}"
                f"{_fmt(g.get('windows_per_sec')):>8}"
                f"{_fmt(g.get('bases_per_sec')):>10}"
                f"{_fmt(g.get('rss_mb')):>8}"
                f"{_fmt(row['inflight'], 0):>6}{_fmt(row['pool'], 0):>6}"
                f"{_pct(g.get('device_idle_frac')):>7}"
                f"{_pct(g.get('host_blocked_frac')):>6}"
                f"{_pct(g.get('overlap_frac')):>6}"
                f"  {(row.get('verdict') or '-'):<12}{outcome}")
    mesh = snap.get("mesh") or {}
    devs = mesh.get("devices") or {}
    if devs:
        out.append("")
        nd = mesh.get("nd")
        nd0 = mesh.get("nd0")
        hdr = f"  MESH {nd}/{nd0}" if nd is not None else "  MESH"
        rung = mesh.get("rung_rows_per_device")
        if rung is not None:
            hdr += f"  rung {rung} rows/device"
        out.append(hdr)
        out.append(f"  {'DEV':>5} {'PLAT':<6}{'STATE':<9}{'TRUST':<13}"
                   f"{'DISP':>7}"
                   f"{'WALL S':>9}{'ROWS':>9}{'HBM PEAK':>10}{'IDLE%':>7}"
                   f"{'OVR%':>6}")
        for k in sorted(devs, key=lambda x: int(x)):
            d = devs[k]
            trust = d.get("trust") or "-"
            if trust != "-" and d.get("strikes") is not None:
                trust = f"{trust}:{d['strikes']}"
            out.append(
                f"  {k:>5} {str(d.get('platform', '?')):<6}"
                f"{str(d.get('state', '?')):<9}"
                f"{trust:<13}"
                f"{_fmt(d.get('dispatches'), 0):>7}"
                f"{_fmt(d.get('dispatch_wall_s'), 2):>9}"
                f"{_fmt(d.get('rows'), 0):>9}"
                f"{_fmt(d.get('hbm_peak_bytes'), 0):>10}"
                f"{_pct(d.get('idle_frac')):>7}"
                f"{_pct(d.get('overlap_frac')):>6}")
    serve = snap.get("serve")
    slo = snap.get("slo")
    if serve is not None or slo is not None:
        out.append("")
        line = "  SERVE"
        if serve is not None:
            jobs = serve.get("jobs", {})
            line += ("  jobs " + " ".join(f"{k}:{v}"
                                          for k, v in sorted(jobs.items()))
                     if jobs else "")
            if "queue_depth" in serve:
                line += f"  queue {serve['queue_depth']}"
            if "shed_level" in serve:
                line += f"  shed {serve['shed_level']}"
            if serve.get("disk_free_mb") is not None:
                line += f"  disk {_fmt(serve['disk_free_mb'])}MB"
                if serve.get("disk_pressure"):
                    line += " PRESSURE"
            if serve.get("verdict"):
                line += f"  verdict {serve['verdict']}"
            if serve.get("peer"):
                line += (f"  peer {serve['peer']}"
                         f"  owns {len(serve.get('leases') or [])}")
        out.append(line)
        if slo is not None:
            out.append(f"    SLO burn {slo.get('burn')} "
                       f"(p99 {slo.get('p99_s', '-')}s vs target "
                       f"{slo.get('target_s')}s, n={slo.get('n')})")
        if serve is not None:
            h = ((serve.get("metrics") or {}).get("hists") or {}).get(
                "job_latency_s")
            if h:
                out.append(f"    latency p50 {_fmt(h.get('p50'), 3)}s "
                           f"p95 {_fmt(h.get('p95'), 3)}s "
                           f"p99 {_fmt(h.get('p99'), 3)}s "
                           f"({h.get('count')} jobs)")
    router = snap.get("router")
    if router is not None:
        # front door (ISSUE 16): peer table + tenant ownership + spill and
        # scale tallies from router.events.jsonl
        out.append("")
        out.append(f"  ROUTER  routes {router['routes']} "
                   f"spills {router['spills']} "
                   f"proxy-errs {router['proxy_errors']}")
        if router["peers"]:
            out.append(f"    {'PEER':<26}{'UP':<5}{'READY':<7}"
                       f"{'NET':<13}URL")
            for name in sorted(router["peers"]):
                d = router["peers"][name]
                ready = d.get("ready")
                # network column (ISSUE 18): partition verdict beats the
                # breaker state — a partitioned peer is the operator event
                net = "PARTITIONED" if d.get("partitioned") else \
                    (d.get("breaker") or "-")
                out.append(
                    f"    {str(name):<26}"
                    f"{('yes' if d.get('up') else 'NO'):<5}"
                    f"{('yes' if ready else ('-' if ready is None else 'NO')):<7}"
                    f"{net:<13}"
                    f"{d.get('url') or d.get('reason') or '-'}")
        if router["owners"]:
            owners = " ".join(f"{t}->{p_}" for t, p_ in
                              sorted(router["owners"].items()))
            out.append(f"    owners: {owners}"[:100])
        for s in router["scale"]:
            detail = " ".join(f"{k}={v}" for k, v in s.items()
                              if k not in ("event", "peer"))
            out.append(f"    {s['event']} {s.get('peer')} {detail}".rstrip())
    fleet = snap.get("fleet")
    if fleet is not None:
        out.append("")
        out.append(f"  FLEET  done {len(fleet.get('done', []))} "
                   f"poison {len(fleet.get('poison', []))} "
                   f"capacity-requeued {len(fleet.get('capacity_requeued', []))}")
    if snap.get("leases"):
        # per-process ownership (ISSUE 15): which process holds which
        # job/shard, and how stale each heartbeat is — a row past its TTL
        # is takeover bait
        out.append("")
        out.append(f"  {'LEASE':<28}{'HOLDER':<24}{'UNIT':<14}{'AGE S':>7}")
        for l in snap["leases"]:
            out.append(f"  {l['name']:<28}{l['holder']:<24}"
                       f"{l['unit']:<14}{_fmt(l['age_s']):>7}")
    if snap["ratchets"]:
        out.append("")
        out.append("  GOVERNOR ratchets:")
        for k, w in sorted(snap["ratchets"].items()):
            out.append(f"    {k} -> {w}")
    if snap["faults"]:
        out.append("")
        out.append("  RECENT FAULTS:")
        for f in snap["faults"]:
            detail = " ".join(f"{k}={v}" for k, v in f.items()
                              if k not in ("src", "event"))
            out.append(f"    [{f['src']}] {f['event']} {detail}"[:100])
    return "\n".join(out) + "\n"


def top_main(argv=None) -> int:
    """daccord-top: refreshing one-screen health snapshot from live
    events/metrics sidecars (run dir, fleet outdir, or serve workdir)."""
    p = argparse.ArgumentParser(prog="daccord-top",
                                description=top_main.__doc__)
    p.add_argument("paths", nargs="+",
                   help="run/fleet/serve directories or events files")
    p.add_argument("--once", action="store_true",
                   help="render one snapshot and exit (tests/CI)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw snapshot dict instead of the screen")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh cadence in seconds (loop mode)")
    p.add_argument("--tail-kb", type=int, default=256,
                   help="read only the last N KiB of each events file")
    args = p.parse_args(argv)
    while True:
        snap = collect(args.paths, tail_kb=args.tail_kb)
        if args.json:
            print(json.dumps(snap, default=str))
        else:
            if not args.once:
                # ANSI clear + home: the refresh contract of a top-alike
                sys.stdout.write("\x1b[2J\x1b[H")
            sys.stdout.write(render(snap))
            sys.stdout.flush()
        if args.once or args.json:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(top_main())
