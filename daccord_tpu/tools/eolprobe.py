"""Empirical-OL fate probe at large sample sizes (VERDICT r3 item 9).

Round 3 measured the empirical-OL blend slightly *negative* in 7/8 mismatch
regimes at the production 4-pile x 32-window sample and flipped the default
off; the open question was whether the sign flips once the offset sample is
large (sampling noise was the suspected mechanism). The native engine makes
a 256-pile estimation + solve cheap, so this probe runs:

    eol off | eol on @ 4 piles | eol on @ 48 | eol on @ 256

all solving with the production top-M semantics via the native engine
(``--backend native`` carries the device ladder's caps; cross-engine e2e
agreement is tested), on the profilevar dataset. Decision rule: if eol@256
beats eol-off by > 0.1 Q, the blend stays with a documented minimum sample;
if it is still <= eol-off, the r3 default-off verdict is confirmed at every
affordable sample size and the feature is retired per VERDICT r3 #9.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--piles", default="4,48,256")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    import jax

    jax.config.update("jax_platforms", "cpu")   # Q is backend-independent
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import (PipelineConfig, correct_to_fasta,
                                              estimate_profile_for_shard)
    from daccord_tpu.tools.ladderbench import _dataset, _qveval
    from daccord_tpu.tools.profilevar import _SHAPE

    paths = _dataset("profilevar", **_SHAPE)
    d = os.path.dirname(paths["db"])
    db = read_db(paths["db"])
    las = LasFile(paths["las"])

    def cell(label: str, use_eol: bool, n_piles: int) -> dict:
        cfg = PipelineConfig(profile_sample_piles=n_piles,
                             empirical_ol=use_eol, native_solver=True)
        t0 = time.perf_counter()
        if use_eol:
            prof, counts = estimate_profile_for_shard(db, las, cfg,
                                                      collect_offsets=True)
        else:
            prof, counts = estimate_profile_for_shard(db, las, cfg), None
        out_fa = os.path.join(d, f"eol_{label}.fasta")
        stats = correct_to_fasta(paths["db"], paths["las"], out_fa, cfg,
                                 profile=prof, offset_counts=counts)
        q = _qveval(out_fa, paths["truth"], None)
        row = {"arm": label, "piles": n_piles, "eol": use_eol,
               "q": q.get("qscore"), "errors": q.get("errors"),
               "solve": round(stats.n_solved / max(stats.n_windows, 1), 4),
               "wall_s": round(time.perf_counter() - t0, 1)}
        print(json.dumps(row), flush=True)
        if args.out:
            with open(args.out, "at") as fh:
                fh.write(json.dumps(row) + "\n")
        return row

    sizes = [int(x) for x in args.piles.split(",")]
    off = cell("off", False, max(sizes))
    best = None
    for sp in sizes:
        r = cell(f"on{sp}", True, sp)
        if best is None or (r["q"] or 0) > (best["q"] or 0):
            best = r
    dq = round((best["q"] or 0) - (off["q"] or 0), 3)
    verdict = ("keep: eol wins at large sample" if dq > 0.1
               else "retire: eol <= off at every affordable sample size")
    print(json.dumps({"best_eol_arm": best["arm"], "delta_q_vs_off": dq,
                      "verdict": verdict}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
