"""Host-feeder throughput bench: how fast can the host side cut windows?

The reference's 64-thread CPU becomes this framework's *feeder* (SURVEY.md
§7.3 item 5): LAS streaming + trace-point refinement + window cutting must
outrun the device or the chip starves. This tool measures the feeder alone —
no device work — in windows/sec and (input) bases/sec, for 1..N threads.

Usage: ``python -m daccord_tpu.tools.feederbench [--threads 1,4,8] [--genome 60000]``
Prints one JSON line per thread count.
"""

from __future__ import annotations

import argparse
import json
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--threads", default="1,4,8")
    p.add_argument("--genome", type=int, default=60_000)
    p.add_argument("--coverage", type=float, default=20.0)
    args = p.parse_args(argv)

    import os
    import tempfile

    from daccord_tpu.native import available as native_available
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import (
        PipelineConfig, _iter_pile_blocks, _iter_pile_blocks_threaded)
    from daccord_tpu.sim import SimConfig, make_dataset

    if not native_available():
        print(json.dumps({"error": "native host path unavailable"}))
        return 1

    with tempfile.TemporaryDirectory() as d:
        out = make_dataset(d, SimConfig(genome_len=args.genome,
                                        coverage=args.coverage, seed=7), name="fb")
        db = read_db(out["db"])
        las = LasFile(out["las"])
        for nt in (int(x) for x in args.threads.split(",")):
            cfg = PipelineConfig(feeder_threads=nt)
            t0 = time.perf_counter()
            n_win = n_bases = n_reads = 0
            it = (_iter_pile_blocks_threaded(db, las, cfg, None, None, nt)
                  if nt > 0 else _iter_pile_blocks(db, las, cfg, None, None, True))
            for aread, a, seqs, lens, nsegs in it:
                n_reads += 1
                n_win += len(nsegs)
                n_bases += len(a)
            dt = time.perf_counter() - t0
            print(json.dumps({
                "threads": nt, "reads": n_reads, "windows": n_win,
                "wall_s": round(dt, 3),
                "windows_per_s": round(n_win / dt, 1),
                "bases_per_s": round(n_bases / dt, 1)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
