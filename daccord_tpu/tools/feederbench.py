"""Host-feeder throughput bench: how fast can the host side cut windows?

The reference's 64-thread CPU becomes this framework's *feeder* (SURVEY.md
§7.3 item 5): LAS streaming + trace-point refinement + window cutting must
outrun the device or the chip starves. This tool measures the feeder alone —
no device work — in windows/sec and (input) bases/sec, for 1..N threads,
with the saturation profiler's per-stage breakdown (decode / rank / realign
/ kmer / tensorize) on every line (ISSUE 14).

Each run COMMITS a durable ``FEEDER_r*.json`` sidecar (same r-series wrapper
format as BENCH_*, with the ``last_real_tpu_ts`` staleness stamp), so the
feeder trajectory is sentinel-guarded history instead of stdout that
scrolls away — ``--sidecar-dir ''`` opts out (tests, throwaway runs).

Usage: ``python -m daccord_tpu.tools.feederbench [--threads 1,4,8] [--genome 60000]``
Prints one JSON line per thread count.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time


def commit_sidecar(lines: list[dict], argv_echo: str,
                   sidecar_dir: str) -> str:
    """Commit the run as the next ``FEEDER_rNN.json`` in ``sidecar_dir`` —
    the BENCH_* r-series wrapper format (``{"n", "cmd", "rc", "parsed"}``)
    so daccord-sentinel's trajectory checks and daccord-prof's readers
    consume it with zero special-casing. The headline metric is the best
    thread count's windows/sec; the per-line stage tables ride in
    ``lines``. Stamped with the tunnel staleness fields like bench.py, so
    a feeder number is datable against the last real chip sighting."""
    from daccord_tpu.tools.trace import last_alive_info
    from daccord_tpu.utils.aio import durable_write

    existing = glob.glob(os.path.join(sidecar_dir, "FEEDER_r*.json"))
    idx = 0
    for p in existing:
        stem = os.path.basename(p)[len("FEEDER_r"):-len(".json")]
        if stem.isdigit():
            idx = max(idx, int(stem))
    path = os.path.join(sidecar_dir, f"FEEDER_r{idx + 1:02d}.json")
    best = max(lines, key=lambda ln: ln.get("windows_per_s", 0.0))
    ts, age_h = last_alive_info(os.path.join(sidecar_dir,
                                             "TUNNEL_LOG.jsonl"))
    payload = {
        "n": idx + 1, "cmd": f"daccord-feederbench {argv_echo}".strip(),
        "rc": 0,
        "parsed": {"metric": "feeder_windows_per_sec",
                   "value": best.get("windows_per_s"), "unit": "windows/s",
                   "threads": best.get("threads"),
                   "stages": best.get("stages"),
                   "stage_threads": max(best.get("threads", 1), 1),
                   "fallback": False,
                   "verdict": "host_feeder",   # by construction: no device
                   "lines": lines,
                   "ts": round(time.time(), 1),
                   "last_real_tpu_ts": ts, "last_real_tpu_age_h": age_h}}
    durable_write(path, lambda fh: json.dump(payload, fh), mode="wt")
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--threads", default="1,4,8")
    p.add_argument("--genome", type=int, default=60_000)
    p.add_argument("--coverage", type=float, default=20.0)
    p.add_argument("--paged", action="store_true",
                   help="also measure paged packing (kernels/paging.py): "
                        "family derivation + pack_paged over the fed "
                        "windows in --batch-row batches; reports the pack "
                        "wall as a fraction of the feeder wall (the ISSUE 7 "
                        "acceptance bound is <= 5%%)")
    p.add_argument("--batch-rows", type=int, default=512,
                   help="rows per packed batch in --paged mode")
    p.add_argument("--sidecar-dir", default=".",
                   help="directory for the durable FEEDER_r*.json sidecar "
                        "(empty string = stdout only, no commit)")
    args = p.parse_args(argv)

    import tempfile

    from daccord_tpu.native import available as native_available
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import (
        PipelineConfig, _iter_pile_blocks, _iter_pile_blocks_threaded)
    from daccord_tpu.sim import SimConfig, make_dataset
    from daccord_tpu.utils.obs import StageProfile

    if not native_available():
        print(json.dumps({"error": "native host path unavailable"}))
        return 1

    lines: list[dict] = []
    with tempfile.TemporaryDirectory() as d:
        out = make_dataset(d, SimConfig(genome_len=args.genome,
                                        coverage=args.coverage, seed=7), name="fb")
        db = read_db(out["db"])
        las = LasFile(out["las"])
        for nt in (int(x) for x in args.threads.split(",")):
            cfg = PipelineConfig(feeder_threads=nt)
            prof = StageProfile(threads=max(nt, 1))
            t0 = time.perf_counter()
            n_win = n_bases = n_reads = 0
            blocks = []
            it = (_iter_pile_blocks_threaded(db, las, cfg, None, None, nt,
                                             prof=prof)
                  if nt > 0 else _iter_pile_blocks(db, las, cfg, None, None,
                                                   True, prof=prof))
            for aread, a, seqs, lens, nsegs in it:
                n_reads += 1
                n_win += len(nsegs)
                n_bases += len(a)
                if args.paged and len(nsegs):
                    blocks.append((seqs, lens, nsegs))
            dt = time.perf_counter() - t0
            summ = prof.summary()
            line = {
                "threads": nt, "reads": n_reads, "windows": n_win,
                "wall_s": round(dt, 3),
                "windows_per_s": round(n_win / dt, 1),
                "bases_per_s": round(n_bases / dt, 1),
                # per-stage feeder decomposition (ISSUE 14): the live
                # replacement for ARCHITECTURE.md's hand-measured table
                "stages": summ["stages"]}
            if args.paged and blocks:
                line.update(_measure_pack(blocks, cfg, dt,
                                          args.batch_rows))
            elif args.paged:
                # zero window blocks (empty/degenerate corpus): report the
                # feeder numbers rather than abort on an empty concatenate
                line["paged_windows"] = 0
            lines.append(line)
            print(json.dumps(line))
    if lines and args.sidecar_dir:
        import sys

        # echo the flags argparse actually consumed: console-script and
        # `python -m` invocations pass argv=None, and an empty cmd would
        # make r-series entries from different configs indistinguishable
        flags = argv if argv is not None else sys.argv[1:]
        path = commit_sidecar(lines, " ".join(flags), args.sidecar_dir)
        print(json.dumps({"sidecar": path}))
    return 0


def _measure_pack(blocks, cfg, feeder_wall_s: float, batch_rows: int) -> dict:
    """Host-side paged-packing overhead over already-fed window blocks.

    Two arms over the SAME windows: the paged router (family assign + row
    slice + budget cut + ``pack_paged``) and the dense router it replaces
    (depth-bucket assign + row slice + ``pad_batch``) — both are per-dispatch
    feeder-thread work, so the ISSUE 7 acceptance bound (<= 5% of feeder
    wall) is judged on their DELTA: what paging *adds* to the feeder, not
    the routing cost both wire formats pay."""
    import time as _time

    import numpy as np

    from ..kernels import paging
    from ..kernels.tensorize import BatchShape, WindowBatch, pad_batch

    seqs = np.concatenate([b[0] for b in blocks])
    lens = np.concatenate([b[1] for b in blocks])
    nsegs = np.concatenate([b[2] for b in blocks])

    def _wb(sub, depth):
        return WindowBatch(seqs=seqs[sub, :depth], lens=lens[sub, :depth],
                           nsegs=nsegs[sub],
                           shape=BatchShape(depth=depth,
                                            seg_len=cfg.seg_len),
                           read_ids=np.zeros(len(sub), np.int64),
                           wstarts=np.zeros(len(sub), np.int64))

    # ---- paged arm -----------------------------------------------------
    t0 = _time.perf_counter()
    pages = paging.window_pages(lens, cfg.page_len)
    # derive from a strided sample, like the pipeline (which samples a few
    # piles) — the full-corpus greedy would charge the pack wall for work
    # the real feeder never does
    samp = np.unique(np.linspace(0, len(nsegs) - 1,
                                 min(4096, len(nsegs))).astype(int))
    fams = paging.derive_families(
        nsegs[samp], pages[samp], max_depth=cfg.depth,
        max_pages=-(-cfg.depth * cfg.seg_len // cfg.page_len),
        budget=cfg.paged_families, page_len=cfg.page_len)
    assign = paging.assign_family(fams, nsegs, pages)
    n_packed = 0
    shipped = used = 0
    for fi, fam in enumerate(fams):
        idx = np.nonzero(assign == fi)[0]
        pgs_f = pages[idx]
        cap = batch_rows * fam.budget
        c0 = 0
        while c0 < len(idx):
            # same budget cut as the pipeline router: the largest prefix
            # whose pages fit one pool
            take = min(batch_rows, len(idx) - c0)
            fit = int(np.searchsorted(np.cumsum(pgs_f[c0 : c0 + take]),
                                      cap, side="right"))
            take = max(min(take, fit), 1)
            sub = idx[c0 : c0 + take]
            pb = paging.pack_paged(_wb(sub, fam.depth), fam,
                                   target_rows=batch_rows)
            n_packed += len(sub)
            shipped += pb.pool.size
            used += int(lens[sub].sum())
            c0 += take
    paged_s = _time.perf_counter() - t0

    # ---- dense arm (the default depth-bucket router + jit pad) ---------
    t0 = _time.perf_counter()
    d_buckets = sorted({b for b in cfg.depth_buckets
                        if 0 < b < cfg.depth} | {cfg.depth})
    d_assign = np.searchsorted(np.asarray(d_buckets), nsegs, side="left")
    dense_shipped = 0
    for di, dv in enumerate(d_buckets):
        idx = np.nonzero(d_assign == di)[0]
        for c0 in range(0, len(idx), batch_rows):
            sub = idx[c0 : c0 + batch_rows]
            db_ = pad_batch(_wb(sub, dv), batch_rows)
            dense_shipped += db_.seqs.size
    dense_s = _time.perf_counter() - t0

    return {"paged_windows": int(n_packed),
            "families": [f.describe() for f in fams],
            "pack_wall_s": round(paged_s, 3),
            "dense_route_wall_s": round(dense_s, 3),
            "pack_overhead_pct_of_feeder": round(
                100.0 * (paged_s - dense_s) / max(feeder_wall_s, 1e-9), 2),
            "paged_pad_waste": round(1.0 - used / max(shipped, 1), 4),
            "dense_pad_waste": round(1.0 - used / max(dense_shipped, 1), 4)}


if __name__ == "__main__":
    raise SystemExit(main())
