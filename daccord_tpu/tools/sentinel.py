"""daccord-sentinel: regression sentinel over committed telemetry artifacts.

The bench trajectory (BENCH_r01..., MULTICHIP_r...) and the smoke sidecars
had no tool that detects drift — BENCH_r05 silently records
``fallback: true`` and nothing would flag a 20% throughput regression
between rounds (ISSUE 13). The sentinel closes that gap with three checks:

- **Bench trajectory** (``*.json`` sidecars): within each (metric, batch)
  series — sorted by filename, so BENCH_r01 < BENCH_r02 — every
  ``fallback: true`` entry is flagged, and every honest value that drops
  more than the noise band below the median of its predecessors is flagged
  as a regression. MULTICHIP sidecars compare per-mesh-rung
  ``windows_per_sec`` and ``scaling_vs_single`` the same way. Wrapper
  files (``{"parsed": {...}}``, the committed r-series format) unwrap.
  Each sidecar's ``last_real_tpu_age_h`` provenance stamp is checked
  against ``--tpu-stale-h`` (default 168 h): a trajectory that has not
  seen a live chip in over a week flags instead of aging out silently.

- **Metrics rollups** (``*.metrics.json``): structural sanity (a rollup
  must carry counters/gauges), and with ``--baseline`` the throughput
  gauges (windows_per_sec, bases_per_sec) compare against the baseline
  rollup under the same noise band.

- **Events sidecars** (``*.events.jsonl`` / directories): outcome red
  flags a green CI would otherwise land silently — a supervisor failover
  (``sup_failover``), a degraded ``shard_done``, a ``bench_rung`` with
  ``fallback: true``, an SLO breach (``serve.slo`` burn >= 1).

- **Prom expositions** (``*.prom``, or any path via ``--prom``): the
  scrape-parse lint (``utils.obs.parse_prom``) — every sample line must
  parse, every TYPE must have samples.

Exit code: ``--strict`` exits 1 on any finding (the pounce pre-chip gate —
a fallback or regression then fails the run instead of landing silently);
without it findings print as warnings and the exit is 0 (advisory mode for
the committed history, which already contains known-degraded rounds).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

#: fraction below the historical reference that counts as regression (not
#: noise). 0.15 keeps a 20% drop (the ISSUE 13 acceptance case) flagged
#: while CPU-run jitter (measured well under 10% on the committed series)
#: passes.
DEFAULT_NOISE = 0.15

#: hours since the last real TPU life sign beyond which a committed
#: sidecar's own staleness stamp flags (ISSUE 20 satellite: the
#: ``last_real_tpu_age_h`` stamp has existed since PR 13 but nothing ever
#: read it — a week of chip-free "trajectory" landed self-reported yet
#: invisible). One week by default.
DEFAULT_TPU_STALE_H = 168.0


def load_bench(path: str) -> dict | None:
    """A bench sidecar's payload dict. The committed r-series wraps the
    bench line as ``{"parsed": {...}}`` (with the raw line in ``tail``) —
    unwrap it; bare bench lines load as-is."""
    try:
        with open(path) as fh:
            d = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(d, dict):
        return None
    if isinstance(d.get("parsed"), dict):
        d = d["parsed"]
    return d if "metric" in d or "fallback" in d else None


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    return s[len(s) // 2]


def check_bench_series(entries: list[tuple[str, dict]],
                       noise: float = DEFAULT_NOISE,
                       tpu_stale_h: float = DEFAULT_TPU_STALE_H
                       ) -> list[str]:
    """Drift/fallback findings over bench sidecars. ``entries`` is
    ``[(name, payload)]`` in trajectory order (the caller sorts by
    filename); series group by (metric, batch) so a B=64 rung never
    compares against a B=2048 one."""
    issues: list[str] = []
    series: dict[tuple, list[tuple[str, dict]]] = {}
    for name, d in entries:
        key = (d.get("metric"), d.get("batch"), d.get("mesh"))
        series.setdefault(key, []).append((name, d))
    for key, items in series.items():
        hist_vals: list[float] = []
        hist_rungs: dict[int, list[float]] = {}
        hist_dshare: dict[int, list[float]] = {}
        hist_scaling: list[float] = []
        for name, d in items:
            # trajectory staleness (ISSUE 20): the sidecar's own dated
            # provenance stamp says how long ago a real chip last answered;
            # past the threshold every device number in it is archaeology,
            # not telemetry — flag it instead of letting the series age out
            # silently
            age = d.get("last_real_tpu_age_h")
            if (isinstance(age, (int, float)) and not isinstance(age, bool)
                    and tpu_stale_h > 0 and age > tpu_stale_h):
                issues.append(
                    f"{name}: last real TPU life sign {age:g} h before this "
                    f"sidecar committed (> {tpu_stale_h:g} h) — the tunnel "
                    "has been dead for over the staleness budget; this is "
                    "a chip-free trajectory self-reporting as such")
            # storage red flags (ISSUE 17): a committed sidecar recording
            # disk pressure or dropped telemetry means the bench ran on a
            # sick volume — its numbers are not comparable. A CHAOS sidecar
            # (BENCH_DISK.json sets "chaos": true) injected the pressure on
            # purpose; its own assertions cover it, the sentinel skips it.
            if not d.get("chaos"):
                for fld in ("disk_pressure_events", "telemetry_dropped"):
                    n = d.get(fld)
                    if isinstance(n, (int, float)) \
                            and not isinstance(n, bool) and n > 0:
                        issues.append(
                            f"{name}: {fld} = {n:g} — the bench ran under "
                            "disk pressure / dropped telemetry (volume was "
                            "sick; numbers not comparable)")
            if d.get("fallback"):
                reason = d.get("fallback_reason") or d.get("device") or "?"
                issues.append(f"{name}: fallback: true ({reason}) — not a "
                              "real device measurement")
                continue
            v = d.get("value")
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                if hist_vals:
                    ref = _median(hist_vals)
                    if ref > 0 and v < (1.0 - noise) * ref:
                        issues.append(
                            f"{name}: {key[0]}: {v:g} is "
                            f"{100 * (1 - v / ref):.0f}% below the series "
                            f"median {ref:g} (noise band {noise:.0%})")
                hist_vals.append(float(v))
            for rung in d.get("rungs") or []:
                m = rung.get("mesh")
                wps = rung.get("windows_per_sec")
                if not isinstance(m, int) or not isinstance(wps, (int, float)):
                    continue
                prev = hist_rungs.setdefault(m, [])
                if prev:
                    ref = _median(prev)
                    if ref > 0 and wps < (1.0 - noise) * ref:
                        issues.append(
                            f"{name}: mesh-{m} rung: {wps:g} windows/s is "
                            f"{100 * (1 - wps / ref):.0f}% below the series "
                            f"median {ref:g}")
                prev.append(float(wps))
                # dispatch-share regression (ISSUE 19): the host-only
                # dispatch wall's share of the rung RISING beyond the noise
                # band means the staged pipeline is re-serializing against
                # the solve — the same inverse rule as idle-rise
                disp, wall = rung.get("dispatch_s"), rung.get("wall_s")
                if (isinstance(disp, (int, float)) and not isinstance(disp, bool)
                        and isinstance(wall, (int, float)) and wall
                        and not isinstance(wall, bool)):
                    share = float(disp) / float(wall)
                    dprev = hist_dshare.setdefault(m, [])
                    if dprev:
                        ref = _median(dprev)
                        if share > ref + noise:
                            issues.append(
                                f"{name}: mesh-{m} rung: dispatch share "
                                f"{share:.0%} of wall is {share - ref:.2f} "
                                f"above the series median {ref:.0%} (band "
                                f"{noise:.0%}) — host dispatch is newly "
                                "serializing against the solve")
                    dprev.append(share)
            sc = d.get("scaling_vs_single")
            if isinstance(sc, (int, float)) and not isinstance(sc, bool):
                if hist_scaling:
                    ref = _median(hist_scaling)
                    if ref > 0 and sc < (1.0 - noise) * ref:
                        issues.append(
                            f"{name}: mesh scaling {sc:g}x is "
                            f"{100 * (1 - sc / ref):.0f}% below the series "
                            f"median {ref:g}x")
                hist_scaling.append(float(sc))
    issues.extend(check_saturation_series(series, noise))
    return issues


def check_saturation_series(series: dict, noise: float) -> list[str]:
    """Saturation-profiler drift over the r-series (ISSUE 14):

    - ``device_idle_frac`` RISING beyond the noise band above its series
      median is a regression (the chip is newly starving — the inverse
      direction of the throughput rule);
    - a feeder sub-stage's SHARE of the stage table drifting more than the
      noise band in either direction flags (a stage quietly doubling its
      share is the regression the hand-measured anatomy table could never
      catch);
    - a ``host_feeder`` verdict on a mesh >= 4 sidecar is an advisory red
      flag regardless of history: one host visibly cannot feed that mesh,
      which is exactly the condition ROADMAP item 2 exists to fix.
    """
    issues: list[str] = []
    for _key, items in series.items():
        hist_idle: list[float] = []
        hist_share: dict[str, list[float]] = {}
        for name, d in items:
            sat = d.get("saturation") or {}
            idle = sat.get("device_idle_frac")
            if isinstance(idle, (int, float)) and not isinstance(idle, bool):
                if hist_idle:
                    ref = _median(hist_idle)
                    if idle > ref + noise:
                        issues.append(
                            f"{name}: device_idle_frac {idle:g} is "
                            f"{idle - ref:.2f} above the series median "
                            f"{ref:g} (noise band {noise:.2f}) — the "
                            "device is newly starving")
                hist_idle.append(float(idle))
            stages = d.get("stages")
            if isinstance(stages, dict) and stages:
                walls = {k: (v.get("wall_s") if isinstance(v, dict) else v)
                         for k, v in stages.items()}
                walls = {k: float(v) for k, v in walls.items()
                         if isinstance(v, (int, float))}
                tot = sum(walls.values())
                if tot > 0:
                    for st, w in walls.items():
                        share = w / tot
                        prev = hist_share.setdefault(st, [])
                        if prev:
                            ref = _median(prev)
                            if abs(share - ref) > noise:
                                issues.append(
                                    f"{name}: stage {st!r} share "
                                    f"{share:.0%} drifted from the series "
                                    f"median {ref:.0%} (band {noise:.0%})")
                        prev.append(share)
            mesh = d.get("mesh")
            if (d.get("verdict") == "host_feeder"
                    and isinstance(mesh, int) and mesh >= 4):
                issues.append(
                    f"{name}: host_feeder verdict on a mesh-{mesh} run — "
                    "one host cannot feed this mesh (advisory: ROADMAP "
                    "item 2, device-side ingest)")
    return issues


def _unwrap_rollup(d):
    """serve.metrics.json nests the registry under "metrics" (beside
    health/admission/warm state); shard rollups are flat."""
    if isinstance(d, dict) and isinstance(d.get("metrics"), dict) \
            and "gauges" in d["metrics"]:
        return d["metrics"]
    return d


def check_rollup(path: str, baseline: dict | None = None,
                 noise: float = DEFAULT_NOISE) -> list[str]:
    """Structural + (with a baseline) throughput-drift findings for one
    committed ``*.metrics.json`` rollup."""
    issues: list[str] = []
    try:
        with open(path) as fh:
            d = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable rollup ({e})"]
    d = _unwrap_rollup(d)
    if not isinstance(d, dict) or "counters" not in d or "gauges" not in d:
        return [f"{path}: not a metrics rollup (counters/gauges missing)"]
    # dropped telemetry (ISSUE 17): the counter only appears when nonzero
    # (obs.MetricsRegistry), so its presence at all means events were lost
    # to a sick volume — whatever this rollup claims is an undercount
    td = (d.get("counters") or {}).get("telemetry_dropped_total")
    if isinstance(td, (int, float)) and not isinstance(td, bool) and td > 0:
        issues.append(f"{path}: telemetry_dropped_total = {td:g} — events "
                      "were dropped (full/sick volume); every other number "
                      "here is an undercount")
    if baseline is not None:
        bl = _unwrap_rollup(baseline)
        bg = (bl.get("gauges") or {}) if isinstance(bl, dict) else {}
        for k in ("windows_per_sec", "bases_per_sec"):
            cur, ref = (d.get("gauges") or {}).get(k), bg.get(k)
            if (isinstance(cur, (int, float)) and isinstance(ref, (int, float))
                    and ref > 0 and cur < (1.0 - noise) * ref):
                issues.append(f"{path}: {k} {cur:g} is "
                              f"{100 * (1 - cur / ref):.0f}% below baseline "
                              f"{ref:g}")
        # saturation drift vs baseline (ISSUE 14): idle RISING is the
        # regression direction here — the device newly starving behind the
        # same workload
        cur = (d.get("gauges") or {}).get("device_idle_frac")
        ref = bg.get("device_idle_frac")
        if (isinstance(cur, (int, float)) and isinstance(ref, (int, float))
                and cur > ref + noise):
            issues.append(f"{path}: device_idle_frac {cur:g} is "
                          f"{cur - ref:.2f} above baseline {ref:g} — the "
                          "device is newly starving")
    return issues


#: events-file red flags: (event kind, predicate over the record, message)
def scan_events(path: str) -> list[str]:
    """Outcome red flags inside one events sidecar — things a green exit
    code would land silently: failovers, degraded completions, fallback
    bench rungs, SLO breaches."""
    issues: list[str] = []
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    # crash-durable serve tier (ISSUE 15): jobs a restart replayed must
    # reach a terminal journal record in the same events stream — a
    # replayed-without-commit orphan means recovery started work it never
    # finished (or the stream was cut again: either way, look). Repeated
    # takeovers of one job mean peers are trading a lease without anyone
    # finishing — a crash loop or a TTL set below real job latency.
    replayed_open: dict[str, int] = {}
    takeovers: dict[str, int] = {}
    # front door (ISSUE 16): a scale-out that never relieved the burn it
    # was spawned for is capacity that cost money and helped nobody — track
    # each scale.spawn's ambient burn and whether any later sample dropped
    # below it. And an aot.miss on a key this stream already PUBLISHED
    # means the fleet cache lost an entry it held (evicted, torn, or a
    # version skew) — the cold compile quietly came back.
    last_burn: float | None = None
    spawns_open: list[tuple[int, float, float]] = []  # (ln, burn@spawn, min since)
    aot_published: set[str] = set()
    # network fault matrix (ISSUE 18): a reap/drain landing inside a
    # peer's partition window killed live hardware (the lease was fresh —
    # the peer was cut off, not dead), and a breaker that opened but never
    # re-closed means a peer was written off for the rest of the run
    # (cooldown never probed back, or the peer genuinely never recovered —
    # either way, look).
    partitioned_now: set[str] = set()
    breaker_open_at: dict[str, int] = {}
    for ln, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue   # eventcheck's job, not the sentinel's
        if not isinstance(rec, dict):
            continue
        ev = rec.get("event")
        if ev == "sup_failover":
            issues.append(f"{path}:{ln}: supervisor failover "
                          f"({rec.get('reason', '?')[:80]})")
        elif ev == "shard_done" and rec.get("degraded"):
            issues.append(f"{path}:{ln}: shard completed DEGRADED "
                          f"({rec.get('fallback_reason') or 'fallback engine'})")
        elif (ev == "shard_done" and rec.get("verdict") == "host_feeder"
              and isinstance(rec.get("mesh"), int) and rec["mesh"] >= 4):
            # ISSUE 14: a mesh >= 4 run bottlenecked on the host feeder —
            # the starvation condition device-side ingest (ROADMAP 2) fixes
            issues.append(f"{path}:{ln}: host_feeder verdict on a "
                          f"mesh-{rec['mesh']} run (device starving behind "
                          "the host feeder)")
        elif ev == "bench_rung" and rec.get("fallback"):
            issues.append(f"{path}:{ln}: bench rung recorded "
                          "fallback: true")
        elif ev == "serve.slo":
            burn = rec.get("burn")
            if isinstance(burn, (int, float)) and burn >= 1.0:
                issues.append(f"{path}:{ln}: SLO BREACH (burn {burn:g}, "
                              f"p99 vs target {rec.get('target_s')}s)")
        elif ev == "serve.journal":
            jid, rk = str(rec.get("job")), rec.get("rec")
            if rk == "replayed":
                replayed_open[jid] = ln
            elif rk in ("committed", "aborted", "failed"):
                replayed_open.pop(jid, None)
        elif ev == "serve.takeover":
            jid = str(rec.get("job"))
            takeovers[jid] = takeovers.get(jid, 0) + 1
        elif ev == "scale.spawn":
            spawns_open.append((ln, last_burn if last_burn is not None
                                else float("inf"), float("inf")))
        elif ev == "router.partition":
            peer = str(rec.get("peer"))
            if rec.get("state") == "begin":
                partitioned_now.add(peer)
                # like disk pressure: a partition window is a red flag
                # even when it later heals — the network needs an operator
                # before the next one lands somewhere less survivable
                issues.append(
                    f"{path}:{ln}: ASYMMETRIC PARTITION of peer {peer!r} "
                    f"(healthz unreachable, announce lease fresh at "
                    f"{rec.get('lease_age_s', '?')}s) — routed around, "
                    "not reaped")
            else:
                partitioned_now.discard(peer)
        elif ev in ("scale.reap", "scale.drain"):
            peer = str(rec.get("peer"))
            if peer in partitioned_now:
                issues.append(
                    f"{path}:{ln}: {ev} of peer {peer!r} DURING its "
                    "partition window — the announce lease was fresh, the "
                    "peer was alive; the autoscaler killed cut-off "
                    "hardware")
        elif ev == "router.breaker":
            peer = str(rec.get("peer"))
            if rec.get("state") == "open":
                breaker_open_at.setdefault(peer, ln)
            elif rec.get("state") == "closed":
                breaker_open_at.pop(peer, None)
        if ev in ("serve.slo", "scale.burn"):
            burn = rec.get("burn")
            if isinstance(burn, (int, float)) and not isinstance(burn, bool):
                last_burn = float(burn)
                spawns_open = [(sl, b0, min(mn, last_burn))
                               for sl, b0, mn in spawns_open]
        elif ev == "aot.publish":
            aot_published.add(str(rec.get("key")))
        elif ev == "aot.hit":
            aot_published.add(str(rec.get("key")))
        elif ev == "aot.miss":
            key = str(rec.get("key"))
            if key in aot_published:
                issues.append(f"{path}:{ln}: AOT cache MISS on fingerprint "
                              f"{key!r} this stream already held (entry "
                              "lost/torn/version-skewed — the cold compile "
                              "is back)")
        elif ev == "aot.reject" and rec.get("reason") == "corrupt":
            issues.append(f"{path}:{ln}: corrupt AOT cache entry for "
                          f"{rec.get('key')!r} (torn publish or shared-FS "
                          "damage; cold fallback engaged)")
        elif ev == "disk.pressure" and rec.get("level") in ("enter",
                                                            "spawn_floor"):
            # ISSUE 17: a committed run that went into disk pressure is a
            # red flag even when it recovered — the volume needs an
            # operator before the next run hits the hard watermark
            issues.append(
                f"{path}:{ln}: DISK PRESSURE ({rec.get('src', '?')}: "
                f"{str(rec.get('detail', ''))[:80]}; free "
                f"{rec.get('free_mb', '?')} MiB)")
    for sl, b0, mn in spawns_open:
        if b0 != float("inf") and mn >= b0:
            issues.append(f"{path}:{sl}: scale-out spawned at burn {b0:g} "
                          "but burn never dropped below it afterwards — "
                          "added capacity did not relieve the p99 it was "
                          "bought for")
    for jid, ln in sorted(replayed_open.items()):
        issues.append(f"{path}:{ln}: job {jid} replayed but never reached "
                      "a terminal journal record (orphan re-admitted, "
                      "recovery incomplete)")
    for jid, n in sorted(takeovers.items()):
        if n >= 2:
            issues.append(f"{path}: job {jid} taken over {n} times (peers "
                          "trading the lease without finishing — crash "
                          "loop, or lease TTL below real job latency)")
    for peer, ln in sorted(breaker_open_at.items()):
        issues.append(f"{path}:{ln}: circuit breaker for peer {peer!r} "
                      "opened and never re-closed — the peer was written "
                      "off for the rest of the run (no half-open probe "
                      "succeeded)")
    for peer in sorted(partitioned_now):
        issues.append(f"{path}: peer {peer!r} still partitioned at stream "
                      "end (healthz never came back while the lease stayed "
                      "fresh — asymmetric partition unresolved)")
    return issues


def check_prom(path: str) -> list[str]:
    """Scrape-parse lint of a Prometheus text exposition file."""
    from ..utils.obs import parse_prom

    try:
        with open(path) as fh:
            text = fh.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    samples, errs = parse_prom(text)
    if not errs and not samples:
        errs = ["no samples in exposition"]
    return [f"{path}: {e}" for e in errs]


def _expand(paths: list[str]) -> tuple[list, list[str], list[str], list[str]]:
    """(bench entries, rollup files, event files, prom files)."""
    bench: list[tuple[str, dict]] = []
    rollups: list[str] = []
    events: list[str] = []
    proms: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            events.extend(sorted(glob.glob(os.path.join(p, "*.events.jsonl"))))
            rollups.extend(sorted(glob.glob(os.path.join(p, "*.metrics.json"))))
            proms.extend(sorted(glob.glob(os.path.join(p, "*.prom"))))
            for pat in ("BENCH_*.json", "MULTICHIP_*.json", "FEEDER_r*.json"):
                for bp in sorted(glob.glob(os.path.join(p, pat))):
                    d = load_bench(bp)
                    if d is not None:
                        bench.append((os.path.basename(bp), d))
            continue
        if p.endswith(".events.jsonl") or p.endswith(".jsonl"):
            events.append(p)
        elif p.endswith(".prom"):
            proms.append(p)
        elif p.endswith(".metrics.json"):
            rollups.append(p)
        elif p.endswith(".json"):
            d = load_bench(p)
            if d is not None:
                bench.append((os.path.basename(p), d))
        else:
            events.append(p)
    bench.sort(key=lambda x: x[0])
    return bench, rollups, events, proms


def sentinel_main(argv=None) -> int:
    """daccord-sentinel: flag silent regressions — fallback rungs,
    throughput drift beyond the noise band, degraded/failed-over runs,
    SLO breaches, and malformed prom expositions."""
    p = argparse.ArgumentParser(prog="daccord-sentinel",
                                description=sentinel_main.__doc__)
    p.add_argument("paths", nargs="+",
                   help="bench sidecars (*.json), metrics rollups "
                        "(*.metrics.json), events sidecars "
                        "(*.events.jsonl), prom expositions (*.prom), or "
                        "directories of any of them")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on any finding (the pounce pre-chip gate); "
                        "default is advisory (warn, exit 0)")
    p.add_argument("--noise", type=float, default=DEFAULT_NOISE,
                   help="regression noise band as a fraction "
                        f"(default {DEFAULT_NOISE}: drops beyond it flag)")
    p.add_argument("--tpu-stale-h", type=float, default=DEFAULT_TPU_STALE_H,
                   metavar="H",
                   help="flag sidecars whose last_real_tpu_age_h stamp "
                        f"exceeds H hours (default {DEFAULT_TPU_STALE_H:g}; "
                        "0 disables)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline *.metrics.json rollup the current "
                        "rollups compare against")
    p.add_argument("--prom", action="append", default=[], metavar="PATH",
                   help="treat PATH as a prom exposition regardless of "
                        "extension")
    args = p.parse_args(argv)

    bench, rollups, events, proms = _expand(args.paths)
    proms.extend(args.prom)
    baseline = None
    if args.baseline:
        try:
            with open(args.baseline) as fh:
                baseline = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"daccord-sentinel: --baseline unreadable: {e}",
                  file=sys.stderr)
            return 2

    findings: list[str] = []
    findings.extend(check_bench_series(bench, noise=args.noise,
                                       tpu_stale_h=args.tpu_stale_h))
    for path in rollups:
        findings.extend(check_rollup(path, baseline, noise=args.noise))
    for path in events:
        findings.extend(scan_events(path))
    for path in proms:
        findings.extend(check_prom(path))

    n_files = len(bench) + len(rollups) + len(events) + len(proms)
    for f in findings:
        print(f"daccord-sentinel: {'FLAG' if args.strict else 'warn'}: {f}",
              file=sys.stderr)
    print(f"daccord-sentinel: {n_files} artifact(s): "
          + ("OK" if not findings else f"{len(findings)} finding(s)"),
          file=sys.stderr)
    return 1 if (findings and args.strict) else 0


if __name__ == "__main__":
    raise SystemExit(sentinel_main())
