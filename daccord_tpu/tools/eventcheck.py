"""eventcheck: validate a jsonl events file against the event schema.

The supervisor (``runtime/supervisor.py``) and the pipeline emit structured
jsonl events so pounce/bench scripts get a machine-readable "compiling vs
wedged vs dead" signal. This lint keeps that contract honest: tests validate
the events their runs produce, and ``tools_pounce.sh`` validates every bench
sidecar before committing it. ``--strict`` additionally checks that the
supervisor's state transitions follow the legal machine
(HEALTHY -> SUSPECT -> COMPILING|RETRYING -> LOST -> DEGRADED -> FAILBACK)
and that relative timestamps are monotonic.

Usage: ``python -m daccord_tpu.tools.cli eventcheck [--strict] FILE...``
"""

from __future__ import annotations

import argparse
import json
import sys

_NUM = (int, float)

#: required fields (name -> allowed types) per event. Events not listed are
#: accepted as long as they carry the base fields — the schema constrains the
#: machine-consumed events, it does not forbid new informational ones.
BASE_FIELDS = {"t": _NUM, "ts": _NUM, "event": str}
EVENT_FIELDS: dict[str, dict] = {
    # telemetry spine (ISSUE 6): trace spans, metrics snapshots, the
    # per-window outcome ledger, and the per-run stream boundary
    "shard_start": {"start": int, "end": int, "pid": int},
    "span_open": {"span": str, "parent": str, "name": str},
    "span_close": {"span": str, "name": str, "wall_s": _NUM},
    "metrics": {"counters": dict, "gauges": dict, "hists": dict},
    "window": {"aread": int, "widx": int, "len": int, "depth": int,
               "tier": int, "k": int, "solved": bool, "stream": str,
               "rescued": bool, "wall_s": _NUM},
    "sup_init": {"primary": str, "op_deadline_s": _NUM,
                 "compile_deadline_s": _NUM},
    # (ts moved to BASE_FIELDS: the logger stamps every record)
    "sup_state": {"state_from": str, "state_to": str, "reason": str},
    "sup_compile": {"key": str, "expected_wall_s": _NUM},
    # the measured counterpart (ISSUE 13): cold dispatch wall ~= compile
    # wall (jit compiles synchronously at call time); also folded into the
    # compile-fingerprint registry for daccord-sentinel's drift bands
    "sup_compile_done": {"key": str, "wall_s": _NUM},
    # opt-in jax.profiler capture bracket (DACCORD_PROFILE_DIR)
    "profile.capture": {"dir": str, "dispatch": int, "state": str},
    "sup_heartbeat": {"op": str, "key": str, "waited_s": _NUM,
                      "deadline_s": _NUM},
    # cls = retry class (timeout | transient): budgets apply per class, and
    # deterministic classes (capacity) never appear here at all — they skip
    # straight to their remedy (governor ladder / failover)
    "sup_retry": {"op": str, "attempt": int, "cls": str, "delay_s": _NUM,
                  "reason": str},
    "sup_probe": {"alive": bool, "wall_s": _NUM},
    "sup_fault": {"kind": str, "op": str, "n": int},
    "sup_failover": {"reason": str, "fallback": str},
    "sup_failback": {},
    "sup_done": {"state": str, "degraded": bool},
    "batch": {"windows": int, "solved": int},
    # ragged paged window batching (kernels/paging.py, ISSUE 7): one
    # paging.family row per derived shape family at shard start, one
    # batch.paged row per paged dispatch (pages = live pages shipped,
    # pool_pages = the family's static pool budget, occupancy = their
    # ratio, table_cells = the page table's transfer cost in cell units)
    "paging.family": {"family": str, "bucket": int, "depth": int,
                      "pages": int, "page_len": int, "pool_pages": int},
    "batch.paged": {"windows": int, "bucket": int, "family": str,
                    "pages": int, "pool_pages": int, "table_cells": int,
                    "occupancy": _NUM},
    # mesh-native solve path (parallel/mesh.py): one mesh.init per built
    # sharded solver; mesh.shrink = the partial-mesh degradation rung
    # (N -> N/2 on declared device loss, run stays on the smaller primary;
    # culprit = attributed dead member index, -1 unknown); mesh.restore =
    # failback rebuilt the full mesh; mesh.degrade = no smaller mesh exists
    # (width 1) — whole-program failover follows. mesh.device (ISSUE 13) is
    # the per-chip flight-recorder row: one per member at snapshot cadence
    # (state ok + wall/rows/HBM gauges) and one the moment a shrink flips a
    # member to lost/dropped — the record that makes a partial-mesh
    # degradation attributable to a single device index.
    "mesh.init": {"nd": int, "devices": str, "esc_cap": int},
    "mesh.shrink": {"nd_from": int, "nd_to": int, "culprit": int,
                    "reason": str},
    "mesh.restore": {"nd_from": int, "nd_to": int},
    "mesh.degrade": {"nd": int, "reason": str},
    "mesh.device": {"device": int, "state": str},
    # silent-data-corruption defense plane (ISSUE 20): sup_sdc = a sampled
    # shadow audit caught a row whose device bytes diverge from the trusted
    # reference (culprit = attributed mesh member, -1 unknown/non-mesh);
    # audit.attrib = the per-member single-window re-dispatch that
    # attributed it; audit.disabled = the reference engine failed to build
    # (auditing off for the run, never fatal); trust.state / trust.load =
    # the per-device trust ratchet (TRUSTED -> SUSPECT -> QUARANTINED,
    # persisted in the trust registry beside the compile/capacity ones)
    "sup_sdc": {"key": str, "rows": int, "sampled": int, "divergent": int,
                "row": int, "culprit": int},
    "audit.attrib": {"row": int, "culprit": int, "nd": int},
    "audit.disabled": {"error": str},
    "trust.state": {"device": int, "state_from": str, "state_to": str,
                    "strikes": int},
    "trust.load": {"device": int, "state": str, "strikes": int},
    # two-stream tier ladder (ISSUE 4): one row per Stream B rescue dispatch
    # (rows = live rescue windows, slots = padded batch width, reason =
    # full | lag | final | pressure — the last is a host-watermark
    # force-flush, ISSUE 5)
    "ladder.flush": {"rows": int, "slots": int, "reason": str},
    # staged dispatch pipeline (ISSUE 19): dispatch.pipeline announces the
    # double buffer once per run; dispatch.stage is one row per staged batch
    # (host pad/pack + per-device shard-transfer sub-walls, measured on the
    # staging thread but EMITTED by the pipeline thread so the sidecar keeps
    # one monotonic writer); dispatch.launch is the jit-call row, whose
    # trace span pairs under the ordinary span_open/span_close rule.
    "dispatch.pipeline": {"depth": int, "solver": str},
    "dispatch.stage": {"rows": int, "pack_s": _NUM, "stage_s": _NUM},
    "dispatch.launch": {"rows": int, "launch_s": _NUM},
    # capacity governor (runtime/governor.py, ISSUE 5): memory faults walk a
    # byte-identical degradation ladder instead of the transient retry ladder
    "governor.classify": {"key": str, "width": int, "reason": str},
    "governor.shrink": {"key": str, "width_from": int, "width_to": int},
    "governor.clamp": {"key": str, "width": int, "esc_cap": int},
    "governor.ratchet": {"key": str, "width": int},
    "governor.restore": {"key": str, "width": int, "ok": bool},
    "governor.backpressure": {"level": str, "rss_mb": _NUM},
    "governor.monster": {"aread": int, "overlaps": int, "budget": int},
    # saturation profiler (ISSUE 14): stage.profile is the periodic
    # per-stage feeder snapshot (stages = StageProfile.summary()['stages'],
    # feeder_s = the pipeline-visible blocked-on-feeder wall, verdict = the
    # live bottleneck attribution); shard_done carries the committed final
    # form (stages wall table, verdict string, bottleneck gauge dict)
    "stage.profile": {"stages": dict, "feeder_s": _NUM, "verdict": str},
    "shard_done": {"reads": int, "windows": int, "solved": int,
                   "wall_s": _NUM, "degraded": bool,
                   "verdict": str, "bottleneck": dict, "stages": dict},
    # ingest integrity layer (formats/ingest.py, ISSUE 2)
    "ingest.scan": {"path": str, "records": int, "piles": int, "issues": int,
                    "policy": str},
    "ingest.issue": {"kind": str, "offset": int, "aread": int, "detail": str},
    "ingest.quarantine": {"kind": str, "offset": int, "aread": int},
    "ingest.commit": {"emitted": int, "fasta_bytes": int},
    "ingest.fault": {"kind": str, "path": str, "record": int},
    # shard fleet orchestrator (parallel/fleet.py, ISSUE 3)
    "fleet.init": {"nshards": int, "workers": int, "host": str},
    "fleet.spawn": {"shard": int, "attempt": int, "pid": int},
    "fleet.heartbeat": {"shard": int, "emitted": int},
    "fleet.takeover": {"shard": int, "prev_host": str, "stale_s": _NUM},
    "fleet.retry": {"shard": int, "attempt": int, "delay_s": _NUM,
                    "reason": str},
    "fleet.poison": {"shard": int, "attempts": int, "reason": str},
    "fleet.speculate": {"shard": int, "throughput": _NUM, "median": _NUM},
    "fleet.done": {"shard": int, "reads": int, "degraded": bool},
    # OOM-killed worker requeued once at a reduced batch (not poison credit)
    "fleet.capacity": {"shard": int, "batch": int},
    "fleet.fault": {"kind": str, "shard": int},
    "fleet.demote": {"shard": int, "new_host": str},
    "fleet.finish": {"done": int, "poison": int, "wall_s": _NUM},
    # serving plane (daccord_tpu/serve, ISSUE 10): service lifecycle,
    # admission decisions, cross-job merged batches, per-job commits. The
    # serve.batch row is the batcher's accounting unit: `jobs` counts the
    # distinct jobs cohabiting the merged batch (>= 2 = cross-job batching
    # happened), `windows` the live rows, `width` the padded dispatch width
    "serve.start": {"workdir": str, "backend": str, "batch": int,
                    "workers": int, "pid": int},
    "serve.job": {"job": str, "state": str, "tenant": str},
    "serve.admit": {"tenant": str, "job": str, "bytes": int, "queued": int},
    "serve.reject": {"tenant": str, "reason": str, "job": str, "bytes": int},
    "serve.batch": {"windows": int, "jobs": int, "stream": str, "width": int,
                    "reason": str, "job": str},
    "serve.commit": {"job": str, "fragments": int, "bytes": int},
    "serve.abort": {"job": str, "reason": str},
    "serve.shed": {"level": int, "rss_mb": _NUM},
    "serve.group": {"group": str, "key": str, "backend": str, "batch": int},
    "serve.evict": {"group": str, "key": str, "idle_s": _NUM},
    "serve.done": {"jobs": int, "done": int, "wall_s": _NUM},
    # SLO burn tracking (ISSUE 13): rolling p99-vs-target over the serve
    # latency window — burn = p99/target (>= the shed fraction drives the
    # batch-width shed ladder BEFORE breach; >= 1 is a breach), n = jobs in
    # the window. Emitted by the serve ticker when burn changes band.
    "serve.slo": {"target_s": _NUM, "burn": _NUM, "n": int},
    # crash-durable serve tier (ISSUE 15): serve.journal mirrors each
    # write-ahead journal append (rec = admitted | running | progress |
    # committing | committed | aborted | failed | interrupted | replayed |
    # demoted) into the events stream; serve.replay summarizes a restart's
    # journal fold (orphans re-admitted through the quota path, finished =
    # commits recovered without a re-run, torn = tolerated torn-tail
    # lines); serve.takeover is a peer claiming a dead process's stale
    # per-job lease and finishing its journaled job.
    "serve.journal": {"rec": str, "job": str},
    "serve.replay": {"jobs": int, "orphans": int, "finished": int,
                     "torn": int},
    "serve.takeover": {"job": str, "prev_host": str, "stale_s": _NUM},
    # front door (ISSUE 16). serve.announce = a peer publishing its URL as
    # an announce lease for router discovery; serve.evict_defer = the idle
    # sweep deferring a warm-group eviction because a live router's
    # stickiness still points a recently-routed tenant at it (the
    # evict-vs-route race fix).
    "serve.announce": {"url": str, "peer": str},
    "serve.evict_defer": {"group": str, "key": str, "routed_s": _NUM},
    # fleet-shared AOT executable cache (serve/aotcache.py): hit = a warm
    # load (memory or deserialize) skipping a jit compile, publish = a
    # fresh compile serialized for the fleet, reject = a cache entry
    # refused (reason = corrupt | version | deserialize | ...) with cold
    # fallback — a reject on a registry-held fingerprint is a sentinel
    # finding, never a correctness event.
    "aot.hit": {"key": str, "wall_s": _NUM},
    "aot.miss": {"key": str},
    "aot.publish": {"key": str, "bytes": int, "wall_s": _NUM},
    "aot.reject": {"key": str, "reason": str},
    # storage fault matrix (ISSUE 17). io.fault = one observed disk refusal
    # (domain = journal | lease | manifest | spool | sidecar | aot, real or
    # injected; error = errno text or grace-beat accounting). disk.pressure
    # = the governor's state transitions (level = enter | clear |
    # spawn_floor; src = journal | watermark | probe | fleet; free_mb = -1
    # when the volume was unreadable). journal.compact = one ONLINE journal
    # compaction (before/after bytes, kept = live + idempotency-keyed jobs,
    # torn = tolerated unparseable lines). aot.sweep = the shared AOT dir's
    # size-capped LRU eviction (freed/total in bytes).
    "io.fault": {"domain": str, "op": str, "error": str},
    "disk.pressure": {"level": str, "src": str, "free_mb": _NUM,
                      "detail": str},
    "journal.compact": {"before": int, "after": int, "kept": int,
                        "torn": int},
    "aot.sweep": {"removed": int, "freed": int, "total": int,
                  "cap_mb": _NUM},
    # stateless tenant router (serve/router.py): route = one admission
    # decision (spilled = stickiness overridden), spill = why + where,
    # peer_up/peer_down = discovery transitions (announce lease + healthz),
    # proxy_error = transport failure answered 502-retryable (the client's
    # idempotency key makes the retry exactly-once).
    "router.start": {"workdir": str, "peer_dir": str, "pid": int},
    "router.route": {"tenant": str, "peer": str, "spilled": bool},
    "router.spill": {"tenant": str, "owner": str, "to": str, "reason": str},
    "router.proxy_error": {"peer": str, "error": str},
    "router.peer_up": {"peer": str, "url": str, "ready": bool},
    "router.peer_down": {"peer": str, "reason": str},
    "router.done": {"wall_s": _NUM, "routes": int, "spills": int},
    # network fault matrix (ISSUE 18). net.fault = one injected socket
    # fault observed at the serve/netio.py choke point (kind = net_* per
    # the DACCORD_FAULT grammar, domain = healthz|submit|result|stream|
    # abort). net.hedge = a hedged read fired because the peer exceeded
    # its p99-derived latency budget. router.breaker = a per-peer circuit
    # breaker transition (state = open | half-open | closed).
    # router.partition = asymmetry reconciliation: healthz unreachable but
    # the announce lease is fresh (state = begin | end) — the peer spills
    # but is never reaped or takeover-claimed. router.client_gone = the
    # DOWNSTREAM client disconnected mid-proxied-stream (classified apart
    # from peer failures so a healthy peer is not blamed).
    "net.fault": {"kind": str, "domain": str, "peer": str},
    "net.hedge": {"peer": str, "domain": str, "budget_s": _NUM},
    "router.breaker": {"peer": str, "state": str},
    "router.partition": {"peer": str, "state": str, "lease_age_s": _NUM},
    "router.client_gone": {"peer": str, "path": str, "bytes": int},
    # SLO-burn autoscaler (serve/autoscale.py): burn = fleet band change
    # audit trail, spawn/drain/reap = the bounded scale-out/in lifecycle.
    "scale.burn": {"burn": _NUM, "band": int, "n_ready": int, "n_live": int},
    "scale.spawn": {"peer": str, "pid": int, "workdir": str,
                    "n_spawned": int},
    "scale.drain": {"peer": str, "reason": str},
    "scale.reap": {"peer": str, "rc": int, "life_s": _NUM},
    "bench_start": {"batch": int},
    "bench_compile": {"batch": int, "cached": bool, "expected_wall_s": _NUM},
    # self-staging bench ladder: one row per completed rung (sidecar
    # committed the moment the rung lands — see bench.py ladder mode).
    # pad_waste rides every rung so paged-vs-dense is attributable per rung
    "bench_rung": {"batch": int, "bases_per_sec": _NUM, "fallback": bool,
                   "pad_waste": _NUM},
    "bench_drain": {"fetched": int, "inflight": int},
    "bench_done": {"wall_s": _NUM},
}

_STATES = ("HEALTHY", "COMPILING", "SUSPECT", "RETRYING", "LOST",
           "DEGRADED", "FAILBACK")

# device trust ratchet (ISSUE 20): tightens within a run (self-loops are
# repeat strikes under a >2 threshold); QUARANTINED -> SUSPECT is the one
# loosening edge — the registry-load probation demotion
_TRUST_STATES = ("TRUSTED", "SUSPECT", "QUARANTINED")
_TRUST_TRANSITIONS = {
    "TRUSTED": {"SUSPECT", "QUARANTINED"},
    "SUSPECT": {"SUSPECT", "QUARANTINED"},
    "QUARANTINED": {"QUARANTINED", "SUSPECT"},
}


def validate_events(path: str, strict: bool = False) -> list[str]:
    """Errors found in the events file (empty list = valid)."""
    from ..runtime.supervisor import TRANSITIONS

    errs: list[str] = []
    state = None
    last_t = None
    open_spans: set[str] = set()
    in_shard_segment = False
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    for ln, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            errs.append(f"line {ln}: not JSON ({e})")
            continue
        if not isinstance(rec, dict):
            errs.append(f"line {ln}: not an object")
            continue
        fields = dict(BASE_FIELDS)
        fields.update(EVENT_FIELDS.get(rec.get("event", ""), {}))
        for name, types in fields.items():
            tt = types if isinstance(types, tuple) else (types,)
            if name not in rec:
                errs.append(f"line {ln}: {rec.get('event', '?')} missing "
                            f"field {name!r}")
                continue
            val = rec[name]
            # bool is an int subclass; only accept it where bool is declared
            ok = isinstance(val, tt) and (bool in tt
                                          or not isinstance(val, bool))
            if not ok:
                errs.append(f"line {ln}: {rec.get('event', '?')}.{name} has "
                            f"type {type(val).__name__}")
        if not strict:
            continue
        ev_name = rec.get("event")
        if ev_name == "shard_start" or (
                # serve.start joins the boundary set: a restarted
                # daccord-serve appends to the same serve.events.jsonl
                # with a fresh relative clock (same contract as a
                # requeued shard's sidecar)
                # router.start likewise: a restarted daccord-router
                # appends to the same router.events.jsonl
                ev_name in ("sup_init", "bench_start", "serve.start",
                            "router.start")
                and not in_shard_segment):
            # stream boundary: JsonlLogger appends with a per-process
            # relative clock, so a rerun against the same --events path (or
            # a resumed shard) legitimately restarts t and the state chain.
            # Spans reset too — a killed attempt's unclosed spans must not
            # poison the next attempt's pairing (daccord-trace --check is
            # the stricter per-segment lint). Inside a shard_start-opened
            # segment the mid-run sup_init is NOT a boundary (the telemetry
            # spine emits shard_start first; spans opened before the
            # supervisor exists must stay tracked) — bench and pre-spine
            # files, which have no shard_start, keep the old reset points.
            last_t = None
            state = None
            open_spans = set()
            in_shard_segment = ev_name == "shard_start"
        t = rec.get("t")
        if (isinstance(t, _NUM) and not isinstance(t, bool)
                # shard-level commit/fault rows are stamped by launch.py's
                # logger, whose relative clock starts earlier than the
                # pipeline logger appending to the same file — exempt them
                # from monotonicity rather than flag healthy runs
                and rec.get("event") not in ("ingest.commit", "ingest.fault")):
            if last_t is not None and t < last_t:
                errs.append(f"line {ln}: t went backwards "
                            f"({t} < {last_t})")
            last_t = t
        if rec.get("event") == "span_open":
            sid = rec.get("span")
            if isinstance(sid, str):
                if sid in open_spans:
                    errs.append(f"line {ln}: span {sid!r} opened twice")
                open_spans.add(sid)
        elif rec.get("event") == "span_close":
            sid = rec.get("span")
            if isinstance(sid, str):
                if sid not in open_spans:
                    errs.append(f"line {ln}: span_close {sid!r} without a "
                                "matching span_open")
                open_spans.discard(sid)
        if rec.get("event") == "sup_state":
            f, to = rec.get("state_from"), rec.get("state_to")
            if f not in _STATES or to not in _STATES:
                errs.append(f"line {ln}: unknown supervisor state "
                            f"{f!r} -> {to!r}")
            elif to not in TRANSITIONS.get(f, set()):
                errs.append(f"line {ln}: illegal transition {f} -> {to}")
            elif state is not None and f != state:
                errs.append(f"line {ln}: transition from {f} but supervisor "
                            f"was {state}")
            state = to
        if rec.get("event") == "trust.state":
            f, to = rec.get("state_from"), rec.get("state_to")
            if f not in _TRUST_STATES or to not in _TRUST_STATES:
                errs.append(f"line {ln}: unknown trust state {f!r} -> {to!r}")
            elif to not in _TRUST_TRANSITIONS.get(f, set()):
                errs.append(f"line {ln}: illegal trust transition {f} -> {to}")
    return errs


def eventcheck_main(argv=None) -> int:
    """eventcheck: lint a jsonl events file against the event schema."""
    p = argparse.ArgumentParser(prog="eventcheck",
                                description=eventcheck_main.__doc__)
    p.add_argument("files", nargs="+", help="events jsonl file(s)")
    p.add_argument("--strict", action="store_true",
                   help="also enforce supervisor transition legality and "
                        "monotonic timestamps")
    p.add_argument("--max-report", type=int, default=20)
    args = p.parse_args(argv)
    bad = 0
    for path in args.files:
        errs = validate_events(path, strict=args.strict)
        for e in errs[: args.max_report]:
            print(f"{path}: {e}", file=sys.stderr)
        if len(errs) > args.max_report:
            print(f"{path}: ... {len(errs) - args.max_report} more",
                  file=sys.stderr)
        n = sum(1 for ln in open(path) if ln.strip()) if not errs else 0
        print(f"{path}: {'OK (%d events)' % n if not errs else 'BAD (%d errors)' % len(errs)}",
              file=sys.stderr)
        bad += bool(errs)
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(eventcheck_main())
