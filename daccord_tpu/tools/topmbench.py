"""Top-M decision sweep: is the active-set cap harmless, and where?

The kernel truncates each window's surviving k-mer set to the top-M by count
(M = ``max_kmers``, default 64) where the reference builds the full filtered
DBG (SURVEY.md:65, §3.3). The cap binds on 60-70% of windows at production
depth, so this is a real semantic divergence — round 2 accepted it on one
25x sim. This sweep puts it on solid ground (VERDICT r2 item 5): M in
{48, 64, 96, 128} plus the ``--overflow-rescue`` arm (M=64 with capped
windows re-solved at 256 — reference semantics restored exactly where the
cap binds) across four regimes:

  pb25   25x PacBio-like (the original evidence regime)
  pb60   60x PacBio-like (cap binds on most windows)
  ont    ONT R10-like (long reads, low error)
  rep8   8%-diverged two-copy repeat (cross-copy k-mer pollution inflates
         the set exactly where truncation could hide real variants)

Decision rule: if Q(rescue) > Q(64) anywhere, overflow windows carry real
signal and the rescue (or a bigger M) becomes the default for that regime;
if Q stays flat-or-worse as M grows, truncation is a beneficial noise filter
and 64 stays, documented as a deliberate improvement over the reference.

Usage: ``python -m daccord_tpu.tools.topmbench [--regimes ...] [--cells ...]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .ladderbench import _dataset, _qveval

REGIMES: dict[str, dict] = {
    "pb25": dict(genome_len=12_000, coverage=25, read_len_mean=2_500, seed=91),
    "pb60": dict(genome_len=10_000, coverage=60, read_len_mean=2_500, seed=92),
    "ont": dict(genome_len=12_000, coverage=15, read_len_mean=6_000,
                read_len_sigma=0.5, p_ins=0.008, p_del=0.018, p_sub=0.01,
                min_overlap=2_000, seed=93),
    "rep8": dict(genome_len=6_000, coverage=24, read_len_mean=800,
                 repeat_fraction=0.35, repeat_divergence=0.08, seed=94),
}

# (label, max_kmers, overflow_rescue)
CELLS = [("M48", 48, False), ("M64", 64, False), ("M96", 96, False),
         ("M128", 128, False), ("M64+rescue", 64, True)]


def run_cell(paths: dict, label: str, max_kmers: int, rescue: bool,
             prof=None) -> dict:
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import (PipelineConfig, correct_to_fasta,
                                              estimate_profile_for_shard)

    cfg = PipelineConfig(max_kmers=max_kmers, overflow_rescue=rescue)
    if prof is None:
        # estimation is cap-independent; callers sweeping cells on one
        # dataset estimate once and pass it in
        prof = estimate_profile_for_shard(read_db(paths["db"]),
                                          LasFile(paths["las"]), cfg)
    out_fa = os.path.join(os.path.dirname(paths["db"]),
                          f"tm_{label.replace('+', '_')}.fasta")
    t0 = time.perf_counter()
    stats = correct_to_fasta(paths["db"], paths["las"], out_fa, cfg,
                             profile=prof)
    wall = time.perf_counter() - t0
    q = _qveval(out_fa, paths["truth"], None)
    return {"cell": label, "max_kmers": max_kmers, "rescue": rescue,
            "q": q.get("qscore"), "errors": q.get("errors"),
            "solve": round(stats.n_solved / max(stats.n_windows, 1), 4),
            "topm_overflow": stats.n_topm_overflow,
            "windows": stats.n_windows, "wall_s": round(wall, 1)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--regimes", default=",".join(REGIMES))
    ap.add_argument("--cells", default=",".join(c[0] for c in CELLS))
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    import jax

    jax.config.update("jax_platforms", "cpu")   # Q is backend-independent
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()
    want = set(args.cells.split(","))
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import (PipelineConfig,
                                              estimate_profile_for_shard)

    for name in args.regimes.split(","):
        paths = _dataset(f"tm_{name}", **REGIMES[name])
        prof = estimate_profile_for_shard(
            read_db(paths["db"]), LasFile(paths["las"]), PipelineConfig())
        for label, mk, rescue in CELLS:
            if label not in want:
                continue
            row = {"regime": name,
                   **run_cell(paths, label, mk, rescue, prof)}
            print(json.dumps(row), flush=True)
            if args.out:
                with open(args.out, "at") as fh:
                    fh.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
