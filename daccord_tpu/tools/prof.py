"""daccord-prof: saturation-profiler reader — stage flame table, checks, diffs.

The pipeline's always-on saturation profiler (ISSUE 14) stamps every run
with a per-stage host-feeder decomposition (``shard_done.stages`` + periodic
``stage.profile`` events), device starvation gauges (``device_idle_frac``,
``host_blocked_frac``, ``overlap_frac``), and a committed bottleneck verdict
(``host_feeder | device | io | balanced`` with the dominant feeder sub-stage
named). This tool is the one reader of all of it:

- **Flame table** (default): per-source stage walls with share-of-host bars,
  the starvation gauges, and the verdict — the "where does the wall-clock
  go" screen. The table renderer (:func:`stage_table`) is shared with
  ``daccord-trace``'s wall decomposition, so the two tools can never print
  different numbers for the same run.

- **Reconciliation** (``--check``, exit 1 on violation — the pounce gate):
  stage sums must agree with the run's own anchors within 5% / 50 ms —
  the feeder sub-stages against the pipeline-visible blocked-on-feeder wall
  (scaled by the feeder thread count: a pool's thread-summed walls
  legitimately exceed the overlapped wall), the full stage sum against
  ``host_s``, and ``host_s + device_s`` against ``wall_s``. Honest
  telemetry reconciles by construction; a drifted timer or a torn sidecar
  does not.

- **Diff** (``--diff A B``): stage-by-stage wall/share deltas between two
  runs — how the ROADMAP item-2 device-ingest PR proves its win against
  the committed baseline with the same tool that measured it.

Inputs: events jsonl files (``shard_done`` is authoritative; an aborted
run's last ``stage.profile`` snapshot is the fallback), committed
``*.metrics.json`` rollups (``stage_<name>_s`` gauges), bench/feeder
sidecars (``BENCH_*.json`` / ``FEEDER_r*.json``, wrapper or bare), or
directories of any of them.

Usage::

    daccord-prof out/                      # flame table per shard
    daccord-prof --check run.events.jsonl  # pounce reconciliation gate
    daccord-prof --diff base.events.jsonl fast.events.jsonl
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from .trace import _read_jsonl, _segments

#: stages that decompose the FEEDER span (the block-iterator __next__):
#: everything the StageProfile books except `pack`, which runs at dispatch
#: assembly in the pile loop, outside the feeder wall
FEEDER_SUBSTAGES = ("decode", "rank", "realign", "kmer", "tensorize",
                    "stall")

#: reconciliation tolerance: 5% of the anchor, floored at 50 ms (the ISSUE
#: acceptance bound) — near-zero anchors (a toy corpus's 20 ms feeder) must
#: not flag on timer granularity
TOL_FRAC = 0.05
TOL_ABS = 0.05

#: staged-dispatch sub-walls (ISSUE 19): host-only decomposition of the
#: dispatch wall — pad/pack assembly, per-device shard transfer, jit call.
#: Like `pack` they are NOT feeder sub-stages (staging runs on its own
#: thread, outside the feeder wall); they reconcile against dispatch_s.
DISPATCH_SUBWALLS = ("pack_s", "stage_s", "launch_s")


def _tol(anchor: float) -> float:
    return max(TOL_FRAC * max(anchor, 0.0), TOL_ABS)


def _dispatch_walls(payload: dict) -> dict | None:
    """The pack/stage/launch sub-wall dict carried by a shard_done record or
    a MULTICHIP bench rung payload, or None when the run predates (or never
    ran) the staged dispatch path."""
    dw = {k: float(payload[k]) for k in DISPATCH_SUBWALLS
          if isinstance(payload.get(k), (int, float))}
    return dw or None


def profile_from_events(records: list[dict], src: str = "") -> dict | None:
    """Normalized profile of one events file's LAST completed segment
    (``shard_done`` authoritative), falling back to the segment's last
    ``stage.profile`` snapshot for aborted runs. None when the file carries
    neither (fleet/bench sidecars)."""
    for seg in reversed(_segments(records)):
        done = next((r for r in reversed(seg)
                     if r.get("event") == "shard_done"), None)
        snap = next((r for r in reversed(seg)
                     if r.get("event") == "stage.profile"), None)
        if done is None and snap is None:
            continue
        if done is not None and isinstance(done.get("stages"), dict):
            bn = done.get("bottleneck") or {}
            return {"src": src, "partial": False,
                    "wall_s": done.get("wall_s"),
                    "device_s": done.get("device_s"),
                    "host_s": done.get("host_s"),
                    "feeder_s": done.get("feeder_s"),
                    "dispatch_s": done.get("dispatch_s"),
                    "dispatch_walls": _dispatch_walls(done),
                    "threads": int(done.get("stage_threads") or 1),
                    "stages": {k: float(v)
                               for k, v in done["stages"].items()},
                    "verdict": done.get("verdict"),
                    "stage": bn.get("stage"),
                    "gauges": {k: bn.get(k) for k in
                               ("device_idle_frac", "host_blocked_frac",
                                "overlap_frac") if k in bn}}
        if snap is not None:
            stages = {k: float(v.get("wall_s", 0.0))
                      for k, v in (snap.get("stages") or {}).items()}
            return {"src": src, "partial": True,
                    "wall_s": None, "device_s": None, "host_s": None,
                    "feeder_s": snap.get("feeder_s"),
                    "dispatch_s": snap.get("dispatch_s"),
                    "threads": int(snap.get("threads") or 1),
                    "stages": stages, "verdict": snap.get("verdict"),
                    "stage": snap.get("stage") or None,
                    "gauges": {k: snap.get(k) for k in
                               ("device_idle_frac", "host_blocked_frac",
                                "overlap_frac") if k in snap}}
    return None


def profile_from_rollup(path: str) -> dict | None:
    """Normalized profile from a committed ``*.metrics.json`` rollup (the
    ``stage_<name>_s`` gauges + saturation gauges + verdict)."""
    try:
        with open(path) as fh:
            d = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(d, dict):
        return None
    if isinstance(d.get("metrics"), dict):   # serve.metrics.json nesting
        inner = d["metrics"]
    else:
        inner = d
    gauges = inner.get("gauges") or {}
    stages = {k[len("stage_"):-2]: float(v) for k, v in gauges.items()
              if k.startswith("stage_") and k.endswith("_s")}
    threads = int(gauges.get("stage_threads") or 1)
    if not stages and "verdict" not in inner and "verdict" not in d:
        return None
    sat = {k: gauges.get(k) for k in ("device_idle_frac",
                                      "host_blocked_frac", "overlap_frac")
           if k in gauges}
    return {"src": os.path.basename(path), "partial": False,
            "wall_s": d.get("wall_s"), "device_s": d.get("device_s"),
            "host_s": d.get("host_s"), "feeder_s": gauges.get("feeder_s"),
            "dispatch_s": gauges.get("dispatch_s"),
            "threads": threads, "stages": stages,
            "verdict": inner.get("verdict") or d.get("verdict"),
            "stage": None, "gauges": sat}


def profile_from_bench(payload: dict, name: str) -> dict | None:
    """Normalized profile from a bench/feeder sidecar payload (already
    unwrapped from the ``{"parsed": {...}}`` r-series format)."""
    rungs = payload.get("rungs")
    if isinstance(rungs, list) and rungs and isinstance(rungs[-1], dict):
        # MULTICHIP sidecar: profile the final (mesh-N) rung — the subject
        # of the scaling claim; the mesh-1 rung is its control. Older
        # sidecars carry verdict/saturation only per rung (or not at all);
        # newer ones also commit them top-level, which the rung inherits.
        rung = dict(rungs[-1])
        if rung.get("verdict") is None and payload.get("verdict") is not None:
            rung["verdict"] = payload["verdict"]
        sub = profile_from_bench(rung, f"{name}:mesh{rung.get('mesh')}")
        if sub is not None:
            return sub
    stages = payload.get("stages")
    sat = payload.get("saturation") or {}
    if not isinstance(stages, dict) and not sat \
            and "verdict" not in payload \
            and not ("mesh" in payload
                     and isinstance(payload.get("dispatch_s"), (int, float))):
        return None
    if isinstance(stages, dict) and stages and \
            isinstance(next(iter(stages.values())), dict):
        stages = {k: float(v.get("wall_s", 0.0)) for k, v in stages.items()}
    return {"src": name, "partial": False,
            "wall_s": payload.get("wall_s"), "device_s": None,
            "host_s": None, "feeder_s": payload.get("feeder_s"),
            "dispatch_s": payload.get("dispatch_s"),
            "dispatch_walls": _dispatch_walls(payload),
            "threads": int(payload.get("stage_threads")
                           or payload.get("threads") or 1),
            "stages": stages if isinstance(stages, dict) else {},
            "verdict": payload.get("verdict"),
            "stage": (payload.get("bottleneck") or {}).get("stage"),
            "gauges": {k: sat.get(k) for k in
                       ("device_idle_frac", "host_blocked_frac",
                        "overlap_frac") if k in sat}}


def load_profiles(paths: list[str]) -> tuple[list[dict], list[str]]:
    """(profiles, warnings) for every recognized input. Directories
    contribute their ``*.events.jsonl`` + ``*.metrics.json`` + bench/feeder
    sidecars. A profile-less file is a warning only when it was named
    EXPLICITLY (under ``--check`` that warning is a violation — the gate
    exists to catch a run that silently stopped committing its profile);
    directory sweeps skip profile-less files quietly (a fleet orchestrator
    sidecar legitimately has no shard_done)."""
    from .sentinel import load_bench

    files: list[tuple[str, bool]] = []   # (path, explicit)
    for p in paths:
        if os.path.isdir(p):
            swept: list[str] = []
            swept.extend(sorted(glob.glob(os.path.join(p, "*.events.jsonl"))))
            swept.extend(sorted(glob.glob(os.path.join(p, "*.metrics.json"))))
            for pat in ("BENCH_*.json", "MULTICHIP_*.json",
                        "FEEDER_r*.json"):
                swept.extend(sorted(glob.glob(os.path.join(p, pat))))
            files.extend((f, False) for f in swept)
        else:
            files.append((p, True))
    profiles: list[dict] = []
    warns: list[str] = []
    for path, explicit in files:
        base = os.path.basename(path)
        d = None
        if path.endswith(".metrics.json"):
            d = profile_from_rollup(path)
        elif path.endswith(".json"):
            payload = load_bench(path)
            if payload is None:
                try:
                    with open(path) as fh:
                        payload = json.load(fh)
                except (OSError, json.JSONDecodeError):
                    payload = None
            if isinstance(payload, dict):
                if isinstance(payload.get("parsed"), dict):
                    payload = payload["parsed"]
                d = profile_from_bench(payload, base)
        else:
            recs = _read_jsonl(path)
            d = profile_from_events(recs,
                                    base.replace(".events.jsonl", ""))
        if d is None:
            if explicit:
                warns.append(f"{path}: no stage profile found")
        else:
            profiles.append(d)
    return profiles, warns


def stage_table(stages: dict, total_s: float | None = None,
                width: int = 28) -> list[str]:
    """THE stage flame-table renderer (one source of truth, shared with
    ``daccord-trace``): one line per stage, heaviest first, with wall,
    share of ``total_s`` (the host/feeder anchor), and a proportional
    bar."""
    if not stages:
        return ["  (no stage walls recorded)"]
    tot = total_s if total_s and total_s > 0 else sum(stages.values())
    tot = max(tot, 1e-9)
    lines = []
    for name in sorted(stages, key=lambda k: -stages[k]):
        w = float(stages[name])
        share = w / tot
        bar = "#" * max(int(share * width + 0.5), 1 if w > 0 else 0)
        lines.append(f"  {name:<10} {w:9.3f}s {100 * share:5.1f}%  {bar}")
    return lines


def render_profile(d: dict) -> str:
    """One source's full screen block: header anchors, gauges + verdict,
    and the stage flame table."""
    out = [f"{d['src']}:" + ("  [partial: no shard_done]"
                             if d.get("partial") else "")]
    anchors = []
    for key in ("wall_s", "host_s", "device_s", "feeder_s", "dispatch_s"):
        v = d.get(key)
        if isinstance(v, (int, float)):
            anchors.append(f"{key.replace('_s', '')} {v:.3f}s")
    if d.get("threads", 1) > 1:
        anchors.append(f"feeder x{d['threads']} threads")
    if anchors:
        out.append("  " + "  ".join(anchors))
    g = d.get("gauges") or {}
    if g:
        out.append("  device_idle {:.0%}  host_blocked {:.0%}  "
                   "overlap {:.0%}".format(
                       float(g.get("device_idle_frac") or 0.0),
                       float(g.get("host_blocked_frac") or 0.0),
                       float(g.get("overlap_frac") or 0.0)))
    dw = d.get("dispatch_walls")
    if dw:
        out.append("  dispatch: " + "  ".join(
            f"{k.replace('_s', '')} {dw[k]:.3f}s"
            for k in DISPATCH_SUBWALLS if k in dw))
    v = d.get("verdict")
    if v:
        dom = d.get("stage")
        out.append(f"  verdict: {v.upper()}"
                   + (f" (dominant stage: {dom})" if dom else ""))
    out.extend(stage_table(d.get("stages") or {},
                           d.get("host_s") or d.get("feeder_s")))
    return "\n".join(out)


def check_profile(d: dict) -> list[str]:
    """Reconciliation findings for one profile (the ``--check`` rules).

    The committed numbers must be internally consistent within 5% / 50 ms:

    - every stage wall finite and non-negative, and a verdict committed;
    - feeder sub-stage sum vs the blocked-on-feeder wall (``feeder_s``):
      equal within tolerance for a SERIAL feeder (the sub-stages are
      exactly what the pile loop blocked on). Under a feeder pool
      (``threads > 1``) the pool works in the background of the pile loop,
      so thread-summed walls carry no fixed relation to the blocked wall —
      only the host envelope below constrains them;
    - total stage sum (per-thread) must fit inside ``host_s``;
    - ``host_s + device_s`` must equal ``wall_s`` (anchor integrity).
    """
    errs: list[str] = []
    src = d["src"]
    stages = d.get("stages") or {}
    for name, w in stages.items():
        if not isinstance(w, (int, float)) or w != w or w < 0:
            errs.append(f"{src}: stage {name!r} wall is not a finite "
                        f"non-negative number: {w!r}")
    if not d.get("verdict"):
        errs.append(f"{src}: no bottleneck verdict committed")
    threads = max(int(d.get("threads") or 1), 1)
    feeder = d.get("feeder_s")
    sub = sum(float(stages.get(s, 0.0)) for s in FEEDER_SUBSTAGES)
    if isinstance(feeder, (int, float)) and threads <= 1:
        if abs(sub - float(feeder)) > _tol(float(feeder)):
            errs.append(
                f"{src}: feeder sub-stage sum {sub:.3f}s does not "
                f"reconcile with the blocked-on-feeder wall "
                f"{float(feeder):.3f}s (tolerance "
                f"{_tol(float(feeder)):.3f}s)")
    host = d.get("host_s")
    if isinstance(host, (int, float)):
        per_thread = sum(float(v) for v in stages.values()) / threads
        if per_thread > float(host) + _tol(float(host)):
            errs.append(
                f"{src}: stage sum {per_thread:.3f}s (per thread) exceeds "
                f"host_s {float(host):.3f}s (tolerance "
                f"{_tol(float(host)):.3f}s)")
    dw = d.get("dispatch_walls")
    disp = d.get("dispatch_s")
    if dw and isinstance(disp, (int, float)):
        # staged dispatch (ISSUE 19): the committed sub-walls must rebuild
        # the host-only dispatch wall — a sub-wall that silently swallowed
        # a synchronous solve (the MULTICHIP_r06 double-count) cannot
        sub_sum = sum(dw.values())
        if abs(sub_sum - float(disp)) > _tol(float(disp)):
            errs.append(
                f"{src}: dispatch sub-wall sum {sub_sum:.3f}s "
                f"(pack+stage+launch) does not reconcile with dispatch_s "
                f"{float(disp):.3f}s (tolerance {_tol(float(disp)):.3f}s)")
    wall, dev = d.get("wall_s"), d.get("device_s")
    if all(isinstance(x, (int, float)) for x in (wall, host, dev)):
        if abs((float(host) + float(dev)) - float(wall)) > _tol(float(wall)):
            errs.append(
                f"{src}: host_s {float(host):.3f}s + device_s "
                f"{float(dev):.3f}s does not reconcile with wall_s "
                f"{float(wall):.3f}s")
    return errs


def diff_profiles(a: dict, b: dict) -> list[str]:
    """Stage-by-stage diff lines (B relative to A) — wall delta and
    share-of-total delta per stage, plus gauge and verdict changes."""
    lines = [f"stage diff: {a['src']} -> {b['src']}"]
    sa, sb = a.get("stages") or {}, b.get("stages") or {}
    ta = max(sum(sa.values()), 1e-9)
    tb = max(sum(sb.values()), 1e-9)
    for name in sorted(set(sa) | set(sb),
                       key=lambda k: -(sb.get(k, 0.0) + sa.get(k, 0.0))):
        wa, wb = float(sa.get(name, 0.0)), float(sb.get(name, 0.0))
        d_share = wb / tb - wa / ta
        pct = f"{100 * (wb - wa) / wa:+.0f}%" if wa > 1e-9 else "new"
        lines.append(f"  {name:<10} {wa:9.3f}s -> {wb:9.3f}s  ({pct}, "
                     f"share {d_share:+.1%})")
    # staged-dispatch decomposition (ISSUE 19): the blocked-dispatch wall
    # plus its host-only sub-walls — how the async pipeline PR proves the
    # host pack/shard/transfer left the critical path ("new" on the B side
    # when the baseline predates the split)
    da, db = a.get("dispatch_s"), b.get("dispatch_s")
    if isinstance(da, (int, float)) and isinstance(db, (int, float)):
        pct = f"{100 * (db - da) / da:+.0f}%" if da > 1e-9 else "new"
        lines.append(f"  {'dispatch_s':<10} {da:9.3f}s -> {db:9.3f}s  ({pct})")
    dwa, dwb = a.get("dispatch_walls") or {}, b.get("dispatch_walls") or {}
    for k in DISPATCH_SUBWALLS:
        if k in dwa or k in dwb:
            wa, wb = float(dwa.get(k, 0.0)), float(dwb.get(k, 0.0))
            pct = f"{100 * (wb - wa) / wa:+.0f}%" if wa > 1e-9 else "new"
            lines.append(f"  {k:<10} {wa:9.3f}s -> {wb:9.3f}s  ({pct})")
    ga, gb = a.get("gauges") or {}, b.get("gauges") or {}
    for k in ("device_idle_frac", "host_blocked_frac", "overlap_frac"):
        va, vb = ga.get(k), gb.get(k)
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            lines.append(f"  {k:<18} {va:.1%} -> {vb:.1%}")
    if a.get("verdict") != b.get("verdict"):
        lines.append(f"  verdict: {a.get('verdict')} -> {b.get('verdict')}")
    else:
        lines.append(f"  verdict: {a.get('verdict')} (unchanged)")
    return lines


def prof_main(argv=None) -> int:
    """daccord-prof: render/check/diff the saturation profiler's committed
    stage tables, starvation gauges, and bottleneck verdicts."""
    p = argparse.ArgumentParser(prog="daccord-prof",
                                description=prof_main.__doc__)
    p.add_argument("paths", nargs="+",
                   help="events jsonl, *.metrics.json, bench/feeder "
                        "sidecars, or directories of them")
    p.add_argument("--check", action="store_true",
                   help="reconcile stage sums against the run's own "
                        "feeder_s/host_s/device_s anchors (5%%/50 ms "
                        "tolerance); exit 1 on any violation — the pounce "
                        "pre-chip gate")
    p.add_argument("--diff", action="store_true",
                   help="diff exactly two inputs stage-by-stage (baseline "
                        "first)")
    p.add_argument("--json", action="store_true",
                   help="emit the normalized profiles (and findings) as "
                        "one JSON line on stdout")
    args = p.parse_args(argv)

    profiles, warns = load_profiles(args.paths)
    out = sys.stderr
    errs: list[str] = []
    if args.check:
        # an input that SHOULD carry a profile but doesn't is a violation
        # in check mode, not a warning — the gate exists to catch exactly
        # that silent regression
        errs.extend(warns)
        for d in profiles:
            errs.extend(check_profile(d))
    if args.diff:
        if len(profiles) != 2:
            print(f"daccord-prof: --diff needs exactly 2 profiled inputs "
                  f"(got {len(profiles)})", file=out)
            return 2
        for ln in diff_profiles(profiles[0], profiles[1]):
            print(ln, file=out)
    elif not args.json:
        for d in profiles:
            print(render_profile(d), file=out)
    if args.json:
        print(json.dumps({"profiles": profiles, "errors": errs,
                          "warnings": warns}))
    for w in warns if not args.check else []:
        print(f"daccord-prof: warn: {w}", file=out)
    for e in errs:
        print(f"daccord-prof: {e}", file=out)
    print(f"daccord-prof: {len(profiles)} profile(s): "
          + ("OK" if not errs else f"{len(errs)} error(s)"), file=out)
    return 1 if errs else 0


if __name__ == "__main__":
    raise SystemExit(prof_main())
