"""Command-line tools mirroring the reference tool suite.

One ``main()`` per tool (reference: one binary per ``src/*.cpp``, SURVEY.md
§2.1), exposed both as console entry points and as ``python -m
daccord_tpu.tools.cli <tool> ...``. Flag names keep reference parity where
sensible (``-w`` window, ``-a`` advance, ``-d`` depth, ``-J i,n`` sharding;
SURVEY.md §5 config row) so published recipes translate.
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import sys

from ..formats.dazzdb import read_db
from ..formats.las import LasFile, shard_ranges
from ..oracle.consensus import ConsensusConfig
from ..runtime.pipeline import PipelineConfig, correct_to_fasta
from . import lastools


def _add_J(p: argparse.ArgumentParser):
    p.add_argument("-J", default=None, metavar="i,n",
                   help="process shard i of n (aread-aligned LAS byte ranges)")


def _resolve_range(args, las_path: str):
    if args.J is None:
        return None, None
    i, n = (int(x) for x in args.J.split(","))
    if not (0 <= i < n):
        raise SystemExit(f"bad -J {args.J}")
    r = shard_ranges(las_path, n)
    return r[i]


def daccord_main(argv=None) -> int:
    """daccord-tpu: consensus/error correction (reference tool ``daccord``)."""
    p = argparse.ArgumentParser(prog="daccord-tpu", description=daccord_main.__doc__)
    p.add_argument("db", help="Dazzler DB path (.db)")
    p.add_argument("las", help="LAS alignments (sorted by aread)")
    p.add_argument("-o", "--out", default="-", help="output FASTA ('-' = stdout)")
    p.add_argument("-w", type=int, default=40, help="window size")
    p.add_argument("-a", type=int, default=10, help="window advance")
    p.add_argument("-k", type=int, default=8,
                   help="base k-mer size; the escalation ladder becomes "
                        "(k,2,2),(k+2,2,2),(k+4,2,2),(k,1,1) (reference -k role)")
    p.add_argument("-b", "--batch", type=int, default=None, help="device batch size (default auto: 2048 on tpu, 512 otherwise)")
    p.add_argument("-t", "--threads", type=int, default=0,
                   help="host windowing threads (reference -t; 0 = synchronous)")
    p.add_argument("--native-threads", type=int, default=0,
                   help="C++ engine threads for --backend native "
                        "(0 = all host cores); independent of -t, which "
                        "only drives the host windowing pool")
    p.add_argument("--depth", type=int, default=32, help="max segments per window")
    p.add_argument("--seg-len", type=int, default=64, help="max segment length")
    p.add_argument("-M", "--max-kmers", type=int, default=64,
                   help="tier-0 compacted active-set size (top-M k-mers per "
                        "window). Measured across 4 regimes (BASELINE.md r3 "
                        "top-M table): 64 is the best default; 48 is better "
                        "AND cheaper on high-error CLR; uncapped rescue "
                        "(--overflow-rescue) and the full graph (-M 0, "
                        "--backend native only) measured never better")
    p.add_argument("--hp-rescue", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="homopolymer rescue: re-solve windows that failed or "
                        "solved badly in run-length-compressed space, where "
                        "length-dependent hp indels are invisible, then "
                        "re-expand runs by aligned per-position vote "
                        "(oracle/hp.py; capability the reference's k-mer DBG "
                        "lacks — runs >= k are self-repeating for it too). "
                        "Measured +0.6..+4.0 Q on every PacBio-like regime "
                        "(BASELINE.md r4). Default ON for --backend native "
                        "(the C++ engine makes it cheap); opt-in elsewhere "
                        "until the on-chip cost is measured")
    p.add_argument("--hp-vote", choices=("median", "posterior"),
                   default="median",
                   help="hp run-length vote: median (r4) or the profile-"
                        "calibrated length posterior (r5; engages only when "
                        "the fitted hp slope shows length-dependent indels, "
                        "so clean data is untouched). BASELINE.md r5 table")
    p.add_argument("--hp-accept", choices=("rescore", "likelihood"),
                   default="rescore",
                   help="hp acceptance objective: raw unit-cost rescore (r4) "
                        "or the likelihood-ratio under the calibrated "
                        "observation model (r5: hp stress Q 14.23 -> 16.29, "
                        "composite-stress Q 18.11 -> 23.29; implemented in "
                        "the C++ engine — production-speed on every "
                        "backend, byte-identical to the python reference "
                        "pass by test). Same fitted-slope gate as --hp-vote")
    p.add_argument("--overflow-rescue", action="store_true",
                   help="re-solve windows whose top-M cap bound at the rescue "
                        "active-set size (reference full-graph semantics for "
                        "exactly the truncated windows; costs one extra wide "
                        "sub-batch when any window overflows)")
    p.add_argument("--mode", choices=("split", "patch"), default="split",
                   help="unsolved windows split the read or get patched with raw bases")
    p.add_argument("-E", "--eprof", default=None, metavar="PATH",
                   help="error-profile file: load it if it exists, else estimate "
                        "and save it there (reference: cached error profile). "
                        "With -J array jobs, precompute it once via --eprof-only "
                        "so every shard corrects with the same profile")
    p.add_argument("--eprof-only", action="store_true",
                   help="estimate the error profile, write it to -E, and exit "
                        "(reference --eprofonly role)")
    p.add_argument("--stats", default=None, help="write run stats JSON here")
    p.add_argument("--log", default=None, help="jsonl event log path ('-' = stderr)")
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="per-window outcome ledger jsonl (window identity, "
                        "length, depth, tier reached, rescue membership, "
                        "batch solve wall — the learned-router training "
                        "set; see daccord-trace)")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="supervisor events jsonl (state transitions, "
                        "compile heartbeats, retries, failover; schema: "
                        "tools/eventcheck.py). Default: share --log")
    p.add_argument("--no-supervise", action="store_true",
                   help="disable the device supervisor (watchdog deadlines, "
                        "retry, mid-run failover to the degraded engine)")
    p.add_argument("--ingest-policy", choices=("strict", "quarantine", "off"),
                   default="strict",
                   help="validated LAS/DB decode policy (formats/ingest.py): "
                        "strict aborts with a structured report naming the "
                        "corrupt byte offset; quarantine contains each "
                        "corrupt overlap/pile (skipped, its read emitted "
                        "uncorrected, recorded in the quarantine sidecar + "
                        "ingest.* events); off trusts the input (pre-ISSUE-2 "
                        "behavior)")
    p.add_argument("--quarantine", default=None, metavar="PATH",
                   help="quarantine sidecar jsonl (default: <out>."
                        "quarantine.jsonl next to a file output)")
    p.add_argument("--max-pile-overlaps", type=int,
                   default=PipelineConfig().max_pile_overlaps, metavar="N",
                   help="monster-pile budget (capacity governor): a pile "
                        "holding more overlaps than this is contained "
                        "through the quarantine machinery (read emitted "
                        "uncorrected) BEFORE the quadratic windowing spend "
                        "can OOM-kill the worker; 0 disables (default: "
                        f"{PipelineConfig().max_pile_overlaps}). Device-OOM "
                        "and host-RSS degradation are governed automatically "
                        "(DACCORD_GOV_* env knobs: MIN_WIDTH, ESC_CLAMP, "
                        "PROBATION, RSS_SOFT_MB, RSS_HARD_MB)")
    p.add_argument("--failover-backend", choices=("auto", "native", "cpu"),
                   default="auto",
                   help="degraded-mode engine on declared device loss "
                        "(auto: the byte-exact host JAX ladder on cpu "
                        "platforms, the native C++ ladder on device "
                        "platforms — a dead device backend cannot be "
                        "swapped for cpu in-process, so native must be "
                        "built there)")
    p.add_argument("--failback", action="store_true",
                   help="let a background re-probe route dispatches back to "
                        "a revived chip (re-compiles every bucket shape)")
    p.add_argument("--audit-rate", type=float, default=None, metavar="F",
                   help="sampled shadow verification: fraction of windows "
                        "per fetched batch re-solved on the trusted host "
                        "ladder and compared byte-for-byte (default: env "
                        "DACCORD_AUDIT_RATE or 1/64; 0 disables). Changes "
                        "detection latency only, never output bytes")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler device trace into DIR")
    p.add_argument("--no-native", action="store_true", help="disable C++ host path")
    p.add_argument("--candidates", type=int, default=3, metavar="N",
                   help="DBG paths rescored per window (measured on synthetic "
                        "25x PacBio-like: 5 -> +0.5 Q and slightly fewer "
                        "fragments vs 3, at extra per-window backtrack/rescore "
                        "device cost)")
    p.add_argument("--max-err", type=float, default=0.3,
                   help="reject window consensus above this mean edit rate vs "
                        "its segments (0.2 -> +0.7 Q but +11%% fragments on the "
                        "same measurement)")
    p.add_argument("--qv-track", default="inqual", metavar="NAME",
                   help="intrinsic-QV track joined into the depth-ranking "
                        "score (written by the inqual tool; reference: "
                        "daccord loads the computeintrinsicqv track). "
                        "Missing track falls back to trace-diff ranking; "
                        "'' disables")
    p.add_argument("--profile-sample", type=int, default=None, metavar="N",
                   help="piles sampled by the error-profile estimation pass "
                        "(default 4 — measured sufficient, 0.08 Q spread; "
                        "BASELINE.md r3 variance probe)")
    p.add_argument("--no-end-trim", action="store_true",
                   help="keep rescue-tier solutions at read ends (default: "
                        "trim them — thin end-of-read piles solved with the "
                        "frequency filter off carry ~10x the interior error rate)")
    p.add_argument("--backend", choices=("auto", "cpu", "tpu", "native"),
                   default="auto",
                   help="device backend (SURVEY.md §5 config row); 'cpu' forces the "
                        "host platform before any backend init — the only reliable "
                        "override under this image's axon plugin; 'native' solves "
                        "windows with the C++ tier ladder (device-ladder top-M "
                        "semantics by default, -M 0 for the full graph; no "
                        "device: the fast degraded mode, 4-7x the JAX-CPU "
                        "path per core) AND defaults --hp-rescue ON — for a "
                        "cross-backend output-parity check, pass an explicit "
                        "--hp-rescue/--no-hp-rescue to both arms")
    p.add_argument("--ladder", choices=("fused", "split"), default="fused",
                   help="JAX ladder dispatch strategy: 'fused' runs tier 0 "
                        "plus every rescue tier in one jitted program per "
                        "batch (esc_cap = full width — the r1-r8 behavior); "
                        "'split' is the two-stream ladder: tier0-only "
                        "batches (Stream A) with failures/top-M-overflow "
                        "pooled on host and re-solved in dense full-ladder "
                        "batches (Stream B) — byte-identical output, the "
                        "M=256 quadratic rescue DP only ever runs over "
                        "saturated batches. Default fused until the on-chip "
                        "fused-vs-split decision row lands (kernelbench "
                        "--stages ladder_full,ladder_split). Ignored by "
                        "--backend native (per-window host escalation); "
                        "composes with --mesh (sharded tier0 + sharded "
                        "full-ladder programs)")
    p.add_argument("--paged", choices=("on", "off", "auto"), default="off",
                   help="ragged paged window batching (kernels/paging.py): "
                        "batches ship as a page pool + per-window page table "
                        "bucketed into corpus-derived (depth, pages) shape "
                        "families instead of dense [B, D, L] rectangles — "
                        "byte-identical FASTA, the dense tile is gathered "
                        "device-side inside the same jitted program; 'auto' "
                        "enables it on device (non-cpu) platforms. Default "
                        "off until the on-chip paged-vs-dense decision row "
                        "lands (BASELINE.md). JAX ladder paths only")
    p.add_argument("--page-len", type=int, default=16, metavar="N",
                   help="paged page length in bases (must divide --seg-len; "
                        "segments are page-aligned, so rounding waste "
                        "averages half a page per segment)")
    p.add_argument("--pallas", action="store_true",
                   help="run the heaviest-path DP as the Pallas TPU kernel "
                        "(bit-identical results; TPU backend only)")
    p.add_argument("--mesh", type=int, default=0, metavar="N",
                   help="shard window batches over the first N local devices "
                        "(shard_map data parallelism; 0/1 = single device). "
                        "First-class multi-chip path: mesh programs get "
                        "supervisor identity (:m<N> compile keys, watchdog/"
                        "retry, partial-mesh degradation N->N/2->...->1 "
                        "before whole-program failover), per-device governor "
                        "capacity handling, and compose with --paged and "
                        "--ladder split; auto batch scales by N. Off-pod "
                        "verification: JAX_PLATFORMS=cpu XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N")
    p.add_argument("--block", type=int, default=None, metavar="I",
                   help="process only DB block I (1-based, after db-split; the "
                        "reference's per-block workflow). Mutually exclusive with -J")
    _add_J(p)
    args = p.parse_args(argv)

    # cheap argument validation BEFORE any backend resolution: the auto
    # probe below can take 150 s on a dead tunnel, and a usage error should
    # never wait behind it
    if args.block is not None and args.J is not None:
        raise SystemExit("--block and -J are mutually exclusive")
    k = args.k
    if not (4 <= k <= 11):  # k+4 must still pack into int32 k-mer codes
        raise SystemExit(f"-k {k}: supported range is 4..11")
    # kernel k-mer positions come from seg_len (npos = seg_len - k + 1 > 0);
    # window size only needs to accommodate the base k
    if k + 4 > min(args.w, args.seg_len - 1):
        raise SystemExit(f"escalated k {k + 4} (from -k {k}) needs window size > "
                         f"{k + 4} and --seg-len > {k + 5}")
    if args.backend == "native" and args.mesh > 1:
        raise SystemExit("--backend native solves on host C++; it cannot be "
                         "combined with --mesh (pick one)")
    if args.ladder == "split" and args.backend == "native":
        # an AUTO-resolved native backend only warns (the same command must
        # work whatever the tunnel's health) — but explicitly asking for
        # both is a contradiction worth stopping
        raise SystemExit("--ladder split is a JAX-ladder dispatch strategy; "
                         "--backend native escalates per window on host "
                         "(drop one of the two flags)")
    if args.paged == "on" and args.backend == "native":
        # same rule as --ladder split: the native engine iterates dense rows
        # on host, so an explicit paged request is a contradiction (an
        # auto-resolved native backend only logs and runs dense)
        raise SystemExit("--paged on is a JAX-ladder wire format; --backend "
                         "native solves dense rows on host (drop one flag)")
    if args.paged != "off" and (args.page_len <= 0
                                or args.seg_len % args.page_len):
        raise SystemExit(f"--page-len {args.page_len} must be positive and "
                         f"divide --seg-len {args.seg_len}")
    if args.max_kmers == 0 and args.backend not in ("native", "auto"):
        # on the device ladder M=0 means top_k(…, 0): an empty active set
        # that silently solves nothing — only the native engine interprets
        # 0 as "uncapped full graph"
        raise SystemExit("-M 0 (full graph) requires --backend native; the "
                         "device ladder needs a positive top-M cap")

    backend_auto = args.backend == "auto"
    if backend_auto:
        # a dead axon tunnel hangs default-backend init forever; auto must
        # probe (bounded, subprocess) and fall back before any jax touch.
        # --mesh shards over devices — incompatible with the native engine,
        # so a dead tunnel then falls back to the CPU device ladder
        from ..utils.obs import resolve_auto_backend

        args.backend = resolve_auto_backend(prefer_native=args.mesh <= 1)
        if args.max_kmers == 0 and args.backend != "native":
            raise SystemExit("-M 0 (full graph) requires --backend native; "
                             "the device ladder needs a positive top-M cap")
    if args.backend in ("cpu", "native"):
        # native solves on host C++, but incidental jax usage (estimation
        # helpers) must still never touch a possibly-dead TPU tunnel
        import jax

        jax.config.update("jax_platforms", "cpu")
    from ..utils.obs import enable_compilation_cache

    enable_compilation_cache()

    from ..formats.ingest import IngestError

    def _ingest_exit(ex: IngestError):
        # integrity failure: exit with the structured report (kind + byte
        # offset + pile per issue), not a traceback. The hint must match
        # the situation: under quarantine a surviving failure comes from a
        # path that NEEDS the aread index (-J/--block sharding), which a
        # corrupt file cannot provide — suggesting the already-set flag
        # would be a loop
        hint = ("(rerun with --ingest-policy quarantine to contain the "
                "corrupt piles instead)" if args.ingest_policy == "strict"
                else "(byte-range sharding needs the aread index, which "
                     "cannot be built over a corrupt LAS — repair the file "
                     "or run unsharded)")
        raise SystemExit(f"daccord: {ex}\n{hint}")

    try:
        if args.block is not None:
            from ..formats.dazzdb import db_blocks
            from ..formats.las import range_for_areads

            blocks = db_blocks(args.db)
            if not (1 <= args.block <= len(blocks)):
                raise SystemExit(f"--block {args.block}: DB has {len(blocks)} blocks")
            lo, hi = blocks[args.block - 1]
            start, end = range_for_areads(args.las, lo, hi)
        else:
            start, end = _resolve_range(args, args.las)
    except IngestError as ex:
        _ingest_exit(ex)
    tiers = ((k, 2, 2), (k + 2, 2, 2), (k + 4, 2, 2), (k, 1, 1))
    from ..oracle.dbg import DBGParams

    ccfg = ConsensusConfig(w=args.w, adv=args.a, mode=args.mode, tiers=tiers,
                           dbg=DBGParams(n_candidates=args.candidates,
                                         max_err=args.max_err),
                           hp_rescue=(args.hp_rescue
                                      if args.hp_rescue is not None
                                      # default ON for the host engines: the
                                      # drain costs 2.7% of the cpu-path wall
                                      # (hpdrainbench r5) for +2.0 Q. OFF for
                                      # tpu: worst-case non-overlapped bound
                                      # is 64-80% of the chip's 67 us/window
                                      # (BASELINE.md r5 hp drain table) -
                                      # flip pending the on-chip overlap
                                      # measurement (DACCORD_BENCH_HP=1).
                                      # An auto-resolved engine must not flip
                                      # defaults with tunnel health: the same
                                      # command has to produce the same bases
                                      # today and tomorrow
                                      else (args.backend in ("native", "cpu")
                                            and not backend_auto)),
                           hp_vote=args.hp_vote,
                           hp_accept=args.hp_accept)
    cfg = PipelineConfig(consensus=ccfg, batch_size=args.batch,
                         depth=args.depth, seg_len=args.seg_len,
                         max_kmers=args.max_kmers,
                         log_path=args.log, events_path=args.events,
                         supervise=not args.no_supervise,
                         failover_backend=args.failover_backend,
                         failback=args.failback,
                         audit_rate=args.audit_rate,
                         use_native=not args.no_native,
                         feeder_threads=args.threads, use_pallas=args.pallas,
                         end_trim=not args.no_end_trim,
                         qv_track=args.qv_track or None,
                         profile_sample_piles=(
                             args.profile_sample
                             if args.profile_sample is not None
                             else PipelineConfig().profile_sample_piles),
                         overflow_rescue=args.overflow_rescue,
                         native_solver=args.backend == "native",
                         native_threads=args.native_threads,
                         ingest_policy=args.ingest_policy,
                         quarantine_path=args.quarantine,
                         ladder_mode=args.ladder,
                         paged=args.paged, page_len=args.page_len,
                         mesh=args.mesh,
                         max_pile_overlaps=args.max_pile_overlaps,
                         ledger_path=args.ledger)

    import os

    from ..oracle.profile import ErrorProfile

    def _estimate_validated():
        # -E pre-estimation under the same ingest policy as the run:
        # without the scan, a coords-corrupt record sails through index_las
        # (framing intact) and dies as a raw assertion inside refine_overlap.
        # Strict -> structured IngestError; quarantine -> sample clean piles
        from ..runtime.pipeline import estimate_profile_for_shard

        db_ = read_db(args.db, strict=args.ingest_policy == "strict")
        las_ = LasFile(args.las)
        clean = None
        if args.ingest_policy != "off":
            from ..formats.ingest import scan_with_db

            rep = scan_with_db(db_, las_, start, end)
            if rep.issues:
                if args.ingest_policy == "strict":
                    raise rep.error()
                clean = rep.pile_ranges
        return estimate_profile_for_shard(db_, las_, cfg, start, end,
                                          pile_ranges=clean)

    # everything that touches the artifacts — the -E pre-estimation pass
    # included — runs under the IngestError handler so an integrity
    # failure always exits with the structured report, never a traceback
    try:
        prof = None
        if args.eprof and os.path.exists(args.eprof) and not args.eprof_only:
            prof = ErrorProfile.load(args.eprof)
        elif args.eprof or args.eprof_only:
            if not args.eprof:
                raise SystemExit("--eprof-only requires -E/--eprof PATH")
            prof = _estimate_validated()
            prof.save(args.eprof)
            if args.eprof_only:
                print(json.dumps({"eprof": args.eprof, "p_ins": prof.p_ins,
                                  "p_del": prof.p_del, "p_sub": prof.p_sub}),
                      file=sys.stderr)
                return 0

        if args.mesh > 1:
            # fail fast with the off-pod recipe before any artifact work;
            # the pipeline builds the sharded solver itself (cfg.mesh) from
            # the run's own TierLadder — supervisor/governor/paging/split
            # all wrap it like the single-device path
            from ..parallel.mesh import check_mesh_devices

            check_mesh_devices(args.mesh)

        if args.profile:
            import jax

            with jax.profiler.trace(args.profile):
                stats = correct_to_fasta(args.db, args.las, args.out, cfg, start=start,
                                         end=end, profile=prof)
        else:
            stats = correct_to_fasta(args.db, args.las, args.out, cfg, start=start,
                                     end=end, profile=prof)
    except IngestError as ex:
        _ingest_exit(ex)
    line = {
        "reads": stats.n_reads, "windows": stats.n_windows, "solved": stats.n_solved,
        "skipped_shallow": stats.n_skipped_shallow, "qv_ranked": stats.qv_ranked,
        "topm_overflow": stats.n_topm_overflow,
        "end_trimmed": stats.n_end_trimmed,
        "fragments": stats.n_fragments, "bases_in": stats.bases_in,
        "bases_out": stats.bases_out, "wall_s": round(stats.wall_s, 3),
        "device_s": round(stats.device_s, 3),
        "tier_histogram": stats.tier_histogram,
        "pad_waste": round(stats.pad_waste, 4),
        "paged": stats.paged,
        "native_host": stats.native_host,
        "degraded": stats.degraded,
        "quarantined": stats.n_quarantined,
        "ingest_issues": stats.n_ingest_issues,
        # two-stream ladder decision counters (--ladder; ISSUE 4)
        "ladder": args.ladder,
        "rescue_slots": stats.rescue_slots_executed,
        "rescue_windows": stats.n_rescue_windows,
        "rescue_density": round(stats.rescue_density, 4),
    }
    if stats.degraded:
        line["fallback_reason"] = stats.fallback_reason
    print(json.dumps(line), file=sys.stderr)
    if args.stats:
        with open(args.stats, "wt") as fh:
            json.dump(line, fh)
    return 0


def intrinsicqv_main(argv=None) -> int:
    """compute-inqual: intrinsic QV track (reference ``computeintrinsicqv``)."""
    p = argparse.ArgumentParser(prog="compute-inqual", description=intrinsicqv_main.__doc__)
    p.add_argument("db")
    p.add_argument("las")
    p.add_argument("-d", type=int, default=20, help="expected coverage depth")
    p.add_argument("--block", type=int, default=None, metavar="I",
                   help="process only DB block I (1-based); writes a per-block "
                        "track to merge with `catrack`")
    args = p.parse_args(argv)
    db = read_db(args.db, load_bases=False)  # lengths only: block jobs stay O(block)
    las = LasFile(args.las)
    lastools.compute_intrinsic_qv(db, las, depth=args.d, block=args.block)
    return 0


def detectrepeats_main(argv=None) -> int:
    """las-detect-repeats: repeat intervals (reference ``lasdetectsimplerepeats``)."""
    p = argparse.ArgumentParser(prog="las-detect-repeats", description=detectrepeats_main.__doc__)
    p.add_argument("db")
    p.add_argument("las")
    p.add_argument("-d", type=int, default=20, help="expected coverage depth")
    p.add_argument("--factor", type=float, default=2.0, help="over-coverage factor")
    p.add_argument("--qv-track", default="inqual", metavar="NAME",
                   help="intrinsic-QV track gating which tiles may be repeat-"
                        "annotated (reference: the tool consumes "
                        "computeintrinsicqv output); '' disables")
    p.add_argument("--qv-max", type=int, default=100,
                   help="tiles with QV above this are too low-quality to "
                        "repeat-annotate (255 = no coverage always excluded)")
    p.add_argument("--grow", type=int, default=2,
                   help="dilate detected intervals by this many tiles per "
                        "side (tile-granular thresholding under-calls repeat "
                        "edges where coverage decays)")
    p.add_argument("--block", type=int, default=None, metavar="I",
                   help="process only DB block I (1-based); writes a per-block "
                        "track to merge with `catrack`")
    args = p.parse_args(argv)
    db = read_db(args.db, load_bases=False)
    las = LasFile(args.las)
    lastools.detect_repeats(db, las, depth=args.d, cov_factor=args.factor,
                            block=args.block, qv_track=args.qv_track or None,
                            qv_max=args.qv_max, grow=args.grow)
    return 0


def filteralignments_main(argv=None) -> int:
    """las-filter: drop repeat-inconsistent alignments (reference ``lasfilteralignments``)."""
    p = argparse.ArgumentParser(prog="las-filter", description=filteralignments_main.__doc__)
    p.add_argument("db")
    p.add_argument("las")
    p.add_argument("out")
    p.add_argument("--max-err", type=float, default=None)
    p.add_argument("--rep-margin", type=float, default=0.015,
                   help="repeat-confined alignments survive while their error "
                        "rate is within this of the unique-region profile "
                        "(cross-repeat-copy alignments carry the copies' "
                        "divergence on top of it)")
    p.add_argument("--mem-records", type=int, default=2_000_000,
                   help="bound peak memory to ~this many records (the "
                        "pre-filter LAS is the workflow's largest file); "
                        "chunked pile-aligned passes, byte-identical output")
    args = p.parse_args(argv)
    db = read_db(args.db, load_bases=False)
    las = LasFile(args.las)
    n = lastools.filter_alignments(db, las, args.out, max_err=args.max_err,
                                   rep_margin=args.rep_margin,
                                   mem_records=args.mem_records)
    print(f"kept {n} of {las.novl}", file=sys.stderr)
    return 0


def filtersym_main(argv=None) -> int:
    """las-filter-sym: symmetrize a filtered LAS (reference ``filtersym``)."""
    p = argparse.ArgumentParser(prog="las-filter-sym", description=filtersym_main.__doc__)
    p.add_argument("las")
    p.add_argument("out")
    p.add_argument("--db", default=None, help="DB for exact complement mirroring")
    p.add_argument("--mem-records", type=int, default=2_000_000,
                   help="above this record count (with --db) the symmetric "
                        "join hash-partitions its key sets onto disk so "
                        "memory stays bounded; output is byte-identical")
    args = p.parse_args(argv)
    db = read_db(args.db, load_bases=False) if args.db else None
    if db is not None and LasFile(args.las).novl > args.mem_records:
        from ..formats.extsort import filter_symmetric_external

        n = filter_symmetric_external(args.las, args.out, db,
                                      mem_records=args.mem_records)
    else:
        n = lastools.filter_symmetric(args.las, args.out, db=db)
    print(f"kept {n}", file=sys.stderr)
    return 0


def lasindex_main(argv=None) -> int:
    """las-index: build/refresh the aread byte index sidecar (reference
    OverlapIndexer role); sharded jobs then skip the full-file scan."""
    p = argparse.ArgumentParser(prog="las-index", description=lasindex_main.__doc__)
    p.add_argument("las")
    args = p.parse_args(argv)
    from ..formats.las import index_las

    idx = index_las(args.las)
    print(f"{len(idx)} piles -> {args.las}.idx", file=sys.stderr)
    return 0


def lassort_main(argv=None) -> int:
    """las-sort: sort a LAS by (aread, bread) (reference LAsort role — a
    block-memory external sort, so inputs far larger than RAM still sort)."""
    p = argparse.ArgumentParser(prog="las-sort", description=lassort_main.__doc__)
    p.add_argument("las")
    p.add_argument("out")
    p.add_argument("--mem-records", type=int, default=2_000_000,
                   help="records held in memory per sorted run; files with "
                        "more records than this go through on-disk runs + "
                        "k-way merge (byte-identical to the in-memory sort)")
    args = p.parse_args(argv)
    from ..formats.extsort import sort_las_external

    n = sort_las_external(args.las, args.out, mem_records=args.mem_records)
    print(f"sorted {n} overlaps", file=sys.stderr)
    return 0


def dbsplit_main(argv=None) -> int:
    """db-split: recompute the DB's block partition (DAZZ_DB ``DBsplit``
    role). Blocks bound per-job work in daligner-style workflows; this
    framework's own sharding is LAS-byte-range based (-J), so blocks exist
    for workflow interop."""
    p = argparse.ArgumentParser(prog="db-split", description=dbsplit_main.__doc__)
    p.add_argument("db")
    p.add_argument("-s", "--size", type=float, default=200.0,
                   help="block size in megabases (DBsplit -s)")
    args = p.parse_args(argv)
    from ..formats.dazzdb import split_db

    blocks = split_db(args.db, int(args.size * 1_000_000))
    print(f"{len(blocks)} blocks", file=sys.stderr)
    return 0


def catrack_main(argv=None) -> int:
    """catrack: merge per-block tracks into the whole-DB track (DAZZ_DB
    ``Catrack`` role; completes the per-block cluster workflow for the
    track-writing tools `inqual --block` / `repeats --block`)."""
    p = argparse.ArgumentParser(prog="catrack", description=catrack_main.__doc__)
    p.add_argument("db")
    p.add_argument("track", help="track name (e.g. inqual, rep)")
    p.add_argument("-d", "--delete", action="store_true",
                   help="remove the per-block track files after merging")
    args = p.parse_args(argv)
    from ..formats.dazzdb import catrack

    n = catrack(args.db, args.track, delete=args.delete)
    print(f"merged track '{args.track}' over {n} reads", file=sys.stderr)
    return 0


def lasmerge_main(argv=None) -> int:
    """las-merge: merge sorted LAS files into one (reference LAmerge role —
    DALIGNER emits one LAS per DB-block pair; downstream tools want one
    aread-sorted file)."""
    p = argparse.ArgumentParser(prog="las-merge", description=lasmerge_main.__doc__)
    p.add_argument("out")
    p.add_argument("las", nargs="+", help="input LAS files (aread-sorted)")
    args = p.parse_args(argv)
    import heapq
    import os

    from ..formats.las import write_las

    if os.path.abspath(args.out) in {os.path.abspath(f) for f in args.las}:
        raise SystemExit("las-merge: output path must not be one of the inputs "
                         "(inputs are streamed lazily while the output is written)")
    files = [LasFile(f) for f in args.las]
    tspaces = {f.tspace for f in files}
    if len(tspaces) != 1:
        raise SystemExit(f"mismatched tspace across inputs: {sorted(tspaces)}")
    tspace = tspaces.pop()
    from ..utils.aio import is_mem

    native_ok = not any(is_mem(p) for p in [args.out, *args.las])
    if native_ok:
        try:
            from ..native import available
            native_ok = available()
        except Exception:
            native_ok = False
    if native_ok:
        # native heap merge (LAmerge is native in the reference too); same
        # ordering as the Python path below (parity-tested)
        from ..formats.las import invalidate_index
        from ..native.api import las_merge_native
        from ..utils.aio import local_path

        n = las_merge_native([local_path(p) for p in args.las],
                             local_path(args.out), tspace)
        invalidate_index(args.out)
    else:
        # k-way merge of already-sorted streams, keyed like lassort
        streams = [iter(f) for f in files]
        merged = heapq.merge(*streams, key=lambda o: (o.aread, o.bread, o.abpos))
        n = write_las(args.out, tspace, merged)
    print(f"merged {len(files)} files -> {n} overlaps", file=sys.stderr)
    return 0


def fasta2db_main(argv=None) -> int:
    """fasta2db: build a Dazzler DB triple from FASTA (DAZZ_DB fasta2DB role)."""
    p = argparse.ArgumentParser(prog="fasta2db", description=fasta2db_main.__doc__)
    p.add_argument("fasta")
    p.add_argument("db", help="output .db path")
    p.add_argument("--cutoff", type=int, default=0,
                   help="drop reads shorter than this (Dazzler trim semantics)")
    args = p.parse_args(argv)
    from ..formats.dazzdb import write_db
    from ..formats.fasta import read_fasta
    from ..utils.bases import seq_to_ints

    recs = list(read_fasta(args.fasta))
    n_all = len(recs)
    if args.cutoff > 0:
        recs = [r for r in recs if len(r.seq) >= args.cutoff]
    db = write_db(args.db, [seq_to_ints(r.seq) for r in recs],
                  names=[r.name for r in recs], cutoff=args.cutoff)
    dropped = n_all - len(recs)
    print(f"{db.nreads} reads, {db.totlen} bases"
          + (f" ({dropped} below cutoff dropped)" if dropped else ""),
          file=sys.stderr)
    return 0


def db2fasta_main(argv=None) -> int:
    """db2fasta: dump a Dazzler DB back to FASTA (DAZZ_DB DB2fasta role)."""
    p = argparse.ArgumentParser(prog="db2fasta", description=db2fasta_main.__doc__)
    p.add_argument("db")
    p.add_argument("-o", "--out", default="-", help="output FASTA ('-' = stdout)")
    args = p.parse_args(argv)
    from ..formats.dazzdb import read_db
    from ..formats.fasta import FastaRecord, write_fasta
    from ..utils.bases import ints_to_seq

    db = read_db(args.db)
    recs = [FastaRecord(db.names[i] if i < len(db.names) else f"read{i}",
                        ints_to_seq(db.read_bases(i)))
            for i in range(db.nreads)]
    write_fasta(sys.stdout if args.out == "-" else args.out, recs)
    return 0


def dbstats_main(argv=None) -> int:
    """db-stats: read/base counts, length distribution, N50, block partition
    (DAZZ_DB ``DBstats`` role)."""
    p = argparse.ArgumentParser(prog="db-stats", description=dbstats_main.__doc__)
    p.add_argument("db")
    args = p.parse_args(argv)
    import numpy as np

    from ..formats.dazzdb import db_blocks, read_lengths

    rlens = np.sort(read_lengths(args.db))[::-1]
    tot = int(rlens.sum())
    n50 = 0
    if tot:
        n50 = int(rlens[np.searchsorted(np.cumsum(rlens), tot / 2)])
    try:
        nblocks = len(db_blocks(args.db))
    except (OSError, ValueError, IndexError):
        nblocks = 1
    print(f"{len(rlens):>12,} reads  {tot:>15,} bases  in {nblocks} block(s)")
    if len(rlens):
        print(f"{'min':>12} {int(rlens[-1]):>11,}\n"
              f"{'median':>12} {int(np.median(rlens)):>11,}\n"
              f"{'mean':>12} {int(rlens.mean()):>11,}\n"
              f"{'N50':>12} {n50:>11,}\n"
              f"{'max':>12} {int(rlens[0]):>11,}")
    return 0


def dbshow_main(argv=None) -> int:
    """db-show: print selected reads as FASTA (DAZZ_DB ``DBshow`` role).
    Read selectors are 0-based ids or i-j ranges (end exclusive); no selector
    dumps the whole DB."""
    p = argparse.ArgumentParser(prog="db-show", description=dbshow_main.__doc__)
    p.add_argument("db")
    p.add_argument("reads", nargs="*", help="read ids: '7' or '3-12' (0-based, end-exclusive)")
    p.add_argument("-o", "--out", default="-", help="output FASTA ('-' = stdout)")
    args = p.parse_args(argv)
    from ..formats.dazzdb import decode_reads_from_bps
    from ..formats.fasta import FastaRecord, write_fasta
    from ..utils.bases import ints_to_seq

    db = read_db(args.db, load_bases=False)  # bases seeked per selected read
    ids: list[int] = []
    for sel in args.reads:
        try:
            if "-" in sel:
                i, j = (int(x) for x in sel.split("-", 1))
                ids.extend(range(i, j))
            else:
                ids.append(int(sel))
        except ValueError:
            raise SystemExit(f"db-show: bad read selector {sel!r} (use 'i' or 'i-j')")
    if not args.reads:
        ids = list(range(db.nreads))
    bad = [i for i in ids if not (0 <= i < db.nreads)]
    if bad:
        raise SystemExit(f"db-show: read id(s) out of range (DB has {db.nreads} reads): {bad[:5]}")
    recs = (FastaRecord(db.names[i] if i < len(db.names) else f"read{i}",
                        ints_to_seq(bases))
            for i, bases in zip(ids, decode_reads_from_bps(db, ids)))
    write_fasta(sys.stdout if args.out == "-" else args.out, recs)
    return 0


def lasshow_main(argv=None) -> int:
    """las-show: human-readable LAS dump (DALIGNER ``LAshow`` role)."""
    p = argparse.ArgumentParser(prog="las-show", description=lasshow_main.__doc__)
    p.add_argument("las")
    p.add_argument("-n", type=int, default=None, help="print at most N records")
    p.add_argument("--trace", action="store_true", help="also print per-tile (diffs, b-bases)")
    args = p.parse_args(argv)
    las = LasFile(args.las)
    print(f"{las.novl} records, tspace {las.tspace}")
    for i, o in enumerate(las):
        if args.n is not None and i >= args.n:
            break
        strand = "c" if o.is_comp else "n"
        print(f"{o.aread:>9} {o.bread:>9} {strand} "
              f"[{o.abpos:>9}..{o.aepos:>9}] x [{o.bbpos:>9}..{o.bepos:>9}] "
              f"diffs {o.diffs}")
        if args.trace:
            for d, b in o.trace:
                print(f"          ({d:>4}, {b:>4})")
    return 0


def lascheck_main(argv=None) -> int:
    """las-check: validate LAS structure (DALIGNER ``LAcheck`` role): header
    count vs records, aread sort order, coordinate sanity, per-record trace
    tile counts, and (with a DB) coordinate bounds against read lengths.
    Exit status 1 on any violation."""
    p = argparse.ArgumentParser(prog="las-check", description=lascheck_main.__doc__)
    p.add_argument("las")
    p.add_argument("--db", default=None, help="DB to bounds-check coordinates against")
    p.add_argument("--max-report", type=int, default=10)
    args = p.parse_args(argv)
    rlens = None
    if args.db:
        from ..formats.dazzdb import read_lengths

        rlens = read_lengths(args.db)
    try:
        las = LasFile(args.las)
    except ValueError as ex:  # IngestError: torn/corrupt header
        print(f"{args.las}: {ex}", file=sys.stderr)
        print(f"{args.las}: 0 records BAD", file=sys.stderr)
        return 1
    errs: list[str] = []

    def report(msg: str):
        if len(errs) < args.max_report:
            errs.append(msg)

    n = 0
    prev = (-1, -1, -1)
    try:
        for o in las:
            key = (o.aread, o.bread, o.abpos)
            if key < prev:
                report(f"record {n}: sort order violated {prev} > {key}")
            prev = key
            if not (0 <= o.abpos < o.aepos) or not (0 <= o.bbpos < o.bepos):
                report(f"record {n}: degenerate span a[{o.abpos},{o.aepos}) b[{o.bbpos},{o.bepos})")
            elif len(o.trace) != o.ntiles(las.tspace):
                report(f"record {n}: {len(o.trace)} trace tiles, expected {o.ntiles(las.tspace)}")
            elif int(o.trace[:, 1].sum()) != o.bepos - o.bbpos:
                report(f"record {n}: trace b-bases {int(o.trace[:, 1].sum())} != span {o.bepos - o.bbpos}")
            if rlens is not None:
                if not (0 <= o.aread < len(rlens)) or not (0 <= o.bread < len(rlens)):
                    report(f"record {n}: read id out of range ({o.aread}, {o.bread})")
                elif o.aepos > rlens[o.aread] or o.bepos > rlens[o.bread]:
                    report(f"record {n}: span exceeds read length")
            n += 1
    except (ValueError, struct.error) as ex:
        # a file truncated mid-record/mid-trace is exactly what this tool
        # exists to detect — report it, don't traceback
        report(f"record {n}: file truncated or corrupt mid-record ({ex})")
    if n != las.novl:
        report(f"header novl {las.novl} != {n} records")
    from ..formats.ingest import sidecar_issues

    for iss in sidecar_issues(args.las):
        # a torn .idx sidecar silently costs every array job a full rescan;
        # surface it here (the loader itself rebuilds rather than erroring)
        report(iss.describe())
    for e in errs:
        print(e, file=sys.stderr)
    print(f"{args.las}: {n} records {'OK' if not errs else 'BAD'}", file=sys.stderr)
    return 1 if errs else 0


def lassplit_main(argv=None) -> int:
    """las-split: split an aread-sorted LAS into per-DB-block files (DALIGNER
    ``LAsplit`` role — the inverse of las-merge; block jobs then read only
    their own file). Output template must contain '#' (block number)."""
    p = argparse.ArgumentParser(prog="las-split", description=lassplit_main.__doc__)
    p.add_argument("las")
    p.add_argument("db", help="DB whose block partition drives the split")
    p.add_argument("template", help="output path template, e.g. out.#.las")
    args = p.parse_args(argv)
    if "#" not in args.template:
        raise SystemExit("las-split: template must contain '#'")
    from ..formats.dazzdb import db_blocks
    from ..formats.las import range_for_areads, write_las

    las = LasFile(args.las)
    total = 0
    for i, (lo, hi) in enumerate(db_blocks(args.db), start=1):
        start, end = range_for_areads(args.las, lo, hi)
        n = write_las(args.template.replace("#", str(i)), las.tspace,
                      las.iter_range(start, end))
        total += n
        print(f"block {i}: reads [{lo},{hi}) -> {n} overlaps", file=sys.stderr)
    if total != las.novl:
        # e.g. a LAS built against a different (larger) DB: records whose
        # aread lies outside the block partition would vanish silently
        raise SystemExit(f"las-split: {las.novl - total} of {las.novl} overlaps "
                         f"fall outside {args.db}'s block partition")
    return 0


def shard_main(argv=None) -> int:
    """daccord-shard: run one LAS shard with manifest + mid-shard checkpoints
    (the reference's -J array-job model with resumability)."""
    p = argparse.ArgumentParser(prog="daccord-shard", description=shard_main.__doc__)
    p.add_argument("db")
    p.add_argument("las")
    p.add_argument("outdir")
    p.add_argument("-J", required=True, metavar="i,n", help="shard i of n")
    p.add_argument("-b", "--batch", type=int, default=None)
    p.add_argument("--checkpoint-every", type=int, default=64,
                   help="checkpoint progress every N emitted reads (0 = off)")
    p.add_argument("--force", action="store_true", help="recompute even if manifest exists")
    p.add_argument("--profile-sample", type=int, default=None, metavar="N",
                   help="piles sampled by the profile estimation pass")
    p.add_argument("--backend", choices=("auto", "cpu", "tpu", "native"),
                   default="auto")
    p.add_argument("--paged", choices=("on", "off", "auto"), default="off",
                   help="ragged paged window batching (see daccord --paged)")
    p.add_argument("--mesh", type=int, default=0, metavar="N",
                   help="shard window batches over the first N local devices "
                        "(see daccord --mesh); fleet workers drive a local "
                        "mesh through this — one host, N chips is ONE "
                        "worker, auto batch scales by N")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="supervisor events jsonl (see daccord --events)")
    p.add_argument("--ledger", default="auto", metavar="PATH",
                   help="per-window outcome ledger jsonl (see daccord "
                        "--ledger); 'auto' (default) = "
                        "shardNNNN.ledger.jsonl in OUTDIR, 'none' disables")
    p.add_argument("--ingest-policy", choices=("strict", "quarantine", "off"),
                   default="strict",
                   help="validated LAS/DB decode policy (see daccord "
                        "--ingest-policy); the quarantine sidecar lands at "
                        "shardNNNN.quarantine.jsonl in OUTDIR")
    p.add_argument("--max-pile-overlaps", type=int,
                   default=PipelineConfig().max_pile_overlaps, metavar="N",
                   help="monster-pile budget (see daccord "
                        "--max-pile-overlaps); 0 disables (default: "
                        f"{PipelineConfig().max_pile_overlaps})")
    args = p.parse_args(argv)
    if args.backend == "native" and args.mesh > 1:
        raise SystemExit("--backend native solves on host C++; it cannot be "
                         "combined with --mesh (pick one)")
    if args.backend == "auto":
        from ..utils.obs import resolve_auto_backend

        # --mesh shards over devices — incompatible with the native engine,
        # so a dead tunnel then falls back to the CPU device ladder
        args.backend = resolve_auto_backend(prefer_native=args.mesh <= 1)
    if args.backend in ("cpu", "native"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    from ..utils.obs import enable_compilation_cache

    enable_compilation_cache()
    if args.mesh > 1:
        from ..parallel.mesh import check_mesh_devices

        check_mesh_devices(args.mesh)
    i, n = (int(x) for x in args.J.split(","))
    if not (0 <= i < n):
        raise SystemExit(f"bad -J {args.J}")
    from ..parallel.launch import run_shard, shard_paths

    ledger = args.ledger
    if ledger == "auto":
        ledger = shard_paths(args.outdir, i)["ledger"]
    elif ledger == "none":
        ledger = None
    scfg = PipelineConfig(batch_size=args.batch,
                          native_solver=args.backend == "native",
                          events_path=args.events,
                          ingest_policy=args.ingest_policy,
                          paged=args.paged, mesh=args.mesh,
                          max_pile_overlaps=args.max_pile_overlaps,
                          ledger_path=ledger)
    if args.profile_sample is not None:
        scfg.profile_sample_piles = args.profile_sample
    from ..formats.ingest import IngestError

    try:
        m = run_shard(args.db, args.las, args.outdir, i, n, scfg,
                      force=args.force, checkpoint_every=args.checkpoint_every)
    except IngestError as ex:
        hint = ("(rerun with --ingest-policy quarantine to contain the "
                "corrupt piles instead)" if args.ingest_policy == "strict"
                else "(multi-shard splitting needs the aread index, which "
                     "cannot be built over a corrupt LAS — repair the file "
                     "or run single-shard: -J 0,1)")
        raise SystemExit(f"daccord-shard: {ex}\n{hint}")
    print(json.dumps(m), file=sys.stderr)
    return 0


def serve_main(argv=None) -> int:
    """daccord-serve: always-on consensus service (ISSUE 10) — HTTP/JSON
    front-end accepting concurrent correction jobs, cross-job continuous
    batching into shared device batches (byte-identical per job to a solo
    daccord run), per-tenant admission control with RSS-watermark load
    shedding, and a warm-state manager keeping compiled programs and
    capacity ratchets resident across jobs."""
    p = argparse.ArgumentParser(prog="daccord-serve",
                                description=serve_main.__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8947,
                   help="listen port (0 = ephemeral; pair with --ready-file)")
    p.add_argument("--workdir", required=True,
                   help="service state root: job spool dirs, durable job "
                        "commits, telemetry sidecars")
    p.add_argument("--backend", choices=("auto", "cpu", "tpu", "native"),
                   default="auto",
                   help="shared solve engine for every group (see daccord "
                        "--backend); auto probes the tunnel once at startup")
    p.add_argument("-b", "--batch", type=int, default=None,
                   help="merged cross-job dispatch width (default: the "
                        "backend's auto batch)")
    p.add_argument("--workers", type=int, default=2,
                   help="concurrent job slots (each job runs its own "
                        "feeder; the device is shared through the batcher)")
    p.add_argument("--ladder", choices=("fused", "split"), default="fused",
                   help="group dispatch strategy (see daccord --ladder); "
                        "JAX groups only — native groups run fused dense")
    p.add_argument("--paged", action="store_true",
                   help="pack merged cross-job batches as the ragged paged "
                        "wire format (kernels/paging.py); JAX groups only")
    p.add_argument("--mesh", type=int, default=0, metavar="N",
                   help="mesh-backed solve groups: merged cross-job batches "
                        "shard over the first N local devices (see daccord "
                        "--mesh) — N x the continuous-batching width per "
                        "warm compile; auto -b scales by N. JAX groups only")
    p.add_argument("--flush-lag-ms", type=float, default=50.0,
                   help="stale cross-job pool flush deadline: bounds the "
                        "latency one job's rows can pay waiting for "
                        "cohabitants")
    p.add_argument("--idle-evict-s", type=float, default=600.0,
                   help="warm solve-group TTL (compiled programs + ratchet "
                        "state evict after this long idle)")
    p.add_argument("--max-queued", type=int, default=32,
                   help="service-wide admission queue depth")
    p.add_argument("--tenant-max-queued", type=int, default=8,
                   help="queued+running jobs per tenant")
    p.add_argument("--tenant-max-mb", type=float, default=1024.0,
                   help="queued input bytes per tenant (MB)")
    p.add_argument("--rss-soft-mb", type=float, default=0.0,
                   help="pause admission at this host RSS (set BELOW the "
                        "pipeline's DACCORD_GOV_RSS_* watermarks so new "
                        "work sheds before running feeders pause); 0 = off")
    p.add_argument("--rss-hard-mb", type=float, default=0.0,
                   help="reject + engage the batch-ladder shed at this "
                        "host RSS; 0 = off")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="service events jsonl (serve.* lifecycle + metrics "
                        "snapshots; default WORKDIR/serve.events.jsonl)")
    p.add_argument("--ready-file", default=None, metavar="PATH",
                   help="write {port, pid} JSON here once the listener is "
                        "bound (scripts discovering an ephemeral --port 0)")
    p.add_argument("--metrics-snapshot-s", type=float, default=30.0)
    p.add_argument("--slo-p99-s", type=float, default=0.0,
                   help="p99 job-latency SLO target: the ticker tracks "
                        "rolling p99 vs this, emits serve.slo burn events, "
                        "and drives the batch-width shed ladder BEFORE "
                        "breach (0 = off)")
    p.add_argument("--slo-window-s", type=float, default=60.0,
                   help="rolling window the SLO p99 is computed over")
    # crash-durable tier (ISSUE 15)
    p.add_argument("--no-journal", action="store_true",
                   help="disable the write-ahead job journal (jobs queued "
                        "or running at a crash are then LOST; the default "
                        "journal makes them replay on restart)")
    p.add_argument("--checkpoint-reads", type=int, default=16,
                   help="per-job progress checkpoint stride (emitted reads "
                        "between durable progress manifests — the resume "
                        "point for replay/takeover; 0 = off)")
    p.add_argument("--peer-dir", default=None, metavar="DIR",
                   help="shared-FS lease root: serve processes pointing at "
                        "the SAME dir form a takeover group — any of them "
                        "detects a dead peer's stale per-job lease, claims "
                        "the journaled job, and finishes it byte-identically "
                        "(default: off — journal replay only)")
    p.add_argument("--peer-name", default="", metavar="NAME",
                   help="lease holder identity (default "
                        "<workdir-basename>:<pid>)")
    p.add_argument("--lease-ttl-s", type=float, default=15.0,
                   help="a per-job lease older than this is stale "
                        "(peer takeover fires)")
    p.add_argument("--heartbeat-s", type=float, default=1.0,
                   help="lease renewal + takeover-scan cadence")
    p.add_argument("--drain-deadline-s", type=float, default=0.0,
                   help="bounded graceful shutdown: a drain outliving this "
                        "journal-marks in-flight jobs INTERRUPTED "
                        "(resumable on restart) and exits NONZERO — a "
                        "wedged group thread can no longer hang shutdown "
                        "forever (0 = unbounded)")
    p.add_argument("--audit-rate", type=float, default=None, metavar="F",
                   help="sampled shadow verification for solve groups: "
                        "fraction of windows per merged batch re-solved on "
                        "the trusted host ladder and byte-compared (default: "
                        "env DACCORD_AUDIT_RATE or 1/64; 0 disables; native "
                        "groups never audit). Never changes output bytes")
    # front door (ISSUE 16)
    p.add_argument("--aot-cache", default=None, metavar="DIR",
                   help="fleet-shared AOT executable cache: jitted solve "
                        "groups load serialized compiled programs from (and "
                        "publish to) this shared-FS dir, so a freshly "
                        "spawned peer answers its first job warm instead of "
                        "paying the cold jit compile. Default: "
                        "$DACCORD_AOT_CACHE, else <peer-dir>/aotcache when "
                        "--peer-dir is set; 'off' disables")
    args = p.parse_args(argv)

    backend_explicit = args.backend != "auto"
    if args.backend == "native" and args.mesh > 1:
        raise SystemExit("--backend native solves on host C++; it cannot be "
                         "combined with --mesh (pick one)")
    if args.backend == "auto":
        from ..utils.obs import resolve_auto_backend

        args.backend = resolve_auto_backend(prefer_native=args.mesh <= 1)
    if args.backend in ("cpu", "native"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.paged and args.backend == "native":
        raise SystemExit("--paged is a JAX-ladder wire format; --backend "
                         "native solves dense rows on host (drop one flag)")
    if args.ladder == "split" and args.backend == "native":
        raise SystemExit("--ladder split is a JAX-ladder dispatch strategy; "
                         "--backend native escalates per window on host")
    from ..utils.obs import auto_batch_size, enable_compilation_cache

    enable_compilation_cache()
    if args.mesh > 1:
        from ..parallel.mesh import check_mesh_devices

        check_mesh_devices(args.mesh)
    if args.batch is None:
        # mesh-backed groups get N x the merged width per warm compile —
        # each device's slice keeps the single-device batch
        args.batch = auto_batch_size(args.backend == "native",
                                     args.backend if args.backend != "native"
                                     else None, mesh=args.mesh)
    from ..serve import AdmissionConfig, ConsensusService, ServeConfig
    from ..serve.http import start_server

    aot_dir = args.aot_cache or os.environ.get("DACCORD_AOT_CACHE")
    if not aot_dir and args.peer_dir:
        # fleet convention (ISSUE 16): the executable cache lives beside
        # the lease dir — every peer of a takeover group shares it
        aot_dir = os.path.join(args.peer_dir, "aotcache")
    if aot_dir in ("off", "none", "0"):
        aot_dir = None
    cfg = ServeConfig(
        workdir=args.workdir, backend=args.backend,
        backend_explicit=backend_explicit, batch=args.batch,
        workers=args.workers, ladder_mode=args.ladder, paged=args.paged,
        mesh=args.mesh,
        flush_lag_s=args.flush_lag_ms / 1000.0,
        idle_evict_s=args.idle_evict_s,
        metrics_snapshot_s=args.metrics_snapshot_s,
        slo_p99_s=args.slo_p99_s, slo_window_s=args.slo_window_s,
        journal=not args.no_journal,
        checkpoint_reads=args.checkpoint_reads,
        peer_dir=args.peer_dir, peer_name=args.peer_name,
        lease_ttl_s=args.lease_ttl_s, heartbeat_s=args.heartbeat_s,
        drain_deadline_s=args.drain_deadline_s, aot_dir=aot_dir,
        audit_rate=args.audit_rate,
        admission=AdmissionConfig(
            max_queued_jobs=args.max_queued,
            tenant_max_queued=args.tenant_max_queued,
            tenant_max_bytes=int(args.tenant_max_mb * 1024 * 1024),
            rss_soft_mb=args.rss_soft_mb, rss_hard_mb=args.rss_hard_mb),
        events_path=args.events)
    svc = ConsensusService(cfg)
    httpd, port, _t = start_server(svc, args.host, args.port)
    # router discovery (ISSUE 16): publish our URL as an announce lease
    # beside the job leases — no-op without --peer-dir
    svc.announce(f"http://{args.host}:{port}")
    if args.ready_file:
        from ..utils.aio import durable_write

        durable_write(args.ready_file,
                      lambda fh: json.dump({"port": port,
                                            "pid": os.getpid()}, fh),
                      mode="wt")
    print(json.dumps({"serving": f"http://{args.host}:{port}",
                      "backend": args.backend, "batch": args.batch,
                      "workdir": args.workdir}), file=sys.stderr)
    import signal

    def _stop(signum, frame):
        # graceful drain on SIGTERM/SIGINT: in-flight jobs finish, pools
        # drain, telemetry commits durably — the smoke's clean-shutdown
        # contract
        import threading

        threading.Thread(target=lambda: (svc.shutdown(drain=True),
                                         httpd.shutdown()),
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _stop)
    # SIGINT routes through the same graceful handler (a KeyboardInterrupt
    # can no longer surface once the handler is installed)
    signal.signal(signal.SIGINT, _stop)
    # serve_forever runs on the daemon thread; block until shutdown()
    _t.join()
    # bounded-drain contract (ISSUE 15 satellite): an unclean drain — a
    # wedged group thread outliving --drain-deadline-s, with its in-flight
    # jobs journal-marked INTERRUPTED — exits nonzero so supervisors
    # (systemd, the soak driver) know to restart-and-replay
    return 0 if getattr(svc, "clean", True) else 1


def router_main(argv=None) -> int:
    """daccord-router: stateless front door for a serve fleet (ISSUE 16) —
    discovers peers from the shared lease dir's announce leases, rendezvous-
    hashes tenants to warm-group-owning peers (stickiness), spills around
    shedding/red-burn owners, proxies submit/result/stream/abort with
    idempotency keys passing through, and (optionally) runs the SLO-burn
    autoscaler that spawns/reaps daccord-serve peers."""
    p = argparse.ArgumentParser(prog="daccord-router",
                                description=router_main.__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8946,
                   help="listen port (0 = ephemeral; pair with --ready-file)")
    p.add_argument("--workdir", required=True,
                   help="router state root: router.events.jsonl telemetry")
    p.add_argument("--peer-dir", required=True, metavar="DIR",
                   help="the serve fleet's shared lease root (the SAME dir "
                        "every daccord-serve --peer-dir points at): peers "
                        "are discovered from its announce leases")
    p.add_argument("--poll-s", type=float, default=1.0,
                   help="healthz poll + discovery sweep cadence")
    p.add_argument("--lease-ttl-s", type=float, default=15.0,
                   help="an announce lease older than this = peer down")
    p.add_argument("--spill-burn", type=float, default=1.0,
                   help="owner SLO burn >= this (red band) spills the "
                        "tenant to the least-loaded ready peer (0 = never "
                        "spill on burn)")
    p.add_argument("--proxy-timeout-s", type=float, default=600.0)
    p.add_argument("--healthz-timeout-s", type=float, default=5.0,
                   help="per-poll deadline — bounds the poll loop against "
                        "a hung peer socket")
    p.add_argument("--breaker-fails", type=int, default=3,
                   help="consecutive transport failures that open a "
                        "peer's circuit breaker")
    p.add_argument("--breaker-open-s", type=float, default=5.0,
                   help="breaker cooldown before a half-open probe")
    p.add_argument("--net-retries", type=int, default=2,
                   help="transient-class (reset/refused) retry budget per "
                        "proxied call; non-idempotent submits never retry")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="router events jsonl (router.* + scale.*; default "
                        "WORKDIR/router.events.jsonl)")
    p.add_argument("--ready-file", default=None, metavar="PATH",
                   help="write {port, pid} JSON here once bound")
    # SLO-burn autoscaler (off unless --autoscale-max > 0)
    p.add_argument("--autoscale-max", type=int, default=0, metavar="N",
                   help="enable the autoscaler with this fleet-size cap: "
                        "sustained fleet burn spawns daccord-serve peers "
                        "into --autoscale-root, idle spawned peers drain "
                        "after --autoscale-idle-s (0 = autoscaler off)")
    p.add_argument("--autoscale-min", type=int, default=1)
    p.add_argument("--autoscale-root", default=None, metavar="DIR",
                   help="workdir root for spawned peers (default "
                        "WORKDIR/peers)")
    p.add_argument("--autoscale-burn", type=float, default=1.0,
                   help="fleet burn (max over ready peers) >= this arms the "
                        "scale-out trigger")
    p.add_argument("--autoscale-sustain-s", type=float, default=5.0)
    p.add_argument("--autoscale-cooldown-s", type=float, default=30.0)
    p.add_argument("--autoscale-idle-s", type=float, default=120.0,
                   help="an idle spawned peer older than this drains "
                        "(graceful shutdown; 0 = never scale in)")
    p.add_argument("--autoscale-backend",
                   choices=("auto", "cpu", "tpu", "native"), default="native")
    p.add_argument("--autoscale-batch", type=int, default=64)
    p.add_argument("--autoscale-workers", type=int, default=2)
    p.add_argument("--autoscale-slo-p99-s", type=float, default=0.0,
                   help="forwarded to spawned peers so they report burn")
    p.add_argument("--autoscale-arg", action="append", default=[],
                   metavar="ARG", help="extra daccord-serve flag for "
                        "spawned peers (repeatable)")
    args = p.parse_args(argv)

    from ..serve.router import Router, RouterConfig, start_router

    rcfg = RouterConfig(workdir=args.workdir, peer_dir=args.peer_dir,
                        poll_s=args.poll_s, lease_ttl_s=args.lease_ttl_s,
                        spill_burn=args.spill_burn,
                        proxy_timeout_s=args.proxy_timeout_s,
                        healthz_timeout_s=args.healthz_timeout_s,
                        breaker_fails=args.breaker_fails,
                        breaker_open_s=args.breaker_open_s,
                        net_retries=args.net_retries,
                        events_path=args.events)
    router = Router(rcfg)
    if args.autoscale_max > 0:
        from ..serve.autoscale import AutoscaleConfig, Autoscaler

        acfg = AutoscaleConfig(
            peer_dir=args.peer_dir,
            root=args.autoscale_root or os.path.join(args.workdir, "peers"),
            max_peers=args.autoscale_max, min_peers=args.autoscale_min,
            spawn_burn=args.autoscale_burn,
            sustain_s=args.autoscale_sustain_s,
            cooldown_s=args.autoscale_cooldown_s,
            idle_ttl_s=args.autoscale_idle_s,
            backend=args.autoscale_backend, batch=args.autoscale_batch,
            workers=args.autoscale_workers,
            slo_p99_s=args.autoscale_slo_p99_s,
            extra_args=tuple(args.autoscale_arg))
        router.autoscaler = Autoscaler(acfg, router.log)
    httpd, port, _t = start_router(router, args.host, args.port)
    if args.ready_file:
        from ..utils.aio import durable_write

        durable_write(args.ready_file,
                      lambda fh: json.dump({"port": port,
                                            "pid": os.getpid()}, fh),
                      mode="wt")
    print(json.dumps({"routing": f"http://{args.host}:{port}",
                      "peer_dir": args.peer_dir,
                      "autoscale_max": args.autoscale_max}), file=sys.stderr)
    import signal

    def _stop(signum, frame):
        import threading

        threading.Thread(target=lambda: (router.shutdown(),
                                         httpd.shutdown()),
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    _t.join()
    return 0


def merge_main(argv=None) -> int:
    """daccord-merge: validating merge gate + crash-durable concatenation of
    shard FASTAs (reference merge step, minus its trust in whatever it finds):
    manifests are checked for presence, byte-range coverage, and read/base
    counts before the output commits via tmp+fsync+rename."""
    p = argparse.ArgumentParser(prog="daccord-merge", description=merge_main.__doc__)
    p.add_argument("outdir")
    p.add_argument("n", type=int, help="number of shards")
    p.add_argument("out_fasta")
    p.add_argument("--allow-degraded", action="store_true",
                   help="merge even when shards completed degraded/quarantined "
                        "— and skip shards with no output at all (poison-"
                        "quarantined by daccord-fleet) instead of refusing")
    args = p.parse_args(argv)
    from ..parallel.launch import MergeGateError, merge_shards

    try:
        n = merge_shards(args.outdir, args.n, args.out_fasta,
                         allow_degraded=args.allow_degraded)
    except MergeGateError as ex:
        raise SystemExit("daccord-merge: refusing to merge:\n  "
                         + "\n  ".join(ex.issues))
    from ..utils.obs import sha256_file

    print(f"merged {n} fragments sha256={sha256_file(args.out_fasta)}",
          file=sys.stderr)
    return 0


def fleet_main(argv=None) -> int:
    """daccord-fleet: run all N shards to completion under supervision — a
    bounded local worker pool plus shared-FS lease takeover for multi-host
    elasticity; crashed/hung workers are requeued with backoff, a shard that
    kills K consecutive workers is poison-quarantined while the rest of the
    fleet continues, and --merge ends in the validating merge gate."""
    p = argparse.ArgumentParser(prog="daccord-fleet", description=fleet_main.__doc__)
    p.add_argument("db")
    p.add_argument("las")
    p.add_argument("outdir")
    p.add_argument("-n", "--nshards", type=int, required=True)
    p.add_argument("--workers", type=int, default=2,
                   help="local worker subprocess slots")
    p.add_argument("--max-attempts", type=int, default=5,
                   help="worker spawns per shard before it is quarantined")
    p.add_argument("--poison-after", type=int, default=3,
                   help="consecutive worker failures that declare a shard poison")
    p.add_argument("--heartbeat", type=float, default=1.0, metavar="S",
                   help="lease mtime renewal period")
    p.add_argument("--lease-ttl", type=float, default=15.0, metavar="S",
                   help="a lease older than this is stale: any host may take "
                        "the shard over (must exceed a few heartbeats plus "
                        "shared-FS mtime lag and host clock skew)")
    p.add_argument("--stall-timeout", type=float, default=600.0, metavar="S",
                   help="a worker whose progress manifest has not moved for "
                        "this long is declared hung and requeued")
    p.add_argument("--speculate-factor", type=float, default=4.0,
                   help="re-execute a shard lagging the fleet median "
                        "throughput by this factor once slots are idle "
                        "(0 = off)")
    p.add_argument("--checkpoint-every", type=int, default=16,
                   help="worker checkpoint cadence (reads); progress "
                        "manifests also drive hang detection")
    p.add_argument("-b", "--batch", type=int, default=None)
    p.add_argument("--backend", choices=("auto", "cpu", "tpu", "native"),
                   default="auto")
    p.add_argument("--ingest-policy", choices=("strict", "quarantine", "off"),
                   default="strict")
    p.add_argument("--paged", choices=("on", "off", "auto"), default="off",
                   help="ragged paged window batching forwarded to every "
                        "worker (see daccord --paged)")
    p.add_argument("--mesh", type=int, default=0, metavar="N",
                   help="each worker shards its batches over the first N "
                        "local devices (see daccord --mesh): one host, N "
                        "chips is ONE worker — size --workers for the host's "
                        "device pool, and auto batch scales by N")
    p.add_argument("--max-pile-overlaps", type=int, default=None, metavar="N",
                   help="monster-pile budget forwarded to every worker (see "
                        "daccord --max-pile-overlaps); 0 disables")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="fleet events jsonl (spawn/heartbeat/takeover/retry/"
                        "poison/speculate/done; schema: tools/eventcheck.py). "
                        "Default: OUTDIR/fleet.events.jsonl")
    p.add_argument("--no-worker-telemetry", action="store_true",
                   help="do not thread per-worker telemetry sidecars "
                        "(shardNNNN.events.jsonl trace spans + "
                        "shardNNNN.ledger.jsonl outcome ledger) through the "
                        "workers — daccord-trace then sees the fleet file "
                        "only")
    p.add_argument("--merge", default=None, metavar="FASTA",
                   help="after the fleet finishes, run the validating merge "
                        "gate into this file")
    p.add_argument("--allow-degraded", action="store_true",
                   help="let --merge proceed over degraded/quarantined/"
                        "missing shards, and exit 0 even when shards were "
                        "poisoned")
    args = p.parse_args(argv)
    if args.backend == "native" and args.mesh > 1:
        # fail fast here like daccord/daccord-shard/daccord-serve do —
        # forwarded to workers, the pair would crash every spawn and surface
        # as a confusing multi-shard poison report instead of a config error
        raise SystemExit("--backend native solves on host C++; it cannot be "
                         "combined with --mesh (pick one)")
    from ..parallel.fleet import FleetConfig, run_fleet
    from ..parallel.launch import MergeGateError, merge_shards

    cfg = FleetConfig(nshards=args.nshards, workers=args.workers,
                      max_attempts=args.max_attempts,
                      poison_after=args.poison_after,
                      heartbeat_s=args.heartbeat, lease_ttl_s=args.lease_ttl,
                      stall_timeout_s=args.stall_timeout,
                      speculate_factor=args.speculate_factor,
                      checkpoint_every=args.checkpoint_every,
                      batch=args.batch, backend=args.backend,
                      ingest_policy=args.ingest_policy,
                      paged=args.paged, mesh=args.mesh,
                      max_pile_overlaps=args.max_pile_overlaps,
                      worker_telemetry=not args.no_worker_telemetry,
                      events_path=args.events if args.events is not None
                      else os.path.join(args.outdir, "fleet.events.jsonl"))
    manifest = run_fleet(args.db, args.las, args.outdir, cfg)
    print(json.dumps({k: manifest[k] for k in
                      ("nshards", "done", "poison", "degraded", "wall_s")}),
          file=sys.stderr)
    if args.merge:
        try:
            n = merge_shards(args.outdir, args.nshards, args.merge,
                             allow_degraded=args.allow_degraded)
        except MergeGateError as ex:
            raise SystemExit("daccord-fleet: merge gate refused:\n  "
                             + "\n  ".join(ex.issues))
        # merged-output digest into fleet.json (ISSUE 20): the integrity
        # chain's last durable link — daccord-audit re-verifies it offline
        from ..parallel.launch import _write_manifest_durable
        from ..utils.obs import sha256_file

        merged_sha = sha256_file(args.merge)
        fj = os.path.join(args.outdir, "fleet.json")
        try:
            with open(fj) as fh:
                fm = json.load(fh)
        except (OSError, ValueError):
            fm = None
        if fm is not None:
            fm["merged_fasta"] = args.merge
            fm["merged_fragments"] = n
            fm["merged_sha256"] = merged_sha
            _write_manifest_durable(fj, fm)
        print(f"merged {n} fragments -> {args.merge} sha256={merged_sha}",
              file=sys.stderr)
    return 0 if (not manifest["poison"] or args.allow_degraded) else 1


def fillfasta_main(argv=None) -> int:
    """fill-fasta: replace non-ACGT symbols with (seeded) random bases so the
    2-bit Dazzler DB can hold the reads (reference ``fillfasta`` role)."""
    p = argparse.ArgumentParser(prog="fill-fasta", description=fillfasta_main.__doc__)
    p.add_argument("fasta")
    p.add_argument("out", help="output FASTA ('-' = stdout)")
    p.add_argument("--seed", type=int, default=0, help="RNG seed for the fill bases")
    args = p.parse_args(argv)
    import numpy as np

    from ..formats.fasta import FastaRecord, read_fasta, write_fasta

    rng = np.random.default_rng(args.seed)
    acgt = np.frombuffer(b"ACGT", dtype=np.uint8)
    stats = {"reads": 0, "filled": 0}

    def fill():  # streamed: O(one read) memory at CHM-scale inputs
        for rec in read_fasta(args.fasta):
            s = np.frombuffer(rec.seq.upper().encode(), dtype=np.uint8).copy()
            bad = ~np.isin(s, acgt)
            nb = int(bad.sum())
            if nb:
                s[bad] = acgt[rng.integers(0, 4, size=nb)]
                stats["filled"] += nb
            stats["reads"] += 1
            yield FastaRecord(rec.name, s.tobytes().decode())

    write_fasta(sys.stdout if args.out == "-" else args.out, fill())
    print(f"filled {stats['filled']} non-ACGT symbols in {stats['reads']} reads",
          file=sys.stderr)
    return 0


def qveval_main(argv=None) -> int:
    """qv-eval: align corrected reads back to per-read truth and report the
    consensus Q-score (the BASELINE.md protocol: 'consensus aligned back to
    truth'; the paper's evaluation harness)."""
    p = argparse.ArgumentParser(prog="qv-eval", description=qveval_main.__doc__)
    p.add_argument("fasta", help="corrected FASTA (names 'read<ID>/<frag>')")
    p.add_argument("truth", help="sim truth .npz (genome/starts/ends/strands)")
    p.add_argument("--raw-db", default=None,
                   help="also score the uncorrected reads of this DB (raw Q)")
    p.add_argument("--json", default="-", help="write the JSON line here")
    args = p.parse_args(argv)
    import math

    import numpy as np

    from ..formats.fasta import read_fasta
    from ..oracle.align import edit_distance, infix_distance
    from ..utils.bases import revcomp_ints, seq_to_ints

    t = np.load(args.truth)
    genome, starts, ends, strands = t["genome"], t["starts"], t["ends"], t["strands"]

    def truth_of(rid: int) -> np.ndarray:
        tr = genome[starts[rid] : ends[rid]]
        return revcomp_ints(tr) if strands[rid] == 1 else tr

    tot_e = tot_l = 0
    n_frags = n_skipped = 0
    scored_rids = set()
    for rec in read_fasta(args.fasta):
        name = rec.name.split()[0]
        try:
            if not name.startswith("read"):
                raise ValueError(name)
            rid = int(name.removeprefix("read").split("/")[0])
            if not (0 <= rid < len(starts)):  # also rejects negative-index rids
                raise IndexError(rid)
            tr = truth_of(rid)
        except (ValueError, IndexError):
            n_skipped += 1
            continue
        f = seq_to_ints(rec.seq)
        tot_e += infix_distance(f, tr)
        tot_l += len(f)
        n_frags += 1
        scored_rids.add(rid)
    err = tot_e / tot_l if tot_l else float("nan")
    q = -10.0 * math.log10(max(err, 1e-9)) if tot_l else float("nan")
    line = {"fragments": n_frags, "skipped": n_skipped, "bases": tot_l,
            "errors": tot_e, "error_rate": round(err, 6), "qscore": round(q, 2)}

    if args.raw_db:
        db = read_db(args.raw_db)
        raw_e = raw_l = 0
        for rid in sorted(scored_rids):
            raw = db.read_bases(rid)
            raw_e += edit_distance(raw, truth_of(rid))
            raw_l += len(raw)  # same errors/len(sequence) convention as above
        raw_err = raw_e / raw_l if raw_l else float("nan")
        raw_q = -10.0 * math.log10(max(raw_err, 1e-9)) if raw_l else float("nan")
        line.update(raw_error_rate=round(raw_err, 6), raw_qscore=round(raw_q, 2),
                    delta_q=round(q - raw_q, 2))
    out = json.dumps(line)
    if args.json == "-":
        print(out)
    else:
        with open(args.json, "wt") as fh:
            fh.write(out + "\n")
        print(out, file=sys.stderr)
    return 0


_TOOLS = {
    "daccord": daccord_main,
    "shard": shard_main,
    "fleet": fleet_main,
    "serve": serve_main,
    "router": router_main,
    "merge": merge_main,
    "inqual": intrinsicqv_main,
    "repeats": detectrepeats_main,
    "filter": filteralignments_main,
    "filtersym": filtersym_main,
    "lassort": lassort_main,
    "lasmerge": lasmerge_main,
    "catrack": catrack_main,
    "lasindex": lasindex_main,
    "lasshow": lasshow_main,
    "lascheck": lascheck_main,
    "lassplit": lassplit_main,
    "dbstats": dbstats_main,
    "dbshow": dbshow_main,
    "fasta2db": fasta2db_main,
    "db2fasta": db2fasta_main,
    "dbsplit": dbsplit_main,
    "fillfasta": fillfasta_main,
    "qveval": qveval_main,
}


def _eventcheck_main(argv=None) -> int:
    from .eventcheck import eventcheck_main

    return eventcheck_main(argv)


def _trace_main(argv=None) -> int:
    from .trace import trace_main

    return trace_main(argv)


def _top_main(argv=None) -> int:
    from .top import top_main

    return top_main(argv)


def _sentinel_main(argv=None) -> int:
    from .sentinel import sentinel_main

    return sentinel_main(argv)


def _prof_main(argv=None) -> int:
    from .prof import prof_main

    return prof_main(argv)


_TOOLS["eventcheck"] = _eventcheck_main
_TOOLS["trace"] = _trace_main
_TOOLS["top"] = _top_main
_TOOLS["sentinel"] = _sentinel_main
_TOOLS["prof"] = _prof_main


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m daccord_tpu.tools.cli <tool> [args]\n"
              f"tools: {', '.join(_TOOLS)}")
        return 0
    tool = argv.pop(0)
    if tool not in _TOOLS:
        print(f"unknown tool {tool!r}; tools: {', '.join(_TOOLS)}", file=sys.stderr)
        return 2
    # every jit-compiling tool benefits; idempotent with the per-entry-point
    # calls (console scripts invoke *_main directly, bypassing this dispatcher)
    from ..utils.obs import enable_compilation_cache

    enable_compilation_cache()
    return _TOOLS[tool](argv)


if __name__ == "__main__":
    raise SystemExit(main())
