"""daccord-trace: merge per-worker telemetry, attribute wall clock, lint spans.

Every process stamps events with an absolute wall-clock ``ts`` next to its
process-relative ``t`` (``utils/obs.py``), so the per-worker sidecars of a
fleet run — the orchestrator's ``fleet.events.jsonl`` plus each worker's
``shardNNNN.events.jsonl`` — merge into ONE timeline here, the thing the
per-process relative clocks could never give (ParaFold's lesson: attributing
CPU pre/post stages vs device compute is what unlocks fleet-size scaling
decisions).

Three jobs:

- **Span lint** (``--check``): every ``span_open`` has a matching
  ``span_close`` (the pipeline/fleet ``finally`` unwinds guarantee this even
  on abort/failover paths), no double-opens, no orphan closes; plus the
  strict ``eventcheck`` schema lint, and per-shard ledger row-count
  reconciliation (rows deduped on aread+widx must equal the manifest's
  window count for non-resumed shards). Exit 1 on any violation — the
  tools_pounce.sh pre-chip gate.

- **Per-stage wall decomposition**: stage sums over span walls (feeder,
  dispatch, device.fetch, hp, flush, governor rungs, setup) per worker, with
  the device/host split reconciled against the run's own
  ``stats.device_s``/``host_s`` anchors in ``shard_done`` — the
  ``device.fetch`` span wraps exactly the region the ``device_s`` timer
  measures, so honest telemetry reconciles to well under 5%.

- **Fleet timeline** (and ``--probe-history``): milestone events on the
  merged absolute clock; ``--probe-history`` summarizes TUNNEL_LOG.jsonl
  (probe pass/fail runs, last-alive timestamp) so a ``fallback: true`` bench
  row is attributable to a dated tunnel death at a glance.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

#: span names per decomposition stage; device.fetch wraps exactly the
#: region stats.device_s times, setup = one-time pre-loop work
STAGES = (
    ("feeder", ("feeder",)),
    ("dispatch", ("dispatch",)),
    ("device.fetch", ("device.fetch",)),
    ("hp", ("hp",)),
    ("flush", ("flush",)),
    ("governor", ("governor.rung",)),
    ("setup", ("scan", "profile", "ladder.build", "paging.derive")),
    ("probe", ("probe",)),
)

#: merged-timeline milestone events (everything else is summarized, not
#: printed — a 100k-window run has far too many batch/window rows to list)
MILESTONES = frozenset({
    "fleet.init", "fleet.spawn", "fleet.takeover", "fleet.retry",
    "fleet.poison", "fleet.capacity", "fleet.speculate", "fleet.done",
    "fleet.finish", "fleet.demote", "fleet.fault",
    "shard_start", "shard_done", "sup_init", "sup_failover", "sup_failback",
    "sup_fault", "governor.classify", "governor.backpressure",
    "governor.monster", "ingest.quarantine", "ingest.fault",
    "bench_start", "bench_rung", "bench_done",
    # serving plane (ISSUE 10): job lifecycle + admission/shed decisions
    # are milestones; the per-batch serve.batch rows are summarized only
    "serve.start", "serve.job", "serve.admit", "serve.reject",
    "serve.commit", "serve.abort", "serve.shed", "serve.group",
    "serve.evict", "serve.done",
    # flight recorder (ISSUE 13): mesh topology changes, SLO burn band
    # changes, and profiler capture brackets are operator-grade milestones
    # (the per-snapshot mesh.device gauge rows are summarized only)
    "mesh.init", "mesh.shrink", "mesh.restore", "mesh.degrade",
    "serve.slo", "profile.capture",
    # crash-durable serve tier (ISSUE 15): recovery milestones — the
    # per-append serve.journal mirror rows are summarized only
    "serve.replay", "serve.takeover",
    # front door (ISSUE 16): routing/scale transitions are milestones
    # (the per-request router.route rows are summarized only, like
    # serve.batch); aot.publish/reject are the cache's rare, load-bearing
    # moments — hits and misses are summarized
    "router.start", "router.spill", "router.proxy_error",
    "router.peer_up", "router.peer_down", "router.done",
    "scale.burn", "scale.spawn", "scale.drain", "scale.reap",
    "serve.announce", "serve.evict_defer", "aot.publish", "aot.reject",
})


def _read_jsonl(path: str) -> list[dict]:
    out = []
    try:
        with open(path) as fh:
            for ln in fh:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue   # eventcheck reports malformed lines
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _segments(records: list[dict]) -> list[list[dict]]:
    """Split one file's records at ``shard_start`` boundaries (appended
    worker attempts / resumes restart the stream there). Files without
    shard_start (fleet sidecars, bench files) are one segment."""
    segs: list[list[dict]] = []
    cur: list[dict] = []
    for rec in records:
        if rec.get("event") == "shard_start" and cur:
            segs.append(cur)
            cur = []
        cur.append(rec)
    if cur:
        segs.append(cur)
    return segs


def check_spans(records: list[dict], src: str = "") -> tuple[list[str], dict]:
    """Span-pairing lint over one file's records.

    Returns ``(errors, stage_walls)`` where ``stage_walls`` maps span name →
    summed wall over the file's CLOSED spans. Pairing is validated per
    shard_start segment: every open must close (the telemetry bundle's
    ``finally`` unwind makes that hold even for aborted attempts — an
    unclosed span means lost telemetry, e.g. a SIGKILLed worker's unflushed
    buffer, and is flagged). Exception (ISSUE 15): a SUPERSEDED segment —
    one followed by a later shard_start — with unclosed spans is the
    expected signature of a killed attempt whose successor appended (fleet
    requeue, serve journal replay); only the FINAL segment's unclosed spans
    mean telemetry was lost from a run nothing recovered."""
    errs: list[str] = []
    walls: dict[str, float] = {}
    segs = _segments(records)
    for si, seg in enumerate(segs):
        open_spans: dict[str, str] = {}
        for rec in seg:
            ev = rec.get("event")
            if ev == "span_open":
                sid = str(rec.get("span"))
                if sid in open_spans:
                    errs.append(f"{src}: span {sid} opened twice")
                open_spans[sid] = str(rec.get("name"))
            elif ev == "span_close":
                sid = str(rec.get("span"))
                if sid not in open_spans:
                    errs.append(f"{src}: span_close {sid} "
                                f"({rec.get('name')}) without a matching "
                                "span_open")
                else:
                    open_spans.pop(sid)
                    w = rec.get("wall_s")
                    if isinstance(w, (int, float)):
                        name = str(rec.get("name"))
                        walls[name] = walls.get(name, 0.0) + float(w)
        if si == len(segs) - 1:
            for sid, name in open_spans.items():
                errs.append(f"{src}: span {sid} ({name}) never closed "
                            f"(segment {si}: telemetry lost mid-flight?)")
    return errs, walls


def decompose(records: list[dict], src: str = "") -> dict | None:
    """Per-stage wall decomposition of one worker file's LAST completed
    segment (the one whose shard_done carries the run's anchors). None when
    the file has no shard_done (fleet/bench sidecars)."""
    segs = _segments(records)
    for seg in reversed(segs):
        done = next((r for r in reversed(seg)
                     if r.get("event") == "shard_done"), None)
        if done is None:
            continue
        _, walls = check_spans(seg, src)
        sup = next((r for r in seg if r.get("event") == "sup_init"), None)
        inline = bool(sup.get("inline")) if sup else True
        stages = {label: round(sum(walls.get(n, 0.0) for n in names), 4)
                  for label, names in STAGES}
        run_wall = walls.get("run", float(done.get("wall_s") or 0.0))
        # the device side of the split: grouped fetches, plus governor-rung
        # solves when the engine is remote (inline engines run rungs on
        # host — the pipeline books them as host time too)
        device_sum = stages["device.fetch"] + (
            0.0 if inline else stages["governor"])
        accounted = sum(stages.values())
        return {"src": src, "wall_s": round(run_wall, 4),
                "device_s": done.get("device_s"),
                "host_s": done.get("host_s"),
                "stages": stages,
                # saturation profiler (ISSUE 14): the feeder bucket's
                # sub-stage decomposition + verdict come from the SAME
                # shard_done record daccord-prof reads, so the two tools
                # render one table (prof.stage_table is the one renderer)
                "feeder_stages": (done.get("stages")
                                  if isinstance(done.get("stages"), dict)
                                  else None),
                "feeder_threads": int(done.get("stage_threads") or 1),
                "verdict": done.get("verdict"),
                "device_sum": round(device_sum, 4),
                "host_sum": round(run_wall - device_sum, 4),
                "other": round(max(run_wall - accounted, 0.0), 4),
                "windows": done.get("windows"),
                "reads": done.get("reads"),
                "degraded": done.get("degraded")}
    return None


def reconcile(d: dict, tol_frac: float = 0.05,
              tol_abs: float = 0.05) -> list[str]:
    """Decomposition-vs-anchors check: the trace's device/host sums must
    agree with the run's own ``stats.device_s``/``host_s`` within
    ``tol_frac`` of the wall (floored at ``tol_abs`` seconds for near-zero
    device time, e.g. the native engine)."""
    issues = []
    tol = max(tol_frac * max(d["wall_s"], 1e-9), tol_abs)
    for key, mine in (("device_s", d["device_sum"]),
                      ("host_s", d["host_sum"])):
        anchor = d.get(key)
        if anchor is None:
            continue
        if abs(float(anchor) - mine) > tol:
            issues.append(f"{d['src']}: {key} decomposition off: span sum "
                          f"{mine:.3f}s vs stats {float(anchor):.3f}s "
                          f"(tolerance {tol:.3f}s)")
    return issues


def ledger_rows(path: str) -> tuple[int, int]:
    """(total rows, distinct windows) of a ledger sidecar — a resumed shard
    legitimately re-records the windows past its checkpoint, so the
    manifest reconciliation keys on the DEDUPED count. The optional ``job``
    field (serving plane, ISSUE 10) joins the dedupe key: two jobs over the
    same inputs legitimately record the same (aread, widx) twice in a
    merged/concatenated ledger and are distinct windows."""
    seen = set()
    total = 0
    for rec in _read_jsonl(path):
        if rec.get("event") != "window":
            continue
        total += 1
        seen.add((rec.get("job"), rec.get("aread"), rec.get("widx")))
    return total, len(seen)


def check_dir_ledgers(outdir: str) -> tuple[list[str], list[str]]:
    """(errors, report lines): per-shard ledger row counts vs manifest
    window counts. Resumed shards (manifest ``resumed_at_read``) can
    over-count in the MANIFEST (in-flight windows recount across the
    checkpoint), so only non-resumed shards are enforced."""
    errs: list[str] = []
    lines: list[str] = []
    for mpath in sorted(glob.glob(os.path.join(outdir, "shard*.json"))):
        if mpath.endswith("progress.json") or mpath.endswith("metrics.json"):
            continue
        try:
            with open(mpath) as fh:
                m = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(m, dict) or "windows" not in m:
            continue
        lpath = mpath[: -len(".json")] + ".ledger.jsonl"
        if not os.path.exists(lpath):
            continue
        total, distinct = ledger_rows(lpath)
        ok = distinct == m["windows"]
        resumed = "resumed_at_read" in m
        lines.append(f"  {os.path.basename(lpath)}: {distinct} windows "
                     f"({total} rows) vs manifest {m['windows']}"
                     + (" [resumed]" if resumed else "")
                     + ("" if ok or resumed else "  MISMATCH"))
        if not ok and not resumed:
            errs.append(f"{lpath}: ledger holds {distinct} distinct windows, "
                        f"manifest says {m['windows']}")
    return errs, lines


def _expand(paths: list[str]) -> tuple[list[str], list[str], list[str]]:
    """(event files, ledger files, dirs) from the argument list; a directory
    contributes its fleet + per-shard sidecars."""
    events, ledgers, dirs = [], [], []
    for p in paths:
        if os.path.isdir(p):
            dirs.append(p)
            events.extend(sorted(glob.glob(os.path.join(p, "*.events.jsonl"))))
            ledgers.extend(sorted(glob.glob(os.path.join(p, "*.ledger.jsonl"))))
        elif p.endswith("ledger.jsonl"):
            # covers shardNNNN.ledger.jsonl AND the serve tier's per-job
            # jobs/<id>/ledger.jsonl — a ledger linted as an event stream
            # would fail strict monotonicity on every appended resume
            ledgers.append(p)
        else:
            events.append(p)
    return events, ledgers, dirs


def _fmt_ts(ts: float) -> str:
    import time as _time

    return _time.strftime("%H:%M:%S", _time.localtime(ts))


def print_timeline(merged: list[tuple[float, str, dict]], out) -> None:
    """Milestone events on the merged absolute clock, offsets from t0."""
    rows = [(ts, src, rec) for ts, src, rec in merged
            if rec.get("event") in MILESTONES]
    if not rows:
        return
    t0 = rows[0][0]
    print(f"merged timeline ({len(rows)} milestones, "
          f"t0 {_fmt_ts(t0)}):", file=out)
    for ts, src, rec in rows:
        ev = rec.get("event")
        detail = {k: v for k, v in rec.items()
                  if k not in ("t", "ts", "event")}
        print(f"  +{ts - t0:9.3f}s  [{src}] {ev} "
              f"{json.dumps(detail, default=str)[:120]}", file=out)


def trace_main(argv=None) -> int:
    """daccord-trace: merge per-worker event files on absolute timestamps,
    validate span pairing, and print the fleet timeline + per-stage wall
    decomposition (reconciled against stats.device_s/host_s)."""
    p = argparse.ArgumentParser(prog="daccord-trace",
                                description=trace_main.__doc__)
    p.add_argument("paths", nargs="*",
                   help="event jsonl files, ledger sidecars, or run "
                        "directories (a directory contributes its "
                        "*.events.jsonl + *.ledger.jsonl + manifests)")
    p.add_argument("--check", action="store_true",
                   help="lint mode: strict eventcheck schema + span pairing "
                        "+ ledger/manifest reconciliation; exit 1 on any "
                        "violation")
    p.add_argument("--json", action="store_true",
                   help="emit the decomposition as one JSON line on stdout")
    p.add_argument("--no-timeline", action="store_true")
    p.add_argument("--probe-history", nargs="?", const="TUNNEL_LOG.jsonl",
                   default=None, metavar="LOG",
                   help="summarize a tunnel probe log (default "
                        "TUNNEL_LOG.jsonl): pass/fail runs and the "
                        "last-alive timestamp, so 'fallback: true' bench "
                        "rows are attributable at a glance")
    args = p.parse_args(argv)

    if args.probe_history is not None:
        return probe_history_main(args.probe_history)
    if not args.paths:
        p.error("no input files (or use --probe-history)")

    from .eventcheck import validate_events

    events, ledgers, dirs = _expand(args.paths)
    errors: list[str] = []
    out = sys.stderr

    # 1) schema lint (strict for event streams, shape-only for ledgers —
    # appended resume segments legitimately restart a ledger's clock)
    for path in events:
        errors.extend(f"{path}: {e}"
                      for e in validate_events(path, strict=True))
    for path in ledgers:
        errors.extend(f"{path}: {e}"
                      for e in validate_events(path, strict=False))

    # 2) span pairing + decomposition per file, merged timeline rows
    merged: list[tuple[float, str, dict]] = []
    decomps: list[dict] = []
    for path in events:
        recs = _read_jsonl(path)
        src = os.path.basename(path).replace(".events.jsonl", "")
        errs, _ = check_spans(recs, src)
        errors.extend(errs)
        d = decompose(recs, src)
        if d is not None:
            errors.extend(reconcile(d))
            decomps.append(d)
        for rec in recs:
            ts = rec.get("ts")
            if isinstance(ts, (int, float)):
                merged.append((float(ts), src, rec))
    merged.sort(key=lambda x: x[0])

    # 3) ledger reconciliation per run directory
    ledger_lines: list[str] = []
    for d_ in dirs:
        errs, lines = check_dir_ledgers(d_)
        errors.extend(errs)
        ledger_lines.extend(lines)

    if not args.no_timeline and not args.json:
        print_timeline(merged, out)
    if decomps and not args.json:
        from .prof import stage_table

        print("per-stage wall decomposition:", file=out)
        for d in decomps:
            dev = d.get("device_s")
            anchor = (f" [stats device {dev:.3f}s host {d['host_s']:.3f}s]"
                      if isinstance(dev, (int, float)) else "")
            print(f"  {d['src']}: wall {d['wall_s']:.3f}s = "
                  f"device {d['device_sum']:.3f}s + host "
                  f"{d['host_sum']:.3f}s{anchor}", file=out)
            for label, _names in STAGES:
                v = d["stages"][label]
                if v > 0:
                    print(f"      {label:<14} {v:9.3f}s", file=out)
                if label == "feeder" and d.get("feeder_stages"):
                    # ISSUE 14: the feeder is no longer one opaque host
                    # bucket — its sub-stage table (the saturation
                    # profiler's) renders through the SAME renderer
                    # daccord-prof uses, indented under the feeder line
                    ft = d.get("feeder_threads", 1)
                    if ft > 1:
                        print(f"        (sub-stages thread-summed over "
                              f"{ft} feeder threads)", file=out)
                    for ln in stage_table(d["feeder_stages"], v or None):
                        print("      " + ln, file=out)
            print(f"      {'other(host)':<14} {d['other']:9.3f}s", file=out)
            if d.get("verdict"):
                print(f"      verdict: {d['verdict']}", file=out)
    if ledger_lines and not args.json:
        print("outcome ledgers:", file=out)
        for ln in ledger_lines:
            print(ln, file=out)
    if args.json:
        print(json.dumps({"decomposition": decomps,
                          "errors": errors,
                          "milestones": sum(1 for _, _, r in merged
                                            if r.get("event") in MILESTONES)}))
    for e in errors[:40]:
        print(f"daccord-trace: {e}", file=out)
    if len(errors) > 40:
        print(f"daccord-trace: ... {len(errors) - 40} more", file=out)
    n_files = len(events) + len(ledgers)
    print(f"daccord-trace: {n_files} file(s), {len(merged)} records, "
          f"{len(decomps)} decomposition(s): "
          + ("OK" if not errors else f"{len(errors)} error(s)"), file=out)
    return 1 if (errors and args.check) else 0


def last_alive_info(path: str = "TUNNEL_LOG.jsonl") -> tuple[str | None, float | None]:
    """``(iso_ts, age_hours)`` of the most recent alive:true probe in a
    TUNNEL_LOG-style jsonl (None, None when the log has no alive record).
    The one staleness reader shared by ``--probe-history``, bench.py's
    startup echo, and the BENCH_* ``last_real_tpu_ts`` stamp — so a
    ``fallback: true`` rung is attributable to a dated tunnel death at a
    glance, from the sidecar alone."""
    import calendar
    import time as _time

    last = None
    for r in _read_jsonl(path):
        if r.get("alive"):
            last = str(r.get("ts", ""))
    if not last:
        return None, None
    try:
        t = calendar.timegm(_time.strptime(last, "%Y-%m-%dT%H:%M:%SZ"))
        return last, round((_time.time() - t) / 3600.0, 1)
    except ValueError:
        return last, None


def probe_history_main(path: str) -> int:
    """--probe-history: pass/fail runs over a TUNNEL_LOG-style jsonl."""
    recs = _read_jsonl(path)
    if not recs:
        print(f"daccord-trace: {path}: no probe records", file=sys.stderr)
        return 1
    runs: list[tuple[bool, int, str, str]] = []   # (alive, n, first, last)
    last_alive = None
    n_alive = 0
    for r in recs:
        alive = bool(r.get("alive"))
        ts = str(r.get("ts", "?"))
        if alive:
            last_alive = ts
            n_alive += 1
        if runs and runs[-1][0] == alive:
            a, n, first, _ = runs[-1]
            runs[-1] = (a, n + 1, first, ts)
        else:
            runs.append((alive, 1, ts, ts))
    print(f"{path}: {len(recs)} probes, {n_alive} alive / "
          f"{len(recs) - n_alive} dead")
    _, age_h = last_alive_info(path)
    print(f"  last alive: {last_alive or 'NEVER'}"
          + (f" ({age_h}h ago)" if age_h is not None else ""))
    cur = runs[-1]
    print(f"  current streak: {'ALIVE' if cur[0] else 'dead'} x{cur[1]} "
          f"(since {cur[2]})")
    print("  timeline (pass/fail runs):")
    for alive, n, first, last in runs:
        mark = "#" if alive else "."
        label = "alive" if alive else "dead"
        span = first if first == last else f"{first} .. {last}"
        print(f"    {mark * min(n, 40):<40} {label:>5} x{n:<4} {span}")
    # attributability hook: the most recent reasons help date a death
    tail = recs[-3:]
    for r in tail:
        print(f"  recent: {r.get('ts')} alive={r.get('alive')} "
              f"reason={r.get('reason', r.get('note', '?'))} "
              f"after={r.get('after', '-')}")
    return 0


if __name__ == "__main__":
    raise SystemExit(trace_main())
