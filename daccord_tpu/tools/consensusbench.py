"""Per-core consensus-engine bench: the north-star projection's anchor.

BASELINE.md's ≥20x north star is defined against a *64-thread reference
binary* that cannot be built (mount empty, BASELINE.md "published: {}").
Round 2 could only anchor the projection on the single-core numpy oracle
with an ASSUMED C++-over-numpy factor. This bench replaces the assumption
with a measurement: the native C++ window-consensus engine
(``dazz_native.cpp solve_windows``) implements the same full-graph tier
ladder as the reference's handleWindow (SURVEY.md §3.3), so its per-core
windows/s IS a measured stand-in for reference-class per-core speed on
identical inputs.

Reports, on one self-similar window population (cfg2-like shape):
  - native C++ engine: windows/s/core (1 thread; --threads N to probe scaling)
  - numpy oracle:      windows/s (subsampled; the executable spec)
  - implied factor and bases/s/core at adv bases emitted per window

Usage: ``python -m daccord_tpu.tools.consensusbench [--windows N] [--threads N]``
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from .ladderbench import _dataset

_SHAPE = dict(genome_len=20_000, coverage=30, read_len_mean=4_000, seed=61)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--windows", type=int, default=4096)
    ap.add_argument("--threads", default="1",
                    help="comma list of thread counts to run (e.g. 1,2,4)")
    ap.add_argument("--oracle-sample", type=int, default=128)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.kernels import BatchShape, tensorize_windows
    from daccord_tpu.native import available
    from daccord_tpu.native.api import solve_windows_native
    from daccord_tpu.oracle import cut_windows, refine_overlap
    from daccord_tpu.oracle.consensus import (ConsensusConfig,
                                              estimate_profile_two_pass,
                                              make_offset_likely)
    from daccord_tpu.oracle.dbg import DBGParams, window_consensus

    if not available():
        print(json.dumps({"error": "native library unavailable"}))
        return 1
    paths = _dataset("consbench", **_SHAPE)
    db = read_db(paths["db"])
    las = LasFile(paths["las"])
    ccfg = ConsensusConfig()
    windows = []
    refined = []
    for aread, pile in las.iter_piles():
        a = db.read_bases(aread)
        pile_refined = [refine_overlap(o, a, db.read_bases(o.bread),
                                       las.tspace)
                        for o in pile]
        refined.extend(pile_refined)
        windows.extend(cut_windows(a, pile_refined, w=ccfg.w, adv=ccfg.adv))
        if len(windows) >= args.windows:
            windows = windows[: args.windows]
            break
    prof = estimate_profile_two_pass(refined, windows[:48], ccfg, sample=24)
    ols = make_offset_likely(prof, ccfg)
    shape = BatchShape(depth=32, seg_len=64, wlen=ccfg.w)
    batch = tensorize_windows([(0, ws) for ws in windows], shape)

    row: dict = {"windows": len(windows), "adv": ccfg.adv,
                 "depth_cap": shape.depth}
    thread_list = [int(x) for x in args.threads.split(",")]
    base_wps = None
    for nt in thread_list:
        # warm one small run first so the .so build/page-in is outside timing
        solve_windows_native(batch_slice(batch, 64), ols, ccfg, n_threads=nt)
        t0 = time.perf_counter()
        out = solve_windows_native(batch, ols, ccfg, n_threads=nt)
        dt = time.perf_counter() - t0
        wps = len(windows) / dt
        row[f"native_wps_t{nt}"] = round(wps, 1)
        row[f"native_bases_per_s_t{nt}"] = round(wps * ccfg.adv, 1)
        if base_wps is None:
            base_wps = wps / nt   # per-thread rate of the first cell
            row["native_solve_rate"] = round(
                float(out["solved"].sum()) / len(windows), 4)

    n_or = min(args.oracle_sample, len(windows))
    t0 = time.perf_counter()
    solved = 0
    for ws in windows[:n_or]:
        segs = [np.asarray(s[: shape.seg_len], dtype=np.int8)
                for s in ws.segments[: shape.depth]]
        if len(segs) < ccfg.dbg.min_depth:
            continue
        for k, mc, emc in ccfg.tiers:
            p = DBGParams(**{**ccfg.dbg.__dict__, "k": k,
                             "min_count": mc, "edge_min_count": emc})
            if window_consensus(segs, ols[k], p, wlen=ccfg.w).seq is not None:
                solved += 1
                break
    dt = time.perf_counter() - t0
    row["oracle_wps"] = round(n_or / dt, 1)
    row["oracle_bases_per_s"] = round(n_or / dt * ccfg.adv, 1)
    row["native_over_oracle"] = round(base_wps / row["oracle_wps"], 1)
    print(json.dumps(row), flush=True)
    if args.out:
        with open(args.out, "at") as fh:
            fh.write(json.dumps(row) + "\n")
    return 0


def batch_slice(batch, n: int):
    """First-n-windows view of a WindowBatch (warmup helper)."""
    from ..kernels.tensorize import slice_batch

    return slice_batch(batch, 0, n)


if __name__ == "__main__":
    sys.exit(main())
