"""Measure the hp-rescue drain-pass cost on the DEVICE-ladder path.

Decision harness for the device-backend hp_rescue default (VERDICT r4 weak
#3): the native backend ships hp rescue ON (+2.0 Q clean control, +2.7 Q on
cfg2 — BASELINE.md r4), but the device paths kept it opt-in pending a
hardware overlap measurement that has been unrunnable for three rounds.
This measures the same decision without a chip:

  - the hp drain pass is HOST-side work (C++ via NativeLadder.hp_rescue)
    whose wall does not depend on which device produced the batch — the
    CPU-fallback pipeline exercises the identical drain code path
    (runtime/pipeline.py hp_pass), so its measured ``hp_wall_s`` transfers;
  - the worst-case NON-OVERLAPPED bound for a TPU run is therefore
    hp_wall_s / (projected_device_wall + hp_wall_s), with the projected
    device wall taken from the one measured TPU rate (windows / 14.8k w/s,
    BENCH_TPU_LAST.json r1) — worst case because the async pipeline
    (bounded in-flight deque) can overlap most of the drain behind device
    compute + tunnel RTT, and because the r1 rate predates the r3/r4 device
    optimizations.

Two regimes per the r4 decision-table method: the clean control (routing
cost only — a max-run scan plus a handful of routed windows) and the hp
stress regime (hp_indel_slope=1.0, the most windows routed). One JSON line
per regime.

Run: ``python -m daccord_tpu.tools.hpdrainbench [--batch 512] [--out F]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

# measured once on the real chip (r1): 14.8k windows/s/chip end to end.
# The honest anchor for "how long would the device side of this run take".
TPU_WINDOWS_PER_SEC = 14_800.0


def run_regime(name: str, sim_kw: dict, batch: int, tmp: str) -> dict:
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.runtime.pipeline import PipelineConfig, correct_to_fasta
    from daccord_tpu.sim import SimConfig, make_dataset

    d = os.path.join(tmp, name)
    out = make_dataset(d, SimConfig(**sim_kw), name=name)
    # ON arm only: the decision quantity is the per-window drain cost h
    # (host wall / window); the worst-case non-overlapped TPU fraction is
    # h / (1/r + h) with r the measured TPU window rate, independent of
    # dataset size — an off arm would only re-measure the device ladder
    ccfg = ConsensusConfig(hp_rescue=True)
    pcfg = PipelineConfig(batch_size=batch, consensus=ccfg, hp_native=True)
    t0 = time.time()
    st = correct_to_fasta(out["db"], out["las"],
                          os.path.join(d, "on.fasta"), pcfg)
    wall = time.time() - t0
    h = st.hp_wall_s / max(st.n_windows, 1)
    bound = h / (1.0 / TPU_WINDOWS_PER_SEC + h)
    line = {
        "regime": name, "batch": batch,
        "windows": st.n_windows, "hp_rescued": st.n_hp_rescued,
        "hp_wall_s": round(st.hp_wall_s, 3),
        "cpu_pipe_wall_s": round(st.wall_s, 2),
        "cpu_total_wall_s": round(wall, 2),
        "cpu_hp_fraction": round(st.hp_wall_s / st.wall_s, 4)
        if st.wall_s else 0.0,
        "hp_wall_per_window_us": round(1e6 * h, 2),
        "tpu_worst_case_nonoverlap_fraction": round(bound, 4),
    }
    print(json.dumps(line))
    return line


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=512,
                   help="production CPU batch size (tpu default is 2048; "
                        "hp cost scales with windows, not batch shape)")
    p.add_argument("--out", default=None, help="also append JSON lines here")
    p.add_argument("--keep", action="store_true")
    args = p.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")   # drain cost is host-side;
    # the device ladder itself runs wherever — cpu keeps this chip-free
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()

    regimes = {
        # cfg2's error model / pile depth at 2/5 genome scale (per-window
        # drain cost is size-independent; only the routing MIX matters)
        "clean_cfg2": dict(genome_len=20_000, coverage=100,
                           read_len_mean=8_000, seed=12),
        # same shape under the hp stress knob: worst-case routing volume
        "hp_cfg2": dict(genome_len=20_000, coverage=100, read_len_mean=8_000,
                        hp_indel_slope=1.0, seed=12),
    }
    tmp = tempfile.mkdtemp(prefix="hpdrain_") if not args.keep else "/tmp/hpdrain"
    lines = []
    for name, kw in regimes.items():
        lines.append(run_regime(name, kw, args.batch, tmp))
    if args.out:
        with open(args.out, "a") as fh:
            for ln in lines:
                fh.write(json.dumps(ln) + "\n")
    if not args.keep:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
