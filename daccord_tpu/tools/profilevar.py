"""Estimator-variance probe: Q sensitivity to the profile sample size.

The error-profile pass samples ``profile_sample_piles`` piles strided across
the shard (``runtime/pipeline.py _strided_pile_ranges``) and 32 windows per
pile for the second pass (single-read rates vs a sample consensus). The
production default is 4 piles — a thin sample whose variance had never been
measured (VERDICT r2 weak #4). This probe runs the full pipeline with the
profile estimated from

  - sample sizes ``--piles`` (default 2,4,16,48), and
  - for the default size, several disjoint sample offsets
    (``profile_sample_offset``) — the across-sample variance at the default,

and reports consensus Q per cell. Decision rule (VERDICT r2 item 8): if the
spread at the default is <= 0.1 Q, 4 piles is documented sufficient; otherwise
the default rises.

Usage: ``python -m daccord_tpu.tools.profilevar [--piles 2,4,16] [--offsets 3]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .ladderbench import _dataset, _qveval

_SHAPE = dict(genome_len=25_000, coverage=35, read_len_mean=4_000, seed=81)


def run_cell(paths: dict, n_piles: int, offset: int) -> dict:
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import (PipelineConfig, correct_to_fasta,
                                              estimate_profile_for_shard)

    # the verdict governs the PRODUCTION configuration, so the probe solves
    # with the production (top-M-capped) ladder semantics, not the native
    # full-graph engine: the capped ladder could be more profile-sensitive
    # (tables interact with which k-mers survive the cap), and a verdict
    # measured under a different engine could lock in an undersized default
    cfg = PipelineConfig(profile_sample_piles=n_piles,
                         profile_sample_offset=offset)
    t0 = time.perf_counter()
    prof = estimate_profile_for_shard(read_db(paths["db"]),
                                      LasFile(paths["las"]), cfg)
    est_s = time.perf_counter() - t0
    out_fa = os.path.join(os.path.dirname(paths["db"]),
                          f"pv_{n_piles}_{offset}.fasta")
    stats = correct_to_fasta(paths["db"], paths["las"], out_fa, cfg,
                             profile=prof)
    q = _qveval(out_fa, paths["truth"], None)
    return {"piles": n_piles, "offset": offset,
            "p_ins": round(prof.p_ins, 4), "p_del": round(prof.p_del, 4),
            "p_sub": round(prof.p_sub, 4), "est_s": round(est_s, 1),
            "q": q.get("qscore"), "errors": q.get("errors"),
            "solve": round(stats.n_solved / max(stats.n_windows, 1), 4)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--piles", default="2,4,16,48")
    ap.add_argument("--offsets", type=int, default=3,
                    help="disjoint sample offsets probed at the --probe-size")
    ap.add_argument("--probe-size", type=int, default=None,
                    help="sample size whose across-sample spread decides the "
                         "verdict (default: PipelineConfig's production "
                         "default)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.probe_size is None:
        from daccord_tpu.runtime.pipeline import PipelineConfig

        args.probe_size = PipelineConfig().profile_sample_piles
    import jax

    jax.config.update("jax_platforms", "cpu")   # Q is backend-independent
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()
    paths = _dataset("profilevar", **_SHAPE)
    rows = []
    sizes = [int(x) for x in args.piles.split(",")]
    if args.probe_size not in sizes:
        sizes.append(args.probe_size)
    for sp in sizes:
        n_off = args.offsets if sp == args.probe_size else 1
        for off in range(n_off):
            row = run_cell(paths, sp, off)
            rows.append(row)
            print(json.dumps(row), flush=True)
            if args.out:
                with open(args.out, "at") as fh:
                    fh.write(json.dumps(row) + "\n")
    qs = [r["q"] for r in rows
          if r["piles"] == args.probe_size and r["q"] is not None]
    if len(qs) > 1:
        spread = max(qs) - min(qs)
        v = (f"{args.probe_size} piles sufficient" if spread <= 0.1
             else "raise profile_sample_piles")
        print(json.dumps({"probe_size": args.probe_size,
                          "probe_size_q_spread": round(spread, 3),
                          "verdict": v}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
