"""hp-rescue routing-threshold sweep on the mismatchbench hp regime.

One-off decision tool for the r4 default: reuses the cached ``mm_hp``
dataset + a single estimation pass, then runs ``correct_to_fasta`` arms over
(hp_err, hp_min_run) and prints Q / errors / rescued / wall per arm.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--arms", default="0.18:3,0.12:3,0.12:2,0.25:3",
                    help="hp_err:hp_min_run[:vote] per arm; vote in "
                         "{median, posterior} (default median). The "
                         "posterior arm runs the python host pass "
                         "(hp_native off) — the C++ engine implements "
                         "median only until the vote decision lands")
    ap.add_argument("--regime", default="hp")
    ap.add_argument("--accept", default="rescore",
                    choices=("rescore", "likelihood"),
                    help="acceptance objective for ALL arms (hp_accept); "
                         "non-rescore arms run the python host pass")
    ap.add_argument("--lambda-c", type=float, default=None,
                    help="hp_lambda_c override for the likelihood arm")
    args = ap.parse_args(argv)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()
    import os

    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.runtime.pipeline import (PipelineConfig, correct_to_fasta,
                                              estimate_profile_for_shard)
    from daccord_tpu.tools.ladderbench import _dataset, _qveval
    from daccord_tpu.tools.mismatchbench import REGIMES

    paths = _dataset(f"mm_{args.regime}", **REGIMES[args.regime])
    d = os.path.dirname(paths["db"])
    prof = estimate_profile_for_shard(read_db(paths["db"]),
                                      LasFile(paths["las"]), PipelineConfig())
    for arm in args.arms.split(","):
        parts = arm.split(":")
        he, hmr = parts[0], parts[1]
        vote = parts[2] if len(parts) > 2 else "median"
        kw = dict(hp_rescue=True, hp_err=float(he), hp_min_run=int(hmr),
                  hp_vote=vote, hp_accept=args.accept)
        if args.lambda_c is not None:
            kw["hp_lambda_c"] = args.lambda_c
        ccfg = ConsensusConfig(**kw)
        # every vote/acceptance combination runs in the C++ engine now
        # (byte-identical by test); --no-native would be the parity lever
        cfg = PipelineConfig(consensus=ccfg)
        out_fa = os.path.join(
            d, f"corr_hp_{he}_{hmr}_{vote}_{args.accept}.fasta")
        t0 = time.perf_counter()
        stats = correct_to_fasta(paths["db"], paths["las"], out_fa, cfg,
                                 profile=prof)
        q = _qveval(out_fa, paths["truth"], None)
        print(json.dumps({"hp_err": float(he), "hp_min_run": int(hmr),
                          "vote": vote,
                          "q": q.get("qscore"), "errors": q.get("errors"),
                          "solve": round(stats.n_solved
                                         / max(stats.n_windows, 1), 4),
                          "rescued": stats.n_hp_rescued,
                          "wall_s": round(time.perf_counter() - t0, 1)}),
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
