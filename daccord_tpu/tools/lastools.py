"""Preprocessing tool logic: intrinsic QV, repeat detection, alignment filtering.

Equivalents of the reference tools (SURVEY.md §2.1, §3.2, §3.4; reference
file:line citations pending backfill — mount empty, SURVEY.md §0):

- ``computeintrinsicqv``  -> :func:`compute_intrinsic_qv`  (writes track
  ``inqual``: one QV byte per tspace tile per read)
- ``lasdetectsimplerepeats`` -> :func:`detect_repeats` (writes interval track
  ``rep``: int64 start/end pairs per read)
- ``lasfilteralignments`` -> :func:`filter_alignments` (drops alignments whose
  error profile is inconsistent with the unique-region profile)
- ``filtersym`` -> :func:`filter_symmetric` (keep A->B iff B->A kept)

These are cheap single-pass streaming passes over LAS piles (the reference
runs them as separate processes composed via the filesystem; kept that way —
each is independently restartable, which is the checkpoint/resume model of
SURVEY.md §5).

QV convention: ``qv = clip(round(200 * rate), 0, 250)`` where ``rate`` is the
per-tile error rate of the depth-d quantile alignment; 251..255 reserved
(255 = no coverage). Downstream consumers in this framework use the same
convention, making the pipeline self-consistent.
"""

from __future__ import annotations

import numpy as np

from ..formats.dazzdb import DazzDB, read_track, write_track
from ..formats.las import LasFile, Overlap, write_las

QV_NOCOV = 255
QV_SCALE = 200.0


def _native_ok() -> bool:
    """True when the C++ host library is importable and built. Only the
    import is guarded — bugs inside the native-path math must propagate, not
    silently degrade to the slow fallback."""
    try:
        from ..native import available
    except Exception:
        return False
    return available()


def _pile_tile_rates(db: DazzDB, aread: int, pile: list[Overlap], tspace: int):
    """Per-tile lists of alignment error rates for one A read."""
    rlen = db.read_length(aread)
    ntiles = (rlen + tspace - 1) // tspace
    rates: list[list[float]] = [[] for _ in range(ntiles)]
    for o in pile:
        bounds = o.tile_bounds(tspace)
        for t in range(len(bounds) - 1):
            a0, a1 = int(bounds[t]), int(bounds[t + 1])
            tl = a1 - a0
            if tl <= 0:
                continue
            g = a0 // tspace
            # pair diffs count both reads' errors; halve for a per-read rate
            rates[g].append(0.5 * float(o.trace[t, 0]) / tl)
    return rates


def _read_lengths(db: DazzDB, lo: int = 0, hi: int | None = None) -> np.ndarray:
    hi = db.nreads if hi is None else hi
    return np.fromiter((db.reads[i].rlen for i in range(lo, hi)), np.int64, hi - lo)


def _tile_table(db: DazzDB, tspace: int, lo: int = 0, hi: int | None = None) -> np.ndarray:
    """Tile offsets over reads [lo, hi): tile_base[i] .. tile_base[i+1] are
    read lo+i's tiles. Block jobs pass their read range so every flat array
    downstream is O(block), not O(whole DB)."""
    ntiles = (_read_lengths(db, lo, hi) + tspace - 1) // tspace
    tile_base = np.zeros(len(ntiles) + 1, np.int64)
    np.cumsum(ntiles, out=tile_base[1:])
    return tile_base


def _block_range(db: DazzDB, las: LasFile, block: int | None) -> tuple[int, int, int | None, int | None]:
    """(lo, hi, byte_start, byte_end) for DB block ``block`` (1-based);
    ``block=None`` means the whole run (all reads, full file)."""
    if block is None:
        return 0, db.nreads, None, None
    from ..formats.dazzdb import db_blocks
    from ..formats.las import range_for_areads

    blocks = db_blocks(db.path)
    if not (1 <= block <= len(blocks)):
        raise ValueError(f"block {block}: DB has {len(blocks)} blocks")
    lo, hi = blocks[block - 1]
    start, end = range_for_areads(las.path, lo, hi)
    return lo, hi, start, end


def _intrinsic_qv_native(db: DazzDB, las: LasFile, depth: int,
                         rlo: int = 0, rhi: int | None = None,
                         byte_range=(None, None)) -> list[np.ndarray]:
    """Vectorized QV pass over the native columnar LAS load (SURVEY.md §2.4:
    the streaming path rides C++ + numpy vector math, not per-record Python).
    Bit-identical to the per-pile fallback below (parity-tested). All flat
    arrays cover only reads [rlo, rhi) so block jobs stay O(block)."""
    from ..native.api import ColumnarLas

    rhi = db.nreads if rhi is None else rhi
    col = ColumnarLas(las.path, *byte_range)
    tspace = col.tspace
    tile_base = _tile_table(db, tspace, rlo, rhi)
    qv_flat = np.full(int(tile_base[-1]), QV_NOCOV, dtype=np.uint8)

    if col.novl:
        T = (np.diff(col.trace_off) // 2).astype(np.int64)   # tiles per overlap
        n = col.novl
        total = int(T.sum())
        ov = np.repeat(np.arange(n), T)
        starts = np.zeros(n + 1, np.int64)
        np.cumsum(T, out=starts[1:])
        tloc = np.arange(total, dtype=np.int64) - np.repeat(starts[:-1], T)
        g = col.abpos.astype(np.int64)[ov] // tspace + tloc  # per-read tile id
        lo = np.maximum(col.abpos[ov], g * tspace)
        hi = np.minimum(col.aepos[ov], (g + 1) * tspace)
        tl = hi - lo
        dif = col.trace_flat[np.repeat(col.trace_off[:-1], T) + 2 * tloc]
        ok = tl > 0
        gid = (tile_base[col.aread.astype(np.int64)[ov] - rlo] + g)[ok]
        # same expression shape as the fallback: (0.5 * diff) / tile_len
        rate = 0.5 * dif[ok].astype(np.float64) / tl[ok]
        order = np.lexsort((rate, gid))
        gid_s, rate_s = gid[order], rate[order]
        uniq, gstart, gcount = np.unique(gid_s, return_index=True, return_counts=True)
        sel = gstart + np.minimum(max(depth // 2, 1), gcount) - 1
        q = np.minimum(np.round(QV_SCALE * rate_s[sel]), 250).astype(np.uint8)
        qv_flat[uniq] = q
    return [qv_flat[tile_base[i] : tile_base[i + 1]] for i in range(rhi - rlo)]


def compute_intrinsic_qv(db: DazzDB, las: LasFile, depth: int = 20,
                         track: str = "inqual", use_native: bool = True,
                         block: int | None = None) -> list[np.ndarray]:
    """Per-read per-tile intrinsic QVs from pile error statistics.

    The depth-d quantile (d-th lowest rate) is robust to repeat-induced piles:
    repeats inflate coverage with *worse* alignments, leaving the best d
    mostly intact (reference ``computeintrinsicqv -d``).

    With ``block``, only that DB block's reads are processed (via the LAS
    aread-range byte index) and a per-block track is written; merge the block
    tracks with :func:`daccord_tpu.formats.dazzdb.catrack`.
    """
    tspace = las.tspace
    lo, hi, start, end = _block_range(db, las, block)
    payloads: list[np.ndarray] | None = None
    if use_native and _native_ok():
        payloads = _intrinsic_qv_native(db, las, depth, lo, hi, byte_range=(start, end))
    if payloads is None:
        payloads = [np.zeros(0, dtype=np.uint8)] * (hi - lo)
        for aread, pile in las.iter_piles(start, end):
            rates = _pile_tile_rates(db, aread, pile, tspace)
            qv = np.full(len(rates), QV_NOCOV, dtype=np.uint8)
            for t, rl in enumerate(rates):
                if not rl:
                    continue
                rl = sorted(rl)
                q = rl[min(max(depth // 2, 1), len(rl)) - 1]
                qv[t] = min(int(round(QV_SCALE * q)), 250)
            payloads[aread - lo] = qv
        # reads with no pile get all-NOCOV tracks of the right length
        for i in range(hi - lo):
            if len(payloads[i]) == 0:
                nt = (db.read_length(lo + i) + tspace - 1) // tspace
                payloads[i] = np.full(nt, QV_NOCOV, dtype=np.uint8)
    write_track(db.path, track, payloads, block=block)
    return payloads


def _tile_coverage_native(db: DazzDB, las: LasFile, rlo: int = 0, rhi: int | None = None,
                          byte_range=(None, None)) -> tuple[np.ndarray, np.ndarray]:
    """(tile_base, cov_flat): per-tile alignment coverage over reads
    [rlo, rhi) via the native columnar load + a difference-array sweep (no
    per-record Python). Interval deltas cancel within each read, so one
    global cumsum yields every read's coverage."""
    from ..native.api import ColumnarLas

    rhi = db.nreads if rhi is None else rhi
    col = ColumnarLas(las.path, *byte_range)
    tspace = col.tspace
    tile_base = _tile_table(db, tspace, rlo, rhi)
    delta = np.zeros(int(tile_base[-1]) + 1, dtype=np.int64)
    if col.novl:
        ar = col.aread.astype(np.int64) - rlo
        g0 = col.abpos.astype(np.int64) // tspace
        g1 = np.maximum(col.aepos.astype(np.int64) - 1, col.abpos) // tspace
        np.add.at(delta, tile_base[ar] + g0, 1)
        np.add.at(delta, tile_base[ar] + g1 + 1, -1)
    return tile_base, np.cumsum(delta[:-1])


def _load_qv_gate(db: DazzDB, qv_track: str | None, qv_max: int,
                  lo: int, hi: int, tspace: int, block: int | None = None):
    """Per-read boolean tile masks from an intrinsic-QV track: True = the
    tile is trustworthy enough to repeat-annotate. None when the track is
    absent/disabled or its tile geometry doesn't match ``tspace``. In block
    mode the per-block track (from ``inqual --block``) is preferred, falling
    back to the merged whole-DB track."""
    if not qv_track:
        return None
    qv, base = None, 0
    if block is not None:
        try:
            qv, base = read_track(db.path, qv_track, block=block), lo
        except (FileNotFoundError, OSError):
            qv = None
    if qv is None:
        try:
            qv = read_track(db.path, qv_track)
        except (FileNotFoundError, OSError):
            return None
    gates = []
    for i in range(lo, hi):
        j = i - base
        q = qv[j] if 0 <= j < len(qv) else np.zeros(0, np.uint8)
        nt = (db.read_length(i) + tspace - 1) // tspace
        if len(q) != nt:   # track written under a different tspace
            return None
        gates.append(q <= qv_max)   # QV_NOCOV (255) masks automatically
    return gates


def _grow_intervals(iv: np.ndarray, grow_bases: int, rlen: int) -> np.ndarray:
    """Dilate [n,2] intervals by ``grow_bases`` on each side and merge.

    Coverage decays toward a repeat copy's edges (shorter overlaps don't
    qualify there), so thresholded tiles under-call the interval by a tile
    or two per side; an alignment confined to the repeat then shows a fake
    "unique" overhang that defeats the span test in ``filter_alignments``.
    """
    if len(iv) == 0 or grow_bases <= 0:
        return iv
    lo = np.maximum(iv[:, 0] - grow_bases, 0)
    hi = np.minimum(iv[:, 1] + grow_bases, rlen)
    out = [[int(lo[0]), int(hi[0])]]
    for s, e in zip(lo[1:], hi[1:]):
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], int(e))
        else:
            out.append([int(s), int(e)])
    return np.asarray(out, dtype=np.int64)


def detect_repeats(db: DazzDB, las: LasFile, depth: int = 20,
                   cov_factor: float = 2.0, track: str = "rep",
                   use_native: bool = True, block: int | None = None,
                   qv_track: str | None = "inqual",
                   qv_max: int = 100, grow: int = 2) -> list[np.ndarray]:
    """Detect simple-repeat intervals from pile over-coverage.

    A tile whose alignment coverage exceeds ``cov_factor * depth`` is repeat-
    annotated; adjacent repeat tiles merge into intervals (int64 start/end
    pairs per read, written as track ``rep``), dilated by ``grow`` tiles per
    side (see :func:`_grow_intervals` — undoes the edge erosion of tile-
    granular thresholding).

    When the intrinsic-QV track is available (reference: the tool consumes
    ``computeintrinsicqv`` output, SURVEY.md §2.1/§3.4), tiles whose QV is
    worse than ``qv_max`` are excluded: over-coverage on a tile where even the
    depth-d best alignment is junk is a low-quality pile-up, not a simple
    repeat — annotating it would knock real alignments out downstream in
    ``filter_alignments``. A missing/mismatched track degrades gracefully to
    coverage-only detection.

    With ``block``, processes only that DB block (per-block track; merge with
    ``catrack``) — the reference's per-block cluster workflow.
    """
    tspace = las.tspace
    lo, hi, start, end = _block_range(db, las, block)
    qv_gate = _load_qv_gate(db, qv_track, qv_max, lo, hi, tspace, block)
    payloads: list[np.ndarray] | None = None
    if use_native and _native_ok():
        tile_base, cov_flat = _tile_coverage_native(db, las, lo, hi,
                                                    byte_range=(start, end))
        hot_flat = cov_flat > cov_factor * depth
        if qv_gate:
            # empty gate list == no reads in range: nothing to mask
            hot_flat &= np.concatenate(qv_gate)
        # global run extraction: a zero separator at every read boundary
        # keeps runs from merging across reads; one diff finds all runs
        seps = tile_base[1:-1]
        ext = np.insert(hot_flat.astype(np.int8), seps, 0)
        d = np.diff(np.concatenate([[0], ext, [0]]))
        p0 = np.nonzero(d == 1)[0]          # run starts, separator space
        p1 = np.nonzero(d == -1)[0]         # run ends (exclusive)
        # map back: subtract the number of separators inserted before p
        sep_pos = seps + np.arange(len(seps))   # separator indices in ext
        t0 = p0 - np.searchsorted(sep_pos, p0)
        t1 = p1 - np.searchsorted(sep_pos, p1)
        rid = np.searchsorted(tile_base, t0, side="right") - 1  # block-local ids
        rlens = _read_lengths(db, lo, hi)
        iv = np.empty((len(t0), 2), dtype=np.int64)
        iv[:, 0] = (t0 - tile_base[rid]) * tspace
        iv[:, 1] = np.minimum((t1 - tile_base[rid]) * tspace, rlens[rid])
        counts = np.bincount(rid, minlength=hi - lo)
        splits = np.split(iv, np.cumsum(counts)[:-1])
        payloads = [np.ascontiguousarray(
                        _grow_intervals(s, grow * tspace, int(rlens[i]))
                    ).reshape(-1).view(np.uint8)
                    for i, s in enumerate(splits)]
    if payloads is None:
        payloads = [np.zeros(0, dtype=np.uint8)] * (hi - lo)
        for aread, pile in las.iter_piles(start, end):
            rlen = db.read_length(aread)
            ntiles = (rlen + tspace - 1) // tspace
            cov = np.zeros(ntiles, dtype=np.int64)
            for o in pile:
                g0 = o.abpos // tspace
                g1 = (max(o.aepos - 1, o.abpos)) // tspace
                cov[g0 : g1 + 1] += 1
            hot = cov > cov_factor * depth
            if qv_gate is not None:
                hot &= qv_gate[aread - lo]
            ivals: list[int] = []
            t = 0
            while t < ntiles:
                if hot[t]:
                    t0 = t
                    while t < ntiles and hot[t]:
                        t += 1
                    ivals.extend([t0 * tspace, min(t * tspace, rlen)])
                else:
                    t += 1
            iv = np.asarray(ivals, dtype=np.int64).reshape(-1, 2)
            payloads[aread - lo] = np.ascontiguousarray(
                _grow_intervals(iv, grow * tspace, rlen)).reshape(-1).view(np.uint8)
    write_track(db.path, track, payloads, block=block)
    return payloads


def read_repeat_track(db: DazzDB, track: str = "rep") -> list[np.ndarray]:
    """Interval track back as [n, 2] int64 arrays."""
    raw = read_track(db.path, track)
    return [r.view(np.int64).reshape(-1, 2) if len(r) else np.zeros((0, 2), dtype=np.int64)
            for r in raw]


_MBINS = 1 << 20   # rate-histogram resolution for the streaming exact median


def _rate_bins(r: np.ndarray) -> np.ndarray:
    # rates live in [0, ~0.5]; anything >= 1 (pathological traces) shares the
    # overflow bin. Binning is pure float64 multiply+floor, so every pass
    # maps a given record to the same bin deterministically.
    return np.minimum((r * _MBINS).astype(np.int64), _MBINS)


class _StreamMedian:
    """Exact ``np.median`` over a streamed sequence in O(bins) memory.

    Pass 1 (:meth:`add`) histograms the values; :meth:`plan` locates the
    bins holding the middle order statistics; pass 2 (:meth:`collect`)
    gathers only the values in those bins (bins strictly between the two
    middle bins are provably empty); :meth:`result` reproduces ``np.median``
    exactly — same middle elements, same float mean of the two."""

    def __init__(self):
        self.hist = np.zeros(_MBINS + 1, dtype=np.int64)
        self.n = 0
        self._bins: np.ndarray | None = None
        self._vals: list[np.ndarray] = []

    def add(self, vals: np.ndarray) -> None:
        if len(vals):
            # touch only the bins present: a minlength=_MBINS bincount would
            # allocate 8 MB per call, paid once per pile in the fallback path
            u, c = np.unique(_rate_bins(vals), return_counts=True)
            self.hist[u] += c
            self.n += len(vals)

    def plan(self) -> None:
        if self.n == 0:
            raise ValueError("_StreamMedian.plan() on an empty stream "
                             "(no values added)")
        k1, k2 = (self.n - 1) // 2, self.n // 2
        cum = np.cumsum(self.hist)
        b1 = int(np.searchsorted(cum, k1 + 1))
        b2 = int(np.searchsorted(cum, k2 + 1))
        self._k1, self._k2 = k1, k2
        self._below = int(cum[b1 - 1]) if b1 else 0
        self._bins = np.unique([b1, b2])

    def collect(self, vals: np.ndarray) -> None:
        if len(vals):
            m = np.isin(_rate_bins(vals), self._bins)
            if m.any():
                self._vals.append(np.asarray(vals[m], dtype=np.float64))

    def result(self) -> float:
        v = np.sort(np.concatenate(self._vals))
        v1 = v[self._k1 - self._below]
        v2 = v[self._k2 - self._below]
        return float(v1) if self._k1 == self._k2 else float((v1 + v2) / 2.0)


def _chunk_filter_stats(col, reps):
    """(prates, uspan, alen) for one columnar chunk — the per-record math of
    the native filter path, shared by the whole-file and bounded-memory
    streaming variants so they cannot diverge."""
    n = col.novl
    alen = np.maximum(col.aepos.astype(np.int64) - col.abpos, 1)
    pairs = col.trace_flat[::2]
    if len(pairs):
        # a zero sentinel keeps trailing empty-trace groups in range
        # without clipping into the previous group's last element;
        # zero-length groups (which alias the next group's first
        # element under reduceat) are masked after
        pairs_s = np.concatenate([pairs, [0]])
        dsum = np.add.reduceat(pairs_s, col.trace_off[:-1] // 2)
        dsum = np.where(np.diff(col.trace_off) > 0, dsum, 0)
    else:
        dsum = np.zeros(n, np.int64)
    prates = dsum / alen
    rep_reads = ({i for i in range(len(reps)) if len(reps[i])}
                 if reps is not None else set())
    uspan = (col.aepos.astype(np.int64) - col.abpos).copy()
    if rep_reads:
        # repeat-bearing reads dominate exactly the piles this tool
        # targets, so the subtraction is grouped by read and done with
        # searchsorted against the read's interval boundaries instead
        # of a per-record Python loop
        sel = np.nonzero(np.isin(
            col.aread, np.fromiter(rep_reads, np.int64)))[0]
        sel = sel[np.argsort(col.aread[sel], kind="stable")]
        grp = np.split(sel, np.nonzero(np.diff(col.aread[sel]))[0] + 1)
        for g in grp:
            if not len(g):
                continue
            a = int(col.aread[g[0]])
            iv = np.asarray(reps[a], dtype=np.int64).reshape(-1, 2)
            st, en = iv[:, 0], iv[:, 1]
            ab = col.abpos[g].astype(np.int64)
            ae = col.aepos[g].astype(np.int64)
            if len(iv) and np.all(st[1:] >= en[:-1]):
                # sorted disjoint intervals (the track writer's
                # invariant): covered length via prefix sums minus
                # the two end overhangs
                cum = np.concatenate([[0], np.cumsum(en - st)])
                i0 = np.searchsorted(en, ab, side="right")
                i1 = np.searchsorted(st, ae, side="left")
                has = i1 > i0
                cov = cum[i1] - cum[i0]
                cov -= np.where(has, np.maximum(
                    0, ab - st[np.minimum(i0, len(iv) - 1)]), 0)
                cov -= np.where(has, np.maximum(
                    0, en[np.maximum(i1, 1) - 1] - ae), 0)
                uspan[g] = (ae - ab) - cov
            else:
                for j, i in enumerate(g):
                    span = int(ae[j] - ab[j])
                    for s, e in reps[a]:
                        span -= max(0, min(int(ae[j]), int(e))
                                    - max(int(ab[j]), int(s)))
                    uspan[i] = span
    return prates, uspan, alen


def _pile_keep(prates, uspan, alen, pile_starts, gmed: float,
               max_err: float | None, min_unique_span: int,
               rep_margin: float) -> np.ndarray:
    """Apply the per-pile consistency rule (shared whole-file/streaming)."""
    is_uniq = uspan >= min_unique_span
    span_ok = alen >= min_unique_span
    keep = np.zeros(len(prates), dtype=bool)
    for p in range(len(pile_starts) - 1):
        s, e = int(pile_starts[p]), int(pile_starts[p + 1])
        u = is_uniq[s:e]
        med = float(np.median(prates[s:e][u])) if u.sum() >= 5 else gmed
        cut = max_err if max_err is not None else max(2.0 * med, med + 0.15)
        keep[s:e] = np.where(
            u, prates[s:e] <= cut,
            prates[s:e] <= med + rep_margin) & span_ok[s:e]
    return keep


def filter_alignments(db: DazzDB, las: LasFile, out_path: str,
                      max_err: float | None = None,
                      repeat_track: str | None = "rep",
                      min_unique_span: int = 100,
                      rep_margin: float = 0.015,
                      mem_records: int | None = None) -> int:
    """Drop alignments inconsistent with the unique-region error profile.

    The paper's "local genomic consistency analysis" at the file level
    (reference ``lasfilteralignments``, SURVEY.md §2.1: "drops alignments
    inconsistent with the unique-region error profile"):

    - alignments with >= ``min_unique_span`` bases outside repeat intervals
      ("unique" alignments) are kept unless their error rate is far above
      the pile median (2x / +0.15, or the explicit ``max_err``);
    - alignments confined to repeat intervals are kept ONLY while their
      error rate stays within ``rep_margin`` of the unique-region rate
      profile. Same-copy alignments inside a repeat match the unique
      profile; cross-copy alignments carry the copies' divergence on top of
      it — the consistency test separates them where a blanket confined-
      alignment drop would starve every repeat-interior pile of its true
      alignments (measured: blanket drop cost -2.3 Q on a 3%%-diverged
      two-copy repeat sim; the reference's behavior is consistency-based).

    The unique-rate reference is the pile's median over its own unique
    alignments when it has >= 5 of them, else the file-wide median.

    ``mem_records``: bound peak memory to ~that many records at a time (the
    pre-filter LAS is by design the largest file of the workflow; at
    CHM-scale 1e9 records the whole-file columnar load would need 40+ GB).
    The streaming variant makes pile-aligned chunked passes — histogram +
    exact-median-collect + apply — and writes kept records as it goes;
    output is byte-identical to the whole-file path (parity-tested).
    """
    tspace = las.tspace
    reps = None
    if repeat_track is not None:
        try:
            reps = read_repeat_track(db, repeat_track)
        except FileNotFoundError:
            reps = None

    def unique_span(aread: int, abpos: int, aepos: int) -> int:
        span = aepos - abpos
        if reps is None or aread >= len(reps):
            return span
        for s, e in reps[aread]:
            span -= max(0, min(aepos, int(e)) - max(abpos, int(s)))
        return span

    if mem_records is not None and mem_records <= 0:
        mem_records = None   # 0 / negative: "no bound", not a chunk size

    if _native_ok():
        # columnar passes: per-overlap rates and per-pile medians vectorized;
        # only overlaps on repeat-annotated reads pay the interval check
        from ..formats.las import shard_ranges
        from ..native.api import ColumnarLas

        if mem_records is not None and las.novl > mem_records:
            ranges = [r for r in shard_ranges(
                las.path, max(1, -(-las.novl // mem_records))) if r[0] < r[1]]
        else:
            ranges = None

        rec_iter = iter(las)

        def stream_write(keep_per_chunk):
            def kept_iter():
                for keep in keep_per_chunk:
                    for flag in keep:
                        o = next(rec_iter)
                        if flag:
                            yield o
            return write_las(out_path, tspace, kept_iter())

        if ranges is None:
            # whole-file: one parse, one stats computation, direct median
            col = ColumnarLas(las.path)
            if not col.novl:
                return write_las(out_path, tspace, iter(()))
            pr, uspan, alen = _chunk_filter_stats(col, reps)
            uq = uspan >= min_unique_span
            gmed = float(np.median(pr[uq])) if uq.any() \
                else float(np.median(pr))
            keep = _pile_keep(pr, uspan, alen, col.pile_starts, gmed,
                              max_err, min_unique_span, rep_margin)
            return stream_write([keep])

        def chunks():
            for b0, b1 in ranges:
                col = ColumnarLas(las.path, b0, b1)
                if col.novl:
                    yield col

        # pass 1: global unique-rate median (exact, O(bins) memory)
        med_u, med_a = _StreamMedian(), _StreamMedian()
        for col in chunks():
            pr, uspan, _ = _chunk_filter_stats(col, reps)
            med_u.add(pr[uspan >= min_unique_span])
            med_a.add(pr)
        sel = med_u if med_u.n else med_a
        gmed = 0.0
        if sel.n:
            sel.plan()
            for col in chunks():
                pr, uspan, _ = _chunk_filter_stats(col, reps)
                sel.collect(pr[uspan >= min_unique_span]
                            if sel is med_u else pr)
            gmed = sel.result()

        # pass 2: per-pile rule, records streamed straight into the writer
        def keeps():
            for col in chunks():
                pr, uspan, alen = _chunk_filter_stats(col, reps)
                yield _pile_keep(pr, uspan, alen, col.pile_starts, gmed,
                                 max_err, min_unique_span, rep_margin)

        return stream_write(keeps())
    else:
        # pure-python fallback: one pile in memory at a time
        def pile_stats(aread, pile):
            r = np.asarray([float(o.trace[:, 0].sum())
                            / max(o.aepos - o.abpos, 1) for o in pile])
            u = np.asarray([unique_span(aread, o.abpos, o.aepos)
                            >= min_unique_span for o in pile], dtype=bool)
            return r, u

        bounded = mem_records is not None and las.novl > mem_records
        if not bounded:
            # two passes: per-record rates kept in memory, direct np.median
            ra, ua = [], []
            for aread, pile in las.iter_piles():
                r, u = pile_stats(aread, pile)
                ra.append(r)
                ua.append(u)
            ra = np.concatenate(ra) if ra else np.zeros(0)
            ua = np.concatenate(ua) if ua else np.zeros(0, bool)
            gmed = float(np.median(ra[ua])) if ua.any() else \
                (float(np.median(ra)) if len(ra) else 0.0)
        else:
            # three streaming passes (rates recomputed per pass; the python
            # record parse dominates either way); exact-median machinery
            med_u, med_a = _StreamMedian(), _StreamMedian()
            for aread, pile in las.iter_piles():
                r, u = pile_stats(aread, pile)
                med_u.add(r[u])
                med_a.add(r)
            sel = med_u if med_u.n else med_a
            gmed = 0.0
            if sel.n:
                sel.plan()
                for aread, pile in las.iter_piles():
                    r, u = pile_stats(aread, pile)
                    sel.collect(r[u] if sel is med_u else r)
                gmed = sel.result()

        i0 = 0

        def kept_iter():
            nonlocal i0
            for aread, pile in las.iter_piles():
                if bounded:
                    r, u = pile_stats(aread, pile)
                else:
                    r, u = ra[i0 : i0 + len(pile)], ua[i0 : i0 + len(pile)]
                    i0 += len(pile)
                med = float(np.median(r[u])) if u.sum() >= 5 else gmed
                cut = max_err if max_err is not None \
                    else max(2.0 * med, med + 0.15)
                for j, o in enumerate(pile):
                    if o.aepos - o.abpos < min_unique_span:
                        continue
                    if (r[j] <= cut) if u[j] else (r[j] <= med + rep_margin):
                        yield o

        return write_las(out_path, tspace, kept_iter())


def filter_symmetric(las_path: str, out_path: str, db: DazzDB | None = None) -> int:
    """Keep A->B overlaps iff a matching B->A record exists (reference
    ``filtersym``).

    With a DB (read lengths known) the match is exact: for plain overlaps the
    mirror of (a,b,[ab,ae),[bb,be)) is (b,a,[bb,be),[ab,ae)); for complemented
    overlaps both sides' coordinates flip through their read length
    (DALIGNER complement-space symmetry). Without a DB, matching falls back to
    per-(a,b,comp) record counts.
    """
    las = LasFile(las_path)

    if db is not None:
        keys: set = set()
        for o in las:
            keys.add((o.aread, o.bread, o.is_comp, o.abpos, o.aepos, o.bbpos, o.bepos))

        def mirror_key(o: Overlap):
            if not o.is_comp:
                return (o.bread, o.aread, False, o.bbpos, o.bepos, o.abpos, o.aepos)
            alen = db.read_length(o.aread)
            blen = db.read_length(o.bread)
            return (o.bread, o.aread, True,
                    blen - o.bepos, blen - o.bbpos,
                    alen - o.aepos, alen - o.abpos)

        kept = [o for o in las if mirror_key(o) in keys]
    else:
        from collections import Counter

        counts: Counter = Counter()
        for o in las:
            counts[(o.aread, o.bread, o.is_comp)] += 1
        budget: Counter = Counter()
        kept = []
        for o in las:
            key = (o.aread, o.bread, o.is_comp)
            quota = min(counts[key], counts[(o.bread, o.aread, o.is_comp)])
            if budget[key] < quota:
                budget[key] += 1
                kept.append(o)
    return write_las(out_path, las.tspace, kept)
