"""Preprocessing tool logic: intrinsic QV, repeat detection, alignment filtering.

Equivalents of the reference tools (SURVEY.md §2.1, §3.2, §3.4; reference
file:line citations pending backfill — mount empty, SURVEY.md §0):

- ``computeintrinsicqv``  -> :func:`compute_intrinsic_qv`  (writes track
  ``inqual``: one QV byte per tspace tile per read)
- ``lasdetectsimplerepeats`` -> :func:`detect_repeats` (writes interval track
  ``rep``: int64 start/end pairs per read)
- ``lasfilteralignments`` -> :func:`filter_alignments` (drops alignments whose
  error profile is inconsistent with the unique-region profile)
- ``filtersym`` -> :func:`filter_symmetric` (keep A->B iff B->A kept)

These are cheap single-pass streaming passes over LAS piles (the reference
runs them as separate processes composed via the filesystem; kept that way —
each is independently restartable, which is the checkpoint/resume model of
SURVEY.md §5).

QV convention: ``qv = clip(round(200 * rate), 0, 250)`` where ``rate`` is the
per-tile error rate of the depth-d quantile alignment; 251..255 reserved
(255 = no coverage). Downstream consumers in this framework use the same
convention, making the pipeline self-consistent.
"""

from __future__ import annotations

import numpy as np

from ..formats.dazzdb import DazzDB, read_track, write_track
from ..formats.las import LasFile, Overlap, write_las

QV_NOCOV = 255
QV_SCALE = 200.0


def _native_ok() -> bool:
    """True when the C++ host library is importable and built. Only the
    import is guarded — bugs inside the native-path math must propagate, not
    silently degrade to the slow fallback."""
    try:
        from ..native import available
    except Exception:
        return False
    return available()


def _pile_tile_rates(db: DazzDB, aread: int, pile: list[Overlap], tspace: int):
    """Per-tile lists of alignment error rates for one A read."""
    rlen = db.read_length(aread)
    ntiles = (rlen + tspace - 1) // tspace
    rates: list[list[float]] = [[] for _ in range(ntiles)]
    for o in pile:
        bounds = o.tile_bounds(tspace)
        for t in range(len(bounds) - 1):
            a0, a1 = int(bounds[t]), int(bounds[t + 1])
            tl = a1 - a0
            if tl <= 0:
                continue
            g = a0 // tspace
            # pair diffs count both reads' errors; halve for a per-read rate
            rates[g].append(0.5 * float(o.trace[t, 0]) / tl)
    return rates


def _read_lengths(db: DazzDB, lo: int = 0, hi: int | None = None) -> np.ndarray:
    hi = db.nreads if hi is None else hi
    return np.fromiter((db.reads[i].rlen for i in range(lo, hi)), np.int64, hi - lo)


def _tile_table(db: DazzDB, tspace: int, lo: int = 0, hi: int | None = None) -> np.ndarray:
    """Tile offsets over reads [lo, hi): tile_base[i] .. tile_base[i+1] are
    read lo+i's tiles. Block jobs pass their read range so every flat array
    downstream is O(block), not O(whole DB)."""
    ntiles = (_read_lengths(db, lo, hi) + tspace - 1) // tspace
    tile_base = np.zeros(len(ntiles) + 1, np.int64)
    np.cumsum(ntiles, out=tile_base[1:])
    return tile_base


def _block_range(db: DazzDB, las: LasFile, block: int | None) -> tuple[int, int, int | None, int | None]:
    """(lo, hi, byte_start, byte_end) for DB block ``block`` (1-based);
    ``block=None`` means the whole run (all reads, full file)."""
    if block is None:
        return 0, db.nreads, None, None
    from ..formats.dazzdb import db_blocks
    from ..formats.las import range_for_areads

    blocks = db_blocks(db.path)
    if not (1 <= block <= len(blocks)):
        raise ValueError(f"block {block}: DB has {len(blocks)} blocks")
    lo, hi = blocks[block - 1]
    start, end = range_for_areads(las.path, lo, hi)
    return lo, hi, start, end


def _intrinsic_qv_native(db: DazzDB, las: LasFile, depth: int,
                         rlo: int = 0, rhi: int | None = None,
                         byte_range=(None, None)) -> list[np.ndarray]:
    """Vectorized QV pass over the native columnar LAS load (SURVEY.md §2.4:
    the streaming path rides C++ + numpy vector math, not per-record Python).
    Bit-identical to the per-pile fallback below (parity-tested). All flat
    arrays cover only reads [rlo, rhi) so block jobs stay O(block)."""
    from ..native.api import ColumnarLas

    rhi = db.nreads if rhi is None else rhi
    col = ColumnarLas(las.path, *byte_range)
    tspace = col.tspace
    tile_base = _tile_table(db, tspace, rlo, rhi)
    qv_flat = np.full(int(tile_base[-1]), QV_NOCOV, dtype=np.uint8)

    if col.novl:
        T = (np.diff(col.trace_off) // 2).astype(np.int64)   # tiles per overlap
        n = col.novl
        total = int(T.sum())
        ov = np.repeat(np.arange(n), T)
        starts = np.zeros(n + 1, np.int64)
        np.cumsum(T, out=starts[1:])
        tloc = np.arange(total, dtype=np.int64) - np.repeat(starts[:-1], T)
        g = col.abpos.astype(np.int64)[ov] // tspace + tloc  # per-read tile id
        lo = np.maximum(col.abpos[ov], g * tspace)
        hi = np.minimum(col.aepos[ov], (g + 1) * tspace)
        tl = hi - lo
        dif = col.trace_flat[np.repeat(col.trace_off[:-1], T) + 2 * tloc]
        ok = tl > 0
        gid = (tile_base[col.aread.astype(np.int64)[ov] - rlo] + g)[ok]
        # same expression shape as the fallback: (0.5 * diff) / tile_len
        rate = 0.5 * dif[ok].astype(np.float64) / tl[ok]
        order = np.lexsort((rate, gid))
        gid_s, rate_s = gid[order], rate[order]
        uniq, gstart, gcount = np.unique(gid_s, return_index=True, return_counts=True)
        sel = gstart + np.minimum(max(depth // 2, 1), gcount) - 1
        q = np.minimum(np.round(QV_SCALE * rate_s[sel]), 250).astype(np.uint8)
        qv_flat[uniq] = q
    return [qv_flat[tile_base[i] : tile_base[i + 1]] for i in range(rhi - rlo)]


def compute_intrinsic_qv(db: DazzDB, las: LasFile, depth: int = 20,
                         track: str = "inqual", use_native: bool = True,
                         block: int | None = None) -> list[np.ndarray]:
    """Per-read per-tile intrinsic QVs from pile error statistics.

    The depth-d quantile (d-th lowest rate) is robust to repeat-induced piles:
    repeats inflate coverage with *worse* alignments, leaving the best d
    mostly intact (reference ``computeintrinsicqv -d``).

    With ``block``, only that DB block's reads are processed (via the LAS
    aread-range byte index) and a per-block track is written; merge the block
    tracks with :func:`daccord_tpu.formats.dazzdb.catrack`.
    """
    tspace = las.tspace
    lo, hi, start, end = _block_range(db, las, block)
    payloads: list[np.ndarray] | None = None
    if use_native and _native_ok():
        payloads = _intrinsic_qv_native(db, las, depth, lo, hi, byte_range=(start, end))
    if payloads is None:
        payloads = [np.zeros(0, dtype=np.uint8)] * (hi - lo)
        for aread, pile in las.iter_piles(start, end):
            rates = _pile_tile_rates(db, aread, pile, tspace)
            qv = np.full(len(rates), QV_NOCOV, dtype=np.uint8)
            for t, rl in enumerate(rates):
                if not rl:
                    continue
                rl = sorted(rl)
                q = rl[min(max(depth // 2, 1), len(rl)) - 1]
                qv[t] = min(int(round(QV_SCALE * q)), 250)
            payloads[aread - lo] = qv
        # reads with no pile get all-NOCOV tracks of the right length
        for i in range(hi - lo):
            if len(payloads[i]) == 0:
                nt = (db.read_length(lo + i) + tspace - 1) // tspace
                payloads[i] = np.full(nt, QV_NOCOV, dtype=np.uint8)
    write_track(db.path, track, payloads, block=block)
    return payloads


def _tile_coverage_native(db: DazzDB, las: LasFile, rlo: int = 0, rhi: int | None = None,
                          byte_range=(None, None)) -> tuple[np.ndarray, np.ndarray]:
    """(tile_base, cov_flat): per-tile alignment coverage over reads
    [rlo, rhi) via the native columnar load + a difference-array sweep (no
    per-record Python). Interval deltas cancel within each read, so one
    global cumsum yields every read's coverage."""
    from ..native.api import ColumnarLas

    rhi = db.nreads if rhi is None else rhi
    col = ColumnarLas(las.path, *byte_range)
    tspace = col.tspace
    tile_base = _tile_table(db, tspace, rlo, rhi)
    delta = np.zeros(int(tile_base[-1]) + 1, dtype=np.int64)
    if col.novl:
        ar = col.aread.astype(np.int64) - rlo
        g0 = col.abpos.astype(np.int64) // tspace
        g1 = np.maximum(col.aepos.astype(np.int64) - 1, col.abpos) // tspace
        np.add.at(delta, tile_base[ar] + g0, 1)
        np.add.at(delta, tile_base[ar] + g1 + 1, -1)
    return tile_base, np.cumsum(delta[:-1])


def detect_repeats(db: DazzDB, las: LasFile, depth: int = 20,
                   cov_factor: float = 2.0, track: str = "rep",
                   use_native: bool = True, block: int | None = None) -> list[np.ndarray]:
    """Detect simple-repeat intervals from pile over-coverage.

    A tile whose alignment coverage exceeds ``cov_factor * depth`` is repeat-
    annotated; adjacent repeat tiles merge into intervals (int64 start/end
    pairs per read, written as track ``rep``).

    With ``block``, processes only that DB block (per-block track; merge with
    ``catrack``) — the reference's per-block cluster workflow.
    """
    tspace = las.tspace
    lo, hi, start, end = _block_range(db, las, block)
    payloads: list[np.ndarray] | None = None
    if use_native and _native_ok():
        tile_base, cov_flat = _tile_coverage_native(db, las, lo, hi,
                                                    byte_range=(start, end))
        hot_flat = cov_flat > cov_factor * depth
        # global run extraction: a zero separator at every read boundary
        # keeps runs from merging across reads; one diff finds all runs
        seps = tile_base[1:-1]
        ext = np.insert(hot_flat.astype(np.int8), seps, 0)
        d = np.diff(np.concatenate([[0], ext, [0]]))
        p0 = np.nonzero(d == 1)[0]          # run starts, separator space
        p1 = np.nonzero(d == -1)[0]         # run ends (exclusive)
        # map back: subtract the number of separators inserted before p
        sep_pos = seps + np.arange(len(seps))   # separator indices in ext
        t0 = p0 - np.searchsorted(sep_pos, p0)
        t1 = p1 - np.searchsorted(sep_pos, p1)
        rid = np.searchsorted(tile_base, t0, side="right") - 1  # block-local ids
        rlens = _read_lengths(db, lo, hi)
        iv = np.empty((len(t0), 2), dtype=np.int64)
        iv[:, 0] = (t0 - tile_base[rid]) * tspace
        iv[:, 1] = np.minimum((t1 - tile_base[rid]) * tspace, rlens[rid])
        counts = np.bincount(rid, minlength=hi - lo)
        splits = np.split(iv, np.cumsum(counts)[:-1])
        payloads = [np.ascontiguousarray(s).reshape(-1).view(np.uint8)
                    for s in splits]
    if payloads is None:
        payloads = [np.zeros(0, dtype=np.uint8)] * (hi - lo)
        for aread, pile in las.iter_piles(start, end):
            rlen = db.read_length(aread)
            ntiles = (rlen + tspace - 1) // tspace
            cov = np.zeros(ntiles, dtype=np.int64)
            for o in pile:
                g0 = o.abpos // tspace
                g1 = (max(o.aepos - 1, o.abpos)) // tspace
                cov[g0 : g1 + 1] += 1
            hot = cov > cov_factor * depth
            ivals: list[int] = []
            t = 0
            while t < ntiles:
                if hot[t]:
                    t0 = t
                    while t < ntiles and hot[t]:
                        t += 1
                    ivals.extend([t0 * tspace, min(t * tspace, rlen)])
                else:
                    t += 1
            payloads[aread - lo] = np.asarray(ivals, dtype=np.int64).view(np.uint8)
    write_track(db.path, track, payloads, block=block)
    return payloads


def read_repeat_track(db: DazzDB, track: str = "rep") -> list[np.ndarray]:
    """Interval track back as [n, 2] int64 arrays."""
    raw = read_track(db.path, track)
    return [r.view(np.int64).reshape(-1, 2) if len(r) else np.zeros((0, 2), dtype=np.int64)
            for r in raw]


def filter_alignments(db: DazzDB, las: LasFile, out_path: str,
                      max_err: float | None = None,
                      repeat_track: str | None = "rep",
                      min_unique_span: int = 100) -> int:
    """Drop alignments inconsistent with the unique-region error profile.

    The paper's "local genomic consistency analysis" at the file level
    (reference ``lasfilteralignments``): an alignment whose error rate over
    the A read's *non-repeat* tiles is far above the pile median is likely a
    repeat-induced mis-pile; drop it. Alignments confined entirely to repeat
    intervals (< ``min_unique_span`` unique bases) are dropped too.
    """
    tspace = las.tspace
    reps = None
    if repeat_track is not None:
        try:
            reps = read_repeat_track(db, repeat_track)
        except FileNotFoundError:
            reps = None

    def unique_span(aread: int, o: Overlap) -> int:
        if reps is None or aread >= len(reps):
            return o.aepos - o.abpos
        span = o.aepos - o.abpos
        for s, e in reps[aread]:
            span -= max(0, min(o.aepos, e) - max(o.abpos, s))
        return span

    if _native_ok():
        # columnar pass: per-overlap rates and per-pile medians vectorized;
        # only overlaps on repeat-annotated reads pay the interval check
        from ..native.api import ColumnarLas

        col = ColumnarLas(las.path)
        n = col.novl
        rate_keep = np.zeros(n, dtype=bool)
        if n:
            alen = np.maximum(col.aepos.astype(np.int64) - col.abpos, 1)
            pairs = col.trace_flat[::2]
            if len(pairs):
                # a zero sentinel keeps trailing empty-trace groups in range
                # without clipping into the previous group's last element;
                # zero-length groups (which alias the next group's first
                # element under reduceat) are masked after
                pairs_s = np.concatenate([pairs, [0]])
                dsum = np.add.reduceat(pairs_s, col.trace_off[:-1] // 2)
                dsum = np.where(np.diff(col.trace_off) > 0, dsum, 0)
            else:
                dsum = np.zeros(n, np.int64)
            prates = dsum / alen
            for p in range(len(col.pile_starts) - 1):
                s, e = int(col.pile_starts[p]), int(col.pile_starts[p + 1])
                med = float(np.median(prates[s:e]))
                cut = max_err if max_err is not None else max(2.0 * med, med + 0.15)
                rate_keep[s:e] = prates[s:e] <= cut
            # span test: on repeat-free reads unique_span == aepos - abpos,
            # and repeat subtraction only shrinks it, so this cut is exact
            rate_keep &= (col.aepos.astype(np.int64) - col.abpos) >= min_unique_span
        kept = []
        rep_reads = ({i for i in range(len(reps)) if len(reps[i])}
                     if reps is not None else set())
        for i, o in enumerate(las):
            if not rate_keep[i]:
                continue
            if o.aread in rep_reads and unique_span(o.aread, o) < min_unique_span:
                continue
            kept.append(o)
    else:
        kept = []
        for aread, pile in las.iter_piles():
            prates = []
            for o in pile:
                alen = max(o.aepos - o.abpos, 1)
                prates.append(float(o.trace[:, 0].sum()) / alen)
            med = float(np.median(prates)) if prates else 0.0
            cut = max_err if max_err is not None else max(2.0 * med, med + 0.15)
            for o, r in zip(pile, prates):
                if r <= cut and unique_span(aread, o) >= min_unique_span:
                    kept.append(o)
    write_las(out_path, tspace, kept)
    return len(kept)


def filter_symmetric(las_path: str, out_path: str, db: DazzDB | None = None) -> int:
    """Keep A->B overlaps iff a matching B->A record exists (reference
    ``filtersym``).

    With a DB (read lengths known) the match is exact: for plain overlaps the
    mirror of (a,b,[ab,ae),[bb,be)) is (b,a,[bb,be),[ab,ae)); for complemented
    overlaps both sides' coordinates flip through their read length
    (DALIGNER complement-space symmetry). Without a DB, matching falls back to
    per-(a,b,comp) record counts.
    """
    las = LasFile(las_path)

    if db is not None:
        keys: set = set()
        for o in las:
            keys.add((o.aread, o.bread, o.is_comp, o.abpos, o.aepos, o.bbpos, o.bepos))

        def mirror_key(o: Overlap):
            if not o.is_comp:
                return (o.bread, o.aread, False, o.bbpos, o.bepos, o.abpos, o.aepos)
            alen = db.read_length(o.aread)
            blen = db.read_length(o.bread)
            return (o.bread, o.aread, True,
                    blen - o.bepos, blen - o.bbpos,
                    alen - o.aepos, alen - o.abpos)

        kept = [o for o in las if mirror_key(o) in keys]
    else:
        from collections import Counter

        counts: Counter = Counter()
        for o in las:
            counts[(o.aread, o.bread, o.is_comp)] += 1
        budget: Counter = Counter()
        kept = []
        for o in las:
            key = (o.aread, o.bread, o.is_comp)
            quota = min(counts[key], counts[(o.bread, o.aread, o.is_comp)])
            if budget[key] < quota:
                budget[key] += 1
                kept.append(o)
    return write_las(out_path, las.tspace, kept)
