"""Order-of-magnitude scale proof: the FULL production path, measured.

VERDICT r4 "Next round" #2: every CHM-scale claim so far is arithmetic from
toy runs; nothing proves the framework survives one order of magnitude up
(RSS, disk, sidecar index size, manifest churn). This runs the complete
production chain on a ~1/10-CHM-chr20-scale synthetic dataset —

    sim -> fasta2db -> inqual -> repeats -> filter --mem-records
        -> filtersym -> lassort -> sharded daccord (checkpoints, native
        engine) -> merge -> qveval

— each stage in its own subprocess under ``/usr/bin/time -v``, and emits one
JSON line per stage: wall seconds, PEAK RSS (the scale claim), and bytes
written. The final summary line aggregates the table for BASELINE.md.

Default shape: 30 Mb genome, 42x, 4 kb reads -> ~1.2 Gbases of reads and
~1e7 LAS records (sized by VERDICT's floor). ``--genome-mb/--coverage``
scale it; ``--dir`` places the dataset (needs ~15 GB free at the default
shape). The dataset is NOT cached — this tool is a measurement, rerun it
end to end.

Run: ``python -m daccord_tpu.tools.scalebench [--genome-mb 30] [--shards 8]``
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def du_bytes(*paths: str) -> int:
    tot = 0
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                tot += sum(os.path.getsize(os.path.join(root, f))
                           for f in files)
        elif os.path.exists(p):
            tot += os.path.getsize(p)
    return tot


_RSS_WRAPPER = (
    "import resource, subprocess, sys;"
    "rc = subprocess.run(sys.argv[1:]).returncode;"
    "print('MAX_RSS_KB', resource.getrusage(resource.RUSAGE_CHILDREN)"
    ".ru_maxrss, file=sys.stderr);"
    "sys.exit(rc)")


def timed_stage(name: str, argv: list[str], outputs: tuple[str, ...] = (),
                env: dict | None = None) -> dict:
    """Run one pipeline stage in a subprocess; record wall + peak child RSS
    (no GNU time binary in this image — ru_maxrss of RUSAGE_CHILDREN)."""
    cmd = [sys.executable, "-c", _RSS_WRAPPER, sys.executable, "-m",
           "daccord_tpu.tools.cli", *argv]
    t0 = time.time()
    r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                       env={**os.environ, **(env or {})})
    wall = time.time() - t0
    if r.returncode != 0:
        raise RuntimeError(f"stage {name} failed (rc={r.returncode}):\n"
                           f"{r.stderr[-2000:]}")
    m = re.search(r"MAX_RSS_KB (\d+)", r.stderr)
    rss_mb = round(int(m.group(1)) / 1024, 1) if m else None
    row = {"stage": name, "wall_s": round(wall, 1), "peak_rss_mb": rss_mb,
           "out_bytes": du_bytes(*outputs)}
    print(json.dumps(row), flush=True)
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--genome-mb", type=float, default=30.0)
    ap.add_argument("--coverage", type=float, default=42.0)
    ap.add_argument("--read-len", type=float, default=4000.0)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--dir", default="/tmp/daccord_scale")
    ap.add_argument("--mem-records", type=int, default=2_000_000,
                    help="filter/lassort bounded-memory record budget")
    ap.add_argument("--out", default=None, help="append stage rows here")
    ap.add_argument("--keep", action="store_true")
    ap.add_argument("--reuse", action="store_true",
                    help="skip the sim stage when the dataset files already "
                         "exist in --dir (a killed run's --keep leftovers); "
                         "the sim row is then omitted, not re-measured")
    args = ap.parse_args(argv)

    d = args.dir
    os.makedirs(d, exist_ok=True)
    rows = []

    def emit(row: dict) -> None:
        # every row lands on disk IMMEDIATELY: a multi-hour run killed by a
        # wall-clock limit must keep the stages it finished
        rows.append(row)
        if args.out:
            with open(args.out, "a") as fh:
                fh.write(json.dumps(row) + "\n")

    # stage 0: synthetic dataset (sim is part of the measurement: it is this
    # environment's only read source at scale)
    gen = int(args.genome_mb * 1e6)
    t0 = time.time()
    paths = {k: os.path.join(d, f"scale.{ext}") for k, ext in
             (("db", "db"), ("las", "las"), ("truth", "truth.npz"))}
    if args.reuse and all(os.path.exists(p) for p in paths.values()):
        out = paths
    else:
        from daccord_tpu.sim import SimConfig, make_dataset

        out = make_dataset(d, SimConfig(genome_len=gen,
                                        coverage=args.coverage,
                                        read_len_mean=args.read_len,
                                        min_overlap=1000, seed=50),
                           name="scale")
        row = {"stage": "sim", "wall_s": round(time.time() - t0, 1),
               "peak_rss_mb": None,
               "out_bytes": du_bytes(out["db"], out["las"],
                                     os.path.join(d, ".scale.bps"))}
        print(json.dumps(row), flush=True)
        emit(row)
    db, las = out["db"], out["las"]
    depth = str(int(args.coverage))
    mem = str(args.mem_records)

    filt = os.path.join(d, "filt.las")
    sym = os.path.join(d, "sym.las")
    srt = os.path.join(d, "sym.sorted.las")
    outdir = os.path.join(d, "shards")
    fa = os.path.join(d, "corrected.fasta")

    emit(timed_stage("inqual", ["inqual", db, las, "-d", depth],
                            outputs=(os.path.join(d, ".scale.inqual.anno"),
                                     os.path.join(d, ".scale.inqual.data"))))
    emit(timed_stage("repeats", ["repeats", db, las, "-d", depth,
                                        "--factor", "1.5"],
                            outputs=(os.path.join(d, ".scale.rep.anno"),
                                     os.path.join(d, ".scale.rep.data"))))
    emit(timed_stage("filter", ["filter", db, las, filt,
                                       "--mem-records", mem],
                            outputs=(filt,)))
    emit(timed_stage("filtersym", ["filtersym", filt, sym,
                                          "--db", db, "--mem-records", mem],
                            outputs=(sym,)))
    emit(timed_stage("lassort", ["lassort", sym, srt,
                                        "--mem-records", mem],
                            outputs=(srt,)))
    for s in range(args.shards):
        emit(timed_stage(
            f"shard{s}", ["shard", db, srt, outdir,
                          "-J", f"{s},{args.shards}",
                          "--backend", "native", "--checkpoint-every", "256"],
            outputs=(outdir,)))
    emit(timed_stage("merge", ["merge", outdir, str(args.shards), fa],
                            outputs=(fa,)))
    emit(timed_stage("qveval", ["qveval", fa, out["truth"],
                                       "--raw-db", db]))

    summary = {
        "stage": "TOTAL", "genome_mb": args.genome_mb,
        "coverage": args.coverage,
        "wall_s": round(sum(r["wall_s"] for r in rows), 1),
        "peak_rss_mb": max((r["peak_rss_mb"] or 0) for r in rows),
        "disk_bytes": du_bytes(d),
    }
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "a") as fh:
            fh.write(json.dumps(summary) + "\n")
    if not args.keep:
        import shutil

        shutil.rmtree(d, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
