"""Kernel-stage breakdown bench: where does a ladder batch spend its time?

Usage: ``python -m daccord_tpu.tools.kernelbench [--batch 1024] [--reps 4]
[--stages ladder_full,ladder_split]``
Prints one JSON line per timing (full ladder, two-stream split ladder, tier0,
and cumulative stage prefixes of the window kernel), so kernel optimizations
can be attributed to stages. ``--stages ladder_full,ladder_split``
additionally emits the fused-vs-split decision row (ISSUE 4: does paying the
rescue tiers only over dense pooled batches beat the fused single-dispatch
program?). Uses the same cached window set as bench.py.

Not run by the driver (bench.py remains the single-line round artifact).
"""

from __future__ import annotations

import argparse
import functools
import json
import time

#: stages in run order; --stages picks a comma-separated subset
STAGES = ("ladder_full", "ladder_pallas", "ladder_paged", "ladder_mesh",
          "ladder_split", "tier0", "prefixes")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--backend", choices=("auto", "cpu"), default="auto")
    p.add_argument("--stages", default=",".join(STAGES), metavar="LIST",
                   help="comma-separated subset of: " + ", ".join(STAGES)
                        + " (ladder_pallas is TPU-only and auto-skipped "
                          "elsewhere)")
    args = p.parse_args(argv)
    stages = [s.strip() for s in args.stages.split(",") if s.strip()]
    bad = [s for s in stages if s not in STAGES]
    if bad:
        raise SystemExit(f"kernelbench: unknown stage(s) {bad}; "
                         f"known: {', '.join(STAGES)}")

    import os
    import sys

    # bench.py lives at the repo root, two levels above this package
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))
    import bench as round_bench

    import jax

    from daccord_tpu.utils.obs import device_alive

    if args.backend == "cpu" or not device_alive():
        jax.config.update("jax_platforms", "cpu")
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()
    import jax.numpy as jnp
    import numpy as np
    from daccord_tpu.kernels.tiers import (TierLadder, fetch,
                                           rescue_candidates,
                                           solve_ladder_async,
                                           solve_ladder_split,
                                           solve_tier0_async)
    from daccord_tpu.kernels.window_kernel import _solve_one
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.oracle.profile import ErrorProfile

    data = round_bench.build_windows()
    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]), float(data["p_sub"]))
    ladder = TierLadder.from_config(prof, ConsensusConfig())
    B = min(args.batch, len(data["nsegs"]))
    seqs = jnp.asarray(data["seqs"][:B])
    lens = jnp.asarray(data["lens"][:B])
    nsegs = jnp.asarray(data["nsegs"][:B])
    p0 = ladder.params[0]
    ol = ladder.tables[p0.k]

    def timed(label, fn, *a, extra=None):
        out = fn(*a)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.reps):
            jax.block_until_ready(fn(*a))
        ms = (time.perf_counter() - t0) / args.reps * 1e3
        line = {"stage": label, "ms_per_batch": round(ms, 2), "batch": B,
                "device": str(jax.devices()[0]).replace(" ", "")}
        if extra:
            line.update(extra)
        print(json.dumps(line))
        return ms

    # full ladder (what the fused pipeline dispatches)
    from daccord_tpu.kernels.tensorize import BatchShape, WindowBatch
    shape = BatchShape(depth=seqs.shape[1], seg_len=seqs.shape[2], wlen=p0.wlen)
    wb = WindowBatch(seqs=data["seqs"][:B], lens=data["lens"][:B],
                     nsegs=data["nsegs"][:B], shape=shape,
                     read_ids=np.zeros(B, np.int64), wstarts=np.zeros(B, np.int64))
    ms_full = None
    if "ladder_full" in stages:
        ms_full = timed("ladder_full",
                        lambda: fetch(solve_ladder_async(wb, ladder)))

    # full ladder with the fused Pallas kernel (DP+selection+backtrack in one
    # pallas_call, pallas_window.py) — the on-chip fused-vs-scan decision row
    # (VERDICT r3 item 4); interpret mode off-TPU is parity-only, not a perf
    # signal, so the arm is TPU-gated
    if "ladder_pallas" in stages and jax.default_backend() == "tpu":
        timed("ladder_pallas",
              lambda: fetch(solve_ladder_async(wb, ladder, use_pallas=True)))

    if "ladder_paged" in stages:
        # ragged paged wire format (ISSUE 7): the same full-ladder program
        # fed pool + page table, dense tile gathered device-side. The
        # decision row weighs kernel-side gather cost against the shipped-
        # cell reduction; on a tunneled chip the transfer saving is the
        # larger term (pad_waste is the per-rung sidecar metric)
        from daccord_tpu.kernels import paging

        pgs = paging.window_pages(wb.lens)
        fams = paging.derive_families(wb.nsegs, pgs,
                                      max_depth=wb.seqs.shape[1],
                                      max_pages=-(-wb.seqs.shape[1]
                                                  * wb.seqs.shape[2]
                                                  // paging.PAGE_LEN),
                                      budget=1)
        t_pack = time.perf_counter()
        pwb = paging.pack_paged(wb, fams[-1], target_rows=B)
        pack_ms = (time.perf_counter() - t_pack) * 1e3
        dense_waste = round(wb.pad_waste(), 4)
        paged_waste = round(pwb.pad_waste(), 4)
        ms_paged = timed(
            "ladder_paged",
            lambda: fetch(solve_ladder_async(pwb, ladder)),
            extra={"family": fams[-1].describe(),
                   "pack_ms": round(pack_ms, 2),
                   "pad_waste_dense": dense_waste,
                   "pad_waste_paged": paged_waste})
        ms_paged_pl = None
        if jax.default_backend() == "tpu":
            # the gather_pages Pallas DMA kernel is the arm the decision
            # exists to judge on chip — the jnp row above is its fallback
            # cost; TPU-gated exactly like the ladder_pallas stage
            # (interpret mode off-TPU is parity-only, not a perf signal)
            ms_paged_pl = timed(
                "ladder_paged_pallas",
                lambda: fetch(solve_ladder_async(pwb, ladder,
                                                 use_pallas=True)))
        if ms_full is not None:
            row = {
                "stage": "decision:paged", "batch": B,
                "dense_ms": round(ms_full, 2), "paged_ms": round(ms_paged, 2),
                "paged_speedup": round(ms_full / ms_paged, 3) if ms_paged
                else None,
                "pad_waste_dense": dense_waste,
                "pad_waste_paged": paged_waste,
                "shipped_cells_dense": int(wb.seqs.size),
                "shipped_cells_paged": int(pwb.shipped_cells),
                "pack_ms": round(pack_ms, 2),
                "device": str(jax.devices()[0]).replace(" ", "")}
            if ms_paged_pl is not None:
                row["paged_pallas_ms"] = round(ms_paged_pl, 2)
            print(json.dumps(row))

    if "ladder_mesh" in stages:
        # mesh-sharded full ladder (parallel/mesh.py): the same batch solved
        # over every visible device vs the single-device program above. On a
        # pod slice this is the on-chip mesh rung; off-pod the forced-host-
        # device recipe (conftest's trick) gives the pre-chip parity/scaling
        # signal — wall-clock scaling on N virtual CPU devices is bounded by
        # host cores, so the decision row carries the recipe for the queued
        # on-chip rung (DACCORD_BENCH_MESH=1 in a live tunnel window).
        nd = min(8, len(jax.devices()))
        if nd < 2:
            print(json.dumps({
                "stage": "ladder_mesh", "skipped": True,
                "reason": f"{len(jax.devices())} device(s) visible",
                "recipe": "JAX_PLATFORMS=cpu XLA_FLAGS="
                          "--xla_force_host_platform_device_count=8"}))
        else:
            from daccord_tpu.parallel.mesh import (make_mesh,
                                                   make_sharded_solver)

            solver = make_sharded_solver(ladder, make_mesh(nd), batch=B)
            ms_mesh = timed("ladder_mesh",
                            lambda: solver(wb),
                            extra={"mesh": nd,
                                   "pad_to_mesh_rows": int(
                                       (-B) % nd)})
            if ms_full is not None:
                print(json.dumps({
                    "stage": "decision:mesh", "batch": B, "mesh": nd,
                    "single_ms": round(ms_full, 2),
                    "mesh_ms": round(ms_mesh, 2),
                    "mesh_speedup": round(ms_full / ms_mesh, 3)
                    if ms_mesh else None,
                    "per_device_rows": B // nd,
                    "queued_on_chip_rung": "DACCORD_BENCH_MESH=1 python "
                                           "bench.py (live tunnel window)",
                    "device": str(jax.devices()[0]).replace(" ", "")}))

    if "ladder_split" in stages:
        # two-stream ladder (ISSUE 4): tier0 over the full batch + the full
        # rescue ladder over the compacted candidates only. The rescue
        # sub-batch shape is fixed ONCE (candidate count rounded up to a
        # power of two) so the timed loop re-runs one compiled program pair
        # rather than compiling per candidate count.
        from daccord_tpu.utils.obs import JsonlLogger, Tracer

        # kernel.tier0 / kernel.rescue spans (ISSUE 6) land in the bench
        # events sidecar pounce already collects and lints: the trace can
        # then attribute this row's wall to the cheap-vs-quadratic split
        ev_path = os.environ.get("DACCORD_BENCH_EVENTS")
        tr_log = JsonlLogger(ev_path) if ev_path else None
        tracer = Tracer(tr_log)
        out0 = fetch(solve_tier0_async(wb, ladder))
        n_resc = int(np.sum(rescue_candidates(out0, wb.nsegs, ladder)))
        rb = 1
        while rb < max(n_resc, 1):
            rb *= 2
        rb = min(rb, B)
        ms_split = timed(
            "ladder_split",
            lambda: solve_ladder_split(wb, ladder, rescue_batch=rb,
                                       tracer=tracer),
            extra={"rescue_rows": n_resc, "rescue_batch": rb,
                   "rescue_fraction": round(n_resc / B, 4)})
        if tr_log is not None:
            tr_log.close()
        if ms_full is not None:
            # the decision row: fused vs two-stream on identical inputs.
            # split_speedup > 1 means Stream A + dense Stream B beat the
            # fused program; on a tunneled chip weigh the extra dispatch
            # RTT (split pays two fetches per rescue-bearing batch here,
            # while the production pipeline amortizes Stream B across many
            # Stream A batches — this row is the kernel-cost bound)
            print(json.dumps({
                "stage": "decision:ladder_split", "batch": B,
                "fused_ms": round(ms_full, 2), "split_ms": round(ms_split, 2),
                "split_speedup": round(ms_full / ms_split, 3) if ms_split else None,
                "rescue_rows": n_resc,
                "rescue_fraction": round(n_resc / B, 4),
                "device": str(jax.devices()[0]).replace(" ", "")}))

    if "tier0" in stages:
        # tier0 alone
        f_t0 = jax.jit(jax.vmap(functools.partial(_solve_one, p=p0),
                                in_axes=(0, 0, 0, None)))
        timed("tier0", f_t0, seqs, lens, nsegs, ol)

    if "prefixes" in stages:
        # cumulative stage prefixes of the tier0 kernel (deltas attribute
        # time to each stage; the final prefix differs from tier0 only by
        # fusion effects)
        from daccord_tpu.kernels.window_kernel import _kmer_ids

        k, M = p0.k, p0.max_kmers
        SENT = jnp.int32(4 ** k)
        P, O = ol.shape

        def stage_counts(seqs, lens, nsegs):
            ids = _kmer_ids(seqs, lens, k)
            flat = ids.reshape(-1)
            N = flat.shape[0]
            si = jnp.sort(flat)
            newrun = jnp.concatenate([jnp.array([True]), si[1:] != si[:-1]])
            is_start = newrun & (si < SENT)
            ar_n = jnp.arange(N, dtype=jnp.int32)
            starts = jnp.where(newrun, ar_n, jnp.int32(N))
            nxt = jnp.concatenate([starts[1:], jnp.array([N], jnp.int32)])
            nxt = jax.lax.associative_scan(jnp.minimum, nxt, reverse=True)
            sc = jnp.where(is_start, nxt - ar_n, 0)
            thresh = jnp.maximum(jnp.int32(p0.min_count),
                                 jnp.ceil(p0.count_frac * nsegs).astype(jnp.int32))
            sc = jnp.where(sc >= thresh, sc, 0)
            topv, topi = jax.lax.top_k(sc, M)
            sel = jnp.sort(jnp.where(topv > 0, si[topi], SENT))
            return ids, sel

        def stage_eq(seqs, lens, nsegs):
            ids, sel = stage_counts(seqs, lens, nsegs)
            npos = ids.shape[1]
            eq = (ids[:, :, None] == sel[None, None, :]) & (ids < SENT)[:, :, None]
            occ_pos = jnp.sum(eq, axis=0).astype(jnp.float32)
            o_idx = jnp.minimum(jnp.arange(npos), O - 1)
            occ = jax.ops.segment_sum(occ_pos, o_idx, num_segments=O).T
            eqh = eq.astype(jnp.bfloat16)
            support = jnp.einsum("diu,div->uv", eqh[:, :-1, :], eqh[:, 1:, :],
                                 preferred_element_type=jnp.float32)
            return occ @ ol.T, support, sel

        for label, fn in (("prefix:counts+topk", stage_counts),
                          ("prefix:+eq/occ/einsum", stage_eq)):
            f = jax.jit(jax.vmap(fn, in_axes=(0, 0, 0)))
            timed(label, f, seqs, lens, nsegs)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
