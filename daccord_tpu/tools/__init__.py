from . import lastools
from .cli import main

__all__ = ["lastools", "main"]
