"""daccord-audit: offline re-verification of a committed run's integrity
chain (ISSUE 20).

The run-time defense plane (sampled shadow verification, the merge gate's
digest check, the journal's committing digest) catches a lying chip while
the run is alive. This tool is the cold half: given a committed outdir it
re-walks every durable link — shard manifest digests against the FASTA
bytes on disk, the fleet manifest's merged digest against the merged
output, serve job manifests against their committed results — and, with
``--db/--las --resolve K``, re-solves the first K piles of a shard on the
pure host reference path and compares the fragments byte-for-byte against
what the shard FASTA committed. Exit 0 = every link verified; exit 1 = at
least one mismatch (each printed); exit 2 = nothing auditable found.

Chip-free by construction: the reference path is the host ladder, so an
audit runs anywhere the repo runs — the same doctrine as every fault
matrix.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def _check(ok: bool, label: str, detail: str, report: list[dict],
           quiet: bool) -> bool:
    report.append({"check": label, "ok": bool(ok), "detail": detail})
    if not quiet:
        print(f"  [{'ok' if ok else 'FAIL'}] {label}: {detail}")
    return ok


def audit_outdir(outdir: str, nshards: int | None = None,
                 merged: str | None = None, quiet: bool = False
                 ) -> tuple[list[dict], int]:
    """Verify every durable digest link under ``outdir``. Returns
    ``(report_rows, n_audited)`` — failures are rows with ``ok: False``."""
    from ..parallel.launch import load_shard_manifest, shard_paths
    from ..utils.obs import sha256_file

    report: list[dict] = []
    n = 0

    # fleet manifest: shard roster + the merged-output digest link
    fleet = None
    fj = os.path.join(outdir, "fleet.json")
    if os.path.exists(fj):
        try:
            with open(fj) as fh:
                fleet = json.load(fh)
        except (OSError, ValueError):
            _check(False, "fleet.json", "torn or unreadable", report, quiet)
        if fleet is not None and nshards is None:
            nshards = fleet.get("nshards")

    if nshards is None:
        found = [f for f in glob.glob(os.path.join(outdir, "shard*.json"))
                 if re.fullmatch(r"shard\d+\.json", os.path.basename(f))]
        nshards = len(found)

    for s in range(nshards or 0):
        m, why = load_shard_manifest(outdir, s)
        if m is None:
            # a fleet-poisoned shard legitimately has no output; anything
            # else (torn manifest, belied byte count) is a broken link
            poisoned = bool(fleet and s in (fleet.get("poison") or []))
            if not poisoned:
                _check(False, f"shard {s}",
                       why or "manifest missing", report, quiet)
                n += 1
            continue
        n += 1
        sha = m.get("fasta_sha256")
        if sha is None:
            _check(True, f"shard {s}",
                   "pre-digest manifest (byte counts only)", report, quiet)
            continue
        actual = sha256_file(shard_paths(outdir, s)["fasta"])
        _check(actual == sha, f"shard {s}",
               f"fasta sha256 {'verified' if actual == sha else 'MISMATCH'}"
               f" ({m.get('fasta_bytes', '?')} bytes)", report, quiet)

    # merged output: fleet.json's digest (or an explicitly named file that
    # must then match the per-shard concatenation digests indirectly)
    mpath = merged or (fleet or {}).get("merged_fasta")
    msha = (fleet or {}).get("merged_sha256")
    if mpath and os.path.exists(mpath):
        n += 1
        if msha:
            actual = sha256_file(mpath)
            _check(actual == msha, "merged",
                   f"{os.path.basename(mpath)} sha256 "
                   f"{'verified' if actual == msha else 'MISMATCH'}",
                   report, quiet)
        else:
            _check(True, "merged",
                   f"{os.path.basename(mpath)}: no recorded digest "
                   "(pre-digest fleet manifest)", report, quiet)
    elif mpath:
        n += 1
        _check(False, "merged", f"{mpath}: recorded but missing on disk",
               report, quiet)

    # serve jobs committed under this dir (a serve workdir audits the same
    # way: every done manifest carries the result digest)
    for mf in sorted(glob.glob(os.path.join(outdir, "jobs", "*",
                                            "manifest.json"))):
        try:
            with open(mf) as fh:
                jm = json.load(fh)
        except (OSError, ValueError):
            _check(False, f"job {os.path.basename(os.path.dirname(mf))}",
                   "torn manifest", report, quiet)
            n += 1
            continue
        sha, fpath = jm.get("fasta_sha256"), jm.get("fasta")
        if not sha or not fpath:
            continue
        n += 1
        ok = os.path.exists(fpath) and sha256_file(fpath) == sha
        _check(ok, f"job {jm.get('job', '?')}",
               f"result sha256 {'verified' if ok else 'MISMATCH'}",
               report, quiet)
    return report, n


def resolve_sample(outdir: str, shard: int, db_path: str, las_path: str,
                   k: int, report: list[dict], quiet: bool = False) -> None:
    """Re-solve the first ``k`` piles of ``shard`` on the pure host
    reference path and compare fragment bytes against the committed shard
    FASTA — the offline twin of the supervisor's shadow audit. Sound
    because output bytes are engine-invariant (the repo's load-bearing
    parity) and per-read fragments are independent."""
    from ..formats import LasFile, read_db
    from ..parallel.launch import load_shard_manifest, shard_paths
    from ..runtime import PipelineConfig, correct_shard
    from ..utils.bases import ints_to_seq

    m, why = load_shard_manifest(outdir, shard)
    if m is None:
        _check(False, f"resolve shard {shard}", why or "no manifest",
               report, quiet)
        return
    # committed fragments keyed the way correct_to_fasta names them
    committed: dict[str, str] = {}
    name = None
    with open(shard_paths(outdir, shard)["fasta"]) as fh:
        for line in fh:
            if line.startswith(">"):
                name = line[1:].strip()
                committed[name] = ""
            elif name:
                committed[name] += line.strip()
    start, end = m.get("byte_range") or (None, None)
    db = read_db(db_path)
    las = LasFile(las_path)
    # reference config: host path, no native, supervision (and its audit)
    # off — this IS the reference, nothing to escalate to
    cfg = PipelineConfig(supervise=False, use_native=False)
    done = 0
    for rid, frags, _ in correct_shard(db, las, cfg, start, end):
        for fi, f in enumerate(frags):
            key = f"read{rid}/{fi}"
            got = ints_to_seq(f)
            want = committed.get(key)
            if want is None:
                _check(False, f"resolve read{rid}",
                       f"fragment {fi} absent from committed FASTA",
                       report, quiet)
            elif got != want:
                _check(False, f"resolve read{rid}",
                       f"fragment {fi} bytes differ from committed FASTA",
                       report, quiet)
        done += 1
        if done >= k:
            break
    _check(True, f"resolve shard {shard}",
           f"{done} pile(s) re-solved on the reference path", report, quiet)


def audit_main(argv=None) -> int:
    """daccord-audit: re-verify a committed run's digests offline, and
    optionally re-solve a sample of piles on the reference path."""
    p = argparse.ArgumentParser(prog="daccord-audit",
                                description=audit_main.__doc__)
    p.add_argument("outdir", help="shard/fleet outdir or serve workdir")
    p.add_argument("--nshards", type=int, default=None,
                   help="shard count (default: fleet.json, else glob)")
    p.add_argument("--merged", default=None, metavar="FASTA",
                   help="merged output to verify against fleet.json's "
                        "recorded digest (default: the recorded path)")
    p.add_argument("--db", default=None, help="Dazzler DB (for --resolve)")
    p.add_argument("--las", default=None, help="LAS file (for --resolve)")
    p.add_argument("--resolve", type=int, default=0, metavar="K",
                   help="re-solve the first K piles of --shard on the host "
                        "reference path and byte-compare (requires --db/--las)")
    p.add_argument("--shard", type=int, default=0,
                   help="which shard --resolve samples (default 0)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    args = p.parse_args(argv)

    report, n = audit_outdir(args.outdir, nshards=args.nshards,
                             merged=args.merged, quiet=args.json)
    if args.resolve > 0:
        if not (args.db and args.las):
            p.error("--resolve requires --db and --las")
        resolve_sample(args.outdir, args.shard, args.db, args.las,
                       args.resolve, report, quiet=args.json)
    failed = [r for r in report if not r["ok"]]
    if args.json:
        print(json.dumps({"audited": n, "checks": report,
                          "failed": len(failed)}))
    else:
        print(f"daccord-audit: {len(report)} check(s), "
              f"{len(failed)} failure(s)", file=sys.stderr)
    if failed:
        return 1
    return 0 if n else 2


if __name__ == "__main__":
    raise SystemExit(audit_main())
