"""Shard fleet orchestrator: supervised multi-shard runs (``daccord-fleet``).

PR 1 made a single shard survive device loss and PR 2 made its inputs and
outputs survive corruption and crashes; this layer supervises the *job*: the
reference's ``-J i,n`` model (SURVEY.md §2.3) asks a human to submit every
shard and to notice dead workers, and ``daccord-merge`` concatenated whatever
it found. Here one orchestrator (or several, on different hosts) drives all N
shards to completion unattended — the ParaFold supervising-scheduler model
(PAPERS.md) over the reference's shared-filesystem data plane.

**Work distribution is coordinator-free.** A shard is claimed by atomically
creating its lease file (``O_CREAT|O_EXCL``, :func:`aio.exclusive_create`) in
``OUTDIR/leases/``; of N hosts racing, exactly one wins. The holder renews
the lease by bumping its mtime every ``heartbeat_s``; a lease whose mtime is
older than ``lease_ttl_s`` is *stale* — its host died or wedged — and any
orchestrator (including a recovered self) may take the shard over by removing
the stale file and re-claiming. No coordinator process, no network protocol:
the shared filesystem the reference already requires IS the control plane,
so hosts can join or leave an in-flight run freely (elasticity). The TTL
must exceed a few heartbeats plus worst-case shared-FS mtime propagation and
host clock skew; takeover is logged with the previous holder's identity.

**Workers are expendable subprocesses** (``daccord-shard``), bounded by a
local slot pool. Their failure modes are detected, not awaited:

- *crash* — nonzero exit (or exit 0 without a trustworthy manifest);
- *hang* — no shard-manifest commit and no progress-manifest mtime movement
  for ``stall_timeout_s`` (the worker is SIGKILLed);
- *host death* — the lease goes stale and another orchestrator takes over.

A failed shard is requeued with exponential backoff + deterministic jitter
and bounded attempts. Because shard commits are idempotent and crash-durable
(PR 2), a requeued worker resumes from the last checkpoint and the final
FASTA is byte-identical to an unfaulted run.

**Poison-shard quarantine.** A shard that kills ``poison_after`` consecutive
workers (or exhausts ``max_attempts``) is declared poison and quarantined in
the fleet manifest — with its last stderr tail and the quarantine-sidecar
path, mirroring PR 2's per-pile containment one level up — while the rest of
the fleet continues. The validating merge gate (:func:`launch.merge_shards`)
then refuses the incomplete fleet unless ``--allow-degraded``.

**Stragglers** are flagged from progress-manifest throughput (reads/s vs the
fleet median, :func:`flag_stragglers`) and may be speculatively re-executed:
the lagging worker is killed and the shard requeued immediately — safe
because the checkpointed commit makes re-execution lossless, and strictly
serialized per shard so two workers never append to one FASTA.

**Capacity awareness** (ISSUE 5). A worker that exits 137 / SIGKILL without
a watchdog kill of our own is the kernel OOM-killer's work, not a crash and
not poison: the shard is requeued ONCE at a reduced batch (threaded through
the worker's ``-b``), logged as ``fleet.capacity``; the checkpointed resume
keeps the merged output byte-identical. A second OOM at the reduced batch
falls through to the normal failure ladder. Shards whose workers ratcheted
their dispatch width (capacity governor, ``runtime/governor.py``) commit
``batch_effective``/``governor`` manifest state and pass the merge gate
WITHOUT ``--allow-degraded`` — capacity degrades speed, never bytes.

Fault injection (``runtime/faults.py``): ``worker_crash:N`` sends the Nth
spawned worker a mid-shard ``crash`` spec, ``worker_hang:N`` replaces the Nth
spawn with a progress-free sleeper, ``worker_oom:N`` replaces it with an
exit-137 OOM-kill stand-in, ``lease_stall`` stops heartbeating the Nth
claimed lease (backdated so the takeover fires without waiting out the
TTL) — the whole matrix runs on CPU in CI. Events (``fleet.*``: spawn,
heartbeat, takeover, retry, capacity, poison, speculate, done) are
schema-linted by ``eventcheck``.
"""

from __future__ import annotations

import json
import os
import random
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass

from ..runtime.faults import FaultPlan, non_fleet_spec
from ..utils import aio, lease
from ..utils.obs import JsonlLogger, NullLogger
from .launch import _write_manifest_durable, load_shard_manifest, shard_paths

#: device-op index of the ``crash`` spec injected into a worker_crash-
#: sabotaged worker: late enough that the shard is genuinely mid-flight
#: (batches dispatched, checkpoints possibly committed), so the requeue
#: exercises resume — not just a failed spawn
_WORKER_CRASH_OP = 3

#: stderr bytes preserved in the fleet manifest for a poison shard
_STDERR_TAIL_BYTES = 4000


def lease_path(outdir: str, shard: int) -> str:
    return os.path.join(outdir, "leases", f"shard{shard:04d}.lease")


# The lease protocol itself (O_EXCL claim, re-read-before-renew heartbeat,
# holder-checked release, stale takeover) lives in utils/lease.py — shared
# verbatim with the serve tier's per-job leases (ISSUE 15). These wrappers
# keep the fleet's (outdir, shard) addressing.

def claim_lease(outdir: str, shard: int, host: str,
                ttl_s: float) -> tuple[bool, dict | None]:
    """Try to claim ``shard``'s lease for ``host`` (see ``utils.lease.claim``
    for the race-safety contract)."""
    return lease.claim(lease_path(outdir, shard), host, ttl_s,
                       extra={"shard": shard})


def read_lease(outdir: str, shard: int) -> dict | None:
    """The lease's payload, or None when absent/torn."""
    return lease.read(lease_path(outdir, shard))


def renew_lease(outdir: str, shard: int) -> None:
    """Heartbeat: bump the lease mtime (the staleness clock other hosts read)."""
    lease.renew(lease_path(outdir, shard))


def release_lease(outdir: str, shard: int, host: str | None = None) -> None:
    """Remove the lease; with ``host`` given, only while it still names that
    host (holder-checked release — see ``utils.lease.release``)."""
    lease.release(lease_path(outdir, shard), host=host)


def backdate_lease(outdir: str, shard: int, age_s: float) -> None:
    """Set the lease's mtime ``age_s`` into the past — how ``lease_stall``
    makes a wedged host's lease stale deterministically instead of burning
    ``lease_ttl_s`` of CI wall-clock (also the test hook for simulating a
    host that died right after claiming)."""
    lease.backdate(lease_path(outdir, shard), age_s)


def flag_stragglers(throughputs: dict[int, float],
                    factor: float) -> list[int]:
    """Shard ids whose reads/s lag the fleet median by ``factor``×.

    Pure policy, unit-testable: with fewer than 2 measurable shards or a
    zero median (nobody has emitted yet) nothing is flagged — speculation
    must never trigger on startup noise."""
    if factor <= 0 or len(throughputs) < 2:
        return []
    vals = sorted(throughputs.values())
    median = vals[len(vals) // 2]
    if median <= 0:
        return []
    return sorted(s for s, v in throughputs.items() if v * factor < median)


@dataclass
class FleetConfig:
    nshards: int
    workers: int = 2                  # local worker subprocess slots
    max_attempts: int = 5             # worker spawns per shard before poison
    poison_after: int = 3             # consecutive failures => poison
    heartbeat_s: float = 1.0          # lease mtime renewal period
    lease_ttl_s: float = 15.0         # older lease is stale (takeover)
    stall_timeout_s: float = 600.0    # no progress movement => hung worker
    backoff_base_s: float = 0.25
    backoff_cap_s: float = 8.0
    jitter: float = 0.25              # +[0, jitter) fraction, deterministic RNG
    speculate_factor: float = 4.0     # straggler threshold vs median (0 = off)
    speculate_min_runtime_s: float = 60.0
    poll_s: float = 0.05
    host: str = ""                    # lease identity; default hostname:pid
    events_path: str | None = None    # fleet.* jsonl sidecar
    # worker knobs (forwarded to daccord-shard)
    backend: str = "auto"
    batch: int | None = None
    checkpoint_every: int = 16        # >0: progress manifests drive hang
                                      # detection and lossless requeue
    ingest_policy: str = "strict"
    paged: str = "off"                # ragged paged window batching, forwarded
                                      # to every worker (see daccord --paged)
    mesh: int = 0                     # each worker shards its batches over
                                      # the first N local devices (forwarded
                                      # as daccord-shard --mesh): one host,
                                      # N chips is ONE worker — the capacity
                                      # requeue and auto batch sizing scale
                                      # by N (0/1 = single device)
    max_pile_overlaps: int | None = None  # monster-pile budget (None = the
                                          # pipeline default; 0 disables)
    disk_floor_mb: float = 0.0        # free-bytes spawn floor (ISSUE 17):
                                      # below this much free space on the
                                      # outdir volume the orchestrator
                                      # refuses to spawn NEW workers (each
                                      # writes shard outputs + telemetry
                                      # there) — running workers finish,
                                      # leases stay claimable by peers on
                                      # healthier volumes. 0 = off
    worker_telemetry: bool = True     # thread per-worker telemetry sidecars
                                      # (ISSUE 6): every daccord-shard worker
                                      # writes shardNNNN.events.jsonl (trace
                                      # spans + supervisor/governor events,
                                      # absolute-ts merge-able by
                                      # daccord-trace) and the per-window
                                      # outcome ledger shardNNNN.ledger.jsonl


@dataclass
class _Shard:
    shard: int
    status: str = "pending"           # pending|foreign|running|done|poison
    attempts: int = 0
    consec_fail: int = 0
    next_try_t: float = 0.0
    proc: subprocess.Popen | None = None
    spawn_t: float = 0.0
    stderr_path: str | None = None
    kill_reason: str | None = None
    last_emitted: int = 0
    last_beat: float = 0.0
    speculated: bool = False
    manifest: dict | None = None
    poison_reason: str | None = None
    # capacity awareness (ISSUE 5): a worker the kernel OOM-killed is a
    # resource-fit problem, not a poison input — requeued ONCE at a reduced
    # batch (threaded to the worker) before the normal failure ladder applies
    oom_requeued: bool = False
    batch_override: int | None = None
    span: str | None = None           # open worker-attempt trace span id


def _stderr_tail(path: str | None) -> str:
    if not path or not os.path.exists(path):
        return ""
    try:
        with open(path, "rb") as fh:
            fh.seek(max(0, os.path.getsize(path) - _STDERR_TAIL_BYTES))
            return fh.read().decode(errors="replace")
    except OSError:
        return ""


class Fleet:
    """One orchestrator instance; :func:`run_fleet` is the entry point."""

    def __init__(self, db: str, las: str, outdir: str, cfg: FleetConfig,
                 faults: FaultPlan | None = None):
        self.db, self.las, self.outdir, self.cfg = db, las, outdir, cfg
        self.faults = faults
        self.host = cfg.host or f"{socket.gethostname()}:{os.getpid()}"
        os.makedirs(outdir, exist_ok=True)  # the events sidecar lands here
        self.log = JsonlLogger(cfg.events_path) if cfg.events_path \
            else NullLogger()
        # trace spans (ISSUE 6): one span per worker attempt (spawn → reap)
        # under a fleet-run root, so daccord-trace can draw the fleet
        # timeline straight from the orchestrator's own sidecar
        from ..utils.obs import Tracer

        self.tracer = Tracer(self.log)
        self._run_span: str | None = None
        self._rng = random.Random(0xF1EE7)  # deterministic backoff jitter
        self.shards = {s: _Shard(s) for s in range(cfg.nshards)}
        self.poison: list[dict] = []
        self._t0 = time.time()
        # pre-resolve the auto-backend batch off the heartbeat path: the
        # capacity requeue needs it, and resolving lazily would block the
        # single-threaded fleet loop on the bounded backend probe (up to
        # DACCORD_PROBE_TIMEOUT_S) — long enough to stale every lease this
        # host holds and hand its healthy shards to other hosts
        self._auto_batch: int | None = None
        self._auto_batch_thread: threading.Thread | None = None
        if cfg.backend == "auto" and not cfg.batch:
            self._auto_batch_thread = threading.Thread(
                target=self._resolve_auto_batch, daemon=True)
            self._auto_batch_thread.start()

    def _resolve_auto_batch(self) -> None:
        from ..utils.obs import auto_batch_size, resolve_auto_backend

        mesh = self.cfg.mesh if self.cfg.mesh and self.cfg.mesh > 1 else 0
        try:
            # mesh workers cannot run the native engine — resolve exactly
            # as the worker CLI will (prefer_native=mesh<=1)
            backend = resolve_auto_backend(prefer_native=not mesh)
        except Exception:
            backend = "cpu"
        self._auto_batch = auto_batch_size(backend == "native", backend,
                                           mesh=mesh)

    # -- worker process management ------------------------------------------

    def _worker_argv(self, shard: int) -> list[str]:
        cfg = self.cfg
        argv = [sys.executable, "-m", "daccord_tpu.tools.cli", "shard",
                self.db, self.las, self.outdir,
                "-J", f"{shard},{cfg.nshards}",
                "--backend", cfg.backend,
                "--checkpoint-every", str(cfg.checkpoint_every),
                "--ingest-policy", cfg.ingest_policy,
                "--paged", cfg.paged]
        if cfg.worker_telemetry:
            # per-worker sidecars land beside the shard outputs; attempts
            # append (shard_start is the eventcheck stream boundary) and
            # daccord-trace merges them with the fleet's own file on ts
            p = shard_paths(self.outdir, shard)
            argv += ["--events", p["events"], "--ledger", p["ledger"]]
        else:
            # daccord-shard's own --ledger default is 'auto': an opted-out
            # fleet must say so explicitly or workers write ledgers anyway
            argv += ["--ledger", "none"]
        if cfg.mesh and cfg.mesh > 1:
            # the worker drives a local device mesh (daccord --mesh model);
            # plumbed like --max-pile-overlaps was in PR 5 — a fleet that
            # cannot forward it would run every multi-chip host single-chip
            argv += ["--mesh", str(cfg.mesh)]
        if cfg.max_pile_overlaps is not None:
            argv += ["--max-pile-overlaps", str(cfg.max_pile_overlaps)]
        # a capacity-requeued shard re-runs at its reduced batch (the env-
        # derived override threaded through the worker's own -b knob); the
        # checkpointed resume keeps the output byte-identical regardless —
        # batch size never reaches the per-window math
        batch = self.shards[shard].batch_override or cfg.batch
        if batch:
            argv += ["-b", str(batch)]
        return argv

    def _worker_batch(self) -> int:
        """The batch a worker actually runs: cfg.batch when -b was given,
        else the pipeline's auto-selection for this backend (native 4096;
        JAX 2048 on TPU, 512 elsewhere; scaled by mesh width — one host, N
        chips is one worker). The capacity requeue halves THIS number —
        halving a hardcoded guess instead would cut an auto-batch native
        worker 16x, not 2x."""
        from ..utils.obs import auto_batch_size

        mesh = self.cfg.mesh if self.cfg.mesh and self.cfg.mesh > 1 else 0
        if self.cfg.batch:
            return self.cfg.batch
        if self.cfg.backend == "auto":
            # resolved exactly as the worker CLI will (bounded probe, native
            # preferred on a dead tunnel) by the thread started at init —
            # by the time a worker has run long enough to OOM, the probe is
            # long done and this join is instant
            if self._auto_batch_thread is not None:
                self._auto_batch_thread.join()
            return self._auto_batch or auto_batch_size(False, mesh=mesh)
        return auto_batch_size(self.cfg.backend == "native", self.cfg.backend,
                               mesh=mesh)

    def _worker_env(self, sabotage: str | None) -> dict:
        env = dict(os.environ)
        # the worker must import daccord_tpu regardless of its cwd or an
        # uninstalled checkout: prepend the package's parent directory
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        # fleet kinds describe THIS orchestrator; only device/data kinds
        # pass through to the worker
        spec = non_fleet_spec(env.pop("DACCORD_FAULT", None))
        if sabotage == "worker_crash":
            spec = ",".join(x for x in (spec, f"crash:{_WORKER_CRASH_OP}") if x)
        if spec:
            env["DACCORD_FAULT"] = spec
        return env

    def _spawn(self, st: _Shard) -> None:
        cfg, s = self.cfg, st.shard
        sabotage = self.faults.fleet_spawn() if self.faults else None
        st.attempts += 1
        argv = self._worker_argv(s)
        if sabotage == "worker_hang":
            # a wedged worker: alive pid, no progress manifest ever — only
            # the stall watchdog can reclaim its slot
            argv = [sys.executable, "-c", "import time; time.sleep(600)"]
        elif sabotage == "worker_oom":
            # an OOM-killed worker: the kernel's SIGKILL surfaces as exit
            # status 137 with no manifest — the capacity-requeue path's
            # deterministic stand-in
            argv = [sys.executable, "-c", "import os; os._exit(137)"]
        if sabotage:
            self.log.log("fleet.fault", kind=sabotage, shard=s)
        st.stderr_path = os.path.join(
            self.outdir, f"shard{s:04d}.a{st.attempts}.stderr")
        with open(st.stderr_path, "wb") as errfh:
            st.proc = subprocess.Popen(argv, env=self._worker_env(sabotage),
                                       stdout=errfh,
                                       stderr=subprocess.STDOUT)
        st.status = "running"
        st.spawn_t = st.last_beat = time.time()
        st.kill_reason = None
        st.last_emitted = 0
        st.span = self.tracer.open("worker", attach=False,
                                   parent=self._run_span or "",
                                   shard=s, attempt=st.attempts,
                                   pid=st.proc.pid)
        self.log.log("fleet.spawn", shard=s, attempt=st.attempts,
                     pid=st.proc.pid)

    def _progress(self, st: _Shard) -> tuple[float, int]:
        """(mtime, emitted) of the shard's progress manifest; the spawn time
        and 0 when none exists yet (startup / non-checkpointed worker)."""
        p = shard_paths(self.outdir, st.shard)["progress"]
        try:
            mtime = os.path.getmtime(p)
        except OSError:
            return st.spawn_t, st.last_emitted
        emitted = st.last_emitted
        try:
            with open(p) as fh:
                emitted = int(json.load(fh).get("emitted", emitted))
        except (OSError, json.JSONDecodeError, ValueError, TypeError):
            pass  # torn mid-commit read: keep the last good value
        return mtime, emitted

    # -- failure / completion handling --------------------------------------

    def _mark_done(self, st: _Shard, m: dict) -> None:
        st.status, st.manifest = "done", m
        release_lease(self.outdir, st.shard, host=self.host)
        self.log.log("fleet.done", shard=st.shard,
                     reads=int(m.get("reads", 0)),
                     degraded=bool(m.get("degraded")))

    def _fail(self, st: _Shard, reason: str) -> None:
        cfg = self.cfg
        release_lease(self.outdir, st.shard, host=self.host)
        if reason in ("speculate", "capacity"):
            # not shard failures: a speculative kill is the fleet's own
            # doing, and an OOM-killed worker is a resource-fit problem the
            # reduced-batch requeue remedies — neither earns poison-streak
            # credit (attempts stay bounded either way)
            st.status, st.next_try_t = "pending", 0.0
            self.log.log("fleet.retry", shard=st.shard, attempt=st.attempts,
                         delay_s=0.0, reason=reason)
            return
        st.consec_fail += 1
        if st.consec_fail >= cfg.poison_after or st.attempts >= cfg.max_attempts:
            why = (f"{st.consec_fail} consecutive worker failures"
                   if st.consec_fail >= cfg.poison_after
                   else f"attempts exhausted ({st.attempts})")
            st.status, st.poison_reason = "poison", f"{why}; last: {reason}"
            qpath = shard_paths(self.outdir, st.shard)["quarantine"]
            self.poison.append({
                "shard": st.shard, "attempts": st.attempts,
                "reason": st.poison_reason,
                "stderr_tail": _stderr_tail(st.stderr_path),
                "quarantine": qpath if os.path.exists(qpath) else None,
            })
            self.log.log("fleet.poison", shard=st.shard, attempts=st.attempts,
                         reason=st.poison_reason)
            return
        delay = min(cfg.backoff_cap_s,
                    cfg.backoff_base_s * (2 ** (st.consec_fail - 1)))
        delay *= 1.0 + cfg.jitter * self._rng.random()
        st.status, st.next_try_t = "pending", time.time() + delay
        self.log.log("fleet.retry", shard=st.shard, attempt=st.attempts,
                     delay_s=round(delay, 3), reason=reason)

    # -- supervision loop ----------------------------------------------------

    def _reap(self) -> None:
        for st in self.shards.values():
            if st.status != "running" or st.proc is None:
                continue
            rc = st.proc.poll()
            if rc is None:
                continue
            st.proc = None
            self.tracer.close(st.span, rc=int(rc),
                              reason=st.kill_reason or "")
            st.span = None
            m, why = load_shard_manifest(self.outdir, st.shard)
            if rc == 0 and m is not None:
                st.consec_fail = 0
                self._mark_done(st, m)
            elif st.kill_reason == "ownership_lost":
                # the taker's worker owns the shard; watch it like any
                # foreign shard (done when its manifest lands, reclaimable
                # when its lease goes stale). Not a failure of the shard.
                st.status = "foreign"
            elif st.kill_reason == "speculate":
                self._fail(st, "speculate")
            elif rc in (137, -9) and st.kill_reason is None \
                    and not st.oom_requeued:
                # exit 137 / SIGKILL without a watchdog kill of our own: the
                # kernel OOM-killer (or the injected worker_oom stand-in).
                # A capacity-degraded exit is NOT a crash: requeue ONCE at a
                # reduced batch — the checkpointed resume keeps the bytes —
                # instead of counting it toward poison. A second OOM at the
                # reduced batch falls through to the normal failure ladder.
                st.oom_requeued = True
                st.batch_override = max(16, self._worker_batch() // 2)
                self.log.log("fleet.capacity", shard=st.shard,
                             batch=st.batch_override)
                self._fail(st, "capacity")
            else:
                reason = st.kill_reason or f"exit:{rc}"
                if rc == 0:
                    reason = f"exit:0 without a valid manifest" \
                             + (f" ({why})" if why else "")
                self._fail(st, reason)

    def _watchdog(self, now: float) -> None:
        cfg = self.cfg
        for st in self.shards.values():
            if st.status != "running" or st.proc is None or st.kill_reason:
                continue
            # a manifest committed DURING this attempt means the worker is in
            # its final moments — never classify that as a hang. A manifest
            # predating the spawn is the stale one this attempt exists to
            # recompute; it must not mute the watchdog.
            try:
                committed = os.path.getmtime(
                    shard_paths(self.outdir, st.shard)["manifest"])
            except OSError:
                committed = None
            if committed is not None and committed >= st.spawn_t:
                continue
            mtime, emitted = self._progress(st)
            st.last_emitted = emitted
            if now - max(st.spawn_t, mtime) > cfg.stall_timeout_s:
                st.kill_reason = "hang"
                st.proc.kill()

    def _heartbeat(self, now: float) -> None:
        for st in self.shards.values():
            if st.status != "running" or st.kill_reason:
                continue
            if now - st.last_beat < self.cfg.heartbeat_s:
                continue
            st.last_beat = now
            # ownership re-check before renewal: if our lease went stale
            # (host pause, FS stall) and another orchestrator took the shard
            # over, renewing would keep THE TAKER'S lease fresh while two
            # workers append to one FASTA. Kill ours instead and treat the
            # shard as foreign — the taker owns it now.
            lease = read_lease(self.outdir, st.shard)
            if lease is not None and lease.get("host") != self.host:
                st.kill_reason = "ownership_lost"
                st.proc.kill()
                self.log.log("fleet.demote", shard=st.shard,
                             new_host=str(lease.get("host", "?")))
                continue
            renew_lease(self.outdir, st.shard)
            self.log.log("fleet.heartbeat", shard=st.shard,
                         emitted=st.last_emitted)

    def _recheck_foreign(self) -> None:
        """Shards another (live) host holds: done when their manifest lands,
        back to pending when their lease goes stale or vanishes."""
        for st in self.shards.values():
            if st.status != "foreign":
                continue
            m, _ = load_shard_manifest(self.outdir, st.shard)
            if m is not None:
                st.status, st.manifest = "done", m
                self.log.log("fleet.done", shard=st.shard,
                             reads=int(m.get("reads", 0)),
                             degraded=bool(m.get("degraded")))
                continue
            path = lease_path(self.outdir, st.shard)
            try:
                stale = time.time() - os.path.getmtime(path) > self.cfg.lease_ttl_s
            except OSError:
                stale = True  # released without output: reclaimable
            if stale:
                st.status, st.next_try_t = "pending", 0.0

    def _claim_and_spawn(self, now: float) -> None:
        cfg = self.cfg
        if cfg.disk_floor_mb:
            from ..utils.obs import disk_free_mb

            free = disk_free_mb(self.outdir)
            if 0 <= free < cfg.disk_floor_mb:
                # below the free-bytes floor: spawning another writer would
                # only deepen the hole. Running workers finish; pending
                # shards wait (their leases stay claimable by peers whose
                # volumes have headroom). Logged at most once per second —
                # the poll loop spins at poll_s.
                if now - getattr(self, "_disk_floor_logged", 0.0) >= 1.0:
                    self._disk_floor_logged = now
                    self.log.log("disk.pressure", level="spawn_floor",
                                 src="fleet", free_mb=round(free, 1),
                                 detail=f"floor {cfg.disk_floor_mb:.0f} MiB")
                return
        slots = cfg.workers - sum(1 for st in self.shards.values()
                                  if st.status == "running")
        for st in sorted(self.shards.values(), key=lambda s: s.shard):
            if slots <= 0:
                break
            if st.status != "pending" or st.next_try_t > now:
                continue
            claimed, takeover = claim_lease(self.outdir, st.shard, self.host,
                                            cfg.lease_ttl_s)
            if not claimed:
                st.status = "foreign"
                continue
            if takeover:
                self.log.log("fleet.takeover", shard=st.shard, **takeover)
            if self.faults and self.faults.fleet_claim_stall():
                # the host wedges right after claiming: heartbeats never
                # start, and the backdate makes the stale-lease takeover
                # (by any orchestrator, this one included) fire immediately
                backdate_lease(self.outdir, st.shard, cfg.lease_ttl_s + 1.0)
                self.log.log("fleet.fault", kind="lease_stall", shard=st.shard)
                continue
            self._spawn(st)
            slots -= 1

    def _maybe_speculate(self, now: float) -> None:
        cfg = self.cfg
        if cfg.speculate_factor <= 0:
            return
        if any(st.status == "pending" for st in self.shards.values()):
            return  # real work queued: never burn a slot on speculation
        if sum(1 for st in self.shards.values()
               if st.status == "running") >= cfg.workers:
            return
        # kill_reason guards the race with _watchdog in the same iteration
        # (a hang kill must keep its classification — and its poison-streak
        # credit); zero-emitted workers are the watchdog's problem, never
        # speculation's
        thr = {st.shard: st.last_emitted / max(now - st.spawn_t, 1e-9)
               for st in self.shards.values()
               if st.status == "running" and not st.speculated
               and not st.kill_reason and st.last_emitted > 0
               and now - st.spawn_t > cfg.speculate_min_runtime_s}
        for s in flag_stragglers(thr, cfg.speculate_factor):
            st = self.shards[s]
            vals = sorted(thr.values())
            self.log.log("fleet.speculate", shard=s,
                         throughput=round(thr[s], 6),
                         median=round(vals[len(vals) // 2], 6))
            st.speculated, st.kill_reason = True, "speculate"
            st.proc.kill()

    def run(self) -> dict:
        cfg = self.cfg
        os.makedirs(self.outdir, exist_ok=True)
        self.log.log("fleet.init", nshards=cfg.nshards, workers=cfg.workers,
                     host=self.host)
        self._run_span = self.tracer.open("fleet.run", nshards=cfg.nshards)
        # idempotent rerun: shards that already committed need no worker
        for st in self.shards.values():
            m, _ = load_shard_manifest(self.outdir, st.shard)
            if m is not None:
                self._mark_done(st, m)
        try:
            # process reaping and claim/spawn run at poll_s (local, cheap);
            # everything that stats/reads the shared filesystem (progress
            # manifests, foreign manifests/leases) runs at heartbeat cadence
            # — that state only changes on a heartbeat timescale, and a
            # 20 Hz metadata storm per orchestrator is what kills shared-FS
            # deployments
            scan_every = min(cfg.heartbeat_s, 1.0)
            last_scan = 0.0
            while any(st.status not in ("done", "poison")
                      for st in self.shards.values()):
                now = time.time()
                self._reap()
                if now - last_scan >= scan_every:
                    last_scan = now
                    self._watchdog(now)
                    self._recheck_foreign()
                    self._maybe_speculate(now)
                self._heartbeat(now)
                self._claim_and_spawn(now)
                time.sleep(cfg.poll_s)
            manifest = {
                "nshards": cfg.nshards, "host": self.host,
                "wall_s": round(time.time() - self._t0, 3),
                "done": sorted(s for s, st in self.shards.items()
                               if st.status == "done"),
                "poison": self.poison,
                "degraded": sorted(s for s, st in self.shards.items()
                                   if st.manifest
                                   and (st.manifest.get("degraded")
                                        or st.manifest.get("quarantined"))),
                "attempts": {str(s): st.attempts
                             for s, st in self.shards.items()},
                # capacity awareness (ISSUE 5): OOM-killed workers requeued
                # at a reduced batch — enumerated (with the shard manifests'
                # batch_effective/governor state) so a round report can tell
                # capacity-degraded speed from degraded output
                "capacity_requeued": sorted(
                    s for s, st in self.shards.items() if st.oom_requeued),
            }
            _write_manifest_durable(os.path.join(self.outdir, "fleet.json"),
                                    manifest)
            # fleet-level scrape target (ISSUE 13): the shard rollups as one
            # shard-labeled prom exposition beside fleet.json
            try:
                prom = _fleet_prom_text(self.outdir)
                if prom:
                    aio.durable_write(
                        os.path.join(self.outdir, "fleet.metrics.prom"),
                        lambda fh: fh.write(prom), mode="wt")
            except OSError:
                pass
            self.log.log("fleet.finish", done=len(manifest["done"]),
                         poison=len(manifest["poison"]),
                         wall_s=manifest["wall_s"])
            self.tracer.close(self._run_span, status="done")
            return manifest
        finally:
            # an exception (or KeyboardInterrupt) must not strand worker
            # processes; released/stale leases let another host take over
            for st in self.shards.values():
                if st.proc is not None and st.proc.poll() is None:
                    st.proc.kill()
                    st.proc.wait()
                if st.status == "running":
                    release_lease(self.outdir, st.shard, host=self.host)
            # abort unwind: any spans still open (stranded workers, the
            # fleet-run root on an exception path) close with status=abort
            self.tracer.unwind()
            self.log.close()


def _fleet_prom_text(outdir: str) -> str:
    """One Prometheus exposition merging every committed shard rollup,
    shard-labeled — the fleet-level scrape target (ISSUE 13). The text
    format requires all samples of a metric to form ONE group, so samples
    regroup per metric family across shards (a shard-by-shard concat
    would interleave families and fail promtool) under a single ``# TYPE``
    each; a torn rollup skips — best-effort, it never sinks the fleet."""
    import glob
    import json as _json

    from ..utils.obs import render_prom

    fam_type: dict[str, str] = {}
    fam_samples: dict[str, list[str]] = {}
    order: list[str] = []
    for mp in sorted(glob.glob(os.path.join(outdir,
                                            "shard*.metrics.json"))):
        try:
            with open(mp) as fh:
                roll = _json.load(fh)
        except (OSError, _json.JSONDecodeError):
            continue
        if not isinstance(roll, dict) or "gauges" not in roll:
            continue
        text = render_prom(roll, labels={"shard": roll.get("shard", "?")})
        fam = None
        for ln in text.splitlines():
            if ln.startswith("# TYPE "):
                # render_prom emits every sample (incl. a summary's _count/
                # _sum) directly under its family's TYPE line
                fam = ln.split()[2]
                if fam not in fam_samples:
                    fam_type[fam] = ln
                    fam_samples[fam] = []
                    order.append(fam)
            elif fam is not None and ln.strip():
                fam_samples[fam].append(ln)
    lines: list[str] = []
    for fam in order:
        lines.append(fam_type[fam])
        lines.extend(fam_samples[fam])
    return "\n".join(lines) + "\n" if lines else ""


def run_fleet(db: str, las: str, outdir: str, cfg: FleetConfig,
              faults: FaultPlan | str | None = "env") -> dict:
    """Run all ``cfg.nshards`` shards to completion under supervision;
    returns (and durably writes, as ``OUTDIR/fleet.json``) the fleet
    manifest. ``faults`` defaults to the process ``DACCORD_FAULT`` plan
    (fleet kinds only — device/data kinds pass through to workers); pass
    ``None`` for an explicitly clean run or a :class:`FaultPlan` directly.

    The final `fleet.finish` event and the manifest enumerate done vs poison
    shards; completion of the *fleet* means every shard is terminal — a
    poison shard is quarantined, not blocking.
    """
    if faults == "env":
        faults = FaultPlan.from_env()
    return Fleet(db, las, outdir, cfg, faults=faults).run()
