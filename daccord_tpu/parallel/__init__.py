from .mesh import make_mesh, make_sharded_solver
from .launch import (init_distributed, run_shard, merge_shards,
                     load_shard_manifest, MergeGateError)
from .fleet import FleetConfig, run_fleet

__all__ = ["make_mesh", "make_sharded_solver", "init_distributed",
           "run_shard", "merge_shards", "load_shard_manifest",
           "MergeGateError", "FleetConfig", "run_fleet"]
