from .mesh import make_mesh, make_sharded_solver
from .launch import init_distributed, run_shard, merge_shards

__all__ = ["make_mesh", "make_sharded_solver", "init_distributed", "run_shard", "merge_shards"]
