"""Device mesh + sharded window solving.

The reference's only intra-process parallelism is a pthread pool over
reads/windows (SimpleThreadPool, SURVEY.md §2.3); the TPU equivalent shards
the *window batch dimension* across a 1-D device mesh. Piles are independent,
so there is no cross-window communication — the only collective is the psum
of the escalation-overflow counter, deliberately preserving the reference's
zero-communication design (SURVEY.md §5 "Distributed communication backend").

The full escalation ladder (tier 0 + device-compacted rescue tiers, see
``kernels.tiers.ladder_core``) runs INSIDE shard_map: each device solves and
escalates its own slice, so one sharded batch costs one dispatch and one
fetch regardless of mesh size. The solver speaks every wire format the
single-device path does:

- dense ``WindowBatch`` (the r1-r8 format);
- the ragged paged format (``kernels/paging.py``): the page TABLE shards on
  the batch axis while the page POOL replicates — per-device gather indices
  are global pool-page ids, so each shard gathers its own dense tile from
  the replicated pool inside the same jitted program (Ragged Paged
  Attention's per-device-gather argument, PAPERS.md arxiv 2604.15464);
- the two-stream split ladder (``routes_streams``): a ``stream='tier0'``
  batch dispatches the sharded tier0-only program, everything else the full
  sharded ladder — the same routing rule as ``kernels.tiers
  .stream_dispatcher``, so ``:t0`` and ``:m<N>`` compile keys compose.

**Partial-mesh degradation** (runtime/supervisor.py): :meth:`shrink` halves
the device set N → N/2 → … → 1 and the supervisor re-dispatches retained
batches on the smaller mesh instead of failing over whole-program — byte-
identical by per-window independence (re-sharding a window cannot change its
bytes). :meth:`restore` rebuilds the full mesh on failback.

**Staged dispatch** (ISSUE 19): the monolithic pad+split+transfer+launch
dispatch decomposes into :meth:`ShardedLadderSolver.stage` (host pad to a
mesh multiple, then per-device single-shard ``device_put`` assembled into
one global ``jax.Array`` via ``make_array_from_single_device_arrays`` — the
pre-partitioned-input pattern, which skips the commit-to-device-0-then-
reshard slow path of ``device_put(jnp.asarray(x), sharding)``) and
:meth:`ShardedLadderSolver.launch` (the jitted program call on the staged
arrays). ``dispatch`` remains the fused convenience form; the pipeline's
double-buffer stages batch N+1 under batch N's solve and launches the
retained ticket. A :class:`StagedBatch` keeps its *host-side* batch
(``replay_batch``) alive: launch detects a mesh changed since staging
(shrink/restore) and transparently discards + re-stages on the current
mesh, so supervisor replay, partial-mesh degradation, and the governor's
bisect always operate on host-side state — byte-identical by per-window
independence. Dispatch sub-walls accrue as ``pack_s``/``stage_s``/
``launch_s`` and the per-member ``overlap_frac`` (staging wall that ran
under an in-flight solve) rides :meth:`health_map`.

Multi-host scale-out composes this with host-side LAS byte-range sharding
(``formats.las.shard_ranges``): every process corrects its own aread range on
its local devices; see ``parallel.launch``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..kernels.tensorize import WindowBatch, pad_batch
from ..kernels.tiers import TierLadder, ladder_core, tier0_core
from ..kernels.window_kernel import KernelParams


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over ``n_devices`` (default: all local devices), axis 'd'."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=("d",))


#: the off-pod recipe every mesh entry point names on a device-count failure
OFF_POD_RECIPE = ("off-pod: set JAX_PLATFORMS=cpu and "
                  "XLA_FLAGS=--xla_force_host_platform_device_count=N")


def check_mesh_devices(n_devices: int) -> None:
    """Raise ``SystemExit`` with the off-pod recipe when fewer than
    ``n_devices`` devices are visible — the one device-count gate shared by
    the CLI, the pipeline's in-run construction, and the serve group."""
    if len(jax.devices()) < n_devices:
        raise SystemExit(
            f"mesh {n_devices}: only {len(jax.devices())} devices visible "
            f"({OFF_POD_RECIPE})")


def _vma_kw(use_pallas: bool) -> tuple:
    # pallas_call's out_shape carries no varying-axes info, so the vma check
    # must be off when the ladder routes its DP through the Pallas kernel
    # (the pre-0.8 fallback spells the same knob check_rep)
    try:
        from jax import shard_map  # jax >= 0.8
        return shard_map, {"check_vma": not use_pallas}
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
        return shard_map, {"check_rep": not use_pallas}


@functools.partial(jax.jit,
                   static_argnames=("params", "esc_cap", "mesh", "use_pallas",
                                    "pallas_interpret", "wide_p0"))
def _ladder_sharded(seqs, lens, nsegs, tables, params, esc_cap, mesh,
                    use_pallas=False, pallas_interpret=False, wide_p0=None):
    shard_map, vma_kw = _vma_kw(use_pallas)

    def local(seqs, lens, nsegs, tables):
        out = ladder_core(seqs, lens, nsegs, tables, params, esc_cap,
                          use_pallas, pallas_interpret, wide_p0)
        out["esc_overflow"] = jax.lax.psum(out["esc_overflow"], "d")
        return out

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P("d"), P("d"), P("d"), P()),
                   out_specs={"cons": P("d"), "cons_len": P("d"), "err": P("d"),
                              "solved": P("d"), "tier": P("d"), "m_ovf": P("d"),
                              "esc_overflow": P()},
                   **vma_kw)
    return fn(seqs, lens, nsegs, tables)


@functools.partial(jax.jit,
                   static_argnames=("params", "esc_cap", "mesh", "use_pallas",
                                    "pallas_interpret", "wide_p0"))
def _ladder_sharded_packed(seqs, lens, nsegs, tables, params, esc_cap, mesh,
                           use_pallas=False, pallas_interpret=False,
                           wide_p0=None):
    from ..kernels.tiers import pack_result

    # pack OUTSIDE shard_map, inside the same jit (nested jit inlines): the
    # packing ops are elementwise along the sharded batch axis, so XLA keeps
    # them local to each device and the result crosses as ONE array
    return pack_result(_ladder_sharded(
        seqs, lens, nsegs, tables, params, esc_cap, mesh, use_pallas,
        pallas_interpret, wide_p0))


@functools.partial(jax.jit,
                   static_argnames=("p0", "mesh", "use_pallas",
                                    "pallas_interpret"))
def _tier0_sharded_packed(seqs, lens, nsegs, table0, p0, mesh,
                          use_pallas=False, pallas_interpret=False):
    """Stream A of the two-stream ladder, sharded: each device runs the
    cheap tier0-only program over its own slice (the ``:t0`` compile, now at
    a ``:m<N>`` key). No collective at all — tier0 has no overflow counter."""
    from ..kernels.tiers import pack_result

    shard_map, vma_kw = _vma_kw(use_pallas)

    def local(seqs, lens, nsegs, table0):
        return tier0_core(seqs, lens, nsegs, table0, p0, use_pallas,
                          pallas_interpret)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P("d"), P("d"), P("d"), P()),
                   out_specs={"cons": P("d"), "cons_len": P("d"), "err": P("d"),
                              "solved": P("d"), "tier": P("d"), "m_ovf": P("d"),
                              "esc_overflow": P()},
                   **vma_kw)
    return pack_result(fn(seqs, lens, nsegs, table0))


@functools.partial(jax.jit,
                   static_argnames=("params", "esc_cap", "mesh", "page_len",
                                    "seg_len", "use_pallas",
                                    "pallas_interpret", "wide_p0"))
def _ladder_sharded_paged_packed(pool, table, lens, nsegs, tables, params,
                                 esc_cap, mesh, page_len, seg_len,
                                 use_pallas=False, pallas_interpret=False,
                                 wide_p0=None):
    """Paged wire format through shard_map: the page table (and lens/nsegs)
    shard on the batch axis, the page pool replicates, and each device's
    gather reconstructs its own dense tile from the replicated pool —
    table entries are global pool-page ids, so no offset rebasing is needed.
    The full ladder then runs per shard exactly as in the dense program."""
    from ..kernels.paging import gather_windows
    from ..kernels.tiers import pack_result

    shard_map, vma_kw = _vma_kw(use_pallas)

    def local(pool, table, lens, nsegs, tables):
        seqs = gather_windows(pool, table, lens, page_len=page_len,
                              seg_len=seg_len, use_pallas=use_pallas,
                              interpret=pallas_interpret)
        out = ladder_core(seqs, lens, nsegs, tables, params, esc_cap,
                          use_pallas, pallas_interpret, wide_p0)
        out["esc_overflow"] = jax.lax.psum(out["esc_overflow"], "d")
        return out

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(), P("d"), P("d"), P("d"), P()),
                   out_specs={"cons": P("d"), "cons_len": P("d"), "err": P("d"),
                              "solved": P("d"), "tier": P("d"), "m_ovf": P("d"),
                              "esc_overflow": P()},
                   **vma_kw)
    return pack_result(fn(pool, table, lens, nsegs, tables))


@functools.partial(jax.jit,
                   static_argnames=("p0", "mesh", "page_len", "seg_len",
                                    "use_pallas", "pallas_interpret"))
def _tier0_sharded_paged_packed(pool, table, lens, nsegs, table0, p0, mesh,
                                page_len, seg_len, use_pallas=False,
                                pallas_interpret=False):
    from ..kernels.paging import gather_windows
    from ..kernels.tiers import pack_result

    shard_map, vma_kw = _vma_kw(use_pallas)

    def local(pool, table, lens, nsegs, table0):
        seqs = gather_windows(pool, table, lens, page_len=page_len,
                              seg_len=seg_len, use_pallas=use_pallas,
                              interpret=pallas_interpret)
        return tier0_core(seqs, lens, nsegs, table0, p0, use_pallas,
                          pallas_interpret)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(), P("d"), P("d"), P("d"), P()),
                   out_specs={"cons": P("d"), "cons_len": P("d"), "err": P("d"),
                              "solved": P("d"), "tier": P("d"), "m_ovf": P("d"),
                              "esc_overflow": P()},
                   **vma_kw)
    return pack_result(fn(pool, table, lens, nsegs, table0))


class StagedBatch:
    """A batch staged onto the mesh ahead of launch: the global sharded
    ``jax.Array`` inputs plus the retained *host-side* batch they were built
    from. The host batch is the replayable truth — failover, partial-mesh
    shrink, and capacity bisect all re-dispatch it; the staged device
    buffers are disposable (``launch`` discards and re-stages them when the
    mesh changed since staging). ``size``/``stream`` proxy the host batch so
    supervisor bookkeeping (shape keys, row accounting) reads identically
    off either form."""

    __slots__ = ("replay_batch", "arrays", "mesh", "nd", "target", "B0",
                 "paged", "pack_s", "stage_s")

    def __init__(self, replay_batch, arrays, mesh, nd, target, paged,
                 pack_s, stage_s):
        self.replay_batch = replay_batch
        self.arrays = arrays
        self.mesh = mesh
        self.nd = nd
        self.target = target
        self.B0 = replay_batch.size
        self.paged = paged
        self.pack_s = pack_s
        self.stage_s = stage_s

    @property
    def size(self) -> int:
        return self.B0

    @property
    def stream(self) -> str:
        return getattr(self.replay_batch, "stream", "full")


class ShardedLadderSolver:
    """Async mesh solver: ``dispatch`` returns a non-blocking handle,
    ``fetch`` materializes it (single packed-array transfer, like the
    single-device path in ``kernels.tiers``). Calling the object directly is
    the blocking convenience form used by tests and the dry run.

    Supervisor contract (runtime/supervisor.py): ``nd``/``shrink``/
    ``restore`` drive the partial-mesh degradation rung and the dynamic
    ``:m<N>`` shape-key suffix; ``routes_streams``/``supports_paged`` opt the
    pipeline's split-ladder and paged machinery in.
    """

    #: a stream='tier0' batch dispatches the sharded tier0-only program —
    #: the pipeline's split-ladder machinery may run against this solver
    routes_streams = True
    #: paged batches dispatch the table-sharded/pool-replicated program —
    #: the pipeline's paged router may run against this solver
    supports_paged = True

    def __init__(self, ladder: TierLadder, mesh: Mesh, esc_cap: int | None = None,
                 use_pallas: bool = False, pallas_interpret: bool = False,
                 batch: int | None = None):
        self.ladder = ladder
        self.mesh = mesh
        self.nd = mesh.devices.size
        # full-mesh device list, retained for restore() after a failback
        self._devices0 = list(mesh.devices.flat)
        # per-device flight recorder (ISSUE 13): one row per ORIGINAL mesh
        # member, keyed by its index in the construction-time device list —
        # dispatch wall + row counts accrue per dispatch (two float adds per
        # device, noise against the jit launch), HBM peak refreshes at
        # snapshot cadence (health_map), and state tracks the partial-mesh
        # rung (ok -> lost for the attributed culprit, dropped for members
        # the deterministic halving removed alongside it)
        self.device_stats: dict[int, dict] = {
            i: {"platform": d.platform, "state": "ok", "dispatches": 0,
                "dispatch_wall_s": 0.0, "rows": 0, "hbm_peak_bytes": None}
            for i, d in enumerate(self._devices0)}
        # solver birth time: the denominator of the per-member idle_frac
        # gauge (saturation profiler, ISSUE 14) — a member that accrued
        # little dispatch wall since construction is a starving chip
        import time as _time

        self._created = _time.time()
        self.sharding = NamedSharding(mesh, P("d"))
        self.replicated = NamedSharding(mesh, P())
        self.tables = tuple(ladder.tables[p.k] for p in ladder.params)
        self.params = tuple(ladder.params)
        self.wide_p0 = ladder.wide_p0
        # per-device escalation capacity. Explicit esc_cap wins; the default
        # resolves ONCE from the configured batch (first dispatch when no
        # batch was configured) instead of per dispatch — the old
        # ``target // nd`` default made the capacity a function of batch
        # width, so every distinct batch size (governor bisect rungs, final
        # partial flushes) compiled a fresh mesh program. A fixed cap >= the
        # per-device slice keeps overflow structurally impossible (narrower
        # governor-shrunk batches reuse the same cap; the jnp.nonzero rescue
        # compaction tolerates cap > slice).
        self.esc_cap = esc_cap       # explicit per-device cap (None = auto)
        self.batch = batch           # configured dispatch width (None = lazy)
        self._cap_base = batch       # width the auto cap derives from
        self._auto_cap: int | None = None
        self.use_pallas = use_pallas
        self.pallas_interpret = pallas_interpret
        self.cl = ladder.params[0].cons_len
        # pad-to-mesh-multiple accounting (rows added so B divides nd) —
        # the MULTICHIP bench sidecar's waste metric
        self.pad_rows = 0
        self.live_rows = 0
        # staged-dispatch sub-walls (ISSUE 19): the host-only dispatch wall
        # decomposes into pack (pad to mesh multiple) + stage (per-device
        # shard transfer) + launch (jitted program issue). The lock covers
        # these and the occupancy/overlap state below — stage() runs on the
        # pipeline's staging thread while launch/fetch run on the main one.
        import threading as _threading

        self._stat_lock = _threading.Lock()
        self.pack_s = 0.0
        self.stage_s = 0.0
        self.launch_s = 0.0
        self.restaged = 0            # stale staged buffers discarded+rebuilt
        # solve-occupancy integral: launch opens an interval when no handle
        # is outstanding, the fetch that drains the last one closes it —
        # the honest per-member busy/idle denominator now that dispatch no
        # longer blocks on host prep (pre-ISSUE-19 idle_frac used the
        # dispatch wall as a busy proxy, which the async split breaks)
        self._outstanding = 0
        self._occ_t0: float | None = None
        self._occ_busy_s = 0.0
        self._created_pc = _time.perf_counter()
        # per-member overlap gauge: staging wall spent while a solve was in
        # flight, over total staging wall — the ISSUE 19 acceptance gauge
        self._stage_total_s = 0.0
        self._stage_overlap_s = 0.0

    # ---- partial-mesh degradation (supervisor hooks) --------------------

    @property
    def host_local(self) -> bool:
        """True when every mesh device is a host CPU device (forced host
        platform count): the supervisor then runs inline — a local shard_map
        cannot hang the way a tunnel can."""
        return all(d.platform == "cpu" for d in self._devices0)

    def _rebuild(self, devices) -> None:
        self.mesh = Mesh(np.asarray(devices), axis_names=("d",))
        self.nd = self.mesh.devices.size
        self.sharding = NamedSharding(self.mesh, P("d"))
        self.replicated = NamedSharding(self.mesh, P())
        if self.esc_cap is None and self._cap_base is not None:
            # keep overflow structurally impossible on the new (wider)
            # per-device slice: the cap follows the slice width
            self._auto_cap = max(-(-int(self._cap_base) // self.nd), 1)

    def _dev_index(self, dev) -> int:
        """Original mesh-member index of ``dev`` (-1 when foreign)."""
        for i, d in enumerate(self._devices0):
            if d is dev:
                return i
        return -1

    def member_ids(self) -> list[int]:
        """Original member index of every ACTIVE device, in slice order:
        position j of this list owns row slice ``[j*per, (j+1)*per)`` of a
        staged batch. The shadow audit's injection/attribution row map
        (ISSUE 20) — it is how ``sdc:N@K`` finds member K's rows and how a
        divergent probe row names its chip."""
        return [self._dev_index(d) for d in self.mesh.devices.flat]

    def shrink(self, culprit: int = -1) -> bool:
        """Partial-mesh degradation rung: halve the device set. With an
        attributed ``culprit`` (original member index — fault injection
        names it, or a per-device probe found it) the surviving half is the
        one WITHOUT the dead chip; unattributed losses keep the first half
        (deterministic — a survivor set containing the dead device just
        shrinks again on the next loss). Dropped members' ``device_stats``
        rows flip to ``lost`` (the culprit) / ``dropped`` (halving
        casualties), the per-chip attribution ``mesh.device`` events carry.
        Returns False at mesh width 1 — the supervisor then falls through
        to whole-program failover."""
        if self.nd <= 1:
            return False
        active = list(self.mesh.devices.flat)
        half = self.nd // 2
        first, second = active[:half], active[half:]
        keep = first
        if 0 <= culprit < len(self._devices0):
            bad = self._devices0[culprit]
            if any(d is bad for d in first) and not any(
                    d is bad for d in second):
                keep = second
        for d in active:
            if any(k is d for k in keep):
                continue
            i = self._dev_index(d)
            if i >= 0:
                self.device_stats[i]["state"] = (
                    "lost" if i == culprit else "dropped")
        self._rebuild(keep)
        return True

    def restore(self) -> None:
        """Rebuild the full construction-time mesh (supervisor failback:
        the revived device pool re-enters, and every shape recompiles under
        its original ``:m<N>`` key). Every member's fault state resets to
        ``ok`` — the revived pool re-enters whole."""
        for row in self.device_stats.values():
            row["state"] = "ok"
        self._rebuild(self._devices0)

    def _esc_cap_for(self, target: int) -> int:
        if self.esc_cap is not None:
            return self.esc_cap
        if self._auto_cap is None:
            self._cap_base = self._cap_base or target
            self._auto_cap = max(-(-int(self._cap_base) // self.nd), 1)
        # safety: a batch wider than the configured base still must not
        # overflow (cap >= per-device slice keeps it structurally impossible)
        return max(self._auto_cap, -(-target // self.nd))

    # ---- dispatch / fetch ----------------------------------------------

    def dispatch(self, batch):
        """Stage + launch fused (the unpipelined form), or launch-only when
        handed a :class:`StagedBatch` the pipeline staged ahead of time.
        Per-device dispatch wall + row accounting accrue on every ACTIVE
        member (host-side issue cost is shared — the jit launch is one call
        — while rows split evenly by the batch-axis sharding). Two float
        adds per device per dispatch: telemetry stays inside the <=2%
        hot-path budget."""
        import time as _time

        t0 = _time.perf_counter()
        try:
            staged = (batch if isinstance(batch, StagedBatch)
                      else self.stage(batch))
            return self.launch(staged)
        finally:
            dt = _time.perf_counter() - t0
            rows = -(-int(batch.size) // max(self.nd, 1))
            for d in self.mesh.devices.flat:
                i = self._dev_index(d)
                if i >= 0:
                    row = self.device_stats[i]
                    row["dispatches"] += 1
                    row["dispatch_wall_s"] += dt
                    row["rows"] += rows

    def _refresh_hbm(self) -> None:
        """Per-device HBM peak via ``memory_stats()`` (None on backends that
        do not report it — host CPU devices usually). Called at snapshot
        cadence, never per dispatch."""
        for i, d in enumerate(self._devices0):
            try:
                ms = d.memory_stats()
                if ms and "peak_bytes_in_use" in ms:
                    self.device_stats[i]["hbm_peak_bytes"] = int(
                        ms["peak_bytes_in_use"])
            except Exception:
                pass

    def health_map(self) -> dict:
        """The mesh health map metrics snapshots embed (ISSUE 13): current
        vs construction width, per-device state/wall/rows/HBM-peak keyed by
        original member index, plus the per-member ``busy_frac``/
        ``idle_frac`` starvation gauges (ISSUE 14: the solve-occupancy
        integral over the solver's lifetime — a high idle_frac across ALL
        ok members means the host feeder is starving the mesh, which is
        exactly what the host_feeder verdict on a mesh run asserts; the
        pre-ISSUE-19 dispatch-wall proxy stopped meaning busy once dispatch
        became a non-blocking launch) and the per-member ``overlap_frac``
        (staging wall that ran under an in-flight solve — the pipelined-
        dispatch acceptance gauge; every active member shares the global
        batch, so it is uniform across them). A partial-mesh degradation
        reads off this map as exactly which chip is ``lost`` and which rows
        moved."""
        import time as _time

        self._refresh_hbm()
        with self._stat_lock:
            busy_s = self._occ_busy_s
            if self._occ_t0 is not None:
                busy_s += _time.perf_counter() - self._occ_t0
            ovr = (round(self._stage_overlap_s / self._stage_total_s, 4)
                   if self._stage_total_s > 0 else None)
        el = max(_time.perf_counter() - self._created_pc, 1e-9)
        busy = min(busy_s / el, 1.0)
        out = {}
        for i, row in self.device_stats.items():
            out[i] = dict(row, busy_frac=round(busy, 4),
                          idle_frac=round(1.0 - busy, 4),
                          overlap_frac=ovr)
        return {"nd": int(self.nd), "nd0": len(self._devices0),
                "devices": out}

    def probe_devices(self, timeout_s: float = 15.0) -> list[int]:
        """Original indexes of ACTIVE members that fail a tiny per-device
        op — the culprit finder for unattributed real losses. All probes
        start first and join against ONE shared deadline, so a fully
        wedged mesh (the common tunnel-death shape) costs ``timeout_s``
        total, not ``timeout_s`` per member — this runs inside the shrink
        recovery path, whose stall it must bound, not multiply. Probe
        threads are daemons: an abandoned one dies with the process."""
        import threading
        import time as _time

        probes: list[tuple[threading.Thread, list, object]] = []
        for d in self.mesh.devices.flat:
            ok: list = []

            def work(dev=d, ok=ok):
                try:
                    jax.block_until_ready(
                        jax.device_put(jnp.zeros(8, jnp.int32), dev))
                    ok.append(True)
                except Exception:
                    pass

            t = threading.Thread(target=work, daemon=True,
                                 name="daccord-mesh-probe")
            t.start()
            probes.append((t, ok, d))
        deadline = _time.monotonic() + timeout_s
        dead: list[int] = []
        for t, ok, d in probes:
            t.join(max(0.0, deadline - _time.monotonic()))
            if not ok:
                i = self._dev_index(d)
                if i >= 0:
                    dead.append(i)
        return dead

    def stage(self, batch, prof=None) -> StagedBatch:
        """Host half of the dispatch: pad ``batch`` to a mesh multiple
        (``pack``), then build the global sharded inputs from per-device
        single-shard transfers (``stage``). Safe to call from a staging
        thread while a solve is in flight — the mesh is snapshotted once at
        entry, so a concurrent shrink can never tear the pad width against
        the slice layout (launch detects the stale mesh and re-stages).
        ``prof`` (a StageProfile) books the two walls under the ``pack``/
        ``stage`` stages; the solver-level counters accrue regardless."""
        if isinstance(batch, StagedBatch):
            return batch
        import time as _time

        t0 = _time.perf_counter()
        overlapped = self._outstanding > 0
        mesh = self.mesh
        nd = mesh.devices.size
        devices = list(mesh.devices.flat)
        sharding = NamedSharding(mesh, P("d"))
        B0 = batch.size
        target = ((B0 + nd - 1) // nd) * nd
        padded = pad_batch(batch, target) if target != B0 else batch
        t1 = _time.perf_counter()
        per = target // nd

        def shard_put(a):
            # per-device pre-partitioned transfer: slice the host array into
            # its final single-device shards and assemble the global array
            # from them — device_put(jnp.asarray(x), sharding) would commit
            # the whole array to one device first and reshard from there
            a = np.ascontiguousarray(a)
            shards = [jax.device_put(a[i * per:(i + 1) * per], d)
                      for i, d in enumerate(devices)]
            return jax.make_array_from_single_device_arrays(
                a.shape, sharding, shards)

        paged = getattr(batch, "pool", None) is not None
        if paged:
            # paged wire format: table/lens/nsegs shard, the pool replicates
            pool = jax.device_put(jnp.asarray(padded.pool),
                                  NamedSharding(mesh, P()))
            arrays = (pool, shard_put(padded.table), shard_put(padded.lens),
                      shard_put(padded.nsegs))
        else:
            arrays = (shard_put(padded.seqs), shard_put(padded.lens),
                      shard_put(padded.nsegs))
        t2 = _time.perf_counter()
        dt_pack, dt_stage = t1 - t0, t2 - t1
        if prof is not None:
            prof.add("pack", dt_pack)
            prof.add("stage", dt_stage)
        with self._stat_lock:
            self.pack_s += dt_pack
            self.stage_s += dt_stage
            self._stage_total_s += dt_stage
            if overlapped or self._outstanding > 0:
                self._stage_overlap_s += dt_stage
        return StagedBatch(batch, arrays, mesh, nd, target, paged,
                           dt_pack, dt_stage)

    def launch(self, staged: StagedBatch):
        """Device half of the dispatch: call the jitted sharded program on
        the staged arrays (async — the handle resolves at fetch). A staged
        batch whose mesh changed since staging (partial-mesh shrink, or a
        failback restore) is STALE: its device buffers are discarded and the
        retained host batch re-stages on the current mesh — byte-identical
        by per-window independence."""
        from ..kernels.tiers import _PackedHandle

        if staged.mesh is not self.mesh:
            self.restaged += 1
            staged = self.stage(staged.replay_batch)
        import time as _time

        t0 = _time.perf_counter()
        target, B0 = staged.target, staged.B0
        self.pad_rows += target - B0
        self.live_rows += B0
        tier0 = staged.stream == "tier0"
        if staged.paged:
            rb = staged.replay_batch
            pl, sl = rb.family.page_len, rb.shape.seg_len
            if tier0:
                arr = _tier0_sharded_paged_packed(
                    *staged.arrays, self.tables[0], p0=self.params[0],
                    mesh=staged.mesh, page_len=pl, seg_len=sl,
                    use_pallas=self.use_pallas,
                    pallas_interpret=self.pallas_interpret)
            else:
                arr = _ladder_sharded_paged_packed(
                    *staged.arrays, self.tables, params=self.params,
                    esc_cap=self._esc_cap_for(target), mesh=staged.mesh,
                    page_len=pl, seg_len=sl, use_pallas=self.use_pallas,
                    pallas_interpret=self.pallas_interpret,
                    wide_p0=self.wide_p0)
        elif tier0:
            arr = _tier0_sharded_packed(
                *staged.arrays, self.tables[0], p0=self.params[0],
                mesh=staged.mesh, use_pallas=self.use_pallas,
                pallas_interpret=self.pallas_interpret)
        else:
            arr = _ladder_sharded_packed(
                *staged.arrays, self.tables, params=self.params,
                esc_cap=self._esc_cap_for(target), mesh=staged.mesh,
                use_pallas=self.use_pallas,
                pallas_interpret=self.pallas_interpret, wide_p0=self.wide_p0)
        now = _time.perf_counter()
        with self._stat_lock:
            self.launch_s += now - t0
            self._outstanding += 1
            if self._occ_t0 is None:
                self._occ_t0 = now
        return (_PackedHandle(arr, self.cl), B0)

    def dispatch_walls(self) -> dict:
        """Cumulative host-only dispatch sub-walls (ISSUE 19). ``dispatch_s``
        is their sum — what the bench/pipeline report as the dispatch wall,
        now meaning host work only on every backend (the solve itself books
        under fetch/occupancy, never here)."""
        with self._stat_lock:
            return {"pack_s": self.pack_s, "stage_s": self.stage_s,
                    "launch_s": self.launch_s,
                    "dispatch_s": self.pack_s + self.stage_s + self.launch_s,
                    "restaged": self.restaged}

    def _occ_close(self, n: int) -> None:
        # a fetch drained n handles: close the occupancy interval when the
        # outstanding count hits zero
        import time as _time

        with self._stat_lock:
            self._outstanding = max(0, self._outstanding - n)
            if self._outstanding == 0 and self._occ_t0 is not None:
                self._occ_busy_s += _time.perf_counter() - self._occ_t0
                self._occ_t0 = None

    @staticmethod
    def _trim(out: dict, B0: int) -> dict:
        """Drop the rows added by the pad-to-mesh-multiple in dispatch."""
        return {k: (v[:B0] if np.ndim(v) else v) for k, v in out.items()}

    def fetch(self, handle) -> dict:
        # one wire format, one decoder: delegate to kernels.tiers.fetch
        from ..kernels.tiers import fetch as fetch_packed

        ph, B0 = handle
        try:
            return self._trim(fetch_packed(ph), B0)
        finally:
            self._occ_close(1)

    def fetch_many(self, handles) -> list[dict]:
        from ..kernels.tiers import fetch_many as fetch_many_packed

        try:
            outs = fetch_many_packed([ph for ph, _ in handles])
        finally:
            self._occ_close(len(handles))
        return [self._trim(out, B0) for out, (_, B0) in zip(outs, handles)]

    def describe(self) -> str:
        """Short engine tag for supervisor events (what the run was on when
        it died matters when reading the events file after the fact)."""
        kinds = {d.platform for d in self.mesh.devices.flat}
        return f"mesh{self.nd}-{'/'.join(sorted(kinds))}-ladder"

    def __call__(self, batch: WindowBatch) -> dict:
        return self.fetch(self.dispatch(batch))


def make_sharded_solver(ladder: TierLadder, mesh: Mesh, esc_cap: int | None = None,
                        use_pallas: bool = False, pallas_interpret: bool = False,
                        batch: int | None = None):
    """WindowBatch -> results dict, the full ladder sharded over the mesh.

    ``esc_cap`` is an explicit per-device escalation capacity (None = auto:
    resolved once from ``batch``, the configured dispatch width). A drop-in
    ``solver`` for ``runtime.pipeline.correct_shard`` (which detects the
    async ``dispatch``/``fetch`` interface and pipelines batches through
    it); ``PipelineConfig.mesh`` builds it in-pipeline."""
    return ShardedLadderSolver(ladder, mesh, esc_cap, use_pallas,
                               pallas_interpret, batch=batch)


def build_sharded_solver(n_devices: int, profile, consensus_cfg,
                         esc_cap: int | None = None,
                         use_pallas: bool = False,
                         max_kmers: int = 64,
                         rescue_max_kmers: int = 256,
                         overflow_rescue: bool = False,
                         batch: int | None = None) -> ShardedLadderSolver:
    """Device-count-checked mesh solver from an error profile.

    Standalone construction (bench/tests); the pipeline builds from its own
    TierLadder instead (``PipelineConfig.mesh``) so the OffsetLikely tables
    are not constructed twice. Raises ``SystemExit`` with the off-pod recipe
    when fewer than ``n_devices`` devices are visible."""
    check_mesh_devices(n_devices)
    from ..kernels.window_kernel import pallas_needs_interpret

    ladder = TierLadder.from_config(profile, consensus_cfg,
                                    max_kmers=max_kmers,
                                    rescue_max_kmers=rescue_max_kmers,
                                    overflow_rescue=overflow_rescue)
    interpret = use_pallas and pallas_needs_interpret()
    return make_sharded_solver(ladder, make_mesh(n_devices), esc_cap,
                               use_pallas=use_pallas,
                               pallas_interpret=interpret, batch=batch)
