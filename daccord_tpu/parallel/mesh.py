"""Device mesh + sharded window solving.

The reference's only intra-process parallelism is a pthread pool over
reads/windows (SimpleThreadPool, SURVEY.md §2.3); the TPU equivalent shards
the *window batch dimension* across a 1-D device mesh. Piles are independent,
so there is no cross-window communication — the only collective is the psum
of the escalation-overflow counter, deliberately preserving the reference's
zero-communication design (SURVEY.md §5 "Distributed communication backend").

The full escalation ladder (tier 0 + device-compacted rescue tiers, see
``kernels.tiers.ladder_core``) runs INSIDE shard_map: each device solves and
escalates its own slice, so one sharded batch costs one dispatch and one
fetch regardless of mesh size.

Multi-host scale-out composes this with host-side LAS byte-range sharding
(``formats.las.shard_ranges``): every process corrects its own aread range on
its local devices; see ``parallel.launch``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..kernels.tensorize import WindowBatch, pad_batch
from ..kernels.tiers import TierLadder, ladder_core
from ..kernels.window_kernel import KernelParams


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over ``n_devices`` (default: all local devices), axis 'd'."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=("d",))


@functools.partial(jax.jit,
                   static_argnames=("params", "esc_cap", "mesh", "use_pallas",
                                    "pallas_interpret", "wide_p0"))
def _ladder_sharded(seqs, lens, nsegs, tables, params, esc_cap, mesh,
                    use_pallas=False, pallas_interpret=False, wide_p0=None):
    # pallas_call's out_shape carries no varying-axes info, so the vma check
    # must be off when the ladder routes its DP through the Pallas kernel
    # (the pre-0.8 fallback spells the same knob check_rep)
    try:
        from jax import shard_map  # jax >= 0.8
        vma_kw = {"check_vma": not use_pallas}
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
        vma_kw = {"check_rep": not use_pallas}

    def local(seqs, lens, nsegs, tables):
        out = ladder_core(seqs, lens, nsegs, tables, params, esc_cap,
                          use_pallas, pallas_interpret, wide_p0)
        out["esc_overflow"] = jax.lax.psum(out["esc_overflow"], "d")
        return out

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P("d"), P("d"), P("d"), P()),
                   out_specs={"cons": P("d"), "cons_len": P("d"), "err": P("d"),
                              "solved": P("d"), "tier": P("d"), "m_ovf": P("d"),
                              "esc_overflow": P()},
                   **vma_kw)
    return fn(seqs, lens, nsegs, tables)


@functools.partial(jax.jit,
                   static_argnames=("params", "esc_cap", "mesh", "use_pallas",
                                    "pallas_interpret", "wide_p0"))
def _ladder_sharded_packed(seqs, lens, nsegs, tables, params, esc_cap, mesh,
                           use_pallas=False, pallas_interpret=False,
                           wide_p0=None):
    from ..kernels.tiers import pack_result

    # pack OUTSIDE shard_map, inside the same jit (nested jit inlines): the
    # packing ops are elementwise along the sharded batch axis, so XLA keeps
    # them local to each device and the result crosses as ONE array
    return pack_result(_ladder_sharded(
        seqs, lens, nsegs, tables, params, esc_cap, mesh, use_pallas,
        pallas_interpret, wide_p0))


class ShardedLadderSolver:
    """Async mesh solver: ``dispatch`` returns a non-blocking handle,
    ``fetch`` materializes it (single packed-array transfer, like the
    single-device path in ``kernels.tiers``). Calling the object directly is
    the blocking convenience form used by tests and the dry run."""

    def __init__(self, ladder: TierLadder, mesh: Mesh, esc_cap: int | None = None,
                 use_pallas: bool = False, pallas_interpret: bool = False):
        self.mesh = mesh
        self.nd = mesh.devices.size
        self.sharding = NamedSharding(mesh, P("d"))
        self.tables = tuple(ladder.tables[p.k] for p in ladder.params)
        self.params = tuple(ladder.params)
        self.wide_p0 = ladder.wide_p0
        self.esc_cap = esc_cap   # None = full per-device slice (no overflow)
        self.use_pallas = use_pallas
        self.pallas_interpret = pallas_interpret
        self.cl = ladder.params[0].cons_len

    def dispatch(self, batch: WindowBatch):
        from ..kernels.tiers import _PackedHandle

        B0 = batch.size
        target = ((B0 + self.nd - 1) // self.nd) * self.nd
        batch = pad_batch(batch, target) if target != B0 else batch
        esc_cap = self.esc_cap if self.esc_cap is not None else target // self.nd
        arr = _ladder_sharded_packed(
            jax.device_put(jnp.asarray(batch.seqs), self.sharding),
            jax.device_put(jnp.asarray(batch.lens), self.sharding),
            jax.device_put(jnp.asarray(batch.nsegs), self.sharding),
            self.tables, params=self.params, esc_cap=esc_cap,
            mesh=self.mesh, use_pallas=self.use_pallas,
            pallas_interpret=self.pallas_interpret, wide_p0=self.wide_p0)
        return (_PackedHandle(arr, self.cl), B0)

    @staticmethod
    def _trim(out: dict, B0: int) -> dict:
        """Drop the rows added by the pad-to-mesh-multiple in dispatch."""
        return {k: (v[:B0] if np.ndim(v) else v) for k, v in out.items()}

    def fetch(self, handle) -> dict:
        # one wire format, one decoder: delegate to kernels.tiers.fetch
        from ..kernels.tiers import fetch as fetch_packed

        ph, B0 = handle
        return self._trim(fetch_packed(ph), B0)

    def fetch_many(self, handles) -> list[dict]:
        from ..kernels.tiers import fetch_many as fetch_many_packed

        outs = fetch_many_packed([ph for ph, _ in handles])
        return [self._trim(out, B0) for out, (_, B0) in zip(outs, handles)]

    def describe(self) -> str:
        """Short engine tag for supervisor events (what the run was on when
        it died matters when reading the events file after the fact)."""
        kinds = {d.platform for d in self.mesh.devices.flat}
        return f"mesh{self.nd}-{'/'.join(sorted(kinds))}-ladder"

    def __call__(self, batch: WindowBatch) -> dict:
        return self.fetch(self.dispatch(batch))


def make_sharded_solver(ladder: TierLadder, mesh: Mesh, esc_cap: int | None = None,
                        use_pallas: bool = False, pallas_interpret: bool = False):
    """WindowBatch -> results dict, the full ladder sharded over the mesh.

    ``esc_cap`` is the per-device escalation capacity. A drop-in ``solver``
    for ``runtime.pipeline.correct_shard`` (which detects the async
    ``dispatch``/``fetch`` interface and pipelines batches through it)."""
    return ShardedLadderSolver(ladder, mesh, esc_cap, use_pallas, pallas_interpret)


def build_sharded_solver(n_devices: int, profile, consensus_cfg,
                         esc_cap: int | None = None,
                         use_pallas: bool = False,
                         max_kmers: int = 64,
                         rescue_max_kmers: int = 256,
                         overflow_rescue: bool = False) -> ShardedLadderSolver:
    """Device-count-checked mesh solver from an error profile.

    The one construction path shared by the ``daccord --mesh`` CLI and the
    ladder bench; raises ``SystemExit`` with the off-pod recipe when fewer
    than ``n_devices`` devices are visible."""
    if len(jax.devices()) < n_devices:
        raise SystemExit(
            f"mesh {n_devices}: only {len(jax.devices())} devices visible "
            "(off-pod: set JAX_PLATFORMS=cpu and "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    from ..kernels.window_kernel import pallas_needs_interpret

    ladder = TierLadder.from_config(profile, consensus_cfg,
                                    max_kmers=max_kmers,
                                    rescue_max_kmers=rescue_max_kmers,
                                    overflow_rescue=overflow_rescue)
    interpret = use_pallas and pallas_needs_interpret()
    return make_sharded_solver(ladder, make_mesh(n_devices), esc_cap,
                               use_pallas=use_pallas, pallas_interpret=interpret)
