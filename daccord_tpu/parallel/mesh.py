"""Device mesh + sharded window solving.

The reference's only intra-process parallelism is a pthread pool over
reads/windows (SimpleThreadPool, SURVEY.md §2.3); the TPU equivalent shards
the *window batch dimension* across a 1-D device mesh. Piles are independent,
so there is no cross-window communication — the only collective is the stats
reduction (psum), deliberately preserving the reference's zero-communication
design (SURVEY.md §5 "Distributed communication backend").

Multi-host scale-out composes this with host-side LAS byte-range sharding
(``formats.las.shard_ranges``): every process corrects its own aread range on
its local devices; see ``parallel.launch``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..kernels.tensorize import WindowBatch, pad_batch
from ..kernels.tiers import TierLadder
from ..kernels.window_kernel import KernelParams, _solve_one


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over ``n_devices`` (default: all local devices), axis 'd'."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=("d",))


@functools.partial(jax.jit, static_argnames=("params", "mesh"))
def _solve_sharded(seqs, lens, nsegs, ol, params: KernelParams, mesh: Mesh):
    """Batch-sharded solve: inputs sharded on the window axis, OL replicated.

    Implemented with shard_map so the partitioning is explicit: each device
    runs the identical per-window program on its slice (SPMD over ICI); a
    psum-reduced solve counter rides along as the collective.
    """
    from jax.experimental.shard_map import shard_map

    def local(seqs, lens, nsegs, ol):
        out = jax.vmap(functools.partial(_solve_one, p=params),
                       in_axes=(0, 0, 0, None))(seqs, lens, nsegs, ol)
        n_solved = jax.lax.psum(jnp.sum(out["solved"].astype(jnp.int32)), "d")
        return out, n_solved

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P("d"), P("d"), P("d"), P()),
                   out_specs=({"cons": P("d"), "cons_len": P("d"),
                               "err": P("d"), "solved": P("d")}, P()))
    return fn(seqs, lens, nsegs, ol)


def make_sharded_solver(ladder: TierLadder, mesh: Mesh, compact_size: int = 64):
    """WindowBatch -> results dict, tier-0 sharded over the mesh.

    Escalation tiers run compacted on device 0 (they see <10% of windows;
    sharding them wastes ICI latency on tiny batches). The returned callable
    is a drop-in ``solver`` for ``runtime.pipeline.correct_shard``.
    """
    from ..kernels.tiers import solve_tiered

    nd = mesh.devices.size
    sharding = NamedSharding(mesh, P("d"))

    def solver(batch: WindowBatch) -> dict:
        B0 = batch.size
        target = ((B0 + nd - 1) // nd) * nd
        batch = pad_batch(batch, target) if target != B0 else batch
        p0 = ladder.params[0]
        args = (jax.device_put(jnp.asarray(batch.seqs), sharding),
                jax.device_put(jnp.asarray(batch.lens), sharding),
                jax.device_put(jnp.asarray(batch.nsegs), sharding),
                jnp.asarray(ladder.tables[p0.k]))
        out, _ = _solve_sharded(*args, params=p0, mesh=mesh)
        cons = np.array(out["cons"][:B0])
        cons_len = np.array(out["cons_len"][:B0])
        err = np.array(out["err"][:B0])
        solved = np.array(out["solved"][:B0])
        tier_of = np.where(solved, 0, -1).astype(np.int32)

        # escalation on the (small) failure set: reuse the host ladder with the
        # tier-0 results pre-filled
        idx = np.nonzero(~solved)[0]
        if len(idx):
            from ..kernels.tensorize import BatchShape, WindowBatch as WB
            sub = WB(seqs=batch.seqs[idx], lens=batch.lens[idx],
                     nsegs=batch.nsegs[idx], shape=batch.shape,
                     read_ids=batch.read_ids[idx], wstarts=batch.wstarts[idx])
            rest = solve_tiered(sub, ladder, compact_size=compact_size, skip_tier0=True)
            take = idx[rest["solved"]]
            if len(take):
                cons[take] = rest["cons"][rest["solved"]]
                cons_len[take] = rest["cons_len"][rest["solved"]]
                err[take] = rest["err"][rest["solved"]]
                solved[take] = True
                tier_of[take] = rest["tier"][rest["solved"]]
        return dict(cons=cons, cons_len=cons_len, err=err, solved=solved, tier=tier_of)

    return solver
