"""Multi-host launch: jax.distributed + per-host LAS byte-range shards.

The reference scales across nodes with ``-J i,n`` cluster array jobs over a
shared filesystem (SURVEY.md §2.3); this module keeps exactly that data-plane
model — host ``i`` of ``n`` streams LAS byte range ``i`` (aread-aligned) and
writes its own FASTA shard + manifest — while the compute plane inside each
host is the mesh-sharded solver over its local devices. No cross-host traffic
is needed for correctness; ``jax.distributed`` provides the process group so
the per-host meshes can be combined into a global mesh when a pod slice is
used as one device pool.

Per-shard outputs + JSON manifests make reruns idempotent (the reference's
crash => rerun-the-shard model, SURVEY.md §5 failure row).
"""

from __future__ import annotations

import json
import os

from ..formats.dazzdb import read_db
from ..formats.las import LasFile, index_las, shard_ranges
from ..runtime.pipeline import PipelineConfig, correct_shard, correct_to_fasta


def init_distributed(coordinator: str | None = None, num_processes: int | None = None,
                     process_id: int | None = None) -> tuple[int, int]:
    """Initialize jax.distributed when running multi-process; no-op otherwise.

    Returns (process_id, num_processes). Reads the standard env vars when
    arguments are not given; single-process when neither is available.
    """
    import jax

    if coordinator is None:
        coordinator = os.environ.get("DACCORD_COORDINATOR")
    if coordinator:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    return jax.process_index(), jax.process_count()


def shard_paths(outdir: str, shard: int) -> dict:
    return {
        "fasta": os.path.join(outdir, f"shard{shard:04d}.fasta"),
        "manifest": os.path.join(outdir, f"shard{shard:04d}.json"),
        "progress": os.path.join(outdir, f"shard{shard:04d}.progress.json"),
        "quarantine": os.path.join(outdir, f"shard{shard:04d}.quarantine.jsonl"),
        # telemetry spine sidecars (ISSUE 6): structured events (+ trace
        # spans), the per-window outcome ledger, and the end-of-run metrics
        # rollup committed beside the manifest
        "events": os.path.join(outdir, f"shard{shard:04d}.events.jsonl"),
        "ledger": os.path.join(outdir, f"shard{shard:04d}.ledger.jsonl"),
        "metrics": os.path.join(outdir, f"shard{shard:04d}.metrics.json"),
    }


def _write_manifest_durable(path: str, obj: dict) -> None:
    """Manifest commit via :func:`aio.durable_write`: a crash can only leave
    the OLD manifest (or none) — never a torn JSON that wedges every later
    idempotent rerun, and a failed commit leaves no tmp litter."""
    from ..utils.aio import durable_write

    durable_write(path, lambda fh: json.dump(obj, fh), mode="wt")


def load_shard_manifest(outdir: str, shard: int) -> tuple[dict | None, str | None]:
    """``(manifest, issue)`` — the shard's manifest iff it is trustworthy.

    A manifest only counts when the FASTA it references still exists and (for
    manifests new enough to record ``fasta_bytes``) still has the committed
    byte size; a deleted or truncated FASTA under a valid-looking manifest
    must trigger recomputation (``run_shard``) or a merge-gate refusal, never
    a silent short-circuit over missing output. Returns ``(None, None)`` when
    the manifest is absent or torn (PR 2 doctrine: torn JSON == never
    written), ``(None, reason)`` when it is present but belied by the FASTA.
    """
    paths = shard_paths(outdir, shard)
    if not os.path.exists(paths["manifest"]):
        return None, None
    try:
        with open(paths["manifest"]) as fh:
            m = json.load(fh)
    except (json.JSONDecodeError, OSError):
        # torn manifest (crash mid-write under the pre-ISSUE-2 plain write,
        # or disk damage) must not wedge the idempotent rerun: treat as absent
        return None, None
    if not isinstance(m, dict):
        return None, None
    if not os.path.exists(paths["fasta"]):
        return None, "manifest present but its FASTA is missing"
    fb = m.get("fasta_bytes")
    if fb is not None:
        size = os.path.getsize(paths["fasta"])
        if size != fb:
            return None, (f"FASTA is {size} bytes, manifest committed {fb} "
                          "(truncated or tampered)")
    return m, None


def run_shard(db_path: str, las_path: str, outdir: str, shard: int, nshards: int,
              cfg: PipelineConfig | None = None, force: bool = False,
              checkpoint_every: int = 0) -> dict:
    """Correct one LAS byte-range shard to its own FASTA + manifest.

    Idempotent: an existing manifest (unless ``force``) short-circuits, so a
    failed multi-host run is resumed by re-submitting the same command.

    With ``checkpoint_every=N`` the shard also checkpoints every N emitted
    reads: a progress JSON records the count of fully-emitted piles, the
    pile-aligned LAS byte offset to resume from, and the FASTA byte size at
    that point (SURVEY.md §5 checkpoint row: per-shard progress manifest
    enabling window-range resume). A crashed run restarted with the same
    command truncates the partial FASTA tail and resumes mid-shard instead of
    redoing the whole byte range.
    """
    os.makedirs(outdir, exist_ok=True)
    paths = shard_paths(outdir, shard)
    if not force:
        # the short-circuit must validate, not just exist: a cached manifest
        # whose FASTA was deleted (or truncated — fasta_bytes catches that)
        # would otherwise satisfy the rerun while the merge reads nothing
        cached, _ = load_shard_manifest(outdir, shard)
        if cached is not None:
            return cached
    if force:
        # --force means recompute from scratch, not resume the old run —
        # the progress manifest, the quarantine sidecar, and the outcome
        # ledger all reset
        for key in ("progress", "quarantine", "ledger"):
            if os.path.exists(paths[key]):
                os.remove(paths[key])
    cfg = cfg or PipelineConfig()
    if cfg.ingest_policy == "quarantine" and cfg.quarantine_path is None:
        import dataclasses

        cfg = dataclasses.replace(cfg, quarantine_path=paths["quarantine"])
    # shard_ranges skips the aread index for nshards<=1, so single-shard
    # quarantine runs over a damaged LAS work without a repair pass
    ranges = shard_ranges(las_path, nshards)
    start, end = ranges[shard]
    if not checkpoint_every:
        # (correct_to_fasta starts a fresh ledger sidecar itself: whole-range
        # runs never append)
        stats = correct_to_fasta(db_path, las_path, paths["fasta"], cfg,
                                 start=start, end=end)
        counters = {"reads": stats.n_reads, "windows": stats.n_windows,
                    "solved": stats.n_solved, "bases_out": stats.bases_out,
                    # FASTA-record count: `reads` counts emitted piles, which
                    # the merge gate cannot reconcile with the file (a pile
                    # may legitimately emit zero fragments)
                    "fragments": stats.n_fragments,
                    "wall_s": stats.wall_s,
                    "quarantined": stats.n_quarantined,
                    "ingest_issues": stats.n_ingest_issues,
                    # a shard that finished on the fallback engine is still
                    # correct output, but the manifest must say so: reruns
                    # and round reports need the degraded runs enumerable
                    "degraded": stats.degraded,
                    "fallback_reason": stats.fallback_reason,
                    # capacity-governor state (ISSUE 5): a ratcheted shard is
                    # degraded SPEED, not output (byte-identical), so it is
                    # deliberately NOT `degraded` — the merge gate accepts it
                    # without --allow-degraded
                    "batch_effective": stats.batch_effective,
                    "capacity_events": stats.n_capacity_events,
                    "governor": stats.governor_ratchet or None,
                    "device_s": round(stats.device_s, 4),
                    "host_s": round(stats.host_s, 4),
                    "_metrics": stats.metrics}
    else:
        counters = _run_shard_checkpointed(db_path, las_path, paths, start, end,
                                           cfg, checkpoint_every)
    # metrics rollup (ISSUE 6): committed durably BESIDE the manifest, not
    # inside it — the merge gate and idempotent-rerun logic stay metric-blind
    metrics_rollup = counters.pop("_metrics", None)
    manifest = {
        "shard": shard, "nshards": nshards, "byte_range": [start, end],
        **counters, "fasta": paths["fasta"],
        # committed output size: lets the stale-manifest short-circuit and
        # the merge gate catch a truncated FASTA, not just a missing one
        "fasta_bytes": os.path.getsize(paths["fasta"]),
    }
    # content digest (ISSUE 20): the merge gate re-verifies it before
    # concatenating, closing the silent-corruption window the byte-count
    # checks can't see (a lying chip writes the right NUMBER of bytes)
    from ..utils.obs import sha256_file

    manifest["fasta_sha256"] = sha256_file(paths["fasta"])
    _write_manifest_durable(paths["manifest"], manifest)
    if metrics_rollup:
        _write_manifest_durable(paths["metrics"], {
            "shard": shard, "wall_s": counters.get("wall_s"),
            "device_s": counters.get("device_s"),
            "host_s": counters.get("host_s"), **metrics_rollup})
        # the scrapeable twin (ISSUE 13): same rollup as a Prometheus text
        # exposition, shard-labeled, committed durably beside the JSON —
        # a node exporter (or plain curl | promtool) reads shards with no
        # JSON adapter in between
        from ..utils.aio import durable_write
        from ..utils.obs import render_prom

        prom = render_prom(metrics_rollup, labels={"shard": shard})
        durable_write(paths["metrics"][: -len(".json")] + ".prom",
                      lambda fh: fh.write(prom), mode="wt")
    if os.path.exists(paths["progress"]):
        os.remove(paths["progress"])
    return manifest


def _run_shard_checkpointed(db_path: str, las_path: str, paths: dict,
                            start: int, end: int, cfg: PipelineConfig | None,
                            every: int) -> dict:
    """Stream one shard with periodic progress checkpoints; resumes from an
    existing progress file (piles emit in input order, so `emitted` piles map
    1:1 onto the first `emitted` pile offsets of the byte range)."""
    import time

    from ..formats.fasta import FastaRecord, write_fasta
    from ..oracle.profile import ErrorProfile
    from ..runtime.faults import maybe_apply_data_faults
    from ..runtime.pipeline import estimate_profile_for_shard
    from ..utils.bases import ints_to_seq
    from ..utils.obs import JsonlLogger

    cfg = cfg or PipelineConfig()
    t0 = time.time()
    fired = maybe_apply_data_faults(las_path=las_path, db_path=db_path)
    if fired and cfg.events_path:
        # short-lived logger: the abort paths below (strict scan failure,
        # resume refusal) must not leak a held fd per retry attempt
        with JsonlLogger(cfg.events_path) as _fl:
            for f in fired:
                _fl.log("ingest.fault", kind=f["kind"], path=f["path"],
                        record=f["record"], offset=f.get("offset", -1))

    emitted = 0
    base = {"reads": 0, "windows": 0, "solved": 0, "bases_out": 0,
            "fragments": 0, "wall_s": 0.0}
    fasta_bytes = 0
    resumed = None
    prog = None
    if os.path.exists(paths["progress"]):
        try:
            with open(paths["progress"]) as fh:
                prog = json.load(fh)
        except (json.JSONDecodeError, OSError):
            # torn progress manifest (pre-durable-commit crash or disk
            # damage): fall back to a fresh run of the shard — the FASTA is
            # rewritten from scratch, never spliced onto an untrusted tail
            prog = None
        # a progress file is only valid for the same byte range (resharding
        # with a different n would map `emitted` onto different piles) and
        # only while its FASTA prefix still exists
        if prog is not None and prog.get("byte_range") != [start, end]:
            prog = None
        elif prog is not None and not os.path.exists(paths["fasta"]):
            prog = None
        elif prog is not None and \
                os.path.getsize(paths["fasta"]) < prog.get("fasta_bytes", 0):
            # a FASTA shorter than the checkpoint claims cannot be resumed:
            # truncate(fasta_bytes) on the shorter file would zero-fill the
            # hole and splice new output onto NULs — recompute instead
            prog = None
        if prog is not None:
            emitted = prog["emitted"]
            base = prog["counters"]
            fasta_bytes = prog["fasta_bytes"]
            resumed = emitted
    if not emitted:
        # fresh (non-resume) shard run: reset the sidecars so a recompute
        # (e.g. after a torn manifest) cannot accumulate duplicate rows —
        # resumes append deliberately (ledger dedupe key: aread+widx)
        for p in (cfg.quarantine_path, cfg.ledger_path):
            if p and os.path.exists(p):
                os.remove(p)

    db = read_db(db_path, strict=cfg.ingest_policy == "strict")
    las = LasFile(las_path)
    # pre-flight ingest scan (the pipeline rescans its own byte range — this
    # header-only pass is cheap): the checkpointed path must know about
    # corruption BEFORE it samples piles (index_las rightly rejects a
    # corrupt file) and before it trusts the emitted-pile resume mapping
    clean_piles = None
    scan_rep = None
    if cfg.ingest_policy != "off":
        from ..formats.ingest import scan_with_db

        rep = scan_rep = scan_with_db(db, las, start, end)
        if rep.issues:
            if cfg.ingest_policy == "strict":
                raise rep.error()
            if emitted:
                # quarantine markers need not emit a read, so `emitted`
                # no longer maps 1:1 onto pile offsets — resuming would
                # re-emit (duplicate) or skip reads silently
                raise SystemExit(
                    f"{paths['progress']}: cannot resume mid-shard over a "
                    "corrupt LAS under the quarantine policy (contained "
                    "piles break the emitted-pile offset mapping) — rerun "
                    "the shard with --force")
            clean_piles = rep.pile_ranges
    if emitted:
        # pile-aligned offsets are only needed on resume (index_las is a full
        # file scan; a fresh run skips it)
        idx = index_las(las_path)
        offs = [int(o) for _, o in idx if start <= o < end] + [end]
        resume_off = offs[min(emitted, len(offs) - 1)]
    else:
        resume_off = start

    # the error profile is estimated ONCE (from the shard's own start) and
    # persisted, so a resumed run reproduces the uninterrupted run's output
    # byte-for-byte rather than re-estimating from the resume point
    if prog is not None and "profile" in prog:
        if prog.get("ol_counts") is not None:
            # pre-r4 checkpoint written with the retired --empirical-ol
            # blend: the emitted head used blended OL tables this code can
            # no longer reproduce, so resuming would splice analytically-
            # corrected tail onto a blended head — refuse rather than emit
            # a silently mixed FASTA (rerun the shard with --force)
            raise SystemExit(
                f"{paths['progress']}: checkpoint was written by a pre-r4 "
                "run with --empirical-ol (retired); a resume cannot "
                "reproduce its tables — rerun the shard with --force")
        profile = ErrorProfile(*prog["profile"])
    else:
        profile = estimate_profile_for_shard(db, las, cfg, start, end,
                                             pile_ranges=clean_piles)
    prof_row = [float(profile.p_ins), float(profile.p_del), float(profile.p_sub)]
    counters = dict(base)
    # fragments resumed from a pre-fleet progress file are uncountable (the
    # field did not exist); omit the counter rather than commit a wrong one
    frag_base = base.get("fragments")
    nfrag = 0
    # truncate any partial tail past the last checkpoint, then append
    mode = "r+t" if emitted else "wt"
    last_st = None
    with open(paths["fasta"], mode) as out:
        out.truncate(fasta_bytes)
        out.seek(fasta_bytes)
        since = 0
        for rid, frags, st in correct_shard(
                db, las, cfg, resume_off, end, profile=profile,
                # reuse the pre-flight scan when it covered the same range
                # (fresh runs) — the validating walk is the slowest part of
                # ingesting a damaged multi-GB file, and would run twice
                ingest_report=scan_rep if resume_off == start else None):
            last_st = st
            write_fasta(out, [FastaRecord(f"read{rid}/{fi}", ints_to_seq(f))
                              for fi, f in enumerate(frags)])
            emitted += 1
            since += 1
            nfrag += len(frags)
            # st counters are cumulative over this run; add the pre-resume base
            counters = {"reads": base["reads"] + emitted - (resumed or 0),
                        "windows": base["windows"] + st.n_windows,
                        "solved": base["solved"] + st.n_solved,
                        "bases_out": base["bases_out"] + st.bases_out,
                        "wall_s": round(base["wall_s"] + (time.time() - t0), 3)}
            if frag_base is not None:
                counters["fragments"] = frag_base + nfrag
            if since >= every:
                # crash-durable commit ordering (ISSUE 2): (1) the FASTA
                # bytes the manifest will reference reach the platter, (2)
                # the manifest tmp's content does, (3) the rename publishes
                # it. A checkpoint can then never point past durable FASTA
                # bytes — a kill between any two fsync points resumes with
                # no lost or duplicated reads (the stale manifest's prefix
                # is durable by step 1; the partial tail truncates on resume)
                out.flush()
                os.fsync(out.fileno())
                _write_manifest_durable(
                    paths["progress"],
                    {"emitted": emitted, "fasta_bytes": out.tell(),
                     "counters": counters, "profile": prof_row,
                     "byte_range": [start, end]})
                if cfg.events_path:
                    # short-lived append (noise next to the two fsyncs):
                    # no held fd to leak when an abort path unwinds
                    with JsonlLogger(cfg.events_path) as _cl:
                        _cl.log("ingest.commit", emitted=emitted,
                                fasta_bytes=out.tell())
                since = 0
    counters["wall_s"] = round(base["wall_s"] + (time.time() - t0), 3)
    if resumed is not None:
        counters["resumed_at_read"] = resumed
    if last_st is not None:
        # degraded state is only final once the shard's generator is
        # exhausted (failover can happen in the last drain)
        counters["degraded"] = last_st.degraded
        counters["fallback_reason"] = last_st.fallback_reason
        counters["quarantined"] = last_st.n_quarantined
        counters["ingest_issues"] = last_st.n_ingest_issues
        # capacity-governor state: degraded speed, not output — the merge
        # gate accepts these without --allow-degraded
        counters["batch_effective"] = last_st.batch_effective
        counters["capacity_events"] = last_st.n_capacity_events
        counters["governor"] = last_st.governor_ratchet or None
        # decomposition anchors + metrics rollup (ISSUE 6). On a resume
        # these cover the resumed run only — wall_s alone is cumulative
        counters["device_s"] = round(last_st.device_s, 4)
        counters["host_s"] = round(last_st.host_s, 4)
        counters["_metrics"] = last_st.metrics
    return counters


class MergeGateError(ValueError):
    """The merge gate refused to concatenate: one message per violation in
    ``issues`` (missing/stale manifests, coverage gaps, count mismatches,
    degraded shards without ``allow_degraded``)."""

    def __init__(self, issues: list):
        self.issues = list(issues)
        super().__init__("; ".join(self.issues))


def merge_shards(outdir: str, nshards: int, out_fasta: str,
                 allow_degraded: bool = False) -> int:
    """Validating merge gate + crash-durable concatenation (the reference's
    merge step, which happily concatenated whatever it found).

    Before a single byte is written every shard manifest is checked: present
    and trustworthy (:func:`load_shard_manifest` — FASTA exists with the
    committed ``fasta_bytes``), indexed consistently (``shard``/``nshards``
    fields), and byte-range coverage is gapless across the fleet. Shards that
    finished degraded (failover engine) or with quarantined piles are refused
    unless ``allow_degraded``; with it, MISSING shards (poison-quarantined by
    the fleet) are also skipped rather than fatal — the merge then covers the
    surviving byte ranges only. While concatenating, each healthy shard's
    emitted read and base counts are cross-checked against its manifest
    instead of silently trusting the files. The output commits through
    :func:`aio.durable_write` (tmp + fsync + rename): a crash mid-merge can
    never leave a valid-looking truncated FASTA, and a failed count check
    aborts before publishing anything. Returns the fragment count.
    """
    from ..utils.aio import durable_write

    manifests: dict[int, dict] = {}
    missing: list[int] = []
    degraded: list[int] = []
    corrupt: list[int] = []
    issues: list[str] = []
    for s in range(nshards):
        m, why = load_shard_manifest(outdir, s)
        if m is None:
            if why:
                # present-but-belied manifests are corruption, never skippable
                issues.append(f"shard {s}: {why}")
            else:
                missing.append(s)
            continue
        if m.get("shard") not in (None, s):
            issues.append(f"shard {s}: manifest claims shard {m.get('shard')}")
        if m.get("nshards") not in (None, nshards):
            issues.append(f"shard {s}: manifest was written for a "
                          f"{m.get('nshards')}-way split, merging {nshards}")
        # capacity-degraded shards (manifest `batch_effective` below the
        # configured batch / a non-empty `governor` ratchet) pass WITHOUT
        # --allow-degraded by design: the governor degrades dispatch width,
        # never bytes — unlike engine failover (`degraded`) or quarantined
        # piles, whose output genuinely differs from the healthy run
        if m.get("degraded") or m.get("quarantined"):
            degraded.append(s)
        # content verification (ISSUE 20): the committed digest must match
        # the bytes on disk — byte COUNTS pass under silent corruption (a
        # lying chip writes the right number of wrong bytes), the digest
        # cannot. Manifests from before the digest era verify by counts only.
        sha = m.get("fasta_sha256")
        if sha is not None:
            from ..utils.obs import sha256_file

            if sha256_file(shard_paths(outdir, s)["fasta"]) != sha:
                corrupt.append(s)
        manifests[s] = m
    if corrupt and not allow_degraded:
        issues.append(f"shard(s) {corrupt}: FASTA content digest mismatches "
                      "the committed manifest (silent corruption) — rerun "
                      "them, or pass --allow-degraded to merge the bytes on "
                      "disk anyway")
    if missing and not allow_degraded:
        issues.append(f"missing shard output(s) {missing} — rerun them or "
                      "pass --allow-degraded to merge without them")
    if degraded and not allow_degraded:
        issues.append(f"shard(s) {degraded} completed degraded/quarantined — "
                      "pass --allow-degraded to merge anyway")
    if not missing:
        # byte-range coverage: gapless, non-overlapping, in shard order.
        # (With explicitly allowed missing shards the gaps are the point.)
        for a, b in zip(sorted(manifests), sorted(manifests)[1:]):
            ra, rb = manifests[a].get("byte_range"), manifests[b].get("byte_range")
            if ra and rb and ra[1] != rb[0]:
                issues.append(f"byte-range gap between shard {a} (ends {ra[1]}) "
                              f"and shard {b} (starts {rb[0]})")
    if issues:
        raise MergeGateError(issues)

    def _concat(out) -> int:
        frags = 0
        for s in sorted(manifests):
            m = manifests[s]
            reads: set[str] = set()
            bases = 0
            frag_count = 0
            with open(shard_paths(outdir, s)["fasta"]) as fh:
                for line in fh:
                    out.write(line)
                    if line.startswith(">"):
                        frag_count += 1
                        reads.add(line[1:].split("/", 1)[0].strip())
                    else:
                        bases += len(line.rstrip("\n"))
            # count cross-check (healthy shards only: quarantined piles may
            # legitimately emit no read, so their counters do not reconcile)
            if not m.get("quarantined"):
                errs = []
                if (m.get("fragments") is not None
                        and frag_count != m["fragments"]):
                    errs.append(f"shard {s}: FASTA holds {frag_count} "
                                f"fragments, manifest says {m['fragments']}")
                # a pile may legitimately emit zero fragments, so distinct
                # read ids can run BELOW the manifest's pile count — but
                # never above it
                if m.get("reads") is not None and len(reads) > m["reads"]:
                    errs.append(f"shard {s}: FASTA holds {len(reads)} reads, "
                                f"manifest says {m['reads']}")
                if m.get("bases_out") is not None and bases != m["bases_out"]:
                    errs.append(f"shard {s}: FASTA holds {bases} bases, "
                                f"manifest says {m['bases_out']}")
                if errs:
                    # raising here aborts durable_write BEFORE the rename —
                    # no partial merged FASTA is ever published
                    raise MergeGateError(errs)
            frags += frag_count
        return frags

    return durable_write(out_fasta, _concat, mode="wt")
