"""Multi-host launch: jax.distributed + per-host LAS byte-range shards.

The reference scales across nodes with ``-J i,n`` cluster array jobs over a
shared filesystem (SURVEY.md §2.3); this module keeps exactly that data-plane
model — host ``i`` of ``n`` streams LAS byte range ``i`` (aread-aligned) and
writes its own FASTA shard + manifest — while the compute plane inside each
host is the mesh-sharded solver over its local devices. No cross-host traffic
is needed for correctness; ``jax.distributed`` provides the process group so
the per-host meshes can be combined into a global mesh when a pod slice is
used as one device pool.

Per-shard outputs + JSON manifests make reruns idempotent (the reference's
crash => rerun-the-shard model, SURVEY.md §5 failure row).
"""

from __future__ import annotations

import json
import os

from ..formats.dazzdb import read_db
from ..formats.las import LasFile, shard_ranges
from ..runtime.pipeline import PipelineConfig, correct_to_fasta


def init_distributed(coordinator: str | None = None, num_processes: int | None = None,
                     process_id: int | None = None) -> tuple[int, int]:
    """Initialize jax.distributed when running multi-process; no-op otherwise.

    Returns (process_id, num_processes). Reads the standard env vars when
    arguments are not given; single-process when neither is available.
    """
    import jax

    if coordinator is None:
        coordinator = os.environ.get("DACCORD_COORDINATOR")
    if coordinator:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    return jax.process_index(), jax.process_count()


def shard_paths(outdir: str, shard: int) -> dict:
    return {
        "fasta": os.path.join(outdir, f"shard{shard:04d}.fasta"),
        "manifest": os.path.join(outdir, f"shard{shard:04d}.json"),
    }


def run_shard(db_path: str, las_path: str, outdir: str, shard: int, nshards: int,
              cfg: PipelineConfig | None = None, force: bool = False) -> dict:
    """Correct one LAS byte-range shard to its own FASTA + manifest.

    Idempotent: an existing manifest (unless ``force``) short-circuits, so a
    failed multi-host run is resumed by re-submitting the same command.
    """
    os.makedirs(outdir, exist_ok=True)
    paths = shard_paths(outdir, shard)
    if not force and os.path.exists(paths["manifest"]):
        with open(paths["manifest"]) as fh:
            return json.load(fh)
    ranges = shard_ranges(las_path, nshards)
    start, end = ranges[shard]
    stats = correct_to_fasta(db_path, las_path, paths["fasta"], cfg,
                             start=start, end=end)
    manifest = {
        "shard": shard, "nshards": nshards, "byte_range": [start, end],
        "reads": stats.n_reads, "windows": stats.n_windows,
        "solved": stats.n_solved, "bases_out": stats.bases_out,
        "wall_s": stats.wall_s, "fasta": paths["fasta"],
    }
    with open(paths["manifest"], "wt") as fh:
        json.dump(manifest, fh)
    return manifest


def merge_shards(outdir: str, nshards: int, out_fasta: str) -> int:
    """Concatenate shard FASTAs in shard order (the reference's merge step)."""
    n = 0
    with open(out_fasta, "wt") as out:
        for s in range(nshards):
            paths = shard_paths(outdir, s)
            if not os.path.exists(paths["fasta"]):
                raise FileNotFoundError(f"missing shard output {paths['fasta']}")
            with open(paths["fasta"]) as fh:
                for line in fh:
                    out.write(line)
                    if line.startswith(">"):
                        n += 1
    return n
