"""Round-trip and layout tests for the Dazzler DB / LAS / FASTA format layer."""

import io
import struct

import numpy as np
import pytest

from daccord_tpu.formats import (
    FastaRecord,
    LasFile,
    Overlap,
    index_las,
    read_db,
    read_fasta,
    read_las,
    read_track,
    write_db,
    write_fasta,
    write_las,
    write_track,
)
from daccord_tpu.formats.las import shard_ranges, OVL_COMP
from daccord_tpu.utils import (
    ints_to_seq,
    pack_2bit,
    revcomp_seq,
    seq_to_ints,
    unpack_2bit,
)


def test_base_coding_roundtrip():
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 4, size=1001, dtype=np.int8)
    s = ints_to_seq(arr)
    assert len(s) == 1001
    np.testing.assert_array_equal(seq_to_ints(s), arr)
    np.testing.assert_array_equal(unpack_2bit(pack_2bit(arr), len(arr)), arr)


def test_revcomp():
    assert revcomp_seq("ACGTT") == "AACGT"
    assert revcomp_seq(revcomp_seq("GATTACA")) == "GATTACA"


def test_fasta_roundtrip(tmp_path):
    recs = [FastaRecord("r1", "ACGT" * 50), FastaRecord("r2 extra words", "TTT")]
    p = tmp_path / "x.fasta"
    write_fasta(str(p), recs, width=13)
    back = list(read_fasta(str(p)))
    assert back[0].name == "r1" and back[0].seq == "ACGT" * 50
    assert back[1].name == "r2" and back[1].seq == "TTT"
    # stream from file object too
    back2 = list(read_fasta(io.StringIO(p.read_text())))
    assert back2[0].seq == back[0].seq


def test_db_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    seqs = [rng.integers(0, 4, size=n, dtype=np.int8) for n in (13, 200, 1, 77)]
    db = write_db(str(tmp_path / "toy.db"), seqs)
    back = read_db(str(tmp_path / "toy.db"))
    assert back.nreads == 4
    assert back.totlen == sum(len(s) for s in seqs)
    assert back.maxlen == 200
    for i, s in enumerate(seqs):
        np.testing.assert_array_equal(back.read_bases(i), s)
    assert back.names == db.names


def test_db_header_layout(tmp_path):
    """The .idx header must be exactly 112 bytes with nreads at offset 48."""
    seqs = [np.zeros(5, dtype=np.int8)]
    write_db(str(tmp_path / "h.db"), seqs)
    raw = (tmp_path / ".h.idx").read_bytes()
    assert struct.unpack_from("<i", raw, 48)[0] == 1  # nreads
    assert struct.unpack_from("<q", raw, 40)[0] == 5  # totlen
    assert len(raw) == 112 + 40  # header + one DAZZ_READ


def test_track_roundtrip(tmp_path):
    write_db(str(tmp_path / "t.db"), [np.zeros(10, dtype=np.int8)] * 3)
    payloads = [np.array([1, 2, 3], dtype=np.uint8), np.array([], dtype=np.uint8), np.array([9], dtype=np.uint8)]
    write_track(str(tmp_path / "t.db"), "inqual", payloads)
    back = read_track(str(tmp_path / "t.db"), "inqual")
    assert len(back) == 3
    for a, b in zip(payloads, back):
        np.testing.assert_array_equal(a, b)


def _mk_ovl(aread, bread, abpos=0, aepos=250, tspace=100, flags=0):
    o = Overlap(aread=aread, bread=bread, abpos=abpos, aepos=aepos,
                bbpos=abpos, bepos=aepos, flags=flags)
    nt = o.ntiles(tspace)
    bounds = o.tile_bounds(tspace)
    trace = np.stack([np.arange(nt, dtype=np.int32) % 5,
                      np.diff(bounds).astype(np.int32)], axis=1)
    o.trace = trace
    return o


def test_las_roundtrip(tmp_path):
    p = str(tmp_path / "a.las")
    ovls = [_mk_ovl(0, 1), _mk_ovl(0, 2, abpos=37, aepos=213, flags=OVL_COMP), _mk_ovl(3, 0)]
    n = write_las(p, 100, ovls)
    assert n == 3
    tspace, back = read_las(p)
    assert tspace == 100
    assert [o.aread for o in back] == [0, 0, 3]
    assert back[1].is_comp
    np.testing.assert_array_equal(back[1].trace, ovls[1].trace)
    assert back[1].abpos == 37 and back[1].aepos == 213


def test_tile_bounds():
    o = Overlap(aread=0, bread=0, abpos=37, aepos=213, bbpos=0, bepos=0)
    b = o.tile_bounds(100)
    np.testing.assert_array_equal(b, [37, 100, 200, 213])
    assert o.ntiles(100) == 3
    o2 = Overlap(aread=0, bread=0, abpos=0, aepos=100, bbpos=0, bepos=0)
    np.testing.assert_array_equal(o2.tile_bounds(100), [0, 100])


def test_las_index_and_shards(tmp_path):
    p = str(tmp_path / "b.las")
    ovls = []
    for a in range(10):
        for b in range(3):
            ovls.append(_mk_ovl(a, 20 + b))
    write_las(p, 100, ovls)
    idx = index_las(p)
    assert idx.shape == (10, 2)
    assert list(idx[:, 0]) == list(range(10))

    ranges = shard_ranges(p, 4)
    assert len(ranges) == 4
    f = LasFile(p)
    seen = []
    for s, e in ranges:
        seen.extend(o.aread for o in f.iter_range(s, e))
    assert seen == [o.aread for o in ovls]  # partition, no loss, in order

    # piles grouping
    piles = list(f.iter_piles())
    assert len(piles) == 10
    assert all(len(pile) == 3 for _, pile in piles)

    # sidecar: second call reads the cache and matches the fresh scan;
    # rewriting the LAS invalidates it
    import os

    assert os.path.exists(p + ".idx")
    idx2 = index_las(p)
    np.testing.assert_array_equal(idx, idx2)
    write_las(p, 100, ovls[:6])
    assert not os.path.exists(p + ".idx")
    idx3 = index_las(p)
    assert idx3.shape[0] == 2


def test_las_trace_u16(tmp_path):
    """tspace > 125 switches the trace to uint16."""
    p = str(tmp_path / "c.las")
    o = _mk_ovl(0, 1, abpos=0, aepos=1000, tspace=500)
    write_las(p, 500, [o])
    tspace, back = read_las(p)
    assert tspace == 500
    np.testing.assert_array_equal(back[0].trace, o.trace)


def test_dbsplit_blocks(tmp_path):
    """DBsplit-role partition: boundaries at read edges, sizes bounded,
    blocks cover all reads; stub round-trips through db_blocks."""
    import numpy as np

    from daccord_tpu.formats.dazzdb import db_blocks, read_db, split_db, write_db

    rng = np.random.default_rng(3)
    seqs = [rng.integers(0, 4, int(n), dtype=np.int8)
            for n in rng.integers(200, 1200, size=40)]
    db_path = str(tmp_path / "b.db")
    write_db(db_path, seqs)

    blocks = split_db(db_path, block_bases=5000)
    assert blocks == db_blocks(db_path)
    assert blocks[0][0] == 0 and blocks[-1][1] == len(seqs)
    for (s, e), (s2, _) in zip(blocks, blocks[1:]):
        assert e == s2
    db = read_db(db_path)
    for s, e in blocks:
        tot = sum(db.reads[i].rlen for i in range(s, e))
        # bounded unless a single long read forces a bigger block
        assert tot <= 5000 or e - s == 1
    # db still readable and bases intact after the stub rewrite
    assert np.array_equal(db.read_bases(0), seqs[0])


def test_aio_mem_streams():
    """aio URL streams (libmaus2 aio role, SURVEY.md §2.2): FASTA and LAS
    round-trip through mem: in-memory files — the reference's test-fixture
    infrastructure — byte-identically to the disk path."""
    from daccord_tpu.formats.fasta import FastaRecord, read_fasta, write_fasta
    from daccord_tpu.formats.las import LasFile, Overlap, write_las
    from daccord_tpu.utils import aio

    # fasta round trip
    recs = [FastaRecord("r0", "ACGT" * 30), FastaRecord("r1", "TTAA")]
    write_fasta("mem:t.fasta", recs)
    back = list(read_fasta("mem:t.fasta"))
    assert [(r.name, r.seq) for r in back] == [(r.name, r.seq) for r in recs]

    # las round trip incl. byte-range iteration and index
    ovls = [Overlap(aread=a, bread=a + 1, abpos=0, aepos=100, bbpos=5,
                    bepos=105, diffs=3,
                    trace=np.asarray([[3, 105]], dtype=np.int32))
            for a in range(5)]
    n = write_las("mem:t.las", 100, ovls)
    assert n == 5
    las = LasFile("mem:t.las")
    assert las.novl == 5 and las.tspace == 100
    assert [o.aread for o in las] == [0, 1, 2, 3, 4]

    from daccord_tpu.formats.las import range_for_areads, shard_ranges

    r = shard_ranges("mem:t.las", 2)
    assert len(r) == 2
    s, e = range_for_areads("mem:t.las", 2, 4)
    assert [o.aread for o in las.iter_range(s, e)] == [2, 3]

    aio.remove("mem:t.las")
    assert not aio.exists("mem:t.las")
    with pytest.raises(FileNotFoundError):
        aio.open_input("mem:t.las")


def test_aio_file_scheme_sidecar(tmp_path):
    """file: URLs strip to the same sidecar the plain path manages, so the
    index cache is shared across both spellings."""
    from daccord_tpu.formats.las import Overlap, index_las, write_las
    from daccord_tpu.utils import aio

    p = str(tmp_path / "f.las")
    ovls = [Overlap(aread=a, bread=a + 1, abpos=0, aepos=50, bbpos=0, bepos=50,
                    trace=np.asarray([[1, 50]], dtype=np.int32))
            for a in range(3)]
    write_las(p, 100, ovls)
    idx1 = index_las(p)                       # builds sidecar f.las.idx
    assert (tmp_path / "f.las.idx").exists()
    idx2 = index_las("file:" + p)             # must REUSE it, not rescan/fail
    np.testing.assert_array_equal(idx1, idx2)
    assert aio.getsize("file:" + p) == aio.getsize(p)

    import pytest as _pytest
    with _pytest.raises(FileNotFoundError):
        aio.remove("mem:never-existed")
