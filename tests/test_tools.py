"""Preprocessing tool suite: intrinsic QV, repeats, filters, CLI."""

import numpy as np
import pytest

from daccord_tpu.formats import LasFile, read_db, read_track
from daccord_tpu.sim import SimConfig, make_dataset
from daccord_tpu.tools import lastools

# XLA-compile-heavy e2e tier: excluded from `pytest -m 'not slow'` (fast tier)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tools"))
    cfg = SimConfig(genome_len=3000, coverage=14, read_len_mean=800, seed=17)
    return make_dataset(d, cfg, name="t"), d


def test_intrinsic_qv(dataset):
    out, d = dataset
    db = read_db(out["db"])
    las = LasFile(out["las"])
    payloads = lastools.compute_intrinsic_qv(db, las, depth=14)
    assert len(payloads) == db.nreads
    back = read_track(out["db"], "inqual")
    covered = np.concatenate([p[p != lastools.QV_NOCOV] for p in back])
    assert len(covered) > 100
    # typical per-read rate ~13.5% -> qv around 200*0.135/2-ish after halving;
    # just require sane dispersion within covered tiles
    e = out["result"].config.p_ins + out["result"].config.p_del + out["result"].config.p_sub
    assert 0.2 * lastools.QV_SCALE * e < covered.mean() < 2.5 * lastools.QV_SCALE * e
    # tile counts match read lengths
    tspace = las.tspace
    for i in range(db.nreads):
        assert len(back[i]) == (db.read_length(i) + tspace - 1) // tspace


def test_detect_repeats_planted(tmp_path):
    cfg = SimConfig(genome_len=4000, coverage=12, read_len_mean=900,
                    repeat_fraction=0.4, seed=23)
    out = make_dataset(str(tmp_path), cfg, name="r")
    db = read_db(out["db"])
    las = LasFile(out["las"])
    lastools.detect_repeats(db, las, depth=12, cov_factor=1.8)
    reps = lastools.read_repeat_track(db)
    n_with = sum(1 for r in reps if len(r))
    assert n_with > 0  # the planted repeat inflates some piles
    for r in reps:
        for s, e in r:
            assert 0 <= s < e


def test_filter_alignments(dataset, tmp_path):
    out, d = dataset
    db = read_db(out["db"])
    las = LasFile(out["las"])
    outp = str(tmp_path / "filt.las")
    n = lastools.filter_alignments(db, las, outp, repeat_track=None)
    assert 0 < n <= las.novl
    filt = LasFile(outp)
    assert filt.novl == n
    # order by aread preserved
    areads = [o.aread for o in filt]
    assert areads == sorted(areads)


def test_filter_symmetric(dataset, tmp_path):
    out, d = dataset
    db = read_db(out["db"])
    src = out["las"]
    outp = str(tmp_path / "sym.las")
    # the simulator emits symmetric pairs, so everything survives
    n = lastools.filter_symmetric(src, outp, db=db)
    assert n == LasFile(src).novl

    # drop one record; its mirror must then be dropped by the filter
    las = LasFile(src)
    ovls = list(las)
    victim = ovls[0]
    asym = str(tmp_path / "asym.las")
    from daccord_tpu.formats import write_las
    write_las(asym, las.tspace, ovls[1:])
    n2 = lastools.filter_symmetric(asym, str(tmp_path / "sym2.las"), db=db)
    assert n2 == len(ovls) - 2


def test_cli_entrypoints(dataset, tmp_path, capsys):
    out, d = dataset
    from daccord_tpu.tools.cli import main

    assert main(["inqual", out["db"], out["las"], "-d", "14"]) == 0
    assert main(["repeats", out["db"], out["las"], "-d", "14"]) == 0
    filt = str(tmp_path / "f.las")
    assert main(["filter", out["db"], out["las"], filt]) == 0
    assert main(["filtersym", filt, str(tmp_path / "fs.las"), "--db", out["db"]]) == 0
    assert main(["lassort", filt, str(tmp_path / "sorted.las")]) == 0
    assert main(["nonsense"]) == 2
    assert main([]) == 0


def test_fillfasta(tmp_path, capsys):
    from daccord_tpu.formats import read_fasta, write_fasta
    from daccord_tpu.formats.fasta import FastaRecord
    from daccord_tpu.tools.cli import main

    src = str(tmp_path / "in.fasta")
    write_fasta(src, [FastaRecord("r0", "ACGTNNNRYacgt"), FastaRecord("r1", "NNNN")])
    dst = str(tmp_path / "out.fasta")
    assert main(["fillfasta", src, dst, "--seed", "7"]) == 0
    recs = list(read_fasta(dst))
    assert [r.name for r in recs] == ["r0", "r1"]
    assert set(recs[0].seq) <= set("ACGT") and set(recs[1].seq) <= set("ACGT")
    # ACGT symbols preserved (case-normalized), only the bad ones replaced
    assert recs[0].seq[:4] == "ACGT" and recs[0].seq[-4:] == "ACGT"
    # deterministic under the same seed
    dst2 = str(tmp_path / "out2.fasta")
    assert main(["fillfasta", src, dst2, "--seed", "7"]) == 0
    assert open(dst).read() == open(dst2).read()


def test_eprof_cache_and_qveval(dataset, tmp_path, capsys):
    """-E estimates+saves on first run, loads on the second (identical output);
    qv-eval reports a Q uplift vs the raw reads."""
    import json

    from daccord_tpu.oracle.profile import ErrorProfile
    from daccord_tpu.tools.cli import main

    out, d = dataset
    ep = str(tmp_path / "prof.eprof")
    f1 = str(tmp_path / "c1.fasta")
    f2 = str(tmp_path / "c2.fasta")
    args = [out["db"], out["las"], "--backend", "cpu", "-b", "256"]
    assert main(["daccord", *args, "-o", f1, "-E", ep]) == 0
    prof = ErrorProfile.load(ep)
    assert 0 < prof.p_err < 0.5
    assert main(["daccord", *args, "-o", f2, "-E", ep]) == 0
    assert open(f1).read() == open(f2).read()

    assert main(["qveval", f1, out["truth"], "--raw-db", out["db"]]) == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["bases"] > 0
    assert line["qscore"] > line["raw_qscore"] + 5, line
    assert line["delta_q"] > 5


def test_eprof_only(dataset, tmp_path):
    from daccord_tpu.tools.cli import main

    out, d = dataset
    ep = str(tmp_path / "only.eprof")
    assert main(["daccord", out["db"], out["las"], "--backend", "cpu",
                 "-E", ep, "--eprof-only"]) == 0
    import os
    assert os.path.exists(ep)


def test_lasmerge(dataset, tmp_path):
    """Splitting a sorted LAS in two and las-merging must reproduce it
    byte-identically (modulo index sidecar)."""
    from daccord_tpu.formats import write_las
    from daccord_tpu.tools.cli import main

    out, d = dataset
    las = LasFile(out["las"])
    ovls = list(las)
    # interleave piles across the two parts; each part stays aread-sorted
    a = [o for i, o in enumerate(ovls) if (o.aread % 2) == 0]
    b = [o for i, o in enumerate(ovls) if (o.aread % 2) == 1]
    p1, p2 = str(tmp_path / "a.las"), str(tmp_path / "b.las")
    write_las(p1, las.tspace, a)
    write_las(p2, las.tspace, b)
    merged = str(tmp_path / "m.las")
    assert main(["lasmerge", merged, p1, p2]) == 0
    got = list(LasFile(merged))
    want = sorted(ovls, key=lambda o: (o.aread, o.bread, o.abpos))
    assert len(got) == len(want)
    assert all(g.aread == w.aread and g.bread == w.bread and g.abpos == w.abpos
               and g.aepos == w.aepos and g.bbpos == w.bbpos and g.bepos == w.bepos
               and g.diffs == w.diffs and g.flags == w.flags
               and np.array_equal(g.trace, w.trace)
               for g, w in zip(got, want))


def test_daccord_block_mode(dataset, tmp_path):
    """--block i corrects exactly block i's piles; under a shared -E error
    profile (the per-block workflow: profile once, correct per block) the
    concatenation over all blocks equals the whole-file run byte-for-byte.
    Without a shared profile each block would estimate its own."""
    import shutil

    from daccord_tpu.formats.dazzdb import db_blocks, split_db
    from daccord_tpu.tools.cli import main

    out, d = dataset
    # work on a copy: split_db rewrites the stub, and the dataset fixture is
    # shared module-wide
    for f in ("t.db", ".t.idx", ".t.bps", ".t.names"):
        shutil.copy(f"{d}/{f}", tmp_path / f)
    db = str(tmp_path / "t.db")
    split_db(db, block_bases=8000)
    ep = str(tmp_path / "shared.eprof")
    args = [db, out["las"], "--backend", "cpu", "-b", "256", "-E", ep]
    assert main(["daccord", *args, "--eprof-only"]) == 0
    whole = str(tmp_path / "whole.fasta")
    assert main(["daccord", *args, "-o", whole]) == 0

    nb = len(db_blocks(db))
    assert nb >= 2
    parts = []
    for i in range(1, nb + 1):
        p = str(tmp_path / f"b{i}.fasta")
        assert main(["daccord", *args, "-o", p, "--block", str(i)]) == 0
        parts.append(open(p).read())
    assert "".join(parts) == open(whole).read()

    with pytest.raises(SystemExit):
        main(["daccord", *args, "--block", str(nb + 1)])


def test_native_lastools_bit_parity(dataset, tmp_path):
    """The vectorized columnar-native QV and repeat passes must be
    bit-identical to the per-pile Python fallback."""
    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    out, d = dataset
    db = read_db(out["db"])
    las = LasFile(out["las"])
    qn = lastools.compute_intrinsic_qv(db, las, depth=14, use_native=True)
    qp = lastools.compute_intrinsic_qv(db, las, depth=14, use_native=False)
    assert len(qn) == len(qp)
    for a, b in zip(qn, qp):
        assert np.array_equal(a, b)

    cfg2 = SimConfig(genome_len=4000, coverage=12, read_len_mean=900,
                     repeat_fraction=0.4, seed=23)
    out2 = make_dataset(str(tmp_path), cfg2, name="rp")
    db2 = read_db(out2["db"])
    las2 = LasFile(out2["las"])
    rn = lastools.detect_repeats(db2, las2, depth=12, cov_factor=1.8, use_native=True)
    rp = lastools.detect_repeats(db2, las2, depth=12, cov_factor=1.8, use_native=False)
    assert len(rn) == len(rp)
    for a, b in zip(rn, rp):
        assert np.array_equal(np.asarray(a, dtype=np.uint8), np.asarray(b, dtype=np.uint8))


def test_filter_alignments_native_parity(dataset, tmp_path, monkeypatch):
    """Columnar-native filter must keep exactly the overlaps the Python
    per-pile fallback keeps (with and without a repeat track)."""
    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    out, d = dataset
    db = read_db(out["db"])
    las = LasFile(out["las"])

    def run(native: bool, tag: str, repeat_track):
        if not native:
            monkeypatch.setattr(lastools, "_native_ok", lambda: False)
        else:
            monkeypatch.setattr(lastools, "_native_ok", lambda: True)
        p = str(tmp_path / f"{tag}.las")
        n = lastools.filter_alignments(db, las, p, repeat_track=repeat_track)
        return n, open(p, "rb").read()

    lastools.detect_repeats(db, las, depth=14, cov_factor=1.5)
    for rt in (None, "rep"):
        n1, b1 = run(True, f"n{rt}", rt)
        n2, b2 = run(False, f"p{rt}", rt)
        assert n1 == n2 and b1 == b2, (rt, n1, n2)

    # trailing empty-trace overlap: the reduceat edge case (a trailing
    # zero-length trace group must not truncate the previous overlap's sum)
    import dataclasses

    from daccord_tpu.formats import write_las

    ovls = list(las)
    last = ovls[-1]
    tail = dataclasses.replace(last, trace=np.zeros((0, 2), np.int64),
                               abpos=last.abpos, aepos=last.abpos + 120)
    et = str(tmp_path / "et.las")
    write_las(et, las.tspace, ovls + [tail])
    las2 = LasFile(et)
    monkeypatch.setattr(lastools, "_native_ok", lambda: True)
    na = lastools.filter_alignments(db, las2, str(tmp_path / "etn.las"), repeat_track=None)
    monkeypatch.setattr(lastools, "_native_ok", lambda: False)
    pa = lastools.filter_alignments(db, las2, str(tmp_path / "etp.las"), repeat_track=None)
    assert na == pa
    assert open(str(tmp_path / "etn.las"), "rb").read() == open(str(tmp_path / "etp.las"), "rb").read()


def test_daccord_mesh_cli(dataset, tmp_path):
    """--mesh 8 (shard_map over the virtual CPU mesh) is byte-identical to the
    single-device run under a shared error profile."""
    from daccord_tpu.tools.cli import main

    out, d = dataset
    ep = str(tmp_path / "m.eprof")
    args = [out["db"], out["las"], "--backend", "cpu", "-b", "64", "-E", ep]
    assert main(["daccord", *args, "--eprof-only"]) == 0
    single = str(tmp_path / "single.fasta")
    meshed = str(tmp_path / "meshed.fasta")
    assert main(["daccord", *args, "-o", single]) == 0
    assert main(["daccord", *args, "-o", meshed, "--mesh", "8"]) == 0
    assert open(meshed).read() == open(single).read()


def test_ladderbench_rungs_smoke(tmp_path, monkeypatch):
    """The ladder-bench rung drivers work end to end on a micro dataset:
    the plain rung and the shard-workflow rung (checkpoints + merge).
    run_rung_procs is NOT covered here — each subprocess pays a full jax
    import + compile, too slow for CI; it is exercised by the cfg5 hardware
    runs (BASELINE.md)."""
    from daccord_tpu.tools import ladderbench as lb

    monkeypatch.setattr(lb, "CACHE", str(tmp_path))
    kw = dict(genome_len=2500, coverage=10, read_len_mean=700, seed=9)

    row = lb.run_rung("smoke", kw)
    assert row["reads"] > 0 and row["delta_q"] is not None

    row = lb.run_rung_shards("smoke2", kw, shards=2)
    assert row["shards"] == 2 and row["fragments"] > 0
    assert row["q_corrected"] > row["q_raw"]


def test_ladderbench_tracks_rung_smoke(tmp_path, monkeypatch):
    """The two-arm track-pipeline rung (cfg6 shape) runs every CLI stage and
    reports both arms' Q. Subprocess CLI stages pay jax imports, so the
    dataset is tiny; the real measurement is the cfg6 hardware run
    (BASELINE.md 'Track-pipeline measurement')."""
    from daccord_tpu.tools import ladderbench as lb

    monkeypatch.setattr(lb, "CACHE", str(tmp_path))
    row = lb.run_rung_tracks("tsmoke", dict(genome_len=2500, coverage=10,
                                            read_len_mean=700,
                                            repeat_fraction=0.3,
                                            repeat_divergence=0.08, seed=9))
    assert row["q_plain"] > row["q_raw"]
    assert row["q_tracks"] is not None and row["errors_tracks"] is not None


def test_block_tracks_catrack(dataset, tmp_path):
    """inqual/repeats --block write per-block tracks; catrack merges them
    byte-identically to the whole-DB run (the reference's per-block cluster
    workflow: computeintrinsicqv per block + Catrack), native and fallback."""
    import shutil

    from daccord_tpu.formats.dazzdb import db_blocks, split_db
    from daccord_tpu.tools.cli import main

    out, d = dataset
    for f in ("t.db", ".t.idx", ".t.bps", ".t.names"):
        shutil.copy(f"{d}/{f}", tmp_path / f)
    db_path = str(tmp_path / "t.db")
    split_db(db_path, block_bases=8000)
    nb = len(db_blocks(db_path))
    assert nb >= 2

    db = read_db(db_path)
    las = LasFile(out["las"])
    for use_native in (True, False):
        whole_q = lastools.compute_intrinsic_qv(db, las, depth=14, use_native=use_native)
        whole_r = lastools.detect_repeats(db, las, depth=14, cov_factor=1.8,
                                          use_native=use_native)
        block_q: list = []
        block_r: list = []
        for i in range(1, nb + 1):
            block_q.extend(lastools.compute_intrinsic_qv(
                db, las, depth=14, use_native=use_native, block=i))
            block_r.extend(lastools.detect_repeats(
                db, las, depth=14, cov_factor=1.8, use_native=use_native, block=i))
        assert len(block_q) == len(whole_q) and len(block_r) == len(whole_r)
        for a, b in zip(block_q, whole_q):
            assert np.array_equal(a, b)
        for a, b in zip(block_r, whole_r):
            assert np.array_equal(np.asarray(a, np.uint8), np.asarray(b, np.uint8))

    # CLI: per-block runs + catrack == whole-run track files, byte for byte
    whole_anno = (tmp_path / ".t.inqual.anno").read_bytes()
    whole_data = (tmp_path / ".t.inqual.data").read_bytes()
    for i in range(1, nb + 1):
        assert main(["inqual", db_path, out["las"], "-d", "14", "--block", str(i)]) == 0
        assert (tmp_path / f".t.{i}.inqual.anno").exists()
    assert main(["catrack", db_path, "inqual", "-d"]) == 0
    assert (tmp_path / ".t.inqual.anno").read_bytes() == whole_anno
    assert (tmp_path / ".t.inqual.data").read_bytes() == whole_data
    assert not (tmp_path / ".t.1.inqual.anno").exists()  # -d removed block files

    with pytest.raises(ValueError):
        lastools.compute_intrinsic_qv(db, las, depth=14, block=nb + 1)


def test_inspection_tools(dataset, tmp_path, capsys):
    """dbstats/dbshow/lasshow/lascheck/lassplit (DAZZ_DB DBstats/DBshow and
    DALIGNER LAshow/LAcheck/LAsplit roles)."""
    import shutil

    from daccord_tpu.formats.dazzdb import db_blocks, split_db
    from daccord_tpu.formats.las import write_las
    from daccord_tpu.tools.cli import main

    out, d = dataset
    db = read_db(out["db"])

    assert main(["dbstats", out["db"]]) == 0
    stats_out = capsys.readouterr().out
    assert f"{db.nreads:,} reads" in stats_out and "N50" in stats_out

    assert main(["dbshow", out["db"], "0", "2-4", "-o", str(tmp_path / "sel.fasta")]) == 0
    from daccord_tpu.formats.fasta import read_fasta
    recs = list(read_fasta(str(tmp_path / "sel.fasta")))
    assert len(recs) == 3
    assert recs[0].seq == "".join("ACGT"[b] for b in db.read_bases(0))
    with pytest.raises(SystemExit):
        main(["dbshow", out["db"], str(db.nreads)])

    assert main(["lasshow", out["las"], "-n", "5", "--trace"]) == 0
    las = LasFile(out["las"])
    show = capsys.readouterr().out
    assert f"{las.novl} records, tspace {las.tspace}" in show

    # the simulator's LAS is structurally valid, with and without DB bounds
    assert main(["lascheck", out["las"], "--db", out["db"]]) == 0
    # corrupt: drop aepos below abpos in one record
    bad = [o for o in las]
    bad[3].aepos = bad[3].abpos
    badp = str(tmp_path / "bad.las")
    write_las(badp, las.tspace, bad)
    assert main(["lascheck", badp]) == 1
    # truncated header count
    trunc = str(tmp_path / "trunc.las")
    shutil.copy(out["las"], trunc)
    with open(trunc, "r+b") as fh:
        import struct
        fh.write(struct.pack("<q", las.novl + 7))
    assert main(["lascheck", trunc]) == 1
    # file cut mid-trace: must report BAD, not traceback
    cut = str(tmp_path / "cut.las")
    raw = open(out["las"], "rb").read()
    with open(cut, "wb") as fh:
        fh.write(raw[: len(raw) - 3])
    assert main(["lascheck", cut]) == 1
    with pytest.raises(SystemExit):
        main(["dbshow", out["db"], "3-"])

    # lassplit: per-block files concat (in block order) == whole file's records
    for f in ("t.db", ".t.idx", ".t.bps", ".t.names"):
        shutil.copy(f"{d}/{f}", tmp_path / f)
    db_path = str(tmp_path / "t.db")
    split_db(db_path, block_bases=8000)
    nb = len(db_blocks(db_path))
    tmpl = str(tmp_path / "part.#.las")
    assert main(["lassplit", out["las"], db_path, tmpl]) == 0
    tot = 0
    parts = [tmpl.replace("#", str(i)) for i in range(1, nb + 1)]
    for p in parts:
        assert main(["lascheck", p]) == 0
        tot += LasFile(p).novl
    assert tot == las.novl
    merged = str(tmp_path / "merged.las")
    assert main(["lasmerge", merged, *parts]) == 0
    assert open(merged, "rb").read() == open(out["las"], "rb").read()

    # an overlap whose aread is outside the DB's partition must not vanish
    # silently: lassplit exits nonzero instead of dropping it
    stray = [o for o in las][:2]
    stray[1].aread = db.nreads + 5
    strayp = str(tmp_path / "stray.las")
    write_las(strayp, las.tspace, stray)
    with pytest.raises(SystemExit):
        main(["lassplit", strayp, db_path, str(tmp_path / "s.#.las")])


def test_detect_repeats_qv_gate(tmp_path):
    """The intrinsic-QV gate masks untrustworthy tiles from repeat
    annotation: an all-NOCOV track suppresses every interval, an all-good
    track changes nothing vs ungated detection."""
    from daccord_tpu.formats.dazzdb import write_track

    cfg = SimConfig(genome_len=4000, coverage=12, read_len_mean=900,
                    repeat_fraction=0.4, seed=23)
    out = make_dataset(str(tmp_path), cfg, name="rq")
    db = read_db(out["db"])
    las = LasFile(out["las"])
    tspace = las.tspace

    def uniform_track(value):
        return [np.full((db.read_length(i) + tspace - 1) // tspace, value,
                        dtype=np.uint8) for i in range(db.nreads)]

    # baseline: no track on disk -> graceful coverage-only detection
    lastools.detect_repeats(db, las, depth=12, cov_factor=1.8)
    base = lastools.read_repeat_track(db)
    assert sum(len(r) for r in base) > 0

    # all-good track: gate passes every tile, intervals unchanged
    write_track(out["db"], "inqual", uniform_track(10))
    lastools.detect_repeats(db, las, depth=12, cov_factor=1.8)
    gated = lastools.read_repeat_track(db)
    assert all(np.array_equal(a, b) for a, b in zip(base, gated))

    # all-NOCOV track: no tile is trustworthy, nothing gets annotated
    write_track(out["db"], "inqual", uniform_track(lastools.QV_NOCOV))
    lastools.detect_repeats(db, las, depth=12, cov_factor=1.8)
    none = lastools.read_repeat_track(db)
    assert sum(len(r) for r in none) == 0

    # explicit opt-out restores coverage-only behavior
    lastools.detect_repeats(db, las, depth=12, cov_factor=1.8, qv_track=None)
    off = lastools.read_repeat_track(db)
    assert all(np.array_equal(a, b) for a, b in zip(base, off))


def test_stream_median_matches_numpy():
    """_StreamMedian reproduces np.median exactly over chunked streams."""
    rng = np.random.default_rng(5)
    for n in (1, 2, 7, 100, 1001):
        vals = np.round(rng.random(n) * 0.4, 6)
        sm = lastools._StreamMedian()
        for c in np.array_split(vals, 3):
            sm.add(c)
        sm.plan()
        for c in np.array_split(vals, 3):
            sm.collect(c)
        assert sm.result() == float(np.median(vals)), n
    # heavy ties at the median
    vals = np.asarray([0.15] * 50 + [0.1] * 10 + [0.2] * 10)
    sm = lastools._StreamMedian()
    sm.add(vals)
    sm.plan()
    sm.collect(vals)
    assert sm.result() == float(np.median(vals))


def test_filter_alignments_streaming_parity(dataset, tmp_path, monkeypatch):
    """The bounded-memory chunked filter writes byte-identical output to the
    whole-file path, native and fallback alike (VERDICT r3 item 3)."""
    out, d = dataset
    db = read_db(out["db"])
    las = LasFile(out["las"])
    lastools.detect_repeats(db, las, depth=14, cov_factor=1.5)

    from daccord_tpu.native import available

    def run(tag: str, mem, native: bool, repeat_track="rep"):
        monkeypatch.setattr(lastools, "_native_ok", lambda: native)
        p = str(tmp_path / f"{tag}.las")
        n = lastools.filter_alignments(db, las, p, repeat_track=repeat_track,
                                       mem_records=mem)
        return n, open(p, "rb").read()

    if available():
        n_full, b_full = run("full", None, True)
        # small mem_records => many pile-aligned chunks
        for mem in (50, 173, 1000):
            n_s, b_s = run(f"s{mem}", mem, True)
            assert (n_s, b_s) == (n_full, b_full), mem
    # fallback path is always-streaming now; must agree with itself and
    # (already covered by test_filter_alignments_native_parity) with native
    n_p, b_p = run("pyfall", None, False)
    assert n_p > 0 and b_p
