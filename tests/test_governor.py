"""Capacity governor (ISSUE 5): adaptive degradation under device OOM, host
memory pressure, and monster piles.

Fast tier: the fault-plan capacity kinds, governor ladder units (bisect /
merge / ratchet / probation restore / clamp rung) against stub engines,
per-class retry budgets, ratchet persistence, the native-backend e2e matrix
(device_oom bisect parity, host_rss backpressure, monster-pile quarantine
parity, OOM-then-device-loss failover replay), shard-manifest/merge-gate
state, and the fleet capacity-requeue — no XLA ladder compiles. Slow tier:
the JAX ladder arms (fused bisect parity; an OOM landing mid-split-ladder
on a Stream B rescue batch; host-RSS force-flush of a live rescue pool).

The acceptance bar everywhere: FASTA byte-identical to the unfaulted run,
with ZERO full-width re-dispatches of a shape already classified as
capacity-faulted (asserted from governor.*/sup_retry events and engine-side
width logs).
"""

import json
import os

import numpy as np
import pytest

from daccord_tpu.kernels.tensorize import (BatchShape, WindowBatch, pad_batch,
                                           slice_batch)
from daccord_tpu.runtime.faults import (FLEET_KINDS, FaultDeviceOOM, FaultPlan,
                                        non_fleet_spec)
from daccord_tpu.runtime.governor import (CapacityError, GovernorConfig,
                                          is_capacity_error, load_ratchets,
                                          merge_results)
from daccord_tpu.runtime.supervisor import (DEGRADED, HEALTHY,
                                            DeviceSupervisor,
                                            SupervisorConfig)
from daccord_tpu.tools.eventcheck import validate_events
from daccord_tpu.utils.obs import JsonlLogger


# ------------------------------------------------------------- fault plan

def test_fault_plan_capacity_kinds():
    plan = FaultPlan.parse("device_oom:3,host_rss:2,monster_pile:4,worker_oom:2")
    assert [s.kind for s in plan.specs] == ["device_oom", "host_rss",
                                           "monster_pile", "worker_oom"]

    # device_oom: fires at device op 3, leaves a HALF-width virtual ceiling
    plan = FaultPlan.parse("device_oom:2")
    plan.op("dispatch", width=64)
    with pytest.raises(FaultDeviceOOM, match="RESOURCE_EXHAUSTED"):
        plan.op("fetch", width=64)
    assert plan.oom_max_width == 32
    # the ceiling is NOT one-shot: the identical doomed width keeps failing
    with pytest.raises(FaultDeviceOOM):
        plan.op("dispatch", width=64)
    with pytest.raises(FaultDeviceOOM):
        plan.op("dispatch", width=33)
    # ...while a bisected width fits
    plan.op("dispatch", width=32)
    plan.op("fetch", width=16)
    # composing specs forces a deeper walk (each fire halves again)
    plan2 = FaultPlan.parse("device_oom:1,device_oom:2")
    with pytest.raises(FaultDeviceOOM):
        plan2.op("dispatch", width=64)
    assert plan2.oom_max_width == 32
    with pytest.raises(FaultDeviceOOM):
        plan2.op("dispatch", width=32)
    assert plan2.oom_max_width == 16

    # host_rss / monster_pile counters are their own domains
    plan = FaultPlan.parse("host_rss:2,monster_pile:3")
    assert [plan.host_rss_check() for _ in range(3)] == [False, True, False]
    assert [plan.monster_check() for _ in range(4)] == [False, False, True,
                                                        False]

    # worker_oom is a fleet kind: stripped from worker env, spawn-counted
    assert "worker_oom" in FLEET_KINDS
    assert non_fleet_spec("worker_oom:2,device_oom:3") == "device_oom:3"
    plan = FaultPlan.parse("worker_oom:2")
    assert plan.fleet_spawn() is None
    assert plan.fleet_spawn() == "worker_oom"
    assert plan.fleet_spawn() is None


def test_is_capacity_error_classification():
    assert is_capacity_error(FaultDeviceOOM("RESOURCE_EXHAUSTED: injected"))
    assert is_capacity_error(MemoryError())
    assert is_capacity_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "8589934592 bytes"))
    assert is_capacity_error(RuntimeError("Failed to allocate request"))
    assert not is_capacity_error(RuntimeError("socket closed"))
    assert not is_capacity_error(TimeoutError("deadline"))


def test_merge_results_and_slice_batch():
    b = _mini_batch(b=6)
    b.read_ids[:] = np.arange(6)
    s = slice_batch(b, 2, 5)
    assert s.size == 3 and list(s.read_ids) == [2, 3, 4]
    assert s.stream == b.stream
    p = pad_batch(slice_batch(b, 4, 6), 4)
    assert p.size == 4 and list(p.read_ids[:2]) == [4, 5]

    parts = [(3, {"val": np.arange(4), "esc_overflow": np.int32(1),
                  "name": "x"}),
             (2, {"val": np.arange(4) + 10, "esc_overflow": np.int32(2),
                  "name": "x"})]
    m = merge_results(parts)
    np.testing.assert_array_equal(m["val"], [0, 1, 2, 10, 11])
    assert m["esc_overflow"] == 3 and m["name"] == "x"
    # single exact part passes through untouched
    one = {"val": np.arange(3)}
    assert merge_results([(3, one)]) is one


# ------------------------------------------------------------- stub engine

def _mini_batch(b=8, d=2, l=8, stream="full"):
    return WindowBatch(seqs=np.zeros((b, d, l), np.int8),
                       lens=np.zeros((b, d), np.int32),
                       nsegs=np.zeros(b, np.int32),
                       shape=BatchShape(depth=d, seg_len=l, wlen=l),
                       read_ids=np.arange(b, dtype=np.int64),
                       wstarts=np.zeros(b, np.int64), stream=stream)


class WidthLogEngine:
    """Sync stub whose fetch returns each row's read_id — so a bisected,
    merged result is checkable row-for-row — and which logs every dispatch
    width (the zero-full-width-re-dispatch assertion)."""

    def __init__(self):
        self.widths: list[int] = []

    def dispatch(self, batch):
        self.widths.append(batch.size)
        return batch

    def fetch(self, batch):
        return {"val": batch.read_ids.copy(),
                "esc_overflow": np.int32(0)}


def _sup(tmp_path, name, faults=None, gov=None, clamp=None, **cfg_kw):
    cfg_kw.setdefault("backoff_base_s", 0.01)
    eng = WidthLogEngine()
    ev = os.path.join(str(tmp_path), f"{name}.events.jsonl")
    sup = DeviceSupervisor(
        eng.dispatch, eng.fetch, None,
        fallback_factory=lambda: (lambda b: {"val": b.read_ids.copy(),
                                             "esc_overflow": np.int32(0),
                                             "engine": "fallback"}),
        log=JsonlLogger(ev), cfg=SupervisorConfig(**cfg_kw),
        faults=faults, probe_fn=lambda: True, describe="stub",
        clamp_solve=clamp, governor_cfg=gov)
    return sup, eng, ev


def _events(ev):
    return [json.loads(x) for x in open(ev)]


def test_governor_bisect_merge_ratchet(tmp_path, monkeypatch):
    """A classified OOM bisects the retained batch, merges the halves
    byte-exactly, ratchets the shape — and the engine NEVER sees the doomed
    full width again (later batches dispatch at the known-good size)."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    sup, eng, ev = _sup(tmp_path, "bisect",
                        faults=FaultPlan.parse("device_oom:1"),
                        gov=GovernorConfig(min_width=2, persist=True))
    h = sup.dispatch(_mini_batch(b=8))
    out = sup.fetch(h)
    np.testing.assert_array_equal(out["val"], np.arange(8))
    assert sup.state == HEALTHY and not sup.failed_over
    # the injected OOM fired BEFORE the engine ran: it never saw width 8
    assert eng.widths == [4, 4]
    # second batch of the same shape: straight to the ratcheted width
    out2 = sup.fetch(sup.dispatch(_mini_batch(b=8)))
    np.testing.assert_array_equal(out2["val"], np.arange(8))
    assert eng.widths == [4, 4, 4, 4]
    recs = _events(ev)
    evs = [r["event"] for r in recs]
    assert evs.count("governor.classify") == 1
    assert {(r["width_from"], r["width_to"]) for r in recs
            if r["event"] == "governor.shrink"} == {(8, 4)}
    assert [r["width"] for r in recs
            if r["event"] == "governor.ratchet"] == [4]
    # capacity never consumes the transient retry ladder
    assert "sup_retry" not in evs
    assert validate_events(ev, strict=True) == []
    assert sup.governor.active_state() == {"B8xD2xL8": 4}


def test_governor_deep_walk(tmp_path, monkeypatch):
    """Composed device_oom specs force the walk down multiple rungs."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    sup, eng, ev = _sup(tmp_path, "deep",
                        faults=FaultPlan.parse("device_oom:1,device_oom:2"),
                        gov=GovernorConfig(min_width=1))
    out = sup.fetch(sup.dispatch(_mini_batch(b=8)))
    np.testing.assert_array_equal(out["val"], np.arange(8))
    # first fire: ceiling 4; governor tries 4, second fire: ceiling 2 ->
    # chunks of 2 succeed
    assert eng.widths == [2, 2, 2, 2]
    shrinks = [(r["width_from"], r["width_to"]) for r in _events(ev)
               if r["event"] == "governor.shrink"]
    assert shrinks == [(8, 4), (4, 2)]


def test_governor_probation_restore(tmp_path, monkeypatch):
    """Opt-in probation: after N clean reduced solves, one full-width
    re-probe; restore on success (ratchet cleared), re-ratchet on failure —
    mirrors supervisor failback."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    plan = FaultPlan.parse("device_oom:1")
    sup, eng, ev = _sup(tmp_path, "probe", faults=plan,
                        gov=GovernorConfig(min_width=2, probation=2))
    sup.fetch(sup.dispatch(_mini_batch(b=8)))     # classify -> ratchet 4
    sup.fetch(sup.dispatch(_mini_batch(b=8)))     # reduced solve 1
    sup.fetch(sup.dispatch(_mini_batch(b=8)))     # reduced solve 2
    # probation due; the ceiling still stands -> restore probe fails,
    # dispatching stays reduced
    out = sup.fetch(sup.dispatch(_mini_batch(b=8)))
    np.testing.assert_array_equal(out["val"], np.arange(8))
    recs = _events(ev)
    rest = [r for r in recs if r["event"] == "governor.restore"]
    assert rest and rest[0]["ok"] is False
    assert sup.governor.planned_width("B8xD2xL8", 8) == 4
    # the chip frees memory (ceiling lifted): next probe restores full width
    plan.oom_max_width = None
    sup.fetch(sup.dispatch(_mini_batch(b=8)))     # reduced solve (count 1)
    sup.fetch(sup.dispatch(_mini_batch(b=8)))     # reduced solve (count 2)
    out = sup.fetch(sup.dispatch(_mini_batch(b=8)))   # probe -> restore
    np.testing.assert_array_equal(out["val"], np.arange(8))
    rest = [r for r in _events(ev) if r["event"] == "governor.restore"]
    assert rest[-1]["ok"] is True
    assert sup.governor.planned_width("B8xD2xL8", 8) is None
    assert 8 in eng.widths[-1:]     # the restore probe ran full width
    assert validate_events(ev, strict=True) == []


def test_governor_clamp_rung_and_exhaustion(tmp_path, monkeypatch):
    """Bisect floor exhausted -> the esc-cap clamp rung solves at its
    smaller effective width; without a clamp the ladder exhausts and native
    failover (demoted last resort) takes the batch."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    clamped = []

    def clamp(b):
        clamped.append(b.size)
        return {"val": b.read_ids.copy(), "esc_overflow": np.int32(0)}

    # min_width = 8 = full width: the bisect cannot shrink at all, and the
    # composed ceiling (4) fails width 8 -> clamp (effective width 2) fits
    sup, eng, ev = _sup(tmp_path, "clamp",
                        faults=FaultPlan.parse("device_oom:1"),
                        gov=GovernorConfig(min_width=8, esc_clamp=2),
                        clamp=clamp)
    out = sup.fetch(sup.dispatch(_mini_batch(b=8)))
    np.testing.assert_array_equal(out["val"], np.arange(8))
    assert clamped == [8] and not sup.failed_over
    recs = _events(ev)
    assert [r["esc_cap"] for r in recs
            if r["event"] == "governor.clamp"] == [2]
    # same shape again: the clamp rung is the sticky working rung
    sup.fetch(sup.dispatch(_mini_batch(b=8)))
    assert clamped == [8, 8]

    # no clamp configured: ladder exhausted -> native failover last resort
    sup2, eng2, ev2 = _sup(tmp_path, "exhaust",
                           faults=FaultPlan.parse("device_oom:1"),
                           gov=GovernorConfig(min_width=8))
    out = sup2.fetch(sup2.dispatch(_mini_batch(b=8)))
    assert out["engine"] == "fallback" and sup2.failed_over
    assert "capacity ladder exhausted" in sup2.fail_reason
    assert validate_events(ev2, strict=True) == []

    # clamp membership PERSISTS (negative width in the registry): a NEW
    # supervisor re-engages the clamped program directly — never the
    # unclamped program at a width known to OOM
    assert load_ratchets()["B8xD2xL8"] == -8
    clamped3 = []

    def clamp3(b):
        clamped3.append(b.size)
        return {"val": b.read_ids.copy(), "esc_overflow": np.int32(0)}

    sup3, eng3, ev3 = _sup(tmp_path, "clamp_persist", clamp=clamp3)
    out = sup3.fetch(sup3.dispatch(_mini_batch(b=8)))
    np.testing.assert_array_equal(out["val"], np.arange(8))
    assert eng3.widths == [] and clamped3 == [8]
    assert not any(r["event"] == "governor.classify" for r in _events(ev3))


def test_ratchet_persistence_across_supervisors(tmp_path, monkeypatch):
    """The working rung is recorded beside the compile-fingerprint registry:
    a NEW supervisor (new process, same host cache) dispatches the shape at
    the known-good width directly — no classify, no full-width attempt."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    sup, eng, _ = _sup(tmp_path, "persist1",
                       faults=FaultPlan.parse("device_oom:1"),
                       gov=GovernorConfig(min_width=2))
    sup.fetch(sup.dispatch(_mini_batch(b=8)))
    assert load_ratchets() == {"B8xD2xL8": 4}

    sup2, eng2, ev2 = _sup(tmp_path, "persist2", faults=None)
    out = sup2.fetch(sup2.dispatch(_mini_batch(b=8)))
    np.testing.assert_array_equal(out["val"], np.arange(8))
    assert eng2.widths == [4, 4]
    assert not any(r["event"] == "governor.classify" for r in _events(ev2))


def test_per_class_retry_budget(tmp_path, monkeypatch):
    """A timeout retry must not consume the transient budget (and vice
    versa): one injected hang + one transient error on the same logical op
    both recover under max_retries=1, with sup_retry carrying the class."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    calls = {"fetch": 0}

    class Eng:
        def dispatch(self, batch):
            return batch

        def fetch(self, batch):
            calls["fetch"] += 1
            if calls["fetch"] == 1:
                raise RuntimeError("transient socket wobble")
            return {"ok": True}

    eng = Eng()
    ev = os.path.join(str(tmp_path), "cls.events.jsonl")
    sup = DeviceSupervisor(
        eng.dispatch, eng.fetch, None, fallback_factory=None,
        log=JsonlLogger(ev),
        cfg=SupervisorConfig(backoff_base_s=0.01, max_retries=1),
        faults=FaultPlan.parse("fetch_hang:1"), probe_fn=lambda: True)
    out = sup.fetch(sup.dispatch(_mini_batch(b=4)))
    assert out == {"ok": True}
    retries = [r for r in _events(ev) if r["event"] == "sup_retry"]
    assert [r["cls"] for r in retries] == ["timeout", "transient"]
    assert validate_events(ev, strict=True) == []


def test_eventcheck_governor_schema(tmp_path):
    good = tmp_path / "gov.jsonl"
    good.write_text("\n".join([
        json.dumps({"t": 0.1, "ts": 1.1, "event": "governor.classify", "key": "B8",
                    "width": 8, "reason": "RESOURCE_EXHAUSTED"}),
        json.dumps({"t": 0.2, "ts": 1.2, "event": "governor.shrink", "key": "B8",
                    "width_from": 8, "width_to": 4}),
        json.dumps({"t": 0.3, "ts": 1.3, "event": "governor.clamp", "key": "B8",
                    "width": 4, "esc_cap": 2}),
        json.dumps({"t": 0.4, "ts": 1.4, "event": "governor.ratchet", "key": "B8",
                    "width": 4}),
        json.dumps({"t": 0.5, "ts": 1.5, "event": "governor.restore", "key": "B8",
                    "width": 8, "ok": True}),
        json.dumps({"t": 0.6, "ts": 1.6, "event": "governor.backpressure",
                    "level": "hard", "rss_mb": 123.4}),
        json.dumps({"t": 0.7, "ts": 1.7, "event": "governor.monster", "aread": 3,
                    "overlaps": 120000, "budget": 100000}),
        json.dumps({"t": 0.8, "ts": 1.8, "event": "fleet.capacity", "shard": 1,
                    "batch": 256}),
    ]) + "\n")
    assert validate_events(str(good), strict=True) == []
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"t": 0.1, "ts": 1.1, "event": "governor.shrink",
                               "key": "B8", "width_from": "big"}) + "\n")
    errs = validate_events(str(bad))
    assert errs and any("width_to" in e for e in errs)


# ------------------------------------------------------------ e2e (native)

@pytest.fixture(scope="module")
def native_dataset(tmp_path_factory):
    native = pytest.importorskip("daccord_tpu.native")
    if not native.available():
        pytest.skip("native library unavailable")
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("gov_e2e"))
    cfg = SimConfig(genome_len=1500, coverage=12, read_len_mean=500,
                    min_overlap=200, seed=7)
    return make_dataset(d, cfg, name="g"), d


def _run(out, d, name, ev=None, **kw):
    from daccord_tpu.runtime import PipelineConfig, correct_to_fasta

    kw.setdefault("batch_size", 64)
    kw.setdefault("depth_buckets", ())
    fasta = os.path.join(d, f"{name}.fasta")
    stats = correct_to_fasta(out["db"], out["las"], fasta,
                             PipelineConfig(native_solver=True,
                                            events_path=ev, **kw))
    return fasta, stats


def test_e2e_device_oom_byte_parity(native_dataset, monkeypatch, tmp_path):
    """ISSUE 5 acceptance (bisect rung): DACCORD_FAULT=device_oom:N -> the
    run completes HEALTHY (no failover), byte-identical FASTA, the shape
    ratchets, and the event stream shows zero transient retries and zero
    re-classifications after the ratchet engages."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    out, d = native_dataset
    f0, s0 = _run(out, d, "base")
    assert not s0.degraded and s0.batch_effective == 64

    monkeypatch.setenv("DACCORD_FAULT", "device_oom:3")
    ev = os.path.join(d, "oom.events.jsonl")
    f1, s1 = _run(out, d, "oom", ev=ev)
    assert open(f0).read() == open(f1).read()
    assert not s1.degraded                      # the chip is full, not dead
    assert s1.n_capacity_events >= 1
    assert s1.batch_effective == 32
    assert s1.governor_ratchet == {"native:B64xD32xL64": 32}
    recs = [json.loads(x) for x in open(ev)]
    evs = [r["event"] for r in recs]
    assert "governor.classify" in evs and "governor.ratchet" in evs
    assert "sup_retry" not in evs and "sup_failover" not in evs
    # in-flight full-width handles dispatched BEFORE the classification may
    # classify once each; none classifies twice (no full-width re-dispatch)
    assert evs.count("governor.classify") <= evs.count("governor.shrink") + 1
    assert validate_events(ev, strict=True) == []


def test_e2e_oom_during_failover_replay(native_dataset, monkeypatch, tmp_path):
    """OOM then device loss: capacity-solved handles survive the failover
    replay (their results are final), the rest replays on the fallback —
    byte-identical output, degraded=True from the loss (not the OOM)."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    out, d = native_dataset
    f0, _ = _run(out, d, "base2")
    monkeypatch.setenv("DACCORD_FAULT", "device_oom:2,device_lost:8")
    ev = os.path.join(d, "mix.events.jsonl")
    f1, s1 = _run(out, d, "mix", ev=ev)
    assert open(f0).read() == open(f1).read()
    assert s1.degraded and s1.n_capacity_events >= 1
    assert validate_events(ev, strict=True) == []


def test_e2e_host_rss_backpressure(native_dataset, monkeypatch, tmp_path):
    """host_rss:N forces a hard-watermark flush mid-run: buffered rows and
    the in-flight window all drain, output stays byte-identical."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    out, d = native_dataset
    f0, _ = _run(out, d, "base3")
    monkeypatch.setenv("DACCORD_FAULT", "host_rss:2")
    ev = os.path.join(d, "rss.events.jsonl")
    f1, s1 = _run(out, d, "rss", ev=ev)
    assert open(f0).read() == open(f1).read()
    assert s1.n_backpressure == 1
    bp = [json.loads(x) for x in open(ev)
          if '"governor.backpressure"' in x]
    assert bp and bp[0]["level"] == "hard" and bp[0]["injected"]
    assert validate_events(ev, strict=True) == []


def test_e2e_rss_latch_rearms_after_hard(native_dataset, monkeypatch,
                                         tmp_path):
    """Real-pressure latch semantics: retained-heap readings in the soft zone
    after a hard flush stay suppressed, but RSS dropping below the hard
    watermark re-arms it — renewed growth past hard flushes again instead of
    riding a dead guard into the OOM killer. Soft-zone readings after a
    plain soft flush stay suppressed until RSS clears the soft watermark."""
    import daccord_tpu.runtime.governor as govmod

    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    monkeypatch.setenv("DACCORD_GOV_RSS_SOFT_MB", "100")
    monkeypatch.setenv("DACCORD_GOV_RSS_HARD_MB", "200")
    out, d = native_dataset
    # per-block readings: hard trip; two retained-heap soft-zone readings
    # (suppressed, but the second arrives with the latch downgraded); a
    # SECOND hard crossing (must flush again); full drop; a fresh soft trip;
    # a suppressed repeat; then quiet
    readings = iter([50.0, 250.0, 150.0, 150.0, 250.0, 50.0, 150.0, 150.0])
    monkeypatch.setattr(govmod, "host_rss_mb",
                        lambda: next(readings, 10.0))
    ev = os.path.join(d, "latch.events.jsonl")
    f1, s1 = _run(out, d, "latch", ev=ev)
    f0, _ = _run(out, d, "base_latch")
    assert open(f0).read() == open(f1).read()
    levels = [json.loads(x)["level"] for x in open(ev)
              if '"governor.backpressure"' in x]
    assert levels == ["hard", "hard", "soft"]
    assert s1.n_backpressure == 3
    assert validate_events(ev, strict=True) == []


def test_e2e_monster_pile_quarantine_parity(native_dataset, monkeypatch,
                                            tmp_path):
    """monster_pile:N contains the pile through the quarantine machinery:
    its read is emitted UNCORRECTED (raw bases), every other read is
    byte-identical, and the sidecar + stats record the containment."""
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.fasta import read_fasta
    from daccord_tpu.utils.bases import ints_to_seq

    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    out, d = native_dataset
    f0, _ = _run(out, d, "base4")
    monkeypatch.setenv("DACCORD_FAULT", "monster_pile:2")
    ev = os.path.join(d, "mon.events.jsonl")
    qpath = os.path.join(d, "mon.q.jsonl")
    f1, s1 = _run(out, d, "mon", ev=ev, quarantine_path=qpath)
    assert s1.n_monster_piles == 1 and s1.n_quarantined == 1
    mon = [json.loads(x) for x in open(ev) if '"governor.monster"' in x]
    assert len(mon) == 1 and mon[0]["injected"]
    aread = mon[0]["aread"]
    q = [json.loads(x) for x in open(qpath)]
    assert len(q) == 1 and q[0]["kind"] == "monster_pile" \
        and q[0]["aread"] == aread

    def by_read(p):
        m = {}
        for rec in read_fasta(p):
            m.setdefault(rec.name.split("/")[0], []).append(rec.seq)
        return m

    r0, r1 = by_read(f0), by_read(f1)
    bad = f"read{aread}"
    assert all(r0.get(k) == r1.get(k)
               for k in (set(r0) | set(r1)) - {bad})
    # containment contract: the busted pile's read is the RAW read
    db = read_db(out["db"])
    assert r1[bad] == [ints_to_seq(db.read_bases(aread))]
    assert validate_events(ev, strict=True) == []


def test_shard_manifest_and_merge_gate(native_dataset, monkeypatch, tmp_path):
    """Manifests record batch_effective + governor ratchet state, and the
    merge gate accepts a capacity-degraded shard WITHOUT --allow-degraded
    (degraded speed, byte-identical output) — while a monster-quarantined
    shard still needs it (degraded output)."""
    from daccord_tpu.parallel.launch import (MergeGateError, merge_shards,
                                             run_shard)
    from daccord_tpu.runtime import PipelineConfig

    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    out, d = native_dataset
    cfg = PipelineConfig(batch_size=64, native_solver=True, depth_buckets=())

    cap_dir = os.path.join(d, "cap_out")
    monkeypatch.setenv("DACCORD_FAULT", "device_oom:3")
    m = run_shard(out["db"], out["las"], cap_dir, 0, 1, cfg)
    monkeypatch.delenv("DACCORD_FAULT")
    assert m["batch_effective"] == 32 and m["capacity_events"] >= 1
    assert m["governor"] == {"native:B64xD32xL64": 32}
    assert not m["degraded"]
    # capacity-degraded shard merges WITHOUT --allow-degraded
    merged = os.path.join(d, "cap.fasta")
    merge_shards(cap_dir, 1, merged)
    ref_dir = os.path.join(d, "gate_ref_out")
    run_shard(out["db"], out["las"], ref_dir, 0, 1, cfg)
    from daccord_tpu.parallel.launch import shard_paths

    assert open(merged).read() == open(shard_paths(ref_dir, 0)["fasta"]).read()

    # a monster-quarantined shard is degraded OUTPUT: gate still refuses
    mon_dir = os.path.join(d, "mon_out")
    monkeypatch.setenv("DACCORD_FAULT", "monster_pile:2")
    m2 = run_shard(out["db"], out["las"], mon_dir, 0, 1, cfg)
    monkeypatch.delenv("DACCORD_FAULT")
    assert m2["quarantined"] == 1
    with pytest.raises(MergeGateError, match="degraded/quarantined"):
        merge_shards(mon_dir, 1, os.path.join(d, "mon_merge.fasta"))
    merge_shards(mon_dir, 1, os.path.join(d, "mon_merge.fasta"),
                 allow_degraded=True)


def test_checkpointed_shard_records_governor(native_dataset, monkeypatch,
                                             tmp_path):
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    from daccord_tpu.parallel.launch import run_shard
    from daccord_tpu.runtime import PipelineConfig

    out, d = native_dataset
    cfg = PipelineConfig(batch_size=64, native_solver=True, depth_buckets=())
    monkeypatch.setenv("DACCORD_FAULT", "device_oom:3")
    m = run_shard(out["db"], out["las"], os.path.join(d, "ckpt_out"), 0, 1,
                  cfg, checkpoint_every=4)
    assert m["batch_effective"] == 32
    assert m["governor"] == {"native:B64xD32xL64": 32}


# ------------------------------------------------------------ fleet

def test_fleet_worker_oom_requeue_not_poison(tmp_path, monkeypatch):
    """An OOM-killed worker (exit 137) is requeued once at a reduced batch —
    no poison credit, fleet completes, merged output byte-identical."""
    native = pytest.importorskip("daccord_tpu.native")
    if not native.available():
        pytest.skip("native library unavailable")
    from daccord_tpu.parallel.fleet import FleetConfig, run_fleet
    from daccord_tpu.parallel.launch import merge_shards
    from daccord_tpu.sim import SimConfig, make_dataset

    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    d = str(tmp_path / "data")
    ds = make_dataset(d, SimConfig(genome_len=1200, coverage=10,
                                   read_len_mean=400, min_overlap=150,
                                   seed=7), name="fo")

    def fleet_cfg(out_dir, **kw):
        return FleetConfig(nshards=2, workers=2, backend="native",
                           checkpoint_every=2, backoff_base_s=0.05,
                           backoff_cap_s=0.5, batch=64,
                           speculate_min_runtime_s=300.0,
                           events_path=os.path.join(out_dir,
                                                    "fleet.events.jsonl"),
                           **kw)

    ref_dir = str(tmp_path / "ref")
    m_ref = run_fleet(ds["db"], ds["las"], ref_dir, fleet_cfg(ref_dir),
                      faults=None)
    assert m_ref["done"] == [0, 1] and not m_ref["poison"]
    ref_fasta = str(tmp_path / "ref.fasta")
    merge_shards(ref_dir, 2, ref_fasta)

    oom_dir = str(tmp_path / "oom")
    cfg = fleet_cfg(oom_dir, poison_after=1)   # ONE real failure would poison
    m = run_fleet(ds["db"], ds["las"], oom_dir, cfg,
                  faults=FaultPlan.parse("worker_oom:1"))
    assert m["done"] == [0, 1] and not m["poison"], m
    assert m["capacity_requeued"] == [0]
    out_fasta = str(tmp_path / "oom.fasta")
    merge_shards(oom_dir, 2, out_fasta)
    assert open(out_fasta).read() == open(ref_fasta).read()

    ev = [json.loads(x) for x in open(cfg.events_path)]
    cap = [e for e in ev if e["event"] == "fleet.capacity"]
    assert len(cap) == 1 and cap[0]["batch"] == 32
    retries = [e for e in ev if e["event"] == "fleet.retry"]
    assert {e["reason"] for e in retries} == {"capacity"}
    from daccord_tpu.tools.eventcheck import validate_events as _ve

    assert _ve(cfg.events_path, strict=True) == []


# ------------------------------------------------------------ bench

def test_bench_memory_telemetry():
    """The rung sidecar's memory fields: host peak RSS always (Linux), the
    device peak only when the backend exposes memory_stats (CPU: None)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_for_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    t = bench._memory_telemetry()
    assert set(t) == {"device_peak_bytes", "host_peak_rss_mb"}
    assert t["host_peak_rss_mb"] and t["host_peak_rss_mb"] > 10


# ------------------------------------------------------------ e2e (JAX)

@pytest.fixture(scope="module")
def jax_dataset(tmp_path_factory):
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("gov_jax"))
    cfg = SimConfig(genome_len=1200, coverage=10, read_len_mean=400,
                    min_overlap=150, seed=7)
    return make_dataset(d, cfg, name="gj"), d


def _jax_run(out, d, name, ev=None, **kw):
    from daccord_tpu.runtime import PipelineConfig, correct_to_fasta

    kw.setdefault("batch_size", 32)
    kw.setdefault("depth_buckets", ())
    fasta = os.path.join(d, f"{name}.fasta")
    stats = correct_to_fasta(out["db"], out["las"], fasta,
                             PipelineConfig(events_path=ev, **kw))
    return fasta, stats


@pytest.mark.slow
def test_e2e_jax_ladder_oom_parity(jax_dataset, monkeypatch, tmp_path):
    """The JAX ladder arm: a device OOM bisects through real (shrunken)
    ladder programs — shape-keyed compiles — and the FASTA stays
    byte-identical."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    out, d = jax_dataset
    f0, _ = _jax_run(out, d, "jbase")
    monkeypatch.setenv("DACCORD_FAULT", "device_oom:4")
    ev = os.path.join(d, "joom.events.jsonl")
    f1, s1 = _jax_run(out, d, "joom", ev=ev)
    assert open(f0).read() == open(f1).read()
    assert not s1.degraded and s1.batch_effective == 16
    assert validate_events(ev, strict=True) == []


@pytest.mark.slow
def test_e2e_split_ladder_stream_b_oom(jax_dataset, monkeypatch, tmp_path):
    """An OOM landing mid-split-ladder on a Stream B rescue batch: the
    bisected rescue halves keep the stream tag (they re-route to the rescue
    program) and output parity holds. The op index is scanned until a
    classification hits a non-tier0 program (the deterministic corpus makes
    the scan reproducible)."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    out, d = jax_dataset
    kw = dict(ladder_mode="split", rescue_flush_reads=4)
    f0, s0 = _jax_run(out, d, "sbase", **kw)
    assert s0.n_dispatch_rescue > 0    # Stream B actually ran
    hit = None
    for n in (3, 5, 7, 9, 11, 13, 15):
        monkeypatch.setenv("DACCORD_FAULT", f"device_oom:{n}")
        ev = os.path.join(d, f"soom{n}.events.jsonl")
        f1, _ = _jax_run(out, d, f"soom{n}", ev=ev, **kw)
        assert open(f0).read() == open(f1).read(), n
        assert validate_events(ev, strict=True) == []
        keys = [json.loads(x)["key"] for x in open(ev)
                if '"governor.classify"' in x]
        if any(not k.endswith(":t0") for k in keys):
            hit = n
            break
    assert hit is not None, "no op index classified a Stream B batch"


@pytest.mark.slow
def test_e2e_split_host_rss_flushes_pool(jax_dataset, monkeypatch, tmp_path):
    """Hard host pressure force-flushes a LIVE rescue pool (a mid-run
    ladder.flush with its own reason 'pressure' — the 'final' label stays
    reserved for the real end-of-shard drain) and bounds the buffered state
    — with byte-identical output."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    out, d = jax_dataset
    kw = dict(ladder_mode="split", rescue_flush_reads=10 ** 6)
    f0, s0 = _jax_run(out, d, "rbase", **kw)
    # with a deadline that can never expire, the unfaulted run only flushes
    # rescue rows at the end-of-shard drain
    assert s0.n_dispatch_rescue > 0
    base_final = sum(di["reason"] == "final" for di in s0.rescue_dispatches)
    monkeypatch.setenv("DACCORD_FAULT", "host_rss:8")
    ev = os.path.join(d, "rss.events.jsonl")
    f1, s1 = _jax_run(out, d, "rssflush", ev=ev, **kw)
    assert open(f0).read() == open(f1).read()
    assert s1.n_backpressure == 1
    recs = [json.loads(x) for x in open(ev)]
    assert any(r["event"] == "governor.backpressure" and r["level"] == "hard"
               for r in recs)
    # the forced mid-run drain dispatches Stream B under its own 'pressure'
    # reason; the base run (which never saw pressure) has none, and its
    # end-of-shard 'final' flushes keep their label
    assert base_final > 0 and not any(
        di["reason"] == "pressure" for di in s0.rescue_dispatches)
    got_pressure = sum(di["reason"] == "pressure"
                       for di in s1.rescue_dispatches)
    assert got_pressure > 0, (s1.rescue_dispatches, s0.rescue_dispatches)
    assert validate_events(ev, strict=True) == []
