"""Pallas heaviest-path kernel: bit-parity with the lax.scan formulation.

Runs in interpret mode on the CPU test mesh; on TPU the same kernel compiles
through Mosaic (exercised by bench/driver runs).
"""

import numpy as np
import pytest


def _scan_ref(adjW, wt, s0):
    import jax
    import jax.numpy as jnp

    NEG = jnp.float32(-1e30)
    P = wt.shape[1]
    M = adjW.shape[1]

    def one(adjW, wt, s0):
        def step(s, t):
            cand = s[:, None] + adjW
            bu = jnp.argmax(cand, axis=0)
            b = jnp.max(cand, axis=0)
            sn = jnp.where(b > NEG / 2, b + wt[t], NEG)
            return sn, (sn, bu.astype(jnp.int32))

        _, (scores, ptrs) = jax.lax.scan(step, s0, jnp.arange(1, P))
        return (jnp.concatenate([s0[None], scores]),
                jnp.concatenate([jnp.zeros((1, M), jnp.int32), ptrs]))

    return jax.vmap(one)(adjW, wt, s0)


def test_pallas_dp_matches_scan():
    import jax.numpy as jnp

    from daccord_tpu.kernels.pallas_dp import heaviest_path_batch

    rng = np.random.default_rng(7)
    B, M, P = 8, 16, 12
    adj = rng.random((B, M, M)) < 0.15
    adjW = np.where(adj, 0, -1e30).astype(np.float32)
    wt = (rng.random((B, P, M)) * np.rint(rng.random((B, P, M)) * 4)).astype(np.float32)
    s0 = np.where(rng.random((B, M)) < 0.3, rng.random((B, M)), -1e30).astype(np.float32)

    ref_s, ref_p = _scan_ref(jnp.asarray(adjW), jnp.asarray(wt), jnp.asarray(s0))
    pal_s, pal_p = heaviest_path_batch(jnp.asarray(adjW), jnp.asarray(wt),
                                       jnp.asarray(s0), interpret=True)
    np.testing.assert_array_equal(np.asarray(ref_s), np.asarray(pal_s))
    np.testing.assert_array_equal(np.asarray(ref_p), np.asarray(pal_p))
