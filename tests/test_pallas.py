"""Pallas heaviest-path kernel: bit-parity with the lax.scan formulation.

Runs in interpret mode on the CPU test mesh; on TPU the same kernel compiles
through Mosaic (exercised by bench/driver runs).
"""

import numpy as np
import pytest

# XLA-compile-heavy e2e tier: excluded from `pytest -m 'not slow'` (fast tier)
pytestmark = pytest.mark.slow


def _scan_ref(adjW, wt, s0):
    import jax
    import jax.numpy as jnp

    NEG = jnp.float32(-1e30)
    P = wt.shape[1]
    M = adjW.shape[1]

    def one(adjW, wt, s0):
        def step(s, t):
            cand = s[:, None] + adjW
            bu = jnp.argmax(cand, axis=0)
            b = jnp.max(cand, axis=0)
            sn = jnp.where(b > NEG / 2, b + wt[t], NEG)
            return sn, (sn, bu.astype(jnp.int32))

        _, (scores, ptrs) = jax.lax.scan(step, s0, jnp.arange(1, P))
        return (jnp.concatenate([s0[None], scores]),
                jnp.concatenate([jnp.zeros((1, M), jnp.int32), ptrs]))

    return jax.vmap(one)(adjW, wt, s0)


def test_pallas_dp_matches_scan():
    import jax.numpy as jnp

    from daccord_tpu.kernels.pallas_dp import heaviest_path_batch

    rng = np.random.default_rng(7)
    B, M, P = 8, 16, 12
    adj = rng.random((B, M, M)) < 0.15
    adjW = np.where(adj, 0, -1e30).astype(np.float32)
    wt = (rng.random((B, P, M)) * np.rint(rng.random((B, P, M)) * 4)).astype(np.float32)
    s0 = np.where(rng.random((B, M)) < 0.3, rng.random((B, M)), -1e30).astype(np.float32)

    ref_s, ref_p = _scan_ref(jnp.asarray(adjW), jnp.asarray(wt), jnp.asarray(s0))
    pal_s, pal_p = heaviest_path_batch(jnp.asarray(adjW), jnp.asarray(wt),
                                       jnp.asarray(s0), interpret=True)
    np.testing.assert_array_equal(np.asarray(ref_s), np.asarray(pal_s))
    np.testing.assert_array_equal(np.asarray(ref_p), np.asarray(pal_p))


def test_pallas_full_solver_parity():
    """The full batched solver with the DP routed through the Pallas kernel
    (interpret mode off-TPU) is bitwise identical to the vmap/scan path."""
    import jax.numpy as jnp

    from daccord_tpu.kernels.window_kernel import KernelParams, solve_window_batch
    from daccord_tpu.oracle.profile import ErrorProfile, OffsetLikely

    rng = np.random.default_rng(3)
    p = KernelParams(k=8, wlen=40, max_kmers=32)
    prof = ErrorProfile(p_ins=0.08, p_del=0.04, p_sub=0.015)
    ol = jnp.asarray(OffsetLikely(prof, positions=p.positions, max_offset=56).table)

    B, D, L, wlen = 16, 12, 64, 40
    true = rng.integers(0, 4, (B, wlen)).astype(np.int8)
    seqs = np.full((B, D, L), 4, dtype=np.int8)
    lens = np.zeros((B, D), dtype=np.int32)
    for b in range(B):
        for d in range(D):
            s = true[b].copy()
            for _ in range(3):
                s[rng.integers(0, wlen)] = rng.integers(0, 4)
            seqs[b, d, :wlen] = s
            lens[b, d] = wlen
    nsegs = np.full(B, D, dtype=np.int32)
    args = (jnp.asarray(seqs), jnp.asarray(lens), jnp.asarray(nsegs), ol)

    ref = solve_window_batch(*args, params=p)
    pal = solve_window_batch(*args, params=p, use_pallas=True, interpret=True)
    assert bool(np.asarray(ref["solved"]).any())
    for key in ("cons", "cons_len", "err", "solved"):
        np.testing.assert_array_equal(np.asarray(ref[key]), np.asarray(pal[key]))


def test_pallas_ladder_and_mesh_parity():
    """The full escalation ladder — and the mesh-sharded ladder — with the
    Pallas DP (interpret mode off-TPU) match the scan-path ladder bitwise,
    including rescue tiers driven by depth-masked compacted sub-batches."""
    import jax.numpy as jnp

    from daccord_tpu.kernels.tensorize import BatchShape, WindowBatch
    from daccord_tpu.kernels.tiers import TierLadder, solve_ladder
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.parallel.mesh import make_mesh, make_sharded_solver
    from daccord_tpu.oracle.profile import ErrorProfile

    rng = np.random.default_rng(5)
    ccfg = ConsensusConfig()
    prof = ErrorProfile(p_ins=0.08, p_del=0.04, p_sub=0.015)
    ladder = TierLadder.from_config(prof, ccfg, max_kmers=32, rescue_max_kmers=64)

    B, D, L, wlen = 16, 8, 64, ccfg.w
    seqs = np.full((B, D, L), 4, dtype=np.int8)
    lens = np.zeros((B, D), dtype=np.int32)
    for b in range(B):
        true = rng.integers(0, 4, wlen).astype(np.int8)
        # a couple of low-depth windows force tier escalation
        depth = 3 if b % 5 == 0 else D
        for d in range(depth):
            s = true.copy()
            for _ in range(4):
                s[rng.integers(0, wlen)] = rng.integers(0, 4)
            seqs[b, d, :wlen] = s
            lens[b, d] = wlen
    nsegs = (lens > 0).sum(axis=1).astype(np.int32)
    batch = WindowBatch(seqs=seqs, lens=lens, nsegs=nsegs,
                        shape=BatchShape(depth=D, seg_len=L, wlen=wlen),
                        read_ids=np.zeros(B, np.int64),
                        wstarts=np.zeros(B, np.int64))

    ref = solve_ladder(batch, ladder)
    pal = solve_ladder(batch, ladder, use_pallas=True, pallas_interpret=True)
    for key in ("cons", "cons_len", "err", "solved", "tier"):
        np.testing.assert_array_equal(np.asarray(ref[key]), np.asarray(pal[key]))

    mesh_pal = make_sharded_solver(ladder, make_mesh(8), use_pallas=True,
                                   pallas_interpret=True)(batch)
    for key in ("cons", "cons_len", "err", "solved", "tier"):
        np.testing.assert_array_equal(np.asarray(ref[key]),
                                      np.asarray(mesh_pal[key]))
