"""Shared-FS lease protocol units (utils/lease.py, ISSUE 15 satellite).

The protocol was extracted from parallel/fleet.py so the serve tier's
per-job leases (serve/service.py peer takeover) and the fleet's per-shard
leases run the SAME claim/heartbeat/release/takeover code; these are the
fleet's original protocol units moved alongside, now speaking the
path-based API directly, plus the payload/holder-check rules the serve
tier leans on.
"""

import json
import os

from daccord_tpu.utils import lease


def test_lease_claim_renew_takeover_units(tmp_path):
    p = str(tmp_path / "leases" / "job.lease")
    ok, takeover = lease.claim(p, "hostA", ttl_s=60.0)
    assert ok and takeover is None
    # a live lease loses the race
    ok, takeover = lease.claim(p, "hostB", ttl_s=60.0)
    assert not ok and takeover is None
    # a stale lease is taken over, reporting the previous holder
    lease.backdate(p, age_s=120.0)
    ok, takeover = lease.claim(p, "hostB", ttl_s=60.0)
    assert ok and takeover["prev_host"] == "hostA"
    assert takeover["stale_s"] > 60.0
    lease.release(p)
    ok, _ = lease.claim(p, "hostC", ttl_s=60.0)
    assert ok


def test_lease_payload_extra_and_read(tmp_path):
    """The payload carries host/pid/claimed_t plus caller extras — the serve
    tier stores the whole job descriptor so a takeover is self-contained."""
    p = str(tmp_path / "j.lease")
    ok, _ = lease.claim(p, "me", 60.0, extra={"job": "j00001",
                                              "nbytes": 42})
    assert ok
    info = lease.read(p)
    assert info["host"] == "me" and info["pid"] == os.getpid()
    assert info["job"] == "j00001" and info["nbytes"] == 42
    assert isinstance(info["claimed_t"], float)


def test_holder_checked_release(tmp_path):
    """A holder that was taken over must not delete the taker's lease."""
    p = str(tmp_path / "j.lease")
    lease.claim(p, "old", 60.0)
    lease.backdate(p, 120.0)
    ok, tk = lease.claim(p, "taker", 60.0)
    assert ok and tk["prev_host"] == "old"
    lease.release(p, host="old")       # old holder's release: refused
    assert lease.read(p)["host"] == "taker"
    lease.release(p, host="taker")     # the taker's own release: allowed
    assert lease.read(p) is None


def test_torn_lease_still_takeover_able(tmp_path):
    """A killed claimer's torn (non-JSON) lease file reads as None and is
    taken over once stale, with an unknown previous holder."""
    p = str(tmp_path / "j.lease")
    os.makedirs(tmp_path, exist_ok=True)
    with open(p, "w") as fh:
        fh.write('{"host": "torn')
    assert lease.read(p) is None
    lease.backdate(p, 120.0)
    ok, tk = lease.claim(p, "taker", 60.0)
    assert ok and tk["prev_host"] == "?"
    assert lease.read(p)["host"] == "taker"


def test_renew_and_stale_s(tmp_path):
    p = str(tmp_path / "j.lease")
    assert lease.stale_s(p) is None
    lease.claim(p, "me", 60.0)
    lease.backdate(p, 30.0)
    s = lease.stale_s(p)
    assert s is not None and 29.0 < s < 35.0
    lease.renew(p)
    assert lease.stale_s(p) < 5.0
    # renew of a vanished lease is tolerated (taken over mid-heartbeat)
    lease.release(p)
    lease.renew(p)


def test_fleet_wrappers_delegate(tmp_path):
    """The fleet's (outdir, shard) wrappers ride the shared protocol: a
    claim made through the fleet API is visible (and holder-checked)
    through the shared one, and the payload keeps the shard field."""
    from daccord_tpu.parallel import fleet as fleet_mod

    d = str(tmp_path)
    ok, _ = fleet_mod.claim_lease(d, 3, "orchA", ttl_s=60.0)
    assert ok
    p = fleet_mod.lease_path(d, 3)
    info = lease.read(p)
    assert info["host"] == "orchA" and info["shard"] == 3
    ok, _ = lease.claim(p, "orchB", 60.0)
    assert not ok
    fleet_mod.release_lease(d, 3, host="orchB")   # not the holder: refused
    assert fleet_mod.read_lease(d, 3)["host"] == "orchA"
    fleet_mod.release_lease(d, 3, host="orchA")
    assert fleet_mod.read_lease(d, 3) is None


def test_vacancy_claim_after_release_race(tmp_path):
    """A lease released between another claimant's failed O_EXCL create and
    its stat is a vacancy: the claim retries and wins (the claim-the-vacancy
    branch), exercised here by simply claiming an absent path twice."""
    p = str(tmp_path / "j.lease")
    ok, _ = lease.claim(p, "a", 60.0)
    assert ok
    lease.release(p, host="a")
    ok, tk = lease.claim(p, "b", 60.0)
    assert ok and tk is None
    assert json.load(open(p))["host"] == "b"
