"""Crash-durable serve tier (ISSUE 15): write-ahead job journal, replay,
peer lease takeover, bounded drain, and the SIGKILL lifecycle matrix.

The contract under test: a ``daccord-serve`` process that dies — at ANY
lifecycle point — loses no admitted job. On restart the journal replays:
orphans re-admit through the normal quota path and resume from their
per-job checkpoints; a mid-commit crash finalizes without recompute; a
duplicate submission bearing a seen idempotency key dedupes onto the
existing job. With a shared ``peer_dir``, a live peer detects the dead
process's stale per-job lease and finishes the job instead. Everything is
byte-identical to the solo run, quota balances restore, and no spool dir or
charge leaks.

The kill matrix SIGKILLs real server subprocesses (``serve_crash`` fires
``os._exit(137)`` after a chosen journal append — a SIGKILL landing between
syscalls); the in-process arms cover the replay/takeover/drain machinery
without subprocess overhead. The full 2-process chaos soak is the slow arm.
"""

import glob
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from daccord_tpu.sim import SimConfig, make_dataset

try:
    from daccord_tpu.native import available as _native_available

    HAVE_NATIVE = _native_available()
except Exception:
    HAVE_NATIVE = False

needs_native = pytest.mark.skipif(not HAVE_NATIVE,
                                  reason="native host path unavailable")


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("servedur"))
    cfg = SimConfig(genome_len=1500, coverage=10, read_len_mean=500,
                    min_overlap=200, seed=5)
    return make_dataset(d, cfg, name="sv"), d


def _solo_bytes(out, d):
    import dataclasses

    from daccord_tpu.runtime.pipeline import correct_to_fasta
    from daccord_tpu.serve.jobs import JobSpec, build_job_config

    spec = JobSpec.from_json({"db": out["db"], "las": out["las"]}, d)
    cfg = build_job_config(spec, "native", True, 64, "fused", d, "solo")
    cfg = dataclasses.replace(cfg, native_solver=True, supervise=True,
                              events_path=None, ledger_path=None,
                              job_tag=None, quarantine_path=None)
    ref = os.path.join(d, "solo-native.fasta")
    if not os.path.exists(ref):
        correct_to_fasta(out["db"], out["las"], ref, cfg)
    with open(ref, "rb") as fh:
        return fh.read()


def _svc(workdir, fault=None, **kw):
    """In-process service; ``fault`` sets DACCORD_FAULT for THIS service's
    FaultPlan (cleared right after construction)."""
    from daccord_tpu.serve import ConsensusService, ServeConfig

    kw.setdefault("backend", "native")
    kw.setdefault("backend_explicit", True)
    kw.setdefault("batch", 64)
    kw.setdefault("workers", 2)
    kw.setdefault("flush_lag_s", 0.02)
    kw.setdefault("checkpoint_reads", 4)
    old = os.environ.pop("DACCORD_FAULT", None)
    if fault:
        os.environ["DACCORD_FAULT"] = fault
    try:
        return ConsensusService(ServeConfig(workdir=str(workdir), **kw))
    finally:
        os.environ.pop("DACCORD_FAULT", None)
        if old is not None:
            os.environ["DACCORD_FAULT"] = old


def _poll(svc, job_id, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = svc.status(job_id)
        if st and st["state"] in ("done", "failed", "aborted"):
            return st
        time.sleep(0.05)
    return svc.status(job_id)


def _lint(paths):
    from daccord_tpu.tools.eventcheck import validate_events

    for p in paths:
        errs = validate_events(p, strict=True)
        assert not errs, (p, errs[:5])


def _journal(workdir):
    from daccord_tpu.serve.journal import replay

    return replay(os.path.join(str(workdir), "journal.jsonl"))


# ---------------------------------------------------------------------------
# journal units
# ---------------------------------------------------------------------------

def test_journal_append_replay_units(tmp_path):
    from daccord_tpu.serve.journal import JobJournal, replay

    p = str(tmp_path / "journal.jsonl")
    j = JobJournal(p)
    j.append("admitted", "j00001", tenant="a", nbytes=100,
             spec={"db": "x", "las": "y"}, dir="/tmp/j1", idem="k1")
    j.append("running", "j00001")
    j.append("progress", "j00001", emitted=8, bytes=512)
    j.append("admitted", "j00002", tenant="b", nbytes=7, spec={})
    j.append("committing", "j00001", bytes=900)
    j.append("aborted", "j00002")
    j.close()
    ents, torn = replay(p)
    assert torn == 0 and set(ents) == {"j00001", "j00002"}
    e1 = ents["j00001"]
    assert e1.state == "committing" and e1.part_bytes == 900
    assert e1.tenant == "a" and e1.nbytes == 100 and e1.idem == "k1"
    assert e1.dir == "/tmp/j1" and not e1.terminal
    assert ents["j00002"].terminal and ents["j00002"].state == "aborted"


def test_journal_torn_tail_tolerated(tmp_path):
    """A crash mid-append tears the last line; replay trusts exactly the
    records that fsync'd before it — like every torn manifest in the repo."""
    from daccord_tpu.serve.journal import JobJournal, replay

    p = str(tmp_path / "journal.jsonl")
    j = JobJournal(p)
    j.append("admitted", "j00001", tenant="a", nbytes=1, spec={})
    j.append("running", "j00001")
    j.close()
    with open(p, "ab") as fh:
        fh.write(b'{"rec": "committed", "job": "j000')   # torn mid-write
    ents, torn = replay(p)
    assert torn == 1
    assert ents["j00001"].state == "running"   # the torn commit never counts


def test_journal_compact_keeps_idem_memory(tmp_path):
    """Compaction collapses terminal jobs to their idempotency memory and
    drops keyless terminal jobs entirely — the file stays bounded while
    duplicate submissions keep deduping."""
    from daccord_tpu.serve.journal import JobJournal, compact, replay

    p = str(tmp_path / "journal.jsonl")
    j = JobJournal(p)
    j.append("admitted", "j00001", tenant="a", nbytes=1, spec={}, idem="k1")
    j.append("committed", "j00001")
    j.append("admitted", "j00002", tenant="a", nbytes=1, spec={})
    j.append("committed", "j00002")
    j.append("admitted", "j00003", tenant="a", nbytes=1, spec={})
    j.append("running", "j00003")
    j.close()
    ents, _ = replay(p)
    compact(p, ents)
    ents2, torn = replay(p)
    assert torn == 0
    assert set(ents2) == {"j00001", "j00003"}    # j00002: terminal, keyless
    assert ents2["j00001"].terminal and ents2["j00001"].idem == "k1"
    assert ents2["j00003"].state == "running"    # live jobs keep their state


def test_serve_fault_kinds_parse_and_count():
    from daccord_tpu.runtime.faults import FaultPlan

    plan = FaultPlan.parse("serve_crash:3,serve_hang:2")
    assert not plan.serve_crash_check()        # append 1
    assert not plan.serve_crash_check()        # append 2
    assert plan.serve_crash_check()            # append 3 fires
    assert not plan.serve_crash_check()        # one-shot
    assert not plan.serve_hang_check()
    assert plan.serve_hang_check()
    assert not plan.serve_hang_check()
    # unknown-to-serve kinds still parse everywhere (pipeline plans see
    # the same spec); fleet stripping leaves serve kinds alone
    from daccord_tpu.runtime.faults import non_fleet_spec

    assert non_fleet_spec("serve_crash:1,worker_hang:2") == "serve_crash:1"


# ---------------------------------------------------------------------------
# replay + idempotency + bounded drain (in-process)
# ---------------------------------------------------------------------------

@needs_native
def test_replay_requeues_and_resumes(dataset, tmp_path):
    """A dead service's queued AND running jobs replay on restart: the
    running orphan resumes from its per-job checkpoint, the queued one runs
    fresh — both byte-identical, quota balances restored, idempotency keys
    surviving the restart, and exactly one commit per job."""
    out, d = dataset
    ref = _solo_bytes(out, d)
    w = tmp_path / "srv"
    # worker 1 wedges on job 1 (serve_hang): job 2 queues behind it; the
    # abandoned service stands in for a crashed process (the journal holds
    # everything fsync'd — in-process we simply never call shutdown)
    svc1 = _svc(w, fault="serve_hang:1", workers=1)
    j1 = svc1.submit({"db": out["db"], "las": out["las"], "tenant": "a",
                      "idempotency_key": "k1"})
    j2 = svc1.submit({"db": out["db"], "las": out["las"], "tenant": "b"})
    time.sleep(0.6)
    dup = svc1.submit({"db": out["db"], "las": out["las"], "tenant": "a",
                       "idempotency_key": "k1"})
    assert dup["job"] == j1["job"] and dup.get("idempotent")
    svc1._stop.set()     # "crash": no drain, no journal close
    svc2 = _svc(w)
    s1 = _poll(svc2, j1["job"])
    s2 = _poll(svc2, j2["job"])
    assert s1["state"] == "done" and s2["state"] == "done", (s1, s2)
    for j in (j1, j2):
        got = open(os.path.join(str(w), "jobs", j["job"], "out.fasta"),
                   "rb").read()
        assert got == ref
    # idempotency survived the restart (rebuilt from the journal)
    dup2 = svc2.submit({"db": out["db"], "las": out["las"],
                        "idempotency_key": "k1"})
    assert dup2["job"] == j1["job"] and dup2.get("idempotent")
    st = svc2.stats()
    for t in st["admission"]["tenants"].values():
        assert t["queued"] == 0 and t["bytes"] == 0
    assert svc2.shutdown() is True
    ev = [json.loads(l) for l in
          open(os.path.join(str(w), "serve.events.jsonl"))]
    assert any(e["event"] == "serve.replay" and e["orphans"] == 2
               for e in ev)
    commits = [e for e in ev if e["event"] == "serve.commit"]
    assert sorted(e["job"] for e in commits) == sorted(
        [j1["job"], j2["job"]])
    _lint([os.path.join(str(w), "serve.events.jsonl")]
          + glob.glob(os.path.join(str(w), "g*.events.jsonl")))
    # journal folded terminal; no duplicate job dirs
    ents, torn = _journal(w)
    assert torn == 0
    assert sorted(os.listdir(os.path.join(str(w), "jobs"))) == sorted(
        [j1["job"], j2["job"]])


@needs_native
def test_mid_commit_crash_finalizes_without_rerun(dataset, tmp_path):
    """A ``committing`` journal record + an intact part file = the crash
    landed between the FASTA fsync and the publishing rename: replay
    finishes the commit in place — rename + manifest, NO recompute — and
    the job answers done."""
    out, d = dataset
    ref = _solo_bytes(out, d)
    w = tmp_path / "srv"
    # run one job cleanly to get real bytes + a real spec payload
    svc1 = _svc(w)
    j1 = svc1.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
    assert _poll(svc1, j1["job"])["state"] == "done"
    assert svc1.shutdown() is True
    jobdir = os.path.join(str(w), "jobs", j1["job"])
    fasta = os.path.join(jobdir, "out.fasta")
    # rewind the commit: fasta back to part, manifest gone, journal ends
    # at `committing` — exactly the mid-commit crash window
    data = open(fasta, "rb").read()
    os.replace(fasta, os.path.join(jobdir, "out.fasta.part"))
    os.remove(os.path.join(jobdir, "manifest.json"))
    import dataclasses

    from daccord_tpu.serve.jobs import JobSpec
    from daccord_tpu.serve.journal import JobJournal

    spec = JobSpec.from_json({"db": out["db"], "las": out["las"]}, jobdir)
    jj = JobJournal(os.path.join(str(w), "journal.jsonl"))
    jj.append("admitted", j1["job"], tenant="a", nbytes=1,
              spec=dataclasses.asdict(spec), dir=jobdir)
    jj.append("running", j1["job"])
    jj.append("committing", j1["job"], bytes=len(data))
    jj.close()
    svc2 = _svc(w)
    # the finalize happens AT replay (before workers pick anything up):
    # no recompute means the fasta/manifest already exist at construction
    assert os.path.exists(fasta) and open(fasta, "rb").read() == data == ref
    man = json.load(open(os.path.join(jobdir, "manifest.json")))
    assert man.get("recovered") is True
    ents, _ = _journal(w)
    assert ents[j1["job"]].state == "committed"
    assert svc2.shutdown() is True


@needs_native
def test_mid_commit_digest_mismatch_resolves_instead(dataset, tmp_path):
    """ISSUE 20 integrity chain, takeover-finalize link: the ``committing``
    record journals the sha256 of the fsync'd part bytes. A part file
    silently corrupted between crash and recovery — same size, wrong
    bytes, so the size gate passes — must NOT be renamed into place:
    finalize refuses (``io.fault``), the orphan re-admits, and the job
    re-solves to the byte-exact reference."""
    import hashlib

    out, d = dataset
    ref = _solo_bytes(out, d)
    w = tmp_path / "srv"
    svc1 = _svc(w)
    j1 = svc1.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
    assert _poll(svc1, j1["job"])["state"] == "done"
    assert svc1.shutdown() is True
    jobdir = os.path.join(str(w), "jobs", j1["job"])
    fasta = os.path.join(jobdir, "out.fasta")
    data = open(fasta, "rb").read()
    # rewind to the mid-commit window, journaling the TRUE digest...
    os.replace(fasta, os.path.join(jobdir, "out.fasta.part"))
    os.remove(os.path.join(jobdir, "manifest.json"))
    import dataclasses

    from daccord_tpu.serve.jobs import JobSpec
    from daccord_tpu.serve.journal import JobJournal

    spec = JobSpec.from_json({"db": out["db"], "las": out["las"]}, jobdir)
    jj = JobJournal(os.path.join(str(w), "journal.jsonl"))
    jj.append("admitted", j1["job"], tenant="a", nbytes=1,
              spec=dataclasses.asdict(spec), dir=jobdir)
    jj.append("running", j1["job"])
    jj.append("committing", j1["job"], bytes=len(data),
              sha=hashlib.sha256(data).hexdigest())
    jj.close()
    # ...then corrupt the part in place: one flipped base, same length
    part = os.path.join(jobdir, "out.fasta.part")
    seq_at = data.index(b"\n") + 1
    flip = b"C" if data[seq_at:seq_at + 1] != b"C" else b"G"
    with open(part, "r+b") as fh:
        fh.seek(seq_at)
        fh.write(flip)
    svc2 = _svc(w)
    # finalize refused at replay: no wrong-bytes publish at construction
    assert not os.path.exists(fasta)
    st = _poll(svc2, j1["job"])
    assert st["state"] == "done"
    assert open(fasta, "rb").read() == ref       # re-solved, byte-exact
    ev = [json.loads(ln) for ln in
          open(os.path.join(str(w), "serve.events.jsonl")) if ln.strip()]
    refusals = [r for r in ev if r.get("event") == "io.fault"
                and r.get("op") == "finalize"]
    assert refusals and "digest" in refusals[0]["error"]
    assert svc2.shutdown() is True
    _lint([os.path.join(str(w), "serve.events.jsonl")])


@needs_native
def test_bounded_drain_marks_interrupted_and_resumes(dataset, tmp_path):
    """A wedged group thread no longer hangs shutdown forever: past the
    drain deadline the in-flight job is journal-marked INTERRUPTED
    (resumable) and shutdown reports unclean — and the next incarnation
    replays it to a byte-identical commit."""
    out, d = dataset
    ref = _solo_bytes(out, d)
    w = tmp_path / "srv"
    svc1 = _svc(w, fault="serve_hang:1", workers=1, drain_deadline_s=0.5)
    j1 = svc1.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
    time.sleep(0.5)
    t0 = time.time()
    assert svc1.shutdown() is False          # bounded: unclean, not hung
    assert time.time() - t0 < 30
    ents, _ = _journal(w)
    assert ents[j1["job"]].state == "interrupted"
    svc2 = _svc(w)
    st = _poll(svc2, j1["job"])
    assert st["state"] == "done"
    got = open(os.path.join(str(w), "jobs", j1["job"], "out.fasta"),
               "rb").read()
    assert got == ref
    assert svc2.shutdown() is True


@needs_native
def test_peer_takeover_finishes_dead_peers_job(dataset, tmp_path):
    """The tentpole's (b): a peer on the shared FS detects the dead
    process's stale per-job lease, claims the journaled job, and finishes
    it byte-identically — observable via serve.takeover + the takeovers
    counter; the owner's restart then sees the peer's manifest and re-runs
    nothing."""
    out, d = dataset
    ref = _solo_bytes(out, d)
    peer = str(tmp_path / "peer")
    from daccord_tpu.utils import lease

    A = _svc(tmp_path / "srvA", fault="serve_hang:1", workers=1,
             peer_dir=peer, lease_ttl_s=2.0, heartbeat_s=0.2)
    j = A.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
    time.sleep(0.6)
    A._stop.set()      # A "dies": heartbeats stop
    time.sleep(0.3)
    lp = glob.glob(os.path.join(peer, "leases", "*.lease"))
    assert len(lp) == 1 and lp[0].endswith(f"srvA.{j['job']}.lease")
    lease.backdate(lp[0], 10.0)   # don't burn TTL wall-clock
    B = _svc(tmp_path / "srvB", workers=2, peer_dir=peer, lease_ttl_s=2.0,
             heartbeat_s=0.2)
    key = f"srvA.{j['job']}"
    deadline = time.time() + 120
    st = None
    while time.time() < deadline:
        st = B.status(key)
        if st and st["state"] in ("done", "failed", "aborted"):
            break
        time.sleep(0.05)
    assert st and st["state"] == "done", st
    got = open(os.path.join(str(tmp_path / "srvA"), "jobs", j["job"],
                            "out.fasta"), "rb").read()
    assert got == ref
    # the dead owner restarts: replay sees the peer's manifest — finished,
    # zero re-runs (the exactly-once half of the contract)
    C = _svc(tmp_path / "srvA", workers=1, peer_dir=peer, lease_ttl_s=2.0,
             heartbeat_s=0.2)
    evA = [json.loads(l) for l in
           open(os.path.join(str(tmp_path / "srvA"), "serve.events.jsonl"))]
    rep = [e for e in evA if e["event"] == "serve.replay"]
    assert rep and rep[-1]["finished"] == 1 and rep[-1]["orphans"] == 0
    assert B.shutdown() is True and C.shutdown() is True
    roll = json.load(open(os.path.join(str(tmp_path / "srvB"),
                                       "serve.metrics.json")))
    assert roll["metrics"]["counters"].get("takeovers") == 1
    evB = [json.loads(l) for l in
           open(os.path.join(str(tmp_path / "srvB"), "serve.events.jsonl"))]
    tk = [e for e in evB if e["event"] == "serve.takeover"]
    assert len(tk) == 1 and tk[0]["job"] == key
    assert tk[0]["prev_host"].startswith("srvA@")   # service@host:pid
    _lint([os.path.join(str(tmp_path / "srvB"), "serve.events.jsonl"),
           os.path.join(str(tmp_path / "srvA"), "serve.events.jsonl")])


# ---------------------------------------------------------------------------
# SIGKILL lifecycle matrix (real subprocesses)
# ---------------------------------------------------------------------------

def _spawn_serve(workdir, root, tag, fault=None, checkpoint_reads=4,
                 extra=()):
    ready = os.path.join(str(root), f"ready-{tag}.json")
    argv = [sys.executable, "-m", "daccord_tpu.tools.cli", "serve",
            "--workdir", str(workdir), "--backend", "native", "-b", "64",
            "--workers", "2", "--port", "0", "--ready-file", ready,
            "--checkpoint-reads", str(checkpoint_reads), "--flush-lag-ms",
            "20", *extra]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__import__("daccord_tpu").__file__)))
    env["PYTHONPATH"] = pkg_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    if fault:
        env["DACCORD_FAULT"] = fault
    else:
        env.pop("DACCORD_FAULT", None)
    log = open(os.path.join(str(root), f"serve-{tag}.log"), "wb")
    proc = subprocess.Popen(argv, env=env, stdout=log, stderr=log)
    deadline = time.time() + 120
    port = None
    while time.time() < deadline:
        if os.path.exists(ready):
            try:
                port = json.load(open(ready))["port"]
                break
            except (OSError, json.JSONDecodeError, ValueError):
                pass
        if proc.poll() is not None:
            break
        time.sleep(0.05)
    return proc, port


def _req(port, method, path, body=None, timeout=120):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return resp.status, resp.read()


@needs_native
@pytest.mark.parametrize("point,fault,ck,stream", [
    # journal appends run in lifecycle order, so serve_crash:N pins the
    # SIGKILL to an exact point: 1 = the admitted append (post-admit,
    # pre-queue — the 201 may never even reach the client), 3 with a
    # 4-read checkpoint stride = the first progress append (running
    # mid-batch; also the mid-stream client arm), 3 with checkpoints off =
    # the committing append (between the FASTA fsync and the rename)
    ("post_admit", "serve_crash:1", 4, False),
    ("running_mid_batch", "serve_crash:3", 4, True),
    ("mid_commit", "serve_crash:3", 0, False),
])
def test_kill_matrix_sigkill_restart_parity(dataset, tmp_path, point,
                                            fault, ck, stream):
    out, d = dataset
    ref = _solo_bytes(out, d)
    w = tmp_path / "srv"
    proc, port = _spawn_serve(w, tmp_path, "a", fault=fault,
                              checkpoint_reads=ck)
    assert port is not None or proc.poll() is not None
    job_id = None
    if port is not None:
        try:
            code, raw = _req(port, "POST", "/v1/jobs",
                             {"db": out["db"], "las": out["las"],
                              "idempotency_key": f"km-{point}"},
                             timeout=60)
            job_id = json.loads(raw)["job"]
        except (urllib.error.URLError, ConnectionError, OSError):
            pass   # post_admit: the crash can beat the 201 — idempotency
                   # key recovers the identity below
        if stream and job_id:
            # a client mid-stream when the server dies: the disconnect is
            # the client's problem; the job itself must survive
            import http.client

            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/jobs/{job_id}/stream",
                    timeout=5).read()
            except (urllib.error.URLError, ConnectionError, OSError,
                    TimeoutError, http.client.HTTPException):
                pass
    rc = proc.wait(timeout=180)
    assert rc == 137, f"{point}: expected the injected SIGKILL, got {rc}"
    # restart clean: replay must finish the job
    proc2, port2 = _spawn_serve(w, tmp_path, "b", fault=None,
                                checkpoint_reads=ck)
    assert port2 is not None
    # identity via idempotency key (covers the lost-201 case)
    code, raw = _req(port2, "POST", "/v1/jobs",
                     {"db": out["db"], "las": out["las"],
                      "idempotency_key": f"km-{point}"}, timeout=120)
    st = json.loads(raw)
    if job_id is None:
        job_id = st["job"]
    assert st["job"] == job_id
    assert code == 200 and st.get("idempotent"), (code, st)
    code, raw = _req(port2, "GET", f"/v1/jobs/{job_id}/result?wait=1",
                     timeout=300)
    assert code == 200 and raw == ref, f"{point}: resumed FASTA diverged"
    # quota restored + no duplicate job dirs + journal terminal exactly once.
    # The result becomes readable at state=DONE, a moment BEFORE the worker's
    # finally block releases the admission quota — poll briefly so a loaded
    # host doesn't observe that window as a leak
    deadline = time.time() + 30
    while True:
        code, raw = _req(port2, "GET", "/v1/metrics", timeout=60)
        m = json.loads(raw)
        if all(t["queued"] == 0 and t["bytes"] == 0
               for t in m["admission"]["tenants"].values()) \
                or time.time() > deadline:
            break
        time.sleep(0.25)
    for t in m["admission"]["tenants"].values():
        assert t["queued"] == 0 and t["bytes"] == 0
    _req(port2, "POST", "/v1/shutdown", timeout=60)
    assert proc2.wait(timeout=180) == 0
    assert os.listdir(os.path.join(str(w), "jobs")) == [job_id]
    ents, _ = _journal(w)
    assert ents[job_id].state == "committed"
    ev = [json.loads(l) for l in
          open(os.path.join(str(w), "serve.events.jsonl"))]
    commits = [e for e in ev if e["event"] == "serve.commit"]
    assert len(commits) == 1 and commits[0]["job"] == job_id
    if point == "mid_commit":
        # the fsync'd part finalized in place: the recovery manifest marks
        # zero-recompute (commit event carries fragments=-1 at replay)
        man = json.load(open(os.path.join(str(w), "jobs", job_id,
                                          "manifest.json")))
        assert man.get("recovered") is True
    _lint([os.path.join(str(w), "serve.events.jsonl")])


# ---------------------------------------------------------------------------
# tooling: sentinel red flags + top lease table
# ---------------------------------------------------------------------------

def _write_events(path, recs):
    t0 = time.time()
    with open(path, "w") as fh:
        for i, r in enumerate(recs):
            fh.write(json.dumps({"t": 0.001 * i, "ts": t0 + 0.001 * i,
                                 **r}) + "\n")


def test_sentinel_flags_replay_without_commit(tmp_path):
    from daccord_tpu.tools.sentinel import scan_events

    p = str(tmp_path / "serve.events.jsonl")
    _write_events(p, [
        {"event": "serve.journal", "rec": "replayed", "job": "j00001"},
        {"event": "serve.journal", "rec": "replayed", "job": "j00002"},
        {"event": "serve.journal", "rec": "committed", "job": "j00002"},
    ])
    issues = scan_events(p)
    assert any("j00001" in i and "replayed" in i for i in issues)
    assert not any("j00002" in i for i in issues)


def test_sentinel_flags_repeated_takeover(tmp_path):
    from daccord_tpu.tools.sentinel import scan_events

    p = str(tmp_path / "serve.events.jsonl")
    _write_events(p, [
        {"event": "serve.takeover", "job": "srvA.j00001",
         "prev_host": "srvA:1", "stale_s": 5.0},
        {"event": "serve.takeover", "job": "srvA.j00001",
         "prev_host": "srvB:2", "stale_s": 5.0},
        {"event": "serve.journal", "rec": "committed", "job": "srvA.j00001"},
        {"event": "serve.takeover", "job": "srvA.j00002",
         "prev_host": "srvA:1", "stale_s": 5.0},
    ])
    issues = scan_events(p)
    assert any("taken over 2 times" in i for i in issues)
    assert not any("j00002" in i and "taken over" in i for i in issues)


def test_top_renders_lease_ownership(tmp_path):
    from daccord_tpu.tools.top import collect, render
    from daccord_tpu.utils import lease

    peer = tmp_path / "peer"
    lease.claim(str(peer / "leases" / "srvA.j00001.lease"), "srvA:42", 15.0,
                extra={"job": "j00001", "service": "srvA"})
    lease.claim(str(peer / "leases" / "srvB.j00007.lease"), "srvB:43", 15.0,
                extra={"job": "j00007", "service": "srvB"})
    lease.backdate(str(peer / "leases" / "srvB.j00007.lease"), 120.0)
    snap = collect([str(peer)])
    assert len(snap["leases"]) == 2
    by_name = {l["name"]: l for l in snap["leases"]}
    assert by_name["srvA.j00001"]["holder"] == "srvA:42"
    assert by_name["srvB.j00007"]["age_s"] > 60
    text = render(snap)
    assert "LEASE" in text and "srvA.j00001" in text and "srvB:43" in text


def test_eventcheck_accepts_and_rejects_new_kinds(tmp_path):
    from daccord_tpu.tools.eventcheck import validate_events

    good = str(tmp_path / "good.jsonl")
    _write_events(good, [
        {"event": "serve.journal", "rec": "admitted", "job": "j00001"},
        {"event": "serve.replay", "jobs": 3, "orphans": 1, "finished": 1,
         "torn": 0},
        {"event": "serve.takeover", "job": "srvA.j00001",
         "prev_host": "srvA:7", "stale_s": 4.5},
    ])
    assert validate_events(good, strict=True) == []
    bad = str(tmp_path / "bad.jsonl")
    _write_events(bad, [
        {"event": "serve.takeover", "job": "srvA.j00001"},   # missing fields
    ])
    assert validate_events(bad, strict=True)


# ---------------------------------------------------------------------------
# chaos soak (slow): the acceptance gate
# ---------------------------------------------------------------------------

@needs_native
@pytest.mark.slow
def test_chaos_soak_two_processes(tmp_path):
    """The ISSUE 15 acceptance run: 2 serve processes sharing a peer dir,
    >= 20 jobs on a seeded arrival trace, deterministic serve_crash +
    device_lost storm with restarts. run_serve_soak ASSERTS the contract
    (terminal exactly once, byte parity vs solo, zero leaked quota/spool)
    and raises on any violation."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    line = bench.run_serve_soak(root=str(tmp_path / "soak"), n_jobs=20,
                                commit_sidecar=False)
    assert line["jobs"] == 20 and line["parity"] is True
    assert line["done"] + line["aborted"] == 20
    assert line["crashes"] >= 1
    assert line["takeovers"] + line["replay_orphans"] >= 1
