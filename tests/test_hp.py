"""Homopolymer rescue tier (oracle/hp.py): mechanism + gating unit tests."""

import numpy as np
import pytest

from daccord_tpu.oracle.consensus import ConsensusConfig, make_offset_likely
from daccord_tpu.oracle.dbg import DBGParams, window_consensus
from daccord_tpu.oracle.hp import (hp_candidate, hp_compress, hp_expand,
                                   max_run, vote_runs)
from daccord_tpu.oracle.profile import ErrorProfile

TRUTH = np.array([0, 1, 2, 2, 2, 2, 3, 0, 1, 1, 1, 3, 2, 0, 0, 0, 0, 0,
                  1, 2, 3, 3, 1, 0, 2, 1, 1, 1, 1, 3, 0, 2, 3, 1, 0, 0,
                  0, 2, 1, 3], dtype=np.int8)


def test_hp_compress_expand_roundtrip():
    for seg in (TRUTH, np.zeros(5, np.int8), np.array([2], np.int8),
                np.zeros(0, np.int8)):
        c, r = hp_compress(seg)
        assert len(c) == len(r)
        assert np.array_equal(hp_expand(c, r), seg)
        if len(c) > 1:
            assert np.all(c[1:] != c[:-1])   # no adjacent equal bases
    assert max_run(TRUTH) == 5
    assert max_run(np.zeros(0, np.int8)) == 0


def _hp_noisy(rng, seg, slope=1.0, p_ind=0.12, p_sub=0.02):
    """Length-dependent run-length noise: sim/synth.py's hp channel in
    miniature — per-base deletion + GEOMETRIC same-base insertions, both
    length-scaled, ins 2:1 over del. Run observations drift long, which the
    calibrated posterior vote models and the flat median cannot."""
    c, runs = hp_compress(seg)
    out = []
    for b, r in zip(c, runs):
        f = 1 + slope * min(int(r) - 1, 8)
        pd = min(0.45, p_ind * f / 3)
        pi = min(0.45, 2 * p_ind * f / 3)
        rr = 0
        for _ in range(int(r)):
            if rng.random() >= pd:
                rr += 1
            rr += rng.geometric(1 - pi) - 1
        out.extend([b] * rr)
    s = np.array(out, dtype=np.int8)
    subm = rng.random(len(s)) < p_sub
    if subm.any():
        s[subm] = (s[subm] + rng.integers(1, 4, subm.sum())) % 4
    return s


def test_vote_runs_recovers_truth_lengths():
    """Median vote at depth 20 on MILD hp noise recovers run lengths; under
    the full asymmetric stress process its drift bias shows (the posterior
    test below covers that regime)."""
    rng = np.random.default_rng(11)
    cseq, truth_runs = hp_compress(TRUTH)
    comp = [hp_compress(_hp_noisy(rng, TRUTH, slope=0.3, p_ind=0.06))
            for _ in range(20)]
    voted = vote_runs(cseq, comp)
    assert np.abs(voted - truth_runs).sum() <= 1


def test_hp_slope_fit_separates_clean_from_damaged():
    """profile_vs_consensus fits hp_slope ~ 0 on clean pairs and a clearly
    positive slope (with a positive base intensity) on hp-damaged pairs."""
    from daccord_tpu.oracle.profile import profile_vs_consensus

    rng = np.random.default_rng(5)

    def pairs_for(slope):
        out = []
        for _ in range(30):
            g = np.concatenate([np.full(rng.integers(1, 7), rng.integers(0, 4))
                                for _ in range(40)]).astype(np.int8)[:120]
            out.append((g, _hp_noisy(rng, g, slope=slope,
                                     p_ind=0.10 if slope else 0.04)))
        return out

    clean = profile_vs_consensus(pairs_for(0.0))
    damaged = profile_vs_consensus(pairs_for(2.0))
    assert clean.hp_slope <= 0.5
    assert damaged.hp_slope >= 0.8
    assert damaged.hp_base > 0


def test_posterior_vote_beats_median_on_calibrated_noise():
    """At the hp stress regime's rates the flat median mostly misses the true
    run length; the calibrated posterior recovers it far more often."""
    from daccord_tpu.oracle.hp import hp_length_tables, vote_runs_posterior

    rng = np.random.default_rng(7)
    prof = ErrorProfile(p_ins=0.08, p_del=0.04, p_sub=0.015,
                        hp_slope=1.0, hp_base=0.12, hp_cap=8)
    ltab = hp_length_tables(prof)

    def obs_run(L, b, slope=1.0):
        x = min(L - 1, 8)
        qd = min(0.04 * (1 + slope * x), .45)
        qi = min(0.08 * (1 + slope * x), .45)
        seg = []
        for _ in range(L):
            u = rng.random()
            if u < qd:
                pass
            elif u < qd + 0.015:
                seg.append((b + 1) % 4)
            else:
                seg.append(b)
            seg.extend([b] * (rng.geometric(1 - qi) - 1))
        return seg

    hits_m = hits_p = tot = 0
    for _ in range(120):
        L = int(rng.integers(3, 13))
        cons = np.array([2, 0, 3], dtype=np.int8)
        comp = [hp_compress(np.array([2] + obs_run(L, 0) + [3], dtype=np.int8))
                for _ in range(20)]
        hits_m += int(vote_runs(cons, comp)[1] == L)
        hits_p += int(vote_runs_posterior(cons, comp, ltab)[1] == L)
        tot += 1
    assert hits_p / tot >= 0.4
    assert hits_p > hits_m * 2


def test_eprof_hp_fields_roundtrip(tmp_path):
    p = ErrorProfile(p_ins=0.07, p_del=0.03, p_sub=0.01,
                     hp_slope=1.25, hp_base=0.09, hp_cap=8)
    f = str(tmp_path / "e.json")
    p.save(f)
    q = ErrorProfile.load(f)
    assert (q.hp_slope, q.hp_base, q.hp_cap) == (1.25, 0.09, 8)
    # pre-r5 files (no hp fields) load with slope 0 / base 0
    import json

    d = json.load(open(f))
    for k in ("hp_slope", "hp_base", "hp_cap"):
        d.pop(k)
    with open(f, "wt") as fh:
        json.dump(d, fh)
    q = ErrorProfile.load(f)
    assert (q.hp_slope, q.hp_base) == (0.0, 0.0)


def test_hp_candidate_beats_direct_on_damaged_windows():
    from daccord_tpu.oracle.align import edit_distance

    rng = np.random.default_rng(7)
    cfg = ConsensusConfig(hp_rescue=True)
    ols = make_offset_likely(ErrorProfile(p_ins=0.06, p_del=0.06, p_sub=0.02),
                             cfg)
    p = DBGParams(k=8)
    d_tot = h_tot = wins = loses = 0
    for _ in range(8):
        segs = [_hp_noisy(rng, TRUTH) for _ in range(20)]
        direct = window_consensus(segs, ols[8], p, wlen=40)
        d_ed = 99 if direct.seq is None else edit_distance(direct.seq, TRUTH)
        hp = hp_candidate(segs, direct.seq, direct.err, ols, cfg)
        h_ed = d_ed if hp is None else edit_distance(hp.seq, TRUTH)
        d_tot += d_ed
        h_tot += h_ed
        wins += h_ed < d_ed
        loses += h_ed > d_ed
    assert wins >= 2 and loses == 0, (wins, loses)
    assert h_tot < d_tot          # strict improvement in truth edits overall


def test_hp_candidate_not_routed_on_clean_solve():
    rng = np.random.default_rng(3)
    cfg = ConsensusConfig(hp_rescue=True)
    ols = make_offset_likely(ErrorProfile(p_ins=0.02, p_del=0.02, p_sub=0.01),
                             cfg)
    p = DBGParams(k=8)
    segs = [_hp_noisy(rng, TRUTH, slope=0.0, p_ind=0.03, p_sub=0.01)
            for _ in range(20)]
    direct = window_consensus(segs, ols[8], p, wlen=40)
    assert direct.seq is not None and direct.err <= cfg.hp_err
    assert hp_candidate(segs, direct.seq, direct.err, ols, cfg) is None


def test_native_align_parity_random():
    """Native align_map / edit_distance_sum are bit-identical to the python
    align_path / per-pair fallback (and exact vs brute force)."""
    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    from daccord_tpu.oracle import align as A

    rng = np.random.default_rng(13)
    for _ in range(60):
        n, m = int(rng.integers(1, 70)), int(rng.integers(1, 70))
        a = rng.integers(0, 4, n).astype(np.int8)
        b = rng.integers(0, 4, m).astype(np.int8)
        d_nat, map_nat = A.align_path(a, b)
        orig = A._native_lib
        A._native_lib = lambda: None
        try:
            d_py, map_py = A.align_path(a, b)
            d_ed = A.edit_distance(a, b)
        finally:
            A._native_lib = orig
        assert d_nat == d_py
        assert np.array_equal(map_nat, map_py)
        # both paths are exact by the verify-retry rule => equal, not <=
        assert A.edit_distance(a, b) == d_ed
    segs = [rng.integers(0, 4, int(rng.integers(1, 60))).astype(np.int8)
            for _ in range(25)]
    cand = rng.integers(0, 4, 45).astype(np.int8)
    s_nat = A.edit_distance_sum(cand, segs)
    orig = A._native_lib
    A._native_lib = lambda: None
    try:
        s_py = sum(A.edit_distance(cand, s) for s in segs)
    finally:
        A._native_lib = orig
    assert s_nat == s_py


def test_native_hp_rescue_parity(tmp_path):
    """The C++ in-engine hp rescue (hp_rescue_windows) is byte-identical to
    the python host pass on an hp-damaged sim, end to end."""
    import os

    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    from daccord_tpu.runtime.pipeline import PipelineConfig, correct_to_fasta
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path)
    out = make_dataset(d, SimConfig(genome_len=4000, coverage=18,
                                    read_len_mean=900, min_overlap=300,
                                    hp_indel_slope=1.0, seed=31), name="hp")
    f_cpp = os.path.join(d, "hp_cpp.fasta")
    f_py = os.path.join(d, "hp_py.fasta")
    ccfg = ConsensusConfig(hp_rescue=True)
    s_cpp = correct_to_fasta(out["db"], out["las"], f_cpp,
                             PipelineConfig(batch_size=256, native_solver=True,
                                            consensus=ccfg, hp_native=True))
    s_py = correct_to_fasta(out["db"], out["las"], f_py,
                            PipelineConfig(batch_size=256, native_solver=True,
                                           consensus=ccfg, hp_native=False))
    assert s_cpp.n_hp_rescued > 0
    assert s_cpp.n_hp_rescued == s_py.n_hp_rescued
    assert open(f_cpp, "rb").read() == open(f_py, "rb").read()


def test_native_hp_posterior_parity(tmp_path):
    """The C++ in-engine POSTERIOR vote (tables built python-side, walk
    mirrored in C++) is byte-identical to the python host pass end to end,
    and the fixture actually engages the posterior (fitted slope >= 0.1)."""
    import os

    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import (PipelineConfig, correct_to_fasta,
                                              estimate_profile_for_shard)
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path)
    out = make_dataset(d, SimConfig(genome_len=4000, coverage=18,
                                    read_len_mean=900, min_overlap=300,
                                    hp_indel_slope=1.0, seed=31), name="hpp")
    prof = estimate_profile_for_shard(read_db(out["db"]), LasFile(out["las"]),
                                      PipelineConfig())
    assert prof.hp_slope >= 0.1   # the gate must engage or this test is vacuous
    f_cpp = os.path.join(d, "p_cpp.fasta")
    f_py = os.path.join(d, "p_py.fasta")
    ccfg = ConsensusConfig(hp_rescue=True, hp_vote="posterior")
    s_cpp = correct_to_fasta(out["db"], out["las"], f_cpp,
                             PipelineConfig(batch_size=256, native_solver=True,
                                            consensus=ccfg, hp_native=True),
                             profile=prof)
    s_py = correct_to_fasta(out["db"], out["las"], f_py,
                            PipelineConfig(batch_size=256, native_solver=True,
                                           consensus=ccfg, hp_native=False),
                            profile=prof)
    assert s_cpp.n_hp_rescued > 0
    assert s_cpp.n_hp_rescued == s_py.n_hp_rescued
    assert open(f_cpp, "rb").read() == open(f_py, "rb").read()


def test_native_hp_likelihood_accept_parity(tmp_path):
    """The C++ likelihood-ratio acceptance (hp_loglik_c) is byte-identical
    to the python host pass end to end on an hp-damaged sim."""
    import os

    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    from daccord_tpu.formats.dazzdb import read_db
    from daccord_tpu.formats.las import LasFile
    from daccord_tpu.runtime.pipeline import (PipelineConfig, correct_to_fasta,
                                              estimate_profile_for_shard)
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path)
    out = make_dataset(d, SimConfig(genome_len=4000, coverage=18,
                                    read_len_mean=900, min_overlap=300,
                                    hp_indel_slope=1.0, seed=31), name="hpl")
    prof = estimate_profile_for_shard(read_db(out["db"]), LasFile(out["las"]),
                                      PipelineConfig())
    assert prof.hp_slope >= 0.1
    ccfg = ConsensusConfig(hp_rescue=True, hp_vote="posterior",
                           hp_accept="likelihood")
    f_cpp = os.path.join(d, "l_cpp.fasta")
    f_py = os.path.join(d, "l_py.fasta")
    s_cpp = correct_to_fasta(out["db"], out["las"], f_cpp,
                             PipelineConfig(batch_size=256, native_solver=True,
                                            consensus=ccfg, hp_native=True),
                             profile=prof)
    s_py = correct_to_fasta(out["db"], out["las"], f_py,
                            PipelineConfig(batch_size=256, native_solver=True,
                                           consensus=ccfg, hp_native=False),
                            profile=prof)
    assert s_cpp.n_hp_rescued > 0
    assert s_cpp.n_hp_rescued == s_py.n_hp_rescued
    assert open(f_cpp, "rb").read() == open(f_py, "rb").read()


@pytest.mark.slow   # two device-ladder runs -> ladder-shape XLA compiles
                    # (~130 s; was the whole fast tier's budget, VERDICT r4 #8)
def test_device_path_native_hp_parity(tmp_path):
    """The C++ hp pass wired into the DEVICE-ladder drain path (fetched
    strided results -> contiguous shim -> write-back) matches the python
    host loop byte-for-byte."""
    import os

    from daccord_tpu.native import available

    if not available():
        pytest.skip("native host path unavailable")
    from daccord_tpu.runtime.pipeline import PipelineConfig, correct_to_fasta
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path)
    out = make_dataset(d, SimConfig(genome_len=3000, coverage=16,
                                    read_len_mean=800, min_overlap=300,
                                    hp_indel_slope=1.0, seed=37), name="hpd")
    ccfg = ConsensusConfig(hp_rescue=True)
    f_cpp = os.path.join(d, "d_cpp.fasta")
    f_py = os.path.join(d, "d_py.fasta")
    s_cpp = correct_to_fasta(out["db"], out["las"], f_cpp,
                             PipelineConfig(batch_size=256, consensus=ccfg,
                                            hp_native=True))
    s_py = correct_to_fasta(out["db"], out["las"], f_py,
                            PipelineConfig(batch_size=256, consensus=ccfg,
                                           hp_native=False))
    assert s_cpp.n_hp_rescued > 0
    assert s_cpp.n_hp_rescued == s_py.n_hp_rescued
    assert open(f_cpp, "rb").read() == open(f_py, "rb").read()
