"""Test configuration: force JAX onto a virtual 8-device CPU platform.

This is the "fake backend" of SURVEY.md §4 item 4 — multi-chip sharding tests
run against 8 virtual CPU devices so no pod is needed. Must run before any
`import jax` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
