"""Test configuration: force JAX onto a virtual 8-device CPU platform.

This is the "fake backend" of SURVEY.md §4 item 4 — multi-chip sharding tests
run against 8 virtual CPU devices so no pod is needed.

Note: this image's axon TPU plugin pre-imports jax's config machinery at
interpreter startup, so setting JAX_PLATFORMS via os.environ here is too late;
``jax.config.update`` after import is the reliable override. XLA_FLAGS is
still read lazily at CPU backend init, so the device-count flag works from
here as long as no backend has been touched yet.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent compilation cache: the suite is dominated by XLA compiles of the
# ladder/kernel shapes, which are identical run to run
from daccord_tpu.utils.obs import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

# shadow-audit default (ISSUE 20): production default is 1/64, but on the
# CPU test tier the audit re-solves a sample of every supervised batch on
# the SAME host ladder — pure duplication that inflates the fast tier's
# wall. Off by default here; sdc/audit tests opt in with an explicit
# audit_rate (config beats env), so the plane itself stays covered.
os.environ.setdefault("DACCORD_AUDIT_RATE", "0")
