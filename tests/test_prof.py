"""Saturation profiler tests (ISSUE 14): StageProfile accounting, starvation
gauges + verdict rules, the pipeline's committed stage tables, the
feeder_stall A/B flip (byte-identical), daccord-prof render/check/diff, the
FEEDER_r* sidecar, and the sentinel's saturation drift rules.
"""

from __future__ import annotations

import json
import os

import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "obs")

try:
    from daccord_tpu.native import available as _nat_avail

    _HAVE_NATIVE = _nat_avail()
except Exception:
    _HAVE_NATIVE = False

needs_native = pytest.mark.skipif(not _HAVE_NATIVE,
                                  reason="native library unavailable")


# ---------------------------------------------------------------------------
# StageProfile + gauges + verdict units
# ---------------------------------------------------------------------------


def test_stage_profile_accounting():
    from daccord_tpu.utils.obs import StageProfile

    p = StageProfile(threads=2)
    p.add("decode", 0.5)
    p.add("decode", 0.25, calls=3)
    with p.timed("realign"):
        pass
    s = p.summary()
    assert s["threads"] == 2
    assert s["stages"]["decode"]["wall_s"] == 0.75
    assert s["stages"]["decode"]["calls"] == 4
    assert s["stages"]["realign"]["calls"] == 1
    assert p.dominant()[0] == "decode"
    assert p.total() >= 0.75


def test_stage_profile_thread_safety():
    import threading

    from daccord_tpu.utils.obs import StageProfile

    p = StageProfile()

    def work():
        for _ in range(2000):
            p.add("x", 0.001)

    ts = [threading.Thread(target=work) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert p.calls["x"] == 8000
    assert abs(p.walls["x"] - 8.0) < 1e-6


def test_saturation_gauges_and_verdict_rules():
    from daccord_tpu.utils.obs import bottleneck_verdict, saturation_gauges

    # host blocked most of the wall -> device-bound
    g = saturation_gauges(10.0, blocked_s=6.0, busy_s=9.0)
    assert g["host_blocked_frac"] == 0.6
    assert bottleneck_verdict(g)["verdict"] == "device"
    # device mostly idle, compute stage dominant -> host_feeder
    g = saturation_gauges(10.0, blocked_s=0.5, busy_s=2.0)
    assert g["device_idle_frac"] == 0.8
    stages = {"realign": {"wall_s": 5.0}, "decode": {"wall_s": 1.0}}
    v = bottleneck_verdict(g, stages)
    assert v["verdict"] == "host_feeder" and v["stage"] == "realign"
    # same starvation but decode-dominant -> io
    stages = {"realign": {"wall_s": 1.0}, "decode": {"wall_s": 5.0}}
    assert bottleneck_verdict(g, stages)["verdict"] == "io"
    # neither side saturated -> balanced; overlap accounts the rest
    g = saturation_gauges(10.0, blocked_s=2.0, busy_s=9.0)
    assert bottleneck_verdict(g)["verdict"] == "balanced"
    assert g["overlap_frac"] == 0.7


def test_render_prom_verdict_metric():
    from daccord_tpu.utils.obs import parse_prom, render_prom

    roll = {"counters": {}, "gauges": {"device_idle_frac": 0.3},
            "verdict": "host_feeder"}
    text = render_prom(roll, labels={"shard": 1})
    samples, errs = parse_prom(text)
    assert errs == []
    labels, val = samples["daccord_bottleneck_verdict"][0]
    assert 'verdict="host_feeder"' in labels and val == 1.0


def test_eventcheck_stage_profile_schema(tmp_path):
    from daccord_tpu.tools.eventcheck import validate_events

    good = tmp_path / "good.jsonl"
    good.write_text(
        '{"t": 0.0, "ts": 1.0, "event": "stage.profile", "stages": {}, '
        '"feeder_s": 0.5, "verdict": "balanced"}\n')
    assert validate_events(str(good), strict=True) == []
    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        '{"t": 0.0, "ts": 1.0, "event": "stage.profile", "stages": {}}\n')
    msgs = "\n".join(validate_events(str(bad), strict=True))
    assert "missing field 'verdict'" in msgs
    assert "missing field 'feeder_s'" in msgs


# ---------------------------------------------------------------------------
# pipeline integration: committed tables + the feeder_stall A/B (tentpole)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from daccord_tpu.formats import LasFile, read_db
    from daccord_tpu.runtime import PipelineConfig
    from daccord_tpu.runtime.pipeline import estimate_profile_for_shard
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("profcorpus"))
    out = make_dataset(d, SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="pf")
    db = read_db(out["db"])
    las = LasFile(out["las"])
    profile = estimate_profile_for_shard(db, las,
                                         PipelineConfig(batch_size=64))
    return {"db": db, "las": las, "profile": profile, "dir": d}


def _run(corpus, tmp_path, name, **kw):
    from daccord_tpu.runtime import PipelineConfig, correct_shard

    ev = str(tmp_path / f"{name}.events.jsonl")
    cfg = PipelineConfig(batch_size=64, events_path=ev, **kw)
    st = None
    res = []
    for rid, frags, s in correct_shard(corpus["db"], corpus["las"], cfg,
                                       profile=corpus["profile"]):
        st = s
        res.append((rid, [f.tobytes() for f in frags]))
    return res, st, ev


@needs_native
def test_pipeline_stamps_stage_profile_and_verdict(corpus, tmp_path):
    """A native run commits the full saturation record: shard_done carries
    stages/verdict/bottleneck/feeder_s/mesh, stage.profile snapshots land,
    the rollup carries the gauges + verdict, the prom rendering exposes the
    labeled verdict metric, and daccord-prof reconciles it all."""
    res, st, ev = _run(corpus, tmp_path, "base", native_solver=True)
    assert res and st is not None
    assert st.verdict in ("host_feeder", "device", "io", "balanced")
    assert st.stage_profile["stages"], "no feeder stages recorded"
    # native path: the fused C++ pile processor books under realign
    assert "realign" in st.stage_profile["stages"]
    assert st.bottleneck["device_idle_frac"] + \
        st.bottleneck["overlap_frac"] <= 1.0 + 1e-9
    g = st.metrics["gauges"]
    for k in ("device_idle_frac", "host_blocked_frac", "overlap_frac",
              "feeder_s"):
        assert k in g, k
    assert any(k.startswith("stage_") for k in g)
    assert st.metrics["verdict"] == st.verdict
    from daccord_tpu.utils.obs import parse_prom, render_prom

    samples, errs = parse_prom(render_prom(st.metrics))
    assert errs == [] and "daccord_bottleneck_verdict" in samples

    evs = [json.loads(x) for x in open(ev)]
    done = [e for e in evs if e["event"] == "shard_done"][-1]
    for k in ("stages", "verdict", "bottleneck", "feeder_s",
              "stage_threads", "mesh"):
        assert k in done, k
    assert done["mesh"] == 0
    assert [e for e in evs if e["event"] == "stage.profile"]
    from daccord_tpu.tools.eventcheck import validate_events

    assert validate_events(ev, strict=True) == []
    # daccord-prof: load, render, reconcile — the pounce gate must be green
    from daccord_tpu.tools.prof import (check_profile, load_profiles,
                                        prof_main, render_profile)

    profs, warns = load_profiles([ev])
    assert warns == [] and len(profs) == 1
    assert check_profile(profs[0]) == []
    assert "verdict" in render_profile(profs[0]).lower()
    assert prof_main(["--check", ev]) == 0


@needs_native
def test_feeder_stall_flips_verdict_bytes_identical(corpus, tmp_path,
                                                    monkeypatch):
    """The acceptance A/B: DACCORD_FAULT=feeder_stall:N slows every pile,
    flips the committed verdict to host_feeder with `stall` the named
    dominant sub-stage — and the FASTA bytes do not move."""
    base_res, base_st, _ = _run(corpus, tmp_path, "ab-base",
                                native_solver=True)
    monkeypatch.setenv("DACCORD_FAULT", "feeder_stall:40")
    stall_res, stall_st, ev = _run(corpus, tmp_path, "ab-stall",
                                   native_solver=True)
    assert stall_res == base_res, "injected stall changed bytes"
    assert stall_st.verdict == "host_feeder", stall_st.bottleneck
    assert stall_st.bottleneck["stage"] == "stall", stall_st.bottleneck
    assert stall_st.bottleneck["device_idle_frac"] > \
        base_st.bottleneck["device_idle_frac"]
    # the stall books as feeder time, so reconciliation still holds
    from daccord_tpu.tools.prof import check_profile, load_profiles

    profs, _ = load_profiles([ev])
    assert check_profile(profs[0]) == []
    # sentinel advisory: host_feeder on a mesh>=4 record flags, mesh 0 not
    from daccord_tpu.tools.sentinel import scan_events

    assert scan_events(ev) == []


@needs_native
def test_prof_diff_names_the_moved_stage(corpus, tmp_path, monkeypatch):
    _, _, ev_a = _run(corpus, tmp_path, "diff-a", native_solver=True)
    monkeypatch.setenv("DACCORD_FAULT", "feeder_stall:40")
    _, _, ev_b = _run(corpus, tmp_path, "diff-b", native_solver=True)
    monkeypatch.delenv("DACCORD_FAULT")
    from daccord_tpu.tools.prof import diff_profiles, load_profiles, prof_main

    profs, _ = load_profiles([ev_a, ev_b])
    lines = "\n".join(diff_profiles(profs[0], profs[1]))
    assert "stall" in lines and "new" in lines
    assert "verdict" in lines
    assert prof_main(["--diff", ev_a, ev_b]) == 0


def test_prof_check_flags_drifted_anchors(tmp_path):
    """A torn/dishonest record fails --check: stage sums exceeding host_s,
    feeder sub-stages disagreeing with feeder_s, missing verdict."""
    from daccord_tpu.tools.prof import check_profile

    bad = {"src": "x", "wall_s": 10.0, "host_s": 2.0, "device_s": 8.0,
           "feeder_s": 0.5, "threads": 1,
           "stages": {"decode": 4.0}, "verdict": None, "gauges": {}}
    msgs = "\n".join(check_profile(bad))
    assert "no bottleneck verdict" in msgs
    assert "does not reconcile with the blocked-on-feeder wall" in msgs
    assert "exceeds host_s" in msgs
    good = {"src": "x", "wall_s": 10.0, "host_s": 6.0, "device_s": 4.0,
            "feeder_s": 5.0, "threads": 1,
            "stages": {"decode": 2.0, "realign": 2.95, "pack": 0.5},
            "verdict": "balanced", "gauges": {}}
    assert check_profile(good) == []
    # anchors that do not add up flag too
    torn = dict(good, device_s=1.0)
    assert any("does not reconcile with wall_s" in m
               for m in check_profile(torn))


@needs_native
def test_prof_check_explicit_profile_less_file_fails(tmp_path):
    from daccord_tpu.tools.prof import prof_main

    p = tmp_path / "empty.events.jsonl"
    p.write_text('{"t": 0.0, "ts": 1.0, "event": "fleet.init", '
                 '"nshards": 1, "workers": 1, "host": "h"}\n')
    assert prof_main(["--check", str(p)]) == 1
    # swept from a directory, the same file is silently skipped
    assert prof_main(["--check", str(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# trace satellite: feeder bucket splits by the sub-stage table
# ---------------------------------------------------------------------------


@needs_native
def test_trace_decompose_splits_feeder(corpus, tmp_path, capsys):
    from daccord_tpu.tools.trace import decompose, trace_main

    _, _, ev = _run(corpus, tmp_path, "trace", native_solver=True)
    recs = [json.loads(x) for x in open(ev)]
    d = decompose(recs, "trace")
    assert d is not None and d["feeder_stages"], d
    assert d["verdict"] in ("host_feeder", "device", "io", "balanced")
    assert trace_main([ev, "--no-timeline"]) == 0
    err = capsys.readouterr().err
    assert "verdict:" in err
    # a feeder sub-stage line rendered under the feeder bucket
    assert "realign" in err


# ---------------------------------------------------------------------------
# feederbench satellite: durable FEEDER_r* sidecar
# ---------------------------------------------------------------------------


@needs_native
def test_feederbench_commits_sidecar(tmp_path, capsys):
    from daccord_tpu.tools.feederbench import main as fb_main

    rc = fb_main(["--threads", "0", "--genome", "1500", "--coverage", "6",
                  "--sidecar-dir", str(tmp_path)])
    assert rc == 0
    side = tmp_path / "FEEDER_r01.json"
    assert side.exists()
    payload = json.load(open(side))
    assert payload["n"] == 1 and "parsed" in payload
    parsed = payload["parsed"]
    assert parsed["metric"] == "feeder_windows_per_sec"
    assert parsed["stages"] and "realign" in parsed["stages"]
    assert "last_real_tpu_ts" in parsed
    # the r-series unwraps through the sentinel's loader, and prof reads it
    from daccord_tpu.tools.prof import load_profiles
    from daccord_tpu.tools.sentinel import load_bench

    assert load_bench(str(side))["metric"] == "feeder_windows_per_sec"
    profs, _ = load_profiles([str(side)])
    assert profs and profs[0]["stages"]
    # a second run appends r02, never overwrites
    assert fb_main(["--threads", "0", "--genome", "1500", "--coverage", "6",
                    "--sidecar-dir", str(tmp_path)]) == 0
    assert (tmp_path / "FEEDER_r02.json").exists()


# ---------------------------------------------------------------------------
# sentinel satellite: saturation drift + mesh>=4 host_feeder advisory
# ---------------------------------------------------------------------------


def test_sentinel_flags_rising_idle_and_stage_drift():
    from daccord_tpu.tools.sentinel import check_bench_series

    entries = [
        ("r1.json", {"metric": "m", "value": 100.0, "batch": 64,
                     "saturation": {"device_idle_frac": 0.1},
                     "stages": {"decode": 1.0, "realign": 8.0}}),
        ("r2.json", {"metric": "m", "value": 100.0, "batch": 64,
                     "saturation": {"device_idle_frac": 0.12},
                     "stages": {"decode": 1.1, "realign": 8.2}}),
    ]
    assert check_bench_series(entries, noise=0.15) == []
    entries.append(
        ("r3.json", {"metric": "m", "value": 100.0, "batch": 64,
                     "saturation": {"device_idle_frac": 0.55},
                     "stages": {"decode": 6.0, "realign": 3.0}}))
    issues = check_bench_series(entries, noise=0.15)
    joined = "\n".join(issues)
    assert "device_idle_frac" in joined and "newly starving" in joined
    assert "share" in joined and "drifted" in joined


def test_sentinel_mesh4_host_feeder_advisory(tmp_path):
    from daccord_tpu.tools.sentinel import check_bench_series, scan_events

    entries = [("m.json", {"metric": "multichip_windows_per_sec", "mesh": 8,
                           "batch": 64, "verdict": "host_feeder"})]
    issues = check_bench_series(entries, noise=0.15)
    assert any("host_feeder verdict on a mesh-8 run" in i for i in issues)
    # same rule over an events sidecar's shard_done
    ev = tmp_path / "m.events.jsonl"
    ev.write_text(
        '{"t": 1.0, "ts": 2.0, "event": "shard_done", "reads": 1, '
        '"windows": 2, "solved": 2, "wall_s": 1.0, "degraded": false, '
        '"verdict": "host_feeder", "mesh": 8}\n')
    assert any("mesh-8" in i for i in scan_events(str(ev)))
    # mesh < 4 (or non-mesh) does not flag
    ev2 = tmp_path / "s.events.jsonl"
    ev2.write_text(
        '{"t": 1.0, "ts": 2.0, "event": "shard_done", "reads": 1, '
        '"windows": 2, "solved": 2, "wall_s": 1.0, "degraded": false, '
        '"verdict": "host_feeder", "mesh": 0}\n')
    assert scan_events(str(ev2)) == []


def test_sentinel_baseline_idle_rise(tmp_path):
    from daccord_tpu.tools.sentinel import check_rollup

    cur = tmp_path / "a.metrics.json"
    cur.write_text(json.dumps({"counters": {}, "gauges": {
        "windows_per_sec": 100.0, "device_idle_frac": 0.6}}))
    baseline = {"counters": {}, "gauges": {"windows_per_sec": 100.0,
                                           "device_idle_frac": 0.1}}
    issues = check_rollup(str(cur), baseline, noise=0.15)
    assert any("above baseline" in i for i in issues)


# ---------------------------------------------------------------------------
# top satellite: saturation columns over the committed fixtures
# ---------------------------------------------------------------------------


def test_top_renders_saturation_columns():
    from daccord_tpu.tools.top import collect, render

    snap = collect([os.path.join(FIXTURES, "run"),
                    os.path.join(FIXTURES, "srv")])
    screen = render(snap)
    assert "IDLE%" in screen and "BLK%" in screen and "VERDICT" in screen
    # fixture gauges: 30% idle / 35% blocked / balanced verdict
    assert "30" in screen and "35" in screen and "balanced" in screen
    # mesh member idle column from the health map
    assert "MESH 4/8" in screen


def test_fixture_events_pass_new_schema():
    from daccord_tpu.tools.eventcheck import validate_events

    p = os.path.join(FIXTURES, "run", "shard0000.events.jsonl")
    assert validate_events(p, strict=True) == []


# ---------------------------------------------------------------------------
# serve plane: group saturation + service verdict in stats/prom
# ---------------------------------------------------------------------------


@needs_native
def test_serve_stats_carry_saturation_and_verdict(tmp_path):
    from daccord_tpu.serve import ConsensusService, ServeConfig
    from daccord_tpu.sim import SimConfig, make_dataset
    from daccord_tpu.utils.obs import parse_prom

    d = str(tmp_path / "corpus")
    out = make_dataset(d, SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="sv")
    svc = ConsensusService(ServeConfig(
        workdir=str(tmp_path / "srv"), backend="native",
        backend_explicit=True, batch=64, workers=1, flush_lag_s=0.02))
    try:
        j = svc.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
        svc.wait(j["job"], 300)
        st = svc.stats()
        assert st["verdict"] in ("host_feeder", "device", "io", "balanced")
        # per-group saturation rode the group stats
        grp = svc.warm.groups()[0]
        sat = grp.saturation()
        for k in ("device_idle_frac", "host_blocked_frac", "overlap_frac",
                  "busy_s", "blocked_s"):
            assert k in sat, k
        text = svc.stats_prom()
        samples, errs = parse_prom(text)
        assert errs == []
        assert "daccord_serve_bottleneck_verdict" in samples
    finally:
        svc.shutdown()
