"""Silent-data-corruption defense plane (ISSUE 20): fast units.

The plane's pieces, each anchored by a unit that runs in milliseconds:
the ``sdc:N[@K]`` fault grammar (silent by contract — no event at
injection), the shared digest helpers, the re-batching digest-stability
property (the window→batch→shard composition the integrity chain rests
on), the corruption/sampling/trust-ratchet mechanics on an inline
supervisor with a fake mesh, registry persistence + probation, the
eventcheck trust-transition lint, and the sentinel staleness advisory.
The e2e mesh detection arms live in test_mesh.py (shared corpus) and the
committed BENCH_SDC.json soak.
"""

import hashlib
import json
import os

import numpy as np
import pytest

from daccord_tpu.runtime.faults import FaultPlan
from daccord_tpu.runtime.supervisor import DeviceSupervisor


class _CapLog:
    """Minimal event sink with the JsonlLogger .log interface."""

    def __init__(self):
        self.records = []
        self._fh = None          # Tracer probes this; None = spans disabled

    def log(self, event, **kw):
        self.records.append({"event": event, **kw})

    def of(self, kind):
        return [r for r in self.records if r["event"] == kind]


class _FakeMesh:
    """The slice of the mesh surface the trust/audit units touch."""

    def __init__(self, nd=8):
        self.nd = nd
        self._members = list(range(nd))
        self.device_stats = {}
        self.shrunk = []

    def member_ids(self):
        return list(self._members)

    def shrink(self, culprit=-1):
        if self.nd <= 1:
            return False
        self.shrunk.append(culprit)
        self._members = [m for m in self._members if m != culprit]
        self.nd = len(self._members)
        return True


@pytest.fixture()
def isolated_registries(tmp_path, monkeypatch):
    """Trust strikes in these units must never land in the host's real
    registry (same doctrine as the governor/pounce smokes)."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    monkeypatch.delenv("DACCORD_FAULT", raising=False)
    monkeypatch.delenv("DACCORD_TRUST_PROBATION", raising=False)


def _sup(log=None, mesh=None, rate=1.0 / 64.0, factory=object):
    """Inline supervisor with the audit plane armed (the factory is never
    invoked by the units here — it only has to be non-None)."""
    return DeviceSupervisor(
        lambda b: b, lambda h: h, inline=True, log=log or _CapLog(),
        faults=FaultPlan.parse(""), mesh=mesh,
        audit_ref_factory=(factory if factory is not object else (lambda: None)),
        audit_rate=rate)


# ---------------------------------------------------------------------------
# fault grammar: silent by contract
# ---------------------------------------------------------------------------

def test_sdc_grammar_one_shot_and_pinned():
    p = FaultPlan.parse("sdc:3")
    assert p.has_sdc_faults()
    # fires exactly at the 3rd fetched result, unpinned (device -1)
    assert p.sdc_check() is None and p.sdc_check() is None
    s = p.sdc_check()
    assert s is not None and s.kind == "sdc" and s.device == -1
    assert p.sdc_check() is None          # one-shot: fired out
    assert not p.has_sdc_faults()

    p = FaultPlan.parse("sdc:1@2")
    s = p.sdc_check()
    assert s is not None and s.device == 2
    # the fired member joins the persistent liar set: attribution probes
    # keep seeing it lie even after the one-shot spent itself
    assert p.sdc_liars() == {2}
    assert p.has_sdc_faults()             # liar set keeps the gate open


def test_sdc_grammar_storm_never_fires_out():
    p = FaultPlan.parse("sdc:*@3")
    # continuous: every fetched result perturbs, and the member is a liar
    # even before the first hit (attribution must be deterministic)
    assert p.sdc_liars() == {3}
    for _ in range(5):
        s = p.sdc_check()
        assert s is not None and s.device == 3
    assert p.has_sdc_faults()


def test_sdc_grammar_rejects_bad_suffix():
    with pytest.raises(ValueError):
        FaultPlan.parse("device_oom:1@2")     # @device is sdc/device_lost only
    with pytest.raises(ValueError):
        FaultPlan.parse("sdc:1@banana")


# ---------------------------------------------------------------------------
# digest helpers: one implementation for manifest/merge/journal/audit
# ---------------------------------------------------------------------------

def test_sha256_file_streaming_and_limit(tmp_path):
    from daccord_tpu.utils.obs import sha256_file

    p = str(tmp_path / "blob")
    data = bytes(range(256)) * 5000            # > one 1 MiB chunk
    with open(p, "wb") as fh:
        fh.write(data)
    assert sha256_file(p) == hashlib.sha256(data).hexdigest()
    # limit digests exactly the fsync'd prefix the journal recorded
    assert sha256_file(p, limit=1000) == \
        hashlib.sha256(data[:1000]).hexdigest()


def test_result_digest_excludes_routing_fields():
    from daccord_tpu.utils.obs import result_digest

    out = {"cons": np.array([[0, 1, 2, 4], [3, 3, 0, 4]], dtype=np.int8),
           "cons_len": np.array([3, 2], dtype=np.int32),
           "solved": np.array([True, True]),
           "err": np.array([0.1, 0.2], dtype=np.float32),
           "tier": np.array([0, 1], dtype=np.int32)}
    d0 = result_digest(out)
    # err/tier steer routing, never output bytes: digest must not move
    out2 = dict(out, err=out["err"] * 7, tier=out["tier"] + 1)
    assert result_digest(out2) == d0
    # live consensus bytes DO move it
    out3 = dict(out, cons=out["cons"].copy())
    out3["cons"][0, 0] = 2
    assert result_digest(out3) != d0
    # beyond-cons_len padding is excluded
    out4 = dict(out, cons=out["cons"].copy())
    out4["cons"][0, 3] = 1
    assert result_digest(out4) == d0
    # row subset: the shadow audit digests its sample
    assert result_digest(out, rows=[0]) != result_digest(out, rows=[1])


def test_rebatch_round_trips_digest_stable():
    """pack_paged/unpack_paged/to_dense/slice_batch preserve every window's
    content digest — the property that makes the integrity chain's
    window→batch→shard composition sound without re-hashing at every hop."""
    from daccord_tpu.kernels import paging
    from daccord_tpu.kernels.tensorize import (BatchShape, pad_batch,
                                               slice_batch, tensorize_windows)
    from daccord_tpu.oracle.windows import WindowSegments
    from daccord_tpu.utils.obs import batch_digest, row_digests

    for seed in (0, 1, 2):
        rng = np.random.default_rng(seed)
        shape = BatchShape(depth=8, seg_len=64, wlen=40)
        items = []
        for i in range(23):
            nseg = int(rng.integers(0, 9))
            segs = [rng.integers(0, 4, size=int(rng.integers(0, 65)))
                    .astype(np.int8) for _ in range(nseg)]
            items.append((i, WindowSegments(wstart=i * 10, wlen=40,
                                            segments=segs,
                                            breads=[0] * nseg)))
        dense = tensorize_windows(items, shape)
        digests = row_digests(dense)
        whole = batch_digest(dense)
        assert len(digests) == dense.size

        pg = paging.window_pages(dense.lens)
        fam = paging.ShapeFamily(
            depth=8, pages=1 << (max(int(pg.max(initial=1)), 1) - 1)
            .bit_length())
        pb = paging.pack_paged(dense, fam)
        # paged batches digest through their dense view: identical rows
        assert row_digests(pb) == digests
        assert batch_digest(paging.unpack_paged(pb)) == whole
        assert batch_digest(pb.to_dense()) == whole
        # row slices carry exactly their windows' digests
        for lo, hi in ((0, 7), (5, 23), (11, 12)):
            assert row_digests(slice_batch(dense, lo, hi)) == digests[lo:hi]
        # padding appends rows, never rewrites the live prefix
        padded = pad_batch(dense, dense.size + 9)
        assert row_digests(padded)[: dense.size] == digests


# ---------------------------------------------------------------------------
# corruption + sampling mechanics (inline supervisor, no XLA)
# ---------------------------------------------------------------------------

def test_corrupt_rows_touches_only_live_solved_bases(isolated_registries):
    out = {"cons": np.array([[0, 1, 2, 4, 4],
                             [3, 0, 4, 4, 4],
                             [1, 1, 1, 1, 1]], dtype=np.int8),
           "cons_len": np.array([3, 2, 0], dtype=np.int32),
           "solved": np.array([True, False, True])}
    before = out["cons"].copy()
    DeviceSupervisor._corrupt_rows(out, [0, 1, 2])
    # row 0: solved, live bases bumped +1 mod 4 — still a valid alphabet
    np.testing.assert_array_equal(out["cons"][0], [1, 2, 3, 4, 4])
    # row 1 unsolved and row 2 zero-length: untouched
    np.testing.assert_array_equal(out["cons"][1], before[1])
    np.testing.assert_array_equal(out["cons"][2], before[2])


def test_audit_sample_covers_every_member_when_budget_allows(
        isolated_registries):
    sup = _sup(mesh=_FakeMesh(8), rate=1.0 / 64.0)
    B, nd = 512, 8
    per = -(-B // nd)
    rows = sup._audit_sample(B)
    # k = 512/64 = 8 = nd: one row in EVERY member slice, every batch —
    # a lying member cannot hide in the unsampled rows
    assert len(rows) == 8
    assert {r // per for r in rows} == set(range(nd))
    # deterministic for a fixed (seed, ordinal)
    assert rows == sup._audit_sample(B)


def test_audit_sample_rotates_member_slices_under_budget(
        isolated_registries):
    sup = _sup(mesh=_FakeMesh(8), rate=1.0 / 64.0)
    B, nd = 64, 8
    per = -(-B // nd)
    hit = set()
    for _ in range(8):
        rows = sup._audit_sample(B)
        assert len(rows) == 1            # k = 1: budget, not blanket
        hit.add(rows[0] // per)
        sup._n_audit += 1
    # the rotation walks every member slice across 8 audited batches
    assert hit == set(range(nd))


# ---------------------------------------------------------------------------
# trust ratchet + registry
# ---------------------------------------------------------------------------

def test_trust_ratchet_strikes_to_quarantine_and_persists(
        isolated_registries, monkeypatch):
    from daccord_tpu.utils.obs import trust_registry

    monkeypatch.setenv("DACCORD_TRUST_STRIKES", "2")
    log = _CapLog()
    sup = _sup(log=log)                  # no mesh, no fallback: pure ratchet
    sup._trust_strike(3, "unit")
    sup._trust_strike(3, "unit")
    sup._trust_strike(3, "unit")         # quarantine is sticky
    states = [(r["state_from"], r["state_to"]) for r in log.of("trust.state")]
    assert states == [("TRUSTED", "SUSPECT"),
                      ("SUSPECT", "QUARANTINED"),
                      ("QUARANTINED", "QUARANTINED")]
    reg = trust_registry()
    assert reg["m3"]["state"] == "QUARANTINED" and reg["m3"]["strikes"] == 3


def test_trust_registry_load_shrinks_quarantined_member(
        isolated_registries):
    from daccord_tpu.utils.obs import TRUST_QUARANTINED, record_trust

    record_trust("m5", TRUST_QUARANTINED, 2)
    log = _CapLog()
    mesh = _FakeMesh(8)
    _sup(log=log, mesh=mesh)             # _trust_load runs at construction
    # the member is out before it solves a single window
    assert 5 not in mesh.member_ids() and mesh.shrunk == [5]
    assert log.of("trust.load")[0] == {"event": "trust.load", "device": 5,
                                       "state": "QUARANTINED", "strikes": 2}
    shr = log.of("mesh.shrink")
    assert shr and shr[0]["culprit"] == 5 \
        and shr[0]["reason"] == "trust quarantined (registry)"


def test_trust_probation_demotes_to_suspect(isolated_registries,
                                            monkeypatch):
    from daccord_tpu.utils.obs import (TRUST_QUARANTINED, record_trust,
                                       trust_registry)

    record_trust("m5", TRUST_QUARANTINED, 2)
    monkeypatch.setenv("DACCORD_TRUST_PROBATION", "1")
    log = _CapLog()
    mesh = _FakeMesh(8)
    _sup(log=log, mesh=mesh)
    # probation: the member stays IN, demoted to SUSPECT one strike from
    # re-quarantine — the governor's probation lever, mirrored
    assert 5 in mesh.member_ids() and mesh.shrunk == []
    demote = log.of("trust.state")
    assert demote and demote[0]["state_from"] == "QUARANTINED" \
        and demote[0]["state_to"] == "SUSPECT" and demote[0]["strikes"] == 1
    assert trust_registry()["m5"]["state"] == "SUSPECT"


# ---------------------------------------------------------------------------
# eventcheck: trust transition lint
# ---------------------------------------------------------------------------

def test_eventcheck_trust_transition_lint(tmp_path):
    from daccord_tpu.tools.eventcheck import validate_events

    ok = tmp_path / "ok.jsonl"
    ok.write_text("".join(json.dumps(r) + "\n" for r in [
        {"event": "trust.state", "t": 0.1, "ts": 1.0, "device": 3,
         "state_from": "TRUSTED", "state_to": "SUSPECT", "strikes": 1},
        {"event": "trust.state", "t": 0.2, "ts": 1.1, "device": 3,
         "state_from": "SUSPECT", "state_to": "QUARANTINED", "strikes": 2},
        # probation demotion: the ONE legal loosening edge
        {"event": "trust.state", "t": 0.3, "ts": 1.2, "device": 3,
         "state_from": "QUARANTINED", "state_to": "SUSPECT", "strikes": 1},
    ]))
    assert validate_events(str(ok), strict=True) == []
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(
        {"event": "trust.state", "t": 0.1, "ts": 1.0, "device": 3,
         "state_from": "SUSPECT", "state_to": "TRUSTED", "strikes": 0})
        + "\n")
    errs = validate_events(str(bad), strict=True)
    assert errs and "illegal trust transition" in errs[0]


# ---------------------------------------------------------------------------
# sentinel: trajectory staleness advisory (ISSUE 20 satellite)
# ---------------------------------------------------------------------------

def test_sentinel_flags_stale_tpu_provenance():
    from daccord_tpu.tools.sentinel import check_bench_series

    fresh = [("A.json", {"metric": "x", "last_real_tpu_age_h": 102.0})]
    stale = [("B.json", {"metric": "x", "last_real_tpu_age_h": 300.5})]
    assert not [i for i in check_bench_series(fresh) if "life sign" in i]
    hits = [i for i in check_bench_series(stale) if "life sign" in i]
    assert hits and "300.5" in hits[0]
    # threshold is a lever; 0 disables
    assert [i for i in check_bench_series(fresh, tpu_stale_h=50.0)
            if "life sign" in i]
    assert not [i for i in check_bench_series(stale, tpu_stale_h=0)
                if "life sign" in i]
