"""End-to-end pipeline test: synthetic dataset -> corrected FASTA -> Q uplift."""

import os

import numpy as np
import pytest

from daccord_tpu.formats import read_fasta
from daccord_tpu.oracle import edit_distance, infix_distance
from daccord_tpu.runtime import PipelineConfig, correct_to_fasta
from daccord_tpu.sim import SimConfig, make_dataset
from daccord_tpu.utils import revcomp_ints, seq_to_ints

# XLA-compile-heavy e2e tier: excluded from `pytest -m 'not slow'` (fast tier)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("e2e"))
    cfg = SimConfig(genome_len=2000, coverage=15, read_len_mean=600, min_overlap=250, seed=13)
    return make_dataset(d, cfg, name="p"), d




def _fasta_err_rate(fasta: str, res) -> float:
    """Error rate of corrected fragments vs sim truth (shared by the e2e
    quality tests — one copy of the rid-parse/strand-flip/align loop)."""
    tot_e = tot_l = 0
    for rec in read_fasta(fasta):
        rid = int(rec.name[4:].split("/")[0])
        r = res.reads[rid]
        truth = res.genome[r.start : r.end]
        if r.strand == 1:
            truth = revcomp_ints(truth)
        f = seq_to_ints(rec.seq)
        tot_e += infix_distance(f, truth)
        tot_l += len(f)
    return tot_e / max(tot_l, 1)

def test_pipeline_end_to_end(dataset):
    out, d = dataset
    res = out["result"]
    fasta = os.path.join(d, "corr.fasta")
    stats = correct_to_fasta(out["db"], out["las"], fasta, PipelineConfig(batch_size=256))
    piled = {o.aread for o in res.overlaps}
    assert stats.n_reads == len(piled)
    assert stats.n_solved / stats.n_windows > 0.9
    assert stats.bases_out > 0.75 * stats.bases_in

    corr_err = _fasta_err_rate(fasta, res)

    raw_e = raw_l = 0
    for r in res.reads[:8]:
        truth = res.genome[r.start : r.end]
        if r.strand == 1:
            truth = revcomp_ints(truth)
        raw_e += edit_distance(r.seq, truth)
        raw_l += len(truth)
    raw_err = raw_e / raw_l
    assert corr_err < raw_err / 8, (corr_err, raw_err)


def test_threaded_feeder_is_deterministic(dataset):
    """feeder_threads>0 must produce byte-identical FASTA to the synchronous
    path (in-order prefetch; only wall-clock may differ)."""
    from daccord_tpu.native import available as native_available

    if not native_available():
        pytest.skip("native host path unavailable")
    out, d = dataset
    f_sync = os.path.join(d, "sync.fasta")
    f_thr = os.path.join(d, "thr.fasta")
    correct_to_fasta(out["db"], out["las"], f_sync, PipelineConfig(batch_size=256))
    correct_to_fasta(out["db"], out["las"], f_thr,
                     PipelineConfig(batch_size=256, feeder_threads=4))
    assert open(f_sync).read() == open(f_thr).read()


def test_depth_buckets_match_single_bucket(dataset):
    """Routing windows to depth buckets must not change any consensus byte:
    trailing all-PAD segment rows are mathematically inert in the kernel."""
    out, d = dataset
    f_one = os.path.join(d, "b1.fasta")
    f_bkt = os.path.join(d, "b3.fasta")
    correct_to_fasta(out["db"], out["las"], f_one,
                     PipelineConfig(batch_size=256, depth_buckets=()))
    correct_to_fasta(out["db"], out["las"], f_bkt,
                     PipelineConfig(batch_size=256, depth_buckets=(8, 16),
                                    bucket_flush_reads=4))  # exercise partial flush
    assert open(f_one).read() == open(f_bkt).read()
    # second-level seg-len bucketing is exact too (narrower trailing PAD
    # columns are inert in the kernel)
    f_lb = os.path.join(d, "lb.fasta")
    correct_to_fasta(out["db"], out["las"], f_lb,
                     PipelineConfig(batch_size=256, depth_buckets=(16,),
                                    seg_len_buckets=(48,)))
    assert open(f_one).read() == open(f_lb).read()


def test_pipeline_byte_range_shard(dataset):
    """Correcting a byte-range shard touches only that shard's reads."""
    out, d = dataset
    from daccord_tpu.formats import LasFile, read_db
    from daccord_tpu.formats.las import shard_ranges
    from daccord_tpu.runtime import correct_shard

    db = read_db(out["db"])
    las = LasFile(out["las"])
    ranges = shard_ranges(out["las"], 2)
    rids0 = [rid for rid, _, _ in correct_shard(db, las, PipelineConfig(batch_size=256),
                                                start=ranges[0][0], end=ranges[0][1])]
    rids1 = [rid for rid, _, _ in correct_shard(db, las, PipelineConfig(batch_size=256),
                                                start=ranges[1][0], end=ranges[1][1])]
    assert set(rids0).isdisjoint(rids1)
    all_areads = sorted({o.aread for o in out["result"].overlaps})
    assert sorted(rids0 + rids1) == all_areads


def test_ont_preset_end_to_end(tmp_path):
    """ONT R10-like regime (long reads, low deletion-leaning error): the
    pipeline must still deliver a strong Q uplift — the window unit makes
    read length a non-axis (SURVEY.md §2.3 SP row), only window count grows."""
    from daccord_tpu.sim import SimConfig, make_dataset
    from daccord_tpu.tools.cli import qveval_main

    cfg = SimConfig.ont_r10(genome_len=9000, coverage=10, read_len_mean=3000,
                            min_overlap=800, seed=51)
    assert cfg.p_del > cfg.p_ins  # deletion-leaning, unlike the PacBio default
    out = make_dataset(str(tmp_path), cfg, name="ont")
    fasta = str(tmp_path / "ont.corr.fasta")
    stats = correct_to_fasta(out["db"], out["las"], fasta, PipelineConfig(batch_size=256))
    assert stats.n_solved / stats.n_windows > 0.9

    import json as _json
    jout = str(tmp_path / "q.json")
    assert qveval_main([fasta, out["truth"], "--raw-db", out["db"], "--json", jout]) == 0
    line = _json.loads(open(jout).read())
    assert line["qscore"] > line["raw_qscore"] + 6, line


def test_trim_rescue_ends_unit():
    """Prefix/suffix rescue-tier runs are nulled; interior ones and confident
    tiers survive; unsolved gaps are scanned over."""
    from daccord_tpu.runtime.pipeline import PipelineStats, _PendingRead, _trim_rescue_ends

    def mk(tiers_seq):
        pr = _PendingRead(0, np.zeros(100, np.int8), len(tiers_seq))
        st = PipelineStats()
        for j, t in enumerate(tiers_seq):
            seq = None if t is None else np.zeros(40, np.int8)
            pr.results[j] = (j * 10, 40, seq)
            if t is not None:
                pr.tiers[j] = t
                st.n_solved += 1
                st.tier_histogram[t] = st.tier_histogram.get(t, 0) + 1
        return pr, st

    pr, st = mk([3, 0, 3, 1, 3, 3])
    _trim_rescue_ends(pr, {3}, st)
    kept = [pr.results[j][2] is not None for j in range(6)]
    assert kept == [False, True, True, True, False, False]
    assert st.n_end_trimmed == 3 and st.tier_histogram[3] == 1

    # unsolved gaps do not stop the sweep
    pr, st = mk([3, None, 3, 0])
    _trim_rescue_ends(pr, {3}, st)
    assert [pr.results[j][2] is not None for j in range(4)] == [False, False, False, True]
    assert st.n_end_trimmed == 2

    # an all-rescue read trims away entirely
    pr, st = mk([3, 3])
    _trim_rescue_ends(pr, {3}, st)
    assert st.n_end_trimmed == 2 and st.n_solved == 0


def test_end_trim_pipeline(dataset):
    """end_trim drops low-confidence end windows: fewer output bases, solved
    count reduced by exactly the trimmed count, and no fragmentation blow-up."""
    out, d = dataset
    f_on = os.path.join(d, "trim_on.fasta")
    f_off = os.path.join(d, "trim_off.fasta")
    s_on = correct_to_fasta(out["db"], out["las"], f_on,
                            PipelineConfig(batch_size=256, end_trim=True))
    s_off = correct_to_fasta(out["db"], out["las"], f_off,
                             PipelineConfig(batch_size=256, end_trim=False))
    assert s_off.n_end_trimmed == 0
    assert s_on.n_end_trimmed > 0
    assert s_on.n_solved == s_off.n_solved - s_on.n_end_trimmed
    assert s_on.bases_out < s_off.bases_out
    assert s_on.n_fragments <= s_off.n_fragments + s_on.n_end_trimmed

    # patch mode refills unsolved windows with raw bases, which would be
    # strictly worse than the rescue consensus — end_trim must not engage
    from daccord_tpu.oracle.consensus import ConsensusConfig

    s_patch = correct_to_fasta(out["db"], out["las"], os.path.join(d, "patch.fasta"),
                               PipelineConfig(batch_size=256, end_trim=True,
                                              consensus=ConsensusConfig(mode="patch")))
    assert s_patch.n_end_trimmed == 0


def test_skip_shallow_is_exact(dataset):
    """Host-side skip of sub-min_depth windows must be byte-identical to
    letting the kernel mark them unsolved (window_kernel.py:389) — it only
    saves device batch slots."""
    out, d = dataset
    f_on = os.path.join(d, "skip_on.fasta")
    f_off = os.path.join(d, "skip_off.fasta")
    s_on = correct_to_fasta(out["db"], out["las"], f_on,
                            PipelineConfig(batch_size=256, skip_shallow=True))
    s_off = correct_to_fasta(out["db"], out["las"], f_off,
                             PipelineConfig(batch_size=256, skip_shallow=False))
    assert open(f_on).read() == open(f_off).read()
    assert s_off.n_skipped_shallow == 0
    assert s_on.n_skipped_shallow > 0   # thin read ends exist at 15x
    assert s_on.n_solved == s_off.n_solved


def test_qv_ranker_unit():
    """B-interval QV averaging: tile selection, NOCOV exclusion, complement
    coordinate flip, and the median fill for unknown-quality overlaps."""
    from types import SimpleNamespace

    from daccord_tpu.runtime.pipeline import QvRanker, _rank_scores
    from daccord_tpu.tools.lastools import QV_NOCOV, QV_SCALE

    tspace = 100
    # read 0: tiles [40, 80, NOCOV], len 250
    payloads = [np.asarray([40, 80, QV_NOCOV], dtype=np.uint8)]
    db = SimpleNamespace(read_length=lambda r: 250)
    qvr = QvRanker(payloads, tspace, db)
    # forward, tiles 0-1
    assert qvr.rate(0, 0, 200, False) == pytest.approx(60 / QV_SCALE)
    # forward, tile 1 only
    assert qvr.rate(0, 150, 180, False) == pytest.approx(80 / QV_SCALE)
    # NOCOV-only interval -> NaN
    assert np.isnan(qvr.rate(0, 210, 240, False))
    # complement: comp range [0, 100) maps to forward [150, 250) = tiles 1-2;
    # tile 2 is NOCOV so only tile 1 counts
    assert qvr.rate(0, 0, 100, True) == pytest.approx(80 / QV_SCALE)
    # unknown read -> NaN
    assert np.isnan(qvr.rate(7, 0, 100, False))

    # median fill: NaN entries rank neutral, not best
    from daccord_tpu.runtime.pipeline import QV_RANK_WEIGHT

    diffs = np.asarray([10, 10, 10])
    spans = np.asarray([100, 100, 100])
    bq = np.asarray([0.1, np.nan, 0.4])
    s = _rank_scores(diffs, spans, bq)
    assert s[0] < s[1] < s[2]
    # NaN takes the median of known rates, scaled by the ranking weight
    assert s[1] == pytest.approx(0.1 + QV_RANK_WEIGHT * 0.25)


def test_qv_ranked_pipeline_native_parity(dataset):
    """With an inqual track present, the QV-augmented depth ranking must
    produce byte-identical FASTA through the native and oracle host paths
    (one _rank_scores, two feeders)."""
    from daccord_tpu.formats import LasFile, read_db
    from daccord_tpu.tools.lastools import compute_intrinsic_qv

    out, d = dataset
    compute_intrinsic_qv(read_db(out["db"]), LasFile(out["las"]), depth=15)
    f_nat = os.path.join(d, "qv_nat.fasta")
    f_orc = os.path.join(d, "qv_orc.fasta")
    s_nat = correct_to_fasta(out["db"], out["las"], f_nat,
                             PipelineConfig(batch_size=256, use_native=True))
    s_orc = correct_to_fasta(out["db"], out["las"], f_orc,
                             PipelineConfig(batch_size=256, use_native=False))
    assert s_nat.qv_ranked and s_orc.qv_ranked
    assert open(f_nat).read() == open(f_orc).read()

    # disabled track -> ranking reverts to trace-diff only, still works
    f_off = os.path.join(d, "qv_off.fasta")
    s_off = correct_to_fasta(out["db"], out["las"], f_off,
                             PipelineConfig(batch_size=256, qv_track=None))
    assert not s_off.qv_ranked
    assert s_off.n_solved > 0


def test_depth_cap_excludes_cross_copy_segments():
    """In-pile repeat handling: when a repeat-inflated pile is deeper than
    the depth cap, quality-ranked capping (trace-diff rate, which carries
    the copies' divergence) fills the slots predominantly with same-copy
    alignments — the windows never see most cross-copy segments."""
    from daccord_tpu.sim import SimConfig, simulate

    cfg = SimConfig(genome_len=6000, coverage=24, read_len_mean=800,
                    repeat_fraction=0.35, repeat_divergence=0.08, seed=43)
    res = simulate(cfg)
    reads = res.reads

    def is_cross(o):
        a, b = reads[o.aread], reads[o.bread]
        return min(a.end, b.end) <= max(a.start, b.start)

    # the read with the most cross-copy overlaps = deepest repeat pile
    from collections import Counter

    cross_per_read = Counter(o.aread for o in res.overlaps if is_cross(o))
    aread = cross_per_read.most_common(1)[0][0]
    pile = [o for o in res.overlaps if o.aread == aread]
    n_cross = sum(1 for o in pile if is_cross(o))
    D = 16
    assert len(pile) > D and n_cross >= D // 2   # cap binds, repeat is real

    diffs = np.asarray([o.diffs for o in pile])
    spans = np.maximum(np.asarray([o.aepos - o.abpos for o in pile]), 1)
    from daccord_tpu.runtime.pipeline import _rank_scores

    order = np.argsort(_rank_scores(diffs, spans, None), kind="stable")
    top = [pile[i] for i in order[:D]]
    frac_cross_pile = n_cross / len(pile)
    frac_cross_top = sum(1 for o in top if is_cross(o)) / D
    # capping must at least halve the cross-copy fraction vs the raw pile
    assert frac_cross_top <= 0.5 * frac_cross_pile, \
        (frac_cross_top, frac_cross_pile)


def test_native_solver_end_to_end(dataset):
    """--backend native (C++ tier ladder as the window solver): corrects end
    to end at quality matching the device/JAX path. -M 0 gives full-graph
    oracle semantics (zero truncation by construction); the default cap
    mirrors the device ladder and flags its truncations."""
    native = pytest.importorskip("daccord_tpu.native")
    if not native.available():
        pytest.skip("native library unavailable")
    out, d = dataset
    res = out["result"]
    fasta = os.path.join(d, "corr_nat.fasta")
    stats = correct_to_fasta(out["db"], out["las"], fasta,
                             PipelineConfig(batch_size=256, native_solver=True,
                                            max_kmers=0))
    assert stats.n_solved / stats.n_windows > 0.9
    assert stats.n_topm_overflow == 0   # full graph: nothing truncated

    tot_e = tot_l = 0
    for rec in read_fasta(fasta):
        rid = int(rec.name[4:].split("/")[0])
        r = res.reads[rid]
        truth = res.genome[r.start : r.end]
        if r.strand == 1:
            truth = revcomp_ints(truth)
        f = seq_to_ints(rec.seq)
        tot_e += infix_distance(f, truth)
        tot_l += len(f)
    assert tot_e / tot_l < 0.02, tot_e / tot_l


def test_native_vs_jax_ladder_consistency(dataset):
    """Cross-engine guard: the native C++ ladder and the JAX host-routed
    ladder at identical config (same caps, same tables) must agree on
    essentially every window — they implement one spec, differing only in
    f32 accumulation order. Catches silent semantic drift between engines."""
    native = pytest.importorskip("daccord_tpu.native")
    if not native.available():
        pytest.skip("native library unavailable")
    out, d = dataset
    fa_nat = os.path.join(d, "xeng_nat.fasta")
    fa_jax = os.path.join(d, "xeng_jax.fasta")
    s_nat = correct_to_fasta(out["db"], out["las"], fa_nat,
                             PipelineConfig(batch_size=256, native_solver=True))
    s_jax = correct_to_fasta(out["db"], out["las"], fa_jax,
                             PipelineConfig(batch_size=256))
    assert s_nat.n_windows == s_jax.n_windows
    # solve decisions may flip only on float near-ties
    assert abs(s_nat.n_solved - s_jax.n_solved) <= max(2, s_jax.n_windows // 200), (
        s_nat.n_solved, s_jax.n_solved)
    # and the corrected output quality must be indistinguishable
    e_nat = _fasta_err_rate(fa_nat, out["result"])
    e_jax = _fasta_err_rate(fa_jax, out["result"])
    assert abs(e_nat - e_jax) < 2e-3, (e_nat, e_jax)


def test_hp_rescue_pipeline_end_to_end(tmp_path):
    """--hp-rescue through the full pipeline on an hp-sloped sim: rescues
    windows, lifts quality, and never regresses the direct result (the
    acceptance gate requires the expanded candidate to beat it)."""
    native = pytest.importorskip("daccord_tpu.native")
    if not native.available():
        pytest.skip("native library unavailable")
    from daccord_tpu.oracle.consensus import ConsensusConfig

    d = str(tmp_path)
    cfg = SimConfig(genome_len=4000, coverage=18, read_len_mean=900,
                    min_overlap=300, hp_indel_slope=1.0, seed=31)
    out = make_dataset(d, cfg, name="hp")
    res = out["result"]

    base_cfg = PipelineConfig(batch_size=256, native_solver=True)
    hp_cfg = PipelineConfig(batch_size=256, native_solver=True,
                            consensus=ConsensusConfig(hp_rescue=True))
    f_off = os.path.join(d, "hp_off.fasta")
    f_on = os.path.join(d, "hp_on.fasta")
    correct_to_fasta(out["db"], out["las"], f_off, base_cfg)
    stats = correct_to_fasta(out["db"], out["las"], f_on, hp_cfg)
    assert stats.n_hp_rescued > 0
    e_off = _fasta_err_rate(f_off, res)
    e_on = _fasta_err_rate(f_on, res)
    assert e_on < e_off, (e_on, e_off)
