"""Telemetry spine (ISSUE 6): absolute-ts buffered logging, trace spans,
metrics registry, per-window outcome ledger, and the daccord-trace
merge/lint/decomposition tool.

The invariants under test are the ones the spine sells: telemetry-on vs
telemetry-off FASTA is byte-identical under the fault matrix, every
span_open has a span_close even on abort/failover unwind paths, ledger rows
equal the run's window count, and daccord-trace's per-stage wall
decomposition reconciles with ``stats.device_s``/``host_s``.
"""

import json
import os

import pytest

from daccord_tpu.runtime.pipeline import PipelineConfig, correct_to_fasta
from daccord_tpu.sim import SimConfig, make_dataset
from daccord_tpu.tools.eventcheck import validate_events
from daccord_tpu.tools import trace as trace_mod
from daccord_tpu.utils.obs import (
    DURABLE_EVENTS,
    JsonlLogger,
    MetricsRegistry,
    Tracer,
    WindowLedger,
)

pytestmark = pytest.mark.skipif(
    not pytest.importorskip("daccord_tpu.native").available(),
    reason="native engine required (telemetry hot-path tests run on it)")


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tracedata"))
    return make_dataset(d, SimConfig(genome_len=1200, coverage=10,
                                     read_len_mean=400, min_overlap=150,
                                     seed=7), name="tr")


def _events(path):
    return [json.loads(ln) for ln in open(path) if ln.strip()]


# ---------------------------------------------------------------------------
# JsonlLogger: absolute ts + buffered mode (satellites 1 + 2)
# ---------------------------------------------------------------------------

def test_logger_ts_and_relative_t(tmp_path):
    import time

    p = str(tmp_path / "ev.jsonl")
    before = time.time()
    with JsonlLogger(p) as log:
        log.log("batch", windows=3, solved=2)
    rec = _events(p)[0]
    # t stays (human-scale within-run deltas); ts is the cross-process
    # merge key — an absolute epoch stamp
    assert 0.0 <= rec["t"] < 5.0
    assert before - 1 <= rec["ts"] <= time.time() + 1


def test_logger_buffered_mode(tmp_path):
    p = str(tmp_path / "buf.jsonl")
    log = JsonlLogger(p, buffer_lines=100, flush_s=0.0)
    for i in range(5):
        log.log("batch", windows=i, solved=0)
    # nothing hits the disk until a flush condition
    assert open(p).read() == ""
    # durable events flush through immediately — WITH the buffered tail
    # ahead of them (ordering preserved)
    log.log("sup_fault", kind="device_lost", op="dispatch", n=1)
    assert "sup_fault" in DURABLE_EVENTS
    recs = _events(p)
    assert len(recs) == 6 and recs[-1]["event"] == "sup_fault"
    # close flushes the remaining tail
    log.log("batch", windows=9, solved=9)
    log.close()
    assert _events(p)[-1]["windows"] == 9


def test_logger_flush_interval(tmp_path):
    import time

    p = str(tmp_path / "cadence.jsonl")
    log = JsonlLogger(p, buffer_lines=10_000, flush_s=0.05)
    log.log("batch", windows=1, solved=0)
    assert open(p).read() == ""
    time.sleep(0.06)
    log.log("batch", windows=2, solved=0)   # cadence bound fires here
    assert len(_events(p)) == 2
    log.close()


# ---------------------------------------------------------------------------
# Tracer: pairing, nesting, abort unwind
# ---------------------------------------------------------------------------

def test_tracer_nesting_and_pairing(tmp_path):
    p = str(tmp_path / "spans.jsonl")
    log = JsonlLogger(p)
    tr = Tracer(log)
    run = tr.open("run")
    pile = tr.open("pile", aread=3)
    with tr.span("dispatch"):
        pass
    tr.close(pile)
    tr.close(run)
    log.close()
    recs = _events(p)
    opens = {r["span"]: r for r in recs if r["event"] == "span_open"}
    closes = {r["span"] for r in recs if r["event"] == "span_close"}
    assert set(opens) == closes                      # every open has a close
    d = next(r for r in recs
             if r["event"] == "span_open" and r["name"] == "dispatch")
    assert d["parent"] == pile                       # stack parenting
    assert opens[pile]["parent"] == run
    assert opens[run]["parent"] == ""
    assert validate_events(p, strict=False) == []
    errs, walls = trace_mod.check_spans(recs, "t")
    assert errs == [] and walls["run"] >= walls["pile"] >= 0.0


def test_tracer_error_and_unwind(tmp_path):
    p = str(tmp_path / "abort.jsonl")
    log = JsonlLogger(p)
    tr = Tracer(log)
    tr.open("run")
    with pytest.raises(ValueError):
        with tr.span("dispatch"):
            raise ValueError("boom")
    tr.open("pile")
    tr.unwind()          # the telemetry-bundle finally path
    log.close()
    recs = _events(p)
    closes = [r for r in recs if r["event"] == "span_close"]
    assert {r["span"] for r in recs if r["event"] == "span_open"} \
        == {r["span"] for r in closes}
    assert any(r.get("status") == "error" and r["name"] == "dispatch"
               for r in closes)
    assert sum(r.get("status") == "abort" for r in closes) == 2
    assert trace_mod.check_spans(recs, "t")[0] == []
    # double close is a no-op, not a second record
    tr.close("nonexistent-id")


# ---------------------------------------------------------------------------
# MetricsRegistry
# ---------------------------------------------------------------------------

def test_metrics_registry(tmp_path):
    m = MetricsRegistry()
    m.counter("dispatches").inc()
    m.counter("dispatches").inc(2)
    m.gauge("rss_mb").set(123.4)
    h = m.histogram("turnaround_s")
    for v in (0.5, 1.5, 2.5):
        h.observe(v)
    p = str(tmp_path / "m.jsonl")
    with JsonlLogger(p) as log:
        m.snapshot(log)
    assert validate_events(p, strict=False) == []
    rec = _events(p)[0]
    assert rec["event"] == "metrics"
    assert rec["counters"]["dispatches"] == 3
    assert rec["gauges"]["rss_mb"] == 123.4
    assert rec["hists"]["turnaround_s"]["count"] == 3
    roll = m.rollup()
    assert roll["hists"]["turnaround_s"]["max"] == 2.5
    assert abs(roll["hists"]["turnaround_s"]["mean"] - 1.5) < 1e-9


# ---------------------------------------------------------------------------
# eventcheck: new record kinds + strict span rules
# ---------------------------------------------------------------------------

def test_eventcheck_span_rules(tmp_path):
    p = str(tmp_path / "bad.jsonl")
    rows = [
        {"t": 0.0, "ts": 1.0, "event": "shard_start", "start": 0, "end": 9,
         "pid": 1},
        {"t": 0.1, "ts": 1.1, "event": "span_close", "span": "a-1",
         "name": "run", "wall_s": 0.1},                # close without open
        {"t": 0.2, "ts": 1.2, "event": "span_open", "span": "a-2",
         "parent": "", "name": "run"},
        {"t": 0.3, "ts": 1.3, "event": "span_open", "span": "a-2",
         "parent": "", "name": "run"},                 # double open
    ]
    with open(p, "wt") as fh:
        fh.writelines(json.dumps(r) + "\n" for r in rows)
    errs = validate_events(p, strict=True)
    assert any("without a matching span_open" in e for e in errs)
    assert any("opened twice" in e for e in errs)
    # a shard_start boundary resets span tracking (appended worker attempts)
    rows2 = rows[:3] + [
        {"t": 0.0, "ts": 2.0, "event": "shard_start", "start": 0, "end": 9,
         "pid": 2},
        {"t": 0.1, "ts": 2.1, "event": "span_open", "span": "b-1",
         "parent": "", "name": "run"},
        {"t": 0.2, "ts": 2.2, "event": "span_close", "span": "b-1",
         "name": "run", "wall_s": 0.1},
    ]
    with open(p, "wt") as fh:
        fh.writelines(json.dumps(r) + "\n" for r in rows2)
    errs = validate_events(p, strict=True)
    assert len([e for e in errs if "span" in e]) == 1   # only the orphan close


def test_eventcheck_requires_ts():
    import tempfile

    with tempfile.NamedTemporaryFile("wt", suffix=".jsonl",
                                     delete=False) as fh:
        fh.write(json.dumps({"t": 0.0, "event": "batch", "windows": 1,
                             "solved": 1}) + "\n")
        p = fh.name
    errs = validate_events(p)
    assert any("missing field 'ts'" in e for e in errs)
    os.unlink(p)


# ---------------------------------------------------------------------------
# pipeline integration: ledger row count, span lint, decomposition
# ---------------------------------------------------------------------------

def _run(dataset, tmp_path, tag, telemetry: bool, batch=64):
    d = str(tmp_path)
    ev = os.path.join(d, f"{tag}.events.jsonl") if telemetry else None
    led = os.path.join(d, f"{tag}.ledger.jsonl") if telemetry else None
    cfg = PipelineConfig(native_solver=True, batch_size=batch,
                         events_path=ev, ledger_path=led,
                         metrics_snapshot_s=0.2 if telemetry else 0.0)
    out = os.path.join(d, f"{tag}.fasta")
    st = correct_to_fasta(dataset["db"], dataset["las"], out, cfg)
    return out, ev, led, st


def test_ledger_rows_equal_window_count(dataset, tmp_path):
    out, ev, led, st = _run(dataset, tmp_path, "full", telemetry=True)
    rows = [r for r in _events(led) if r["event"] == "window"]
    assert len(rows) == st.n_windows
    # row shape: identity, geometry, outcome — the router training columns
    r = next(r for r in rows if r["solved"])
    assert r["depth"] >= 1 and r["len"] > 0 and r["tier"] >= 0 and r["k"] > 0
    skips = [r for r in rows if r["stream"] == "skip"]
    assert len(skips) == st.n_skipped_shallow
    assert validate_events(led, strict=False) == []
    assert validate_events(ev, strict=True) == []
    # metrics: periodic snapshots plus the final rollup event
    snaps = [r for r in _events(ev) if r["event"] == "metrics"]
    assert snaps and snaps[-1].get("final") is True
    assert snaps[-1]["gauges"]["n_windows"] == st.n_windows
    assert st.metrics["gauges"]["n_windows"] == st.n_windows


def test_trace_check_and_decomposition_single(dataset, tmp_path):
    out, ev, led, st = _run(dataset, tmp_path, "dec", telemetry=True)
    assert trace_mod.trace_main([ev, led, "--check", "--no-timeline"]) == 0
    recs = _events(ev)
    d = trace_mod.decompose(recs, "dec")
    assert d is not None and d["windows"] == st.n_windows
    # the device.fetch spans wrap exactly the device_s timer region, so the
    # decomposition reconciles with the run's own anchors (5% / 50 ms)
    assert trace_mod.reconcile(d) == []
    assert abs(d["device_s"] - d["device_sum"]) <= 0.05
    # stage sums exist for the stages this run exercised
    assert d["stages"]["dispatch"] > 0 and d["stages"]["feeder"] > 0


def test_telemetry_byte_parity_under_fault_matrix(dataset, tmp_path,
                                                  monkeypatch):
    """Telemetry on vs off must be byte-identical, fault or no fault — and
    the faulted runs' span files still lint clean (the failover/governor
    unwind paths close their spans)."""
    # throwaway registry dir: the injected OOM's ratchet must not land in
    # the host's real compcache (the tools_pounce.sh governor-smoke rule)
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    for fault in (None, "device_lost:2", "device_oom:2"):
        tag = (fault or "clean").replace(":", "_")
        sub = tmp_path / tag
        sub.mkdir()
        if fault is None:
            monkeypatch.delenv("DACCORD_FAULT", raising=False)
        else:
            monkeypatch.setenv("DACCORD_FAULT", fault)
        off, _, _, _ = _run(dataset, sub, "off", telemetry=False)
        on, ev, led, st = _run(dataset, sub, "on", telemetry=True)
        assert open(off).read() == open(on).read(), f"parity broke: {fault}"
        assert trace_mod.trace_main([ev, "--check", "--no-timeline"]) == 0, \
            f"span lint failed under {fault}"
        rows = [r for r in _events(led) if r["event"] == "window"]
        assert len(rows) == st.n_windows, f"ledger drift under {fault}"
        if fault == "device_oom:2":
            assert any(r["event"] == "governor.classify" for r in _events(ev))
    monkeypatch.delenv("DACCORD_FAULT", raising=False)


# ---------------------------------------------------------------------------
# fleet acceptance: 2-worker run, merged timeline + reconciled decomposition
# ---------------------------------------------------------------------------

def test_fleet_trace_merge_and_ledgers(dataset, tmp_path):
    """The acceptance scenario: a 2-worker fleet (with an injected worker
    crash — its resumed shard exercises the append/dedupe path) produces
    per-worker sidecars that daccord-trace merges into one timeline on
    absolute ts, with span lint clean, ledger rows reconciling with the
    manifests, and per-worker wall decompositions reconciling with
    device_s/host_s."""
    from daccord_tpu.parallel.fleet import FleetConfig, run_fleet
    from daccord_tpu.parallel.launch import merge_shards, shard_paths
    from daccord_tpu.runtime.faults import FaultPlan

    ref = str(tmp_path / "ref")
    cfg_ref = FleetConfig(nshards=2, workers=2, backend="native",
                          checkpoint_every=4, worker_telemetry=False,
                          events_path=os.path.join(ref, "fleet.events.jsonl"))
    m_ref = run_fleet(dataset["db"], dataset["las"], ref, cfg_ref, faults=None)
    assert m_ref["done"] == [0, 1]

    d = str(tmp_path / "tele")
    cfg = FleetConfig(nshards=2, workers=2, backend="native",
                      checkpoint_every=4, backoff_base_s=0.05,
                      events_path=os.path.join(d, "fleet.events.jsonl"))
    m = run_fleet(dataset["db"], dataset["las"], d, cfg,
                  faults=FaultPlan.parse("worker_crash:1"))
    assert m["done"] == [0, 1] and not m["poison"]

    # telemetry-on (with crash+requeue) vs telemetry-off byte parity
    merge_shards(ref, 2, str(tmp_path / "ref.fasta"))
    merge_shards(d, 2, str(tmp_path / "tele.fasta"))
    assert open(tmp_path / "ref.fasta").read() \
        == open(tmp_path / "tele.fasta").read()

    # the whole-directory lint: strict schema + span pairing + ledger
    # reconciliation across fleet + worker files
    assert trace_mod.trace_main([d, "--check", "--no-timeline"]) == 0

    # merged timeline carries both workers and the orchestrator on ONE clock
    evs, _, _ = trace_mod._expand([d])
    assert len(evs) == 3    # fleet + 2 worker sidecars
    merged = []
    for path in evs:
        src = os.path.basename(path)
        for rec in trace_mod._read_jsonl(path):
            if isinstance(rec.get("ts"), (int, float)):
                merged.append((rec["ts"], src, rec))
    merged.sort()
    srcs = {s for _, s, _ in merged}
    assert len(srcs) == 3
    assert [x[0] for x in merged] == sorted(x[0] for x in merged)

    # per-worker decomposition reconciles against the shard_done anchors
    n_dec = 0
    for path in evs:
        dd = trace_mod.decompose(trace_mod._read_jsonl(path),
                                 os.path.basename(path))
        if dd is None:
            continue   # the fleet's own sidecar has no shard_done
        n_dec += 1
        assert trace_mod.reconcile(dd) == [], dd
    assert n_dec == 2

    # ledger rows (deduped) equal each manifest's window count; worker
    # metrics rollups were committed durably beside the manifests
    errs, lines = trace_mod.check_dir_ledgers(d)
    assert errs == [] and len(lines) == 2
    for s in (0, 1):
        mp = shard_paths(d, s)["metrics"]
        roll = json.load(open(mp))
        assert roll["gauges"]["n_windows"] > 0
        # events sidecar per worker: spans + shard_done landed there
        ev = shard_paths(d, s)["events"]
        assert any(r["event"] == "shard_done" for r in _events(ev))


# ---------------------------------------------------------------------------
# --probe-history (satellite: attributable fallback benches)
# ---------------------------------------------------------------------------

def test_probe_history(tmp_path, capsys):
    p = str(tmp_path / "tunnel.jsonl")
    rows = [
        {"ts": "2026-08-01T00:00:00Z", "alive": False, "probe_s": 120.0,
         "reason": "probe_timeout"},
        {"ts": "2026-08-01T01:00:00Z", "alive": False, "probe_s": 120.0,
         "reason": "probe_timeout"},
        {"ts": "2026-08-02T00:00:00Z", "alive": True, "probe_s": 3.0,
         "reason": "ok", "after": "ladder"},
    ]
    with open(p, "wt") as fh:
        fh.writelines(json.dumps(r) + "\n" for r in rows)
    assert trace_mod.trace_main(["--probe-history", p]) == 0
    out = capsys.readouterr().out
    assert "last alive: 2026-08-02T00:00:00Z" in out
    assert "dead x2" in out and "alive x1" in out
    assert trace_mod.trace_main(["--probe-history",
                                 str(tmp_path / "missing.jsonl")]) == 1
