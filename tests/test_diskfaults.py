"""Storage fault matrix units (ISSUE 17).

The injectable I/O fault kinds (``runtime/faults.py`` ``io_*``), the aio
fault hook + bounded-retry discipline (``utils/aio.py``), and the graceful
degradation each subsystem owes a disk that says no: telemetry drops and
counts (never raises), the journal refusal latches the admission
``disk_pressure`` 507 state and the probe releases it, torn/zero-byte
lease payloads stay takeover-eligible, a refused spool upload releases the
tenant's quota charge with no disk residue, the AOT cache sweeps to its
size cap, and the sentinel/eventcheck tool belt understands the new event
kinds. The end-to-end storm lives in ``bench.run_disk_soak`` (slow rung
here, pounce smoke + ``DACCORD_BENCH_DISK=1`` elsewhere).
"""

import errno
import json
import os
import time

import pytest

from daccord_tpu.runtime.faults import FaultPlan
from daccord_tpu.utils import aio, lease


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test leaves the process-wide aio fault hook and telemetry drop
    counter as it found them (both are process-global by design)."""
    from daccord_tpu.utils import obs

    yield
    aio.install_faults(None)
    obs.reset_telemetry_dropped()


# ---------------------------------------------------------------------------
# grammar + counters
# ---------------------------------------------------------------------------

def test_io_fault_grammar_parse():
    p = FaultPlan.parse("io_enospc:3@journal,io_eio:2,io_slow:50@lease")
    kinds = {(s.kind, s.at, s.domain) for s in p.specs}
    assert ("io_enospc", 3, "journal") in kinds
    assert ("io_eio", 2, "") in kinds
    assert ("io_slow", 50, "lease") in kinds
    assert p.has_io_faults()
    with pytest.raises(ValueError):
        FaultPlan.parse("io_enospc:1@attic")      # unknown domain
    with pytest.raises(ValueError):
        FaultPlan.parse("serve_crash:1@journal")  # @domain is io_*-only
    with pytest.raises(ValueError):
        FaultPlan.parse("io_bogus:1")


def test_io_check_domain_scoped_counter():
    """An ``@journal`` spec indexes ONLY journal-domain traffic: lease ops
    interleaving never advance it toward firing."""
    p = FaultPlan.parse("io_enospc:2@journal")
    assert p.io_check("lease") is None
    assert p.io_check("lease") is None
    assert p.io_check("journal") is None          # journal op #1
    s = p.io_check("journal")                     # journal op #2: fires
    assert s is not None and s.kind == "io_enospc"
    assert p.io_check("journal") is None          # one-shot
    assert not p.has_io_faults()


def test_io_check_global_counter_and_slow():
    p = FaultPlan.parse("io_eio:3,io_slow:25")
    assert p.io_check("journal") is None
    assert p.io_check("lease") is None
    s = p.io_check("manifest")                    # process-wide op #3
    assert s is not None and s.kind == "io_eio"
    assert p.io_slow_ms("spool") == 25.0          # undomained: every class
    assert p.has_io_faults()                      # io_slow never fires out


# ---------------------------------------------------------------------------
# aio primitive matrix
# ---------------------------------------------------------------------------

def test_durable_write_enospc_no_litter(tmp_path):
    dst = str(tmp_path / "m.json")
    aio.install_faults(FaultPlan.parse("io_enospc:1@manifest"))
    with pytest.raises(OSError) as ei:
        aio.durable_write(dst, lambda fh: fh.write(b"x" * 64),
                          domain="manifest")
    assert ei.value.errno == errno.ENOSPC
    assert not os.path.exists(dst)
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]
    # one-shot: the next commit lands durably
    aio.durable_write(dst, lambda fh: fh.write(b"ok"), domain="manifest")
    assert open(dst, "rb").read() == b"ok"


def test_durable_write_short_write_cleans_torn_tmp(tmp_path):
    dst = str(tmp_path / "m.json")
    aio.install_faults(FaultPlan.parse("io_short_write:1@manifest"))
    with pytest.raises(OSError) as ei:
        aio.durable_write(dst, lambda fh: fh.write(b"y" * 128),
                          domain="manifest")
    assert ei.value.errno == errno.ENOSPC
    # the genuinely-torn tmp was removed; nothing published
    assert not os.listdir(tmp_path)


def test_durable_write_transient_eio_absorbed(tmp_path):
    """``io_eio`` is the transient class: the bounded-retry wrapper's next
    attempt runs clean, so the caller never sees the hiccup."""
    dst = str(tmp_path / "m.json")
    aio.install_faults(FaultPlan.parse("io_eio:1@manifest"))
    aio.durable_write(dst, lambda fh: fh.write(b"ok"), domain="manifest")
    assert open(dst, "rb").read() == b"ok"


def test_durable_write_fsync_fail_not_retried(tmp_path):
    dst = str(tmp_path / "m.json")
    aio.install_faults(FaultPlan.parse("io_fsync_fail:1@manifest"))
    with pytest.raises(OSError) as ei:
        aio.durable_write(dst, lambda fh: fh.write(b"z"), domain="manifest")
    assert ei.value.errno == errno.EIO
    assert getattr(ei.value, "fault_kind", None) == "io_fsync_fail"
    assert not os.path.exists(dst)


def test_exclusive_create_unlinks_wreckage(tmp_path):
    """A write/fsync failure AFTER the O_EXCL open must unlink the claim:
    stranded zero-byte wreckage would block every future claimant until the
    stale-TTL takeover."""
    p = str(tmp_path / "j.lease")
    aio.install_faults(FaultPlan.parse("io_enospc:1@lease"))
    with pytest.raises(OSError):
        aio.exclusive_create(p, b'{"host": "me"}', domain="lease")
    assert not os.path.exists(p)                  # no wreckage
    assert aio.exclusive_create(p, b'{"host": "me"}', domain="lease")
    # transient EIO: retrying re-claims — the unlink is what lets the retry
    # attempt's O_EXCL succeed instead of colliding with our own corpse
    p2 = str(tmp_path / "k.lease")
    aio.install_faults(FaultPlan.parse("io_eio:1@lease"))
    assert aio.exclusive_create(p2, b"{}", domain="lease")


def test_io_slow_delays_ops(tmp_path):
    aio.install_faults(FaultPlan.parse("io_slow:40@sidecar"))
    t0 = time.monotonic()
    with aio.open_output(str(tmp_path / "s.jsonl"), "wb",
                         domain="sidecar") as fh:
        fh.write(b"line\n")
    assert time.monotonic() - t0 >= 0.035
    # other domains are untouched by the scoped delay
    t0 = time.monotonic()
    aio.durable_write(str(tmp_path / "m"), lambda fh: fh.write(b"x"),
                      domain="manifest")
    assert time.monotonic() - t0 < 0.035


def test_retrying_bounded_on_real_transient():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(errno.EIO, "hiccup")
        return "ok"

    assert aio.retrying(flaky, base_s=0.001) == "ok"
    assert calls["n"] == 3
    with pytest.raises(OSError):
        aio.retrying(lambda: (_ for _ in ()).throw(
            OSError(errno.ENOSPC, "full")), base_s=0.001)


# ---------------------------------------------------------------------------
# telemetry never raises (satellite: JsonlLogger drop-and-count)
# ---------------------------------------------------------------------------

def test_jsonl_logger_drops_and_counts(tmp_path):
    from daccord_tpu.utils import obs

    obs.reset_telemetry_dropped()
    log = obs.JsonlLogger(str(tmp_path / "ev.jsonl"))
    aio.install_faults(FaultPlan.parse("io_enospc:1@sidecar"))
    log.log("io.fault", domain="journal", op="append", error="x")  # durable
    assert obs.telemetry_dropped_total() == 1     # dropped, never raised
    log.log("disk.pressure", level="enter", src="journal", free_mb=1.0,
            detail="d")
    log.close()
    recs = [json.loads(x) for x in open(tmp_path / "ev.jsonl")]
    assert [r["event"] for r in recs] == ["disk.pressure"]
    assert obs.telemetry_dropped_total() == 1


def test_metrics_snapshot_surfaces_drops_only_when_nonzero(tmp_path):
    from daccord_tpu.utils import obs

    obs.reset_telemetry_dropped()
    reg = obs.MetricsRegistry()
    reg.counter("jobs").inc()
    assert "telemetry_dropped_total" not in reg.rollup()["counters"]
    obs._note_dropped(3)
    assert reg.rollup()["counters"]["telemetry_dropped_total"] == 3


# ---------------------------------------------------------------------------
# lease protocol under a refusing disk (satellite: torn payloads)
# ---------------------------------------------------------------------------

def test_lease_read_result_statuses(tmp_path):
    p = str(tmp_path / "j.lease")
    assert lease.read_result(p) == (None, "absent")
    open(p, "w").close()                          # zero-byte claim corpse
    assert lease.read_result(p) == (None, "torn")
    with open(p, "w") as fh:
        fh.write('{"host": "to')                  # partial write
    assert lease.read_result(p) == (None, "torn")
    with open(p, "w") as fh:
        json.dump({"host": "me"}, fh)
    info, st = lease.read_result(p)
    assert st == "ok" and info["host"] == "me"
    aio.install_faults(FaultPlan.parse("io_eio:1@lease"))
    assert lease.read_result(p) == (None, "error")
    assert lease.read_result(p)[1] == "ok"        # one-shot hiccup


def test_zero_byte_lease_stale_takeover(tmp_path):
    """A zero-byte payload (claimer killed mid-create) must be
    takeover-eligible once stale — it can never renew itself."""
    p = str(tmp_path / "j.lease")
    open(p, "w").close()
    lease.backdate(p, 120.0)
    ok, tk = lease.claim(p, "taker", ttl_s=60.0)
    assert ok and tk["prev_host"] == "?"
    assert lease.read(p)["host"] == "taker"


def test_lease_claim_disk_refusal_loses_gracefully(tmp_path):
    """A disk that says no at claim time is indistinguishable from losing
    the race — never an exception into the submit/heartbeat thread, never
    wreckage blocking the next claimant."""
    p = str(tmp_path / "j.lease")
    aio.install_faults(FaultPlan.parse(
        "io_enospc:1@lease,io_enospc:2@lease"))
    ok, tk = lease.claim(p, "me", ttl_s=60.0)
    assert not ok and tk is None
    assert not os.path.exists(p)
    ok, _ = lease.claim(p, "me", ttl_s=60.0)      # storm spent: wins
    assert ok


def test_lease_renew_eio_returns_false_then_recovers(tmp_path):
    p = str(tmp_path / "j.lease")
    lease.claim(p, "me", 60.0)
    aio.install_faults(FaultPlan.parse("io_eio:2@lease"))
    assert lease.renew(p)                         # lease op #1: clean
    assert not lease.renew(p)                     # op #2: injected EIO
    assert lease.renew(p)                         # transient: next beat ok


# ---------------------------------------------------------------------------
# journal refusal -> disk-pressure latch -> 507 -> probe release
# ---------------------------------------------------------------------------

def _svc(workdir, **kw):
    from daccord_tpu.serve import ConsensusService, ServeConfig

    kw.setdefault("backend", "cpu")
    kw.setdefault("backend_explicit", True)
    kw.setdefault("workers", 1)
    return ConsensusService(ServeConfig(workdir=str(workdir), **kw))


def test_journal_refusal_latches_507_and_probe_clears(tmp_path):
    from daccord_tpu.serve.admission import AdmissionReject

    svc = _svc(tmp_path / "srv")
    try:
        aio.install_faults(FaultPlan.parse("io_enospc:1@journal"))
        svc.journal_mark("admitted", "j99999", tenant="t", nbytes=1)
        assert svc.admission.disk_pressure        # latched
        assert svc.journal.append_failures == 1
        with pytest.raises(AdmissionReject) as ei:
            svc.submit({"tenant": "t"})
        assert ei.value.reason == "disk_pressure" and ei.value.retryable
        # the raw probe proves the volume writable again: latch releases
        svc._disk_tick(time.time())
        assert svc.admission.disk_pressure is None
        svc.admission.admit("t", 1, job="jX")
        svc.admission.release("t", 1)
        evp = os.path.join(str(tmp_path / "srv"), "serve.events.jsonl")
        evs = [json.loads(x) for x in open(evp)]
        kinds = [(e["event"], e.get("level")) for e in evs]
        assert ("io.fault", None) in kinds
        assert ("disk.pressure", "enter") in kinds
        assert ("disk.pressure", "clear") in kinds
    finally:
        aio.install_faults(None)
        svc.shutdown()


def test_spool_enospc_releases_quota_and_dir(tmp_path):
    """A refused upload (ENOSPC mid-spool) raises out of admission, which
    releases the tenant's charge and leaves no spool dir behind."""
    import base64

    svc = _svc(tmp_path / "srv")
    try:
        aio.install_faults(FaultPlan.parse("io_enospc:1@spool"))
        body = {"tenant": "t",
                "files": {"x.db": base64.b64encode(b"junk").decode()}}
        with pytest.raises(OSError):
            svc.submit(body)
        st = svc.admission.stats()["tenants"].get("t", {})
        assert st.get("queued", 0) == 0 and st.get("bytes", 0) == 0
        assert os.listdir(os.path.join(str(tmp_path / "srv"), "jobs")) == []
    finally:
        aio.install_faults(None)
        svc.shutdown()


def test_journal_compact_online(tmp_path):
    from daccord_tpu.serve.journal import JobJournal, replay

    j = JobJournal(str(tmp_path / "journal.jsonl"))
    for i in range(40):
        jid = f"j{i:05d}"
        assert j.append("admitted", jid, tenant="t", nbytes=1)
        assert j.append("committed", jid)         # terminal, no idem: GC-able
    assert j.append("admitted", "jlive", tenant="t", nbytes=1)
    before = j.size_bytes()
    res = j.compact_online()
    assert res is not None
    assert res["before"] == before and res["after"] < before
    assert res["kept"] == 1 and res["torn"] == 0
    # the swapped fd keeps appending durably
    assert j.append("running", "jlive")
    j.close()
    ents, torn = replay(str(tmp_path / "journal.jsonl"))
    assert torn == 0 and set(ents) == {"jlive"}
    assert ents["jlive"].state == "running"


def test_journal_append_refusal_counts_not_raises(tmp_path):
    from daccord_tpu.serve.journal import JobJournal

    j = JobJournal(str(tmp_path / "journal.jsonl"))
    aio.install_faults(FaultPlan.parse("io_enospc:1@journal"))
    assert not j.append("admitted", "j1")
    assert j.append_failures == 1 and "ENOSPC" in (j.last_error or "") \
        or j.last_error
    assert j.append("admitted", "j1")             # storm spent
    j.close()


def test_admission_hard_watermark_rejects(tmp_path):
    from daccord_tpu.serve.admission import (AdmissionConfig,
                                             AdmissionController,
                                             AdmissionReject)

    adm = AdmissionController(AdmissionConfig(
        watch_dir=str(tmp_path), disk_hard_mb=10.0 ** 9))
    level, free = adm.disk_level()
    assert level == "hard" and free >= 0
    with pytest.raises(AdmissionReject) as ei:
        adm.admit("t", 1)
    assert ei.value.reason == "disk_pressure"
    # thresholds off: the governor is inert
    adm2 = AdmissionController(AdmissionConfig(watch_dir=str(tmp_path)))
    assert adm2.disk_level() == (None, -1.0)
    adm2.admit("t", 1)
    adm2.release("t", 1)


def test_disk_free_mb_walks_to_existing_ancestor(tmp_path):
    from daccord_tpu.utils.obs import disk_free_mb

    free = disk_free_mb(str(tmp_path))
    assert free > 0
    # a not-yet-created watch dir reads its nearest existing ancestor
    assert disk_free_mb(str(tmp_path / "no" / "such" / "dir")) > 0


# ---------------------------------------------------------------------------
# AOT cache: skip-and-continue publish + size-capped LRU sweep
# ---------------------------------------------------------------------------

def test_aot_sweep_caps_by_lru(tmp_path):
    from daccord_tpu.serve.aotcache import AotCache

    d = str(tmp_path / "aot")
    os.makedirs(d)
    now = time.time()
    for i in range(4):
        p = os.path.join(d, f"k{i}.aot")
        with open(p, "wb") as fh:
            fh.write(b"\0" * (512 * 1024))        # 0.5 MiB each
        os.utime(p, (now - 100 + i, now - 100 + i))
    cache = AotCache(d, cap_mb=1.0)               # cap: 2 of 4 survive
    removed = cache.sweep(keep=os.path.join(d, "k0.aot"))
    left = sorted(os.listdir(d))
    assert removed == 2
    # k0 is pinned (the file just published); then LRU: oldest unpinned die
    assert "k0.aot" in left and "k3.aot" in left
    assert cache.counters["swept"] == 2
    assert cache.sweep() == 0                     # already under cap


# ---------------------------------------------------------------------------
# tool belt: eventcheck schemas + sentinel flags
# ---------------------------------------------------------------------------

def _write_events(path, recs):
    with open(path, "w") as fh:
        for i, r in enumerate(recs):
            fh.write(json.dumps({"t": float(i), "ts": float(i), **r}) + "\n")
    return str(path)


def test_eventcheck_knows_disk_kinds(tmp_path):
    from daccord_tpu.tools.eventcheck import validate_events

    good = _write_events(tmp_path / "ok.jsonl", [
        {"event": "io.fault", "domain": "journal", "op": "append",
         "error": "ENOSPC"},
        {"event": "disk.pressure", "level": "enter", "src": "journal",
         "free_mb": 12.5, "detail": "x"},
        {"event": "journal.compact", "before": 100, "after": 10,
         "kept": 1, "torn": 0},
        {"event": "aot.sweep", "removed": 2, "freed": 1024, "total": 4096,
         "cap_mb": 1.0},
    ])
    assert validate_events(good, strict=True) == []
    bad = _write_events(tmp_path / "bad.jsonl", [
        {"event": "disk.pressure", "level": 3, "src": "journal",
         "free_mb": "lots", "detail": "x"},
    ])
    assert validate_events(bad, strict=True)


def test_sentinel_flags_disk_pressure_events(tmp_path):
    from daccord_tpu.tools.sentinel import scan_events

    p = _write_events(tmp_path / "ev.jsonl", [
        {"event": "disk.pressure", "level": "enter", "src": "watermark",
         "free_mb": 3.0, "detail": "free 3 MiB <= hard 5 MiB"},
    ])
    issues = scan_events(p)
    assert any("DISK PRESSURE" in s for s in issues)
    clear_only = _write_events(tmp_path / "ev2.jsonl", [
        {"event": "disk.pressure", "level": "clear", "src": "probe",
         "free_mb": 900.0, "detail": ""},
    ])
    assert not any("DISK PRESSURE" in s for s in scan_events(clear_only))


def test_sentinel_bench_chaos_exemption():
    from daccord_tpu.tools.sentinel import check_bench_series

    sick = [("BENCH_SERVE.json", {"metric": "m", "value": 1.0,
                                  "disk_pressure_events": 2})]
    assert any("disk pressure" in s for s in check_bench_series(sick))
    chaos = [("BENCH_DISK.json", {"metric": "disk_soak", "chaos": True,
                                  "disk_pressure_events": 4})]
    assert check_bench_series(chaos) == []


# ---------------------------------------------------------------------------
# the full storm (slow rung; the pounce smoke and DACCORD_BENCH_DISK=1
# run the same contract end-to-end)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_disk_soak_contract(tmp_path):
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)
    line = bench.run_disk_soak(root=str(tmp_path), n_jobs=4,
                               commit_sidecar=False)
    assert line["chaos"] and line["recovered"] and line["parity"]
    assert line["refusals_507"] >= 1 and line["done"] == line["jobs"]
