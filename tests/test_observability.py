"""Fleet flight recorder tests (ISSUE 13): per-device mesh telemetry, the
prom health plane, daccord-top, daccord-sentinel, SLO burn tracking, and
the ledger mesh column.

The per-chip attribution contract under test: a FORCED mesh degradation
(``device_lost:N@K``) must be attributable to device index K from the
events alone — ``mesh.shrink`` names the culprit, ``mesh.device`` flips its
state row to ``lost``, the surviving half excludes it, and the output stays
byte-identical. The golden-output tests run ``daccord-top --once`` and
``daccord-sentinel`` over COMMITTED fixture sidecars (tests/data/obs), so
the render/flag contracts cannot drift silently.
"""

from __future__ import annotations

import glob
import json
import os

import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "obs")


# ---------------------------------------------------------------------------
# prom exposition (render + parse)
# ---------------------------------------------------------------------------


def test_render_parse_prom_roundtrip():
    from daccord_tpu.utils.obs import parse_prom, render_prom

    roll = {"counters": {"dispatches": 7, "weird name!": 2},
            "gauges": {"rss_mb": 812.25},
            "hists": {"lat_s": {"count": 3, "sum": 1.5, "p50": 0.4,
                                "p95": 0.9, "p99": None}}}
    text = render_prom(roll, labels={"shard": 3})
    samples, errs = parse_prom(text)
    assert errs == []
    assert samples["daccord_dispatches_total"] == [('{shard="3"}', 7.0)]
    # illegal chars sanitize into a legal metric name
    assert "daccord_weird_name__total" in samples
    assert samples["daccord_lat_s_count"][0][1] == 3.0
    # the p99=None quantile is omitted, not rendered as "None"
    assert not any("None" in ln for ln in text.splitlines())


def test_parse_prom_flags_malformed():
    from daccord_tpu.utils.obs import parse_prom

    _, errs = parse_prom("daccord_x 1.5\nnot a sample line at all\n"
                         "daccord_y NaN\n# TYPE daccord_ghost gauge\n")
    msgs = "\n".join(errs)
    assert "not a sample" in msgs
    assert "non-finite" in msgs
    assert "ghost" in msgs


# ---------------------------------------------------------------------------
# fingerprint registry v2 (compile-wall telemetry)
# ---------------------------------------------------------------------------


def test_fingerprint_registry_v2_and_legacy(tmp_path, monkeypatch):
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    from daccord_tpu.utils.obs import (fingerprint_registry, fingerprint_seen,
                                       record_fingerprint)

    # legacy list format still reads (pre-ISSUE-13 registries)
    os.makedirs(tmp_path / "cc", exist_ok=True)
    with open(tmp_path / "cc" / "daccord_shapes.json", "wt") as fh:
        json.dump(["cpu:B64xD16xL64"], fh)
    assert fingerprint_seen("cpu:B64xD16xL64")
    # new writes upgrade to the dict format, preserving legacy keys and
    # folding compile telemetry in
    record_fingerprint("cpu:B128xD16xL64", wall_s=12.345)
    reg = fingerprint_registry()
    assert "cpu:B64xD16xL64" in reg
    assert reg["cpu:B128xD16xL64"]["wall_s"] == 12.345
    # re-recording never overwrites the (cold) first wall
    record_fingerprint("cpu:B128xD16xL64", wall_s=0.001)
    assert fingerprint_registry()["cpu:B128xD16xL64"]["wall_s"] == 12.345


# ---------------------------------------------------------------------------
# ledger mesh column (satellite 1)
# ---------------------------------------------------------------------------


def test_ledger_mesh_column_and_byte_stability(tmp_path):
    from daccord_tpu.utils.obs import WindowLedger

    p0, p1 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    led = WindowLedger(p0)
    led.record(1, 2, 40, 10, 0, 8, True, "full", rescued=False, wall_s=0.5)
    led.close()
    # mesh=0 (the default) leaves the row BYTE-identical to the pre-column
    # format: non-mesh ledgers must not change under the router training set
    row = json.loads(open(p0).read())
    assert "mesh" not in row and "job" not in row
    led = WindowLedger(p1)
    led.record(1, 2, 40, 10, 0, 8, True, "full", rescued=False, wall_s=0.5,
               job="jobA", mesh=8)
    led.close()
    row = json.loads(open(p1).read())
    assert row["mesh"] == 8 and row["job"] == "jobA"


# ---------------------------------------------------------------------------
# eventcheck strictness for the new kinds (satellite 4)
# ---------------------------------------------------------------------------


def test_eventcheck_new_kinds(tmp_path):
    from daccord_tpu.tools.eventcheck import validate_events

    good = tmp_path / "good.jsonl"
    good.write_text(
        '{"t": 0.0, "ts": 1.0, "event": "mesh.device", "device": 3, '
        '"state": "lost"}\n'
        '{"t": 0.1, "ts": 1.1, "event": "serve.slo", "target_s": 2.0, '
        '"burn": 0.9, "n": 12}\n'
        '{"t": 0.2, "ts": 1.2, "event": "mesh.shrink", "nd_from": 8, '
        '"nd_to": 4, "culprit": 3, "reason": "x"}\n'
        '{"t": 0.3, "ts": 1.3, "event": "sup_compile_done", '
        '"key": "cpu:B64", "wall_s": 1.5}\n'
        '{"t": 0.4, "ts": 1.4, "event": "profile.capture", "dir": "/p", '
        '"dispatch": 2, "state": "start"}\n')
    assert validate_events(str(good), strict=True) == []
    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        '{"t": 0.0, "ts": 1.0, "event": "mesh.device", "device": "three", '
        '"state": "lost"}\n'
        '{"t": 0.1, "ts": 1.1, "event": "serve.slo", "target_s": 2.0}\n'
        '{"t": 0.2, "ts": 1.2, "event": "mesh.shrink", "nd_from": 8, '
        '"nd_to": 4, "reason": "x"}\n')
    errs = validate_events(str(bad), strict=True)
    msgs = "\n".join(errs)
    assert "mesh.device.device has type str" in msgs
    assert "serve.slo missing field 'burn'" in msgs
    assert "mesh.shrink missing field 'culprit'" in msgs


# ---------------------------------------------------------------------------
# forced mesh degradation: per-device attribution (the tentpole contract)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from daccord_tpu.formats import LasFile, read_db
    from daccord_tpu.runtime import PipelineConfig, correct_shard
    from daccord_tpu.runtime.pipeline import estimate_profile_for_shard
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("obscorpus"))
    # same corpus parameters as tests/test_mesh.py, so the :m8/:m4 shapes
    # reuse the persistent compile cache across the two files
    out = make_dataset(d, SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=700, min_overlap=300,
                                    seed=47), name="mesh")
    db = read_db(out["db"])
    las = LasFile(out["las"])
    base = dict(batch_size=64, depth_buckets=(16,))
    profile = estimate_profile_for_shard(db, las, PipelineConfig(**base))

    def run(**kw):
        cfg = PipelineConfig(**base, **kw)
        return [(rid, [f.tobytes() for f in frags])
                for rid, frags, _ in correct_shard(db, las, cfg,
                                                   profile=profile)]

    single = run()
    assert len(single) > 0
    return {"db": db, "las": las, "base": base, "profile": profile,
            "run": run, "single": single}


def test_forced_degradation_attributes_device(corpus, tmp_path, monkeypatch):
    """device_lost:2@3 on a mesh-8 run: the shrink names culprit device 3,
    its mesh.device row flips to lost, the survivors are the half WITHOUT
    it, snapshots embed the mesh health map, the ledger rows carry mesh=8,
    and the bytes match the single-device run. The whole sidecar passes
    eventcheck --strict and daccord-trace span pairing."""
    monkeypatch.setenv("DACCORD_FAULT", "device_lost:2@3")
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    ev = str(tmp_path / "lost.events.jsonl")
    led = str(tmp_path / "lost.ledger.jsonl")
    from daccord_tpu.runtime import PipelineConfig, correct_shard

    cfg = PipelineConfig(**corpus["base"], mesh=8, events_path=ev,
                         ledger_path=led)
    got = [(rid, [f.tobytes() for f in frags])
           for rid, frags, st in correct_shard(corpus["db"], corpus["las"],
                                               cfg,
                                               profile=corpus["profile"])]
    assert got == corpus["single"]
    evs = [json.loads(x) for x in open(ev)]
    shr = [e for e in evs if e["event"] == "mesh.shrink"]
    assert shr and shr[0]["culprit"] == 3, shr
    dev_rows = [e for e in evs if e["event"] == "mesh.device"]
    # one lost chip, attributed: device 3 (the shrink row + the later
    # snapshot rows all agree)
    assert {e["device"] for e in dev_rows if e["state"] == "lost"} == {3}
    # culprit in the first half -> the SECOND half survives
    dropped = {e["device"] for e in dev_rows if e["state"] == "dropped"}
    assert dropped == {0, 1, 2}
    # the final metrics snapshot embeds the mesh health map with per-device
    # wall/rows and the gauges track the shrunken width
    snaps = [e for e in evs if e["event"] == "metrics" and "mesh" in e]
    assert snaps, "no metrics snapshot carried the mesh health map"
    hm = snaps[-1]["mesh"]
    assert hm["nd"] == 4 and hm["nd0"] == 8
    assert hm["devices"]["3"]["state"] == "lost"
    assert any(r["dispatches"] > 0 and r["dispatch_wall_s"] > 0
               for r in hm["devices"].values())
    assert snaps[-1]["gauges"]["mesh_nd"] == 4.0
    assert snaps[-1]["gauges"]["mesh_devices_lost"] == 4.0
    # ledger mesh column: every row records the mesh-8 solve path
    rows = [json.loads(x) for x in open(led)]
    assert rows and all(r.get("mesh") == 8 for r in rows
                        if r.get("event") == "window")
    # schema + span pairing across the degradation (satellite 4)
    from daccord_tpu.tools.eventcheck import validate_events
    from daccord_tpu.tools.trace import check_spans

    assert validate_events(ev, strict=True) == []
    errs, _ = check_spans(evs, "lost")
    assert errs == []


def test_compile_wall_lands_in_registry(corpus, tmp_path, monkeypatch):
    """The supervisor times fresh dispatches: every cold shape's measured
    wall lands in the fingerprint registry and as sup_compile_done."""
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    ev = str(tmp_path / "run.events.jsonl")
    from daccord_tpu.runtime import PipelineConfig, correct_shard
    from daccord_tpu.utils.obs import fingerprint_registry

    cfg = PipelineConfig(**corpus["base"], events_path=ev)
    list(correct_shard(corpus["db"], corpus["las"], cfg,
                       profile=corpus["profile"]))
    evs = [json.loads(x) for x in open(ev)]
    done = [e for e in evs if e["event"] == "sup_compile_done"]
    assert done and all(e["wall_s"] >= 0 for e in done)
    reg = fingerprint_registry()
    keys = [e["key"] for e in done]
    assert keys and all(k in reg and "wall_s" in reg[k] for k in keys)


def test_profile_capture_hook(corpus, tmp_path, monkeypatch):
    """DACCORD_PROFILE_DIR captures one jax.profiler trace bracketing the
    Nth dispatch; the bracket events land and the trace dir is non-empty."""
    pdir = tmp_path / "prof"
    monkeypatch.setenv("DACCORD_PROFILE_DIR", str(pdir))
    monkeypatch.setenv("DACCORD_PROFILE_DISPATCH", "1")
    monkeypatch.setenv("DACCORD_COMPCACHE", str(tmp_path / "cc"))
    ev = str(tmp_path / "prof.events.jsonl")
    from daccord_tpu.runtime import PipelineConfig, correct_shard

    cfg = PipelineConfig(**corpus["base"], events_path=ev)
    got = [(rid, [f.tobytes() for f in frags])
           for rid, frags, st in correct_shard(corpus["db"], corpus["las"],
                                               cfg,
                                               profile=corpus["profile"])]
    assert got == corpus["single"]
    evs = [json.loads(x) for x in open(ev)]
    caps = [e for e in evs if e["event"] == "profile.capture"]
    assert [c["state"] for c in caps] == ["start", "stop"], caps
    assert os.path.isdir(pdir) and any(os.scandir(pdir))


# ---------------------------------------------------------------------------
# daccord-top over committed fixtures (satellite 4 golden output)
# ---------------------------------------------------------------------------


def test_top_once_over_fixtures(capsys):
    from daccord_tpu.tools.top import collect, render, top_main

    rundir = os.path.join(FIXTURES, "run")
    srvdir = os.path.join(FIXTURES, "srv")
    snap = collect([rundir, srvdir])
    assert snap["mesh"]["devices"]["3"]["state"] == "lost"
    # trust verdicts (ISSUE 20) ride the same device table; the later
    # metrics-snapshot mesh dict must not erase the event-sourced verdict
    assert snap["mesh"]["devices"]["2"]["trust"] == "SUSPECT"
    assert snap["slo"]["burn"] == 0.9
    assert snap["ratchets"]["cpu:B64xD16xL64:m4"] == 32
    screen = render(snap)
    # the one-screen contract: shard row, mesh device table with the lost
    # chip, SLO burn, governor ratchet, and the fault milestones
    assert "shard0000" in screen
    assert "MESH 4/8" in screen
    assert "lost" in screen and "dropped" in screen
    assert "SLO burn 0.9" in screen
    assert "cpu:B64xD16xL64:m4 -> 32" in screen
    assert "mesh.shrink" in screen and "culprit=3" in screen
    # SDC plane on the operator screen: fault panel + TRUST column
    assert "sup_sdc" in screen and "trust.state" in screen
    assert "TRUST" in screen and "SUSPECT:1" in screen
    # the CLI one-shot form exits 0 and prints the same screen
    assert top_main([rundir, srvdir, "--once"]) == 0
    out = capsys.readouterr().out
    assert "daccord-top" in out and "MESH 4/8" in out


def test_top_handles_empty_dir(tmp_path, capsys):
    from daccord_tpu.tools.top import top_main

    assert top_main([str(tmp_path), "--once"]) == 0
    assert "0 source(s)" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# daccord-sentinel (regression + fallback flagging)
# ---------------------------------------------------------------------------


def test_sentinel_flags_regression_and_fallback(capsys):
    """The ISSUE 13 acceptance case: an injected 20% throughput regression
    (BENCH_s03 is 20% below the s01/s02 median) and a fallback: true rung
    (BENCH_s04, committed wrapper format) both flag; strict mode fails."""
    from daccord_tpu.tools.sentinel import sentinel_main

    files = sorted(glob.glob(os.path.join(FIXTURES, "bench", "*.json")))
    assert sentinel_main(files) == 0          # advisory: warn, exit 0
    err = capsys.readouterr().err
    assert "BENCH_s03.json" in err and "below the series median" in err
    assert "BENCH_s04.json" in err and "fallback: true" in err
    assert sentinel_main(["--strict"] + files) == 1


def test_sentinel_noise_band_suppresses_jitter():
    from daccord_tpu.tools.sentinel import check_bench_series

    entries = [("a.json", {"metric": "m", "value": 1000.0, "batch": 64}),
               ("b.json", {"metric": "m", "value": 950.0, "batch": 64})]
    assert check_bench_series(entries, noise=0.15) == []
    entries.append(("c.json", {"metric": "m", "value": 700.0, "batch": 64}))
    issues = check_bench_series(entries, noise=0.15)
    assert len(issues) == 1 and "c.json" in issues[0]
    # different batch = different series: a B=64 rung never compares
    # against a B=2048 one
    entries.append(("d.json", {"metric": "m", "value": 10.0, "batch": 2048}))
    assert len(check_bench_series(entries, noise=0.15)) == 1


def test_sentinel_event_red_flags(tmp_path):
    from daccord_tpu.tools.sentinel import scan_events

    bad = tmp_path / "bad.events.jsonl"
    bad.write_text(
        '{"t": 0.0, "ts": 1.0, "event": "sup_failover", "reason": "dead", '
        '"fallback": "native"}\n'
        '{"t": 1.0, "ts": 2.0, "event": "serve.slo", "target_s": 2.0, '
        '"burn": 1.2, "n": 5}\n'
        '{"t": 2.0, "ts": 3.0, "event": "bench_rung", "batch": 64, '
        '"bases_per_sec": 0.0, "fallback": true, "pad_waste": 0.0}\n'
        '{"t": 3.0, "ts": 4.0, "event": "shard_done", "reads": 1, '
        '"windows": 2, "solved": 2, "wall_s": 1.0, "degraded": true}\n')
    issues = scan_events(str(bad))
    joined = "\n".join(issues)
    assert "failover" in joined and "SLO BREACH" in joined
    assert "fallback: true" in joined and "DEGRADED" in joined
    clean = tmp_path / "clean.events.jsonl"
    clean.write_text('{"t": 0.0, "ts": 1.0, "event": "shard_done", '
                     '"reads": 1, "windows": 2, "solved": 2, "wall_s": 1.0, '
                     '"degraded": false}\n')
    assert scan_events(str(clean)) == []


def test_sentinel_prom_lint(tmp_path):
    from daccord_tpu.tools.sentinel import sentinel_main

    good = tmp_path / "good.prom"
    good.write_text("# TYPE daccord_x gauge\ndaccord_x 1.5\n")
    assert sentinel_main(["--strict", str(good)]) == 0
    bad = tmp_path / "bad.prom"
    bad.write_text("daccord_x one-point-five\n")
    assert sentinel_main(["--strict", str(bad)]) == 1


def test_fixture_sidecars_pass_lint():
    """The committed fixtures stay schema-valid: eventcheck --strict over
    both events files, sentinel-clean for the non-degraded ones."""
    from daccord_tpu.tools.eventcheck import validate_events
    from daccord_tpu.tools.sentinel import scan_events

    for p in (os.path.join(FIXTURES, "run", "shard0000.events.jsonl"),
              os.path.join(FIXTURES, "srv", "serve.events.jsonl")):
        assert validate_events(p, strict=True) == [], p
        assert scan_events(p) == [], p


# ---------------------------------------------------------------------------
# tunnel staleness (satellite 2)
# ---------------------------------------------------------------------------


def test_last_alive_info(tmp_path):
    from daccord_tpu.tools.trace import last_alive_info

    log = tmp_path / "TUNNEL_LOG.jsonl"
    log.write_text(
        '{"ts": "2026-07-30T10:00:00Z", "alive": true, "devices": 1}\n'
        '{"ts": "2026-08-01T08:00:00Z", "alive": false, "devices": 0}\n')
    ts, age_h = last_alive_info(str(log))
    assert ts == "2026-07-30T10:00:00Z"
    assert age_h is not None and age_h > 24.0
    ts, age_h = last_alive_info(str(tmp_path / "missing.jsonl"))
    assert ts is None and age_h is None


# ---------------------------------------------------------------------------
# serve plane: SLO burn + healthz + prom (satellites 3, tentpole 2)
# ---------------------------------------------------------------------------

try:
    from daccord_tpu.native import available as _nat_avail

    _HAVE_NATIVE = _nat_avail()
except Exception:
    _HAVE_NATIVE = False

needs_native = pytest.mark.skipif(not _HAVE_NATIVE,
                                  reason="native library unavailable")


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    from daccord_tpu.sim import SimConfig, make_dataset

    d = str(tmp_path_factory.mktemp("obs-serve"))
    cfg = SimConfig(genome_len=1500, coverage=10, read_len_mean=500,
                    min_overlap=200, seed=5)
    return make_dataset(d, cfg, name="sv"), d


@needs_native
def test_serve_slo_healthz_and_prom(dataset, tmp_path):
    """An impossible SLO target (1 ms) must emit serve.slo with burn >> 1
    and engage the shed ladder before RSS pressure ever would; healthz
    carries uptime/queue depth/per-group busy flags lock-free; the prom
    exposition parses; the durable rollup records peaks; shutdown commits
    serve.metrics.prom."""
    import time as _time

    from daccord_tpu.serve import ConsensusService, ServeConfig
    from daccord_tpu.utils.obs import parse_prom

    out, d = dataset
    svc = ConsensusService(ServeConfig(
        workdir=str(tmp_path / "srv"), backend="native",
        backend_explicit=True, batch=64, workers=2, flush_lag_s=0.02,
        slo_p99_s=0.001, slo_window_s=60.0))
    j1 = svc.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
    svc.wait(j1["job"], 300)
    # let the 1 Hz slo tick observe the finished job
    deadline = _time.time() + 10
    while _time.time() < deadline and svc._slo_shed == 0:
        _time.sleep(0.1)
    h = svc.health()
    assert h["uptime_s"] > 0 and "queue_depth" in h
    assert isinstance(h["groups_busy"], dict) and h["groups_busy"], h
    assert all(isinstance(v, bool) for v in h["groups_busy"].values())
    text = svc.stats_prom()
    samples, errs = parse_prom(text)
    assert errs == [] and "daccord_serve_uptime_s" in samples
    assert svc._slo_shed >= 1, "SLO burn never engaged the shed ladder"
    svc.shutdown()
    evs = [json.loads(x) for x in
           open(os.path.join(svc.cfg.workdir, "serve.events.jsonl"))]
    slo = [e for e in evs if e["event"] == "serve.slo"]
    assert slo and slo[-1]["burn"] > 1.0 and slo[-1]["target_s"] == 0.001
    shed = [e for e in evs if e["event"] == "serve.shed"]
    assert shed and shed[0]["level"] >= 1
    # eventcheck accepts the new kind in a real stream
    from daccord_tpu.tools.eventcheck import validate_events

    assert validate_events(
        os.path.join(svc.cfg.workdir, "serve.events.jsonl"),
        strict=True) == []
    roll = json.load(open(os.path.join(svc.cfg.workdir,
                                       "serve.metrics.json")))
    g = roll["metrics"]["gauges"]
    assert "rss_mb_peak" in g and g["rss_mb_peak"] >= g["rss_mb"] - 1e-6
    assert "queue_depth_peak" in g
    prom_path = os.path.join(svc.cfg.workdir, "serve.metrics.prom")
    assert os.path.exists(prom_path)
    _, perrs = parse_prom(open(prom_path).read())
    assert perrs == []


def test_slo_shed_releases_on_empty_window(tmp_path):
    """A past burst must not pin the shed ladder: once the latency window
    drains empty (traffic stopped), the SLO-held rung releases one per
    tick instead of holding the reduced batch width forever."""
    from daccord_tpu.serve import ConsensusService, ServeConfig

    svc = ConsensusService(ServeConfig(
        workdir=str(tmp_path / "srv"), backend="native",
        backend_explicit=True, slo_p99_s=1.0, slo_window_s=60.0))
    try:
        svc._slo_shed = 3
        assert not svc._lat_window
        for _ in range(3):
            svc._slo_tick()
        assert svc._slo_shed == 0
        svc._slo_tick()          # never goes negative
        assert svc._slo_shed == 0
    finally:
        svc.shutdown()


@needs_native
def test_serve_job_ledger_mesh_zero(dataset, tmp_path):
    """A non-mesh serve job's ledger rows omit the mesh column entirely
    (byte-stability of the router training set)."""
    from daccord_tpu.serve import ConsensusService, ServeConfig

    out, d = dataset
    svc = ConsensusService(ServeConfig(
        workdir=str(tmp_path / "srv"), backend="native",
        backend_explicit=True, batch=64, workers=1, flush_lag_s=0.02))
    j = svc.submit({"db": out["db"], "las": out["las"], "tenant": "a"})
    svc.wait(j["job"], 300)
    svc.shutdown()
    led = os.path.join(svc.cfg.workdir, "jobs", j["job"], "ledger.jsonl")
    rows = [json.loads(x) for x in open(led)]
    win = [r for r in rows if r.get("event") == "window"]
    assert win and all("mesh" not in r for r in win)
